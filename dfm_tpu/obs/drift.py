"""Per-tenant model-quality drift detection (jax-free).

Serving sessions already score every update against the PREVIOUS query's
one-step forecast — standardized innovation magnitude (``innov_z``),
realized 90% band coverage (``coverage``), and loglik-per-row
(``ll_per_row``) all ride on the query trace events with zero extra
dispatches (the numbers fall out of host arithmetic the seams already
do).  ``DriftDetector`` folds that stream into a CUSUM-style change
detector per tenant:

- the first ``baseline_n`` scored updates freeze a rolling baseline
  (mean/sd per signal) — "what does this tenant's model look like when
  it is healthy";
- ``ll_per_row`` enters as its FIRST DIFFERENCE: the level is the
  whole panel's average loglik, which legitimately trends as the panel
  grows or the ring retires history, so CUSUM-on-level would
  accumulate false drift by construction.  The difference is the
  marginal fit of the newest data (plus the warm-EM param step) —
  stationary when the model is healthy, persistently negative when it
  is stale;
- afterwards each update contributes its worst baseline-relative
  exceedance to a one-sided CUSUM ``g = max(0, g + dev - allowance)``
  (Page 1954; undercoverage is measured against the nominal band level,
  so the conservative rank-r bands of arXiv 2405.08971 never read as
  drift when they over-cover);
- the detector FIRES when ``g`` crosses ``threshold`` (with at least
  ``min_updates`` post-baseline observations) and CLEARS below
  ``clear_at * threshold`` — the same fire/clear hysteresis state
  machine as ``obs.slo.SLOMonitor``.  ``drift_score = g / threshold``
  is the live gauge (1.0 == at the firing boundary).

Armed via ``DFM_DRIFT=1`` (library default OFF: ``drift_from_env``
returns None when the variable is unset/0, exactly like
``slo_from_env``).  Deterministic by construction: a pure function of
the observation sequence — no internal clock reads, no randomness —
and ``state_dict``/``from_state`` round-trip through session/fleet
snapshots so a restored detector continues mid-baseline.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Optional

__all__ = ["DriftConfig", "DriftDetector", "drift_from_env"]

_SIGNALS = ("innov_z", "ll_per_row")   # baseline-tracked signals


@dataclasses.dataclass(frozen=True)
class DriftConfig:
    """Model-quality drift objective for one serving tenant."""

    baseline_n: int = 12          # updates that freeze the healthy baseline
    min_updates: int = 3          # post-baseline updates before firing
    # CUSUM slack per update, in baseline-sd units.  The statistic feeds
    # on the WORST of up to three signals, and the expected max of ~3
    # standardized healthy deviations is ~0.85 sd — the allowance must
    # over-cover that bias or the healthy regime accumulates g by
    # construction (false fires).  Genuine breaks move the signals by
    # many sd, so detection lag is barely affected.
    allowance: float = 1.0
    # Cumulative exceedance that fires.  Small serving panels make the
    # per-update signals heavy-tailed (tens of cross-sectionally
    # CORRELATED cells per update — the effective sample is far smaller
    # than the cell count), so a single unlucky update can contribute
    # several sd; a genuine break contributes 5-20 sd per update
    # SUSTAINED, so a threshold a few multiples of the one-update tail
    # still detects within ~1-3 updates.
    threshold: float = 6.0
    clear_at: float = 0.25        # hysteresis: clears below clear_at*threshold
    nominal_coverage: float = 0.90  # band level the coverage signal targets
    coverage_scale: float = 0.10  # undercoverage per one "sd unit" of drift
    sd_floor: float = 1e-3        # baseline sd floor (constant-signal guard)


def drift_from_env() -> Optional[DriftConfig]:
    """DriftConfig from ``DFM_DRIFT`` (+ optional ``DFM_DRIFT_*`` knobs),
    or None when unset/"0"/"off"/"false" — the detector stays disarmed
    and the serving path is bit-identical to a drift-free build."""
    v = os.environ.get("DFM_DRIFT")
    if v is None or v.strip().lower() in ("", "0", "off", "false"):
        return None
    env = os.environ.get
    base = DriftConfig()
    return DriftConfig(
        baseline_n=int(env("DFM_DRIFT_BASELINE_N") or base.baseline_n),
        min_updates=int(env("DFM_DRIFT_MIN_UPDATES") or base.min_updates),
        allowance=float(env("DFM_DRIFT_ALLOWANCE") or base.allowance),
        threshold=float(env("DFM_DRIFT_THRESHOLD") or base.threshold),
        clear_at=float(env("DFM_DRIFT_CLEAR_AT") or base.clear_at))


class DriftDetector:
    """One tenant's CUSUM change detector with fire/clear hysteresis.

    ``observe`` consumes one scored update and returns ``"fire"`` on the
    drift transition, ``"clear"`` on recovery, else None.  Signals may
    arrive partially (a query with no realized overlap carries no
    coverage) — missing signals simply don't contribute that update.
    """

    def __init__(self, config: Optional[DriftConfig] = None):
        self.config = config if config is not None else DriftConfig()
        self.n = 0                    # scored updates seen
        self.g = 0.0                  # CUSUM statistic
        self.drift_score = 0.0        # g / threshold (the live gauge)
        self.drift_score_max = 0.0
        self.breached = False
        self.n_fired = 0
        self.last: dict = {}          # most recent signal values
        # Baseline accumulators per signal: n / sum / sum of squares.
        # The ll_per_row accumulator tracks FIRST DIFFERENCES (see
        # module docstring) — the level is nonstationary by design.
        self._bl = {s: [0, 0.0, 0.0] for s in _SIGNALS}
        self._ll_prev: Optional[float] = None

    # -- baseline ---------------------------------------------------------

    def _baseline(self, sig: str):
        n, s, ss = self._bl[sig]
        if n == 0:
            return None, None
        mean = s / n
        var = max(0.0, ss / n - mean * mean)
        sd = max(self.config.sd_floor, math.sqrt(var))
        return mean, sd

    def _in_baseline(self) -> bool:
        return self.n <= self.config.baseline_n

    # -- the one entry point ----------------------------------------------

    def observe(self, t: float, innov_z: Optional[float] = None,
                coverage: Optional[float] = None,
                ll_per_row: Optional[float] = None) -> Optional[str]:
        del t   # timestamps ride on the emitted events, not the statistic
        cfg = self.config
        self.n += 1
        if isinstance(innov_z, (int, float)) and math.isfinite(innov_z):
            self.last["innov_z"] = float(innov_z)
        if isinstance(coverage, (int, float)) and math.isfinite(coverage):
            self.last["coverage"] = float(coverage)
        # ll_per_row is differenced: the tracked statistic is the change
        # since the previous scored update (None on the first one).
        ll_diff = None
        if isinstance(ll_per_row, (int, float)) and math.isfinite(ll_per_row):
            self.last["ll_per_row"] = float(ll_per_row)
            if self._ll_prev is not None:
                ll_diff = float(ll_per_row) - self._ll_prev
            self._ll_prev = float(ll_per_row)
        if self._in_baseline():
            vals = {"innov_z": innov_z if isinstance(innov_z, (int, float))
                    and math.isfinite(innov_z) else None,
                    "ll_per_row": ll_diff}
            for k, v in vals.items():
                if v is not None:
                    b = self._bl[k]
                    b[0] += 1
                    b[1] += float(v)
                    b[2] += float(v) * float(v)
            return None
        devs = []
        if isinstance(innov_z, (int, float)) and math.isfinite(innov_z):
            mean, sd = self._baseline("innov_z")
            if mean is not None:
                devs.append((float(innov_z) - mean) / sd)   # one-sided: up
        if ll_diff is not None:
            mean, sd = self._baseline("ll_per_row")
            if mean is not None:
                devs.append((mean - ll_diff) / sd)          # down = drift
        if isinstance(coverage, (int, float)) and math.isfinite(coverage):
            # Nominal-relative: over-coverage (conservative bands) is fine.
            devs.append((cfg.nominal_coverage - float(coverage))
                        / cfg.coverage_scale)
        if not devs:
            return None
        self.g = max(0.0, self.g + max(devs) - cfg.allowance)
        self.drift_score = self.g / cfg.threshold if cfg.threshold > 0 else 0.0
        if self.drift_score > self.drift_score_max:
            self.drift_score_max = self.drift_score
        scored = self.n - cfg.baseline_n
        if (not self.breached and self.g > cfg.threshold
                and scored >= cfg.min_updates):
            self.breached = True
            self.n_fired += 1
            return "fire"
        if self.breached and self.g < cfg.clear_at * cfg.threshold:
            self.breached = False
            return "clear"
        return None

    def reset(self) -> None:
        """Start a new regime (called after a hot swap): the refit model
        needs a fresh healthy baseline before it can be accused of
        drifting; fire counters survive for the ledger."""
        self.n = 0
        self.g = 0.0
        self.drift_score = 0.0
        self.breached = False
        self.last = {}
        self._bl = {s: [0, 0.0, 0.0] for s in _SIGNALS}
        self._ll_prev = None

    # -- introspection / persistence --------------------------------------

    def status(self) -> dict:
        bl = {}
        for s in _SIGNALS:
            mean, sd = self._baseline(s)
            if mean is not None:
                bl[s] = {"mean": round(mean, 6), "sd": round(sd, 6)}
        return {
            "breached": self.breached,
            "drift_score": round(self.drift_score, 6),
            "drift_score_max": round(self.drift_score_max, 6),
            "n_fired": self.n_fired,
            "n_observed": self.n,
            "in_baseline": self._in_baseline(),
            "baseline": bl,
            "last": {k: round(v, 6) for k, v in self.last.items()},
        }

    def state_dict(self) -> dict:
        return {
            "v": 1,
            "config": dataclasses.asdict(self.config),
            "n": self.n, "g": self.g,
            "drift_score": self.drift_score,
            "drift_score_max": self.drift_score_max,
            "breached": self.breached, "n_fired": self.n_fired,
            "last": dict(self.last),
            "baseline": {s: list(self._bl[s]) for s in _SIGNALS},
            "ll_prev": self._ll_prev,
        }

    @classmethod
    def from_state(cls, state: dict) -> "DriftDetector":
        cfg = DriftConfig(**state.get("config", {}))
        det = cls(cfg)
        det.n = int(state.get("n", 0))
        det.g = float(state.get("g", 0.0))
        det.drift_score = float(state.get("drift_score", 0.0))
        det.drift_score_max = float(state.get("drift_score_max", 0.0))
        det.breached = bool(state.get("breached", False))
        det.n_fired = int(state.get("n_fired", 0))
        det.last = {str(k): float(v)
                    for k, v in state.get("last", {}).items()}
        for s in _SIGNALS:
            b = state.get("baseline", {}).get(s)
            if b is not None:
                det._bl[s] = [int(b[0]), float(b[1]), float(b[2])]
        lp = state.get("ll_prev")
        det._ll_prev = float(lp) if lp is not None else None
        return det

"""Persistent run registry for the perf observatory (jax-free).

An append-only JSONL registry of benchmark / fit runs so perf history
survives the process: every ``bench.py`` / ``bench/all.py`` /
``bench/batched.py`` invocation appends a :class:`RunRecord` dict, and a
traced ``fit()`` appends one when ``DFM_RUNS`` is explicitly set.  The
``backfill`` importer seeds the registry from every checked-in
``BENCH_*.json`` (per-file kind inference) + ``BENCH_ALL.json`` so
history starts populated.
``obs.regress`` diffs a run against this history.

Resolution of the registry directory (``runs_dir``):

- bench CLIs: ``DFM_RUNS=<dir>`` wins; ``DFM_RUNS=""`` disables;
  unset -> the default ``.dfm_runs/`` (git-ignored).
- traced fits (``ambient_only=True``): only an explicitly set non-empty
  ``DFM_RUNS`` enables appending — a library call must not create
  directories as a side effect of a default.

CLI::

    python -m dfm_tpu.obs.store backfill [--root DIR] [--runs DIR]
    python -m dfm_tpu.obs.store list [--runs DIR] [--json]
"""

from __future__ import annotations

import glob
import json
import os
import re
import statistics
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

RUNS_ENV = "DFM_RUNS"
DEFAULT_DIR = ".dfm_runs"
RUNS_FILE = "runs.jsonl"

# Metric-direction heuristics: throughputs ("..._per_sec...") are
# higher-is-better; walls / per-program costs are lower-is-better.
_LOWER_BETTER_MARKERS = ("ms_per", "_ms", "secs", "wall", "time_s",
                         "compile_s", "dispatch_s", "transfer_s", "host_s",
                         "rel_err", "calib_err", "blocking_transfers",
                         "dispatches_per_fit", "pad_waste", "degraded",
                         "slo_burn_rate", "flight_dumps", "noise_ratio",
                         "evictions_per", "shed_rate", "dropped_queries",
                         "detection_lag", "false_positive", "p99_ratio",
                         "trace_overhead", "tune_dispatches")


def lower_is_better(metric: str) -> bool:
    return any(m in metric for m in _LOWER_BETTER_MARKERS)


# Absolute noise floors for lower-is-better metrics: a relative band alone
# over-triggers when the baseline is tiny (a 0.6 ms CPU-fallback dispatch
# jittering to 1.3 ms is a 2.2x "regression" with zero signal — real
# tunnel dispatches are 60-100 ms).  A regression must clear the relative
# band AND move by more than the metric's unit floor.
_NOISE_FLOORS = (
    # advice_rel_err must match BEFORE the generic rel_err row: the
    # advisor's prediction error is a timing ratio (process jitter alone
    # moves it by several points), not an accuracy contract.
    ("advice_rel_err", 0.10),
    ("rel_err", 1e-6),     # accuracy drift toward the 1e-5 contract bound
    # Posterior-band coverage error (bench.kscale): an empirical frequency
    # over T*k indicator draws — sampling noise alone moves it by a couple
    # of points between DGP seeds, with no numerics-level signal.
    ("calib_err", 0.02),
    # pad_waste must match BEFORE the "_s" row ("pad_waste_frac" is a
    # fraction, not seconds): the planner's DP is deterministic, but the
    # job mix itself varies with bench env knobs — a 2-point move is noise.
    ("pad_waste", 0.02),
    # SLO burn is a ratio of p99 latency to budget: scheduler jitter on
    # the shared CI box moves it by tenths without any code-level signal.
    ("slo_burn_rate", 0.25),
    ("flight_dumps", 0.5),   # integer count; any single dump is signal
    # pit_qr vs sequential f32 loglik-noise ratio (bench.longt): both
    # errors sit near eps*N*T, so run-to-run DGP draws move the ratio by
    # halves without any numerics-level signal.
    ("noise_ratio", 0.5),
    # Ring-buffer evictions per query (bench.stream) track the workload
    # (rows/query), not a perf quality — only a whole-row move is signal.
    ("evictions_per", 0.5),
    # Daemon overload shed fraction (bench.daemon): the overload leg
    # MEANS to shed — the rate tracks thread-timing of the synthetic
    # burst, so only a several-point move is a policy-level signal.
    ("shed_rate", 0.05),
    # Dropped queries are the zero-downtime contract itself: any drop is
    # signal (floor 0 by omission — the 0.5 integer-count convention
    # would forgive exactly the single dropped query the gate exists to
    # catch).
    ("dropped_queries", 0.0),
    # Drift-detection lag (bench.drift) counts updates between the
    # injected break and the detector firing: the CUSUM walk is
    # deterministic given the panel, but DGP seeds move the post-break
    # innovation sizes — a one-update move carries no detector signal.
    ("detection_lag", 1.0),
    # False-positive rate over the pre-break window: an empirical
    # frequency over few dozen updates — one spurious fire flips it by
    # 1/n, with no detector-quality signal below a few points.
    ("false_positive", 0.05),
    # Managed-vs-frozen serving p99 ratio (bench.drift): nearest-rank
    # p99 over ~50 few-ms CPU-fallback walls is a near-max order
    # statistic — even after the bench's symmetric pooled MAD trim the
    # run-to-run spread on the 1-core box is ~±0.2 (measured 0.99/1.08/
    # 1.17 on back-to-back identical runs); the smoke's 5 ms absolute
    # floor is the contract check, the gate only catches gross motion.
    ("p99_ratio", 0.25),
    # Request-tracing overhead (bench.serve / bench.daemon): traced vs
    # untraced warm wall as a percentage.  Both walls are few-ms
    # best-of-N on the 1-core CPU-fallback box, so the ratio of their
    # difference jitters by several points run-to-run with zero tracing-
    # cost signal; only a >5-point move says the span plumbing got
    # heavier.  Must match BEFORE the generic "ms" row ("trace_overhead_
    # pct" is a percentage, not milliseconds... it contains no ms, but
    # keep it ahead of any future broadening of the generic rows).
    ("trace_overhead", 5.0),
    ("ms", 2.0),           # milliseconds: ms_per, _ms, dispatch_ms_...
    ("_s", 0.05),          # seconds: wall_s, dispatch_s, compile_s, time_s
    ("secs", 0.05),
    ("wall", 0.05),
)


def noise_floor(metric: str) -> float:
    """Absolute delta below which a lower-is-better move is noise."""
    for marker, floor in _NOISE_FLOORS:
        if marker in metric:
            return floor
    return 0.0


def runs_dir(explicit: Optional[str] = None, *,
             ambient_only: bool = False) -> Optional[str]:
    """Resolve the registry directory; ``None`` means "do not record"."""
    if explicit:
        return str(explicit)
    env = os.environ.get(RUNS_ENV)
    if env:
        return env
    if env == "":          # explicitly disabled
        return None
    return None if ambient_only else DEFAULT_DIR


def new_run_id() -> str:
    return "r%x-%s" % (int(time.time()), uuid.uuid4().hex[:6])


def git_rev(root: Optional[str] = None) -> Optional[str]:
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             cwd=root, capture_output=True, text=True,
                             timeout=10)
    except Exception:
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


def fingerprint(config: Dict[str, Any]) -> str:
    """Stable config fingerprint: sorted ``k=v`` joined with ``|``."""
    return "|".join("%s=%s" % (k, config[k]) for k in sorted(config))


def device_kind(device: Optional[str]) -> str:
    """Coarse device class ("tpu"/"cpu"/"gpu"/...) for the fingerprint —
    runs on different hardware must not share a perf baseline."""
    d = (device or "").lower()
    for kind in ("tpu", "gpu", "cpu"):
        if kind in d:
            return kind
    return d.split()[0] if d else "unknown"


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def make_record(kind: str, config: Dict[str, Any],
                metrics: Dict[str, Any], *, device: Optional[str] = None,
                loglik: Optional[float] = None,
                convergence: Optional[List[float]] = None,
                dispatches: Optional[int] = None,
                recompiles: Optional[int] = None,
                wall_s: Optional[float] = None, source: str = "live",
                run_id: Optional[str] = None,
                t_unix: Optional[float] = None,
                root: Optional[str] = None) -> Dict[str, Any]:
    """Assemble a RunRecord dict (the registry's one schema)."""
    rec: Dict[str, Any] = {
        "run_id": run_id or new_run_id(),
        "t_unix": time.time() if t_unix is None else float(t_unix),
        "kind": kind,
        "device": device,
        "git_rev": git_rev(root),
        "source": source,
        "config": dict(config),
        "fingerprint": fingerprint(config),
        "metrics": {k: _num(v) for k, v in metrics.items()
                    if _num(v) is not None},
    }
    if loglik is not None and _num(loglik) is not None:
        rec["loglik"] = float(loglik)
    if convergence is not None:
        rec["convergence"] = [float(x) for x in convergence]
    if dispatches is not None:
        rec["dispatches"] = int(dispatches)
    if recompiles is not None:
        rec["recompiles"] = int(recompiles)
    if wall_s is not None:
        rec["wall_s"] = float(wall_s)
    return rec


class RunStore:
    """Append-only JSONL registry in ``<dir>/runs.jsonl``."""

    def __init__(self, path: str):
        self.dir = str(path)
        self.file = os.path.join(self.dir, RUNS_FILE)

    def append(self, rec: Dict[str, Any]) -> str:
        os.makedirs(self.dir, exist_ok=True)
        with open(self.file, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
        return rec["run_id"]

    def load(self) -> List[Dict[str, Any]]:
        """All records, oldest first; corrupt/truncated lines are skipped
        (a run may die mid-append — history must still load)."""
        if not os.path.exists(self.file):
            return []
        out = []
        with open(self.file) as f:
            for i, ln in enumerate(f, 1):
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    print("warning: %s line %d: corrupt record skipped"
                          % (self.file, i), file=sys.stderr)
                    continue
                if isinstance(rec, dict) and "run_id" in rec:
                    out.append(rec)
        return out

    def get(self, run_id: str) -> Optional[Dict[str, Any]]:
        for rec in reversed(self.load()):
            if rec.get("run_id") == run_id:
                return rec
        return None

    def query(self, fingerprint: Optional[str] = None,
              kind: Optional[str] = None) -> List[Dict[str, Any]]:
        recs = self.load()
        if fingerprint is not None:
            recs = [r for r in recs if r.get("fingerprint") == fingerprint]
        if kind is not None:
            recs = [r for r in recs if r.get("kind") == kind]
        return sorted(recs, key=lambda r: r.get("t_unix", 0.0))

    def latest(self, **kw) -> Optional[Dict[str, Any]]:
        recs = self.query(**kw)
        return recs[-1] if recs else None

    def sources(self) -> set:
        return {r.get("source") for r in self.load()}

    def baseline(self, fingerprint: str, metric: str, *, best_n: int = 5,
                 exclude_run: Optional[str] = None) -> Optional[float]:
        """Noise-aware baseline: the median of the best ``best_n``
        historical values of ``metric`` for this fingerprint (best = max
        for throughputs, min for walls).  None when no history."""
        vals = [r["metrics"][metric] for r in self.query(fingerprint)
                if r.get("run_id") != exclude_run
                and metric in r.get("metrics", {})]
        if not vals:
            return None
        vals.sort(reverse=not lower_is_better(metric))
        return float(statistics.median(vals[:max(1, best_n)]))

    def baseline_loglik(self, fingerprint: str, *,
                        exclude_run: Optional[str] = None
                        ) -> Optional[float]:
        lls = [r["loglik"] for r in self.query(fingerprint)
               if r.get("run_id") != exclude_run and "loglik" in r]
        return float(statistics.median(lls)) if lls else None


# -- importer: seed the registry from the checked-in bench artifacts ------

_DEVICE_RE = re.compile(r"(?:JAX )?device: ([^\n;]+)")


def _device_from_tail(tail: str) -> Optional[str]:
    m = _DEVICE_RE.search(tail or "")
    return m.group(1).strip() if m else None


_BENCH_NUMERIC_KEYS = (
    "value", "vs_baseline", "iters_per_sec_with_dispatch",
    "dispatch_ms_per_program", "n_iters_fused", "loglik_rel_err_iter3",
    "loglik_rel_err_iter50", "speedup_vs_looped",
    "e2e_warm_fit_iters_per_sec", "blocking_transfers",
    "e2e_fused_fit_iters_per_sec", "dispatches_per_fit",
    "p99_dispatch_ms", "advice_rel_err",
    "aggregate_mixed_iters_per_sec", "pad_waste_frac",
    "scheduler_overhead_ms",
    "serve_p50_ms", "serve_p99_ms", "serve_blocking_transfers_per_query",
    "serve_degraded_queries",
    # Fleet serving (bench.fleet): aggregate queries/sec is the headline
    # (higher-is-better, no floor); the p99 latency and the admission
    # plan's pad waste ride the "_ms" / "pad_waste" marker rows above.
    "fleet_qps", "fleet_p99_ms", "fleet_pad_waste_frac",
    # Live telemetry plane (obs.live): SLO error-budget burn observed
    # during the bench, and flight-recorder dumps triggered by it —
    # both ~0 on a healthy run (lower-is-better, floors above).
    "fleet_slo_burn_rate", "flight_dumps",
    # Long-T time-parallel sweep (bench.longt): pit_qr speedup vs the
    # sequential scan at each sweep point (higher-is-better; the T=1000
    # crossover is the headline contract) and the f32 loglik-noise ratio
    # vs sequential (lower-is-better, "noise_ratio" marker rows above).
    "pit_qr_speedup_t300", "pit_qr_speedup_t1000", "pit_qr_speedup_t4000",
    "pit_qr_noise_ratio",
    # Unbounded streams (bench.stream): ring-session throughput is the
    # headline (higher-is-better); the p99 / readmission walls ride the
    # "ms" marker rows, evictions/query its own marker row above.
    "stream_qps", "stream_p50_ms", "stream_p99_ms",
    "evictions_per_query", "readmission_ms",
    "stream_blocking_transfers_per_query",
    # Wide-k state-axis sweep (bench.kscale): rank-r lowrank speedup vs
    # the exact info scan per sweep point (higher-is-better; k=50 is the
    # headline contract), the 90%-band coverage error of the rank-r
    # smoother ("calib_err" marker/floor rows above), and the wall of the
    # MF m~25 fit the exact path cannot compile on axon ("_s" floor).
    "kscale_speedup_k10", "kscale_speedup_k25", "kscale_speedup_k50",
    "kscale_speedup_k100", "kscale_calib_err", "kscale_mf_m25_wall_s",
    # Serving daemon (bench.daemon): socket-level throughput/latency are
    # the headline (qps higher-is-better; p99 rides the "ms" rows), the
    # overload leg's shed fraction has its own marker/floor rows, the
    # blue/green swap gap rides "ms", and dropped_queries is the
    # zero-downtime contract (any drop regresses).
    "daemon_qps", "daemon_p99_ms", "daemon_shed_rate",
    "daemon_handoff_gap_ms", "daemon_dropped_queries",
    # Engine-complete serving: aggregate wall of a lowrank-routed wide-k
    # fleet vs its forced-info twin (bench.fleet, same tenants/schedule/
    # container) and of a pit_qr long-window ring session vs its info
    # twin (bench.stream) — both higher-is-better speedup ratios (the
    # regress gate's relative band absorbs twin-ratio timing jitter).
    "fleet_widek_speedup", "stream_pit_speedup",
    # Closed-loop maintenance soak (bench.drift): managed fleet vs its
    # frozen twin on the same simulated break — detection lag (updates
    # from break to fire, lower), held-out quality gain of the managed
    # fleet (higher; the swap either helps or the gate fails), swap
    # count, pre-break false-fire rate (lower) and the managed/frozen
    # serving-p99 ratio (lower; the maintenance loop must not tax the
    # serving path).
    "drift_detection_lag_updates", "managed_vs_frozen_heldout_gain",
    "drift_swaps_total", "drift_false_positive_rate", "drift_p99_ratio",
    # Request-scoped tracing (bench.serve / bench.daemon): traced vs
    # untraced warm wall, best-of-N, as a percentage — the span
    # plumbing's serving-path tax (lower-is-better; "trace_overhead"
    # marker + 5-point floor above).
    "trace_overhead_pct",
    # Differentiable tuning (bench.tune): gradient Q/R search as ONE
    # fused program vs the G-lone-fit grid loop (higher-is-better wall
    # ratio), the held-out one-step MSE improvement of the tuned fit
    # (higher; deterministic given the panel), and the search's blocking
    # d2h count — the dispatch-budget contract itself (lower-is-better
    # marker above; floor 0 by omission — a single extra blocking
    # transfer through the ~60-100 ms tunnel is exactly the regression
    # the gate exists to catch).
    "tune_speedup_vs_grid", "tune_heldout_gain", "tune_dispatches",
)


def record_from_bench_json(parsed: Dict[str, Any], *,
                           device: Optional[str] = None,
                           source: str = "live",
                           t_unix: Optional[float] = None,
                           kind: str = "bench",
                           root: Optional[str] = None) -> Dict[str, Any]:
    """Adapt one ``bench.py``-style JSON line into a RunRecord."""
    metric = parsed.get("metric") or "bench"
    metrics: Dict[str, Any] = {}
    if _num(parsed.get("value")) is not None:
        metrics[metric] = parsed["value"]
    for k in _BENCH_NUMERIC_KEYS[1:]:
        if _num(parsed.get(k)) is not None:
            metrics[k] = parsed[k]
    config = {"bench": kind.replace("bench_", "") if kind != "bench"
              else "headline",
              "metric": metric, "device": device_kind(device)}
    loglik = parsed.get("loglik_tpu_iter50", parsed.get("loglik"))
    return make_record(
        kind, config, metrics, device=device, loglik=loglik,
        dispatches=parsed.get("dispatches"),
        recompiles=parsed.get("recompiles"), source=source,
        t_unix=t_unix, run_id=parsed.get("run_id"), root=root)


_ALL_METRIC_KEYS = ("em_iters_per_sec", "em_iters_per_sec_sustained",
                    "vs_cpu", "vs_cpu_sustained", "total_secs",
                    "e2e_warm_fit_iters_per_sec", "blocking_transfers")


def record_from_bench_all_entry(name: str, res: Dict[str, Any], *,
                                device: Optional[str] = None,
                                source: str = "live",
                                t_unix: Optional[float] = None,
                                root: Optional[str] = None
                                ) -> Optional[Dict[str, Any]]:
    """Adapt one ``bench.all`` results entry into a RunRecord (None when
    the entry errored or carries no numeric metric)."""
    if not isinstance(res, dict) or res.get("error"):
        return None
    metrics = {k: res[k] for k in _ALL_METRIC_KEYS
               if _num(res.get(k)) is not None}
    if not metrics:
        return None
    config = {"bench": "all", "config": res.get("config", name),
              "backend": res.get("backend"),
              "N": res.get("N"), "T": res.get("T"),
              "k": res.get("k"), "device": device_kind(device)}
    return make_record("bench_all", config, metrics, device=device,
                       loglik=res.get("loglik"), source=source,
                       t_unix=t_unix, root=root)


def _backfill_kind(src: str) -> str:
    """RunRecord kind for a ``BENCH_*.json`` artifact, inferred from its
    filename: per-bench artifacts (``BENCH_stream.json``,
    ``BENCH_longt2.json``, ...) map to their bench family's kind so
    ``obs.regress`` compares them against live runs of the same CLI;
    everything else (round artifacts ``BENCH_r5.json`` etc.) is the
    headline ``bench.py`` format."""
    stem = src[len("BENCH_"):].split(".")[0].rstrip("0123456789_")
    family = {"stream": "bench_stream", "longt": "bench_longt",
              "kscale": "bench_kscale", "serve": "bench_serve",
              "mixed": "bench_mixed", "fleet": "bench_fleet",
              "daemon": "bench_daemon", "drift": "bench_drift",
              "tune": "bench_tune"}
    return family.get(stem, "bench")


def backfill(root: str = ".", store: Optional[RunStore] = None,
             runs: Optional[str] = None) -> int:
    """Import every ``BENCH_*.json`` under ``root`` (kind inferred per
    file — see ``_backfill_kind``; ``BENCH_ALL.json`` keeps its own
    per-config format) into the registry.  Idempotent: records whose
    ``source`` is already present are skipped.  Returns the number of
    records appended."""
    store = store or RunStore(runs or runs_dir() or DEFAULT_DIR)
    existing = store.sources()
    n = 0
    # Round artifacts plus any per-bench artifact in either layout: the
    # driver wrapper ({"parsed": <one JSON line>, "tail": ...} —
    # BENCH_stream.json, BENCH_longt.json, ...) or the bare one-JSON-line
    # payload itself (BENCH_daemon.json); BENCH_ALL.json is a different
    # shape and is handled below.
    paths = sorted(set(glob.glob(os.path.join(root, "BENCH_*.json")))
                   - {os.path.join(root, "BENCH_ALL.json")})
    for path in paths:
        src = os.path.basename(path)
        if src in existing:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print("warning: backfill: %s: %s" % (path, e), file=sys.stderr)
            continue
        parsed = data.get("parsed") or {}
        if _num(parsed.get("value")) is None:
            parsed = data          # bare one-JSON-line artifact
        if _num(parsed.get("value")) is None:
            continue
        rec = record_from_bench_json(
            parsed, device=_device_from_tail(data.get("tail", "")),
            source=src, t_unix=os.path.getmtime(path), root=root,
            kind=_backfill_kind(src))
        store.append(rec)
        n += 1
    path = os.path.join(root, "BENCH_ALL.json")
    if os.path.exists(path):
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print("warning: backfill: %s: %s" % (path, e), file=sys.stderr)
            data = {}
        device = data.get("device")
        for name, res in (data.get("results") or {}).items():
            src = "BENCH_ALL.json#%s" % name
            if src in existing:
                continue
            rec = record_from_bench_all_entry(
                name, res, device=device, source=src,
                t_unix=data.get("recorded_unix"), root=root)
            if rec is None:
                continue
            store.append(rec)
            n += 1
    return n


# -- CLI ------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m dfm_tpu.obs.store",
        description="Perf-observatory run registry (jax-free).")
    sub = ap.add_subparsers(dest="cmd", required=True)
    bf = sub.add_parser(
        "backfill",
        help="import every BENCH_*.json (kind inferred per file) "
             "+ BENCH_ALL.json")
    bf.add_argument("--root", default=".")
    bf.add_argument("--runs", default=None)
    ls = sub.add_parser("list", help="list recorded runs")
    ls.add_argument("--runs", default=None)
    ls.add_argument("--json", action="store_true")
    a = ap.parse_args(argv)
    d = runs_dir(a.runs)
    if d is None:
        print("error: no runs dir (DFM_RUNS is disabled)", file=sys.stderr)
        return 2
    store = RunStore(d)
    if a.cmd == "backfill":
        n = backfill(a.root, store=store)
        print("backfilled %d record(s) into %s" % (n, store.file))
        return 0
    recs = store.load()
    if a.json:
        print(json.dumps(recs))
        return 0
    if not recs:
        print("no runs recorded in %s" % store.file)
        return 0
    for r in recs:
        top = sorted(r.get("metrics", {}).items())[:3]
        mt = " ".join("%s=%.4g" % kv for kv in top)
        print("%-24s %-10s %-28s %s" % (
            r.get("run_id", "?"), r.get("kind", "?"),
            (r.get("fingerprint") or "")[:28], mt))
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:      # `... list | head` must exit quietly
        raise SystemExit(0)

"""Offline trace summary: ``python -m dfm_tpu.obs.report trace.jsonl``.

Pure Python (no jax import) so the report runs instantly anywhere — on the
operator's laptop against a trace scp'd off the bench host, or in the round
driver between runs.  ``summarize`` is also what ``Tracer.summary()`` and
``FitResult.telemetry`` delegate to, so the offline CLI and the in-process
summary can never drift.

What it computes from the event stream (schema: ``obs/trace.py``):
- dispatch histogram per program, first-call vs steady wall times (the
  first-call minus steady-state gap is the only compile-time proxy the
  axon tunnel exposes), recompile events
- amortized tunnel latency: barrier'd dispatch wall / fused iterations —
  comparable against the sustained two-point rate in docs/PERF.md
- the convergence curve: per-chunk logliks, deltas vs the noise floor
- per-problem freezes (batched engine) and health events
- static flops/bytes per program when cost capture was on
- p50/p90/p99 dispatch walls (all spans + per-program end-to-end), and
  the advisor's predicted-vs-realized wall when ``fit(auto=True)`` ran

``--chrome out.json`` additionally exports the raw event stream to
Chrome/Perfetto trace-event format for visual pipeline inspection.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Union

__all__ = ["load", "summarize", "to_chrome", "main"]


def load(path: str) -> List[dict]:
    """Parse a JSONL trace, tolerating damage: empty files, and
    truncated/corrupt lines (a process killed mid-write leaves a partial
    last line) are warned about on stderr and skipped — a damaged trace
    must still summarize."""
    events = []
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError as e:
                print(f"warning: {path}:{i + 1}: skipping invalid JSONL "
                      f"({e})", file=sys.stderr)
                continue
            if isinstance(ev, dict):
                events.append(ev)
            else:
                print(f"warning: {path}:{i + 1}: skipping non-object line",
                      file=sys.stderr)
    return events


def _pct(xs: List[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list (the 1e-9 nudge
    keeps float fuzz like 0.9*10 == 9.000000000000002 from bumping the
    rank)."""
    import math
    rank = max(1, math.ceil(q * len(xs) - 1e-9))
    return xs[min(len(xs) - 1, rank - 1)]


def _stats(xs: List[float]) -> dict:
    if not xs:
        return {}
    xs = sorted(xs)
    n = len(xs)
    return {"n": n, "min": xs[0], "max": xs[-1],
            "mean": sum(xs) / n, "p50": _pct(xs, 0.50),
            "p90": _pct(xs, 0.90), "p99": _pct(xs, 0.99)}


def summarize(events_or_path: Union[str, List[dict]]) -> dict:
    """Aggregate an event stream (list of dicts, or a JSONL path)."""
    events = (load(events_or_path) if isinstance(events_or_path, str)
              else list(events_or_path))

    disp = [e for e in events if e.get("kind") == "dispatch"]
    by_prog: dict = {}
    for e in disp:
        p = by_prog.setdefault(e.get("program", "?"), {
            "dispatches": 0, "first_calls": 0, "recompiles": 0, "errors": 0,
            "keys": set(), "first_durs": [], "steady_durs": [],
            "barrier_durs": [], "fused_iters": 0, "bucketed": 0,
            "queue_depths": [], "fused_programs": 0})
        p["dispatches"] += 1
        p["keys"].add(e.get("key", ""))
        if e.get("error"):
            p["errors"] += 1
        first = bool(e.get("first_call"))
        p["first_calls"] += first
        p["recompiles"] += bool(e.get("recompile"))
        p["bucketed"] += e.get("bucket") is not None
        p["fused_programs"] += bool(e.get("fused"))
        if e.get("queue_depth") is not None:
            p["queue_depths"].append(int(e["queue_depth"]))
        dur = e.get("dur")
        if dur is not None:
            (p["first_durs"] if first else p["steady_durs"]).append(dur)
            if e.get("barrier"):
                p["barrier_durs"].append(dur)
                p["fused_iters"] += int(e.get("n_iters") or 1)

    programs = {}
    for name, p in sorted(by_prog.items()):
        entry = {"dispatches": p["dispatches"],
                 "first_calls": p["first_calls"],
                 "recompiles": p["recompiles"],
                 "shape_keys": sorted(p["keys"])}
        if p["bucketed"]:
            entry["bucketed_dispatches"] = p["bucketed"]
        if p["fused_programs"]:
            # A while-loop fit: the whole EM ran inside this one span.
            entry["fused_programs"] = p["fused_programs"]
        if p["queue_depths"]:
            # Speculative (pipelined) launches: depth>1 means the host
            # issued this chunk while an older one was still in flight.
            entry["speculative_dispatches"] = sum(
                1 for d in p["queue_depths"] if d > 1)
            entry["max_queue_depth"] = max(p["queue_depths"])
        if p["errors"]:
            entry["errors"] = p["errors"]
        if p["first_durs"]:
            entry["first_call_s"] = _stats(p["first_durs"])
        if p["steady_durs"]:
            entry["steady_s"] = _stats(p["steady_durs"])
        if p["barrier_durs"]:
            # End-to-end walls: spans the host actually waited out (d2h
            # barrier inside the span) — the serving-latency view.
            entry["e2e_s"] = _stats(p["barrier_durs"])
        # Compile proxy: how much slower the first call ran than steady state.
        if p["first_durs"] and p["steady_durs"]:
            entry["compile_proxy_s"] = (max(p["first_durs"])
                                        - _stats(p["steady_durs"])["p50"])
        if p["fused_iters"]:
            entry["amortized_ms_per_iter"] = (
                1e3 * sum(p["barrier_durs"]) / p["fused_iters"])
        programs[name] = entry

    chunks = [e for e in events if e.get("kind") == "chunk"]
    convergence = None
    if chunks:
        lls: List[float] = []
        for c in chunks:
            lls.extend(float(x) for x in c.get("lls", []))
        deltas = [lls[i + 1] - lls[i] for i in range(len(lls) - 1)]
        nf = next((c.get("noise_floor") for c in chunks
                   if c.get("noise_floor") is not None), None)
        convergence = {"n_chunks": len(chunks), "n_iters": len(lls),
                       "loglik_first": lls[0] if lls else None,
                       "loglik_last": lls[-1] if lls else None,
                       "deltas": deltas, "noise_floor": nf,
                       "below_floor": sum(1 for c in chunks
                                          if c.get("below_floor"))}
        if nf is not None and deltas:
            convergence["deltas_below_floor"] = sum(
                1 for d in deltas if abs(d) < nf)
        # Device-side per-iteration metrics (fit(progress=...) /
        # metrics-enabled chunks): max param-update norm per iteration.
        dparams = [float(x) for c in chunks for x in c.get("dparams", [])]
        if dparams:
            convergence["dparams"] = dparams
            convergence["dparam_last"] = dparams[-1]

    freezes = [e for e in events if e.get("kind") == "freeze"]
    health = [e for e in events if e.get("kind") == "health"]
    costs = {e.get("program", "?"): {k: v for k, v in e.items()
                                     if k not in ("t", "kind", "program")}
             for e in events if e.get("kind") == "cost"}
    fits = [{k: v for k, v in e.items() if k != "kind"}
            for e in events if e.get("kind") == "fit"]
    # Multi-tenant scheduler (sched.submit / fit_jobs): one event per job
    # with its bucket assignment and queue/compute/pad-waste accounting.
    tenants = [{k: v for k, v in e.items() if k != "kind"}
               for e in events if e.get("kind") == "tenant"]
    # Streaming nowcast sessions (serve.NowcastSession): one event per
    # query with its end-to-end wall, row counts and convergence flags.
    queries = [{k: v for k, v in e.items() if k != "kind"}
               for e in events if e.get("kind") == "query"]

    out = {
        "n_events": len(events),
        "dispatches": len(disp),
        "first_calls": sum(1 for e in disp if e.get("first_call")),
        "recompiles": sum(1 for e in disp if e.get("recompile")),
        "dispatch_errors": sum(1 for e in disp if e.get("error")),
        "programs": programs,
    }
    # Execution barriers the host actually waited on: barrier'd dispatch
    # spans (transfer inside the span) + explicit blocking transfer events
    # (the pipelined drivers' one-pull-per-round).  The pipelining win is
    # this number dropping from n_chunks to ~n_chunks/depth.
    transfers = [e for e in events if e.get("kind") == "transfer"]
    out["blocking_transfers"] = (
        sum(1 for e in disp if e.get("barrier"))
        + sum(1 for e in transfers if e.get("blocking")))
    # While-loop (fused) fits: EM iterations that ran inside a single
    # dispatch span — the dispatch-free serving path's headline count.
    fused_iters = sum(int(e.get("n_iters") or 0) for e in disp
                      if e.get("fused"))
    if fused_iters:
        out["fused_iterations"] = fused_iters
    if transfers:
        out["nonblocking_transfers"] = sum(
            1 for e in transfers if not e.get("blocking"))
    cache_evs = [e for e in events if e.get("kind") == "compile_cache"]
    if cache_evs:
        last = cache_evs[-1]
        out["compile_cache"] = {
            "dir": last.get("dir"), "entries": last.get("entries"),
            "new_entries": sum(int(e.get("new_entries") or 0)
                               for e in cache_evs)}
    walls = [e["dur"] for e in disp
             if e.get("dur") is not None and e.get("barrier")]
    if walls:
        out["barrier_dispatch_s"] = _stats(walls)
        fused = sum(int(e.get("n_iters") or 1) for e in disp
                    if e.get("barrier"))
        out["amortized_ms_per_iter"] = 1e3 * sum(walls) / max(fused, 1)
    # Latency percentiles over ALL timed dispatch spans (barrier'd or
    # enqueue-only) — the p50/p90/p99 the serving path will be scored on.
    all_durs = [float(e["dur"]) for e in disp if e.get("dur") is not None]
    if all_durs:
        st = _stats(all_durs)
        out["dispatch_percentiles_ms"] = {
            "p50": 1e3 * st["p50"], "p90": 1e3 * st["p90"],
            "p99": 1e3 * st["p99"], "n": st["n"]}
    # Auto-tuning advisor: the last advice event wins (one per fit(auto=
    # True)); predicted-vs-realized wall is the model-drift metric that
    # obs.regress gates as ``advice_rel_err``.
    advice_evs = [e for e in events if e.get("kind") == "advice"]
    if advice_evs:
        out["advice"] = {k: v for k, v in advice_evs[-1].items()
                         if k not in ("kind", "t")}
        if len(advice_evs) > 1:
            out["advice"]["n_events"] = len(advice_evs)
    # Total wall + per-phase breakdown: dispatch (device walls measured
    # behind a barrier or async enqueue), transfer (h2d/d2h walls), host
    # (everything else — python driver, numpy, event emission).
    ts = [e["t"] for e in events
          if isinstance(e.get("t"), (int, float))]
    if ts:
        end = max(e["t"] + float(e.get("dur") or 0.0) for e in events
                  if isinstance(e.get("t"), (int, float)))
        wall = max(end - min(ts), 0.0)
        dispatch_s = sum(float(e["dur"]) for e in disp
                         if e.get("dur") is not None)
        transfer_s = sum(float(e.get("dur") or 0.0) for e in events
                         if e.get("kind") == "transfer")
        out["wall_s"] = wall
        out["phases"] = {
            "dispatch_s": dispatch_s, "transfer_s": transfer_s,
            "host_s": max(wall - dispatch_s - transfer_s, 0.0)}
    if convergence is not None:
        out["convergence"] = convergence
    if freezes:
        out["freezes"] = [{k: v for k, v in e.items() if k != "kind"}
                          for e in freezes]
    if health:
        out["health_events"] = len(health)
        out["health_kinds"] = sorted({e.get("event", e.get("name", "?"))
                                      for e in health})
    if costs:
        out["costs"] = costs
    if fits:
        out["fits"] = fits
    if tenants:
        waits = [float(t["queue_wait_s"]) for t in tenants
                 if isinstance(t.get("queue_wait_s"), (int, float))]
        wastes = [float(t["pad_waste_frac"]) for t in tenants
                  if isinstance(t.get("pad_waste_frac"), (int, float))]
        out["tenants"] = tenants
        out["tenant_fairness"] = {
            "n_tenants": len(tenants),
            "n_buckets": len({t.get("bucket") for t in tenants}),
            "converged": sum(1 for t in tenants if t.get("converged")),
            "queue_wait_s": _stats(waits),
            "pad_waste_frac_mean": (sum(wastes) / len(wastes)
                                    if wastes else None)}
    if queries:
        per_session: dict = {}
        for q in queries:
            sid = str(q.get("session", "?"))
            ps = per_session.setdefault(
                sid, {"queries": 0, "walls": [], "t_rows": None})
            ps["queries"] += 1
            if isinstance(q.get("wall"), (int, float)):
                ps["walls"].append(float(q["wall"]))
            if q.get("t_rows") is not None:
                ps["t_rows"] = int(q["t_rows"])
        for ps in per_session.values():
            st = _stats(ps.pop("walls"))
            if st:
                ps["query_wall_s"] = st
        walls = [float(q["wall"]) for q in queries
                 if isinstance(q.get("wall"), (int, float))]
        # Warm-path health: any serve_update recompile past each
        # executable's first call means the session's one-program promise
        # broke (shape drift / cache eviction) — should be 0.
        out["queries"] = {
            "n_queries": len(queries),
            "n_sessions": len(per_session),
            "converged": sum(1 for q in queries if q.get("converged")),
            "diverged": sum(1 for q in queries if q.get("diverged")),
            "query_wall_s": _stats(walls),
            "recompiles_after_warmup": sum(
                1 for e in disp if e.get("program") == "serve_update"
                and e.get("recompile")),
            "per_session": per_session,
        }
    # Fleet serving (fleet.SessionFleet): one event per drained tick with
    # the bucket's occupancy (active lanes / batch width), plus queue-wait
    # accounting on the per-tenant query events.  Queries-per-dispatch is
    # the multiplexing win itself: how many tenant answers each fused
    # batched serve_update dispatch produced.
    ticks = [{k: v for k, v in e.items() if k != "kind"}
             for e in events if e.get("kind") == "tick"]
    if ticks:
        occ = [float(t["n_active"]) / float(t["batch"]) for t in ticks
               if isinstance(t.get("n_active"), (int, float))
               and t.get("batch")]
        tick_walls = [float(t["wall"]) for t in ticks
                      if isinstance(t.get("wall"), (int, float))]
        fleet_q = [q for q in queries if q.get("queue_wait") is not None]
        per_tenant_q: dict = {}
        for q in fleet_q:
            pt = per_tenant_q.setdefault(str(q.get("tenant", "?")),
                                         {"queries": 0, "waits": []})
            pt["queries"] += 1
            if isinstance(q.get("queue_wait"), (int, float)):
                pt["waits"].append(float(q["queue_wait"]))
        for pt in per_tenant_q.values():
            st = _stats(pt.pop("waits"))
            if st:
                pt["queue_wait_s"] = st
        per_bucket: dict = {}
        for t in ticks:
            bid = str(t.get("bucket", "?"))
            pb = per_bucket.setdefault(bid, {"ticks": 0, "occ": []})
            pb["ticks"] += 1
            if (isinstance(t.get("n_active"), (int, float))
                    and t.get("batch")):
                pb["occ"].append(float(t["n_active"]) / float(t["batch"]))
        for pb in per_bucket.values():
            os_ = pb.pop("occ")
            if os_:
                pb["occupancy_mean"] = sum(os_) / len(os_)
        out["fleet"] = {
            "n_ticks": len(ticks),
            "n_buckets": len(per_bucket),
            "n_queries": len(fleet_q),
            "queries_per_dispatch": len(fleet_q) / len(ticks),
            "occupancy_mean": (sum(occ) / len(occ)) if occ else None,
            "tick_wall_s": _stats(tick_walls),
            "per_bucket": per_bucket,
            "per_tenant": per_tenant_q,
        }
    # Serving-grade fault tolerance (robust.dispatch / sched quarantine /
    # self-healing sessions): the guard's forensic trail aggregated next
    # to the fairness/queries tables — retries + backoff paid, tenants
    # quarantined out of their buckets, divergences the repair ladder
    # recovered, and queries answered in degraded mode.  Absent entirely
    # on a clean trace.
    degraded = [q for q in queries if q.get("degraded")]
    if health or degraded:
        retried = [e for e in health if e.get("event") == "dispatch_error"
                   and e.get("action") == "retried"]
        rb = {
            "dispatch_retries": len(retried),
            "backoff_s_total": sum(float(e.get("backoff_s") or 0.0)
                                   for e in health),
            "quarantines": sum(1 for e in health
                               if e.get("event") == "quarantine"),
            "recovered_divergences": sum(
                1 for e in health if e.get("event") == "divergence"
                and e.get("action") in ("restored", "repaired")),
            "degraded_queries": len(degraded),
        }
        per_tenant: dict = {}
        for e in health:
            t = e.get("tenant")
            if not t:
                continue
            pt = per_tenant.setdefault(str(t), {
                "events": 0, "retries": 0, "quarantined": False})
            pt["events"] += 1
            pt["retries"] += int(e.get("event") == "dispatch_error"
                                 and e.get("action") == "retried")
            pt["quarantined"] |= e.get("event") == "quarantine"
        per_sess: dict = {}

        def _sess(sid):
            return per_sess.setdefault(str(sid), {
                "events": 0, "retries": 0, "recovered_divergences": 0,
                "degraded_queries": 0})

        for e in health:
            sid = e.get("session")
            if not sid:
                continue
            ps = _sess(sid)
            ps["events"] += 1
            ps["retries"] += int(e.get("event") == "dispatch_error"
                                 and e.get("action") == "retried")
            ps["recovered_divergences"] += int(
                e.get("event") == "divergence"
                and e.get("action") in ("restored", "repaired"))
        for q in degraded:
            _sess(q.get("session", "?"))["degraded_queries"] += 1
        if per_tenant:
            rb["per_tenant"] = per_tenant
        if per_sess:
            rb["per_session"] = per_sess
        out["robustness"] = rb
    return out


def _fmt_s(x: float) -> str:
    return f"{1e3 * x:.1f}ms" if x < 1 else f"{x:.2f}s"


def _print_text(s: dict) -> None:
    print(f"events: {s['n_events']}   dispatches: {s['dispatches']} "
          f"(first-call {s['first_calls']}, recompile {s['recompiles']}, "
          f"errors {s['dispatch_errors']})")
    if "amortized_ms_per_iter" in s:
        print(f"amortized tunnel latency: "
              f"{s['amortized_ms_per_iter']:.2f} ms/iter "
              f"(barrier'd wall / fused iters)")
    dp = s.get("dispatch_percentiles_ms")
    if dp:
        print(f"dispatch walls: p50 {dp['p50']:.2f} ms, "
              f"p90 {dp['p90']:.2f} ms, p99 {dp['p99']:.2f} ms "
              f"(n={dp['n']})")
    if "wall_s" in s:
        ph = s.get("phases", {})
        print(f"wall: {_fmt_s(s['wall_s'])} "
              f"(dispatch {_fmt_s(ph.get('dispatch_s', 0.0))}, "
              f"transfer {_fmt_s(ph.get('transfer_s', 0.0))}, "
              f"host {_fmt_s(ph.get('host_s', 0.0))})")
    if "blocking_transfers" in s:
        line = f"blocking transfers (host barriers): {s['blocking_transfers']}"
        if s.get("nonblocking_transfers"):
            line += (f" (+{s['nonblocking_transfers']} overlapped by the "
                     f"dispatch pipeline)")
        print(line)
    cc = s.get("compile_cache")
    if cc:
        print(f"compile cache: {cc.get('entries')} entries at "
              f"{cc.get('dir')} ({cc.get('new_entries')} new this trace"
              f"{'' if cc.get('new_entries') else ' — warm'})")
    for name, p in s.get("programs", {}).items():
        line = (f"  {name}: {p['dispatches']} dispatch"
                f"{'es' if p['dispatches'] != 1 else ''}, "
                f"{len(p['shape_keys'])} shape key"
                f"{'s' if len(p['shape_keys']) != 1 else ''}")
        if p.get("recompiles"):
            line += f", {p['recompiles']} RECOMPILE"
            if p.get("bucketed_dispatches"):
                # Recompiles despite bucketing = genuine churn (shape/
                # config drift), not tail-chunk proliferation.
                line += " (genuine churn despite bucketing)"
        elif p.get("bucketed_dispatches"):
            line += ", bucketed reuse (1 executable serves all chunk sizes)"
        if p.get("speculative_dispatches"):
            line += (f", {p['speculative_dispatches']} speculative "
                     f"(queue depth {p.get('max_queue_depth')})")
        if p.get("fused_programs"):
            line += ", fused (1 program)"
        if "compile_proxy_s" in p:
            line += f", compile~{_fmt_s(max(p['compile_proxy_s'], 0.0))}"
        if "steady_s" in p:
            line += f", steady p50 {_fmt_s(p['steady_s']['p50'])}"
        if "amortized_ms_per_iter" in p:
            line += f", {p['amortized_ms_per_iter']:.2f} ms/iter"
        if p.get("errors"):
            line += f", {p['errors']} error{'s' if p['errors'] != 1 else ''}"
        print(line)
    c = s.get("convergence")
    if c and c.get("loglik_first") is None:
        # Batched chunk events carry state counts, not a loglik curve.
        print(f"convergence: {c['n_chunks']} chunks (batched: per-problem "
              f"curves live in the freeze/chunk events)")
    elif c:
        print(f"convergence: {c['n_iters']} iters in {c['n_chunks']} chunks, "
              f"loglik {c['loglik_first']:.6g} -> {c['loglik_last']:.6g}")
        if c.get("noise_floor") is not None:
            print(f"  noise floor {c['noise_floor']:.3g}; "
                  f"{c.get('deltas_below_floor', 0)}/{len(c['deltas'])} "
                  f"deltas below floor")
        if c.get("dparam_last") is not None:
            print(f"  per-iteration metrics: {len(c['dparams'])} rows, "
                  f"last max param-update {c['dparam_last']:.3g}")
    if s.get("freezes"):
        for f in s["freezes"]:
            print(f"  freeze: problem {f.get('problem')} -> "
                  f"{f.get('state')} (chunk {f.get('chunk')}, "
                  f"iter {f.get('iteration')})")
    if "health_events" in s:
        print(f"health: {s['health_events']} events "
              f"({', '.join(s['health_kinds'])})")
    rb = s.get("robustness")
    if rb:
        n = rb["dispatch_retries"]
        line = (f"robustness: {n} dispatch retr{'y' if n == 1 else 'ies'} "
                f"({_fmt_s(rb['backoff_s_total'])} backoff), "
                f"{rb['quarantines']} quarantine"
                f"{'' if rb['quarantines'] == 1 else 's'}, "
                f"{rb['recovered_divergences']} recovered divergence"
                f"{'' if rb['recovered_divergences'] == 1 else 's'}, "
                f"{rb['degraded_queries']} degraded quer"
                f"{'y' if rb['degraded_queries'] == 1 else 'ies'}")
        print(line)
        for t, pt in rb.get("per_tenant", {}).items():
            bits = [f"  tenant {t}: {pt['events']} event"
                    f"{'' if pt['events'] == 1 else 's'}"]
            if pt.get("retries"):
                bits.append(f"{pt['retries']} retries")
            if pt.get("quarantined"):
                bits.append("QUARANTINED -> requeued")
            print(", ".join(bits))
        for sid, ps in rb.get("per_session", {}).items():
            bits = [f"  session {sid}: {ps['events']} event"
                    f"{'' if ps['events'] == 1 else 's'}"]
            if ps.get("retries"):
                bits.append(f"{ps['retries']} retries")
            if ps.get("recovered_divergences"):
                bits.append(f"{ps['recovered_divergences']} recovered")
            if ps.get("degraded_queries"):
                bits.append(f"{ps['degraded_queries']} degraded")
            print(", ".join(bits))
    for name, c in s.get("costs", {}).items():
        bits = [f"{k}={v:.3g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in c.items() if k != "key"]
        print(f"  cost {name}: {' '.join(bits)}")
    for f in s.get("fits", []):
        bits = [f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in f.items() if k != "t"]
        print(f"  fit: {' '.join(bits)}")
    tf = s.get("tenant_fairness")
    if tf:
        qw = tf.get("queue_wait_s") or {}
        line = (f"tenants: {tf['n_tenants']} across {tf['n_buckets']} "
                f"bucket{'s' if tf['n_buckets'] != 1 else ''}, "
                f"{tf['converged']} converged")
        if qw:
            line += (f"; queue wait p50 {_fmt_s(qw['p50'])} / "
                     f"p99 {_fmt_s(qw['p99'])}")
        if isinstance(tf.get("pad_waste_frac_mean"), (int, float)):
            line += f"; mean pad waste {100 * tf['pad_waste_frac_mean']:.1f}%"
        print(line)
        for t in s.get("tenants", []):
            shape = f"({t.get('T')}, {t.get('N')}, {t.get('k')})"
            bshape = (f"({t.get('bucket_T')}, {t.get('bucket_N')}, "
                      f"{t.get('bucket_k')})")
            bits = [f"  {str(t.get('tenant', '?')):12s} {shape:>14s} -> "
                    f"bucket {t.get('bucket')} {bshape}"]
            if isinstance(t.get("queue_wait_s"), (int, float)):
                bits.append(f"wait {_fmt_s(float(t['queue_wait_s']))}")
            if isinstance(t.get("compute_s"), (int, float)):
                bits.append(f"compute {_fmt_s(float(t['compute_s']))}")
            if isinstance(t.get("pad_waste_frac"), (int, float)):
                bits.append(f"waste {100 * float(t['pad_waste_frac']):.1f}%")
            if t.get("n_iters") is not None:
                bits.append(f"{t['n_iters']} iters")
            bits.append("converged" if t.get("converged") else "NOT converged")
            print(", ".join(bits))
    qs = s.get("queries")
    if qs:
        qw = qs.get("query_wall_s") or {}
        line = (f"queries: {qs['n_queries']} across {qs['n_sessions']} "
                f"session{'s' if qs['n_sessions'] != 1 else ''}, "
                f"{qs['converged']} converged")
        if qs.get("diverged"):
            line += f", {qs['diverged']} DIVERGED"
        if qw:
            line += (f"; wall p50 {_fmt_s(qw['p50'])} / "
                     f"p99 {_fmt_s(qw['p99'])}")
        r = qs.get("recompiles_after_warmup", 0)
        line += (f"; recompiles after warmup {r}"
                 + (" (!!)" if r else ""))
        print(line)
        for sid, ps in qs.get("per_session", {}).items():
            bits = [f"  session {sid}: {ps['queries']} "
                    f"quer{'ies' if ps['queries'] != 1 else 'y'}"]
            if ps.get("t_rows") is not None:
                bits.append(f"{ps['t_rows']} rows held")
            pw = ps.get("query_wall_s") or {}
            if pw:
                bits.append(f"wall p50 {_fmt_s(pw['p50'])} / "
                            f"p99 {_fmt_s(pw['p99'])}")
            print(", ".join(bits))
    fl = s.get("fleet")
    if fl:
        tw = fl.get("tick_wall_s") or {}
        line = (f"fleet: {fl['n_queries']} queries over {fl['n_ticks']} "
                f"tick{'s' if fl['n_ticks'] != 1 else ''} in "
                f"{fl['n_buckets']} bucket{'s' if fl['n_buckets'] != 1 else ''}"
                f" ({fl['queries_per_dispatch']:.2f} queries/dispatch)")
        if isinstance(fl.get("occupancy_mean"), (int, float)):
            line += f"; mean occupancy {100 * fl['occupancy_mean']:.0f}%"
        if tw:
            line += (f"; tick wall p50 {_fmt_s(tw['p50'])} / "
                     f"p99 {_fmt_s(tw['p99'])}")
        print(line)
        for bid, pb in fl.get("per_bucket", {}).items():
            bits = [f"  bucket {bid}: {pb['ticks']} "
                    f"tick{'s' if pb['ticks'] != 1 else ''}"]
            if isinstance(pb.get("occupancy_mean"), (int, float)):
                bits.append(f"occupancy {100 * pb['occupancy_mean']:.0f}%")
            print(", ".join(bits))
        for tid, pt in fl.get("per_tenant", {}).items():
            bits = [f"  {tid:12s} {pt['queries']} "
                    f"quer{'ies' if pt['queries'] != 1 else 'y'}"]
            qw = pt.get("queue_wait_s") or {}
            if qw:
                bits.append(f"queue wait p50 {_fmt_s(qw['p50'])} / "
                            f"p99 {_fmt_s(qw['p99'])}")
            print(", ".join(bits))
    a = s.get("advice")
    if a:
        pred, real = a.get("predicted_wall_s"), a.get("realized_wall_s")
        line = f"advice: {a.get('engine', '?')} plan"
        if a.get("engine") == "fused" and a.get("fused_chunk") is not None:
            line += f" (fused_chunk={a['fused_chunk']})"
        elif a.get("depth") is not None:
            line += (f" (depth={a['depth']}"
                     f"{', bucket' if a.get('bucket') else ''})")
        if isinstance(pred, (int, float)):
            line += f", predicted {_fmt_s(float(pred))}"
        if isinstance(real, (int, float)):
            line += f", realized {_fmt_s(float(real))}"
        if isinstance(a.get("rel_err"), (int, float)):
            line += f", prediction error {100 * float(a['rel_err']):.0f}%"
        print(line)


_DEVICE_PID, _HOST_PID = 0, 1


def to_chrome(events: List[dict]) -> dict:
    """Convert an event stream to Chrome/Perfetto trace-event format
    (load the result in chrome://tracing or ui.perfetto.dev): dispatch
    spans land on a "device" track (one thread lane per program, so
    pipeline overlap is visible as stacked in-flight spans), transfers
    and host-side markers (chunk checks, fit/advice, health) on a "host"
    track.  Timestamps are rebased to the first event; ts/dur in µs."""
    timed = [e for e in events if isinstance(e.get("t"), (int, float))]
    if not timed:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(float(e["t"]) for e in timed)
    us = lambda t: 1e6 * (float(t) - t0)  # noqa: E731

    tids: dict = {}

    def tid(pid: int, lane: str) -> int:
        return tids.setdefault((pid, lane), len(
            [k for k in tids if k[0] == pid]))

    out = []
    _skip = ("t", "kind", "dur", "program")
    for e in timed:
        kind = e.get("kind")
        args = {k: v for k, v in e.items() if k not in _skip
                and v is not None}
        if kind == "dispatch":
            name = e.get("program", "?")
            out.append({"name": name, "ph": "X", "ts": us(e["t"]),
                        "dur": 1e6 * float(e.get("dur") or 0.0),
                        "pid": _DEVICE_PID, "tid": tid(_DEVICE_PID, name),
                        "cat": "dispatch", "args": args})
        elif kind == "transfer":
            name = ("transfer (blocking)" if e.get("blocking")
                    else "transfer")
            out.append({"name": name, "ph": "X", "ts": us(e["t"]),
                        "dur": 1e6 * float(e.get("dur") or 0.0),
                        "pid": _HOST_PID, "tid": tid(_HOST_PID, "transfer"),
                        "cat": "transfer", "args": args})
        else:
            # Host-side markers: convergence checks, fit/advice summaries,
            # cost captures, health — instants on their own host lane.
            out.append({"name": str(kind), "ph": "i", "s": "t",
                        "ts": us(e["t"]), "pid": _HOST_PID,
                        "tid": tid(_HOST_PID, str(kind)),
                        "cat": str(kind), "args": args})
    meta = [{"ph": "M", "name": "process_name", "pid": _DEVICE_PID,
             "args": {"name": "device (dispatch spans)"}},
            {"ph": "M", "name": "process_name", "pid": _HOST_PID,
             "args": {"name": "host (transfers + checks)"}}]
    meta += [{"ph": "M", "name": "thread_name", "pid": pid, "tid": t,
              "args": {"name": lane}} for (pid, lane), t in tids.items()]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dfm_tpu.obs.report",
        description="Summarize a DFM_TRACE JSONL trace.")
    ap.add_argument("trace", help="path to a trace.jsonl file")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    ap.add_argument("--chrome", default=None, metavar="OUT.json",
                    help="also export the trace to Chrome/Perfetto "
                         "trace-event format (chrome://tracing, "
                         "ui.perfetto.dev)")
    ap.add_argument("--diff", default=None, metavar="RUN|FILE",
                    help="diff this trace against a baseline (another "
                         "trace.jsonl, a RunRecord/bench JSON file, or a "
                         "registry run_id) via obs.regress; exits nonzero "
                         "on a perf/convergence regression")
    args = ap.parse_args(argv)
    if args.chrome is not None:
        trace = to_chrome(load(args.trace))
        with open(args.chrome, "w", encoding="utf-8") as fh:
            json.dump(trace, fh, default=str)
        n = sum(1 for e in trace["traceEvents"] if e.get("ph") != "M")
        print(f"chrome trace: {n} events -> {args.chrome}", file=sys.stderr)
    s = summarize(args.trace)
    if args.diff is not None:
        return _diff(s, args.trace, args.diff, as_json=args.json)
    if args.json:
        json.dump(s, sys.stdout, indent=2, default=str)
        print()
    else:
        _print_text(s)
    return 0


def _diff(s: dict, trace_path: str, baseline: str, *,
          as_json: bool = False) -> int:
    """Gate this trace's summary against a baseline through obs.regress
    (exit 0 ok / 1 regression / 2 usage)."""
    from . import regress
    from .store import RunStore, runs_dir
    cand = regress.record_from_trace_summary(s, source=trace_path)
    try:
        if baseline.endswith(".jsonl"):
            # Another trace: summarize it through the same adapter so the
            # two sides carry the same metric names.
            base = regress.record_from_trace_summary(
                summarize(baseline), source=baseline)
        else:
            d = runs_dir()
            store = RunStore(d) if d is not None else None
            base = regress._load_record(baseline, store)
        diff = regress.diff_records(cand, base)
    except (regress.UsageError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if as_json:
        json.dump(diff, sys.stdout, indent=2, default=str)
        print()
    else:
        regress.print_diff(diff)
    return 0 if diff["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

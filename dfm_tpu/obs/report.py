"""Offline trace summary: ``python -m dfm_tpu.obs.report trace.jsonl``.

Pure Python (no jax import) so the report runs instantly anywhere — on the
operator's laptop against a trace scp'd off the bench host, or in the round
driver between runs.  ``summarize`` is also what ``Tracer.summary()`` and
``FitResult.telemetry`` delegate to, so the offline CLI and the in-process
summary can never drift.

What it computes from the event stream (schema: ``obs/trace.py``):
- dispatch histogram per program, first-call vs steady wall times (the
  first-call minus steady-state gap is the only compile-time proxy the
  axon tunnel exposes), recompile events
- amortized tunnel latency: barrier'd dispatch wall / fused iterations —
  comparable against the sustained two-point rate in docs/PERF.md
- the convergence curve: per-chunk logliks, deltas vs the noise floor
- per-problem freezes (batched engine) and health events
- static flops/bytes per program when cost capture was on
- p50/p90/p99 dispatch walls (all spans + per-program end-to-end), and
  the advisor's predicted-vs-realized wall when ``fit(auto=True)`` ran
- a ``metrics`` digest: the trace replayed through the live plane's
  ``metrics.record_event`` mapping (identical to what ``obs.live``
  accumulates in-process)

``summarize`` is SINGLE-PASS and iterator-friendly: it accepts a JSONL
path, a list of paths (rotated traces, oldest first), or any iterable of
event dicts, and never materializes the event stream — flight-recorder
dumps and week-long soak traces report in O(1) memory (only the numeric
duration lists needed for exact nearest-rank percentiles are kept).

The JSON summary schema is versioned (top-level ``schema_version``) and
the ``tenants`` / ``tenant_fairness`` / ``queries`` / ``fleet`` /
``daemon`` / ``requests`` / ``robustness`` / ``metrics`` sections are
always present with stable keys,
empty or not.

The ``requests`` section aggregates request-scoped waterfalls
(``obs.trace`` ``request`` events): per-stage latency percentiles with
each stage's share of total stage time (the "where does p99 go"
attribution table), per-tenant breakdowns, tail exemplars (the slowest
trace_ids), and the maximum waterfall residual |sum(stages) - e2e| —
zero by construction, so anything over float fuzz flags a broken span.

``--chrome out.json`` additionally exports the raw event stream to
Chrome/Perfetto trace-event format for visual pipeline inspection;
request waterfalls become per-stage slices on a dedicated lane plus
Perfetto flow events linking each request to the query/dispatch spans
that carry its trace_id.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Iterable, Iterator, List, Union

__all__ = ["load", "iter_events", "summarize", "to_chrome", "main",
           "SCHEMA_VERSION"]

SCHEMA_VERSION = 1


def iter_events(path: str) -> Iterator[dict]:
    """Stream a JSONL trace, tolerating damage: empty files, and
    truncated/corrupt lines (a process killed mid-write leaves a partial
    last line) are warned about on stderr and skipped — a damaged trace
    must still summarize."""
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError as e:
                print(f"warning: {path}:{i + 1}: skipping invalid JSONL "
                      f"({e})", file=sys.stderr)
                continue
            if isinstance(ev, dict):
                yield ev
            else:
                print(f"warning: {path}:{i + 1}: skipping non-object line",
                      file=sys.stderr)


def load(path: str) -> List[dict]:
    """Parse a JSONL trace into a list (see ``iter_events``)."""
    return list(iter_events(path))


def _event_stream(events_or_path) -> Iterator[dict]:
    """Normalize summarize's input: a path, a list of paths (rotated
    traces, oldest first), or an iterable of event dicts."""
    if isinstance(events_or_path, str):
        yield from iter_events(events_or_path)
        return
    if (isinstance(events_or_path, (list, tuple)) and events_or_path
            and all(isinstance(p, str) for p in events_or_path)):
        for p in events_or_path:
            yield from iter_events(p)
        return
    yield from events_or_path


def _pct(xs: List[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list (the 1e-9 nudge
    keeps float fuzz like 0.9*10 == 9.000000000000002 from bumping the
    rank)."""
    import math
    rank = max(1, math.ceil(q * len(xs) - 1e-9))
    return xs[min(len(xs) - 1, rank - 1)]


def _stats(xs: List[float]) -> dict:
    if not xs:
        return {}
    xs = sorted(xs)
    n = len(xs)
    return {"n": n, "min": xs[0], "max": xs[-1],
            "mean": sum(xs) / n, "p50": _pct(xs, 0.50),
            "p90": _pct(xs, 0.90), "p99": _pct(xs, 0.99)}


def _numf(x):
    return float(x) if isinstance(x, (int, float)) else None


def summarize(events_or_path: Union[str, List[str], Iterable[dict]]) -> dict:
    """Aggregate an event stream in ONE pass (path(s) or dict iterable)."""
    from .metrics import MetricsRegistry, metrics_summary, record_event
    reg = MetricsRegistry()

    n_events = 0
    # dispatch accumulators (global + per-program)
    n_disp = n_first = n_recomp = n_disp_err = 0
    by_prog: dict = {}
    n_barrier_disp = 0
    fused_iters = 0        # n_iters inside fused-flag barrier'd spans
    barrier_iters = 0      # n_iters (or 1) of every barrier'd span
    barrier_walls: List[float] = []
    all_durs: List[float] = []
    serve_recompiles = 0
    # transfers
    n_blocking_tr = n_nonblocking_tr = 0
    transfer_s = 0.0
    saw_transfer = False
    # compile cache
    cache_last = None
    cache_new = 0
    # wall clock envelope
    t_min = t_end = None
    # chunks / convergence
    n_chunks = 0
    lls: List[float] = []
    noise_floor = None
    below_floor = 0
    dparams: List[float] = []
    # pass-through sections
    freezes: List[dict] = []
    costs: dict = {}
    fits: List[dict] = []
    tenants: List[dict] = []
    advice_last = None
    n_advice = 0
    # differentiable hyper-tuning (estim/tune.py)
    tune_last = None
    n_tunes = 0
    # health / robustness
    n_health = 0
    health_kinds = set()
    backoff_s_total = 0.0
    n_retried = n_quar = n_recovered = 0
    rb_tenant: dict = {}
    rb_sess: dict = {}
    # queries / sessions
    n_queries = q_conv = q_div = 0
    q_walls: List[float] = []
    q_sessions: dict = {}
    n_degraded = 0
    degraded_sess: List[str] = []
    n_fleet_q = 0
    fleet_tenant: dict = {}
    # fleet ticks
    n_ticks = 0
    occ: List[float] = []
    tick_walls: List[float] = []
    per_bucket: dict = {}
    # ring eviction + snapshot tiering
    rows_evicted = 0
    n_evicting_q = 0
    page_counts: dict = {}
    admit_walls: List[float] = []
    # serving daemon (dfm_tpu/daemon/ front door)
    dm_counts: dict = {}
    dm_depths: List[float] = []
    dm_gaps: List[float] = []
    dm_replayed = 0
    dm_tenant: dict = {}
    n_shed = 0
    # model-quality maintenance (obs/drift + fleet/maintenance)
    n_drift_fired = n_drift_cleared = 0
    mt_counts: dict = {}
    mt_tenant: dict = {}
    # request-scoped waterfalls (obs.trace request events)
    rq_n = rq_replayed = rq_dedup = 0
    rq_e2e: List[float] = []
    rq_exemplars: List = []       # (e2e, trace_id) — tail kept at the end
    rq_stage: dict = {}           # stage name -> walls
    rq_tenant: dict = {}
    rq_residual_max = 0.0         # max |sum(stages) - e2e| seen

    def _mt_row(who: str) -> dict:
        return mt_tenant.setdefault(who, {
            "drift_fires": 0, "drift_clears": 0, "drift_score": None,
            "trigger": {}, "refits": 0, "refit_s": 0.0, "swaps": 0,
            "skips": 0, "quality_delta": None, "engine": None,
            "advice": None, "action": None})

    for e in _event_stream(events_or_path):
        n_events += 1
        record_event(reg, None, e)
        t = e.get("t")
        if isinstance(t, (int, float)):
            tf = float(t)
            end = tf + float(e.get("dur") or 0.0)
            t_min = tf if t_min is None else min(t_min, tf)
            t_end = end if t_end is None else max(t_end, end)
        kind = e.get("kind")
        if kind == "dispatch":
            n_disp += 1
            first = bool(e.get("first_call"))
            n_first += first
            n_recomp += bool(e.get("recompile"))
            n_disp_err += bool(e.get("error"))
            if (e.get("program") == "serve_update" and e.get("recompile")):
                serve_recompiles += 1
            p = by_prog.setdefault(e.get("program", "?"), {
                "dispatches": 0, "first_calls": 0, "recompiles": 0,
                "errors": 0, "keys": set(), "first_durs": [],
                "steady_durs": [], "barrier_durs": [], "fused_iters": 0,
                "bucketed": 0, "queue_depths": [], "fused_programs": 0})
            p["dispatches"] += 1
            p["keys"].add(e.get("key", ""))
            if e.get("error"):
                p["errors"] += 1
            p["first_calls"] += first
            p["recompiles"] += bool(e.get("recompile"))
            p["bucketed"] += e.get("bucket") is not None
            p["fused_programs"] += bool(e.get("fused"))
            if e.get("queue_depth") is not None:
                p["queue_depths"].append(int(e["queue_depth"]))
            dur = e.get("dur")
            if dur is not None:
                (p["first_durs"] if first else p["steady_durs"]).append(dur)
                all_durs.append(float(dur))
                if e.get("barrier"):
                    p["barrier_durs"].append(dur)
                    p["fused_iters"] += int(e.get("n_iters") or 1)
                    barrier_walls.append(float(dur))
            if e.get("barrier"):
                n_barrier_disp += 1
                barrier_iters += int(e.get("n_iters") or 1)
                if e.get("fused"):
                    fused_iters += int(e.get("n_iters") or 0)
        elif kind == "transfer":
            saw_transfer = True
            if e.get("blocking"):
                n_blocking_tr += 1
            else:
                n_nonblocking_tr += 1
            transfer_s += float(e.get("dur") or 0.0)
        elif kind == "chunk":
            n_chunks += 1
            lls.extend(float(x) for x in e.get("lls", []))
            if noise_floor is None and e.get("noise_floor") is not None:
                noise_floor = e.get("noise_floor")
            below_floor += bool(e.get("below_floor"))
            dparams.extend(float(x) for x in e.get("dparams", []))
        elif kind == "compile_cache":
            cache_last = e
            cache_new += int(e.get("new_entries") or 0)
        elif kind == "advice":
            advice_last = e
            n_advice += 1
        elif kind == "tune":
            tune_last = e
            n_tunes += 1
        elif kind == "freeze":
            freezes.append({k: v for k, v in e.items() if k != "kind"})
        elif kind == "cost":
            costs[e.get("program", "?")] = {
                k: v for k, v in e.items()
                if k not in ("t", "kind", "program")}
        elif kind == "fit":
            fits.append({k: v for k, v in e.items() if k != "kind"})
        elif kind == "tenant":
            tenants.append({k: v for k, v in e.items() if k != "kind"})
        elif kind == "query":
            n_queries += 1
            q_conv += bool(e.get("converged"))
            q_div += bool(e.get("diverged"))
            sid = str(e.get("session", "?"))
            ps = q_sessions.setdefault(
                sid, {"queries": 0, "walls": [], "t_rows": None,
                      "engine": None, "covs": []})
            ps["queries"] += 1
            if isinstance(e.get("wall"), (int, float)):
                ps["walls"].append(float(e["wall"]))
                q_walls.append(float(e["wall"]))
            if e.get("t_rows") is not None:
                ps["t_rows"] = int(e["t_rows"])
            if e.get("engine"):
                ps["engine"] = str(e["engine"])
            if isinstance(e.get("coverage"), (int, float)):
                ps["covs"].append(float(e["coverage"]))
            if e.get("degraded"):
                n_degraded += 1
                degraded_sess.append(sid)
            if e.get("n_evicted"):
                rows_evicted += int(e["n_evicted"])
                n_evicting_q += 1
            if e.get("queue_wait") is not None:
                n_fleet_q += 1
                pt = fleet_tenant.setdefault(
                    str(e.get("tenant", "?")),
                    {"queries": 0, "waits": [], "engine": None, "covs": []})
                pt["queries"] += 1
                if isinstance(e.get("queue_wait"), (int, float)):
                    pt["waits"].append(float(e["queue_wait"]))
                if e.get("engine"):
                    pt["engine"] = str(e["engine"])
                if isinstance(e.get("coverage"), (int, float)):
                    pt["covs"].append(float(e["coverage"]))
        elif kind == "tick":
            n_ticks += 1
            if (isinstance(e.get("n_active"), (int, float))
                    and e.get("batch")):
                occ.append(float(e["n_active"]) / float(e["batch"]))
            if isinstance(e.get("wall"), (int, float)):
                tick_walls.append(float(e["wall"]))
            bid = str(e.get("bucket", "?"))
            pb = per_bucket.setdefault(bid, {"ticks": 0, "occ": []})
            pb["ticks"] += 1
            if (isinstance(e.get("n_active"), (int, float))
                    and e.get("batch")):
                pb["occ"].append(float(e["n_active"]) / float(e["batch"]))
        elif kind == "page":
            act = str(e.get("action", "?"))
            page_counts[act] = page_counts.get(act, 0) + 1
            if act == "admit" and isinstance(e.get("wall"), (int, float)):
                admit_walls.append(float(e["wall"]))
        elif kind == "daemon":
            act = str(e.get("action", "?"))
            dm_counts[act] = dm_counts.get(act, 0) + 1
            if (act in ("request", "backpressure")
                    and isinstance(e.get("depth"), (int, float))):
                dm_depths.append(float(e["depth"]))
            if act == "handoff" and isinstance(e.get("gap_ms"),
                                               (int, float)):
                dm_gaps.append(float(e["gap_ms"]))
            if act == "replay":
                dm_replayed += int(e.get("n_entries") or 0)
            ten = e.get("tenant")
            if ten is not None and act in ("request", "backpressure"):
                pt = dm_tenant.setdefault(str(ten), {
                    "requests": 0, "backpressure": 0, "shed": 0})
                pt["requests" if act == "request"
                   else "backpressure"] += 1
        elif kind == "request":
            rq_n += 1
            rq_replayed += bool(e.get("replay"))
            rq_dedup += bool(e.get("dedup"))
            stages = e.get("stages") or {}
            e2e = e.get("e2e")
            ssum = 0.0
            for nm, d in stages.items():
                if isinstance(d, (int, float)):
                    rq_stage.setdefault(str(nm), []).append(float(d))
                    ssum += float(d)
            if isinstance(e2e, (int, float)):
                rq_e2e.append(float(e2e))
                tidv = str(e.get("trace_id") or "")
                if tidv:
                    rq_exemplars.append((float(e2e), tidv))
                if stages:
                    rq_residual_max = max(rq_residual_max,
                                          abs(ssum - float(e2e)))
            who = str(e.get("tenant") or e.get("session") or "?")
            pr = rq_tenant.setdefault(
                who, {"n": 0, "e2e": [], "stages": {}})
            pr["n"] += 1
            if isinstance(e2e, (int, float)):
                pr["e2e"].append(float(e2e))
            for nm, d in stages.items():
                if isinstance(d, (int, float)):
                    pr["stages"].setdefault(str(nm), []).append(float(d))
        elif kind == "maintenance":
            act = str(e.get("action", "?"))
            mt_counts[act] = mt_counts.get(act, 0) + 1
            mt = _mt_row(str(e.get("tenant", "?")))
            if act == "trigger":
                mt["trigger"] = {
                    k: float(e[k]) for k in
                    ("drift_score", "innov_z", "coverage", "ll_per_row")
                    if isinstance(e.get(k), (int, float))}
                mt["engine"] = e.get("engine")
                mt["advice"] = e.get("advice")
            elif act == "refit":
                mt["refits"] += 1
                if isinstance(e.get("refit_s"), (int, float)):
                    mt["refit_s"] += float(e["refit_s"])
            elif act in ("swap", "retune", "skip"):
                # "retune" is a swap whose winning candidate came from the
                # hyper search (MaintenancePolicy(retune=True)).
                mt["skips" if act == "skip" else "swaps"] += 1
                mt["action"] = act
                if isinstance(e.get("quality_delta"), (int, float)):
                    mt["quality_delta"] = float(e["quality_delta"])
        elif kind == "health":
            n_health += 1
            health_kinds.add(e.get("event", e.get("name", "?")))
            backoff_s_total += float(e.get("backoff_s") or 0.0)
            retried = (e.get("event") == "dispatch_error"
                       and e.get("action") == "retried")
            n_retried += retried
            n_quar += e.get("event") == "quarantine"
            n_recovered += (e.get("event") == "divergence"
                            and e.get("action") in ("restored", "repaired"))
            if e.get("event") == "shed":
                n_shed += 1
                pt = dm_tenant.setdefault(str(e.get("tenant", "?")), {
                    "requests": 0, "backpressure": 0, "shed": 0})
                pt["shed"] += 1
            if e.get("event") == "drift":
                who = str(e.get("tenant") or e.get("session") or "?")
                mt = _mt_row(who)
                if e.get("action") == "fired":
                    n_drift_fired += 1
                    mt["drift_fires"] += 1
                else:
                    n_drift_cleared += 1
                    mt["drift_clears"] += 1
                if isinstance(e.get("drift_score"), (int, float)):
                    mt["drift_score"] = float(e["drift_score"])
            ten = e.get("tenant")
            if ten:
                pt = rb_tenant.setdefault(str(ten), {
                    "events": 0, "retries": 0, "quarantined": False})
                pt["events"] += 1
                pt["retries"] += int(retried)
                pt["quarantined"] |= e.get("event") == "quarantine"
            sid = e.get("session")
            if sid:
                ps = rb_sess.setdefault(str(sid), {
                    "events": 0, "retries": 0, "recovered_divergences": 0,
                    "degraded_queries": 0})
                ps["events"] += 1
                ps["retries"] += int(retried)
                ps["recovered_divergences"] += int(
                    e.get("event") == "divergence"
                    and e.get("action") in ("restored", "repaired"))

    programs = {}
    for name, p in sorted(by_prog.items()):
        entry = {"dispatches": p["dispatches"],
                 "first_calls": p["first_calls"],
                 "recompiles": p["recompiles"],
                 "shape_keys": sorted(p["keys"])}
        if p["bucketed"]:
            entry["bucketed_dispatches"] = p["bucketed"]
        if p["fused_programs"]:
            # A while-loop fit: the whole EM ran inside this one span.
            entry["fused_programs"] = p["fused_programs"]
        if p["queue_depths"]:
            # Speculative (pipelined) launches: depth>1 means the host
            # issued this chunk while an older one was still in flight.
            entry["speculative_dispatches"] = sum(
                1 for d in p["queue_depths"] if d > 1)
            entry["max_queue_depth"] = max(p["queue_depths"])
        if p["errors"]:
            entry["errors"] = p["errors"]
        if p["first_durs"]:
            entry["first_call_s"] = _stats(p["first_durs"])
        if p["steady_durs"]:
            entry["steady_s"] = _stats(p["steady_durs"])
        if p["barrier_durs"]:
            # End-to-end walls: spans the host actually waited out (d2h
            # barrier inside the span) — the serving-latency view.
            entry["e2e_s"] = _stats(p["barrier_durs"])
        # Compile proxy: how much slower the first call ran than steady state.
        if p["first_durs"] and p["steady_durs"]:
            entry["compile_proxy_s"] = (max(p["first_durs"])
                                        - _stats(p["steady_durs"])["p50"])
        if p["fused_iters"]:
            entry["amortized_ms_per_iter"] = (
                1e3 * sum(p["barrier_durs"]) / p["fused_iters"])
        programs[name] = entry

    convergence = None
    if n_chunks:
        deltas = [lls[i + 1] - lls[i] for i in range(len(lls) - 1)]
        convergence = {"n_chunks": n_chunks, "n_iters": len(lls),
                       "loglik_first": lls[0] if lls else None,
                       "loglik_last": lls[-1] if lls else None,
                       "deltas": deltas, "noise_floor": noise_floor,
                       "below_floor": below_floor}
        if noise_floor is not None and deltas:
            convergence["deltas_below_floor"] = sum(
                1 for d in deltas if abs(d) < noise_floor)
        # Device-side per-iteration metrics (fit(progress=...) /
        # metrics-enabled chunks): max param-update norm per iteration.
        if dparams:
            convergence["dparams"] = dparams
            convergence["dparam_last"] = dparams[-1]

    out = {
        "schema_version": SCHEMA_VERSION,
        "n_events": n_events,
        "dispatches": n_disp,
        "first_calls": n_first,
        "recompiles": n_recomp,
        "dispatch_errors": n_disp_err,
        "programs": programs,
    }
    # Execution barriers the host actually waited on: barrier'd dispatch
    # spans (transfer inside the span) + explicit blocking transfer events
    # (the pipelined drivers' one-pull-per-round).  The pipelining win is
    # this number dropping from n_chunks to ~n_chunks/depth.
    out["blocking_transfers"] = n_barrier_disp + n_blocking_tr
    # While-loop (fused) fits: EM iterations that ran inside a single
    # dispatch span — the dispatch-free serving path's headline count.
    if fused_iters:
        out["fused_iterations"] = fused_iters
    if saw_transfer:
        out["nonblocking_transfers"] = n_nonblocking_tr
    if cache_last is not None:
        out["compile_cache"] = {
            "dir": cache_last.get("dir"),
            "entries": cache_last.get("entries"),
            "new_entries": cache_new}
    if barrier_walls:
        out["barrier_dispatch_s"] = _stats(barrier_walls)
        out["amortized_ms_per_iter"] = (
            1e3 * sum(barrier_walls) / max(barrier_iters, 1))
    # Latency percentiles over ALL timed dispatch spans (barrier'd or
    # enqueue-only) — the p50/p90/p99 the serving path will be scored on.
    if all_durs:
        st = _stats(all_durs)
        out["dispatch_percentiles_ms"] = {
            "p50": 1e3 * st["p50"], "p90": 1e3 * st["p90"],
            "p99": 1e3 * st["p99"], "n": st["n"]}
    # Auto-tuning advisor: the last advice event wins (one per fit(auto=
    # True)); predicted-vs-realized wall is the model-drift metric that
    # obs.regress gates as ``advice_rel_err``.
    if advice_last is not None:
        out["advice"] = {k: v for k, v in advice_last.items()
                         if k not in ("kind", "t")}
        if n_advice > 1:
            out["advice"]["n_events"] = n_advice
    # Differentiable hyper-tuning (estim/tune.py): the last tune event
    # wins (one per tune_fit call); ``dispatches`` is the budget metric
    # obs.regress gates as ``tune_dispatches``.
    if tune_last is not None:
        out["tune"] = {k: v for k, v in tune_last.items()
                       if k not in ("kind", "t")}
        if n_tunes > 1:
            out["tune"]["n_events"] = n_tunes
    # Total wall + per-phase breakdown: dispatch (device walls measured
    # behind a barrier or async enqueue), transfer (h2d/d2h walls), host
    # (everything else — python driver, numpy, event emission).
    if t_min is not None:
        wall = max(t_end - t_min, 0.0)
        dispatch_s = sum(all_durs)
        out["wall_s"] = wall
        out["phases"] = {
            "dispatch_s": dispatch_s, "transfer_s": transfer_s,
            "host_s": max(wall - dispatch_s - transfer_s, 0.0)}
    if convergence is not None:
        out["convergence"] = convergence
    if freezes:
        out["freezes"] = freezes
    if n_health:
        out["health_events"] = n_health
        out["health_kinds"] = sorted(health_kinds)
    if costs:
        out["costs"] = costs
    if fits:
        out["fits"] = fits
    # -- stable sections (always present, empty or not) ------------------
    # Multi-tenant scheduler (sched.submit / fit_jobs): one event per job
    # with its bucket assignment and queue/compute/pad-waste accounting.
    waits = [float(t["queue_wait_s"]) for t in tenants
             if isinstance(t.get("queue_wait_s"), (int, float))]
    wastes = [float(t["pad_waste_frac"]) for t in tenants
              if isinstance(t.get("pad_waste_frac"), (int, float))]
    out["tenants"] = tenants
    out["tenant_fairness"] = {
        "n_tenants": len(tenants),
        "n_buckets": len({t.get("bucket") for t in tenants}),
        "converged": sum(1 for t in tenants if t.get("converged")),
        "queue_wait_s": _stats(waits),
        "pad_waste_frac_mean": (sum(wastes) / len(wastes)
                                if wastes else None)}
    # Streaming nowcast sessions (serve.NowcastSession): one event per
    # query with its end-to-end wall, row counts and convergence flags.
    # Warm-path health: any serve_update recompile past each executable's
    # first call means the session's one-program promise broke (shape
    # drift / cache eviction) — should be 0.
    for ps in q_sessions.values():
        st = _stats(ps.pop("walls"))
        if st:
            ps["query_wall_s"] = st
        covs = ps.pop("covs")
        if covs:
            ps["forecast_coverage"] = sum(covs) / len(covs)
    out["queries"] = {
        "n_queries": n_queries,
        "n_sessions": len(q_sessions),
        "converged": q_conv,
        "diverged": q_div,
        "query_wall_s": _stats(q_walls),
        "recompiles_after_warmup": serve_recompiles,
        "rows_evicted": rows_evicted,
        "evicting_queries": n_evicting_q,
        "per_session": q_sessions,
    }
    # Fleet serving (fleet.SessionFleet): one event per drained tick with
    # the bucket's occupancy (active lanes / batch width), plus queue-wait
    # accounting on the per-tenant query events.  Queries-per-dispatch is
    # the multiplexing win itself: how many tenant answers each fused
    # batched serve_update dispatch produced.
    for pt in fleet_tenant.values():
        st = _stats(pt.pop("waits"))
        if st:
            pt["queue_wait_s"] = st
        covs = pt.pop("covs")
        if covs:
            pt["forecast_coverage"] = sum(covs) / len(covs)
    for pb in per_bucket.values():
        os_ = pb.pop("occ")
        if os_:
            pb["occupancy_mean"] = sum(os_) / len(os_)
    out["fleet"] = {
        "n_ticks": n_ticks,
        "n_buckets": len(per_bucket),
        "n_queries": n_fleet_q,
        "queries_per_dispatch": (n_fleet_q / n_ticks) if n_ticks else None,
        "occupancy_mean": (sum(occ) / len(occ)) if occ else None,
        "tick_wall_s": _stats(tick_walls),
        "per_bucket": per_bucket,
        "per_tenant": fleet_tenant,
        # Snapshot tiering: hot/warm/cold paging traffic — admits are the
        # latency that matters (the query that paid the page-in).
        "paging": {
            "admits": page_counts.get("admit", 0),
            "demotes": page_counts.get("demote", 0),
            "spills": page_counts.get("spill", 0),
            "readmission_s": _stats(admit_walls),
        },
    }
    # Serving daemon (dfm_tpu/daemon/): the front door's admission and
    # lifecycle trail — accepted requests with queue depth at enqueue,
    # deterministic backpressure, SLO-burn load-sheds (HealthEvents, so
    # they also land in the robustness section), snapshots, journal
    # replays, and blue/green handoffs with the gap each one cost.
    out["daemon"] = {
        "n_requests": dm_counts.get("request", 0),
        "n_backpressure": dm_counts.get("backpressure", 0),
        "n_shed": n_shed,
        "n_snapshots": dm_counts.get("snapshot", 0),
        "n_replays": dm_counts.get("replay", 0),
        "n_replayed_entries": dm_replayed,
        "n_handoffs": dm_counts.get("handoff", 0),
        "queue_depth": _stats(dm_depths),
        "handoff_gap_ms": _stats(dm_gaps),
        "per_tenant": dm_tenant,
    }
    # Serving-grade fault tolerance (robust.dispatch / sched quarantine /
    # self-healing sessions): the guard's forensic trail aggregated next
    # to the fairness/queries tables — retries + backoff paid, tenants
    # quarantined out of their buckets, divergences the repair ladder
    # recovered, and queries answered in degraded mode.
    for sid in degraded_sess:
        ps = rb_sess.setdefault(str(sid), {
            "events": 0, "retries": 0, "recovered_divergences": 0,
            "degraded_queries": 0})
        ps["degraded_queries"] += 1
    out["robustness"] = {
        "dispatch_retries": n_retried,
        "backoff_s_total": backoff_s_total,
        "quarantines": n_quar,
        "recovered_divergences": n_recovered,
        "degraded_queries": n_degraded,
        "per_tenant": rb_tenant,
        "per_session": rb_sess,
    }
    # Model-quality maintenance (obs/drift + fleet/maintenance): the
    # closed loop's decision trail — drift detector transitions from the
    # HealthEvents the live plane emits, plus per-tenant trigger/refit/
    # swap rows from the maintenance trace events.
    out["maintenance"] = {
        "drift_fires": n_drift_fired,
        "drift_clears": n_drift_cleared,
        "triggers": mt_counts.get("trigger", 0),
        "refits": mt_counts.get("refit", 0),
        "swaps": mt_counts.get("swap", 0) + mt_counts.get("retune", 0),
        "retunes": mt_counts.get("retune", 0),
        "skips": mt_counts.get("skip", 0),
        "per_tenant": mt_tenant,
    }
    # Request-scoped waterfalls (obs.trace): the per-stage decomposition
    # of client-observed latency.  ``per_stage`` is the "where does p99
    # go" attribution table — each stage's percentiles plus its share of
    # total stage time; ``tail_exemplars`` are the slowest trace_ids (the
    # requests to pull out of the raw trace / flight dump when chasing
    # the p99); ``waterfall_residual_max_s`` must sit at float fuzz —
    # stages telescope off one CLOCK_MONOTONIC timeline by construction.
    def _stage_table(stage_walls: dict) -> dict:
        tot = sum(sum(v) for v in stage_walls.values())
        tbl = {}
        order = ("client_send", "queue_wait", "batch_form", "dispatch",
                 "d2h", "ack")
        for nm in list(order) + sorted(set(stage_walls) - set(order)):
            if nm not in stage_walls:
                continue
            st = _stats(stage_walls[nm])
            st["share"] = (sum(stage_walls[nm]) / tot) if tot > 0 else None
            tbl[nm] = st
        return tbl

    for pr in rq_tenant.values():
        pr["e2e_s"] = _stats(pr.pop("e2e"))
        pr["per_stage"] = _stage_table(pr.pop("stages"))
    rq_exemplars.sort(key=lambda p: -p[0])
    out["requests"] = {
        "n_requests": rq_n,
        "replayed": rq_replayed,
        "dedup": rq_dedup,
        "e2e_s": _stats(rq_e2e),
        "per_stage": _stage_table(rq_stage),
        "per_tenant": rq_tenant,
        "tail_exemplars": [{"e2e_s": v, "trace_id": t}
                           for v, t in rq_exemplars[:3]],
        "waterfall_residual_max_s": rq_residual_max,
    }
    # The live-plane digest: the same record_event mapping obs.live runs
    # in-process, replayed over this trace.
    out["metrics"] = metrics_summary(reg)
    return out


def _fmt_s(x: float) -> str:
    return f"{1e3 * x:.1f}ms" if x < 1 else f"{x:.2f}s"


def _print_text(s: dict) -> None:
    print(f"events: {s['n_events']}   dispatches: {s['dispatches']} "
          f"(first-call {s['first_calls']}, recompile {s['recompiles']}, "
          f"errors {s['dispatch_errors']})")
    if "amortized_ms_per_iter" in s:
        print(f"amortized tunnel latency: "
              f"{s['amortized_ms_per_iter']:.2f} ms/iter "
              f"(barrier'd wall / fused iters)")
    dp = s.get("dispatch_percentiles_ms")
    if dp:
        print(f"dispatch walls: p50 {dp['p50']:.2f} ms, "
              f"p90 {dp['p90']:.2f} ms, p99 {dp['p99']:.2f} ms "
              f"(n={dp['n']})")
    if "wall_s" in s:
        ph = s.get("phases", {})
        print(f"wall: {_fmt_s(s['wall_s'])} "
              f"(dispatch {_fmt_s(ph.get('dispatch_s', 0.0))}, "
              f"transfer {_fmt_s(ph.get('transfer_s', 0.0))}, "
              f"host {_fmt_s(ph.get('host_s', 0.0))})")
    if "blocking_transfers" in s:
        line = f"blocking transfers (host barriers): {s['blocking_transfers']}"
        if s.get("nonblocking_transfers"):
            line += (f" (+{s['nonblocking_transfers']} overlapped by the "
                     f"dispatch pipeline)")
        print(line)
    cc = s.get("compile_cache")
    if cc:
        print(f"compile cache: {cc.get('entries')} entries at "
              f"{cc.get('dir')} ({cc.get('new_entries')} new this trace"
              f"{'' if cc.get('new_entries') else ' — warm'})")
    m = s.get("metrics")
    if m and m.get("n_series"):
        print(f"metrics: {m['n_series']} live series "
              f"({len(m.get('counters', {}))} counters, "
              f"{len(m.get('histograms', {}))} quantile series)")
    for name, p in s.get("programs", {}).items():
        line = (f"  {name}: {p['dispatches']} dispatch"
                f"{'es' if p['dispatches'] != 1 else ''}, "
                f"{len(p['shape_keys'])} shape key"
                f"{'s' if len(p['shape_keys']) != 1 else ''}")
        if p.get("recompiles"):
            line += f", {p['recompiles']} RECOMPILE"
            if p.get("bucketed_dispatches"):
                # Recompiles despite bucketing = genuine churn (shape/
                # config drift), not tail-chunk proliferation.
                line += " (genuine churn despite bucketing)"
        elif p.get("bucketed_dispatches"):
            line += ", bucketed reuse (1 executable serves all chunk sizes)"
        if p.get("speculative_dispatches"):
            line += (f", {p['speculative_dispatches']} speculative "
                     f"(queue depth {p.get('max_queue_depth')})")
        if p.get("fused_programs"):
            line += ", fused (1 program)"
        if "compile_proxy_s" in p:
            line += f", compile~{_fmt_s(max(p['compile_proxy_s'], 0.0))}"
        if "steady_s" in p:
            line += f", steady p50 {_fmt_s(p['steady_s']['p50'])}"
        if "amortized_ms_per_iter" in p:
            line += f", {p['amortized_ms_per_iter']:.2f} ms/iter"
        if p.get("errors"):
            line += f", {p['errors']} error{'s' if p['errors'] != 1 else ''}"
        print(line)
    c = s.get("convergence")
    if c and c.get("loglik_first") is None:
        # Batched chunk events carry state counts, not a loglik curve.
        print(f"convergence: {c['n_chunks']} chunks (batched: per-problem "
              f"curves live in the freeze/chunk events)")
    elif c:
        print(f"convergence: {c['n_iters']} iters in {c['n_chunks']} chunks, "
              f"loglik {c['loglik_first']:.6g} -> {c['loglik_last']:.6g}")
        if c.get("noise_floor") is not None:
            print(f"  noise floor {c['noise_floor']:.3g}; "
                  f"{c.get('deltas_below_floor', 0)}/{len(c['deltas'])} "
                  f"deltas below floor")
        if c.get("dparam_last") is not None:
            print(f"  per-iteration metrics: {len(c['dparams'])} rows, "
                  f"last max param-update {c['dparam_last']:.3g}")
    if s.get("freezes"):
        for f in s["freezes"]:
            print(f"  freeze: problem {f.get('problem')} -> "
                  f"{f.get('state')} (chunk {f.get('chunk')}, "
                  f"iter {f.get('iteration')})")
    if "health_events" in s:
        print(f"health: {s['health_events']} events "
              f"({', '.join(s['health_kinds'])})")
    dm = s.get("daemon")
    if dm and (dm["n_requests"] or dm["n_backpressure"] or dm["n_shed"]
               or dm["n_handoffs"] or dm["n_replays"]):
        line = (f"daemon: {dm['n_requests']} requests, "
                f"{dm['n_backpressure']} backpressure, "
                f"{dm['n_shed']} shed, {dm['n_snapshots']} snapshots")
        qd = dm.get("queue_depth") or {}
        if qd:
            line += (f"; queue depth p50 {qd['p50']:.0f} / "
                     f"p99 {qd['p99']:.0f}")
        print(line)
        if dm["n_handoffs"] or dm["n_replays"]:
            line = (f"  lifecycle: {dm['n_handoffs']} "
                    f"handoff{'s' if dm['n_handoffs'] != 1 else ''}, "
                    f"{dm['n_replays']} "
                    f"replay{'s' if dm['n_replays'] != 1 else ''} "
                    f"({dm['n_replayed_entries']} entries)")
            hg = dm.get("handoff_gap_ms") or {}
            if hg:
                line += f"; handoff gap p99 {hg['p99']:.1f} ms"
            print(line)
        for tid, pt in dm.get("per_tenant", {}).items():
            if pt["backpressure"] or pt["shed"]:
                print(f"  {tid:12s} {pt['requests']} accepted, "
                      f"{pt['backpressure']} backpressure, "
                      f"{pt['shed']} shed")
    rq = s.get("requests")
    if rq and rq["n_requests"]:
        e2 = rq.get("e2e_s") or {}
        line = f"requests: {rq['n_requests']} waterfall"
        line += "s" if rq["n_requests"] != 1 else ""
        extras = []
        if rq.get("replayed"):
            extras.append(f"{rq['replayed']} replayed")
        if rq.get("dedup"):
            extras.append(f"{rq['dedup']} dedup")
        if extras:
            line += f" ({', '.join(extras)})"
        if e2:
            line += (f"; e2e p50 {_fmt_s(e2['p50'])} / "
                     f"p99 {_fmt_s(e2['p99'])}")
        line += (f"; waterfall residual max "
                 f"{1e3 * rq['waterfall_residual_max_s']:.3f} ms")
        print(line)
        ps = rq.get("per_stage") or {}
        if ps:
            # Where does the p99 go: per-stage walls + share of total.
            print(f"  {'stage':12s} {'p50':>9s} {'p99':>9s} {'share':>7s}")
            for nm, st in ps.items():
                share = (f"{100 * st['share']:6.1f}%"
                         if isinstance(st.get("share"), (int, float))
                         else "      -")
                print(f"  {nm:12s} {_fmt_s(st['p50']):>9s} "
                      f"{_fmt_s(st['p99']):>9s} {share:>7s}")
        for who, pr in (rq.get("per_tenant") or {}).items():
            e2t = pr.get("e2e_s") or {}
            bits = [f"  {who:12s} {pr['n']} request"
                    f"{'s' if pr['n'] != 1 else ''}"]
            if e2t:
                bits.append(f"e2e p50 {_fmt_s(e2t['p50'])} / "
                            f"p99 {_fmt_s(e2t['p99'])}")
            pst = pr.get("per_stage") or {}
            if pst:
                top = max(pst.items(),
                          key=lambda kv: kv[1].get("share") or 0.0)
                if isinstance(top[1].get("share"), (int, float)):
                    bits.append(f"dominant stage {top[0]} "
                                f"({100 * top[1]['share']:.0f}%)")
            print(", ".join(bits))
        tails = rq.get("tail_exemplars") or []
        if tails:
            print("  tail exemplars: " + ", ".join(
                f"{t['trace_id']} ({_fmt_s(t['e2e_s'])})" for t in tails))
    rb = s.get("robustness")
    if rb and (rb["dispatch_retries"] or rb["quarantines"]
               or rb["recovered_divergences"] or rb["degraded_queries"]
               or rb["backoff_s_total"] or rb["per_tenant"]
               or rb["per_session"]):
        n = rb["dispatch_retries"]
        line = (f"robustness: {n} dispatch retr{'y' if n == 1 else 'ies'} "
                f"({_fmt_s(rb['backoff_s_total'])} backoff), "
                f"{rb['quarantines']} quarantine"
                f"{'' if rb['quarantines'] == 1 else 's'}, "
                f"{rb['recovered_divergences']} recovered divergence"
                f"{'' if rb['recovered_divergences'] == 1 else 's'}, "
                f"{rb['degraded_queries']} degraded quer"
                f"{'y' if rb['degraded_queries'] == 1 else 'ies'}")
        print(line)
        for t, pt in rb.get("per_tenant", {}).items():
            bits = [f"  tenant {t}: {pt['events']} event"
                    f"{'' if pt['events'] == 1 else 's'}"]
            if pt.get("retries"):
                bits.append(f"{pt['retries']} retries")
            if pt.get("quarantined"):
                bits.append("QUARANTINED -> requeued")
            print(", ".join(bits))
        for sid, ps in rb.get("per_session", {}).items():
            bits = [f"  session {sid}: {ps['events']} event"
                    f"{'' if ps['events'] == 1 else 's'}"]
            if ps.get("retries"):
                bits.append(f"{ps['retries']} retries")
            if ps.get("recovered_divergences"):
                bits.append(f"{ps['recovered_divergences']} recovered")
            if ps.get("degraded_queries"):
                bits.append(f"{ps['degraded_queries']} degraded")
            print(", ".join(bits))
    for name, c in s.get("costs", {}).items():
        bits = [f"{k}={v:.3g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in c.items() if k != "key"]
        print(f"  cost {name}: {' '.join(bits)}")
    for f in s.get("fits", []):
        bits = [f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in f.items() if k != "t"]
        print(f"  fit: {' '.join(bits)}")
    tf = s.get("tenant_fairness")
    if tf and tf["n_tenants"]:
        qw = tf.get("queue_wait_s") or {}
        line = (f"tenants: {tf['n_tenants']} across {tf['n_buckets']} "
                f"bucket{'s' if tf['n_buckets'] != 1 else ''}, "
                f"{tf['converged']} converged")
        if qw:
            line += (f"; queue wait p50 {_fmt_s(qw['p50'])} / "
                     f"p99 {_fmt_s(qw['p99'])}")
        if isinstance(tf.get("pad_waste_frac_mean"), (int, float)):
            line += f"; mean pad waste {100 * tf['pad_waste_frac_mean']:.1f}%"
        print(line)
        for t in s.get("tenants", []):
            shape = f"({t.get('T')}, {t.get('N')}, {t.get('k')})"
            bshape = (f"({t.get('bucket_T')}, {t.get('bucket_N')}, "
                      f"{t.get('bucket_k')})")
            bits = [f"  {str(t.get('tenant', '?')):12s} {shape:>14s} -> "
                    f"bucket {t.get('bucket')} {bshape}"]
            if isinstance(t.get("queue_wait_s"), (int, float)):
                bits.append(f"wait {_fmt_s(float(t['queue_wait_s']))}")
            if isinstance(t.get("compute_s"), (int, float)):
                bits.append(f"compute {_fmt_s(float(t['compute_s']))}")
            if isinstance(t.get("pad_waste_frac"), (int, float)):
                bits.append(f"waste {100 * float(t['pad_waste_frac']):.1f}%")
            if t.get("n_iters") is not None:
                bits.append(f"{t['n_iters']} iters")
            bits.append("converged" if t.get("converged") else "NOT converged")
            print(", ".join(bits))
    qs = s.get("queries")
    if qs and qs["n_queries"]:
        qw = qs.get("query_wall_s") or {}
        line = (f"queries: {qs['n_queries']} across {qs['n_sessions']} "
                f"session{'s' if qs['n_sessions'] != 1 else ''}, "
                f"{qs['converged']} converged")
        if qs.get("diverged"):
            line += f", {qs['diverged']} DIVERGED"
        if qw:
            line += (f"; wall p50 {_fmt_s(qw['p50'])} / "
                     f"p99 {_fmt_s(qw['p99'])}")
        r = qs.get("recompiles_after_warmup", 0)
        line += (f"; recompiles after warmup {r}"
                 + (" (!!)" if r else ""))
        if qs.get("rows_evicted"):
            line += (f"; ring evicted {qs['rows_evicted']} rows over "
                     f"{qs['evicting_queries']} queries")
        print(line)
        for sid, ps in qs.get("per_session", {}).items():
            bits = [f"  session {sid}: {ps['queries']} "
                    f"quer{'ies' if ps['queries'] != 1 else 'y'}"]
            if ps.get("engine"):
                bits.append(f"engine {ps['engine']}")
            if ps.get("t_rows") is not None:
                bits.append(f"{ps['t_rows']} rows held")
            pw = ps.get("query_wall_s") or {}
            if pw:
                bits.append(f"wall p50 {_fmt_s(pw['p50'])} / "
                            f"p99 {_fmt_s(pw['p99'])}")
            if isinstance(ps.get("forecast_coverage"), (int, float)):
                bits.append(f"90% band coverage "
                            f"{100 * ps['forecast_coverage']:.0f}%")
            print(", ".join(bits))
    fl = s.get("fleet")
    if fl and fl["n_ticks"]:
        tw = fl.get("tick_wall_s") or {}
        line = (f"fleet: {fl['n_queries']} queries over {fl['n_ticks']} "
                f"tick{'s' if fl['n_ticks'] != 1 else ''} in "
                f"{fl['n_buckets']} bucket{'s' if fl['n_buckets'] != 1 else ''}"
                f" ({fl['queries_per_dispatch']:.2f} queries/dispatch)")
        if isinstance(fl.get("occupancy_mean"), (int, float)):
            line += f"; mean occupancy {100 * fl['occupancy_mean']:.0f}%"
        if tw:
            line += (f"; tick wall p50 {_fmt_s(tw['p50'])} / "
                     f"p99 {_fmt_s(tw['p99'])}")
        print(line)
        pg = fl.get("paging") or {}
        if pg.get("admits") or pg.get("demotes") or pg.get("spills"):
            line = (f"  paging: {pg['admits']} admits / {pg['demotes']} "
                    f"demotes / {pg['spills']} spills")
            rs = pg.get("readmission_s") or {}
            if rs:
                line += (f"; readmission p50 {_fmt_s(rs['p50'])} / "
                         f"p99 {_fmt_s(rs['p99'])}")
            print(line)
        for bid, pb in fl.get("per_bucket", {}).items():
            bits = [f"  bucket {bid}: {pb['ticks']} "
                    f"tick{'s' if pb['ticks'] != 1 else ''}"]
            if isinstance(pb.get("occupancy_mean"), (int, float)):
                bits.append(f"occupancy {100 * pb['occupancy_mean']:.0f}%")
            print(", ".join(bits))
        for tid, pt in fl.get("per_tenant", {}).items():
            bits = [f"  {tid:12s} {pt['queries']} "
                    f"quer{'ies' if pt['queries'] != 1 else 'y'}"]
            if pt.get("engine"):
                bits.append(f"engine {pt['engine']}")
            qw = pt.get("queue_wait_s") or {}
            if qw:
                bits.append(f"queue wait p50 {_fmt_s(qw['p50'])} / "
                            f"p99 {_fmt_s(qw['p99'])}")
            if isinstance(pt.get("forecast_coverage"), (int, float)):
                bits.append(f"90% band coverage "
                            f"{100 * pt['forecast_coverage']:.0f}%")
            print(", ".join(bits))
    mt = s.get("maintenance")
    if mt and (mt["drift_fires"] or mt["drift_clears"] or mt["triggers"]
               or mt["refits"] or mt["swaps"] or mt["skips"]):
        print(f"maintenance: {mt['drift_fires']} drift fire"
              f"{'' if mt['drift_fires'] == 1 else 's'} "
              f"({mt['drift_clears']} cleared), {mt['triggers']} trigger"
              f"{'' if mt['triggers'] == 1 else 's'}, {mt['refits']} "
              f"refit{'' if mt['refits'] == 1 else 's'}, {mt['swaps']} "
              f"swap{'' if mt['swaps'] == 1 else 's'}, {mt['skips']} "
              f"skip{'' if mt['skips'] == 1 else 's'}")
        for tid, pt in mt.get("per_tenant", {}).items():
            bits = [f"  {tid:12s}"]
            if pt.get("drift_fires") or pt.get("drift_clears"):
                bits.append(f"drift fired x{pt['drift_fires']}"
                            + (f" (score {pt['drift_score']:.2f})"
                               if isinstance(pt.get("drift_score"),
                                             (int, float)) else ""))
            tr = pt.get("trigger") or {}
            if tr:
                bits.append("trigger " + " ".join(
                    f"{k}={v:.3g}" for k, v in tr.items()))
            if pt.get("refits"):
                bits.append(f"{pt['refits']} refit"
                            f"{'' if pt['refits'] == 1 else 's'} "
                            f"({_fmt_s(pt['refit_s'])})")
            if pt.get("action"):
                act = {"swap": "SWAPPED",
                       "retune": "RETUNED (tuned hypers won)"}.get(
                    pt["action"], "skipped (no gain)")
                if isinstance(pt.get("quality_delta"), (int, float)):
                    act += f", quality delta {pt['quality_delta']:+.3g}"
                bits.append(act)
            if pt.get("engine"):
                eng = f"engine {pt['engine']}"
                if pt.get("advice") and pt["advice"] != pt["engine"]:
                    eng += f" (advisor: {pt['advice']})"
                bits.append(eng)
            print(", ".join(b for b in bits if b.strip()))
    a = s.get("advice")
    if a:
        pred, real = a.get("predicted_wall_s"), a.get("realized_wall_s")
        eng = a.get("engine", "?")
        if a.get("filter") not in (None, "seq"):
            eng += f"+{a['filter']}"   # filter engine (pit_qr, lowrank)
        line = f"advice: {eng} plan"
        if a.get("engine") == "fused" and a.get("fused_chunk") is not None:
            line += f" (fused_chunk={a['fused_chunk']})"
        elif a.get("depth") is not None:
            line += (f" (depth={a['depth']}"
                     f"{', bucket' if a.get('bucket') else ''})")
        if isinstance(pred, (int, float)):
            line += f", predicted {_fmt_s(float(pred))}"
        if isinstance(real, (int, float)):
            line += f", realized {_fmt_s(float(real))}"
        if isinstance(a.get("rel_err"), (int, float)):
            line += f", prediction error {100 * float(a['rel_err']):.0f}%"
        print(line)
    tu = s.get("tune")
    if tu:
        line = (f"tune: {tu.get('method', '?')} search, "
                f"q_scale={tu.get('q_scale', 1.0):.3g} "
                f"r_scale={tu.get('r_scale', 1.0):.3g}")
        if tu.get("lam_ridge"):
            line += f" lam_ridge={tu['lam_ridge']:.3g}"
        hb, ha = tu.get("heldout_before"), tu.get("heldout_after")
        if isinstance(hb, (int, float)) and isinstance(ha, (int, float)):
            line += f", held-out MSE {hb:.4g} -> {ha:.4g}"
        if tu.get("dispatches") is not None:
            line += f", {tu['dispatches']} dispatches"
        if isinstance(tu.get("wall"), (int, float)):
            line += f" in {_fmt_s(float(tu['wall']))}"
        print(line)


_DEVICE_PID, _HOST_PID = 0, 1


def to_chrome(events: List[dict]) -> dict:
    """Convert an event stream to Chrome/Perfetto trace-event format
    (load the result in chrome://tracing or ui.perfetto.dev): dispatch
    spans land on a "device" track (one thread lane per program, so
    pipeline overlap is visible as stacked in-flight spans), transfers
    and host-side markers (chunk checks, fit/advice, health) on a "host"
    track.  Timestamps are rebased to the first event; ts/dur in µs.
    Request waterfalls (``request`` events) additionally become per-stage
    slices on their own lane, joined to the query spans carrying the same
    trace_id by Perfetto flow arrows (ph s/t/f, id = crc32(trace_id))."""
    import zlib
    timed = [e for e in events if isinstance(e.get("t"), (int, float))]
    if not timed:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(float(e["t"]) for e in timed)
    us = lambda t: 1e6 * (float(t) - t0)  # noqa: E731

    tids: dict = {}

    def tid(pid: int, lane: str) -> int:
        return tids.setdefault((pid, lane), len(
            [k for k in tids if k[0] == pid]))

    def flow_id(trace_id: str) -> int:
        return zlib.crc32(trace_id.encode("utf-8"))

    out = []
    _skip = ("t", "kind", "dur", "program")
    for e in timed:
        kind = e.get("kind")
        args = {k: v for k, v in e.items() if k not in _skip
                and v is not None}
        if kind == "request":
            # One slice spanning the whole waterfall (the request event's
            # t is the final boundary stamp, so the slice starts e2e
            # earlier), per-stage child slices reconstructed by walking
            # the stage durations forward, and a flow start/finish pair
            # so Perfetto draws arrows to this trace_id's query spans.
            e2e = float(e.get("e2e") or 0.0)
            tidv = str(e.get("trace_id") or "?")
            lane = tid(_HOST_PID, "requests")
            out.append({"name": f"request {tidv}", "ph": "X",
                        "ts": us(float(e["t"]) - e2e), "dur": 1e6 * e2e,
                        "pid": _HOST_PID, "tid": lane,
                        "cat": "request", "args": args})
            cum = float(e["t"]) - e2e
            for nm, d in (e.get("stages") or {}).items():
                if not isinstance(d, (int, float)):
                    continue
                out.append({"name": str(nm), "ph": "X", "ts": us(cum),
                            "dur": 1e6 * float(d), "pid": _HOST_PID,
                            "tid": tid(_HOST_PID, "request stages"),
                            "cat": "request",
                            "args": {"trace_id": tidv}})
                cum += float(d)
            out.append({"name": "request", "ph": "s", "id": flow_id(tidv),
                        "ts": us(float(e["t"]) - e2e), "pid": _HOST_PID,
                        "tid": lane, "cat": "request_flow"})
            out.append({"name": "request", "ph": "f", "bp": "e",
                        "id": flow_id(tidv), "ts": us(e["t"]),
                        "pid": _HOST_PID, "tid": lane,
                        "cat": "request_flow"})
            continue
        if kind == "dispatch":
            name = e.get("program", "?")
            out.append({"name": name, "ph": "X", "ts": us(e["t"]),
                        "dur": 1e6 * float(e.get("dur") or 0.0),
                        "pid": _DEVICE_PID, "tid": tid(_DEVICE_PID, name),
                        "cat": "dispatch", "args": args})
        elif kind == "transfer":
            name = ("transfer (blocking)" if e.get("blocking")
                    else "transfer")
            out.append({"name": name, "ph": "X", "ts": us(e["t"]),
                        "dur": 1e6 * float(e.get("dur") or 0.0),
                        "pid": _HOST_PID, "tid": tid(_HOST_PID, "transfer"),
                        "cat": "transfer", "args": args})
        else:
            # Host-side markers: convergence checks, fit/advice summaries,
            # cost captures, health — instants on their own host lane.
            out.append({"name": str(kind), "ph": "i", "s": "t",
                        "ts": us(e["t"]), "pid": _HOST_PID,
                        "tid": tid(_HOST_PID, str(kind)),
                        "cat": str(kind), "args": args})
            if e.get("trace_id"):
                # A span-carrying marker (query, health, tenant): a flow
                # step joins it to its request's waterfall slice.
                out.append({"name": "request", "ph": "t",
                            "id": flow_id(str(e["trace_id"])),
                            "ts": us(e["t"]), "pid": _HOST_PID,
                            "tid": tid(_HOST_PID, str(kind)),
                            "cat": "request_flow"})
    meta = [{"ph": "M", "name": "process_name", "pid": _DEVICE_PID,
             "args": {"name": "device (dispatch spans)"}},
            {"ph": "M", "name": "process_name", "pid": _HOST_PID,
             "args": {"name": "host (transfers + checks)"}}]
    meta += [{"ph": "M", "name": "thread_name", "pid": pid, "tid": t,
              "args": {"name": lane}} for (pid, lane), t in tids.items()]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dfm_tpu.obs.report",
        description="Summarize a DFM_TRACE JSONL trace (or several rotated "
                    "files, oldest first — pass them in order).")
    ap.add_argument("trace", nargs="+",
                    help="path(s) to trace.jsonl files, oldest first")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    ap.add_argument("--chrome", default=None, metavar="OUT.json",
                    help="also export the trace to Chrome/Perfetto "
                         "trace-event format (chrome://tracing, "
                         "ui.perfetto.dev)")
    ap.add_argument("--diff", default=None, metavar="RUN|FILE",
                    help="diff this trace against a baseline (another "
                         "trace.jsonl, a RunRecord/bench JSON file, or a "
                         "registry run_id) via obs.regress; exits nonzero "
                         "on a perf/convergence regression")
    args = ap.parse_args(argv)
    paths = list(args.trace)
    if args.chrome is not None:
        events: List[dict] = []
        for p in paths:
            events.extend(iter_events(p))
        trace = to_chrome(events)
        with open(args.chrome, "w", encoding="utf-8") as fh:
            json.dump(trace, fh, default=str)
        n = sum(1 for e in trace["traceEvents"] if e.get("ph") != "M")
        print(f"chrome trace: {n} events -> {args.chrome}", file=sys.stderr)
    s = summarize(paths[0] if len(paths) == 1 else paths)
    if args.diff is not None:
        return _diff(s, paths[0], args.diff, as_json=args.json)
    if args.json:
        json.dump(s, sys.stdout, indent=2, default=str)
        print()
    else:
        _print_text(s)
    return 0


def _diff(s: dict, trace_path: str, baseline: str, *,
          as_json: bool = False) -> int:
    """Gate this trace's summary against a baseline through obs.regress
    (exit 0 ok / 1 regression / 2 usage)."""
    from . import regress
    from .store import RunStore, runs_dir
    cand = regress.record_from_trace_summary(s, source=trace_path)
    try:
        if baseline.endswith(".jsonl"):
            # Another trace: summarize it through the same adapter so the
            # two sides carry the same metric names.
            base = regress.record_from_trace_summary(
                summarize(baseline), source=baseline)
        else:
            d = runs_dir()
            store = RunStore(d) if d is not None else None
            base = regress._load_record(baseline, store)
        diff = regress.diff_records(cand, base)
    except (regress.UsageError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if as_json:
        json.dump(diff, sys.stdout, indent=2, default=str)
        print()
    else:
        regress.print_diff(diff)
    return 0 if diff["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

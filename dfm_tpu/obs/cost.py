"""Static program cost capture + recompile detection.

Two observability primitives that need nothing from the hot path:

- ``program_cost``: XLA's own static cost model for a jitted program at a
  concrete arg signature, via ``jitted.lower(...).compile()`` then
  ``cost_analysis()`` / ``memory_analysis()``.  Flops and bytes are what
  the COMPILER thinks the program costs — the roofline numerator the
  measured dispatch wall times (``obs.trace``) divide against.  Opt-in
  (``Tracer(capture_costs=True)`` / ``DFM_TRACE_COST=1``): the
  lower+compile pass is itself a compile-scale cost.

- ``RecompileDetector``: flags when the same LOGICAL program (by name)
  is dispatched under a second distinct shape key.  On a tunneled device
  every compile is seconds of wall time, so shape churn — a panel
  re-padded to a new length, a chunk tail of a different fused length, a
  dtype flip — silently erases the dispatch-amortization the chunked
  drivers exist for.  The detector is PROCESS-local (module singleton),
  mirroring XLA's own process-level executable cache: a program+key pair
  compiled once in this process never recompiles, so a repeated
  same-shape fit must show zero first-calls and zero recompiles.
"""

from __future__ import annotations

from typing import Dict, Optional, Set

__all__ = ["RecompileDetector", "global_detector", "reset_global_detector",
           "program_cost"]


class RecompileDetector:
    """Tracks (program, shape_key) pairs across dispatches.

    ``note`` classifies each dispatch:
      "new"       first time this program is seen at all
      "cached"    this exact (program, key) pair has dispatched before
      "recompile" a NEW key for a program that already compiled under a
                  different one — the shape-churn signal
    """

    def __init__(self):
        self._keys: Dict[str, Set[str]] = {}

    def note(self, program: str, key: str) -> str:
        seen = self._keys.setdefault(program, set())
        if key in seen:
            return "cached"
        seen.add(key)
        return "recompile" if len(seen) > 1 else "new"

    def keys_for(self, program: str) -> Set[str]:
        return set(self._keys.get(program, ()))


_GLOBAL = RecompileDetector()


def global_detector() -> RecompileDetector:
    """The process-local detector (default for every ``Tracer``)."""
    return _GLOBAL


def reset_global_detector() -> None:
    """Forget all seen programs (test seam; XLA's cache is NOT cleared, so
    first-call wall times after a reset are not compile proxies)."""
    global _GLOBAL
    _GLOBAL = RecompileDetector()


def _mem_stats(compiled) -> dict:
    try:
        m = compiled.memory_analysis()
    except Exception:
        return {}
    if m is None:
        return {}
    out = {}
    for name, field in (("argument_bytes", "argument_size_in_bytes"),
                        ("output_bytes", "output_size_in_bytes"),
                        ("temp_bytes", "temp_size_in_bytes"),
                        ("code_bytes", "generated_code_size_in_bytes")):
        v = getattr(m, field, None)
        if v is not None:
            out[name] = int(v)
    return out


def program_cost(jitted, *args, **kwargs) -> Optional[dict]:
    """Static cost of ``jitted`` at this arg signature, or None.

    Returns ``{"flops": float, "bytes_accessed": float, "transcendentals":
    float, "argument_bytes": int, ...}`` with whatever XLA reports
    (``cost_analysis`` returns a per-computation list on some toolchains
    and a flat dict on others; both are handled).  Never raises: a
    backend without a cost model yields None.
    """
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
    except Exception:
        return None
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    out = {}
    if isinstance(ca, dict):
        for name, field in (("flops", "flops"),
                            ("bytes_accessed", "bytes accessed"),
                            ("transcendentals", "transcendentals")):
            v = ca.get(field)
            if v is not None:
                out[name] = float(v)
    out.update(_mem_stats(compiled))
    return out or None

"""Static program cost capture + recompile detection.

Two observability primitives that need nothing from the hot path:

- ``program_cost``: XLA's own static cost model for a jitted program at a
  concrete arg signature, via ``jitted.lower(...).compile()`` then
  ``cost_analysis()`` / ``memory_analysis()``.  Flops and bytes are what
  the COMPILER thinks the program costs — the roofline numerator the
  measured dispatch wall times (``obs.trace``) divide against.  Opt-in
  (``Tracer(capture_costs=True)`` / ``DFM_TRACE_COST=1``): the
  lower+compile pass is itself a compile-scale cost.

- ``RecompileDetector``: flags when the same LOGICAL program (by name)
  is dispatched under a second distinct shape key.  On a tunneled device
  every compile is seconds of wall time, so shape churn — a panel
  re-padded to a new length, a chunk tail of a different fused length, a
  dtype flip — silently erases the dispatch-amortization the chunked
  drivers exist for.  The detector is PROCESS-local (module singleton),
  mirroring XLA's own process-level executable cache: a program+key pair
  compiled once in this process never recompiles, so a repeated
  same-shape fit must show zero first-calls and zero recompiles.

Plus the decision half of the observatory (PR 7, jax-free):

- ``CostModel`` / ``fit_cost_model``: per-device-class coefficients
  (dispatch floor, per-flop / per-byte throughput, scan-step overhead)
  CALIBRATED from the ``obs.profile`` records in the run registry —
  measured walls scale a structured prior, and exact-config profiles
  anchor predictions to their measured median.  ``predict`` turns a
  candidate plan (engine, fused_chunk, pipeline depth) at an unseen
  (N, T, k, iters) into a wall estimate; ``obs.advise`` ranks plans
  with it and ``fit(auto=True)`` applies the winner.
"""

from __future__ import annotations

import dataclasses
import math
from statistics import median
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["RecompileDetector", "global_detector", "reset_global_detector",
           "program_cost", "CostModel", "fit_cost_model", "em_iter_work",
           "DEFAULT_COEFFS"]


class RecompileDetector:
    """Tracks (program, shape_key) pairs across dispatches.

    ``note`` classifies each dispatch:
      "new"       first time this program is seen at all
      "cached"    this exact (program, key) pair has dispatched before
      "recompile" a NEW key for a program that already compiled under a
                  different one — the shape-churn signal
    """

    def __init__(self):
        self._keys: Dict[str, Set[str]] = {}

    def note(self, program: str, key: str) -> str:
        seen = self._keys.setdefault(program, set())
        if key in seen:
            return "cached"
        seen.add(key)
        return "recompile" if len(seen) > 1 else "new"

    def keys_for(self, program: str) -> Set[str]:
        return set(self._keys.get(program, ()))


_GLOBAL = RecompileDetector()


def global_detector() -> RecompileDetector:
    """The process-local detector (default for every ``Tracer``)."""
    return _GLOBAL


def reset_global_detector() -> None:
    """Forget all seen programs (test seam; XLA's cache is NOT cleared, so
    first-call wall times after a reset are not compile proxies)."""
    global _GLOBAL
    _GLOBAL = RecompileDetector()


def _mem_stats(compiled) -> dict:
    try:
        m = compiled.memory_analysis()
    except Exception:
        return {}
    if m is None:
        return {}
    out = {}
    for name, field in (("argument_bytes", "argument_size_in_bytes"),
                        ("output_bytes", "output_size_in_bytes"),
                        ("temp_bytes", "temp_size_in_bytes"),
                        ("code_bytes", "generated_code_size_in_bytes")):
        v = getattr(m, field, None)
        if v is not None:
            out[name] = int(v)
    return out


def program_cost(jitted, *args, **kwargs) -> Optional[dict]:
    """Static cost of ``jitted`` at this arg signature, or None.

    Returns ``{"flops": float, "bytes_accessed": float, "transcendentals":
    float, "argument_bytes": int, ...}`` with whatever XLA reports
    (``cost_analysis`` returns a per-computation list on some toolchains
    and a flat dict on others; both are handled).  Never raises: a
    backend without a cost model yields None.
    """
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
    except Exception:
        return None
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    out = {}
    if isinstance(ca, dict):
        for name, field in (("flops", "flops"),
                            ("bytes_accessed", "bytes accessed"),
                            ("transcendentals", "transcendentals")):
            v = ca.get(field)
            if v is not None:
                out[name] = float(v)
    out.update(_mem_stats(compiled))
    return out or None


# --------------------------------------------------------------------------
# Calibrated cost model: measured profiles -> per-device coefficients
# --------------------------------------------------------------------------

def em_iter_work(N: int, T: int, k: int) -> Tuple[float, float]:
    """Closed-form (flops, bytes) proxy for ONE EM iteration of the
    info-filter fit at panel shape (N, T, k): per time step the E-step
    forms C'R^-1 y (Nk), C'R^-1 C (Nk^2) and a handful of k-by-k
    factorizations/solves (k^3); the smoother and M-step sweeps are the
    same order.  Constants don't matter — calibration scales them — the
    proxy only has to get the SHAPE dependence right so profiles at one
    shape extrapolate to another."""
    flops = 2.0 * T * (N * k + N * k * k + 8.0 * k ** 3)
    bytes_ = 8.0 * T * (N + N * k + 4.0 * k * k)
    return float(flops), float(bytes_)


# Structured priors per device class — the fallback when the registry has
# no profiles, and the shape calibration scales.  The tpu row encodes the
# axon-tunnel facts (CLAUDE.md): ~80 ms dispatch floor, MXU-fed matmuls.
DEFAULT_COEFFS: Dict[str, Dict[str, float]] = {
    "tpu": {"dispatch_floor_s": 0.08, "step_s": 2e-5,
            "per_flop_s": 1.0 / 2e12, "per_byte_s": 1.0 / 4e10,
            "overhead_s": 0.3},
    "cpu": {"dispatch_floor_s": 1e-3, "step_s": 4e-5,
            "per_flop_s": 1.0 / 5e9, "per_byte_s": 1.0 / 1e10,
            "overhead_s": 0.05},
}


# The parallel-in-time QR engine trades the O(T) sequential scan depth
# for ~2*sqrt(T) blocked-prefix-scan steps at a constant-factor flop
# overhead (square-root element build + thin-QR combines).  The factor is
# a structural prior — profiles anchor the real number per shape.
PIT_QR_FLOP_MULT = 4.0

# The rank-r computation-aware engine keeps the O(T) depth but strips the
# k x k linalg out of the scan body (only r x r factorizations + plain
# matmuls remain), cutting per-iteration flops by roughly half at the
# profiled shapes.  A structural prior like PIT_QR_FLOP_MULT — measured
# "lowrank" profiles carry the real residual via ``lowrank_scale``.
LOWRANK_FLOP_MULT = 0.5


def _norm_plan(engine: str, chunk, depth, bucket, filt=None) -> Tuple:
    return (str(engine), int(chunk or 8), int(depth or 1), bool(bucket),
            str(filt or "seq"))


def _pad_plan(plan) -> List:
    """Legacy 4-element plan lists (pre-filter registries) mean the
    sequential time scan."""
    plan = list(plan)
    return plan + ["seq"] if len(plan) == 4 else plan


def _profile_plan(config: dict) -> Optional[Tuple]:
    """Map a ProfileRecord config to a normalized plan tuple (the
    ``pipelined`` variant is the chunked engine at depth>1; the
    ``pit_qr`` variant is the chunked engine under the parallel-in-time
    QR filter)."""
    variant = config.get("profile")
    flt = config.get("filter")
    if variant == "fused":
        return _norm_plan("fused", config.get("chunk"), 1, False, flt)
    if variant in ("chunked", "pipelined", "pit_qr", "lowrank"):
        depth = config.get("depth") or (2 if variant == "pipelined" else 1)
        return _norm_plan("chunked", config.get("chunk"), depth,
                          config.get("bucket"),
                          variant if variant in ("pit_qr", "lowrank")
                          else flt)
    return None


def _iter_features(T: float, flops: float, bytes_: float,
                   filt: str = "seq") -> Tuple[float, float, float]:
    """Per-iteration cost features under a time-scan engine: sequential
    depth, flops, bytes.  pit_qr replaces the T-step depth with the
    blocked prefix scan's ~2*sqrt(T) and pays the element/combine flop
    multiplier — the SAME feature map calibration and prediction use, so
    pit_qr profiles sharpen the shared coefficients instead of skewing
    them."""
    if filt == "pit_qr":
        return (2.0 * math.sqrt(max(T, 1.0)), PIT_QR_FLOP_MULT * flops,
                PIT_QR_FLOP_MULT * bytes_)
    if filt == "lowrank":
        # Same T-step depth; the scan body sheds its k x k linalg.
        return (float(T), LOWRANK_FLOP_MULT * flops,
                LOWRANK_FLOP_MULT * bytes_)
    return (float(T), float(flops), float(bytes_))


@dataclasses.dataclass
class CostModel:
    """Wall-time predictor for a fit plan at shape (N, T, k).

    ``predicted = overhead + n_program_dispatches * dispatch_floor +
    iters * iter_s(N, T, k)`` where ``iter_s = steps*step_s +
    flops*per_flop + bytes*per_byte`` with ``steps = T`` for the
    sequential scan and ``~2*sqrt(T)`` (at a flop multiplier) for the
    ``pit_qr`` time-parallel engine — and when the registry holds a
    profile at the EXACT plan+shape, the prediction is anchored to that
    measured warm median instead (extrapolated across iteration counts
    by the model's own marginal rate)."""

    device: str = "cpu"
    dispatch_floor_s: float = 1e-3
    step_s: float = 4e-5
    per_flop_s: float = 2e-10
    per_byte_s: float = 1e-10
    overhead_s: float = 0.05
    calibrated: bool = False
    n_profiles: int = 0
    # Residual multiplier for the pit_qr feature family: the structural
    # prior (2*sqrt(T) depth, 4x flops) is corrected by the measured
    # pit_qr profiles so an UNmeasured pit_qr plan never undercuts the
    # family's own measurements at other knobs.
    pit_qr_scale: float = 1.0
    # Same construction for the rank-r downdate family: LOWRANK_FLOP_MULT
    # is the structural prior, measured "lowrank" profiles correct it.
    lowrank_scale: float = 1.0
    # Whether the residual scales above come from measured family
    # profiles (vs the un-corrected structural prior).  The advisor uses
    # these to keep an UNmeasured engine-switch plan from undercutting
    # measured plans on raw-prior optimism — picking an engine nobody
    # profiled forces a fresh compile, the one cost the model can't see.
    pit_qr_calibrated: bool = False
    lowrank_calibrated: bool = False
    anchors: List[dict] = dataclasses.field(default_factory=list)

    def iter_s(self, N: int, T: int, k: int, filt: str = "seq") -> float:
        flops, bytes_ = em_iter_work(N, T, k)
        steps, flops, bytes_ = _iter_features(T, flops, bytes_, filt)
        it = (self.step_s * steps + self.per_flop_s * flops
              + self.per_byte_s * bytes_)
        if filt == "pit_qr":
            return it * self.pit_qr_scale
        if filt == "lowrank":
            return it * self.lowrank_scale
        return it

    def dispatches(self, iters: int, *, engine: str, chunk: int = 8,
                   depth: int = 1) -> int:
        """Program dispatches the host pays the tunnel floor for."""
        if engine == "fused":
            return 1
        n_chunks = max(1, math.ceil(iters / max(1, chunk)))
        return max(1, math.ceil(n_chunks / max(1, depth)))

    def _anchor(self, plan: Tuple, N: int, T: int, k: int):
        cands = [a for a in self.anchors
                 if _pad_plan(a["plan"]) == list(plan)
                 and (a["N"], a["T"], a["k"]) == (N, T, k)]
        return max(cands, key=lambda a: a["iters"]) if cands else None

    def predict(self, N: int, T: int, k: int, iters: int, *,
                engine: str, chunk: int = 8, depth: int = 1,
                bucket: bool = False, filter: str = "seq") -> dict:
        plan = _norm_plan(engine, chunk, depth, bucket, filter)
        it = self.iter_s(N, T, k, filter)
        anchor = self._anchor(plan, N, T, k)
        if anchor is not None:
            # Measured wall at this exact config; the model only fills in
            # the marginal cost of the iteration-count difference.
            wall = (float(anchor["warm_wall_s"])
                    + (iters - int(anchor["iters"])) * it
                    + (self.dispatches(iters, engine=engine, chunk=chunk,
                                       depth=depth)
                       - self.dispatches(int(anchor["iters"]),
                                         engine=engine, chunk=chunk,
                                         depth=depth))
                    * self.dispatch_floor_s)
            return {"predicted_wall_s": max(wall, 1e-9), "anchored": True}
        nd = self.dispatches(iters, engine=engine, chunk=chunk, depth=depth)
        wall = self.overhead_s + nd * self.dispatch_floor_s + iters * it
        return {"predicted_wall_s": max(wall, 1e-9), "anchored": False}

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["n_anchors"] = len(d.pop("anchors"))
        return d


def _solve3(A: List[List[float]], b: List[float]) -> Optional[List[float]]:
    """Gaussian elimination for the 3x3 normal equations (jax/numpy-free)."""
    m = [row[:] + [v] for row, v in zip(A, b)]
    for col in range(3):
        piv = max(range(col, 3), key=lambda r: abs(m[r][col]))
        if abs(m[piv][col]) < 1e-30:
            return None
        m[col], m[piv] = m[piv], m[col]
        for r in range(3):
            if r != col:
                f = m[r][col] / m[col][col]
                m[r] = [a - f * c for a, c in zip(m[r], m[col])]
    return [m[i][3] / m[i][i] for i in range(3)]


def fit_cost_model(profiles: Iterable[dict],
                   device: Optional[str] = None) -> CostModel:
    """Calibrate a ``CostModel`` from ProfileRecords (``obs.profile``).

    Coefficients come from measured walls: the dispatch floor is the
    median measured per-dispatch cost; the per-iteration rate is a
    3-parameter least squares over (scan steps, flops, bytes) features
    when the profiles span enough distinct shapes, else a single measured
    scale applied to the structured device prior.  Static ``program_cost``
    flops/bytes captured by the profiler replace the closed-form proxy
    for their observation.  With an empty registry the prior is returned
    un-calibrated (``calibrated=False``)."""
    profs = [p for p in profiles
             if p.get("kind") == "profile" and isinstance(p.get("config"),
                                                          dict)]
    if device is None and profs:
        device = profs[-1]["config"].get("device")
    device = device or "cpu"
    profs = [p for p in profs if p["config"].get("device") in (None, device)]
    prior = DEFAULT_COEFFS.get(device, DEFAULT_COEFFS["cpu"])
    model = CostModel(device=device, calibrated=False, n_profiles=len(profs),
                      **prior)
    if not profs:
        return model

    # Dispatch floor: median measured per-dispatch wall.
    floors = [float(p["metrics"]["dispatch_ms_per_program"]) / 1e3
              for p in profs
              if isinstance(p.get("metrics", {}).get(
                  "dispatch_ms_per_program"), (int, float))]
    if floors:
        model.dispatch_floor_s = max(median(floors), 0.0)

    # Per-iteration observations: (features, measured iter seconds).
    obs = []
    for p in profs:
        c, m = p["config"], p.get("metrics", {})
        it_ms = m.get("sustained_ms_per_iter") or m.get("ms_per_iter_warm")
        if not isinstance(it_ms, (int, float)) or it_ms <= 0:
            continue
        if not all(isinstance(c.get(x), int) for x in ("N", "T", "k")):
            continue
        N, T, k = c["N"], c["T"], c["k"]
        flops, bytes_ = em_iter_work(N, T, k)
        if isinstance(m.get("flops_per_iter"), (int, float)):
            flops = float(m["flops_per_iter"])
        if isinstance(m.get("bytes_per_iter"), (int, float)):
            bytes_ = float(m["bytes_per_iter"])
        prof = c.get("profile")
        flt = (prof if prof in ("pit_qr", "lowrank")
               else c.get("filter") or "seq")
        obs.append((_iter_features(T, flops, bytes_, flt),
                    float(it_ms) / 1e3, (N, T, k, flt)))

    if obs:
        model.calibrated = True
        # Shared coefficients come from the sequential-scan profiles; the
        # pit_qr family carries its own residual scale below (a registry
        # with ONLY pit_qr profiles still calibrates, off those).
        seq_obs = [o for o in obs if o[2][3] == "seq"] or obs
        coeffs = None
        if len({shape for _, _, shape in seq_obs}) >= 3:
            # Enough shape diversity for a genuine 3-param fit (tiny ridge
            # keeps the normal equations sane when features correlate).
            A = [[0.0] * 3 for _ in range(3)]
            rhs = [0.0] * 3
            for f, y, _ in seq_obs:
                for i in range(3):
                    rhs[i] += f[i] * y
                    for j in range(3):
                        A[i][j] += f[i] * f[j]
            for i in range(3):
                A[i][i] *= 1.0 + 1e-9
            sol = _solve3(A, rhs)
            if sol is not None and all(c >= 0.0 for c in sol):
                coeffs = sol
        if coeffs is None:
            # Scaled prior: one measured scalar corrects the whole prior
            # rate — robust down to a single profile.
            def prior_it(f):
                return (prior["step_s"] * f[0] + prior["per_flop_s"] * f[1]
                        + prior["per_byte_s"] * f[2])
            scale = median([y / prior_it(f) for f, y, _ in seq_obs])
            coeffs = [prior["step_s"] * scale, prior["per_flop_s"] * scale,
                      prior["per_byte_s"] * scale]
        model.step_s, model.per_flop_s, model.per_byte_s = coeffs

        def model_it(f):
            return (model.step_s * f[0] + model.per_flop_s * f[1]
                    + model.per_byte_s * f[2])
        pit_obs = [(f, y) for f, y, s in obs if s[3] == "pit_qr"]
        if pit_obs:
            model.pit_qr_scale = median(
                [y / max(model_it(f), 1e-30) for f, y in pit_obs])
            model.pit_qr_calibrated = True
        lowrank_obs = [(f, y) for f, y, s in obs if s[3] == "lowrank"]
        if lowrank_obs:
            model.lowrank_scale = median(
                [y / max(model_it(f), 1e-30) for f, y in lowrank_obs])
            model.lowrank_calibrated = True

    # Anchors + fixed overhead residual.
    overheads = []
    for p in profs:
        c, m = p["config"], p.get("metrics", {})
        plan = _profile_plan(c)
        warm = m.get("warm_wall_s")
        iters = c.get("iters")
        if plan is None or not isinstance(warm, (int, float)) \
                or not isinstance(iters, int):
            continue
        if not all(isinstance(c.get(x), int) for x in ("N", "T", "k")):
            continue
        N, T, k = c["N"], c["T"], c["k"]
        model.anchors.append({"plan": list(plan), "N": N, "T": T, "k": k,
                              "iters": iters,
                              "warm_wall_s": float(warm)})
        engine, chunk, depth, _, flt = plan
        # A measured wall at any knob of an engine-switch family is
        # evidence the family was profiled (even without iter metrics).
        if flt == "pit_qr":
            model.pit_qr_calibrated = True
        elif flt == "lowrank":
            model.lowrank_calibrated = True
        nd = model.dispatches(iters, engine=engine, chunk=chunk, depth=depth)
        ov = (float(warm) - nd * model.dispatch_floor_s
              - iters * model.iter_s(N, T, k, flt))
        overheads.append(max(ov, 0.0))
    if overheads:
        model.overhead_s = median(overheads)
    return model

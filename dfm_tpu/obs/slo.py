"""SLO objectives and rolling error-budget burn-rate evaluation (jax-free).

``SLOConfig`` declares the objective — "p99 latency below ``p99_ms`` with
at most ``latency_budget`` of queries over it, and an error rate below
``error_rate``, evaluated over a rolling ``window`` seconds".  The
``SLOMonitor`` consumes (t, wall_ms, error) observations — timestamps the
trace layer already takes, so the serving path gains no clock reads — and
maintains the burn rate:

    burn = max(frac_over_latency / latency_budget,
               frac_errors / error_rate)

burn == 1.0 means the budget is being spent exactly as fast as the SLO
allows; > 1.0 means the budget is burning down.  The monitor is a
hysteresis state machine: it FIRES when burn > ``fire_at`` with at least
``min_events`` observations in the window, and CLEARS when burn drops
below ``clear_at``.  Both transitions are returned to the caller (the
live plane emits ``HealthEvent(kind="slo_burn")`` / flight-recorder dumps
on them).

``AnomalyDetector`` is the objective-free companion: it tracks a slow
EMA baseline of the windowed p99 and flags a spike when the current p99
exceeds ``spike_ratio`` times the baseline — catching latency regressions
long before a generous SLO notices.

Deterministic by construction: both are pure functions of the observation
sequence (no internal clock reads, no randomness).
"""

from __future__ import annotations

import dataclasses
import math
import os
from collections import deque
from typing import Optional

__all__ = ["SLOConfig", "SLOMonitor", "AnomalyDetector", "slo_from_env"]


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Serving SLO: latency objective + error budget over a rolling window."""

    p99_ms: float = 1000.0        # latency objective per query
    error_rate: float = 0.01      # allowed fraction of failed queries
    window: float = 60.0          # rolling window, seconds (monotonic time)
    latency_budget: float = 0.01  # allowed fraction of queries over p99_ms
    min_events: int = 10          # don't evaluate on fewer observations
    fire_at: float = 1.0          # burn rate that trips the SLO
    clear_at: float = 0.5         # hysteresis: burn rate that clears it


def slo_from_env() -> Optional[SLOConfig]:
    """SLOConfig from DFM_SLO_P99_MS / DFM_SLO_ERROR_RATE / DFM_SLO_WINDOW,
    or None when no DFM_SLO_* variable is set (monitor disarmed)."""
    p99 = os.environ.get("DFM_SLO_P99_MS")
    err = os.environ.get("DFM_SLO_ERROR_RATE")
    win = os.environ.get("DFM_SLO_WINDOW")
    if p99 is None and err is None and win is None:
        return None
    base = SLOConfig()
    return SLOConfig(
        p99_ms=float(p99) if p99 else base.p99_ms,
        error_rate=float(err) if err else base.error_rate,
        window=float(win) if win else base.window)


class SLOMonitor:
    """Rolling-window burn-rate evaluation with fire/clear hysteresis.

    ``observe`` returns ``"fire"`` on the breach transition, ``"clear"``
    on recovery, else None.  An unarmed monitor (``config is None``)
    observes nothing and reports burn 0.0 — the always-on plane stays
    zero-cost until someone declares an objective.
    """

    def __init__(self, config: Optional[SLOConfig] = None):
        self.config = config
        self.breached = False
        self.burn_rate = 0.0
        self.burn_rate_max = 0.0
        self.n_fired = 0
        self._win: deque = deque()   # (t, bad_latency, bad_error)

    @property
    def armed(self) -> bool:
        return self.config is not None

    def set_config(self, config: Optional[SLOConfig]) -> None:
        self.config = config
        self._win.clear()
        self.breached = False
        self.burn_rate = 0.0

    def observe(self, t: float, wall_ms: float,
                error: bool = False) -> Optional[str]:
        cfg = self.config
        if cfg is None:
            return None
        self._win.append((float(t), wall_ms > cfg.p99_ms, bool(error)))
        horizon = float(t) - cfg.window
        while self._win and self._win[0][0] < horizon:
            self._win.popleft()
        n = len(self._win)
        if n < cfg.min_events:
            self.burn_rate = 0.0
            return None
        n_lat = sum(1 for _, bl, _e in self._win if bl)
        n_err = sum(1 for _, _bl, e in self._win if e)
        burn = max(
            (n_lat / n) / cfg.latency_budget if cfg.latency_budget > 0
            else (math.inf if n_lat else 0.0),
            (n_err / n) / cfg.error_rate if cfg.error_rate > 0
            else (math.inf if n_err else 0.0))
        self.burn_rate = burn
        if burn > self.burn_rate_max:
            self.burn_rate_max = burn
        if not self.breached and burn > cfg.fire_at:
            self.breached = True
            self.n_fired += 1
            return "fire"
        if self.breached and burn < cfg.clear_at:
            self.breached = False
            return "clear"
        return None

    def status(self) -> dict:
        cfg = self.config
        return {
            "armed": self.armed,
            "breached": self.breached,
            "burn_rate": round(self.burn_rate, 6),
            "burn_rate_max": round(self.burn_rate_max, 6),
            "n_fired": self.n_fired,
            "n_window": len(self._win),
            "p99_ms": cfg.p99_ms if cfg else None,
            "error_rate": cfg.error_rate if cfg else None,
            "window_s": cfg.window if cfg else None,
        }


class AnomalyDetector:
    """Latency-spike detector: windowed p99 vs a slow EMA baseline.

    Keeps the last ``window_n`` walls (bounded deque); after ``warmup``
    observations, flags a spike when the current window p99 exceeds
    ``spike_ratio`` x the EMA baseline (and the baseline only absorbs
    non-spiking windows, so a sustained regression keeps firing the
    detector rather than normalizing it away).  Returns True from
    ``observe`` on the spike *transition*.
    """

    def __init__(self, window_n: int = 64, warmup: int = 20,
                 spike_ratio: float = 3.0, alpha: float = 0.05,
                 floor_ms: float = 1.0):
        self.window_n = int(window_n)
        self.warmup = int(warmup)
        self.spike_ratio = float(spike_ratio)
        self.alpha = float(alpha)
        self.floor_ms = float(floor_ms)
        self.baseline_ms: Optional[float] = None
        self.spiking = False
        self.n_spikes = 0
        self.n = 0
        self._walls: deque = deque(maxlen=self.window_n)

    def _p99(self) -> float:
        xs = sorted(self._walls)
        rank = max(1, int(math.ceil(0.99 * len(xs) - 1e-9)))
        return xs[rank - 1]

    def observe(self, wall_ms: float) -> bool:
        self.n += 1
        self._walls.append(float(wall_ms))
        if self.n < self.warmup:
            return False
        p99 = self._p99()
        if self.baseline_ms is None:
            self.baseline_ms = p99
            return False
        threshold = max(self.floor_ms, self.spike_ratio * self.baseline_ms)
        spike = p99 > threshold
        if not spike:
            self.baseline_ms += self.alpha * (p99 - self.baseline_ms)
        fired = spike and not self.spiking
        self.spiking = spike
        if fired:
            self.n_spikes += 1
        return fired

    def status(self) -> dict:
        return {"baseline_ms": (round(self.baseline_ms, 6)
                                if self.baseline_ms is not None else None),
                "spiking": self.spiking,
                "n_spikes": self.n_spikes,
                "n_observed": self.n}

"""Process-local, jax-free metrics primitives for the live telemetry plane.

The trace layer (``obs.trace``) is opt-in and post-hoc: an unbounded JSONL
you read after the run ends.  A long-lived serving fleet needs the
opposite — always-on, bounded-memory counters/gauges/quantiles you can
poll *while it serves*.  This module provides the primitives:

- ``Counter`` / ``Gauge``: one float, O(1).
- ``Histogram``: fixed-log-bucket streaming quantile sketch.  ~360 integer
  buckets spanning [1e-6, 1e6) with 8% geometric growth, so every series
  is O(1) memory regardless of event volume and quantiles carry a bounded
  ~4% relative error (quantile = geometric mean of the bucket edges).
  Exact ``count``/``sum``/``min``/``max`` ride along.
- ``MetricsRegistry``: labeled series (``tenant=…, program=…``) behind one
  lock; ``snapshot()``/``from_snapshot()`` round-trip through JSON for the
  ``obs.live`` CLI; ``render_prom()`` is Prometheus text exposition.
- ``Ledger``: per-(session, tenant) resource accounting — queries,
  device-wall ms, EM iterations, estimated flops (``cost.em_iter_work``),
  pad-waste share, retries, degraded/quarantined counts.
- ``record_event(registry, ledger, ev)``: THE mapping from a trace-event
  dict to metric/ledger updates.  Both the live plane (``obs.live``) and
  the post-hoc ``metrics`` section of ``report.summarize`` go through this
  one function, so the two surfaces cannot drift.

Everything here is host-side python on timestamps the trace layer already
takes: no jax import, no device work, no clock reads beyond what callers
pass in.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, Optional, Tuple

from .cost import em_iter_work

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "Ledger",
           "record_event", "LEDGER_FIELDS"]


# -- streaming histogram -------------------------------------------------

_LO = 1e-6          # smallest resolvable value (ms-scale walls: 1 ns)
_HI = 1e6           # largest bucket edge
_GROWTH = 1.08      # geometric bucket growth: <= 4% quantile error
_LOG_G = math.log(_GROWTH)
_NBUCKETS = int(math.ceil(math.log(_HI / _LO) / _LOG_G))  # ~358


class Histogram:
    """Fixed-log-bucket streaming quantile histogram (O(1) memory).

    ``observe`` is an int increment in a dict keyed by bucket index;
    ``quantile`` walks the cumulative counts and returns the geometric
    mean of the matched bucket's edges, clamped to the exact observed
    [min, max].  Values outside [1e-6, 1e6) clamp to the end buckets.

    ``observe(x, exemplar=...)`` keeps ONE tail exemplar per series: the
    trace_id of the largest exemplar-carrying observation so far — the
    request to read when the p99 looks wrong (OpenMetrics exemplar on the
    0.99 quantile in ``render_prom``).
    """

    __slots__ = ("count", "sum", "min", "max", "buckets", "exemplar")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: Dict[int, int] = {}
        self.exemplar: Optional[Tuple[float, str]] = None  # (value, trace_id)

    def observe(self, x: float, exemplar: Optional[str] = None) -> None:
        x = float(x)
        if not math.isfinite(x):
            return
        self.count += 1
        self.sum += x
        if x < self.min:
            self.min = x
        if x > self.max:
            self.max = x
        if exemplar and (self.exemplar is None or x >= self.exemplar[0]):
            self.exemplar = (x, str(exemplar))
        if x <= _LO:
            i = 0
        else:
            i = min(int(math.log(x / _LO) / _LOG_G), _NBUCKETS - 1)
        self.buckets[i] = self.buckets.get(i, 0) + 1

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile estimate; None for an empty series."""
        if self.count == 0:
            return None
        rank = max(1, int(math.ceil(q * self.count - 1e-9)))
        cum = 0
        for i in sorted(self.buckets):
            cum += self.buckets[i]
            if cum >= rank:
                lo = _LO * _GROWTH ** i
                est = lo * math.sqrt(_GROWTH)   # geometric mid of the bucket
                return min(max(est, self.min), self.max)
        return self.max

    def to_dict(self) -> dict:
        d = {"count": self.count, "sum": self.sum,
             "min": self.min if self.count else None,
             "max": self.max if self.count else None,
             "buckets": {str(i): n for i, n in sorted(self.buckets.items())}}
        if self.exemplar is not None:
            d["exemplar"] = {"value": self.exemplar[0],
                             "trace_id": self.exemplar[1]}
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls()
        h.count = int(d.get("count", 0))
        h.sum = float(d.get("sum", 0.0))
        h.min = math.inf if d.get("min") is None else float(d["min"])
        h.max = -math.inf if d.get("max") is None else float(d["max"])
        h.buckets = {int(i): int(n)
                     for i, n in dict(d.get("buckets", {})).items()}
        ex = d.get("exemplar")   # absent in pre-exemplar snapshots: fine
        if ex:
            h.exemplar = (float(ex.get("value", 0.0)),
                          str(ex.get("trace_id", "")))
        return h


class Counter:
    __slots__ = ("value",)

    def __init__(self, value: float = 0.0):
        self.value = value

    def inc(self, v: float = 1.0) -> None:
        self.value += v


class Gauge:
    __slots__ = ("value",)

    def __init__(self, value: float = 0.0):
        self.value = value

    def set(self, v: float) -> None:
        self.value = float(v)


# -- labeled registry ----------------------------------------------------

LabelKey = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, labels: dict) -> LabelKey:
    return name, tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return "dfm_" + out


def _prom_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    esc = [(k, v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n"))
           for k, v in labels]
    return "{" + ",".join(f'{k}="{v}"' for k, v in esc) + "}"


class MetricsRegistry:
    """Thread-safe set of labeled Counter/Gauge/Histogram series."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[LabelKey, Counter] = {}
        self._gauges: Dict[LabelKey, Gauge] = {}
        self._hists: Dict[LabelKey, Histogram] = {}

    def counter(self, name: str, **labels) -> Counter:
        k = _key(name, labels)
        with self._lock:
            c = self._counters.get(k)
            if c is None:
                c = self._counters[k] = Counter()
        return c

    def gauge(self, name: str, **labels) -> Gauge:
        k = _key(name, labels)
        with self._lock:
            g = self._gauges.get(k)
            if g is None:
                g = self._gauges[k] = Gauge()
        return g

    def histogram(self, name: str, **labels) -> Histogram:
        k = _key(name, labels)
        with self._lock:
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = Histogram()
        return h

    @property
    def n_series(self) -> int:
        with self._lock:
            return (len(self._counters) + len(self._gauges)
                    + len(self._hists))

    # -- serialization ---------------------------------------------------

    @staticmethod
    def _flat(k: LabelKey) -> str:
        name, labels = k
        if not labels:
            return name
        return name + "{" + ",".join(f"{a}={b}" for a, b in labels) + "}"

    def snapshot(self) -> dict:
        """JSON-able snapshot of every series (stable key order)."""
        with self._lock:
            return {
                "v": 1,
                "counters": {self._flat(k): c.value
                             for k, c in sorted(self._counters.items())},
                "gauges": {self._flat(k): g.value
                           for k, g in sorted(self._gauges.items())},
                "histograms": {self._flat(k): h.to_dict()
                               for k, h in sorted(self._hists.items())},
            }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "MetricsRegistry":
        reg = cls()
        for flat, v in dict(snap.get("counters", {})).items():
            reg._counters[_unflat(flat)] = Counter(float(v))
        for flat, v in dict(snap.get("gauges", {})).items():
            reg._gauges[_unflat(flat)] = Gauge(float(v))
        for flat, d in dict(snap.get("histograms", {})).items():
            reg._hists[_unflat(flat)] = Histogram.from_dict(d)
        return reg

    def render_prom(self) -> str:
        """Prometheus text exposition (counters, gauges, summaries)."""
        lines = []
        with self._lock:
            by_name: Dict[str, list] = {}
            for (name, labels), c in sorted(self._counters.items()):
                by_name.setdefault(name, []).append(("counter", labels, c))
            for (name, labels), g in sorted(self._gauges.items()):
                by_name.setdefault(name, []).append(("gauge", labels, g))
            for name in sorted(by_name):
                typ = by_name[name][0][0]
                pname = _prom_name(name)
                lines.append(f"# TYPE {pname} {typ}")
                for _, labels, m in by_name[name]:
                    lines.append(f"{pname}{_prom_labels(labels)} {m.value:g}")
            for (name, labels) in sorted(self._hists):
                h = self._hists[(name, labels)]
                pname = _prom_name(name)
                lines.append(f"# TYPE {pname} summary")
                for q in (0.5, 0.9, 0.99):
                    val = h.quantile(q)
                    if val is None:
                        continue
                    lab = labels + (("quantile", f"{q:g}"),)
                    line = f"{pname}{_prom_labels(lab)} {val:g}"
                    if q == 0.99 and h.exemplar is not None:
                        # OpenMetrics tail exemplar: the trace_id of the
                        # worst exemplar-carrying observation — a p99
                        # alert resolves straight to a request trace.
                        xv, tid = h.exemplar
                        line += f' # {{trace_id="{tid}"}} {xv:g}'
                    lines.append(line)
                lines.append(
                    f"{pname}_count{_prom_labels(labels)} {h.count}")
                lines.append(
                    f"{pname}_sum{_prom_labels(labels)} {h.sum:g}")
        return "\n".join(lines) + "\n"


def _unflat(flat: str) -> LabelKey:
    if "{" not in flat:
        return flat, ()
    name, rest = flat.split("{", 1)
    body = rest.rsplit("}", 1)[0]
    labels = tuple(tuple(p.split("=", 1)) for p in body.split(",") if p)
    return name, labels


# -- per-tenant accounting ledger ----------------------------------------

LEDGER_FIELDS = ("queries", "jobs", "device_ms", "em_iters", "est_flops",
                 "retries", "degraded", "quarantined", "shed",
                 "pad_waste_sum", "pad_waste_n")


class Ledger:
    """Per-(session, tenant) resource accounting.

    ``device_ms`` is the tenant's attributed share of dispatch wall time:
    a lone session charges the full query wall; a fleet tick splits its
    wall equally across the tick's active lanes (``wall_share`` on the
    query event), so fleet tenants sum back to the tick walls.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[Tuple[str, str], Dict[str, float]] = {}

    def row(self, session: str, tenant: str) -> Dict[str, float]:
        k = (str(session), str(tenant))
        with self._lock:
            r = self._rows.get(k)
            if r is None:
                r = self._rows[k] = {f: 0.0 for f in LEDGER_FIELDS}
        return r

    def accounting(self, session: Optional[str] = None) -> dict:
        """Per-tenant totals, optionally restricted to one session/fleet id.

        Returns ``{tenant: {queries, jobs, device_ms, em_iters, est_flops,
        retries, degraded, quarantined, pad_waste_frac}}`` (tenants merged
        across sessions when ``session`` is None).
        """
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            items = list(self._rows.items())
        for (sid, ten), r in items:
            if session is not None and sid != str(session):
                continue
            d = out.setdefault(ten, {f: 0.0 for f in LEDGER_FIELDS})
            for f in LEDGER_FIELDS:
                d[f] += r[f]
        for ten, d in out.items():
            n = d.pop("pad_waste_n")
            s = d.pop("pad_waste_sum")
            d["pad_waste_frac"] = (s / n) if n else 0.0
            for f in ("queries", "jobs", "em_iters", "retries",
                      "degraded", "quarantined"):
                d[f] = int(d[f])
        return dict(sorted(out.items()))

    def totals(self) -> dict:
        """Whole-process totals (same shape as one accounting row)."""
        tot = {f: 0.0 for f in LEDGER_FIELDS}
        with self._lock:
            for r in self._rows.values():
                for f in LEDGER_FIELDS:
                    tot[f] += r[f]
        n = tot.pop("pad_waste_n")
        s = tot.pop("pad_waste_sum")
        tot["pad_waste_frac"] = (s / n) if n else 0.0
        return tot

    def snapshot(self) -> list:
        with self._lock:
            return [{"session": k[0], "tenant": k[1], **r}
                    for k, r in sorted(self._rows.items())]

    @classmethod
    def from_snapshot(cls, rows: Iterable[dict]) -> "Ledger":
        led = cls()
        for d in rows:
            r = led.row(d.get("session", "-"), d.get("tenant", "-"))
            for f in LEDGER_FIELDS:
                r[f] += float(d.get(f, 0.0))
        return led


# -- the event -> metrics mapping ----------------------------------------

def _num(x) -> Optional[float]:
    return float(x) if isinstance(x, (int, float)) and not isinstance(
        x, bool) else None


def record_event(registry: MetricsRegistry, ledger: Optional[Ledger],
                 ev: dict) -> None:
    """Apply one trace-event dict to the registry (+ ledger when given).

    This is the single source of truth for how trace events become
    metrics: the live plane calls it per event as they happen, and
    ``report.summarize`` replays a trace through it for the post-hoc
    ``metrics`` section — identical mapping by construction.
    """
    kind = ev.get("kind")
    if kind == "dispatch":
        prog = str(ev.get("program", "?"))
        registry.counter("dispatches_total", program=prog).inc()
        if ev.get("first_call"):
            registry.counter("first_calls_total", program=prog).inc()
        if ev.get("recompile"):
            registry.counter("recompiles_total", program=prog).inc()
        if ev.get("error"):
            registry.counter("dispatch_errors_total", program=prog).inc()
        dur = _num(ev.get("dur"))
        if dur is not None and ev.get("barrier"):
            registry.histogram("dispatch_wall_ms", program=prog).observe(
                dur * 1e3)
    elif kind == "transfer":
        mode = "blocking" if ev.get("blocking", True) else "nonblocking"
        registry.counter("transfers_total", mode=mode).inc()
    elif kind == "query":
        sid = str(ev.get("session", "-"))
        ten = str(ev.get("tenant", sid))
        registry.counter("queries_total", tenant=ten).inc()
        wall = _num(ev.get("wall"))
        if wall is not None:
            registry.histogram("query_wall_ms", tenant=ten).observe(
                wall * 1e3, exemplar=ev.get("trace_id"))
        qw = _num(ev.get("queue_wait"))
        if qw is not None:
            registry.histogram("queue_wait_ms", tenant=ten).observe(qw * 1e3)
        if ev.get("degraded"):
            registry.counter("degraded_queries_total", tenant=ten).inc()
        if ev.get("diverged"):
            registry.counter("diverged_queries_total", tenant=ten).inc()
        ne = _num(ev.get("n_evicted"))
        if ne:
            registry.counter("evicted_rows_total", tenant=ten).inc(ne)
        cov = _num(ev.get("coverage"))
        if cov is not None:
            # Live band calibration: the observed fraction of this
            # query's new rows inside the previous query's 90% band
            # (serving sessions/fleets stamp it per query; conservative
            # lowrank bands should sit at or above 0.90).
            registry.gauge("forecast_coverage", tenant=ten).set(cov)
            registry.histogram("forecast_coverage_pct",
                               tenant=ten).observe(cov * 100.0)
        if ledger is not None:
            row = ledger.row(sid, ten)
            row["queries"] += 1
            share = _num(ev.get("wall_share"))
            if share is None:
                share = wall
            if share is not None:
                row["device_ms"] += share * 1e3
            it = _num(ev.get("n_iters"))
            if it is not None:
                row["em_iters"] += it
                N = _num(ev.get("N"))
                k = _num(ev.get("k"))
                t_rows = _num(ev.get("t_rows"))
                if N and k and t_rows:
                    row["est_flops"] += em_iter_work(
                        int(N), int(t_rows), int(k))[0] * it
            if ev.get("degraded"):
                row["degraded"] += 1
    elif kind == "request":
        # Per-request latency waterfall (obs.trace.finish_request): one
        # e2e histogram with a tail exemplar plus one histogram per stage,
        # so "where does p99 go" is answerable live, not just post-hoc.
        ten = str(ev.get("tenant", "-"))
        tid = ev.get("trace_id")
        registry.counter("requests_total", tenant=ten).inc()
        if ev.get("replay"):
            registry.counter("replayed_requests_total", tenant=ten).inc()
        if ev.get("dedup"):
            registry.counter("dedup_hits_total", tenant=ten).inc()
        e2e = _num(ev.get("e2e"))
        if e2e is not None:
            registry.histogram("request_e2e_ms", tenant=ten).observe(
                e2e * 1e3, exemplar=tid)
        for stage, dur in dict(ev.get("stages") or {}).items():
            d = _num(dur)
            if d is not None:
                registry.histogram("request_stage_ms",
                                   stage=str(stage)).observe(
                    max(d, 0.0) * 1e3, exemplar=tid)
    elif kind == "tick":
        fid = str(ev.get("session", "-"))
        registry.counter("ticks_total", fleet=fid).inc()
        wall = _num(ev.get("wall"))
        if wall is not None:
            registry.histogram("tick_wall_ms", fleet=fid).observe(wall * 1e3)
        b = _num(ev.get("batch"))
        a = _num(ev.get("n_active"))
        if b and a is not None:
            registry.gauge("fleet_occupancy", fleet=fid,
                           bucket=str(ev.get("bucket", "?"))).set(a / b)
    elif kind == "tenant":
        ten = str(ev.get("tenant", "-"))
        registry.counter("jobs_total", tenant=ten).inc()
        cs = _num(ev.get("compute_s"))
        if cs is not None:
            registry.histogram("job_compute_ms", tenant=ten).observe(cs * 1e3)
        if ledger is not None:
            row = ledger.row(str(ev.get("session", "sched")), ten)
            row["jobs"] += 1
            if cs is not None:
                row["device_ms"] += cs * 1e3
            it = _num(ev.get("n_iters"))
            if it is not None:
                row["em_iters"] += it
                N = _num(ev.get("N"))
                k = _num(ev.get("k"))
                T = _num(ev.get("T"))
                if N and k and T:
                    row["est_flops"] += em_iter_work(
                        int(N), int(T), int(k))[0] * it
            pw = _num(ev.get("pad_waste_frac"))
            if pw is not None:
                row["pad_waste_sum"] += pw
                row["pad_waste_n"] += 1
            if ev.get("quarantined"):
                row["quarantined"] += 1
    elif kind == "health":
        event = str(ev.get("event", "?"))
        registry.counter("health_events_total", event=event).inc()
        bo = _num(ev.get("backoff_s"))
        if bo:
            registry.counter("backoff_s_total").inc(bo)
        ten = ev.get("tenant")
        sid = ev.get("session")
        if ledger is not None and (ten or sid):
            row = ledger.row(str(sid or "-"), str(ten or sid))
            if event == "dispatch_error" and ev.get("action") == "retried":
                row["retries"] += 1
            if event == "quarantine":
                row["quarantined"] += 1
            if event == "shed":
                row["shed"] += 1
        if event == "dispatch_error" and ev.get("action") == "retried":
            registry.counter("dispatch_retries_total").inc()
        if event == "quarantine":
            registry.counter("quarantines_total").inc()
        if event == "shed":
            registry.counter("sheds_total",
                             tenant=str(ten or "-")).inc()
        if event == "drift":
            # Model-quality drift transitions (obs/drift.py): the
            # fired/cleared health event carries the CUSUM score, so the
            # gauge is replayable from a trace — live plane and
            # report.summarize see the same values by construction.
            who = str(ten or sid or "-")
            registry.counter("drift_events_total", tenant=who,
                             action=str(ev.get("action", "?"))).inc()
            ds = _num(ev.get("drift_score"))
            if ds is not None:
                registry.gauge("drift_score", tenant=who).set(ds)
    elif kind == "maintenance":
        # Closed-loop maintenance decision trail (fleet/maintenance.py):
        # trigger / refit / swap / skip share one kind with an ``action``
        # discriminator; the Prometheus export rides on these series.
        ten = str(ev.get("tenant", "-"))
        action = str(ev.get("action", "?"))
        registry.counter("maintenance_events_total", tenant=ten,
                         action=action).inc()
        if action == "refit":
            registry.counter("refits_total", tenant=ten).inc()
            cs = _num(ev.get("refit_s"))
            if cs is not None:
                registry.histogram("refit_ms", tenant=ten).observe(cs * 1e3)
        elif action in ("swap", "retune"):
            # "retune" = the hyper-tuned candidate won the held-out gate
            # (MaintenancePolicy(retune=True)) — still a params swap.
            registry.counter("swaps_total", tenant=ten).inc()
            qd = _num(ev.get("quality_delta"))
            if qd is not None:
                registry.gauge("maintenance_quality_delta",
                               tenant=ten).set(qd)
        elif action == "skip":
            registry.counter("maintenance_skips_total", tenant=ten).inc()
    elif kind == "tune":
        # Differentiable hyper-tuning (estim/tune.py): one event per
        # tune_fit call with the search method, chosen scales and the
        # held-out improvement.  Replayable from traces like the
        # maintenance trail — live plane and summarize() agree.
        method = str(ev.get("method", "?"))
        registry.counter("tunes_total", method=method).inc()
        wall = _num(ev.get("wall"))
        if wall is not None:
            registry.histogram("tune_wall_ms", method=method).observe(
                wall * 1e3)
        hb = _num(ev.get("heldout_before"))
        ha = _num(ev.get("heldout_after"))
        if hb is not None and ha is not None and hb > 0:
            registry.gauge("tune_heldout_gain", method=method).set(
                (hb - ha) / hb)
        nd = _num(ev.get("dispatches"))
        if nd is not None:
            registry.gauge("tune_dispatches", method=method).set(nd)
    elif kind == "daemon":
        # The serving daemon's front door (dfm_tpu/daemon/): admission,
        # durability and handoff events share one kind with an
        # ``action`` discriminator.
        fid = str(ev.get("session", "-"))
        action = str(ev.get("action", "?"))
        registry.counter("daemon_events_total", fleet=fid,
                         action=action).inc()
        depth = _num(ev.get("depth"))
        if depth is not None and action in ("request", "backpressure"):
            registry.histogram("daemon_queue_depth", fleet=fid).observe(
                depth)
        if action == "backpressure":
            ra = _num(ev.get("retry_after_s"))
            if ra is not None:
                registry.histogram("daemon_retry_after_ms",
                                   fleet=fid).observe(ra * 1e3)
        if action == "handoff":
            gap = _num(ev.get("gap_ms"))
            if gap is not None:
                registry.histogram("daemon_handoff_gap_ms",
                                   fleet=fid).observe(gap)
        if action == "replay":
            n = _num(ev.get("n_entries"))
            if n:
                registry.counter("daemon_replayed_total",
                                 fleet=fid).inc(n)
    elif kind == "page":
        fid = str(ev.get("session", "-"))
        action = str(ev.get("action", "?"))
        registry.counter("page_events_total", fleet=fid, action=action).inc()
        wall = _num(ev.get("wall"))
        if wall is not None and action == "admit":
            registry.histogram("readmission_ms", fleet=fid).observe(
                wall * 1e3)
    elif kind == "fit":
        registry.counter("fits_total").inc()
        wall = _num(ev.get("wall"))
        if wall is not None:
            registry.histogram("fit_wall_ms").observe(wall * 1e3)
        it = _num(ev.get("n_iters"))
        if it is not None:
            registry.counter("em_iters_total").inc(it)
    elif kind == "chunk":
        registry.counter("chunks_total").inc()


def metrics_summary(registry: MetricsRegistry) -> dict:
    """Compact JSON-able digest of a registry for ``report.summarize``."""
    snap = registry.snapshot()
    hists = {}
    for flat, d in snap["histograms"].items():
        h = Histogram.from_dict(d)
        hists[flat] = {"count": h.count, "sum": round(h.sum, 6),
                       "p50": round(h.quantile(0.5), 6),
                       "p99": round(h.quantile(0.99), 6)}
    return {"n_series": (len(snap["counters"]) + len(snap["gauges"])
                         + len(snap["histograms"])),
            "counters": {k: v for k, v in snap["counters"].items()},
            "gauges": {k: round(v, 6) for k, v in snap["gauges"].items()},
            "histograms": hists}

"""Transfer-barriered micro-profiler: measured program profiles for the
cost model (``python -m dfm_tpu.obs.profile --shape N,T,K``).

Measures what the static ``program_cost`` numbers cannot — the REALIZED
wall of each fit variant (chunked, pipelined, fused, pit_qr — the
chunked driver under the parallel-in-time QR filter — and lowrank, the
same driver under the rank-r downdate filter) at a concrete shape,
split into the components the calibrated cost model (``obs.cost``)
fits:

- warm/cold walls: cold pass compiles, warm passes are a best-of-N
  median of already-compiled fits (every wall is bounded by the fit's
  own d2h reads — the only execution barrier on the axon tunnel, so
  ``time.perf_counter`` around ``fit()`` measures execution, not
  enqueue).
- dispatch overhead vs sustained ms/iter (chunked variant): a two-point
  iteration sweep (``iters`` vs ``2*iters``, same chunk size, so the
  SAME executables serve both points) isolates the per-iteration slope,
  and a chunk-halving probe (same iterations, double the dispatches)
  isolates the per-dispatch cost; sustained = slope minus the amortized
  dispatch share.
- one traced pass per variant feeds dispatch counts, latency
  percentiles, and (cost capture on) static flops/bytes into the record.

Results persist as ``kind="profile"`` records in the ``.dfm_runs/``
registry next to the bench RunRecords; ``obs.advise`` and
``fit(auto=True)`` consume them from there.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from statistics import median
from typing import Iterable, List, Optional, Tuple

__all__ = ["profile_record", "profile_shape", "main", "PROFILE_KIND",
           "VARIANTS"]

PROFILE_KIND = "profile"
VARIANTS = ("chunked", "pipelined", "fused", "pit_qr", "lowrank")


def profile_record(variant: str, N: int, T: int, k: int, *, iters: int,
                   metrics: dict, chunk: Optional[int] = None,
                   depth: Optional[int] = None,
                   bucket: Optional[bool] = None,
                   device: Optional[str] = None,
                   run_id: Optional[str] = None) -> dict:
    """Assemble one ProfileRecord (jax-free; a RunRecord with
    ``kind="profile"`` and the plan baked into the config fingerprint)."""
    from .store import device_kind, make_record
    config = {"profile": str(variant), "N": int(N), "T": int(T),
              "k": int(k), "iters": int(iters),
              "device": device_kind(device)}
    if chunk is not None:
        config["chunk"] = int(chunk)
    if depth is not None:
        config["depth"] = int(depth)
    if bucket is not None:
        config["bucket"] = bool(bucket)
    return make_record(PROFILE_KIND, config, metrics, device=device,
                       run_id=run_id)


def _cost_per_iter(summary: dict, program: str,
                   iters_per_dispatch: float) -> dict:
    c = (summary.get("costs") or {}).get(program) or {}
    out = {}
    if isinstance(c.get("flops"), (int, float)) and iters_per_dispatch > 0:
        out["flops_per_iter"] = float(c["flops"]) / iters_per_dispatch
    if isinstance(c.get("bytes_accessed"), (int, float)) \
            and iters_per_dispatch > 0:
        out["bytes_per_iter"] = float(c["bytes_accessed"]) / iters_per_dispatch
    return out


def profile_shape(N: int, T: int, k: int, *, iters: int = 24,
                  repeats: int = 3, chunk: int = 8,
                  variants: Iterable[str] = VARIANTS, seed: int = 0,
                  capture_costs: bool = True,
                  log=None) -> Tuple[List[dict], str]:
    """Profile the fit variants at shape (N, T, k); returns
    ``(records, device_str)`` — persisting is the caller's decision.

    Probes run with the run registry masked (like ``bench.py``'s timing
    probes): profiling must only ever APPEND profile records the caller
    asked for, never leak per-probe fit records.
    """
    import numpy as np

    import jax

    from ..api import DynamicFactorModel, TPUBackend, fit
    from ..backends import cpu_ref
    from ..utils import dgp
    from .cost import RecompileDetector
    from .trace import Tracer

    say = log or (lambda *_: None)
    rng = np.random.default_rng(seed)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    Y = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Y, k)
    model = DynamicFactorModel(n_factors=k, standardize=False)
    dev = jax.devices()[0]
    device = f"{dev.platform} ({dev.device_kind})"

    runs_env = os.environ.pop("DFM_RUNS", None)
    try:
        def timed(b, n, **kw):
            t0 = time.perf_counter()
            fit(model, Y, backend=b, max_iters=n, tol=0.0, init=p0, **kw)
            return time.perf_counter() - t0

        def traced(b, n, **kw):
            tr = Tracer(capture_costs=capture_costs,
                        detector=RecompileDetector())
            fit(model, Y, backend=b, max_iters=n, tol=0.0, init=p0,
                telemetry=tr, **kw)
            return tr.summary()

        records = []
        for variant in variants:
            if variant not in VARIANTS:
                raise ValueError(f"unknown profile variant {variant!r} "
                                 f"(want one of {VARIANTS})")
            say(f"profile {variant} N={N} T={T} k={k} iters={iters} ...")
            # pit_qr / lowrank = the chunked driver under the respective
            # time-scan engine; everything else (timing, tracing) is
            # identical.
            b = (TPUBackend(fused_chunk=chunk, filter=variant)
                 if variant in ("pit_qr", "lowrank")
                 else TPUBackend(fused_chunk=chunk))
            kw = ({"fused": True} if variant == "fused"
                  else {"pipeline": 2} if variant == "pipelined" else {})
            cold = timed(b, iters, **kw)
            summary = traced(b, iters, **kw)
            warm = median(timed(b, iters, **kw) for _ in range(repeats))
            metrics = {"cold_wall_s": cold, "warm_wall_s": warm,
                       "ms_per_iter_warm": 1e3 * warm / iters,
                       "dispatches": summary.get("dispatches"),
                       "blocking_transfers":
                           summary.get("blocking_transfers")}
            dp = summary.get("dispatch_percentiles_ms")
            if dp:
                metrics["p99_dispatch_ms"] = dp["p99"]
            if variant == "chunked":
                # Two-point iteration sweep: same chunk size, so the same
                # executables serve both points — the slope is pure
                # per-iteration cost (incl. the amortized dispatch share).
                hi = median(timed(b, 2 * iters, **kw)
                            for _ in range(repeats))
                slope = max((hi - warm) / iters, 1e-9)
                # Chunk-halving probe: same iterations, ~double the
                # dispatches — the wall delta is pure dispatch overhead.
                c2 = max(1, chunk // 2)
                b2 = TPUBackend(fused_chunk=c2)
                timed(b2, iters, **kw)            # compile the c2 programs
                half = median(timed(b2, iters, **kw)
                              for _ in range(repeats))
                n_lo = -(-iters // chunk)
                extra = -(-iters // c2) - n_lo
                disp_s = (max((half - warm) / extra, 0.0) if extra > 0
                          else 0.0)
                metrics.update(
                    sustained_ms_per_iter=1e3 * max(slope - disp_s / chunk,
                                                    1e-9),
                    dispatch_ms_per_program=1e3 * disp_s,
                    fit_overhead_s=max(warm - iters * slope, 0.0))
                metrics.update(_cost_per_iter(summary, "em_fit_scan",
                                              chunk))
            elif variant == "fused":
                metrics["dispatches_per_fit"] = summary.get("dispatches")
                metrics.update(_cost_per_iter(summary, "fused_fit", iters))
            metrics = {k_: v for k_, v in metrics.items() if v is not None}
            records.append(profile_record(
                variant, N, T, k, iters=iters, chunk=chunk,
                depth=2 if variant == "pipelined" else None,
                metrics=metrics, device=device))
            say(f"  warm {warm:.3f}s ({1e3 * warm / iters:.2f} ms/iter), "
                f"cold {cold:.3f}s")
        return records, device
    finally:
        if runs_env is not None:
            os.environ["DFM_RUNS"] = runs_env


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dfm_tpu.obs.profile",
        description="Measure per-variant fit walls at a shape and persist "
                    "ProfileRecords for the calibrated cost model.")
    ap.add_argument("--shape", required=True, metavar="N,T,K",
                    help="panel shape to profile")
    ap.add_argument("--iters", type=int, default=24)
    ap.add_argument("--repeats", type=int, default=3,
                    help="warm passes per measurement (median)")
    ap.add_argument("--chunk", type=int, default=8,
                    help="fused_chunk for the chunked/fused variants")
    ap.add_argument("--variants", default=",".join(VARIANTS),
                    help=f"comma list from {VARIANTS}")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--runs", default=None,
                    help="registry dir (default: DFM_RUNS or .dfm_runs)")
    ap.add_argument("--no-costs", action="store_true",
                    help="skip the static program_cost capture pass")
    ap.add_argument("--json", action="store_true",
                    help="emit the ProfileRecords as JSON on stdout")
    args = ap.parse_args(argv)
    try:
        N, T, k = (int(x) for x in args.shape.split(","))
    except ValueError:
        print(f"error: --shape wants N,T,K, got {args.shape!r}",
              file=sys.stderr)
        return 2

    from .store import RunStore, runs_dir
    d = runs_dir(args.runs)
    say = lambda *a: print(*a, file=sys.stderr)  # noqa: E731
    records, device = profile_shape(
        N, T, k, iters=args.iters, repeats=args.repeats, chunk=args.chunk,
        variants=[v for v in args.variants.split(",") if v],
        seed=args.seed, capture_costs=not args.no_costs, log=say)
    if d is not None:
        store = RunStore(d)
        for rec in records:
            store.append(rec)
        say(f"recorded {len(records)} profile(s) for {device} in {d}")
    else:
        say("run registry disabled (DFM_RUNS=\"\"): profiles not persisted")
    if args.json:
        json.dump(records, sys.stdout, indent=2, default=str)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""The always-on live telemetry plane (jax-free).

One process-local ``LivePlane`` singleton aggregates every serving seam —
fleet ticks/queries, session updates, scheduler bucket jobs, guard
retries, fit drivers — into bounded-memory live state:

- a ``MetricsRegistry`` (counters/gauges/streaming quantiles) and a
  per-tenant ``Ledger``, fed through ``metrics.record_event``;
- an ``SLOMonitor`` evaluating rolling error-budget burn rate (armed via
  ``set_slo`` or ``DFM_SLO_P99_MS``/``DFM_SLO_ERROR_RATE``/
  ``DFM_SLO_WINDOW``; disarmed by default) plus an ``AnomalyDetector``
  for p99 spikes vs the rolling baseline;
- a flight recorder: a bounded ring of the most recent trace events,
  always on, auto-dumped to an ``obs.report``-compatible JSONL when an
  SLO breach or latency anomaly fires (dumps only when
  ``DFM_FLIGHT_DIR`` is set — the library never creates files as a side
  effect of serving).

The plane is fed from timestamps the trace layer already takes: when a
tracer is active, ``Tracer.emit`` forwards every event here (post-lock);
when NOT traced, the serving seams build the same event dict they would
have traced and call ``observe`` directly.  Either way the device hot
path is untouched — no extra dispatches, no extra transfers, no clock
reads beyond the ones the seams already make — and ``DFM_METRICS=0``
turns the whole plane into a no-op.

Live surfaces: ``plane().registry.render_prom()``, ``accounting()``,
``status()``, periodic JSON snapshots to ``DFM_METRICS_SNAPSHOT`` (every
``DFM_METRICS_INTERVAL_S``, atomic rename), and the jax-free CLI::

    python -m dfm_tpu.obs.live [snapshot|prom] [--json] [--watch]
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Optional

from .drift import DriftConfig, DriftDetector, drift_from_env
from .metrics import Ledger, MetricsRegistry, record_event
from .slo import AnomalyDetector, SLOConfig, SLOMonitor, slo_from_env

__all__ = ["LivePlane", "plane", "observe", "reset_plane", "set_slo",
           "set_drift", "drift_status", "accounting", "status"]


def _json_default(o):
    for attr in ("item", "tolist"):
        f = getattr(o, attr, None)
        if f is not None:
            try:
                return f()
            except Exception:
                break
    return repr(o)


class LivePlane:
    """Always-on, bounded-memory live metrics for one process."""

    def __init__(self, enabled: bool = True,
                 slo: Optional[SLOConfig] = None,
                 ring_events: int = 4096,
                 flight_dir: Optional[str] = None,
                 flight_min_interval_s: float = 10.0,
                 snapshot_path: Optional[str] = None,
                 snapshot_interval_s: float = 5.0,
                 drift: Optional[DriftConfig] = None):
        self.enabled = enabled
        self.registry = MetricsRegistry()
        self.ledger = Ledger()
        self.slo = SLOMonitor(slo)
        self.anomaly = AnomalyDetector()
        self.drift_cfg = drift           # None == disarmed (the default)
        self._drift: dict = {}           # (tenant-or-session) -> detector
        self.ring: deque = deque(maxlen=int(ring_events))
        self.health_events: list = []       # HealthEvent(kind="slo_burn"/..)
        self.flight_dir = flight_dir
        self.flight_min_interval_s = float(flight_min_interval_s)
        self.flight_dumps = 0
        self.snapshot_path = snapshot_path
        self.snapshot_interval_s = float(snapshot_interval_s)
        self.errors = 0
        self._flight_warned = False      # warn-once on unwritable dir
        self._flight_broken = False      # auto-dumps disabled after OSError
        self._dump_seq = 0
        self._last_dump_t: Optional[float] = None
        self._last_snap_t: Optional[float] = None
        self._lock = threading.Lock()
        self._tls = threading.local()

    @classmethod
    def from_env(cls) -> "LivePlane":
        env = os.environ.get
        enabled = env("DFM_METRICS", "1").lower() not in ("0", "off", "false")
        return cls(
            enabled=enabled,
            slo=slo_from_env(),
            ring_events=int(env("DFM_FLIGHT_EVENTS", "4096")),
            flight_dir=env("DFM_FLIGHT_DIR") or None,
            flight_min_interval_s=float(env("DFM_FLIGHT_MIN_INTERVAL_S",
                                            "10.0")),
            snapshot_path=env("DFM_METRICS_SNAPSHOT") or None,
            snapshot_interval_s=float(env("DFM_METRICS_INTERVAL_S", "5.0")),
            drift=drift_from_env())

    # -- the single entry point ------------------------------------------

    def observe(self, ev: dict) -> None:
        """Fold one trace-event dict into the live state.  Never raises,
        never touches the device, reentrancy-safe (events emitted while
        handling an event — e.g. the slo_burn mirror through an active
        tracer — are dropped rather than recursed)."""
        if not self.enabled:
            return
        if getattr(self._tls, "busy", False):
            return
        self._tls.busy = True
        try:
            with self._lock:
                self.ring.append(ev)
                record_event(self.registry, self.ledger, ev)
                transitions = self._feed_guards(ev)
            for name, action, detail, extra in transitions:
                self._emit_burn(ev, name, action, detail, extra)
            self._maybe_snapshot(ev.get("t"))
        except Exception:
            self.errors += 1
        finally:
            self._tls.busy = False

    # -- SLO / anomaly plumbing ------------------------------------------

    def _feed_guards(self, ev: dict) -> list:
        out = []
        kind = ev.get("kind")
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            return out
        if kind == "query":
            wall = ev.get("wall")
            wall_ms = wall * 1e3 if isinstance(wall, (int, float)) else 0.0
            bad = bool(ev.get("diverged")) or bool(ev.get("error"))
            trans = self.slo.observe(t, wall_ms, error=bad)
            if trans == "fire":
                out.append(("slo_burn", "fired",
                            f"burn_rate={self.slo.burn_rate:.2f}", None))
            elif trans == "clear":
                out.append(("slo_burn", "cleared",
                            f"burn_rate={self.slo.burn_rate:.2f}", None))
            if self.anomaly.observe(wall_ms):
                out.append(("latency_anomaly", "spike",
                            f"p99 vs baseline "
                            f"{self.anomaly.baseline_ms:.3f}ms", None))
            if self.drift_cfg is not None:
                key = str(ev.get("tenant") or ev.get("session") or "-")
                det = self._drift.get(key)
                if det is None:
                    det = self._drift[key] = DriftDetector(self.drift_cfg)
                dt = det.observe(t, innov_z=ev.get("innov_z"),
                                 coverage=ev.get("coverage"),
                                 ll_per_row=ev.get("ll_per_row"))
                if dt is not None:
                    # Carry the CUSUM score + trigger signals on the
                    # health event so record_event can map them (live ==
                    # replay) and the maintenance trail sees the values
                    # at the moment of the decision.
                    extra = {"drift_score": round(det.drift_score, 6),
                             **{k: round(v, 6)
                                for k, v in det.last.items()}}
                    out.append(("drift",
                                "fired" if dt == "fire" else "cleared",
                                f"drift_score={det.drift_score:.2f}",
                                extra))
        elif (kind == "health" and ev.get("event") == "dispatch_error"):
            self.slo.observe(t, 0.0, error=True)
        return out

    def _emit_burn(self, src: dict, name: str, action: str,
                   detail: str, extra: Optional[dict] = None) -> None:
        """Record an slo_burn / latency_anomaly health event: into the
        flight ring + registry directly (the reentrancy guard is up), as
        a ``HealthEvent``, mirrored to any active tracer, and — the whole
        point of the flight recorder — dump the ring to JSONL."""
        t = src.get("t")
        from ..robust.health import HealthEvent
        he = HealthEvent(chunk=-1, iteration=-1, kind=name, detail=detail,
                         action=action, t=t if isinstance(t, (int, float))
                         else 0.0, engine="live",
                         tenant=str(src.get("tenant", "")),
                         session=str(src.get("session", "")),
                         trace_id=str(src.get("trace_id", "")))
        ev = {"t": he.t, "kind": "health", "event": name, "chunk": -1,
              "iteration": -1, "action": action, "detail": detail,
              "engine": "live",
              "burn_rate": round(self.slo.burn_rate, 6)}
        if extra:
            ev.update(extra)
        if he.tenant:
            ev["tenant"] = he.tenant
        if he.session:
            ev["session"] = he.session
        if he.trace_id:
            # The offending request: an SLO burn / latency spike fired on
            # THIS query's observation, so its trace_id is the tail
            # exemplar — the flight dump and the mirrored health event
            # both resolve straight back to the request's full waterfall.
            ev["trace_id"] = he.trace_id
        with self._lock:
            self.health_events.append(he)
            self.ring.append(ev)
            record_event(self.registry, self.ledger, ev)
        from .trace import current_tracer
        tr = current_tracer()
        if tr is not None:
            payload = {k: v for k, v in ev.items() if k not in ("t", "kind")}
            tr.emit("health", t=he.t, **payload)
        if action in ("fired", "spike"):
            self._maybe_dump(he.t)

    # -- flight recorder --------------------------------------------------

    def dump_flight(self, path: Optional[str] = None) -> Optional[str]:
        """Write the ring to an ``obs.report``-compatible JSONL; returns
        the path (None when no destination is configured or the
        destination is unwritable).  A missing/unwritable ``flight_dir``
        warns ONCE per plane and disables further auto-dumps — a breach
        forensics failure must never raise into (or block) the serving
        path that triggered it."""
        auto = path is None
        if auto:
            if not self.flight_dir or self._flight_broken:
                return None
        with self._lock:
            events = list(self.ring)
        try:
            if auto:
                os.makedirs(self.flight_dir, exist_ok=True)
                self._dump_seq += 1
                path = os.path.join(
                    self.flight_dir,
                    f"flight-{os.getpid()}-{self._dump_seq}.jsonl")
            with open(path, "w", encoding="utf-8") as fh:
                for ev in events:
                    fh.write(json.dumps(ev, default=_json_default) + "\n")
        except OSError as e:
            self.errors += 1
            if auto:
                self._flight_broken = True
            if not self._flight_warned:
                self._flight_warned = True
                import warnings
                warnings.warn(
                    f"flight-recorder dump to {path or self.flight_dir!r} "
                    f"failed ({e}); serving continues, "
                    + ("further auto-dumps are disabled for this process"
                       if auto else "this dump was skipped"),
                    RuntimeWarning, stacklevel=2)
            return None
        self.flight_dumps += 1
        return path

    def _maybe_dump(self, t: float) -> None:
        if not self.flight_dir:
            return
        if (self._last_dump_t is not None
                and t - self._last_dump_t < self.flight_min_interval_s):
            return
        self._last_dump_t = t
        self.dump_flight()

    # -- snapshots --------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "v": 1,
            "registry": self.registry.snapshot(),
            "ledger": self.ledger.snapshot(),
            "slo": self.slo.status(),
            "anomaly": self.anomaly.status(),
            "drift": self.drift_status(),
            "flight": {"ring_events": len(self.ring),
                       "dumps": self.flight_dumps,
                       "dir": self.flight_dir},
            "errors": self.errors,
        }

    def write_snapshot(self, path: Optional[str] = None) -> Optional[str]:
        """Atomically write the snapshot JSON (tmp + rename)."""
        path = path or self.snapshot_path
        if not path:
            return None
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self.snapshot(), fh, default=_json_default)
        os.replace(tmp, path)
        return path

    def _maybe_snapshot(self, t) -> None:
        if not self.snapshot_path or not isinstance(t, (int, float)):
            return
        if (self._last_snap_t is not None
                and t - self._last_snap_t < self.snapshot_interval_s):
            return
        self._last_snap_t = t
        self.write_snapshot()

    # -- drift ------------------------------------------------------------

    def set_drift(self, config: Optional[DriftConfig]) -> None:
        """Arm (or disarm, with None) per-tenant drift detection; existing
        detector state is dropped (a new objective needs new baselines)."""
        with self._lock:
            self.drift_cfg = config
            self._drift = {}

    def drift_status(self) -> dict:
        """Live per-tenant drift state (the daemon ``status`` surface)."""
        with self._lock:
            per = {k: d.status() for k, d in sorted(self._drift.items())}
        return {"armed": self.drift_cfg is not None,
                "n_tenants": len(per),
                "breached": sorted(k for k, s in per.items()
                                   if s["breached"]),
                "per_tenant": per}

    def drift_state(self, key: str) -> Optional[dict]:
        """Snapshot one tenant's detector (session/fleet persistence)."""
        with self._lock:
            det = self._drift.get(str(key))
        return det.state_dict() if det is not None else None

    def restore_drift(self, key: str, state: Optional[dict]) -> None:
        """Re-seed one tenant's detector from ``drift_state`` output (only
        meaningful when the plane is armed — a disarmed plane stays
        detector-free so the off path is bit-identical)."""
        if state is None or self.drift_cfg is None:
            return
        with self._lock:
            self._drift[str(key)] = DriftDetector.from_state(state)

    def reset_drift(self, key: str) -> None:
        """Start a fresh baseline for one tenant (post-swap regime)."""
        with self._lock:
            det = self._drift.get(str(key))
            if det is not None:
                det.reset()

    # -- queries ----------------------------------------------------------

    def accounting(self, session: Optional[str] = None) -> dict:
        return self.ledger.accounting(session)

    def status(self) -> dict:
        return {
            "enabled": self.enabled,
            "n_series": self.registry.n_series,
            "slo": self.slo.status(),
            "anomaly": self.anomaly.status(),
            "drift": self.drift_status(),
            "flight_dumps": self.flight_dumps,
            "ring_events": len(self.ring),
            "errors": self.errors,
        }


# -- process singleton ----------------------------------------------------

_PLANE: Optional[LivePlane] = None
_PLANE_LOCK = threading.Lock()


def plane() -> LivePlane:
    """The process-local live plane (created lazily from the environment)."""
    global _PLANE
    p = _PLANE
    if p is None:
        with _PLANE_LOCK:
            p = _PLANE
            if p is None:
                p = _PLANE = LivePlane.from_env()
    return p


def observe(ev: dict) -> None:
    """Module-level fast path used by ``Tracer.emit`` and the untraced
    serving seams."""
    plane().observe(ev)


def reset_plane() -> None:
    """Drop the singleton so the next ``plane()`` re-reads the
    environment (tests / forked workers)."""
    global _PLANE
    with _PLANE_LOCK:
        _PLANE = None


def set_slo(config: Optional[SLOConfig]) -> None:
    """Arm (or disarm, with None) the live plane's SLO monitor."""
    plane().slo.set_config(config)


def set_drift(config: Optional[DriftConfig]) -> None:
    """Arm (or disarm, with None) per-tenant drift detection."""
    plane().set_drift(config)


def drift_status() -> dict:
    """Live per-tenant drift state (armed flag + detector statuses)."""
    return plane().drift_status()


def accounting(session: Optional[str] = None) -> dict:
    return plane().accounting(session)


def status() -> dict:
    return plane().status()


# -- CLI ------------------------------------------------------------------

def _fmt_snapshot(snap: dict) -> str:
    lines = []
    reg = snap.get("registry", {})
    lines.append("== live metrics snapshot ==")
    slo = snap.get("slo", {})
    lines.append(
        f"slo: armed={slo.get('armed')} breached={slo.get('breached')} "
        f"burn_rate={slo.get('burn_rate')} (max {slo.get('burn_rate_max')}, "
        f"fired {slo.get('n_fired')}x)")
    an = snap.get("anomaly", {})
    lines.append(f"anomaly: baseline_ms={an.get('baseline_ms')} "
                 f"spiking={an.get('spiking')} n_spikes={an.get('n_spikes')}")
    fl = snap.get("flight", {})
    lines.append(f"flight: ring={fl.get('ring_events')} events, "
                 f"dumps={fl.get('dumps')}")
    counters = reg.get("counters", {})
    if counters:
        lines.append("-- counters --")
        for k, v in counters.items():
            lines.append(f"  {k:<56s} {v:g}")
    gauges = reg.get("gauges", {})
    if gauges:
        lines.append("-- gauges --")
        for k, v in gauges.items():
            lines.append(f"  {k:<56s} {v:g}")
    hists = reg.get("histograms", {})
    if hists:
        from .metrics import Histogram
        lines.append("-- quantiles --")
        for k, d in hists.items():
            h = Histogram.from_dict(d)
            p50, p99 = h.quantile(0.5), h.quantile(0.99)
            lines.append(
                f"  {k:<44s} n={h.count:<7d} p50={p50:.4g} p99={p99:.4g}")
    ledger = snap.get("ledger", [])
    if ledger:
        lines.append("-- ledger (per session x tenant) --")
        for row in ledger:
            lines.append(
                f"  {row.get('session')}/{row.get('tenant')}: "
                f"queries={int(row.get('queries', 0))} "
                f"jobs={int(row.get('jobs', 0))} "
                f"device_ms={row.get('device_ms', 0.0):.2f} "
                f"em_iters={int(row.get('em_iters', 0))} "
                f"est_flops={row.get('est_flops', 0.0):.3g} "
                f"retries={int(row.get('retries', 0))} "
                f"degraded={int(row.get('degraded', 0))}")
    return "\n".join(lines)


def _render(snap: dict, mode: str, as_json: bool) -> str:
    if mode == "prom":
        return MetricsRegistry.from_snapshot(
            snap.get("registry", {})).render_prom()
    if as_json:
        return json.dumps(snap, default=_json_default)
    return _fmt_snapshot(snap)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m dfm_tpu.obs.live",
        description="Read live-plane metric snapshots (jax-free). The "
                    "serving process writes them when DFM_METRICS_SNAPSHOT "
                    "is set; point --file (or the same env var) here.")
    ap.add_argument("mode", nargs="?", default="snapshot",
                    choices=("snapshot", "prom"))
    ap.add_argument("--file", default=os.environ.get("DFM_METRICS_SNAPSHOT"),
                    help="snapshot JSON path (default: $DFM_METRICS_SNAPSHOT)")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON instead of the text rendering")
    ap.add_argument("--watch", action="store_true",
                    help="re-read and re-render every --interval seconds")
    ap.add_argument("--interval", type=float, default=2.0)
    args = ap.parse_args(argv)
    if not args.file:
        ap.error("no snapshot file: set DFM_METRICS_SNAPSHOT or pass --file")

    def once() -> int:
        try:
            with open(args.file, "r", encoding="utf-8") as fh:
                snap = json.load(fh)
        except FileNotFoundError:
            print(f"obs.live: no snapshot at {args.file} yet", flush=True)
            return 1
        except json.JSONDecodeError as e:
            print(f"obs.live: unreadable snapshot ({e})", flush=True)
            return 1
        print(_render(snap, args.mode, args.json), flush=True)
        return 0

    if not args.watch:
        return once()
    import time
    while True:     # pragma: no cover - interactive loop
        once()
        time.sleep(max(0.1, args.interval))


if __name__ == "__main__":      # pragma: no cover
    raise SystemExit(main())

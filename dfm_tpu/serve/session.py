"""Persistent nowcast sessions: one fused program dispatch per query.

The production query loop (ROADMAP item 1; PAPERS.md arXiv 1910.08615's
online-update-and-impute use of a fitted smoother) is "here is this
month's ragged-edge panel update, give me the nowcast now".  A cold
``fit()`` pays the whole-panel h2d upload plus a stream of ~60-100 ms
tunnel dispatches per query; a ``NowcastSession`` pays them ONCE at open:

- The standardized panel and its {0,1} observation mask live on device in
  a capacity-padded (T_cap, N) buffer (``estim.batched.pad_panel_to_t``
  zero rows + zero-mask tail — the masked filter/M-step are exactly inert
  there, the PR 8 scheduler's proven seam).
- ``update(new_rows)`` uploads only the new rows (tiny h2d), then runs
  ONE jitted program: in-graph scatter append + mask flip, m warm EM
  iterations (``estim.fused._em_while_core`` with a traced live-length
  ``n_steps`` — the t-masked M-step divides by the true transition
  count), RTS smooth, nowcast and state-space + diffusion-index
  forecasts.  The live length and row count are traced scalars, so every
  update of the session's lifetime reuses the SAME executable: zero
  recompiles after warmup.
- Host reads happen inside one barrier'd dispatch span (``serve_update``
  trace program): at most one blocking d2h per query.  The panel buffers
  and params are donated back in place on real devices.

Numerics: an update is the same program a cold ``fit(fused=True)`` on
the concatenated panel would run at the same iteration budget — pinned
by tests/test_serve.py (x64-exact for the dense small-N filter, where
the pad algebra is bitwise inert; fp-tolerance for info-form/f32).
Capacity overflow and row-budget violations raise on host BEFORE any
dispatch.  A diverged update keeps the on-device last-good params (the
fused driver's replay rule) and warns.

Unbounded streams (``ring=True``): the capacity-padded panel becomes a
RING BUFFER — an update past capacity retires the oldest rows in graph
(``serve.batched.ring_evict``: a traced roll back to the buffer origin
plus an exact re-zero of the vacated tail) instead of raising, so ONE
executable serves an infinite stream at constant device + host memory.
The eviction count is a traced scalar riding the same dispatch: zero
recompiles, still at most one blocking d2h per query.  Post-eviction
results are pinned to a cold ``fit(fused=True)`` on the equivalent
trailing window (tests/test_stream.py, x64-exact + f32 variants).

Self-healing (robust layer): sessions resolve a ``RobustPolicy`` from
the backend (or the ``robust=`` argument) and route every query through
``robust.dispatch.guarded_dispatch`` — a failed dispatch retries from
the last-good state (host shadows rebuild the donated device buffers on
real devices; one recovery h2d), a hung d2h trips the watchdog deadline
into the same retry loop, and ``policy.chunk_retries``+1 CONSECUTIVE
diverged updates escalate through the PR 1 repair ladder
(``repair_params`` + re-upload).  ``session.snapshot(path)`` /
``open_session(snapshot=path)`` persist and rebuild a warm session
(params + live standardized panel + config, content-fingerprinted via
``utils.checkpoint``): a restarted process is one h2d upload + one
dispatch away from serving again.  With ``robust=False`` the query path
is byte-identical to the unguarded original.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
import warnings
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..estim.batched import pad_panel_to_t
from ..estim.em import EMConfig, noise_floor_for
from ..estim.fused import (FusedOptions, _CONVERGED, _DIVERGED,
                           _di_forecast_core_masked, _em_while_core)
from ..obs.trace import (current_request, current_tracer, finish_request,
                         new_trace_id, request_clock, shape_key)
from ..ops.precision import accum_dtype
from ..robust.dispatch import guarded_dispatch
from ..robust.health import FitHealth, HealthEvent
from ..ssm.params import SSMParams as JaxParams
from ..utils.data import build_mask
from .batched import ring_evict

__all__ = ["NowcastSession", "SessionUpdate", "open_session"]

_SESSION_IDS = itertools.count(1)

# Engines a session can route (EMConfig.filter values whose masked
# filter/smoother pairs serve a capacity-padded panel; "ss" and "auto"
# resolve through the backend's masked pick instead).
_SERVE_FILTERS = ("dense", "info", "pit", "pit_qr", "lowrank")

# The 90% two-sided band the serving layer reports coverage against —
# the same z as ``ssm.lowrank_filter.state_coverage``'s default.
_Z90 = 1.6448536269514722


def _resolve_serve_engine(b, res, filter, rank, N):
    """Resolve a session's filter engine + lowrank rank.

    An explicit ``filter=`` wins; otherwise the fit's RESOLVED engine
    (``FitResult.filter``, stamped by ``fit``) is inherited when it can
    serve a masked panel, falling back to the backend's masked auto pick
    (``_filter_for``) for ss/auto/absent.  ``rank`` rides only with
    lowrank so every other engine's EMConfig equals the pre-routing one
    — the same executables, bit-identical serving for existing users.
    """
    if filter is not None:
        flt = str(filter)
        if flt not in _SERVE_FILTERS:
            raise ValueError(
                f"unknown serving filter {filter!r}; sessions route "
                f"{_SERVE_FILTERS}")
    else:
        rf = getattr(res, "filter", None)
        flt = rf if rf in _SERVE_FILTERS else b._filter_for(N, True)
    r = int(getattr(b, "rank", 0) if rank is None else rank)
    return flt, (r if flt == "lowrank" else 0)


def live_observe(ev: dict) -> None:
    """Feed the always-on live plane (lazy import: keeps ``python -m
    dfm_tpu.obs.live`` from pre-importing its own module via this one)."""
    from ..obs.live import observe
    observe(ev)


def _live_accounting(session: str) -> dict:
    from ..obs.live import accounting
    return accounting(session)


def _session_core(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur, p0, tol,
                  floor, cfg, max_iters, chunk, opts):
    """One query: evict, append rows, m warm EM iters, smooth, forecast.

    ``rows``/``rmask`` are (r_max, N) with exact-zero rows past ``n_new``
    (host-padded), so the scatter lands zeros on zero-masked tail slots —
    the buffer invariant (pad region exactly zero) is preserved for every
    ragged row count.  ``mode="drop"`` discards rim-adjacent writes past
    capacity (the host raised on real overflow before dispatch).

    ``n_evict`` (traced int32, 0 outside ring mode) first retires the
    oldest rows via ``ring_evict`` — the roll wraps them into the append
    region where the incoming scatter overwrites them (eviction only
    fires when ``t_new == capacity`` and ``n_evict <= n_new``), so the
    buffer always holds exactly the trailing window, zero-padded.
    """
    r_max = rows.shape[0]
    Ybuf, Wbuf = ring_evict(Ybuf, Wbuf, n_evict, t_cur)
    t_cur = t_cur - n_evict
    idx = t_cur + jnp.arange(r_max)
    Ybuf = Ybuf.at[idx].set(rows, mode="drop")
    Wbuf = Wbuf.at[idx].set(rmask, mode="drop")
    t_new = t_cur + n_new
    f = _em_while_core(Ybuf, Wbuf, p0, tol, floor, cfg, max_iters, chunk,
                       opts, n_steps=t_new)
    p_fit = f["p"]
    # Smooth + forecast at the fitted params, same program — the exact
    # pair the fused fit uses (EMConfig.report_pair: pit_qr/lowrank
    # report through their own smoothers, dense/info keep the historical
    # pairs bit-for-bit; ss never reaches masked panels).
    ff, sf = cfg.report_pair()
    kf = ff(Ybuf, p_fit, mask=Wbuf)
    sm = sf(kf, p_fit)
    x_T = jnp.take(sm.x_sm, t_new - 1, axis=0, mode="clip")
    P_T = jnp.take(sm.P_sm, t_new - 1, axis=0, mode="clip")
    nowcast = p_fit.Lam @ x_T
    # Observation-space one-sigma bands (standardized units): the
    # smoothed/predicted state covariance pushed through the loadings
    # plus the idiosyncratic variance.  Under lowrank at r < k these are
    # the CONSERVATIVE covariances (bands only widen) the serving layer
    # promotes to first-class outputs; under the exact engines they are
    # the exact predictive bands.  Free: they ride the one d2h.
    obs_sd = lambda P: jnp.sqrt(jnp.maximum(  # noqa: E731
        jnp.einsum("nk,kl,nl->n", p_fit.Lam, P, p_fit.Lam) + p_fit.R,
        jnp.zeros((), Ybuf.dtype)))
    nowcast_sd = obs_sd(P_T)

    def fstep(carry, _):
        x, P = carry
        x1 = p_fit.A @ x
        P1 = p_fit.A @ P @ p_fit.A.T + p_fit.Q
        return (x1, P1), (x1, p_fit.Lam @ x1, obs_sd(P1))

    _, (f_fore, y_fore, y_sd) = lax.scan(fstep, (x_T, P_T), None,
                                         length=opts.horizon)
    di = (_di_forecast_core_masked(sm.x_sm, Ybuf, t_new, opts.horizon)
          if opts.di else None)
    return {
        "Ybuf": Ybuf,
        "Wbuf": Wbuf,
        "p": p_fit,
        "p_good": f["p_good"],
        "good_it": f["good_it"],
        "lls": f["lls"],
        "n_iters": f["it"],
        "status": f["status"],
        "x_sm": sm.x_sm,
        "P_sm": sm.P_sm,
        "nowcast": nowcast,
        "nowcast_sd": nowcast_sd,
        "f_fore": f_fore,
        "y_fore": y_fore,
        "y_sd": y_sd,
        "di": di,
    }


_STATICS = ("cfg", "max_iters", "chunk", "opts")


@partial(jax.jit, static_argnames=_STATICS)
def _session_impl(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur, p0, tol,
                  floor, *, cfg, max_iters, chunk, opts):
    return _session_core(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur,
                         p0, tol, floor, cfg, max_iters, chunk, opts)


# Donated twin: panel buffers (0, 1) and params (7) are consumed in place
# — the session immediately rebinds the returned arrays, so device memory
# stays one buffer set deep.  CPU backends use the plain twin (donation is
# unimplemented there and warns).
@partial(jax.jit, static_argnames=_STATICS, donate_argnums=(0, 1, 7))
def _session_impl_donated(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur,
                          p0, tol, floor, *, cfg, max_iters, chunk, opts):
    return _session_core(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur,
                         p0, tol, floor, cfg, max_iters, chunk, opts)


@dataclasses.dataclass
class SessionUpdate:
    """Host-side view of one ``NowcastSession.update`` (original units)."""

    nowcast: np.ndarray        # (N,) end-of-sample nowcast, original units
    forecasts: dict            # {"y": (h, N), "f": (h, k), "di": (N,)|None}
    logliks: np.ndarray        # per-iteration loglik path of this update
    n_iters: int               # EM iterations this update consumed
    converged: bool
    diverged: bool
    factors: np.ndarray        # (t, k) smoothed factor means, live prefix
    factor_cov: np.ndarray     # (t, k, k) smoothed covariances
    t: int                     # live panel length after this update
    wall_s: float
    # First-class uncertainty bands (original units; conservative —
    # bands only widen — under ``filter="lowrank"`` at r < k, exact
    # under the exact engines).  ``coverage`` is the observed fraction
    # of THIS update's new rows inside the PREVIOUS query's 90% band
    # (None for the first query or a pure re-forecast).
    nowcast_sd: Optional[np.ndarray] = None    # (N,) one-sigma band
    forecast_sd: Optional[np.ndarray] = None   # (h, N) per-step bands
    coverage: Optional[float] = None


class NowcastSession:
    """Device-resident streaming nowcast session (see module docstring).

    Open via ``open_session(res, Y)`` or ``fit(..., keep_session=True)``;
    then each ``update(new_rows, mask=None)`` appends the rows and
    returns a ``SessionUpdate``.  The first update compiles the program
    (warmup); every later update reuses the same executable.
    """

    def __init__(self, res, Y, mask=None, *, capacity: Optional[int] = None,
                 max_update_rows: int = 8, max_iters: int = 5,
                 tol: float = 1e-6, horizon: Optional[int] = None,
                 di: Optional[bool] = None, ring: bool = False,
                 filter: Optional[str] = None, rank: Optional[int] = None,
                 backend=None, robust=None):
        from ..api import (CPUBackend, DynamicFactorModel, FitResult,
                           _resolve_policy, get_backend)
        if not isinstance(res, FitResult):
            raise TypeError(
                f"open_session needs a FitResult; got {type(res).__name__}")
        if not isinstance(res.model, DynamicFactorModel):
            raise TypeError(
                f"sessions support DynamicFactorModel fits only; got "
                f"{type(res.model).__name__}")
        b = get_backend(backend if backend is not None else "tpu")
        if isinstance(b, CPUBackend) or not hasattr(b, "_fused_panel"):
            raise ValueError(
                f"backend {b.name!r} has no fused device programs; "
                "sessions need a single-device JAX backend "
                "(backend=\"tpu\" or a TPUBackend instance)")
        Y = np.asarray(Y, dtype=np.float64)
        if Y.ndim != 2:
            raise ValueError(f"Y must be (T, N); got shape {Y.shape}")
        T0, N = Y.shape
        Lam = np.asarray(res.params.Lam)
        if Lam.shape[0] != N:
            raise ValueError(
                f"FitResult params are for N={Lam.shape[0]} series but the "
                f"panel has N={N}")
        self._opts = FusedOptions(
            horizon=1 if horizon is None else max(1, int(horizon)),
            di=True if di is None else bool(di))
        if T0 < self._opts.horizon + 3:
            raise ValueError(
                f"session needs T >= horizon + 3 = {self._opts.horizon + 3} "
                f"live rows to anchor the forecast regressions; got T={T0}")
        capacity = 2 * T0 if capacity is None else int(capacity)
        if capacity < T0:
            raise ValueError(f"capacity={capacity} < panel length T={T0}")
        if ring and max_update_rows > capacity:
            raise ValueError(
                f"ring mode needs max_update_rows <= capacity so an "
                f"update never evicts more rows than it appends; got "
                f"max_update_rows={max_update_rows} > capacity={capacity}")
        # Frozen standardizer: incoming rows are transformed with the
        # OPEN-time stats (re-standardizing per query would re-unit the
        # device-resident params).  NaNs stay NaN through the affine map.
        self._std = res.standardizer
        Yz = self._std.transform(Y) if self._std is not None else Y
        W = build_mask(Y, mask)
        Yz = np.where(W > 0, np.nan_to_num(Yz), 0.0)
        dt = b._dtype()
        # Host shadows (standardized units, capacity-padded, f64): the
        # snapshot source and the donated-retry rebuild state.  Pure
        # numpy — maintaining them costs no device traffic.
        self._Yhost = np.asarray(pad_panel_to_t(Yz, capacity), np.float64)
        self._Whost = np.asarray(pad_panel_to_t(W, capacity), np.float64)
        self._p_host = res.params.copy()   # f64 host copy (SSMParams)
        with b._precision_ctx():
            self._Ybuf = jnp.asarray(self._Yhost, dt)
            self._Wbuf = jnp.asarray(self._Whost, dt)
            self._p = JaxParams.from_numpy(res.params, dtype=dt)
        flt, rank_r = _resolve_serve_engine(b, res, filter, rank, N)
        self._cfg = EMConfig(estimate_A=res.model.estimate_A,
                             estimate_Q=res.model.estimate_Q,
                             estimate_init=res.model.estimate_init,
                             filter=flt, rank=rank_r, debug=False)
        self._backend = b
        self._model = res.model
        self._dt = dt
        self._acc = accum_dtype(dt)
        self._N = N
        self._t = T0
        self._t_total = T0
        self._capacity = capacity
        self._ring = bool(ring)
        self._r_max = max(1, int(max_update_rows))
        self._max_iters = max(1, int(max_iters))
        self._tol = float(tol)
        self._chunk = max(1, int(getattr(b, "fused_chunk", 8)))
        self._closed = False
        self._n_queries = 0
        self._last_band = None   # (y_fore, y_sd) of the previous query
        self._sid = f"s{next(_SESSION_IDS)}"
        self._key = shape_key(
            self._Ybuf, flt,
            *((f"rank{rank_r}",) if flt == "lowrank" else ()),
            f"rows{self._r_max}", f"chunk{self._chunk}",
            f"max{self._max_iters}")
        # Self-healing: inherit the backend's robust policy unless the
        # caller overrides (robust=False -> unguarded original path).
        self._policy = _resolve_policy(
            getattr(b, "robust", True) if robust is None else robust)
        self.health = FitHealth(engine="serve")
        self._div_run = 0      # consecutive diverged updates (escalation)

    # -- introspection -------------------------------------------------
    @property
    def t(self) -> int:
        """Live panel length (rows appended so far + the open panel)."""
        return self._t

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def ring(self) -> bool:
        """True if the session evicts its oldest rows past capacity
        (unbounded stream) instead of raising."""
        return self._ring

    @property
    def filter(self) -> str:
        """Resolved serving engine (inherited from the fit's
        ``FitResult.filter`` unless ``open_session(filter=)`` overrode)."""
        return self._cfg.filter

    @property
    def rank(self) -> int:
        """Lowrank conditioning rank (0 outside ``filter="lowrank"``)."""
        return self._cfg.rank

    @property
    def total_rows(self) -> int:
        """Rows the session has EVER held (open panel + every append),
        including rows since evicted — the stream position, as opposed
        to ``t`` (the live trailing-window length)."""
        return self._t_total

    @property
    def n_evicted(self) -> int:
        """Rows retired by the ring buffer so far (0 outside ring mode)."""
        return self._t_total - self._t

    @property
    def remaining(self) -> Optional[int]:
        """Rows that can still be appended before capacity overflow.

        ``None`` in ring mode: the stream is unbounded (appends past
        capacity evict the oldest rows instead of raising), so there is
        no finite remaining budget to report."""
        if self._ring:
            return None
        return self._capacity - self._t

    @property
    def session_id(self) -> str:
        return self._sid

    def params(self):
        """Current device-resident params as host numpy (one transfer)."""
        self._check_open()
        return self._p.to_numpy()

    def _check_open(self):
        if self._closed:
            raise RuntimeError("session is closed")

    # -- the query path ------------------------------------------------
    def update(self, new_rows=None, mask=None, trace=None) -> SessionUpdate:
        """Append ``new_rows`` ((n, N) or (N,), original units; NaN =
        missing, ``mask`` optional {0,1}) and re-estimate: m warm EM
        iterations + smooth + nowcast/forecast in ONE program dispatch.

        ``new_rows=None`` is a pure RE-FORECAST query: no append, same
        single dispatch (warm EM + smooth + nowcast/forecast on the
        resident panel), same executable — refresh the nowcast after a
        budget change or on a schedule without feeding data.

        ``trace`` is an optional request span context (``obs.trace``):
        an explicit dict (or one bound by an enclosing ``request_span``,
        or — when a tracer is active — a fresh birth) is stamped at
        every boundary and emitted as a ``request`` waterfall event.
        Untraced calls with no context take zero clock reads and stay
        byte-identical.

        All capacity/shape validation happens on host BEFORE any device
        work — an oversized update raises without touching the session.
        """
        self._check_open()
        if trace is None:
            trace = current_request()
            if trace is None and current_tracer() is not None:
                trace = {"id": new_trace_id(), "t_send": request_clock()}
        if trace is not None:
            trace.setdefault("t_admit", request_clock())
        if new_rows is None:
            if mask is not None:
                raise ValueError(
                    "mask requires new_rows (a pure re-forecast query "
                    "appends nothing)")
            rows = np.zeros((0, self._N))
        else:
            rows = np.asarray(new_rows, dtype=np.float64)
            if rows.ndim == 1:
                rows = rows[None, :]
            if rows.ndim != 2 or rows.shape[1] != self._N:
                raise ValueError(
                    f"new_rows must be (n, {self._N}) or ({self._N},); "
                    f"got shape {np.asarray(new_rows).shape}")
            if rows.shape[0] == 0:
                raise ValueError("new_rows is empty (pass None for a "
                                 "pure re-forecast query)")
        n_new = rows.shape[0]
        if n_new > self._r_max:
            raise ValueError(
                f"update has {n_new} rows but the session was opened with "
                f"max_update_rows={self._r_max}; open with a larger row "
                "budget (one executable serves every count up to it)")
        n_evict = 0
        if self._t + n_new > self._capacity:
            if not self._ring:
                raise ValueError(
                    f"capacity overflow: session holds {self._t} rows of "
                    f"{self._capacity} and cannot take {n_new} more; open "
                    "with ring=True to evict the oldest rows in place "
                    "(unbounded stream at constant memory), or open a "
                    "fresh session with a larger capacity")
            n_evict = self._t + n_new - self._capacity
        W_rows = build_mask(rows, mask)
        rz = self._std.transform(rows) if self._std is not None else rows
        rz = np.where(W_rows > 0, np.nan_to_num(rz), 0.0)
        pad = self._r_max - n_new
        if pad:   # exact-zero fill past n_new: lands on zero-masked slots
            rz = np.concatenate(
                [rz, np.zeros((pad, self._N), rz.dtype)], axis=0)
            W_rows = np.concatenate(
                [W_rows, np.zeros((pad, self._N), W_rows.dtype)], axis=0)
        t_mid = self._t - n_evict
        t_new = t_mid + n_new
        # Live coverage: the observed fraction of THIS update's new rows
        # inside the PREVIOUS query's 90% band (original units; host-only
        # arithmetic on values already in hand — zero extra dispatches).
        coverage = None
        innov_z = None
        if n_new and self._last_band is not None:
            pf, ps = self._last_band
            n_cmp = min(n_new, pf.shape[0])
            obs = W_rows[:n_cmp] > 0
            if obs.any():
                err = np.abs(rows[:n_cmp] - pf[:n_cmp])
                hit = err <= _Z90 * ps[:n_cmp]
                coverage = float(np.mean(hit[obs]))
                # Standardized innovation magnitude: |realized - forecast|
                # in units of the forecast sd — the drift detector's
                # primary signal (obs/drift.py); ~sqrt(2/pi) when healthy.
                z = err / np.maximum(ps[:n_cmp], 1e-12)
                innov_z = float(np.mean(z[obs]))
        # Per-update absolute loglik noise floor at the LIVE panel size —
        # the same floor a cold fit of the extended panel would use.
        floor = noise_floor_for(self._dt, t_new * self._N,
                                mult=self._cfg.noise_floor_mult)
        rows_j = jnp.asarray(rz, self._dt)
        rmask_j = jnp.asarray(W_rows, self._dt)
        consts = (jnp.asarray(n_new, jnp.int32),
                  jnp.asarray(n_evict, jnp.int32),
                  jnp.asarray(self._t, jnp.int32),
                  jnp.asarray(self._tol, self._acc),
                  jnp.asarray(floor, self._acc))
        kw = dict(cfg=self._cfg, max_iters=self._max_iters,
                  chunk=self._chunk, opts=self._opts)
        impl = (_session_impl if jax.default_backend() == "cpu"
                else _session_impl_donated)
        donated = impl is _session_impl_donated
        pol = self._policy
        tr = current_tracer()

        def _stamp(key):
            # Span stamps land on EVERY attempt (last one wins): a retried
            # dispatch's waterfall truthfully absorbs backoff into its
            # dispatch stage.
            if trace is not None:
                trace[key] = request_clock()

        _stamp("t_tick0")
        t0 = time.perf_counter()

        def _once(attempt):
            if attempt > 0 and donated:
                # The failed dispatch consumed the donated buffers;
                # rebuild device state from the host shadows (one
                # recovery h2d upload of the exact original values).
                self._redeploy()
            args = (self._Ybuf, self._Wbuf, rows_j, rmask_j, consts[0],
                    consts[1], consts[2], self._p, consts[3], consts[4])
            if tr is None:
                out = impl(*args, **kw)
                _stamp("t_launch")
                host = self._read(out, donated and pol is not None)
                _stamp("t_read")
                return out, host
            if attempt == 0:
                tr.maybe_cost("serve_update", self._key, impl, *args, **kw)
            extra = {"attempt": attempt} if pol is not None else {}
            with tr.dispatch("serve_update", self._key, barrier=True,
                             fused=True, n_iters=self._max_iters,
                             **extra) as rec:
                out = impl(*args, **kw)
                _stamp("t_launch")
                host = self._read(out, donated and pol is not None)
                _stamp("t_read")
                if rec is not None:
                    rec["n_iters"] = host["n_iters"]
            return out, host

        with self._backend._precision_ctx():
            if pol is None:
                out, host = _once(0)
            else:
                out, host = guarded_dispatch(
                    _once, pol, self.health, label="session update",
                    session=self._sid, iteration=self._t,
                    trace_id=(trace.get("id", "") if trace is not None
                              else ""),
                    last_good=lambda: self._p_host)
        wall = time.perf_counter() - t0
        # Rebind device state from the program's outputs (the donated
        # inputs are gone on real devices); the host shadows track the
        # same append in numpy.
        self._Ybuf, self._Wbuf = out["Ybuf"], out["Wbuf"]
        if n_evict:
            # Mirror the in-graph ring eviction in numpy: shift the
            # survivors to the origin; the wrapped tail [cap-e, cap) is
            # inside the append range below (e <= n_new), so the row
            # write restores exact host/device agreement.
            self._Yhost[:-n_evict] = self._Yhost[n_evict:].copy()
            self._Whost[:-n_evict] = self._Whost[n_evict:].copy()
        self._Yhost[t_mid:t_new] = rz[:n_new]
        self._Whost[t_mid:t_new] = W_rows[:n_new]
        self._t = t_new
        self._t_total += n_new
        self._n_queries += 1
        if "p_np" in host:     # guarded donated path: last-good shadow
            self._p_host = host["p_np"]
        diverged = host["status"] == _DIVERGED
        repaired = False
        if diverged:
            # Fused replay rule: keep the on-device last-good checkpoint
            # as the resident params — no host round-trip, no re-upload.
            self._p = out["p_good"]
            self._div_run += 1
            warnings.warn(
                f"session update diverged after {host['good_it']} good "
                "iterations; keeping the last-good params (this update's "
                "nowcast/forecasts reflect the pre-divergence state only "
                "loosely — consider a cold refit)", RuntimeWarning,
                stacklevel=2)
            if pol is not None:
                self.health.record(HealthEvent(
                    chunk=-1, iteration=self._t, kind="divergence",
                    action="restored", session=self._sid,
                    detail=(f"update diverged after {host['good_it']} "
                            f"good iterations; kept last-good params")))
                if self._div_run > pol.chunk_retries:
                    # Escalate repeated divergence through the repair
                    # ladder: project the resident params back into the
                    # feasible set and re-upload.
                    self._repair_resident()
                    repaired = True
        else:
            self._p = out["p"]
            self._div_run = 0
        degraded = bool(diverged or repaired)
        # Loglik-per-row trend signal for the drift detector: the final
        # in-loop loglik normalized by the live panel length (host values
        # already in hand — zero extra dispatches).
        n_ll = min(int(host["n_iters"]), self._max_iters)
        ll_per_row = None
        if n_ll > 0 and t_new > 0:
            ll_last = float(host["lls"][n_ll - 1])
            if np.isfinite(ll_last):
                ll_per_row = ll_last / t_new
        qev = dict(session=self._sid, t_rows=int(t_new),
                   n_new=int(n_new), wall=wall,
                   n_iters=int(host["n_iters"]),
                   N=int(self._N), k=int(self._model.n_factors),
                   engine=self._cfg.filter,
                   converged=bool(host["status"] == _CONVERGED),
                   diverged=bool(diverged),
                   **({"coverage": coverage} if coverage is not None
                      else {}),
                   **({"innov_z": innov_z} if innov_z is not None
                      else {}),
                   **({"ll_per_row": ll_per_row} if ll_per_row is not None
                      else {}),
                   **({"n_evicted": int(n_evict)} if n_evict else {}),
                   **({"degraded": True} if degraded else {}),
                   **({"trace_id": trace.get("id", "")}
                      if trace is not None else {}),
                   **({"replay": True}
                      if trace is not None and trace.get("replay")
                      else {}))
        if tr is not None:
            tr.emit("query", **qev)
        else:
            # Untraced serving still feeds the always-on live plane from
            # the timestamps this method already took — same event dict,
            # zero extra dispatches/transfers/clock reads.
            live_observe({"t": t0 + wall, "kind": "query", **qev})
        if trace is not None and trace.get("owner") != "daemon":
            # Lone-session queries end their span here (daemon-owned
            # spans finish at the daemon's ack instead).
            trace["t_ack"] = request_clock()
            rev = finish_request(trace, session=self._sid)
            if tr is not None:
                tr.emit("request", t=trace["t_ack"], **rev)
            else:
                live_observe({"t": trace["t_ack"], "kind": "request",
                              **rev})
        inv = (self._std.inverse if self._std is not None
               else (lambda a: a))
        # Bands destandardize by the scale alone (the affine shift cancels
        # in a standard deviation).
        sd_inv = ((lambda s: s * self._std.scale)
                  if self._std is not None else (lambda s: s))
        y_fore = np.asarray(inv(host["y_fore"]))
        fore_sd = np.asarray(sd_inv(host["y_sd"]))
        self._last_band = (y_fore, fore_sd)
        di = host["di"]
        n = min(int(host["n_iters"]), self._max_iters)
        return SessionUpdate(
            nowcast=np.asarray(inv(host["nowcast"])),
            forecasts={"y": y_fore,
                       "f": host["f_fore"],
                       "di": np.asarray(inv(di)) if di is not None else None},
            logliks=host["lls"][:n],
            n_iters=n,
            converged=bool(host["status"] == _CONVERGED),
            diverged=bool(diverged),
            factors=host["x_sm"][:t_new],
            factor_cov=host["P_sm"][:t_new],
            t=t_new,
            wall_s=wall,
            nowcast_sd=np.asarray(sd_inv(host["nowcast_sd"])),
            forecast_sd=fore_sd,
            coverage=coverage)

    def _read(self, out, want_params: bool = False):
        """Materialize the small host-bound outputs (inside the dispatch
        span, so a traced query counts exactly one blocking transfer).

        ``want_params`` (guarded donated path only) also reads the
        resulting params — a few KB riding the same barrier — so the
        host last-good shadow stays current for donated-retry rebuilds."""
        host = {
            "status": int(out["status"]),
            "n_iters": int(out["n_iters"]),
            "good_it": int(out["good_it"]),
            "lls": np.asarray(out["lls"], np.float64),
            "nowcast": np.asarray(out["nowcast"], np.float64),
            "nowcast_sd": np.asarray(out["nowcast_sd"], np.float64),
            "f_fore": np.asarray(out["f_fore"], np.float64),
            "y_fore": np.asarray(out["y_fore"], np.float64),
            "y_sd": np.asarray(out["y_sd"], np.float64),
            "di": (np.asarray(out["di"], np.float64)
                   if out["di"] is not None else None),
            "x_sm": np.asarray(out["x_sm"], np.float64),
            "P_sm": np.asarray(out["P_sm"], np.float64),
        }
        if want_params:
            src = (out["p_good"] if host["status"] == _DIVERGED
                   else out["p"])
            host["p_np"] = src.to_numpy()
        return host

    # -- self-healing --------------------------------------------------
    def _redeploy(self):
        """Rebuild device state from the host shadows after a failed
        donated dispatch (the consumed buffers are undefined).  The
        shadows hold the exact f64 values originally uploaded, so the
        cast reproduces the device state bit-for-bit."""
        with self._backend._precision_ctx():
            self._Ybuf = jnp.asarray(self._Yhost, self._dt)
            self._Wbuf = jnp.asarray(self._Whost, self._dt)
            self._p = JaxParams.from_numpy(self._p_host, dtype=self._dt)

    def _repair_resident(self):
        """Repair ladder for repeated divergence: project the resident
        params back into the feasible set (PSD clip + R floor lift) on
        host and re-upload.  One small d2h + h2d, off the healthy path."""
        from ..robust.guard import repair_params
        p_np = self._p.to_numpy()
        p_rep = repair_params(p_np, self._policy.r_floor,
                              jitter=self._policy.psd_tol)
        with self._backend._precision_ctx():
            self._p = JaxParams.from_numpy(p_rep, dtype=self._dt)
        self._p_host = p_rep
        self.health.escalate("repair_params")
        self.health.record(HealthEvent(
            chunk=-1, iteration=self._t, kind="divergence",
            action="repaired", session=self._sid,
            detail=(f"{self._div_run} consecutive diverged updates; "
                    "repaired resident params and re-uploaded")))
        self._div_run = 0

    # -- maintenance ----------------------------------------------------
    def swap_params(self, params) -> None:
        """Hot-swap the resident model params (the maintenance seam).

        ``params`` is a ``cpu_ref.SSMParams`` in THIS session's
        standardized scale (e.g. a background refit warm-started from the
        current params — ``fleet.maintenance``).  One h2d upload through
        the same path ``_redeploy`` uses; the serving executable, panel,
        ring ledger and engine are untouched, so the next update is the
        same single dispatch with zero recompiles.  Swapping bit-equal
        params is a bit-identical no-op: casting the same f64 values
        reproduces the same device values.
        """
        self._check_open()
        Lam = np.asarray(params.Lam, np.float64)
        want = (self._N, self._model.n_factors)
        if tuple(Lam.shape) != want:
            raise ValueError(
                f"swap_params: Lam has shape {tuple(Lam.shape)}, session "
                f"serves (N, k)={want}")
        p_np = params.copy()
        with self._backend._precision_ctx():
            self._p = JaxParams.from_numpy(p_np, dtype=self._dt)
        self._p_host = p_np
        self._div_run = 0

    # -- accounting ----------------------------------------------------
    def accounting(self) -> dict:
        """This session's live-plane resource ledger: queries answered,
        attributed device-wall ms, EM iterations, estimated flops
        (``obs.cost.em_iter_work``), retries and degraded counts — always
        on, accumulated host-side with zero extra dispatches.  Keyed by
        tenant (a lone session accounts under its own session id)."""
        return _live_accounting(self._sid)

    # -- durability ----------------------------------------------------
    def snapshot(self, path: str) -> str:
        """Durable session snapshot: params + live standardized panel +
        session config in ONE atomic npz (``utils.checkpoint``, content-
        fingerprinted).  Restore with ``open_session(snapshot=path)`` —
        a restarted process rebuilds the warm device-resident session in
        one h2d upload, and its next ``update`` is one dispatch (in the
        same process it reuses the already-compiled executable).  Costs
        one explicit params d2h; the panel comes from the host shadows
        (no device read).  The file is also a valid EM warm-start
        checkpoint (``load_checkpoint`` ignores the session extras)."""
        self._check_open()
        from ..utils.checkpoint import panel_fingerprint, save_checkpoint
        p_np = self._p.to_numpy()
        Y_live = self._Yhost[:self._t]
        W_live = self._Whost[:self._t]
        m = self._model
        extra = {
            "session_format": 1,
            "Y_live": Y_live,
            "W_live": W_live,
            "std_mean": (self._std.mean if self._std is not None
                         else np.zeros(0)),
            "std_scale": (self._std.scale if self._std is not None
                          else np.zeros(0)),
            "capacity": self._capacity,
            "ring": self._ring,
            "filter": self._cfg.filter,
            "rank": self._cfg.rank,
            "t_total": self._t_total,
            "max_update_rows": self._r_max,
            "max_iters": self._max_iters,
            "tol": self._tol,
            "horizon": self._opts.horizon,
            "di": self._opts.di,
            "n_queries": self._n_queries,
            "model_n_factors": m.n_factors,
            "model_dynamics": m.dynamics,
            "model_standardize": m.standardize,
            "model_estimate_init": m.estimate_init,
        }
        # PR 18: the drift detector's state rides the snapshot (JSON
        # string; empty when the plane is disarmed or nothing scored yet)
        # so a restored session continues mid-baseline.
        import json as _json
        from ..obs.live import plane as _plane
        dstate = _plane().drift_state(self._sid)
        extra["drift_state"] = _json.dumps(dstate) if dstate else ""
        save_checkpoint(path, p_np, it=self._t, logliks=[],
                        fingerprint=panel_fingerprint(Y_live, W_live),
                        converged=False, extra=extra)
        return path

    @classmethod
    def restore(cls, path: str, *, backend=None, robust=None,
                capacity: Optional[int] = None,
                ring: Optional[bool] = None,
                filter: Optional[str] = None,
                rank: Optional[int] = None) -> "NowcastSession":
        """Rebuild a warm session from ``snapshot(path)``.

        The stored panel is verified against its content fingerprint
        (a corrupt or hand-edited snapshot fails loudly), then the
        standardized live panel + params are re-uploaded exactly as the
        original session held them — the restored session's updates are
        numerically identical to the uninterrupted session's (pinned by
        tests/test_chaos.py).

        ``capacity``/``ring`` override the stored values (default: keep
        them).  Restoring into a LARGER capacity just re-pads — the live
        window is untouched.  Restoring into a capacity SMALLER than the
        stored live length keeps the TRAILING ``capacity`` rows (the
        ring-eviction semantics applied at restore time; the dropped
        rows count as evicted) and requires ring mode — a pinned-
        capacity session never drops data silently, so it raises
        instead.  Pinned by tests/test_stream.py."""
        from ..api import (CPUBackend, DynamicFactorModel, _resolve_policy,
                           get_backend)
        from ..backends.cpu_ref import SSMParams
        from ..utils.checkpoint import (_FIELDS, check_schema_version,
                                        panel_fingerprint)
        meta_keys = ("capacity", "max_update_rows", "max_iters", "tol",
                     "horizon", "di", "n_queries", "model_n_factors",
                     "model_dynamics", "model_standardize",
                     "model_estimate_init")
        with np.load(path) as z:
            check_schema_version(z, path)
            if "session_format" not in z.files:
                raise ValueError(
                    f"{path!r} is not a session snapshot (no "
                    "session_format field) — a plain EM checkpoint "
                    "cannot rebuild a session; open one with "
                    "open_session(res, Y)")
            params = SSMParams(*(np.asarray(z[f], np.float64)
                                 for f in _FIELDS))
            Y_live = np.asarray(z["Y_live"], np.float64)
            W_live = np.asarray(z["W_live"], np.float64)
            fp = str(z["fingerprint"]) if "fingerprint" in z.files else ""
            mean = np.asarray(z["std_mean"], np.float64)
            scale = np.asarray(z["std_scale"], np.float64)
            meta = {k: z[k][()] for k in meta_keys}
            # PR 14 fields; default for snapshots written before ring mode.
            meta["ring"] = (z["ring"][()] if "ring" in z.files else False)
            meta["t_total"] = (z["t_total"][()] if "t_total" in z.files
                               else Y_live.shape[0])
            # PR 17 fields: the engine + rank round-trip through the
            # snapshot; pre-engine snapshots fall back to the backend's
            # masked auto pick (the pre-PR behavior).
            meta["filter"] = (str(z["filter"][()]) if "filter" in z.files
                              else "")
            meta["rank"] = (int(z["rank"][()]) if "rank" in z.files else 0)
            # PR 18 field: drift-detector state (absent/empty on older
            # snapshots — the restored session starts a fresh baseline).
            meta["drift_state"] = (str(z["drift_state"][()])
                                   if "drift_state" in z.files else "")
        if fp and panel_fingerprint(Y_live, W_live) != fp:
            raise ValueError(
                f"session snapshot {path!r} is corrupt: the stored live "
                "panel does not match its content fingerprint")
        b = get_backend(backend if backend is not None else "tpu")
        if isinstance(b, CPUBackend) or not hasattr(b, "_fused_panel"):
            raise ValueError(
                f"backend {b.name!r} has no fused device programs; "
                "sessions need a single-device JAX backend "
                "(backend=\"tpu\" or a TPUBackend instance)")
        self = cls.__new__(cls)
        model = DynamicFactorModel(
            n_factors=int(meta["model_n_factors"]),
            dynamics=str(meta["model_dynamics"]),
            standardize=bool(meta["model_standardize"]),
            estimate_init=bool(meta["model_estimate_init"]))
        T_live, N = Y_live.shape
        self._opts = FusedOptions(horizon=int(meta["horizon"]),
                                  di=bool(meta["di"]))
        ring_mode = bool(meta["ring"]) if ring is None else bool(ring)
        capacity = (int(meta["capacity"]) if capacity is None
                    else int(capacity))
        if capacity < self._opts.horizon + 3:
            raise ValueError(
                f"capacity={capacity} < horizon + 3 = "
                f"{self._opts.horizon + 3}: the restored session could "
                "not anchor its forecast regressions")
        if ring_mode and int(meta["max_update_rows"]) > capacity:
            raise ValueError(
                f"ring mode needs max_update_rows <= capacity; the "
                f"snapshot was taken with max_update_rows="
                f"{int(meta['max_update_rows'])} > capacity={capacity}")
        if T_live > capacity:
            # Trailing-window restore: a smaller capacity keeps the most
            # recent ``capacity`` rows — the ring-eviction rule applied
            # at restore time.  Only ring mode may drop data.
            if not ring_mode:
                raise ValueError(
                    f"capacity={capacity} is smaller than the stored "
                    f"live panel (T={T_live}): restoring would drop the "
                    "oldest rows, which only ring mode allows — pass "
                    "ring=True (trailing-window semantics) or a "
                    "capacity >= the stored length")
            Y_live = Y_live[T_live - capacity:]
            W_live = W_live[T_live - capacity:]
            T_live = capacity
        from ..utils.data import Standardizer
        self._std = (Standardizer(mean=mean, scale=scale) if mean.size
                     else None)
        dt = b._dtype()
        self._Yhost = np.asarray(pad_panel_to_t(Y_live, capacity),
                                 np.float64)
        self._Whost = np.asarray(pad_panel_to_t(W_live, capacity),
                                 np.float64)
        self._p_host = params
        with b._precision_ctx():
            # The one restore upload: panel shadows + params to device.
            self._Ybuf = jnp.asarray(self._Yhost, dt)
            self._Wbuf = jnp.asarray(self._Whost, dt)
            self._p = JaxParams.from_numpy(params, dtype=dt)
        # Engine round-trip: an explicit ``filter=``/``rank=`` override
        # wins; otherwise the snapshot's stored engine is restored
        # exactly (pre-engine snapshots fall back to the masked pick).
        stored = type("_S", (), {"filter": meta["filter"]})()
        flt, rank_r = _resolve_serve_engine(
            b, stored, filter, meta["rank"] if rank is None else rank, N)
        self._cfg = EMConfig(estimate_A=model.estimate_A,
                             estimate_Q=model.estimate_Q,
                             estimate_init=model.estimate_init,
                             filter=flt, rank=rank_r, debug=False)
        self._backend = b
        self._model = model
        self._dt = dt
        self._acc = accum_dtype(dt)
        self._N = N
        self._t = T_live
        self._t_total = int(meta["t_total"])
        self._capacity = capacity
        self._ring = ring_mode
        self._r_max = int(meta["max_update_rows"])
        self._max_iters = int(meta["max_iters"])
        self._tol = float(meta["tol"])
        self._chunk = max(1, int(getattr(b, "fused_chunk", 8)))
        self._closed = False
        self._n_queries = int(meta["n_queries"])
        self._last_band = None
        self._sid = f"s{next(_SESSION_IDS)}"
        self._key = shape_key(
            self._Ybuf, flt,
            *((f"rank{rank_r}",) if flt == "lowrank" else ()),
            f"rows{self._r_max}", f"chunk{self._chunk}",
            f"max{self._max_iters}")
        self._policy = _resolve_policy(
            getattr(b, "robust", True) if robust is None else robust)
        self.health = FitHealth(engine="serve")
        self._div_run = 0
        if meta["drift_state"]:
            # Re-seed the live plane's detector under the NEW session id
            # (a no-op when the plane is disarmed — the off path stays
            # bit-identical).
            import json as _json
            from ..obs.live import plane as _plane
            _plane().restore_drift(self._sid, _json.loads(
                meta["drift_state"]))
        return self

    def close(self):
        """Release the device buffers; further updates raise."""
        self._Ybuf = self._Wbuf = self._p = None
        self._Yhost = self._Whost = self._p_host = None
        self._closed = True

    def __repr__(self):
        state = "closed" if self._closed else (
            f"t={self._t}/{self._capacity}"
            + (f", ring (evicted {self.n_evicted})" if self._ring else "")
            + f", {self._n_queries} queries")
        return (f"NowcastSession({self._sid}, N={self._N}, "
                f"filter={self._cfg.filter}, {state})")


def open_session(res=None, Y=None, mask=None, *, snapshot=None,
                 **kwargs) -> NowcastSession:
    """Open a streaming ``NowcastSession`` from a fitted model.

    res  : the ``FitResult`` of a ``DynamicFactorModel`` fit of ``Y``.
    Y    : (T, N) panel the model was fitted on (original units; NaNs =
           missing), ``mask`` as in ``fit``.
    capacity        : padded time budget (default 2*T) — updates can
                      append ``capacity - T`` rows before overflow
                      (ring mode: before eviction starts).
    max_update_rows : largest per-update row count (default 8); ONE
                      executable serves every count up to it.
    max_iters / tol : warm EM budget per query (default 5 / 1e-6).
    horizon / di    : forecast steps and diffusion-index toggle.
    ring            : True turns the panel into a ring buffer — updates
                      past capacity evict the oldest rows in graph
                      (same executable, constant memory, unbounded
                      stream) instead of raising; the session always
                      holds the trailing ``capacity``-row window.
    filter / rank   : serving engine ("dense", "info", "pit", "pit_qr",
                      "lowrank") and lowrank conditioning rank; default
                      inherits the fit's resolved ``FitResult.filter``
                      (rank from the backend), so a pit_qr or lowrank
                      fit serves through the same engine it fitted with.
    backend         : "tpu" (default) or a TPUBackend instance.
    robust          : ``RobustPolicy`` / True / False — the self-healing
                      query guard; default inherits the backend's policy.
    snapshot        : path written by ``session.snapshot(path)`` —
                      restores the saved session instead (pass no
                      res/Y/mask; ``backend``/``robust``/``capacity``/
                      ``ring`` still apply; a smaller capacity keeps the
                      trailing window, ring mode only).
    """
    if snapshot is not None:
        if res is not None or Y is not None or mask is not None:
            raise ValueError(
                "open_session(snapshot=...) restores a saved session: "
                "res/Y/mask come from the snapshot and cannot be passed")
        return NowcastSession.restore(snapshot, **kwargs)
    if res is None or Y is None:
        raise TypeError("open_session needs (res, Y) — or snapshot= to "
                        "restore a saved session")
    return NowcastSession(res, Y, mask=mask, **kwargs)

"""Batched ``serve_update`` core: ONE program answers B tenants' queries.

The lone session (``serve/session.py``) fuses append + warm EM + smooth +
nowcast/forecasts into one dispatch per QUERY; at fleet scale the query
stream is concurrent and the ~60-100 ms tunnel dispatch dominates, so this
module batches the same program over a leading tenant axis: one dispatch
per bucket TICK answers every queued query in the bucket.

Numerics are the point, not an afterthought: every stage is the
``estim.batched`` masked serving twin of exactly the op the lone session
runs — ``batched_ragged_append`` mirrors the per-tenant scatter,
``batched_filter_masked`` mirrors ``info_filter(Y, p, mask=W)``,
``batched_m_step_masked`` mirrors the t-masked ``em._m_step``, and the
final smooth/nowcast/forecast stage mirrors ``_session_core`` line for
line — so lane b of a fleet tick pins to the same tenant's lone
``NowcastSession.update`` at the same budget (tests/test_fleet.py, x64 +
f32 variants).

Engine routing (``_batched_e_step``): a bucket runs any serving engine —
``info`` keeps the hand-batched info-form twins byte-for-byte, while
``pit_qr`` and ``lowrank(rank=r)`` vmap the lone masked filter/smoother
pair over the lane axis (lanes are independent, so the vmap is exact and
shards under ``fleet_impl_sharded`` without collectives).  One fused
``serve_update`` executable per (bucket-shape, engine); parity references
are lone same-engine sessions/fits.

Per-tenant independence inside the one program:

- ``tick_act`` (B,) bool: tenants with no query this tick are FROZEN via
  the same ``jnp.where`` selects the batched EM engine uses — their
  params, buffers and state are bit-identical before and after the tick
  (no contraction ever crosses the batch axis, so a bucket-mate's NaN
  stays in its own lane).
- ``iter_cap`` / ``tol`` / ``floor`` (B,): per-tenant budgets and the
  per-tenant ABSOLUTE loglik noise floor at each tenant's true live size
  (the host computes it exactly as the lone session does).
- Stopping reproduces ``estim.fused._em_while_core`` per iteration:
  relative-tol convergence, plateau, divergence on a drop past the noise
  floor (non-finite logliks included), divergence rolling params back to
  the entry of the offending update (``p_prev`` in the carry).  At
  ``tol=0.0`` a healthy lane runs exactly its cap — the same trajectory
  as the lone session, which is what the parity tests pin.

The EM scan is STATIC-length (no early exit): serve budgets are a few
iterations, and a static scan is what keeps ONE executable per bucket
shape serving every (active-set, row-count, live-length) combination.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as _PSpec

from ..estim.batched import (CONVERGED, DIVERGED, RUNNING, _batched_rts,
                             _bmask, _bT, batched_filter_masked,
                             batched_m_step_masked, batched_ragged_append)
from ..estim.fused import _di_forecast_core_masked
from ..ops.linalg import matmul_vpu, matvec_vpu
from ..ops.precision import accum_dtype

__all__ = ["FleetOptions", "_fleet_core", "_fleet_impl",
           "_fleet_impl_donated", "batched_ring_evict", "fleet_impl_sharded",
           "ring_evict"]


def ring_evict(Ybuf, Wbuf, n_evict, t_cur):
    """Retire the oldest ``n_evict`` rows of a capacity-padded panel IN
    GRAPH: roll the live window back to the buffer origin and re-zero
    everything past the surviving prefix.

    ``n_evict``/``t_cur`` are traced int32 scalars, so ONE executable
    serves every eviction count — the ring-buffer seam that lets a
    session outlive its capacity at constant memory.  The roll wraps the
    evicted rows to the tail of the buffer; the ``where`` mask lands
    exact zeros there (and on the whole former pad region), restoring
    the invariant the masked filter/M-step rely on: rows past the live
    prefix are exactly zero with zero mask.  With ``n_evict == 0`` the
    select reproduces the input bit-for-bit (live rows selected
    unchanged, pad rows already exactly zero), so non-ring sessions pay
    nothing numerically for sharing the executable.
    """
    t_keep = t_cur - n_evict
    keep = (jnp.arange(Ybuf.shape[0]) < t_keep)[:, None]
    Yr = jnp.where(keep, jnp.roll(Ybuf, -n_evict, axis=0),
                   jnp.zeros((), Ybuf.dtype))
    Wr = jnp.where(keep, jnp.roll(Wbuf, -n_evict, axis=0),
                   jnp.zeros((), Wbuf.dtype))
    return Yr, Wr


def batched_ring_evict(Ybuf, Wbuf, n_evict, t_cur):
    """Per-lane ``ring_evict``: (B, T_cap, N) buffers, (B,) int32 counts.
    Lanes are independent (a pure vmap), so frozen and mesh-filler lanes
    pass ``n_evict=0`` and hold bit-exactly."""
    return jax.vmap(ring_evict)(Ybuf, Wbuf, n_evict, t_cur)


def _batched_e_step(Ybuf, Wbuf, p, cfg):
    """Batched masked E-step routed by ``cfg.filter``.

    ``info`` keeps the hand-batched info-form twins BYTE-IDENTICAL to the
    pre-routing fleet (``batched_filter_masked`` + ``_batched_rts``);
    every other engine vmaps the lone masked pair (``cfg.filter_fn`` /
    ``cfg.smoother_fn``) over the lane axis — exactly the program lane
    b's lone session would run, so per-tenant parity is by construction.
    Lanes never interact, so the vmap shards under ``shard_map`` with no
    collectives.  Returns (loglik (B,), x_sm, P_sm, P_lag).
    """
    if cfg.filter == "info":
        ll, (xp, Pp, xf, Pf) = batched_filter_masked(Ybuf, Wbuf, p)
        x_sm, P_sm, P_lag = _batched_rts(xp, Pp, xf, Pf, p.A)
        return ll, x_sm, P_sm, P_lag
    ff, sf = cfg.filter_fn(), cfg.smoother_fn()

    def one(Y, W, p1):
        kf = ff(Y, p1, mask=W)
        sm = sf(kf, p1)
        return kf.loglik, sm.x_sm, sm.P_sm, sm.P_lag

    return jax.vmap(one)(Ybuf, Wbuf, p)


@dataclasses.dataclass(frozen=True)
class FleetOptions:
    """Static per-bucket program options (hashable jit static).

    ``fault_tenant``/``fault_iter``/``fault_drop`` are the deterministic
    chaos seam (the fleet twin of ``FusedOptions.fault_chunk``): subtract
    ``fault_drop`` from lane ``fault_tenant``'s loglik at EM iteration
    ``fault_iter``, forcing that lane — and ONLY that lane — through the
    divergence path while its bucket-mates sail through bit-identically.
    Single-device twins only (a sharded lane index would be shard-local).
    """

    horizon: int = 1
    di: bool = True
    fault_tenant: Optional[int] = None
    fault_iter: int = 1
    fault_drop: float = 1e6


def _fleet_em_scan(Ybuf, Wbuf, p0, tol, floor, iter_cap, tick_act, t_new,
                   cfg, max_iters, opts):
    """Per-lane warm EM: a static ``max_iters`` scan with per-tenant
    in-carry freezes.  Returns (p, state (B,), n_iters (B,), good_it (B,),
    lls (B, max_iters) — NaN past each lane's own trace length)."""
    acc = accum_dtype(Ybuf.dtype)
    i32 = jnp.int32
    B = Ybuf.shape[0]
    tmap = jax.tree_util.tree_map

    def body(c, j):
        p, p_prev, ll_prev, state, n_lls, good_it = c
        ll, x_sm, P_sm, P_lag = _batched_e_step(Ybuf, Wbuf, p, cfg)
        ll = ll.astype(acc)
        if opts.fault_tenant is not None:   # static chaos seam
            ll = ll.at[opts.fault_tenant].add(jnp.where(
                j == opts.fault_iter,
                -jnp.asarray(opts.fault_drop, acc), jnp.zeros((), acc)))
        p_new = batched_m_step_masked(Ybuf, Wbuf, x_sm, P_sm, P_lag, p,
                                      cfg, t_new)
        live = (state == RUNNING) & (n_lls < iter_cap) & tick_act
        n_out = n_lls + live.astype(i32)
        # Per-iteration mirror of _em_while_core's decision block.  On
        # each lane's FIRST iteration ll_prev is NaN: every comparison is
        # False, so only the non-finite rule can fire — exactly the lone
        # driver's has_prev gating.
        rel = (ll - ll_prev) / jnp.maximum(jnp.abs(ll_prev), 1e-12)
        drop = ll_prev - ll
        small = (tol > 0) & (jnp.abs(rel) < tol)
        diver = ~small & (drop > floor)
        plateau = ~small & ~diver & (drop > 0) & (tol > 0)
        prog = jnp.where(small | plateau, CONVERGED,
                         jnp.where(diver, DIVERGED, RUNNING)).astype(i32)
        prog = jnp.where(jnp.isfinite(ll), prog,
                         jnp.asarray(DIVERGED, i32))
        new_state = jnp.where(live, prog, state).astype(i32)
        advance = live & (prog != DIVERGED)
        roll = live & (prog == DIVERGED)
        # 3-way per-lane select: advancing lanes take the M-step update,
        # a diverging lane rolls back to the params that ENTERED the
        # offending update (ll_j is evaluated at p_j, so a drop at j
        # blames the p_{j-1} -> p_j update; last-good = p_prev), frozen
        # lanes hold bit-exactly.
        p_out = tmap(
            lambda n, pv, cur: jnp.where(
                _bmask(advance, n), n, jnp.where(_bmask(roll, pv), pv, cur)),
            p_new, p_prev, p)
        p_prev_out = tmap(
            lambda cur, pv: jnp.where(_bmask(live, cur), cur, pv), p, p_prev)
        ll_prev_out = jnp.where(live, ll, ll_prev)
        good_out = jnp.where(roll, jnp.maximum(n_out - 2, 0).astype(i32),
                             good_it)
        rec = jnp.where(live, ll, jnp.asarray(jnp.nan, acc))
        return ((p_out, p_prev_out, ll_prev_out, new_state, n_out,
                 good_out), rec)

    c0 = (p0, p0, jnp.full((B,), jnp.nan, acc),
          jnp.zeros((B,), i32), jnp.zeros((B,), i32), jnp.zeros((B,), i32))
    (p, _, _, state, n_lls, good_it), lls = lax.scan(
        body, c0, jnp.arange(max_iters))
    return p, state, n_lls, good_it, jnp.moveaxis(lls, 0, 1)


def _fleet_core(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur, p0, tol,
                floor, iter_cap, tick_act, cfg, max_iters, opts):
    """One fleet tick: ring eviction, ragged append, per-lane warm EM,
    smooth, nowcast + forecasts for every lane — the (B,)-batched
    ``_session_core``.

    Ybuf/Wbuf (B, T_cap, N); rows/rmask (B, r_max, N) with exact-zero
    fill past each tenant's true count; n_new/n_evict/t_cur/iter_cap (B,)
    int32; tol/floor (B,) accum dtype; tick_act (B,) bool.  ``n_evict``
    retires each lane's oldest rows in graph (ring fleets; all-zero for
    pinned-capacity fleets, where the select is bit-inert).
    """
    Ybuf, Wbuf = batched_ring_evict(Ybuf, Wbuf, n_evict, t_cur)
    t_cur = t_cur - n_evict
    Ybuf, Wbuf = batched_ragged_append(Ybuf, Wbuf, rows, rmask, t_cur)
    t_new = t_cur + n_new
    p_fit, state, n_iters, good_it, lls = _fleet_em_scan(
        Ybuf, Wbuf, p0, tol, floor, iter_cap, tick_act, t_new, cfg,
        max_iters, opts)
    # Smooth + forecast at the fitted params, same program — the same
    # engine-routed masked pair the lone session core runs (for pit_qr/
    # lowrank this IS ``EMConfig.report_pair``; info keeps the batched
    # info-form twins bit-for-bit).
    _, x_sm, P_sm, _ = _batched_e_step(Ybuf, Wbuf, p_fit, cfg)
    take = lambda a, t: jnp.take(a, t, axis=0, mode="clip")  # noqa: E731
    x_T = jax.vmap(take)(x_sm, t_new - 1)
    P_T = jax.vmap(take)(P_sm, t_new - 1)
    nowcast = jnp.einsum("bnk,bk->bn", p_fit.Lam, x_T)
    # Per-lane observation-space one-sigma bands — the batched twin of
    # the lone session's ``obs_sd`` (conservative under lowrank r < k).
    obs_sd = lambda P: jnp.sqrt(jnp.maximum(  # noqa: E731
        jnp.einsum("bnk,bkl,bnl->bn", p_fit.Lam, P, p_fit.Lam) + p_fit.R,
        jnp.zeros((), Ybuf.dtype)))
    nowcast_sd = obs_sd(P_T)

    def fstep(carry, _):
        x, Pc = carry
        x1 = matvec_vpu(p_fit.A, x)
        P1 = matmul_vpu(matmul_vpu(p_fit.A, Pc), _bT(p_fit.A)) + p_fit.Q
        return (x1, P1), (x1, jnp.einsum("bnk,bk->bn", p_fit.Lam, x1),
                          obs_sd(P1))

    _, (f_fore, y_fore, y_sd) = lax.scan(fstep, (x_T, P_T), None,
                                         length=opts.horizon)
    di = None
    if opts.di:
        di = jax.vmap(
            lambda F, Yb, tn: _di_forecast_core_masked(F, Yb, tn,
                                                       opts.horizon)
        )(x_sm, Ybuf, t_new)
    return {
        "Ybuf": Ybuf,
        "Wbuf": Wbuf,
        "p": p_fit,
        "good_it": good_it,
        "lls": lls,
        "n_iters": n_iters,
        "status": state,
        "x_sm": x_sm,
        "P_sm": P_sm,
        "nowcast": nowcast,
        "nowcast_sd": nowcast_sd,
        "f_fore": jnp.moveaxis(f_fore, 0, 1),    # (B, h, k)
        "y_fore": jnp.moveaxis(y_fore, 0, 1),    # (B, h, N)
        "y_sd": jnp.moveaxis(y_sd, 0, 1),        # (B, h, N)
        "di": di,
    }


_FLEET_STATICS = ("cfg", "max_iters", "opts")


@partial(jax.jit, static_argnames=_FLEET_STATICS)
def _fleet_impl(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur, p0, tol,
                floor, iter_cap, tick_act, *, cfg, max_iters, opts):
    return _fleet_core(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur, p0,
                       tol, floor, iter_cap, tick_act, cfg, max_iters, opts)


# Donated twin: panel buffers (0, 1) and params (7) consumed in place —
# the fleet rebinds the returned arrays, so device memory stays one
# bucket-buffer set deep.  CPU backends use the plain twin.
@partial(jax.jit, static_argnames=_FLEET_STATICS, donate_argnums=(0, 1, 7))
def _fleet_impl_donated(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur, p0,
                        tol, floor, iter_cap, tick_act, *, cfg, max_iters,
                        opts):
    return _fleet_core(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur, p0,
                       tol, floor, iter_cap, tick_act, cfg, max_iters, opts)


@partial(jax.jit, static_argnames=_FLEET_STATICS + ("mesh",))
def fleet_impl_sharded(Ybuf, Wbuf, rows, rmask, n_new, n_evict, t_cur, p0,
                       tol, floor, iter_cap, tick_act, *, cfg, max_iters,
                       opts, mesh):
    """shard_map'd tick: the bucket's batch axis split over the mesh.

    The lanes are INDEPENDENT (no op contracts across B; the ring
    eviction is a per-lane vmap), so every input and every output leaf
    shards with the same P("batch") pytree-prefix spec and the body needs
    no collectives — the ``parallel.batched`` recipe applied to the
    serving tick.  The caller pads B to a multiple of the mesh size with
    ``tick_act=False`` copies of lane 0 (frozen from the start,
    value-inert)."""
    from ..parallel.batched import BATCH_AXIS
    from ..parallel.mesh import shard_map
    Pb = _PSpec(BATCH_AXIS)
    body = lambda *a: _fleet_core(*a, cfg=cfg, max_iters=max_iters,  # noqa: E731
                                  opts=opts)
    return shard_map(body, mesh=mesh, in_specs=(Pb,) * 12,
                     out_specs=Pb)(Ybuf, Wbuf, rows, rmask, n_new, n_evict,
                                   t_cur, p0, tol, floor, iter_cap,
                                   tick_act)

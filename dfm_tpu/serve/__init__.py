"""Streaming nowcast service: device-resident incremental panel updates.

``open_session(res, Y)`` (or ``fit(..., keep_session=True)``) turns a
fitted model into a persistent ``NowcastSession`` whose params AND panel
stay device-resident in a capacity-padded buffer; every
``session.update(new_rows)`` uploads only the new rows and runs ONE fused
jitted program — in-graph panel append, m warm EM iterations, RTS smooth,
nowcast + forecasts — with zero recompiles across updates and at most one
blocking device->host read per query.
"""

from .session import NowcastSession, SessionUpdate, open_session

__all__ = ["NowcastSession", "SessionUpdate", "open_session"]

"""State-space parameter pytree shared by all JAX estimation code.

The JAX mirror of ``dfm_tpu.backends.cpu_ref.SSMParams`` (BASELINE.json:5's
AbstractStateSpaceModel parameter block): a NamedTuple so it is automatically a
pytree — jit/vmap/shard_map transparent, no registration needed.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class SSMParams(NamedTuple):
    """y_t = Lam f_t + eps, eps ~ N(0, diag(R)); f_t = A f_{t-1} + eta ~ N(0,Q).

    Lam: (N, k); A: (k, k); Q: (k, k); R: (N,) diagonal; mu0: (k,); P0: (k, k).
    """

    Lam: jax.Array
    A: jax.Array
    Q: jax.Array
    R: jax.Array
    mu0: jax.Array
    P0: jax.Array

    @property
    def n_series(self) -> int:
        return self.Lam.shape[0]

    @property
    def n_factors(self) -> int:
        return self.Lam.shape[1]

    def astype(self, dtype) -> "SSMParams":
        return SSMParams(*(jnp.asarray(x, dtype) for x in self))

    @classmethod
    def from_numpy(cls, p, dtype=None) -> "SSMParams":
        """From the CPU-reference dataclass (or anything with the same fields)."""
        arrs = (p.Lam, p.A, p.Q, p.R, p.mu0, p.P0)
        return cls(*(jnp.asarray(a, dtype) for a in arrs))

    def to_numpy(self):
        from ..backends.cpu_ref import SSMParams as NpParams
        return NpParams(*(np.asarray(x, dtype=np.float64) for x in self))


class FilterResult(NamedTuple):
    x_pred: jax.Array   # (T, k)
    P_pred: jax.Array   # (T, k, k)
    x_filt: jax.Array   # (T, k)
    P_filt: jax.Array   # (T, k, k)
    loglik: jax.Array   # scalar


class SmootherResult(NamedTuple):
    x_sm: jax.Array     # (T, k)
    P_sm: jax.Array     # (T, k, k)
    P_lag: jax.Array    # (T, k, k); row 0 is zeros

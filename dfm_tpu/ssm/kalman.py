"""Dense-covariance Kalman filter as a ``lax.scan`` over time.

The TPU-native realization of the recursions quoted in BASELINE.json:5
(predict P' = A P A' + Q; update K = P Lam' S^{-1}); the cross-sectional
scale-out (information form + sharding) lives in ``info_filter.py`` — this
dense form is the small-N path and the oracle for it.

Missing data with static shapes (critical under jit, SURVEY.md section 3.4):
for mask w_t in {0,1}^N the masked model is rewritten as
    Lam_t = diag(w_t) Lam,  y_t -> w_t * y_t,  R_t = w_t * R + (1 - w_t)
so masked rows have zero loading, zero innovation, unit variance — they
contribute 0 to the innovation quadratic and log|S|, reproducing the
variable-dimension filter exactly without dynamic shapes (tested against the
CPU reference which drops rows for real).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.linalg import sym, psd_cholesky, chol_solve, chol_logdet
from .params import SSMParams, FilterResult, SmootherResult

__all__ = ["kalman_filter", "rts_smoother", "filter_smoother"]

_LOG2PI = 1.8378770664093453  # log(2*pi)


def _masked_obs(y_t, mask_t, Lam, R):
    """Apply the static-shape masking rewrite; no-op when mask_t is None."""
    if mask_t is None:
        return y_t, Lam, R
    w = mask_t.astype(y_t.dtype)
    # nan_to_num: masked entries may legitimately be NaN (the CPU oracle
    # accepts that encoding); 0 * NaN would otherwise poison the update.
    return w * jnp.nan_to_num(y_t), w[:, None] * Lam, w * R + (1.0 - w)


def kalman_filter(Y: jax.Array, p: SSMParams,
                  mask: Optional[jax.Array] = None) -> FilterResult:
    """Forward filter with exact log-likelihood; O(T) scan of O(N^3) updates.

    Y: (T, N); mask: optional (T, N) {0,1}.  Joseph-form covariance update.
    """
    dtype = Y.dtype
    p = p.astype(dtype)
    N, k = p.Lam.shape
    I_k = jnp.eye(k, dtype=dtype)

    def step(carry, inp):
        x, P = carry                       # predicted state for this t
        y_t, mask_t = inp
        y_m, H, r = _masked_obs(y_t, mask_t, p.Lam, p.R)
        v = y_m - H @ x
        S = H @ P @ H.T + jnp.diag(r)
        L = psd_cholesky(S)
        Sinv_v = chol_solve(L, v)
        K = chol_solve(L, H @ P).T         # (k, N)
        x_f = x + K @ v
        IKH = I_k - K @ H
        P_f = sym(IKH @ P @ IKH.T + (K * r) @ K.T)
        # Masked rows contribute log(1)=0 and v=0 automatically; but the
        # constant n_t*log(2pi) must count only observed rows.
        n_t = jnp.sum(mask_t.astype(dtype)) if mask_t is not None \
            else jnp.asarray(float(N), dtype)
        ll_t = -0.5 * (n_t * _LOG2PI + chol_logdet(L) + v @ Sinv_v)
        x_n = p.A @ x_f
        P_n = sym(p.A @ P_f @ p.A.T + p.Q)
        return (x_n, P_n), (x, P, x_f, P_f, ll_t)

    if mask is not None:
        (xp, Pp, xf, Pf, lls) = lax.scan(
            step, (p.mu0, p.P0), (Y, mask))[1]
    else:
        (xp, Pp, xf, Pf, lls) = lax.scan(
            lambda c, y: step(c, (y, None)), (p.mu0, p.P0), Y)[1]
    return FilterResult(xp, Pp, xf, Pf, jnp.sum(lls))


def rts_smoother(kf: FilterResult, p: SSMParams) -> SmootherResult:
    """Backward RTS pass; lag-one covariances via P_lag[t] = P_sm[t] J_{t-1}'.

    Same identity as the CPU reference (verified there against a brute-force
    joint-Gaussian oracle).
    """
    dtype = kf.x_filt.dtype
    p = p.astype(dtype)
    T, k = kf.x_filt.shape

    # J_t = P_filt[t] A' P_pred[t+1]^{-1} for t = 0..T-2, batched up front.
    Pp_next = kf.P_pred[1:]                                  # (T-1, k, k)
    APf = jnp.einsum("ij,tjk->tik", p.A, kf.P_filt[:-1])     # A P_filt[t]
    L = psd_cholesky(Pp_next)
    J = jnp.swapaxes(jax.vmap(chol_solve)(L, APf), -1, -2)   # (T-1, k, k)

    def step(carry, inp):
        x_next, P_next = carry           # smoothed at t+1
        x_f, P_f, x_p_next, P_p_next, J_t = inp
        x_s = x_f + J_t @ (x_next - x_p_next)
        P_s = sym(P_f + J_t @ (P_next - P_p_next) @ J_t.T)
        return (x_s, P_s), (x_s, P_s)

    init = (kf.x_filt[-1], kf.P_filt[-1])
    inps = (kf.x_filt[:-1], kf.P_filt[:-1], kf.x_pred[1:], kf.P_pred[1:], J)
    (_, _), (x_sm_rev, P_sm_rev) = lax.scan(step, init, inps, reverse=True)
    x_sm = jnp.concatenate([x_sm_rev, kf.x_filt[-1:]], axis=0)
    P_sm = jnp.concatenate([P_sm_rev, kf.P_filt[-1:]], axis=0)
    P_lag_tail = jnp.einsum("tij,tkj->tik", P_sm[1:], J)     # P_sm[t] J_{t-1}'
    P_lag = jnp.concatenate([jnp.zeros((1, k, k), dtype), P_lag_tail], axis=0)
    return SmootherResult(x_sm, P_sm, P_lag)


def filter_smoother(Y, p, mask=None):
    kf = kalman_filter(Y, p, mask=mask)
    return kf, rts_smoother(kf, p)

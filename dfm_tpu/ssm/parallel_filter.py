"""Parallel-in-time Kalman filtering/smoothing via ``lax.associative_scan``.

The sequential T-step scan is the wall-clock floor of the whole framework
(SURVEY.md section 7.2 item 3): 500-1000 dependent k x k steps leave the TPU
idle.  Bayesian filtering is associative (Sarkka & Garcia-Fernandez,
"Temporal Parallelization of Bayesian Smoothers", IEEE TAC 2021 —
PAPERS.md:6): each step is an element of a semigroup whose product yields the
filtered posterior, so the T-fold product runs as a log2(T)-depth prefix scan
of BATCHED k x k algebra — exactly what the TPU wants.

Filtering element a_t = (A, b, C, eta, J); combination (i earlier, j later):

    D   = (I + C_i J_j)^{-1}
    A   = A_j D A_i
    b   = A_j D (b_i + C_i eta_j) + b_j
    C   = A_j D C_i A_j' + C_j
    E   = (I + J_j C_i)^{-1}
    eta = A_i' E (eta_j - J_j b_i) + eta_i
    J   = A_i' E J_j A_i + J_i

After the inclusive prefix product, (b_t, C_t) ARE the filtered moments.

The elements are initialized from the same information-form observation
statistics as the sequential path (ObsStats; per-t C_t, b_t) via push-through
identities so nothing N x N is ever formed:

    A_t = (I + Q C_t)^{-1} F            b_t = Q (I + C_t Q)^{-1} bobs_t
    C_t = (I + Q C_t)^{-1} Q            eta_t = F' (I + C_t Q)^{-1} bobs_t
    J_t = F' (I + C_t Q)^{-1} C_t F

(t=0 uses P0/mu0 with A_0 = 0.)  The log-likelihood is then assembled with
zero sequential steps: predicted moments are one batched matmul off the
filtered ones, and the Woodbury quadratic reuses the cancellation-free
residual pass of ``info_filter``.

The RTS smoother parallelizes the same way with affine elements
(E, g, L): E = E_i E_j, g = E_i g_j + g_i, L = E_i L_j E_i' + L_i under a
reverse prefix product.

Equivalence with the sequential scans is tested to fp tolerance; the EM
wrapper selects this path with ``EMConfig(filter="pit")``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.linalg import (sym, psd_cholesky, chol_solve, chol_logdet,
                          default_jitter, chol_unrolled, chol_solve_unrolled,
                          matmul_vpu, matvec_vpu, tria, tri_solve, psd_factor,
                          QR_UNROLL_K_MAX)
from ..ops.scan import blocked_scan
from .info_filter import (ObsStats, obs_stats, loglik_terms_local,
                          loglik_from_terms)
from .params import SSMParams, FilterResult, SmootherResult

__all__ = ["pit_filter", "pit_smoother", "pit_filter_smoother",
           "pit_from_stats", "pit_qr_filter", "pit_qr_smoother",
           "pit_qr_filter_smoother", "pit_qr_from_stats",
           "qr_filter_elements", "qr_combine_filter", "qr_combine_smoother",
           "qr_generic_elements", "qr_init_posterior"]

_LOG2PI = 1.8378770664093453


def _filter_elements(stats: ObsStats, A, Q, mu0, P0):
    """Batched element construction from info-form stats; all k x k."""
    dtype = stats.b.dtype
    T = stats.b.shape[0]
    k = A.shape[0]
    I_k = jnp.eye(k, dtype=dtype)
    C_t = stats.C
    if C_t.ndim == 2:
        C_t = jnp.broadcast_to(C_t, (T, k, k))
    bobs = stats.b

    # Generic elements (t >= 1): push-through forms with Q.
    M = I_k[None] + jnp.einsum("kl,tlm->tkm", Q, C_t)     # I + Q C_t
    Minv_F = jnp.linalg.solve(M, jnp.broadcast_to(A, (T, k, k)))
    Minv_Q = jnp.linalg.solve(M, jnp.broadcast_to(Q, (T, k, k)))
    # (I + C Q)^{-1} b  =  solve(I + C Q, b)
    N_ = I_k[None] + jnp.einsum("tkl,lm->tkm", C_t, Q)    # I + C_t Q
    Ninv_b = jnp.linalg.solve(N_, bobs[..., None])[..., 0]
    A_el = Minv_F                                          # (I+QC)^-1 F
    b_el = jnp.einsum("kl,tl->tk", Q, Ninv_b)              # Q (I+CQ)^-1 b
    C_el = sym(Minv_Q)                                     # (I+QC)^-1 Q
    eta_el = jnp.einsum("lk,tl->tk", A, Ninv_b)            # F'(I+CQ)^-1 b
    NinvC = jnp.linalg.solve(N_, C_t)
    J_el = sym(jnp.einsum("lk,tlm,mn->tkn", A,
                          NinvC, A))                       # F'(I+CQ)^-1 C F

    # t = 0 element: posterior from the prior (mu0, P0); A_0 = 0.
    M0 = I_k + P0 @ C_t[0]
    b0 = mu0 + P0 @ jnp.linalg.solve(
        I_k + C_t[0] @ P0, bobs[0] - C_t[0] @ mu0)
    C0 = sym(jnp.linalg.solve(M0, P0))
    A_el = A_el.at[0].set(jnp.zeros((k, k), dtype))
    b_el = b_el.at[0].set(b0)
    C_el = C_el.at[0].set(C0)
    eta_el = eta_el.at[0].set(jnp.zeros((k,), dtype))
    J_el = J_el.at[0].set(jnp.zeros((k, k), dtype))
    return (A_el, b_el, C_el, eta_el, J_el)


def _combine_filter(ei, ej):
    """Associative filtering-element product (ei earlier, ej later).

    f32 discipline (same ``sym``/jitter rules as ``ops.linalg``): the C/J
    blocks are re-symmetrized on ENTRY — after ~sqrt(T) rounds of general
    (non-Cholesky) solves the asymmetry drift compounds multiplicatively,
    which is most of the legacy path's 4x-over-sequential f32 noise at S3
    (docs/PERF.md) — and the D/E systems get the precision-matched
    diagonal jitter before the solve (inert at 1e-10 in f64; in f32 it
    conditions the near-singular products of long chains).  Pinned by a
    tolerance test against the f64 sequential scan.
    """
    Ai, bi, Ci, etai, Ji = ei
    Aj, bj, Cj, etaj, Jj = ej
    k = Ai.shape[-1]
    Ci, Jj = sym(Ci), sym(Jj)
    jit_eye = (1.0 + default_jitter(Ai.dtype)) * jnp.eye(k, dtype=Ai.dtype)
    D = jit_eye + Ci @ Jj if Ai.ndim == 2 else \
        jit_eye[None] + jnp.einsum("...kl,...lm->...km", Ci, Jj)
    # batched general solves (D is not symmetric).
    AjD = jnp.linalg.solve(jnp.swapaxes(D, -1, -2),
                           jnp.swapaxes(Aj, -1, -2))
    AjD = jnp.swapaxes(AjD, -1, -2)                       # A_j D^{-1}
    A = AjD @ Ai
    b = jnp.einsum("...kl,...l->...k", AjD,
                   bi + jnp.einsum("...kl,...l->...k", Ci, etaj)) + bj
    C = sym(AjD @ Ci @ jnp.swapaxes(Aj, -1, -2) + Cj)
    E = jit_eye + jnp.einsum("...kl,...lm->...km", Jj, Ci) if Ai.ndim > 2 \
        else jit_eye + Jj @ Ci
    AiT = jnp.swapaxes(Ai, -1, -2)
    EinvRHS = jnp.linalg.solve(
        E, (etaj - jnp.einsum("...kl,...l->...k", Jj, bi))[..., None])
    eta = jnp.einsum("...kl,...l->...k", AiT, EinvRHS[..., 0]) + etai
    EinvJjAi = jnp.linalg.solve(E, Jj @ Ai)
    J = sym(AiT @ EinvJjAi + Ji)
    return (A, b, C, eta, J)


def pit_from_stats(stats: ObsStats, p: SSMParams,
                   scan_impl: str = "blocked"):
    """The replicated part of the PIT filter, from (possibly psum'd) stats:
    element build + prefix product + batched moment/logdet assembly.
    Returns (x_pred, P_pred, x_filt, P_filt, logdetG); the innovation
    quadratic is the caller's (it needs the panel).  Shared by
    ``pit_filter`` and the mixed-frequency E-step (``mixed_freq
    .mf_em_core`` with ``time_scan="pit"`` — the m = L*k augmented scan is
    that family's dominant cost and has no steady-state shortcut, the mask
    makes C time-varying)."""
    elems = _filter_elements(stats, p.A, p.Q, p.mu0, p.P0)
    if scan_impl == "blocked":
        pref = blocked_scan(_combine_filter, elems)
    else:
        pref = lax.associative_scan(_combine_filter, elems)
    x_f, P_f = pref[1], pref[2]

    # Predicted moments: one batched matmul off the filtered ones.
    x_pred = jnp.concatenate([p.mu0[None], x_f[:-1] @ p.A.T], axis=0)
    P_pred = jnp.concatenate(
        [p.P0[None],
         sym(jnp.einsum("ij,tjl,kl->tik", p.A, P_f[:-1], p.A) + p.Q[None])],
        axis=0)

    # Batched logdet: log|I + L' C_t L| over the predicted-cov choleskys.
    k = p.A.shape[0]
    T = stats.b.shape[0]
    C_t = stats.C
    if C_t.ndim == 2:
        C_t = jnp.broadcast_to(C_t, (T, k, k))
    Lp = psd_cholesky(P_pred)
    G = jnp.eye(k, dtype=x_f.dtype)[None] + jnp.einsum(
        "tlk,tlm,tmn->tkn", Lp, C_t, Lp)
    logdetG = chol_logdet(psd_cholesky(G, jitter=0.0))
    return x_pred, P_pred, x_f, P_f, logdetG


def pit_filter(Y: jax.Array, p: SSMParams,
               mask: Optional[jax.Array] = None,
               scan_impl: str = "blocked") -> FilterResult:
    """Parallel-in-time information-form filter; same contract as
    ``info_filter`` (exact loglik, predicted/filtered moments).

    scan_impl: "blocked" (work-efficient sqrt(T)-depth blocked scan — the
    fast path on TPU, see ops.scan) or "associative" (log-depth
    lax.associative_scan — more parallelism, ~2T combines)."""
    p = p.astype(Y.dtype)
    stats = obs_stats(Y, p.Lam, p.R, mask=mask)
    x_pred, P_pred, x_f, P_f, logdetG = pit_from_stats(stats, p, scan_impl)
    quad_R, U = loglik_terms_local(Y, p.Lam, p.R, x_pred, mask)
    ll = loglik_from_terms(stats, logdetG, P_f, quad_R, U)
    return FilterResult(x_pred, P_pred, x_f, P_f, ll)


def _smoother_elements(kf: FilterResult, A):
    """Affine smoothing elements (E, g, L); last element anchors at T-1."""
    T, k = kf.x_filt.shape
    Pp_next = kf.P_pred[1:]
    L = psd_cholesky(Pp_next)
    APf = jnp.einsum("ij,tjk->tik", A, kf.P_filt[:-1])
    J = jnp.swapaxes(jax.vmap(chol_solve)(L, APf), -1, -2)  # (T-1, k, k)
    E = jnp.concatenate([J, jnp.zeros((1, k, k), J.dtype)], axis=0)
    g_head = kf.x_filt[:-1] - jnp.einsum("tkl,tl->tk", J, kf.x_pred[1:])
    g = jnp.concatenate([g_head, kf.x_filt[-1:]], axis=0)
    L_head = sym(kf.P_filt[:-1]
                 - jnp.einsum("tkl,tlm,tnm->tkn", J, Pp_next, J))
    L_el = jnp.concatenate([L_head, kf.P_filt[-1:]], axis=0)
    return (E, g, L_el), J


def _combine_smoother(elater, eearlier):
    """Compose x_t = E x_{t+1} + g + noise(L) elements.

    NOTE argument order: ``lax.associative_scan(..., reverse=True)`` computes
    r[t] = x[T-1] * ... * x[t], i.e. the EARLIER-in-time element arrives as
    the SECOND argument (verified empirically; easy to get backwards).  The
    earlier element is the outer map: E = E_early E_late, etc.
    """
    El, gl, Ll = elater
    Ee, ge, Le = eearlier
    E = Ee @ El
    g = jnp.einsum("...kl,...l->...k", Ee, gl) + ge
    L = sym(Ee @ Ll @ jnp.swapaxes(Ee, -1, -2) + Le)
    return (E, g, L)


def pit_smoother(kf: FilterResult, p: SSMParams,
                 scan_impl: str = "blocked") -> SmootherResult:
    """Parallel-in-time RTS smoother; same contract as ``rts_smoother``."""
    dtype = kf.x_filt.dtype
    p = p.astype(dtype)
    T, k = kf.x_filt.shape
    elems, J = _smoother_elements(kf, p.A)
    if scan_impl == "blocked":
        suf = blocked_scan(_combine_smoother, elems, reverse=True)
    else:
        suf = lax.associative_scan(_combine_smoother, elems, reverse=True)
    x_sm, P_sm = suf[1], suf[2]
    P_lag_tail = jnp.einsum("tij,tkj->tik", P_sm[1:], J)
    P_lag = jnp.concatenate([jnp.zeros((1, k, k), dtype), P_lag_tail], axis=0)
    return SmootherResult(x_sm, P_sm, P_lag)


def pit_filter_smoother(Y, p, mask=None):
    kf = pit_filter(Y, p, mask=mask)
    return kf, pit_smoother(kf, p)


# ---------------------------------------------------------------------------
# QR-factor (square-root / orthogonal-transformation) parallel-in-time engine
# ---------------------------------------------------------------------------
#
# The covariance-form combine above carries batched GENERAL solves and
# products of covariances — ~100x their flop budget on this toolchain
# (batched-linalg lowering, docs/PERF.md item 6a) and the dominant f32
# noise amplifier of the legacy path.  Following "Parallel-in-Time Kalman
# Smoothing Using Orthogonal Transformations" (PAPERS.md, arXiv 2502.11686)
# the elements instead carry SQUARE-ROOT factors, C = U U' and J = Z Z',
# and every combine is a thin QR (``ops.linalg.tria``) of stacked factors
# plus triangular solves against Cholesky factors of I + (PSD) — uniformly
# well-conditioned, so no jitter is ever needed, and every op is a
# statically-unrolled VPU kernel (no linalg primitive in any scan body).
#
# Combine (ei earlier, ej later), with Y = U_i' Z_j:
#
#   Theta = tria([Y  | I])        Theta Theta' = I + U_i' C^J U_i
#   Lam   = tria([Y' | I])        Lam Lam'     = I + Z_j' C_i Z_j
#   (I + C_i J_j)^{-1} M = M - U_i (Theta Theta')^{-1} Y (Z_j' M)
#   (I + J_j C_i)^{-1} M = M - Z_j (Lam Lam')^{-1} Y' (U_i' M)
#   A   = A_j (I + C_i J_j)^{-1} A_i
#   b   = A_j (I + C_i J_j)^{-1} (b_i + U_i U_i' eta_j) + b_j
#   U   = tria([A_j U_i Theta^{-T} | U_j])
#   eta = A_i' (I + J_j C_i)^{-1} (eta_j - Z_j Z_j' b_i) + eta_i
#   Z   = tria([A_i' Z_j Lam^{-T} | Z_i])
#
# (the U/Z rows follow from D^{-1} C_i = U_i (ThetaTheta')^{-1} U_i' and
# E^{-1} J_j = Z_j (LamLam')^{-1} Z_j' — push-through of the Woodbury
# correction.)  Equivalence with the sequential info scan is tested to fp
# tolerance in x64 AND f32; ``EMConfig(filter="pit_qr")`` selects it.


def _gram(U):
    """U U' with the VPU-form product (small trailing dims)."""
    return matmul_vpu(U, jnp.swapaxes(U, -1, -2))


def qr_generic_elements(stats: ObsStats, A, Q):
    """Batched square-root element construction (A, b, U, eta, Z) for
    INTERIOR steps (no t = 0 prior correction — see ``qr_init_posterior``).

    Same push-through identities as ``_filter_elements`` but factored:
    with Lq Lq' = Q, W_t W_t' = C_t (guarded semidefinite factors — C_t is
    rank-deficient whenever a step observes < k series) and
    H_t = chol(I + W_t' Q W_t):

        U_t   = Lq E_t^{-T},  E_t = chol(I + Lq' C_t Lq)
        Z_t   = F' W_t H_t^{-T}
        A_t   = F - Q W_t (H_t H_t')^{-1} W_t' F
        b_t   = Q n_t,  eta_t = F' n_t,  n_t = (I + C_t Q)^{-1} bobs_t

    Everything is unrolled elementwise ops batched over T; no batched
    linalg primitive anywhere (k <= QR_UNROLL_K_MAX; generic fallbacks
    above).  The time-sharded variant builds these locally per shard and
    corrects slot 0 on the first device only.
    """
    dtype = stats.b.dtype
    T = stats.b.shape[0]
    k = A.shape[0]
    C_t = stats.C
    if C_t.ndim == 2:
        C_t = jnp.broadcast_to(C_t, (T, k, k))
    bobs = stats.b
    unroll = k <= QR_UNROLL_K_MAX
    chol = chol_unrolled if unroll else (lambda M: psd_cholesky(M, jitter=0.0))
    chol_slv = chol_solve_unrolled if unroll else chol_solve

    Lq = psd_factor(Q)                                  # (k, k), may be rank-def.
    F_b = jnp.broadcast_to(A, (T, k, k))
    I_k = jnp.eye(k, dtype=dtype)

    # U_t = Lq E^{-T}: E = chol(I + Lq' C Lq) — I + PSD, no guard needed.
    LqT_C = matmul_vpu(jnp.broadcast_to(Lq.T, (T, k, k)), C_t)
    G = I_k[None] + matmul_vpu(LqT_C, jnp.broadcast_to(Lq, (T, k, k)))
    E = chol(G)
    U_el = jnp.swapaxes(tri_solve(E, jnp.broadcast_to(Lq.T, (T, k, k))),
                        -1, -2)

    # W_t = factor(C_t); H = chol(I + W' Q W).
    W = psd_factor(C_t)
    WT = jnp.swapaxes(W, -1, -2)
    QW = matmul_vpu(jnp.broadcast_to(Q, (T, k, k)), W)
    H = chol(I_k[None] + matmul_vpu(WT, QW))

    # n_t = (I + C Q)^{-1} bobs = bobs - W (H H')^{-1} W' Q bobs.
    Qb = matvec_vpu(jnp.broadcast_to(Q, (T, k, k)), bobs)
    n_t = bobs - matvec_vpu(W, chol_slv(H, matvec_vpu(WT, Qb)))
    b_el = matvec_vpu(jnp.broadcast_to(Q, (T, k, k)), n_t)
    eta_el = matvec_vpu(jnp.broadcast_to(A.T, (T, k, k)), n_t)

    # Z_t = F' W H^{-T};  A_t = F - Q W (H H')^{-1} W' F.
    FTW = matmul_vpu(jnp.broadcast_to(A.T, (T, k, k)), W)
    Z_el = jnp.swapaxes(tri_solve(H, jnp.swapaxes(FTW, -1, -2)), -1, -2)
    WTF = matmul_vpu(WT, F_b)
    A_el = F_b - matmul_vpu(QW, chol_slv(H, WTF))
    return (A_el, b_el, U_el, eta_el, Z_el)


def qr_init_posterior(C0, bobs0, mu0, P0):
    """(b0, U0): the first filtered posterior from the prior (mu0, P0).

    The t = 0 element is (A=0, b0, U0, eta=0, Z=0) — it absorbs the prior,
    so every prefix product carries A = 0 and b = filtered mean directly.
    """
    dtype = bobs0.dtype
    k = mu0.shape[0]
    unroll = k <= QR_UNROLL_K_MAX
    chol = chol_unrolled if unroll else (lambda M: psd_cholesky(M, jitter=0.0))
    chol_slv = chol_solve_unrolled if unroll else chol_solve
    I_k = jnp.eye(k, dtype=dtype)
    Lp0 = psd_factor(P0)
    E0 = chol(I_k + Lp0.T @ C0 @ Lp0)
    U0 = jnp.swapaxes(tri_solve(E0, Lp0.T), -1, -2)
    # (I + C0 P0)^{-1} v = v - W0 chol_slv(Hp, W0' P0 v), Hp = chol(I+W0'P0 W0)
    W0 = psd_factor(C0)
    Hp = chol(I_k + W0.T @ P0 @ W0)
    v0 = bobs0 - C0 @ mu0
    n0 = v0 - W0 @ chol_slv(Hp, W0.T @ (P0 @ v0))
    b0 = mu0 + P0 @ n0
    return b0, U0


def qr_filter_elements(stats: ObsStats, A, Q, mu0, P0):
    """Generic square-root elements with the t = 0 prior correction applied
    (single-device entry — see ``qr_generic_elements``)."""
    dtype = stats.b.dtype
    k = A.shape[0]
    A_el, b_el, U_el, eta_el, Z_el = qr_generic_elements(stats, A, Q)
    C0 = stats.C if stats.C.ndim == 2 else stats.C[0]
    b0, U0 = qr_init_posterior(C0, stats.b[0], mu0, P0)
    zeros_kk = jnp.zeros((k, k), dtype)
    A_el = A_el.at[0].set(zeros_kk)
    b_el = b_el.at[0].set(b0)
    U_el = U_el.at[0].set(U0)
    eta_el = eta_el.at[0].set(jnp.zeros((k,), dtype))
    Z_el = Z_el.at[0].set(zeros_kk)
    return (A_el, b_el, U_el, eta_el, Z_el)


def qr_combine_filter(ei, ej):
    """Square-root associative filtering product (ei earlier, ej later).

    QR + triangular solves only — see the section comment for the algebra.
    Works for single elements and arbitrary leading batch dims (the
    blocked scan batches over blocks).
    """
    Ai, bi, Ui, etai, Zi = ei
    Aj, bj, Uj, etaj, Zj = ej
    k = Ai.shape[-1]
    dtype = Ai.dtype
    I_b = jnp.broadcast_to(jnp.eye(k, dtype=dtype), Ai.shape)
    unroll = k <= QR_UNROLL_K_MAX
    chol_slv = chol_solve_unrolled if unroll else chol_solve

    UiT = jnp.swapaxes(Ui, -1, -2)
    ZjT = jnp.swapaxes(Zj, -1, -2)
    Yf = matmul_vpu(UiT, Zj)                      # U_i' Z_j
    Theta = tria(jnp.concatenate([Yf, I_b], axis=-1))
    Lam = tria(jnp.concatenate([jnp.swapaxes(Yf, -1, -2), I_b], axis=-1))

    def Dinv(M):                                  # (I + C_i J_j)^{-1} M
        return M - matmul_vpu(Ui, chol_slv(
            Theta, matmul_vpu(Yf, matmul_vpu(ZjT, M))))

    def Dinv_v(v):
        return v - matvec_vpu(Ui, chol_slv(
            Theta, matvec_vpu(Yf, matvec_vpu(ZjT, v))))

    def Einv_v(v):                                # (I + J_j C_i)^{-1} v
        return v - matvec_vpu(Zj, chol_slv(
            Lam, matvec_vpu(jnp.swapaxes(Yf, -1, -2), matvec_vpu(UiT, v))))

    A = matmul_vpu(Aj, Dinv(Ai))
    b = matvec_vpu(Aj, Dinv_v(bi + matvec_vpu(Ui, matvec_vpu(UiT, etaj)))) + bj
    AjUi = matmul_vpu(Aj, Ui)
    # A_j U_i Theta^{-T}: solve Theta X = (A_j U_i)' then transpose.
    U_half = jnp.swapaxes(tri_solve(Theta, jnp.swapaxes(AjUi, -1, -2)),
                          -1, -2)
    U = tria(jnp.concatenate([U_half, Uj], axis=-1))
    AiT = jnp.swapaxes(Ai, -1, -2)
    eta = matvec_vpu(AiT, Einv_v(etaj - matvec_vpu(Zj, matvec_vpu(ZjT, bi)))) \
        + etai
    AiTZj = matmul_vpu(AiT, Zj)
    Z_half = jnp.swapaxes(tri_solve(Lam, jnp.swapaxes(AiTZj, -1, -2)),
                          -1, -2)
    Z = tria(jnp.concatenate([Z_half, Zi], axis=-1))
    return (A, b, U, eta, Z)


def pit_qr_from_stats(stats: ObsStats, p: SSMParams,
                      scan_impl: str = "blocked"):
    """QR-factor twin of ``pit_from_stats``: element build + prefix product
    + factored moment/logdet assembly.  Same returns (x_pred, P_pred, x_f,
    P_f, logdetG); the predicted factors come straight from
    ``tria([A U_f | Lq])`` — never a re-factorization of an already-rounded
    covariance, which is where the f32 stability of this path comes from.
    """
    elems = qr_filter_elements(stats, p.A, p.Q, p.mu0, p.P0)
    if scan_impl == "blocked":
        pref = blocked_scan(qr_combine_filter, elems)
    else:
        pref = lax.associative_scan(qr_combine_filter, elems)
    x_f, U_f = pref[1], pref[2]
    P_f = _gram(U_f)

    T = stats.b.shape[0]
    k = p.A.shape[0]
    dtype = x_f.dtype
    Lq = psd_factor(p.Q)
    Lp0 = psd_factor(p.P0)
    AU = matmul_vpu(jnp.broadcast_to(p.A, (T - 1, k, k)), U_f[:-1])
    Lp_tail = tria(jnp.concatenate(
        [AU, jnp.broadcast_to(Lq, (T - 1, k, k))], axis=-1))
    Lp = jnp.concatenate([Lp0[None], Lp_tail], axis=0)
    P_pred = _gram(Lp)
    x_pred = jnp.concatenate([p.mu0[None], x_f[:-1] @ p.A.T], axis=0)

    C_t = stats.C
    if C_t.ndim == 2:
        C_t = jnp.broadcast_to(C_t, (T, k, k))
    LpT_C = matmul_vpu(jnp.swapaxes(Lp, -1, -2), C_t)
    G = jnp.eye(k, dtype=dtype)[None] + matmul_vpu(LpT_C, Lp)
    chol = chol_unrolled if k <= QR_UNROLL_K_MAX else \
        (lambda M: psd_cholesky(M, jitter=0.0))
    logdetG = chol_logdet(chol(G))
    return x_pred, P_pred, x_f, P_f, logdetG


def pit_qr_filter(Y: jax.Array, p: SSMParams,
                  mask: Optional[jax.Array] = None,
                  scan_impl: str = "blocked") -> FilterResult:
    """Square-root parallel-in-time filter; same contract as ``info_filter``
    / ``pit_filter`` (exact loglik, predicted/filtered moments)."""
    p = p.astype(Y.dtype)
    stats = obs_stats(Y, p.Lam, p.R, mask=mask)
    x_pred, P_pred, x_f, P_f, logdetG = pit_qr_from_stats(stats, p, scan_impl)
    quad_R, U = loglik_terms_local(Y, p.Lam, p.R, x_pred, mask)
    ll = loglik_from_terms(stats, logdetG, P_f, quad_R, U)
    return FilterResult(x_pred, P_pred, x_f, P_f, ll)


def qr_combine_smoother(elater, eearlier):
    """Square-root smoothing-element product (same reverse-argument
    convention as ``_combine_smoother``): L = D D' combines as
    D = tria([E_e D_l | D_e]) — one thin QR, no covariance products."""
    El, gl, Dl = elater
    Ee, ge, De = eearlier
    E = matmul_vpu(Ee, El)
    g = matvec_vpu(Ee, gl) + ge
    D = tria(jnp.concatenate([matmul_vpu(Ee, Dl), De], axis=-1))
    return (E, g, D)


def _qr_smoother_elements(kf: FilterResult, A, Q):
    """Square-root affine smoothing elements (E, g, D).

    The residual covariance uses the Joseph form
    L_t = (I - J A) P_f (I - J A)' + J Q J'  —  PSD by construction, so its
    factor is one tria of [(I - J A) U_f | J Lq] and the combine never sees
    a subtraction of covariances.
    """
    T, k = kf.x_filt.shape
    dtype = kf.x_filt.dtype
    unroll = k <= QR_UNROLL_K_MAX
    chol_slv = chol_solve_unrolled if unroll else chol_solve
    U_f = psd_factor(kf.P_filt)
    Lq = psd_factor(Q)
    Lp_next = psd_factor(kf.P_pred[1:])
    APf = matmul_vpu(jnp.broadcast_to(A, (T - 1, k, k)), kf.P_filt[:-1])
    J = jnp.swapaxes(chol_slv(Lp_next, APf), -1, -2)       # (T-1, k, k)
    E = jnp.concatenate([J, jnp.zeros((1, k, k), J.dtype)], axis=0)
    g_head = kf.x_filt[:-1] - jnp.einsum("tkl,tl->tk", J, kf.x_pred[1:])
    g = jnp.concatenate([g_head, kf.x_filt[-1:]], axis=0)
    ImJA = jnp.broadcast_to(jnp.eye(k, dtype=dtype), (T - 1, k, k)) \
        - matmul_vpu(J, jnp.broadcast_to(A, (T - 1, k, k)))
    D_head = tria(jnp.concatenate(
        [matmul_vpu(ImJA, U_f[:-1]),
         matmul_vpu(J, jnp.broadcast_to(Lq, (T - 1, k, k)))], axis=-1))
    D = jnp.concatenate([D_head, U_f[-1:]], axis=0)
    return (E, g, D), J


def pit_qr_smoother(kf: FilterResult, p: SSMParams,
                    scan_impl: str = "blocked") -> SmootherResult:
    """Square-root parallel-in-time RTS smoother; contract of
    ``rts_smoother``."""
    dtype = kf.x_filt.dtype
    p = p.astype(dtype)
    T, k = kf.x_filt.shape
    elems, J = _qr_smoother_elements(kf, p.A, p.Q)
    if scan_impl == "blocked":
        suf = blocked_scan(qr_combine_smoother, elems, reverse=True)
    else:
        suf = lax.associative_scan(qr_combine_smoother, elems, reverse=True)
    x_sm, D_sm = suf[1], suf[2]
    P_sm = _gram(D_sm)
    P_lag_tail = jnp.einsum("tij,tkj->tik", P_sm[1:], J)
    P_lag = jnp.concatenate([jnp.zeros((1, k, k), dtype), P_lag_tail], axis=0)
    return SmootherResult(x_sm, P_sm, P_lag)


def pit_qr_filter_smoother(Y, p, mask=None):
    kf = pit_qr_filter(Y, p, mask=mask)
    return kf, pit_qr_smoother(kf, p)

"""Parallel-in-time Kalman filtering/smoothing via ``lax.associative_scan``.

The sequential T-step scan is the wall-clock floor of the whole framework
(SURVEY.md section 7.2 item 3): 500-1000 dependent k x k steps leave the TPU
idle.  Bayesian filtering is associative (Sarkka & Garcia-Fernandez,
"Temporal Parallelization of Bayesian Smoothers", IEEE TAC 2021 —
PAPERS.md:6): each step is an element of a semigroup whose product yields the
filtered posterior, so the T-fold product runs as a log2(T)-depth prefix scan
of BATCHED k x k algebra — exactly what the TPU wants.

Filtering element a_t = (A, b, C, eta, J); combination (i earlier, j later):

    D   = (I + C_i J_j)^{-1}
    A   = A_j D A_i
    b   = A_j D (b_i + C_i eta_j) + b_j
    C   = A_j D C_i A_j' + C_j
    E   = (I + J_j C_i)^{-1}
    eta = A_i' E (eta_j - J_j b_i) + eta_i
    J   = A_i' E J_j A_i + J_i

After the inclusive prefix product, (b_t, C_t) ARE the filtered moments.

The elements are initialized from the same information-form observation
statistics as the sequential path (ObsStats; per-t C_t, b_t) via push-through
identities so nothing N x N is ever formed:

    A_t = (I + Q C_t)^{-1} F            b_t = Q (I + C_t Q)^{-1} bobs_t
    C_t = (I + Q C_t)^{-1} Q            eta_t = F' (I + C_t Q)^{-1} bobs_t
    J_t = F' (I + C_t Q)^{-1} C_t F

(t=0 uses P0/mu0 with A_0 = 0.)  The log-likelihood is then assembled with
zero sequential steps: predicted moments are one batched matmul off the
filtered ones, and the Woodbury quadratic reuses the cancellation-free
residual pass of ``info_filter``.

The RTS smoother parallelizes the same way with affine elements
(E, g, L): E = E_i E_j, g = E_i g_j + g_i, L = E_i L_j E_i' + L_i under a
reverse prefix product.

Equivalence with the sequential scans is tested to fp tolerance; the EM
wrapper selects this path with ``EMConfig(filter="pit")``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.linalg import sym, psd_cholesky, chol_solve, chol_logdet
from ..ops.scan import blocked_scan
from .info_filter import (ObsStats, obs_stats, loglik_terms_local,
                          loglik_from_terms)
from .params import SSMParams, FilterResult, SmootherResult

__all__ = ["pit_filter", "pit_smoother", "pit_filter_smoother",
           "pit_from_stats"]

_LOG2PI = 1.8378770664093453


def _filter_elements(stats: ObsStats, A, Q, mu0, P0):
    """Batched element construction from info-form stats; all k x k."""
    dtype = stats.b.dtype
    T = stats.b.shape[0]
    k = A.shape[0]
    I_k = jnp.eye(k, dtype=dtype)
    C_t = stats.C
    if C_t.ndim == 2:
        C_t = jnp.broadcast_to(C_t, (T, k, k))
    bobs = stats.b

    # Generic elements (t >= 1): push-through forms with Q.
    M = I_k[None] + jnp.einsum("kl,tlm->tkm", Q, C_t)     # I + Q C_t
    Minv_F = jnp.linalg.solve(M, jnp.broadcast_to(A, (T, k, k)))
    Minv_Q = jnp.linalg.solve(M, jnp.broadcast_to(Q, (T, k, k)))
    # (I + C Q)^{-1} b  =  solve(I + C Q, b)
    N_ = I_k[None] + jnp.einsum("tkl,lm->tkm", C_t, Q)    # I + C_t Q
    Ninv_b = jnp.linalg.solve(N_, bobs[..., None])[..., 0]
    A_el = Minv_F                                          # (I+QC)^-1 F
    b_el = jnp.einsum("kl,tl->tk", Q, Ninv_b)              # Q (I+CQ)^-1 b
    C_el = sym(Minv_Q)                                     # (I+QC)^-1 Q
    eta_el = jnp.einsum("lk,tl->tk", A, Ninv_b)            # F'(I+CQ)^-1 b
    NinvC = jnp.linalg.solve(N_, C_t)
    J_el = sym(jnp.einsum("lk,tlm,mn->tkn", A,
                          NinvC, A))                       # F'(I+CQ)^-1 C F

    # t = 0 element: posterior from the prior (mu0, P0); A_0 = 0.
    M0 = I_k + P0 @ C_t[0]
    b0 = mu0 + P0 @ jnp.linalg.solve(
        I_k + C_t[0] @ P0, bobs[0] - C_t[0] @ mu0)
    C0 = sym(jnp.linalg.solve(M0, P0))
    A_el = A_el.at[0].set(jnp.zeros((k, k), dtype))
    b_el = b_el.at[0].set(b0)
    C_el = C_el.at[0].set(C0)
    eta_el = eta_el.at[0].set(jnp.zeros((k,), dtype))
    J_el = J_el.at[0].set(jnp.zeros((k, k), dtype))
    return (A_el, b_el, C_el, eta_el, J_el)


def _combine_filter(ei, ej):
    """Associative filtering-element product (ei earlier, ej later)."""
    Ai, bi, Ci, etai, Ji = ei
    Aj, bj, Cj, etaj, Jj = ej
    k = Ai.shape[-1]
    I_k = jnp.eye(k, dtype=Ai.dtype)
    D = I_k + Ci @ Jj if Ai.ndim == 2 else \
        I_k[None] + jnp.einsum("...kl,...lm->...km", Ci, Jj)
    # batched general solves (D is not symmetric).
    AjD = jnp.linalg.solve(jnp.swapaxes(D, -1, -2),
                           jnp.swapaxes(Aj, -1, -2))
    AjD = jnp.swapaxes(AjD, -1, -2)                       # A_j D^{-1}
    A = AjD @ Ai
    b = jnp.einsum("...kl,...l->...k", AjD,
                   bi + jnp.einsum("...kl,...l->...k", Ci, etaj)) + bj
    C = sym(AjD @ Ci @ jnp.swapaxes(Aj, -1, -2) + Cj)
    E = I_k + jnp.einsum("...kl,...lm->...km", Jj, Ci) if Ai.ndim > 2 \
        else I_k + Jj @ Ci
    AiT = jnp.swapaxes(Ai, -1, -2)
    EinvRHS = jnp.linalg.solve(
        E, (etaj - jnp.einsum("...kl,...l->...k", Jj, bi))[..., None])
    eta = jnp.einsum("...kl,...l->...k", AiT, EinvRHS[..., 0]) + etai
    EinvJjAi = jnp.linalg.solve(E, Jj @ Ai)
    J = sym(AiT @ EinvJjAi + Ji)
    return (A, b, C, eta, J)


def pit_from_stats(stats: ObsStats, p: SSMParams,
                   scan_impl: str = "blocked"):
    """The replicated part of the PIT filter, from (possibly psum'd) stats:
    element build + prefix product + batched moment/logdet assembly.
    Returns (x_pred, P_pred, x_filt, P_filt, logdetG); the innovation
    quadratic is the caller's (it needs the panel).  Shared by
    ``pit_filter`` and the mixed-frequency E-step (``mixed_freq
    .mf_em_core`` with ``time_scan="pit"`` — the m = L*k augmented scan is
    that family's dominant cost and has no steady-state shortcut, the mask
    makes C time-varying)."""
    elems = _filter_elements(stats, p.A, p.Q, p.mu0, p.P0)
    if scan_impl == "blocked":
        pref = blocked_scan(_combine_filter, elems)
    else:
        pref = lax.associative_scan(_combine_filter, elems)
    x_f, P_f = pref[1], pref[2]

    # Predicted moments: one batched matmul off the filtered ones.
    x_pred = jnp.concatenate([p.mu0[None], x_f[:-1] @ p.A.T], axis=0)
    P_pred = jnp.concatenate(
        [p.P0[None],
         sym(jnp.einsum("ij,tjl,kl->tik", p.A, P_f[:-1], p.A) + p.Q[None])],
        axis=0)

    # Batched logdet: log|I + L' C_t L| over the predicted-cov choleskys.
    k = p.A.shape[0]
    T = stats.b.shape[0]
    C_t = stats.C
    if C_t.ndim == 2:
        C_t = jnp.broadcast_to(C_t, (T, k, k))
    Lp = psd_cholesky(P_pred)
    G = jnp.eye(k, dtype=x_f.dtype)[None] + jnp.einsum(
        "tlk,tlm,tmn->tkn", Lp, C_t, Lp)
    logdetG = chol_logdet(psd_cholesky(G, jitter=0.0))
    return x_pred, P_pred, x_f, P_f, logdetG


def pit_filter(Y: jax.Array, p: SSMParams,
               mask: Optional[jax.Array] = None,
               scan_impl: str = "blocked") -> FilterResult:
    """Parallel-in-time information-form filter; same contract as
    ``info_filter`` (exact loglik, predicted/filtered moments).

    scan_impl: "blocked" (work-efficient sqrt(T)-depth blocked scan — the
    fast path on TPU, see ops.scan) or "associative" (log-depth
    lax.associative_scan — more parallelism, ~2T combines)."""
    p = p.astype(Y.dtype)
    stats = obs_stats(Y, p.Lam, p.R, mask=mask)
    x_pred, P_pred, x_f, P_f, logdetG = pit_from_stats(stats, p, scan_impl)
    quad_R, U = loglik_terms_local(Y, p.Lam, p.R, x_pred, mask)
    ll = loglik_from_terms(stats, logdetG, P_f, quad_R, U)
    return FilterResult(x_pred, P_pred, x_f, P_f, ll)


def _smoother_elements(kf: FilterResult, A):
    """Affine smoothing elements (E, g, L); last element anchors at T-1."""
    T, k = kf.x_filt.shape
    Pp_next = kf.P_pred[1:]
    L = psd_cholesky(Pp_next)
    APf = jnp.einsum("ij,tjk->tik", A, kf.P_filt[:-1])
    J = jnp.swapaxes(jax.vmap(chol_solve)(L, APf), -1, -2)  # (T-1, k, k)
    E = jnp.concatenate([J, jnp.zeros((1, k, k), J.dtype)], axis=0)
    g_head = kf.x_filt[:-1] - jnp.einsum("tkl,tl->tk", J, kf.x_pred[1:])
    g = jnp.concatenate([g_head, kf.x_filt[-1:]], axis=0)
    L_head = sym(kf.P_filt[:-1]
                 - jnp.einsum("tkl,tlm,tnm->tkn", J, Pp_next, J))
    L_el = jnp.concatenate([L_head, kf.P_filt[-1:]], axis=0)
    return (E, g, L_el), J


def _combine_smoother(elater, eearlier):
    """Compose x_t = E x_{t+1} + g + noise(L) elements.

    NOTE argument order: ``lax.associative_scan(..., reverse=True)`` computes
    r[t] = x[T-1] * ... * x[t], i.e. the EARLIER-in-time element arrives as
    the SECOND argument (verified empirically; easy to get backwards).  The
    earlier element is the outer map: E = E_early E_late, etc.
    """
    El, gl, Ll = elater
    Ee, ge, Le = eearlier
    E = Ee @ El
    g = jnp.einsum("...kl,...l->...k", Ee, gl) + ge
    L = sym(Ee @ Ll @ jnp.swapaxes(Ee, -1, -2) + Le)
    return (E, g, L)


def pit_smoother(kf: FilterResult, p: SSMParams,
                 scan_impl: str = "blocked") -> SmootherResult:
    """Parallel-in-time RTS smoother; same contract as ``rts_smoother``."""
    dtype = kf.x_filt.dtype
    p = p.astype(dtype)
    T, k = kf.x_filt.shape
    elems, J = _smoother_elements(kf, p.A)
    if scan_impl == "blocked":
        suf = blocked_scan(_combine_smoother, elems, reverse=True)
    else:
        suf = lax.associative_scan(_combine_smoother, elems, reverse=True)
    x_sm, P_sm = suf[1], suf[2]
    P_lag_tail = jnp.einsum("tij,tkj->tik", P_sm[1:], J)
    P_lag = jnp.concatenate([jnp.zeros((1, k, k), dtype), P_lag_tail], axis=0)
    return SmootherResult(x_sm, P_sm, P_lag)


def pit_filter_smoother(Y, p, mask=None):
    kf = pit_filter(Y, p, mask=mask)
    return kf, pit_smoother(kf, p)

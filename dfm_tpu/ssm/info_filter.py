"""Information-form Kalman filter: the N-scalable TPU path (SURVEY.md M2).

The dense filter (``ssm.kalman``) forms the N x N innovation covariance
S_t = Lam P Lam' + R every step — O(T N^3), infeasible at the 10k-series
headline shape (BASELINE.json:2).  With diagonal R the update can be written so
the cross-section enters ONLY through k-dimensional reductions
(BASELINE.json:5 "psum collectives over sharded series"):

    C_t = Lam' W_t R^{-1} Lam          (k, k)   precision added by the obs
    b_t = Lam' W_t R^{-1} y_t          (k,)     information vector
    n_t  = #observed at t              scalar   | log-likelihood pieces
    ldR_t = sum of log R over observed scalar   | (with logdet below)

All of these are einsums over the series axis — one big MXU matmul outside the
time scan (static mask-free case: B = Y R^{-1} Lam is a single (T,N)x(N,k)
product) or a batched one (masked case), and under sharding a local einsum
followed by a psum.  The t-scan itself is pure k x k:

    update   P_f = (P_p^{-1} + C_t)^{-1} = L (I + L' C_t L)^{-1} L',  P_p = LL'
             x_f = x_p + P_f (b_t - C_t x_p)
    loglik   log|S_t| = ldR_t + log|I + L' C_t L|      (matrix det lemma)
             v' S^{-1} v = v' R^{-1} v - u' P_f u,  u = Lam' R^{-1} v (Woodbury)

Float32 note (SURVEY.md section 7.2 item 1): the algebraically-equivalent form
v' R^{-1} v = c2_t - 2 x_p.b_t + x_p' C_t x_p cancels catastrophically in f32
(measured ~1e-3 relative loglik error vs the dense filter's ~6e-6 on the S1
config).  The filter therefore computes the quadratic in a SECOND batched pass
after the scan, from actual residuals V = Y - x_pred Lam' — one extra
(T,N)x(N,k) MXU matmul, no large-term differencing.  Equivalence with the
dense filter is a unit test; SURVEY.md section 7.2 item 2 flags the Woodbury
loglik as the easy-to-get-wrong part.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.linalg import sym, psd_cholesky, chol_solve, chol_logdet
from .params import SSMParams, FilterResult, SmootherResult
from .kalman import rts_smoother

__all__ = ["ObsStats", "obs_stats", "info_scan", "loglik_terms_local",
           "quad_local", "u_from_stats", "loglik_from_terms",
           "info_filter_from_stats", "info_filter", "info_filter_smoother",
           "loglik_eval"]

_LOG2PI = 1.8378770664093453


class ObsStats(NamedTuple):
    """Per-step k-dimensional observation reductions (see module docstring).

    C is (k, k) when the mask is absent (time-invariant precision) and
    (T, k, k) when masked.  Everything here is psum-reducible over series
    shards — this tuple IS the device-boundary payload of the sharded filter.
    """

    b: jax.Array     # (T, k)
    C: jax.Array     # (k, k) or (T, k, k)
    n: jax.Array     # (T,)
    ldR: jax.Array   # (T,)


def obs_stats(Y: jax.Array, Lam: jax.Array, R: jax.Array,
              mask: Optional[jax.Array] = None) -> ObsStats:
    """Reduce the panel to k-dimensional per-step statistics.

    Y (T, N), Lam (N, k), R (N,); mask optional (T, N) {0,1}.  These einsums
    are the only place N appears; under ``shard_map`` each shard computes them
    on its local series block and psums (see ``parallel.sharded``).
    """
    dtype = Y.dtype
    T, N = Y.shape
    Rinv = 1.0 / R
    logR = jnp.log(R)
    if mask is None:
        G = Lam * Rinv[:, None]                     # R^{-1} Lam, (N, k)
        b = Y @ G                                   # (T, k): one big matmul
        C = Lam.T @ G                               # (k, k)
        n = jnp.full((T,), float(N), dtype)
        # ldR repeats the same N-sum T times, so its rounding is systematic
        # across the whole loglik: accumulate the one sum in f64 when
        # available (an N-sized sum once per E-step — free).  The masked
        # branch's W @ logR is a (T,N) matmul and stays in compute dtype.
        from ..ops.precision import accum_dtype
        acc = accum_dtype(dtype)
        ldR = jnp.full((T,), jnp.sum(logR.astype(acc))).astype(acc)
    else:
        W = mask.astype(dtype)
        Yw = W * jnp.nan_to_num(Y)                  # masked entries may be NaN
        G = Lam * Rinv[:, None]
        b = Yw @ G
        C = jnp.einsum("nk,tn,n,nl->tkl", Lam, W, Rinv, Lam)
        n = W.sum(axis=1)
        ldR = W @ logR
    return ObsStats(b, C, n, ldR)


def info_scan(stats: ObsStats, A: jax.Array, Q: jax.Array,
              mu0: jax.Array, P0: jax.Array):
    """k x k time scan given precomputed observation stats (replicated under
    sharding — every device runs this identically after the psum).

    Returns (x_pred, P_pred, x_filt, P_filt, logdetG (T,)) where
    logdetG_t = log|I + L' C_t L| is the low-rank part of log|S_t|.  The
    innovation quadratic is NOT computed here — see ``loglik_terms_local``.
    """
    dtype = stats.b.dtype
    k = A.shape[0]
    I_k = jnp.eye(k, dtype=dtype)
    static_C = stats.C.ndim == 2

    def step(carry, inp):
        x, P = carry
        b_t, C_t = inp
        Lp = psd_cholesky(P)
        CL = C_t @ Lp                               # (k, k)
        G = I_k + Lp.T @ CL                         # >= I: chol needs no jitter
        Lg = psd_cholesky(G, jitter=0.0)
        P_f = sym(Lp @ chol_solve(Lg, Lp.T))
        u = b_t - C_t @ x
        x_f = x + P_f @ u
        x_n = A @ x_f
        P_n = sym(A @ P_f @ A.T + Q)
        return (x_n, P_n), (x, P, x_f, P_f, chol_logdet(Lg))

    if static_C:
        C_seq = jnp.broadcast_to(stats.C, (stats.b.shape[0], k, k))
    else:
        C_seq = stats.C
    return lax.scan(step, (mu0, P0), (stats.b, C_seq))[1]


def loglik_terms_local(Y: jax.Array, Lam: jax.Array, R: jax.Array,
                       x_pred: jax.Array, mask: Optional[jax.Array]):
    """Per-shard innovation-quadratic reductions, cancellation-free.

    V = Y - x_pred Lam' (true residuals, one batched matmul);
    returns (quad_R (T,) = v'R^{-1}v partial sums, U (T, k) = Lam'R^{-1}v
    partial sums) — both psum-reducible over series shards.

    quad_R is a sum of ~N like-signed terms (E[v'R^{-1}v] = n_t), so at
    N = 10k its f32 rounding alone breaks the 1e-5 loglik contract
    (measured 1.3e-5 at the headline shape with bit-perfect params).  When
    x64 is enabled the row-sum accumulates in f64 — the elementwise product
    stays f32, only the (T, N) -> (T,) reduction upgrades.  U has random
    signs (no amplification) and stays on the f32 MXU path.
    """
    quad_R, V = quad_local(Y, Lam, R, x_pred, mask)
    U = (V / R[None, :]) @ Lam
    return quad_R, U


def quad_local(Y: jax.Array, Lam: jax.Array, R: jax.Array,
               x_pred: jax.Array, mask: Optional[jax.Array]):
    """The quad_R half of ``loglik_terms_local`` (returns (quad_R, V)).

    Callers holding the observation stats get U for free as
    ``U_t = b_t - C_t x_pred,t`` (``u_from_stats`` — exactly the innovation
    information vector the filter update uses, a k-sized computation), so
    only the quadratic needs a panel pass: one (T,N)x(N,k) matmul with the
    square-and-reduce fused into its epilogue.  Unlike the fully-expanded
    quadratic (c2 - 2 x'b + x'Cx, catastrophic in f32 — module docstring),
    b and C x_pred are SAME-magnitude sums over series with no blow-up
    (both are Lam' R^{-1}-weighted panel reductions; measured headline-
    shape f32 loglik noise is unchanged at ~1e-5, bench.py's fast check).
    """
    V = Y - x_pred @ Lam.T
    if mask is not None:
        V = mask.astype(Y.dtype) * jnp.nan_to_num(V)
    from ..ops.precision import accum_dtype
    acc = accum_dtype(Y.dtype)
    quad_R = jnp.sum((V * (V / R[None, :])).astype(acc), axis=1)
    return quad_R, V


def u_from_stats(stats: ObsStats, x_pred: jax.Array) -> jax.Array:
    """U (T, k) = Lam'R^{-1}v = b_t - C_t x_pred,t from the (already
    reduced) observation stats — no panel pass.  With per-shard stats this
    is the LOCAL U (psum-able: the map is linear in (b, C))."""
    if stats.C.ndim == 2:
        return stats.b - x_pred @ stats.C          # C symmetric
    return stats.b - jnp.einsum("tkl,tl->tk", stats.C, x_pred)


def quad_expanded(sumsq: jax.Array, Rinv: jax.Array, stats: ObsStats,
                  x_pred: jax.Array):
    """v'R^{-1}v per step WITHOUT a residual panel pass (unmasked only).

    Expands v'R^{-1}v = sum_i y^2/R - 2 x_p.b + x_p'C x_p with ``sumsq`` a
    PRECOMPUTED (T, N) array of y^2 (data-constant: fused EM drivers hoist
    it out of the iteration loop), so the per-iteration panel traffic is
    one (T,N)x(N,) matvec instead of the residual form's (T,N)x(N,k)
    matmul + subtract + reduce.

    Numerics: the naive f32 version of this expansion was measured at
    ~1e-3 relative loglik error (module docstring) because the ~2x-larger
    pieces cancel in f32.  Here the three (T,)-sized pieces are assembled
    in the f64 accum dtype, and each piece's own f32 rounding is the same
    ~eps * piece noise every other loglik piece already carries — callers
    must only use this when ``accum_dtype`` actually upgrades (x64 on; the
    drivers check).  The contract-grade evaluator (``loglik_eval``) never
    uses this path.
    """
    from ..ops.precision import accum_dtype
    acc = accum_dtype(sumsq.dtype)
    c2 = (sumsq @ Rinv).astype(acc)                    # sum_i y^2/R, (T,)
    xb = jnp.einsum("tk,tk->t", x_pred, stats.b).astype(acc)
    if stats.C.ndim == 2:
        xCx = jnp.einsum("tk,kl,tl->t", x_pred, stats.C, x_pred)
    else:
        xCx = jnp.einsum("tk,tkl,tl->t", x_pred, stats.C, x_pred)
    return c2 - 2.0 * xb + xCx.astype(acc)


def loglik_from_terms(stats: ObsStats, logdetG, P_filt, quad_R, U):
    """Assemble sum_t ll_t from global (psum'd) pieces.

    The total is a ~100x-smaller residual of cancelling O(N T) pieces
    (n log2pi + ldR + quad each ~1e7 at the headline shape while the loglik
    is ~1e5), so f32 assembly amplifies rounding two orders of magnitude.
    When x64 is enabled the (T,)-sized assembly runs in float64 — no N- or
    T-sized matmul lives here, so the cost is negligible even on TPUs that
    emulate f64, and the headline-shape loglik error drops ~4x (measured).
    The big (T,N) reductions feeding quad_R/U stay in the compute dtype.
    """
    from ..ops.precision import accum_dtype
    acc = accum_dtype(stats.b.dtype)
    # The U'P_f U einsum stays in the COMPUTE dtype (on TPUs f64 is
    # emulated, and this (T,k,k) contraction would pay ~10x for rounding
    # that is already ~eps * piece — the same noise every piece carries);
    # only the (T,)-sized assembly of the cancelling pieces upgrades.
    upu = jnp.einsum("tk,tkl,tl->t", U.astype(P_filt.dtype), P_filt,
                     U.astype(P_filt.dtype))
    quad = quad_R.astype(acc) - upu.astype(acc)
    lls = -0.5 * (stats.n.astype(acc) * _LOG2PI + stats.ldR.astype(acc)
                  + logdetG.astype(acc) + quad)
    return jnp.sum(lls)


def info_filter_from_stats(stats: ObsStats, A, Q, mu0, P0, Y=None, Lam=None,
                           R=None, mask=None) -> FilterResult:
    """Scan + loglik in one call (single-device; Y/Lam/R for the residual
    pass).  Sharded callers instead compose info_scan + quad_local/
    u_from_stats + psum + loglik_from_terms (see ``parallel.sharded``)."""
    xp, Pp, xf, Pf, logdetG = info_scan(stats, A, Q, mu0, P0)
    quad_R, _ = quad_local(Y, Lam, R, xp, mask)
    ll = loglik_from_terms(stats, logdetG, Pf, quad_R, u_from_stats(stats, xp))
    return FilterResult(xp, Pp, xf, Pf, ll)


def info_filter(Y: jax.Array, p: SSMParams,
                mask: Optional[jax.Array] = None) -> FilterResult:
    """Single-call info-form filter: stats + scan + residual loglik pass."""
    p = p.astype(Y.dtype)
    stats = obs_stats(Y, p.Lam, p.R, mask=mask)
    return info_filter_from_stats(stats, p.A, p.Q, p.mu0, p.P0,
                                  Y=Y, Lam=p.Lam, R=p.R, mask=mask)


def info_filter_smoother(Y, p, mask=None):
    kf = info_filter(Y, p, mask=mask)
    return kf, rts_smoother(kf, p)


def loglik_eval(Y, p, mask=None, precise: bool = True) -> float:
    """Standalone reporting-grade log-likelihood evaluation.

    The in-loop f32 loglik that EM uses for convergence carries a relative
    noise floor of ~1e-5 at the 10k-series headline shape (the total is a
    ~100x-smaller residual of cancelling O(N T) pieces; measured against
    f64 with BIT-PERFECT params the f32 evaluation alone is 0.5-2e-5).
    ``precise=True`` re-evaluates the filter in float64 ON DEVICE (emulated
    on TPUs — ~0.6 s at 10k x 500 vs ~1 ms for the fast path; measured
    5e-13 relative against the NumPy f64 oracle), which is what the 1e-5
    contract of BASELINE.json:5 is checked with in ``bench.py``.  Requires
    ``jax_enable_x64``; falls back to the compute dtype with a warning
    otherwise.  Accepts NumPy or JAX params.
    """
    use_f64 = precise and jax.config.jax_enable_x64
    if precise and not use_f64:
        import warnings
        warnings.warn(
            "precise loglik_eval needs jax_enable_x64; evaluating in the "
            "compute dtype instead", RuntimeWarning, stacklevel=2)
    dtype = jnp.float64 if use_f64 else jnp.asarray(Y).dtype
    Yj = jnp.asarray(Y, dtype)
    pj = SSMParams(*(jnp.asarray(x, dtype) for x in
                     (p.Lam, p.A, p.Q, p.R, p.mu0, p.P0)))
    mj = jnp.asarray(mask, dtype) if mask is not None else None
    return float(_loglik_eval_impl(Yj, pj, mj, mask is not None))


@partial(jax.jit, static_argnames=("has_mask",))
def _loglik_eval_impl(Y, p, mask, has_mask):
    # NOTE: in float32 with a mask at the MF augmented shape (state dim
    # ~25, time-varying C) this loglik-only program SIGABRTs the axon TPU
    # compiler (TpuInstructionFusion::MergeFusionInstruction check failure,
    # 2026-07) — barriers and keeping the scan outputs alive do not dodge
    # it; the f64 program and the full fit-shaped programs compile fine.
    # ``models.mixed_freq.mf_loglik_eval`` therefore routes its fast path
    # through the fit's own E-step program instead of this one.
    return info_filter(Y, p, mask=mask if has_mask else None).loglik


@partial(jax.jit, static_argnames=("filter_fn", "has_mask"))
def smooth_jit(Y, mask, p, filter_fn, has_mask: bool):
    """One fused filter+smoother program returning (x_sm, P_sm).

    Eager composition costs one ~60-100 ms tunneled dispatch PER OP on this
    device class (~2 s for a single smooth, measured) — this is the jitted
    path ``TPUBackend.smooth`` uses.  ``filter_fn`` must be a module-level
    function (hashable jit static).
    """
    kf = filter_fn(Y, p, mask=mask if has_mask else None)
    sm = rts_smoother(kf, p)
    return sm.x_sm, sm.P_sm

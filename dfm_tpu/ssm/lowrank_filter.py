"""Rank-r computation-aware Kalman filter/smoother: the k-scalable path.

Every axis but the state dimension scales (N via the info form, T via
``pit_qr``, B via the scheduler/fleet); the exact k x k posterior algebra
is what caps k at ~10 — and the axon compiler SIGABRTs outright on the
m~25 mixed-frequency augmented program (CLAUDE.md).  Following
"Computation-Aware Kalman Filtering and Smoothing" (arXiv 2405.08971),
this engine conditions each step on only r <= k linear functionals of the
observation instead of the full information update, keeping the posterior
covariance as an exact-prediction + rank-r DOWNDATE:

    policy     V = top-r eigenvectors of C = Lam' R^{-1} Lam   (k, r)
               (the model's static observation information — the data
               directions the panel actually pins down; identical in the
               filter, the smoother, and the NumPy oracle, and the whole
               algorithm is invariant to V -> V B for invertible B, so
               eigh sign/order conventions are exactly inert)
    project    J_t = C_t V (k, r),  Gam_t = V'C_t V + eps I    (r, r)
    update     S_t = J_t' P J_t + Gam_t,      u_t = b_t - C_t x
               x_f = x + P J_t S_t^{-1} V'u_t
               P_f = P - (P J_t) S_t^{-1} (P J_t)'             (downdate)
    loglik     log|S_t| - log|Gam_t|  replaces  log|I + L'C_t L|
               z'(Gam^{-1} - S^{-1})z  replaces  u'(P^{-1}+C)^{-1}u
               (z = V'u — the quad of the SAME approximating Gaussian
               the determinant belongs to; see below)

The downdate is CONSERVATIVE (P_f here >= the exact P_f in the PSD order
— it is the posterior after observing r projections of the data, a
strictly coarser sigma-algebra), which is what keeps the reported
uncertainty bands honest: coverage can only widen, never silently
under-cover (the paper's calibration result; ``state_coverage`` below is
the bench hook).  At r = k any full-rank V reproduces the exact filter:
the gain collapses to P C (C P C + C)^{-1} = (I + P C)^{-1} P and
log|S| - log|Gam| = log|I + P C| (the eps regularization cancels even in
C-null directions, and a fully-masked step — C_t = 0 — is exactly inert
with logdetG_t = 0).

The reported loglik is itself a TRUE Gaussian log-density, not a plug-in:
with the oblique projector W = V Gam^{-1} J' the predictive covariance
S_apx = R + (Lam W) P (Lam W)' satisfies both
log|S_apx| = log|R| + log|S_r| - log|Gam|  (the determinant above) and
v' S_apx^{-1} v = v'R^{-1}v - z'(Gam^{-1} - S_r^{-1})z  (Woodbury), so
determinant and quadratic describe ONE well-defined density — bounded,
sane in magnitude, usable by the EM convergence guard at any r, and
exactly the full Woodbury identity at r = k.  (The naive plug-in
v'R^{-1}v - u'P_f u with the conservative P_f overshoots: P_f is LARGER
than the exact posterior covariance, so early steps with wide priors can
push the "loglik" to large positive garbage.)

Cost per step: the exact info scan pays a k x k Cholesky + solve
(O(k^3) in heavyweight linalg primitives); here the scan body holds ONLY
r x r factorizations — unrolled VPU form for r <= UNROLL_K_MAX, the
batched-small-linalg fix of docs/PERF.md item 6a — plus plain (k,k)@(k,r)
matmuls that sit at the op floor.  The A P A' predict keeps the O(k^2)
moments exact (this is the arXiv 1006.2165 moment-matching view: the
approximation lives solely in which observation functionals get
conditioned on).  The r x r smoother mirrors the structure: gains
G1 = P_f A'V, innovations solved in the projected Sigma = V'P_pred V
metric, rank-r covariance correction — exact at r = k since
V Sigma^{-1} V' = P_pred^{-1} for orthonormal full-rank V.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.linalg import (sym, default_jitter, chol_logdet, chol_small,
                          chol_solve_small)
from .info_filter import ObsStats, obs_stats, quad_local, _LOG2PI
from .params import SSMParams, FilterResult, SmootherResult

__all__ = ["DEFAULT_MAX_RANK", "resolve_rank", "policy_basis",
           "lowrank_from_stats", "lowrank_loglik_from_terms",
           "lowrank_filter", "lowrank_smoother",
           "lowrank_filter_smoother", "state_coverage"]

# Auto-rank cap: keeps the r x r work on the unrolled VPU path
# (ops.linalg.UNROLL_K_MAX) unless the caller asks for more.  Mirrors
# ``backends.cpu_ref.resolve_rank`` — the two must agree or the oracle
# parity tests compare different algorithms.
DEFAULT_MAX_RANK = 8


def resolve_rank(k: int, rank: int = 0) -> int:
    """rank <= 0 -> auto (min(k, DEFAULT_MAX_RANK)); else clamp to [1, k]."""
    if rank <= 0:
        return min(k, DEFAULT_MAX_RANK)
    return max(1, min(int(rank), int(k)))


def policy_basis(Lam: jax.Array, R: jax.Array, r: int) -> jax.Array:
    """Top-r eigenvectors of the static observation information (k, r).

    One k x k eigh per E-step — O(k^3) once, not per time step.  eigh
    returns ascending eigenvalues; reverse for the dominant directions.
    """
    C = sym((Lam * (1.0 / R)[:, None]).T @ Lam)
    _, vecs = jnp.linalg.eigh(C)
    return vecs[:, ::-1][:, :r]


def lowrank_from_stats(stats: ObsStats, p: SSMParams, rank: int = 0):
    """Rank-r scan given precomputed observation stats.

    Contract of ``info_scan``/``pit_qr_from_stats`` plus one output:
    returns (x_pred, P_pred, x_filt, P_filt, logdetG (T,), corr (T,))
    with logdetG_t the low-rank part of log|S_apx,t| — here
    log|S_t^r| - log|Gam_t|, which at r = k equals the exact
    log|I + L'C_t L| — and corr_t = z'(Gam^{-1} - S^{-1})z the matching
    quadratic correction (module docstring): the per-step loglik is
    assembled as quad_R,t - corr_t by ``lowrank_loglik_from_terms``.
    corr_t >= 0 always (S >= Gam in the PSD order) and a fully-masked
    step contributes exactly 0.
    """
    dtype = stats.b.dtype
    T = stats.b.shape[0]
    k = p.A.shape[0]
    r = resolve_rank(k, rank)
    eps = default_jitter(dtype)
    I_r = jnp.eye(r, dtype=dtype)
    V = policy_basis(p.Lam, p.R, r).astype(dtype)
    A, Q = p.A, p.Q

    if stats.C.ndim == 2:
        # Time-invariant precision: one projection, broadcast into the scan.
        J = stats.C @ V                                     # (k, r)
        Gam = sym(V.T @ J) + eps * I_r
        Lg = chol_small(Gam)
        ldg = chol_logdet(Lg)
        Ginv = chol_solve_small(Lg, I_r)
        C_seq = jnp.broadcast_to(stats.C, (T, k, k))
        J_seq = jnp.broadcast_to(J, (T, k, r))
        Gam_seq = jnp.broadcast_to(Gam, (T, r, r))
        Ginv_seq = jnp.broadcast_to(Ginv, (T, r, r))
        ldg_seq = jnp.broadcast_to(ldg, (T,))
    else:
        # Masked: batched projections — contractions over the k axis are
        # real matmuls (large contracted axis); only the r x r chol below
        # is small-matrix work, and it runs ONCE outside the scan.
        C_seq = stats.C
        J_seq = jnp.einsum("tkl,lr->tkr", stats.C, V)
        Gam_seq = sym(jnp.einsum("lr,tls->trs", V, J_seq)) + eps * I_r
        Lg_seq = chol_small(Gam_seq)
        ldg_seq = chol_logdet(Lg_seq)
        Ginv_seq = chol_solve_small(
            Lg_seq, jnp.broadcast_to(I_r, (T, r, r)))

    def step(carry, inp):
        x, P = carry
        b_t, C_t, J_t, Gam_t, Ginv_t, ldg_t = inp
        u = b_t - C_t @ x
        z = V.T @ u                                         # (r,)
        PJ = P @ J_t                                        # (k, r)
        S = sym(J_t.T @ PJ) + Gam_t                         # eps rides Gam_t
        Ls = chol_small(S)
        a = chol_solve_small(Ls, z)
        x_f = x + PJ @ a
        P_f = sym(P - PJ @ chol_solve_small(Ls, PJ.T))      # rank-r downdate
        ld = chol_logdet(Ls) - ldg_t
        # Consistent quad piece of the SAME approximating Gaussian the
        # determinant belongs to (module docstring): z'(Gam^{-1}-S^{-1})z.
        # Gam^{-1} is hoisted out of the scan and z'S^{-1}z reuses the
        # mean-update solve, so the whole correction is one r x r matvec.
        corr = z @ (Ginv_t @ z) - z @ a
        x_n = A @ x_f
        P_n = sym(A @ P_f @ A.T + Q)
        return (x_n, P_n), (x, P, x_f, P_f, ld, corr)

    return lax.scan(step, (p.mu0, p.P0),
                    (stats.b, C_seq, J_seq, Gam_seq, Ginv_seq, ldg_seq))[1]


def lowrank_loglik_from_terms(stats: ObsStats, logdetG, corr, quad_R):
    """Assemble sum_t ll_t from the rank-r scan's (logdetG, corr) series
    and the residual-pass quad_R — the ``loglik_from_terms`` twin with the
    u'P_f u plug-in replaced by the consistent subspace correction (the
    two coincide at r = k).  Same precision policy: the (T,)-sized
    assembly of cancelling pieces upgrades to the accumulation dtype."""
    from ..ops.precision import accum_dtype
    acc = accum_dtype(stats.b.dtype)
    quad = quad_R.astype(acc) - corr.astype(acc)
    lls = -0.5 * (stats.n.astype(acc) * _LOG2PI + stats.ldR.astype(acc)
                  + logdetG.astype(acc) + quad)
    return jnp.sum(lls)


def lowrank_filter(Y: jax.Array, p: SSMParams,
                   mask: Optional[jax.Array] = None,
                   rank: int = 0) -> FilterResult:
    """Rank-r computation-aware filter; contract of ``info_filter`` (the
    loglik is the exact Gaussian log-density of the rank-r approximating
    predictive — module docstring; exact at r = k — with quad_R from the
    same cancellation-free residual pass)."""
    p = p.astype(Y.dtype)
    stats = obs_stats(Y, p.Lam, p.R, mask=mask)
    xp, Pp, xf, Pf, logdetG, corr = lowrank_from_stats(stats, p, rank)
    quad_R, _ = quad_local(Y, p.Lam, p.R, xp, mask)
    ll = lowrank_loglik_from_terms(stats, logdetG, corr, quad_R)
    return FilterResult(xp, Pp, xf, Pf, ll)


def lowrank_smoother(kf: FilterResult, p: SSMParams,
                     rank: int = 0) -> SmootherResult:
    """Rank-r RTS smoother; contract of ``rts_smoother`` (P_lag row 0 is
    zeros).  The backward gain is restricted to the policy subspace:
    J_t ~= G1_t Sigma_t^{-1} V' with G1_t = P_f,t A'V and
    Sigma_t = V'P_pred,t+1 V + eps I — only r x r solves in the scan."""
    dtype = kf.x_filt.dtype
    p = p.astype(dtype)
    T, k = kf.x_filt.shape
    r = resolve_rank(k, rank)
    eps = default_jitter(dtype)
    I_r = jnp.eye(r, dtype=dtype)
    V = policy_basis(p.Lam, p.R, r).astype(dtype)
    AV = p.A.T @ V                                          # (k, r)

    # Batched precompute (T-1 leading): k-contractions as real matmuls,
    # r x r factorization on the small-matrix path.
    Pp1 = kf.P_pred[1:]
    Sig = sym(jnp.einsum("lr,tlm,ms->trs", V, Pp1, V)) + eps * I_r
    Lsig = chol_small(Sig)
    G1 = jnp.einsum("tkl,lr->tkr", kf.P_filt[:-1], AV)

    def step(carry, inp):
        x_sm_n, P_sm_n = carry
        x_f, P_f, x_p_n, G1_t, Lsig_t, Sig_t = inp
        a = chol_solve_small(Lsig_t, V.T @ (x_sm_n - x_p_n))
        x_sm = x_f + G1_t @ a
        # E = V'(P_sm,t+1 - P_pred,t+1)V; Sig already carries +eps I.
        E = V.T @ P_sm_n @ V - Sig_t + eps * I_r
        S = chol_solve_small(Lsig_t, chol_solve_small(Lsig_t, E).T).T
        P_sm = sym(P_f + G1_t @ sym(S) @ G1_t.T)
        return (x_sm, P_sm), (x_sm, P_sm)

    init = (kf.x_filt[-1], kf.P_filt[-1])
    _, (x_head, P_head) = lax.scan(
        step, init,
        (kf.x_filt[:-1], kf.P_filt[:-1], kf.x_pred[1:], G1, Lsig, Sig),
        reverse=True)
    x_sm = jnp.concatenate([x_head, kf.x_filt[-1:]], axis=0)
    P_sm = jnp.concatenate([P_head, kf.P_filt[-1:]], axis=0)

    # Lag-one covariance P_sm,t J_{t-1}' in the rank-r gain:
    # P_sm,t V Sigma_{t-1}^{-1} (V'A P_f,t-1) — exactly P_sm J' at r = k.
    Minv = chol_solve_small(Lsig, jnp.broadcast_to(I_r, (T - 1, r, r)))
    PV = jnp.einsum("tkl,lr->tkr", P_sm[1:], V)
    P_lag_tail = jnp.einsum("tkr,trs,tls->tkl", PV, Minv, G1)
    P_lag = jnp.concatenate(
        [jnp.zeros((1, k, k), dtype), P_lag_tail], axis=0)
    return SmootherResult(x_sm, P_sm, P_lag)


def lowrank_filter_smoother(Y, p, mask=None, rank: int = 0):
    kf = lowrank_filter(Y, p, mask=mask, rank=rank)
    return kf, lowrank_smoother(kf, p, rank=rank)


def state_coverage(x, P, truth, z: float = 1.6448536269514722) -> float:
    """Empirical z-interval coverage of a state trajectory (jax-free).

    Fraction of (t, i) cells with |truth - x| <= z * sqrt(diag P) — the
    calibration hook of arXiv 2405.08971: at the nominal z (90% two-sided
    by default) the exact smoother covers ~0.90, and the conservative
    rank-r downdate can only match or widen.  ``bench.kscale`` reports
    |coverage - nominal| as ``kscale_calib_err``.
    """
    x = np.asarray(x, dtype=np.float64)
    truth = np.asarray(truth, dtype=np.float64)
    sd = np.sqrt(np.maximum(
        np.diagonal(np.asarray(P, dtype=np.float64), axis1=-2, axis2=-1),
        0.0))
    return float(np.mean(np.abs(truth - x) <= z * sd))

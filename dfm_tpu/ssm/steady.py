"""Steady-state accelerated Kalman filter/smoother (the headline speed path).

For time-invariant, fully-observed panels the covariance recursion
P -> A[(P^{-1}+C)^{-1}]A' + Q is DATA-INDEPENDENT and converges geometrically
to the DARE fixed point, so almost all of the sequential scan the exact
filter pays for is spent recomputing numbers that stopped changing.  This
module exploits that:

  1. Run the exact covariance recursion for ``tau`` steps only (lax.scan);
     freeze (P_pred, P_filt, logdetG, gain) at their step-tau values for
     t >= tau.  The freeze error decays like rho(A_closed)^(2 tau) — a
     convergence diagnostic (relative last-step change) is returned.
  2. The filtered-mean recursion x_f[t] = M_t x_f[t-1] + P_f[t] b_t now has
     piecewise-constant coefficients: a short sequential vector scan covers
     the tau exact-coefficient steps, and the frozen tail runs as a
     log-depth shift-doubling prefix (``ops.scan.affine_const_prefix`` —
     each round is ONE (T, k) x (k, k) batched matmul; no (k, k) prefix
     products, no factorizations anywhere on the T axis).
  3. The smoother reuses the trick backward: the smoothed covariance solves
     a fixed-point equation in the interior (iterated tau steps from the
     end), with exact boundary passes of length tau at both edges; smoothed
     means are the same doubling-plus-short-scan in reverse; the
     log-likelihood is the same batched residual pass as ``info_filter``.

Sequential depth drops from 2T (filter + smoother) to ~3 tau + O(log T)
regardless of T.  Masked panels and T <= 2 tau + 4 fall back to the exact
sequential path automatically (shape-level Python branch, resolved at trace
time).  Select with ``EMConfig(filter="ss")`` / ``TPUBackend(filter="ss")``.

Exactness: NOT bit-exact — equivalence to the exact filter holds to the
covariance-convergence tolerance (tested at ~1e-8 relative loglik for
tau=96 on a rho=0.7 DGP; grows toward 1e-5 only for very slowly mixing
dynamics — raise ``tau`` in that regime).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.linalg import sym, psd_cholesky, chol_solve, chol_logdet
from ..ops.scan import affine_const_prefix
from .info_filter import (obs_stats, info_filter, loglik_from_terms,
                          quad_expanded, quad_local, u_from_stats)
from .kalman import rts_smoother
from .params import SSMParams, FilterResult, SmootherResult

__all__ = ["ss_filter", "ss_smoother", "ss_filter_smoother", "ss_from_stats",
           "riccati_mixing_steps", "auto_tau", "remeasure_tau", "DEFAULT_TAU"]

DEFAULT_TAU = 96


def riccati_mixing_steps(p, tol: float = 1e-12, max_steps: int = 512) -> int:
    """Steps until the predicted-covariance recursion stops moving.

    Host-side NumPy f64 (k x k per step — microseconds): the Riccati path
    P -> A (P^{-1} + C)^{-1} A' + Q is data-independent, so its mixing time
    can be measured once at the entry params and used to size ``tau``
    (see ``auto_tau``).  ``p`` is any params object with Lam/A/Q/R/P0.
    """
    import numpy as np
    Lam = np.asarray(p.Lam, np.float64)
    A = np.asarray(p.A, np.float64)
    Q = np.asarray(p.Q, np.float64)
    C = (Lam / np.asarray(p.R, np.float64)[:, None]).T @ Lam
    k = A.shape[0]
    P = np.asarray(p.P0, np.float64)
    for t in range(1, max_steps + 1):
        Pf = np.linalg.solve(np.eye(k) + P @ C, P)
        Pn = A @ (0.5 * (Pf + Pf.T)) @ A.T + Q
        if np.max(np.abs(Pn - P)) <= tol * max(np.max(np.abs(Pn)), 1e-30):
            return t
        P = Pn
    return max_steps


def auto_tau(p, margin: float = 2.0, lo: int = 8, hi: int = 192) -> int:
    """Data-driven steady-state horizon: ``margin`` x the measured mixing
    time at the entry params (the margin covers parameter drift across EM
    iterations), bucketed to powers-of-two-ish values so repeated fits hit
    the jit cache instead of recompiling per panel.  The ss freeze
    diagnostic (``warn_ss_delta``) still guards the choice at runtime."""
    import numpy as np
    tau = margin * riccati_mixing_steps(p)
    for b in (8, 12, 16, 24, 32, 48, 64, 96, 128, 192):
        if b >= lo and tau <= b:
            return int(min(b, hi))
    return hi


def remeasure_tau(p, current_tau: int, margin: float = 2.0,
                  hi: int = 192) -> int:
    """Re-size ``tau`` at the CURRENT params (not the entry params).

    ``auto_tau`` is measured once at the warm start; EM can drift the
    dynamics toward slower mixing until the freeze delta trips the runtime
    diagnostic.  This re-measures the Riccati mixing time where the fit
    actually is and returns a tau covering it — never smaller than
    ``current_tau``, so a return value equal to ``current_tau`` means
    "a longer freeze horizon cannot help; change engines instead"
    (the guard then falls back ss -> info).
    """
    return max(int(current_tau),
               auto_tau(p, margin=margin, lo=int(current_tau), hi=hi))


def _affine_combine(earlier, later):
    """(M, d) semigroup: apply earlier first.  x -> M_l (M_e x + d_e) + d_l.

    No longer on the hot path (the mean recursions use
    ``affine_const_prefix`` since the doubling change) but kept for the
    ``bench.profile`` subcommands (components/slope/ablate), which
    decompose the old blocked-scan formulation piece by piece.
    """
    Me, de = earlier
    Ml, dl = later
    return (Ml @ Me, jnp.einsum("...kl,...l->...k", Ml, de) + dl)


def _cov_path(C, A, Q, P0, tau, dtype):
    """tau exact covariance steps; returns per-step (P_pred, P_filt, M,
    logdetG) stacked plus a convergence diagnostic."""
    k = A.shape[0]
    I_k = jnp.eye(k, dtype=dtype)
    CA = C @ A       # loop-invariant: M = (I - P_f C) A = A - P_f (C A)

    def step(P, _):
        Lp = psd_cholesky(P)
        G = I_k + Lp.T @ (C @ Lp)
        Lg = psd_cholesky(G, jitter=0.0)
        P_f = sym(Lp @ chol_solve(Lg, Lp.T))
        M = A - P_f @ CA
        P_next = sym(A @ P_f @ A.T + Q)
        return P_next, (P, P_f, M, chol_logdet(Lg))

    P_last, (Pp, Pf, M, ldG) = lax.scan(step, P0, None, length=tau)
    # Relative change of the last predicted covariance step.
    delta = jnp.max(jnp.abs(P_last - Pp[-1])) / (
        jnp.max(jnp.abs(P_last)) + 1e-30)
    return Pp, Pf, M, ldG, delta


def _freeze(path, T, tau):
    """Piecewise array: exact first tau entries then the step-tau value."""
    tail = jnp.broadcast_to(path[-1], (T - tau,) + path.shape[1:])
    return jnp.concatenate([path, tail], axis=0)


def ss_from_stats(stats, p: SSMParams, T: int, tau: int):
    """The replicated k x k part of the steady-state pass, from GLOBAL stats.

    Everything below depends on the panel only through ``stats`` (already
    psum'd under sharding — see ``parallel.sharded``), so every device runs it
    identically.  Returns (x_pred, P_pred, x_filt, P_filt, logdetG, sm,
    delta); the innovation-quadratic loglik pieces are NOT computed here —
    callers run ``quad_local`` on their (local) panel block, take U from
    ``u_from_stats``, and assemble with ``loglik_from_terms``.
    """
    dtype = stats.b.dtype
    k = p.A.shape[0]
    C = stats.C
    Pp_ex, Pf_ex, M_ex, ldG_ex, delta = _cov_path(
        C, p.A, p.Q, p.P0, tau, dtype)
    P_pred = _freeze(Pp_ex, T, tau)
    P_filt = _freeze(Pf_ex, T, tau)
    M_path = _freeze(M_ex, T, tau)
    logdetG = _freeze(ldG_ex, T, tau)

    # Filtered means: x_f[0] from the prior update; then
    # x_f[t] = M_t x_f[t-1] + P_f[t] b_t with M_t EXACT for t < tau and
    # CONSTANT after — a short sequential vector scan over the exact head
    # plus the log-depth doubling prefix over the frozen tail (faster than
    # composing (k,k) affine elements with ``blocked_scan`` over all T:
    # ~tau + log2(T) batched steps and only vector carries).
    b = stats.b
    x0 = p.mu0 + Pf_ex[0] @ (b[0] - C @ p.mu0)
    d = jnp.einsum("tkl,tl->tk", P_filt[1:], b[1:])          # (T-1, k)

    def vstep(x, inp):
        M_t, d_t = inp
        x_new = M_t @ x + d_t
        return x_new, x_new

    if tau > 1:
        x_h_last, x_head = lax.scan(vstep, x0, (M_ex[1:], d[:tau - 1]))
    else:
        x_h_last, x_head = x0, jnp.zeros((0, k), dtype)
    x_tail = affine_const_prefix(M_ex[-1], d[tau - 1:], x_h_last)
    x_filt = jnp.concatenate([x0[None], x_head, x_tail], axis=0)
    x_pred = jnp.concatenate([p.mu0[None], x_filt[:-1] @ p.A.T], axis=0)

    # ----- smoother -----
    # Gains: exact for t < tau, steady after (J_t depends only on P path).
    Lp_ex = psd_cholesky(Pp_ex[1:])                          # P_pred[1..tau-1]
    APf_ex = jnp.einsum("ij,tjk->tik", p.A, Pf_ex[:-1])
    J_ex = jnp.swapaxes(jax.vmap(chol_solve)(Lp_ex, APf_ex), -1, -2)
    Lp_ss = psd_cholesky(Pp_ex[-1])
    J_ss = chol_solve(Lp_ss, p.A @ Pf_ex[-1]).T
    J = jnp.concatenate(
        [J_ex, jnp.broadcast_to(J_ss, (T - tau, k, k))], axis=0)  # (T-1,k,k)

    # Smoothed covariances: iterate backward from the end with J_ss for tau
    # steps (this IS the exact end-boundary path since P_filt is steady
    # there), converging to the interior fixed point...
    Pp_ss, Pf_ss = Pp_ex[-1], Pf_ex[-1]

    def bstep_ss(Ps, _):
        Ps_new = sym(Pf_ss + J_ss @ (Ps - Pp_ss) @ J_ss.T)
        return Ps_new, Ps_new

    Ps_mid, Psm_end_rev = lax.scan(bstep_ss, Pf_ss, None, length=tau)
    Psm_end = jnp.flip(Psm_end_rev, axis=0)      # P_sm[T-1-tau .. T-2]
    # ...then the exact front boundary t = tau-1 .. 0 with the exact J path.
    def bstep_ex(Ps, inp):
        P_f_t, P_p_next, J_t = inp
        Ps_new = sym(P_f_t + J_t @ (Ps - P_p_next) @ J_t.T)
        return Ps_new, Ps_new

    # P_pred[t+1] for t = 0..tau-1: the exact path shifted, last entry frozen.
    Pp_next_ex = jnp.concatenate([Pp_ex[1:], Pp_ex[-1:]], axis=0)
    _, Psm_front_rev = lax.scan(
        bstep_ex, Ps_mid, (Pf_ex, Pp_next_ex, J[:tau]), reverse=True)
    # Assemble: [front (tau), interior steady, end (tau), P_f at T-1].
    n_mid = T - 1 - 2 * tau
    P_sm = jnp.concatenate([
        Psm_front_rev,
        jnp.broadcast_to(Ps_mid, (n_mid, k, k)),
        Psm_end,
        Pf_ss[None],
    ], axis=0)

    # Smoothed means, x_sm[t] = J_t x_sm[t+1] + c_t backward from t = T-2:
    # in reversed time the coefficient is J_ss for the first T-tau steps
    # (J[t] is frozen for t >= tau-1) and exact for the final tau-1 — the
    # same doubling-plus-short-scan structure as the filtered means.
    c = x_filt[:-1] - jnp.einsum("tkl,tl->tk", J, x_pred[1:])
    c_rev = jnp.flip(c, axis=0)                   # c_rev[s-1] = c[T-1-s]
    y_const = affine_const_prefix(J_ss, c_rev[: T - tau], x_filt[-1])
    if tau > 1:
        _, y_exact = lax.scan(vstep, y_const[-1],
                              (jnp.flip(J_ex, axis=0), c_rev[T - tau:]))
        ys = jnp.concatenate([y_const, y_exact], axis=0)
    else:
        ys = y_const
    x_sm = jnp.concatenate([jnp.flip(ys, axis=0), x_filt[-1:]], axis=0)

    P_lag_tail = jnp.einsum("tij,tkj->tik", P_sm[1:], J)
    P_lag = jnp.concatenate([jnp.zeros((1, k, k), dtype), P_lag_tail],
                            axis=0)
    return (x_pred, P_pred, x_filt, P_filt, logdetG,
            SmootherResult(x_sm, P_sm, P_lag), delta)


def ss_filter_smoother(Y: jax.Array, p: SSMParams, tau: int = DEFAULT_TAU,
                       mask: Optional[jax.Array] = None, sumsq=None
                       ) -> Tuple[FilterResult, SmootherResult, jax.Array]:
    """Filter + smoother with steady-state acceleration.

    Returns (FilterResult, SmootherResult, convergence_diagnostic).  Falls
    back to the exact sequential pair when masked or T <= 2 tau + 4 (the
    diagnostic is then 0).

    ``sumsq``: optional precomputed Y*Y (T, N) — data-constant, so fused EM
    drivers hoist it out of the iteration loop.  When provided AND the
    accum dtype upgrades (x64 on), the loglik quadratic uses the expanded
    form (one matvec over ``sumsq`` instead of a residual matmul pass — see
    ``info_filter.quad_expanded`` for why this needs the f64 assembly).
    """
    T = Y.shape[0]
    # tau <= 0 (a caller computing its own horizon from short windows can
    # land there) must not reach the ss path: a zero-length exact-tail scan
    # and a freeze at the prior are both wrong.  It means "no steady-state
    # horizon" — route to the exact pair, same as masked/short panels.
    tau = int(tau)
    if mask is not None or tau < 1 or T <= 2 * tau + 4:
        kf = info_filter(Y, p, mask=mask)
        return kf, rts_smoother(kf, p), jnp.zeros((), Y.dtype)

    p = p.astype(Y.dtype)
    stats = obs_stats(Y, p.Lam, p.R)         # C static, b (T, k)
    x_pred, P_pred, x_filt, P_filt, logdetG, sm, delta = ss_from_stats(
        stats, p, T, tau)
    from ..ops.precision import accum_dtype
    if sumsq is not None and accum_dtype(Y.dtype) != Y.dtype:
        quad_R = quad_expanded(sumsq, 1.0 / p.R, stats, x_pred)
    else:
        quad_R, _ = quad_local(Y, p.Lam, p.R, x_pred, None)
    ll = loglik_from_terms(stats, logdetG, P_filt, quad_R,
                           u_from_stats(stats, x_pred))
    return FilterResult(x_pred, P_pred, x_filt, P_filt, ll), sm, delta


def ss_filter(Y, p, mask=None, tau: int = DEFAULT_TAU) -> FilterResult:
    return ss_filter_smoother(Y, p, tau=tau, mask=mask)[0]


def ss_smoother(Y, p, mask=None, tau: int = DEFAULT_TAU) -> SmootherResult:
    return ss_filter_smoother(Y, p, tau=tau, mask=mask)[1]

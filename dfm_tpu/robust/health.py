"""Per-fit health records.

A ``FitHealth`` is collected host-side by the guarded chunk loop (one
update per fused chunk — never per iteration, so the device hot path is
untouched) and attached to the fit result.  ``ok`` distinguishes "clean
fit" from "fit that needed intervention"; the ``events`` list is the
forensic trail.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

__all__ = ["HealthEvent", "FitHealth", "health_from_trace"]

# Event kinds the guard emits:
#   nan_loglik      non-finite loglik in a chunk
#   divergence      loglik drop beyond the noise floor
#   freeze_drift    ss freeze delta above the policy threshold
#   stall           successive chunks wiggling inside the noise floor
#   nonpsd          Q or P0 lost positive semi-definiteness
#   r_floor         R entries pinned at the EM floor
#   nonfinite_params  NaN/inf in the parameter pytree itself
#   dispatch_error  device dispatch raised (tunnel error / timeout)
# The live plane (obs/live.py) adds:
#   slo_burn        SLO error-budget burn crossed fire/clear hysteresis
#   latency_anomaly p99 spike vs the rolling baseline
# The serving daemon (dfm_tpu/daemon/) adds:
#   shed            overload load-shed: a request rejected while the SLO
#                   burn signal fired (lowest-priority tenants first)
#   handoff         blue/green listener handoff (detail carries gap_ms)


@dataclasses.dataclass
class HealthEvent:
    """One observed pathology and what the guard did about it."""

    chunk: int          # fused-chunk index (0-based)
    iteration: int      # EM iteration count at the chunk entry
    kind: str
    detail: str = ""
    action: str = "none"   # retried | restored | repaired | remeasure_tau
    #                      # | fallback_info | loglik_f64 | stopped | abort
    t: float = 0.0      # time.perf_counter() at record time (0 = unstamped);
    #                   # monotonic, comparable to obs.trace event times
    engine: str = ""    # emitting engine ("tpu_em", "batched_em", ...)
    tenant: str = ""    # fit_jobs tenant id (multi-tenant attribution)
    session: str = ""   # NowcastSession id (serving attribution)
    backoff_s: float = 0.0  # sleep charged to this event before the retry
    trace_id: str = ""  # request trace this pathology struck (obs.trace)

    def __str__(self) -> str:
        eng = f" {self.engine}" if self.engine else ""
        who = ""
        if self.tenant:
            who += f" tenant={self.tenant}"
        if self.session:
            who += f" session={self.session}"
        return (f"[chunk {self.chunk} it {self.iteration}]{eng}{who} "
                f"{self.kind} -> {self.action}"
                + (f" ({self.detail})" if self.detail else ""))


@dataclasses.dataclass
class FitHealth:
    """Aggregate health of one EM run (attached to ``FitResult.health``)."""

    n_chunks: int = 0
    n_dispatch_retries: int = 0
    n_recoveries: int = 0
    max_ss_delta: float = 0.0
    monotonicity_violations: int = 0
    r_floor_hits: int = 0
    nonpsd_events: int = 0
    stalled: bool = False
    escalations: List[str] = dataclasses.field(default_factory=list)
    events: List[HealthEvent] = dataclasses.field(default_factory=list)
    fallback_backend: Optional[str] = None
    engine: str = ""    # default engine name stamped onto recorded events

    @property
    def ok(self) -> bool:
        """True iff the fit needed no intervention of any kind."""
        return (not self.events and not self.escalations
                and self.fallback_backend is None and not self.stalled)

    def record(self, event: HealthEvent, emit: bool = True) -> HealthEvent:
        """Record ``event`` (stamping time/engine) and, when a tracer is
        active and ``emit`` is true, mirror it into the telemetry stream.
        ``emit=False`` is for replaying an already-emitted event into
        additional health records (the batched engine fans dispatch events
        out to every problem's health)."""
        if event.t == 0.0:
            event.t = time.perf_counter()
        if not event.engine:
            event.engine = self.engine
        self.events.append(event)
        if event.kind == "nonpsd":
            self.nonpsd_events += 1
        if event.action in ("restored", "repaired", "retried"):
            self.n_recoveries += 1
        if emit:
            from ..obs.trace import current_tracer
            tr = current_tracer()
            extra = {}
            # Attribution/backoff keys ride along only when set, so
            # pre-existing trace payloads stay byte-identical.
            if event.tenant:
                extra["tenant"] = event.tenant
            if event.session:
                extra["session"] = event.session
            if event.backoff_s:
                extra["backoff_s"] = event.backoff_s
            if event.trace_id:
                extra["trace_id"] = event.trace_id
            if tr is not None:
                tr.emit("health", t=event.t, event=event.kind,
                        chunk=event.chunk, iteration=event.iteration,
                        action=event.action, detail=event.detail,
                        engine=event.engine, **extra)
            else:
                # Untraced: the always-on live plane still accounts for
                # retries/quarantines (same payload the tracer mirrors).
                from ..obs.live import observe as live_observe
                live_observe({"t": event.t, "kind": "health",
                              "event": event.kind, "chunk": event.chunk,
                              "iteration": event.iteration,
                              "action": event.action,
                              "detail": event.detail,
                              "engine": event.engine, **extra})
        return event

    def escalate(self, action: str) -> None:
        self.escalations.append(action)

    def summary(self) -> str:
        if self.ok:
            return f"healthy ({self.n_chunks} chunks)"
        bits = [f"{len(self.events)} events"]
        if self.escalations:
            bits.append("escalations: " + ",".join(self.escalations))
        if self.fallback_backend:
            bits.append(f"fell back to {self.fallback_backend}")
        if self.stalled:
            bits.append("stalled")
        return "; ".join(bits)


def health_from_trace(lls, noise_floor: float = 0.0,
                      max_ss_delta: float = 0.0,
                      engine: str = "") -> FitHealth:
    """Post-hoc health record from a loglik trace.

    The family drivers (MF/TVL/SV) run their own fused loops without the
    full chunk guard; this gives their results the same ``health`` surface
    from the information the loop already has on host — finite-loglik and
    monotonicity checks plus the ss freeze delta where the engine reports
    one.  No device work.
    """
    import numpy as np
    h = FitHealth(engine=engine)
    a = np.asarray(lls, np.float64)
    for i in np.flatnonzero(~np.isfinite(a))[:8]:
        h.record(HealthEvent(chunk=-1, iteration=int(i), kind="nan_loglik",
                             detail="non-finite loglik in trace"))
    if a.size >= 2:
        drops = a[:-1] - a[1:]
        with np.errstate(invalid="ignore"):
            h.monotonicity_violations = int(np.sum(drops > noise_floor))
    h.max_ss_delta = float(max_ss_delta)
    return h

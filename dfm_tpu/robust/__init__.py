"""Guarded fit: health-monitored chunked EM with automatic recovery.

The chunked EM drivers dispatch one fused XLA program per chunk and only
see the loglik trace on the host between dispatches — exactly the place a
health monitor can live without touching the hot path.  This package
supplies that monitor, and (since the serving stack landed) the unified
dispatch guard every one-shot program goes through:

- ``health``  — ``FitHealth`` / ``HealthEvent`` records attached to results.
- ``guard``   — ``RobustPolicy`` (knobs), ``GuardControls`` (backend hooks),
  ``guarded_run_em_chunked`` (the monitored loop ``estim.em.run_em_chunked``
  delegates to when a monitor is passed), ``GuardFailure`` (carries the last
  good params out for graceful degradation).
- ``dispatch`` — ``guarded_dispatch``, the shared retry/backoff/watchdog
  seam around every dispatch site: the chunked ``_dispatch``, the fused
  fit, the scheduler bucket programs, and ``session.update`` all route
  their dispatch + blocking d2h read through it.
- ``faults``  — deterministic fault injection for testing every recovery
  path on the fake CPU mesh (NaN-poisoned chunks, dispatch exceptions,
  hung transfers, non-PSD parameter corruption, forced freeze drift).
"""

from .health import FitHealth, HealthEvent, health_from_trace
from .guard import (ChunkMonitor, GuardControls, GuardFailure, RobustPolicy,
                    check_param_health, guarded_run_em_chunked, repair_params)
from .dispatch import guarded_dispatch
from .faults import FaultInjector, InjectedDispatchError

__all__ = [
    "FitHealth", "HealthEvent", "health_from_trace",
    "ChunkMonitor", "GuardControls", "GuardFailure", "RobustPolicy",
    "check_param_health", "guarded_run_em_chunked", "repair_params",
    "guarded_dispatch",
    "FaultInjector", "InjectedDispatchError",
]

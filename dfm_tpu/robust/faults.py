"""Deterministic fault injection for the guarded EM loop.

An injector wraps a chunk ``scan_fn`` (via ``RobustPolicy.wrap_scan``) and
perturbs specific dispatches by CALL INDEX, so every recovery path is
reproducible on the fake CPU mesh without real hardware faults:

- ``nan_chunk(at)``           — poison the logliks of dispatch #at with NaN
- ``dispatch_failure(at, count)`` — raise ``InjectedDispatchError`` for
  ``count`` consecutive dispatches starting at #at (count=-1: forever)
- ``nonpsd_params(at)``       — corrupt the returned Q to non-PSD
- ``freeze_drift(at, count, delta)`` — force the reported ss freeze deltas
  above threshold for ``count`` dispatches

Call indices count EVERY dispatch the guard makes (including retries and
replays), which is what makes one-shot faults recoverable: the retry is a
new call index and passes clean.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

__all__ = ["InjectedDispatchError", "FaultInjector"]


class InjectedDispatchError(RuntimeError):
    """Stands in for an axon tunnel / PJRT dispatch failure."""


class FaultInjector:
    def __init__(self):
        self.calls = 0
        self.log: List[Tuple[int, str]] = []
        self._faults: Dict[int, List[tuple]] = {}
        self._persistent_fail_from = None

    def _plan(self, at: int, fault: tuple) -> "FaultInjector":
        self._faults.setdefault(int(at), []).append(fault)
        return self

    def nan_chunk(self, at: int) -> "FaultInjector":
        return self._plan(at, ("nan",))

    def dispatch_failure(self, at: int, count: int = 1) -> "FaultInjector":
        if count < 0:
            self._persistent_fail_from = int(at)
            return self
        for j in range(count):
            self._plan(at + j, ("raise",))
        return self

    def nonpsd_params(self, at: int) -> "FaultInjector":
        return self._plan(at, ("nonpsd",))

    def freeze_drift(self, at: int, count: int = 1,
                     delta: float = 1e-2) -> "FaultInjector":
        for j in range(count):
            self._plan(at + j, ("drift", delta))
        return self

    def wrap(self, scan_fn):
        """The ``RobustPolicy.wrap_scan`` callable."""

        def wrapped(p, n):
            idx = self.calls
            self.calls += 1
            faults = list(self._faults.get(idx, ()))
            if (self._persistent_fail_from is not None
                    and idx >= self._persistent_fail_from):
                faults.append(("raise",))
            for f in faults:
                if f[0] == "raise":
                    self.log.append((idx, "raise"))
                    raise InjectedDispatchError(
                        f"injected dispatch failure at call {idx}")
            p_new, lls, deltas = scan_fn(p, n)
            for f in faults:
                if f[0] == "nan":
                    self.log.append((idx, "nan"))
                    lls = np.full(np.shape(lls), np.nan)
                elif f[0] == "nonpsd":
                    self.log.append((idx, "nonpsd"))
                    Qr = np.asarray(p_new.Q)
                    Q = np.asarray(Qr, np.float64)
                    Q = Q - 10.0 * np.eye(Q.shape[0])
                    p_new = p_new._replace(Q=np.asarray(Q, Qr.dtype))
                elif f[0] == "drift":
                    self.log.append((idx, "drift"))
                    deltas = np.full(
                        np.shape(lls) if deltas is None else
                        np.shape(deltas), float(f[1]))
            return p_new, lls, deltas

        return wrapped

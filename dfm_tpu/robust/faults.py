"""Deterministic fault injection for the guarded EM loop.

An injector wraps a chunk ``scan_fn`` (via ``RobustPolicy.wrap_scan``) and
perturbs specific dispatches by CALL INDEX, so every recovery path is
reproducible on the fake CPU mesh without real hardware faults:

- ``nan_chunk(at)``           — poison the logliks of dispatch #at with NaN
- ``dispatch_failure(at, count)`` — raise ``InjectedDispatchError`` for
  ``count`` consecutive dispatches starting at #at (count=-1: forever)
- ``nonpsd_params(at)``       — corrupt the returned Q to non-PSD
- ``freeze_drift(at, count, delta)`` — force the reported ss freeze deltas
  above threshold for ``count`` dispatches
- ``hung_transfer(at, seconds)`` — simulate a hung d2h transfer: block for
  ``seconds`` and then die without ever returning a result (with a
  ``RobustPolicy.dispatch_deadline_s`` shorter than ``seconds`` the
  watchdog fires first and the retry proceeds deterministically)

The same injector also serves the one-shot serving programs (fused fit,
scheduler bucket, ``session.update``) through ``wrap_call``, the
``RobustPolicy.wrap_dispatch`` seam: it consumes one call index per
dispatch thunk invocation and applies the ``raise``/``hang`` faults
host-side, before the device program runs — NaN faults for one-shot
programs use the on-device ``FusedOptions.fault_chunk`` seam instead
(their reads happen inside the program, out of host reach).

Call indices count EVERY dispatch the guard makes (including retries and
replays), which is what makes one-shot faults recoverable: the retry is a
new call index and passes clean.
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

import numpy as np

__all__ = ["InjectedDispatchError", "FaultInjector"]


class InjectedDispatchError(RuntimeError):
    """Stands in for an axon tunnel / PJRT dispatch failure."""


class FaultInjector:
    def __init__(self):
        self.calls = 0
        self.log: List[Tuple[int, str]] = []
        self._faults: Dict[int, List[tuple]] = {}
        self._persistent_fail_from = None

    def _plan(self, at: int, fault: tuple) -> "FaultInjector":
        self._faults.setdefault(int(at), []).append(fault)
        return self

    def nan_chunk(self, at: int) -> "FaultInjector":
        return self._plan(at, ("nan",))

    def dispatch_failure(self, at: int, count: int = 1) -> "FaultInjector":
        if count < 0:
            self._persistent_fail_from = int(at)
            return self
        for j in range(count):
            self._plan(at + j, ("raise",))
        return self

    def nonpsd_params(self, at: int) -> "FaultInjector":
        return self._plan(at, ("nonpsd",))

    def freeze_drift(self, at: int, count: int = 1,
                     delta: float = 1e-2) -> "FaultInjector":
        for j in range(count):
            self._plan(at + j, ("drift", delta))
        return self

    def hung_transfer(self, at: int,
                      seconds: float = 0.5) -> "FaultInjector":
        return self._plan(at, ("hang", float(seconds)))

    def _pre_faults(self, idx: int):
        """Faults applied BEFORE the dispatch runs (raise / hang);
        returns the remaining (post-dispatch) faults."""
        faults = list(self._faults.get(idx, ()))
        if (self._persistent_fail_from is not None
                and idx >= self._persistent_fail_from):
            faults.append(("raise",))
        post = []
        for f in faults:
            if f[0] == "raise":
                self.log.append((idx, "raise"))
                raise InjectedDispatchError(
                    f"injected dispatch failure at call {idx}")
            if f[0] == "hang":
                # A hung transfer never returns: log, block, then die.
                # Under a watchdog deadline the caller's TimeoutError
                # fires first; without one this degenerates to a slow
                # dispatch failure — either way the retry is clean.
                self.log.append((idx, "hang"))
                time.sleep(f[1])
                raise InjectedDispatchError(
                    f"injected hung transfer at call {idx} "
                    f"(released after {f[1]:g}s)")
            post.append(f)
        return post

    def wrap_call(self, call):
        """The ``RobustPolicy.wrap_dispatch`` callable: the same
        call-index fault plan applied to a one-shot dispatch thunk
        (fused fit / bucket program / session update)."""

        def wrapped(*a, **kw):
            idx = self.calls
            self.calls += 1
            self._pre_faults(idx)
            return call(*a, **kw)

        return wrapped

    def wrap(self, scan_fn):
        """The ``RobustPolicy.wrap_scan`` callable."""

        def wrapped(p, n):
            idx = self.calls
            self.calls += 1
            faults = self._pre_faults(idx)
            p_new, lls, deltas = scan_fn(p, n)
            for f in faults:
                if f[0] == "nan":
                    self.log.append((idx, "nan"))
                    lls = np.full(np.shape(lls), np.nan)
                elif f[0] == "nonpsd":
                    self.log.append((idx, "nonpsd"))
                    Qr = np.asarray(p_new.Q)
                    Q = np.asarray(Qr, np.float64)
                    Q = Q - 10.0 * np.eye(Q.shape[0])
                    p_new = p_new._replace(Q=np.asarray(Q, Qr.dtype))
                elif f[0] == "drift":
                    self.log.append((idx, "drift"))
                    deltas = np.full(
                        np.shape(lls) if deltas is None else
                        np.shape(deltas), float(f[1]))
            return p_new, lls, deltas

        return wrapped

"""Health-monitored chunked EM loop.

``guarded_run_em_chunked`` mirrors ``estim.em.run_em_chunked`` exactly on
the healthy path (same chunk replay, same stopping rule, same callback
contract) and adds, strictly BETWEEN fused dispatches:

- finite-loglik checks (the legacy ``em_progress`` treats NaN as
  "continue" — silent NaN propagation), recorded always, with
  restore-from-chunk-entry + bounded chunk retries when the policy opts
  into ``recover_divergence=True``,
- bounded retries + exponential backoff around the device dispatch itself
  (axon tunnel errors / timeouts),
- an escalation ladder driven by ``GuardControls``: re-measure ``tau`` /
  fall back ``ss -> info`` when the steady-state freeze delta exceeds the
  threshold (the correction ADVICE r5 finding #2 asked for, not a
  warning), escalate the in-loop loglik to f64 when convergence stalls
  inside the noise floor, and eigenvalue-clip + re-jitter on non-PSD
  parameter pathologies.

Nothing here runs per EM iteration and nothing touches the fused scan
program: a clean fit executes the identical device workload.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Tuple

import numpy as np

from ..backends.cpu_ref import SSMParams
from .dispatch import guarded_dispatch
from .health import FitHealth, HealthEvent

__all__ = ["RobustPolicy", "GuardControls", "ChunkMonitor", "GuardFailure",
           "repair_params", "check_param_health", "guarded_run_em_chunked"]


@dataclasses.dataclass(frozen=True)
class RobustPolicy:
    """Knobs for the guarded loop (``fit(..., robust=RobustPolicy(...))``).

    The defaults are tuned so a healthy fit behaves byte-for-byte like the
    unguarded driver: no per-chunk parameter transfers
    (``check_params="on_event"``), legacy stop semantics on divergence
    (``recover_divergence=False``), and escalations only on observed
    pathologies.
    """

    # Device-dispatch retry (tunnel errors, timeouts).
    dispatch_retries: int = 3
    backoff_base: float = 0.25          # seconds; doubles per attempt
    backoff_factor: float = 2.0
    retry_exceptions: tuple = (RuntimeError, OSError, TimeoutError,
                               ConnectionError)   # XlaRuntimeError is a
    #                                             # RuntimeError subclass
    # Chunk-level recovery.
    chunk_retries: int = 2              # NaN-chunk restore+retry budget
    # False (default): legacy semantics — non-finite logliks sail through
    # (recorded, not rewritten) and a diverged trace stops.  True:
    # restore-from-chunk-entry + repair + retry on both.
    recover_divergence: bool = False
    # Steady-state freeze escalation (closes ADVICE #2).
    freeze_threshold: float = 1e-4
    freeze_action: str = "auto"         # auto | remeasure_tau
    #                                   # | fallback_info | warn
    # Stall escalation: this many consecutive chunks entirely inside the
    # noise floor without meeting tol -> f64 in-loop loglik (if x64 is on).
    stall_chunks: int = 2
    escalate_f64: bool = True
    # Parameter pathology checks.
    psd_tol: float = 1e-10
    r_floor: float = 1e-6
    check_params: str = "on_event"      # on_event | always | never
    # Terminal behaviour: "raise" propagates GuardFailure; "cpu" makes
    # ``fit`` re-run from the last good params on the NumPy f64 oracle.
    on_failure: str = "raise"
    # Save the last good params here before declaring failure (resume seam).
    checkpoint_path: Optional[str] = None
    checkpoint_fingerprint: Optional[str] = None
    iter_offset: int = 0                # checkpoint resume: iters already run
    # Test seam: wraps the chunk scan_fn (fault injection lives here).
    wrap_scan: Optional[Callable] = None
    # Watchdog deadline (seconds) around each dispatch + blocking read.
    # On axon the d2h transfer is the only barrier and a hung tunnel
    # blocks forever; a deadline turns the hang into a retryable
    # TimeoutError (see robust.dispatch).  None (default) = no watchdog.
    dispatch_deadline_s: Optional[float] = None
    # Test seam for one-shot programs: wraps the ``call(attempt)`` thunk
    # handed to ``robust.dispatch.guarded_dispatch`` (fused fit, bucket
    # program, session update — FaultInjector.wrap_call lives here).
    wrap_dispatch: Optional[Callable] = None

    def __post_init__(self):
        # Fail at construction, naming the field — a nonsensical knob
        # otherwise only surfaces deep inside guarded_dispatch, mid-fit.
        def bad(field, want):
            raise ValueError(f"RobustPolicy.{field} {want}; got "
                             f"{getattr(self, field)!r}")
        for field in ("dispatch_retries", "chunk_retries", "iter_offset"):
            if int(getattr(self, field)) < 0:
                bad(field, "must be >= 0")
        if self.backoff_base < 0:
            bad("backoff_base", "is a delay in seconds and must be >= 0")
        if self.backoff_factor < 1.0:
            bad("backoff_factor", "must be >= 1.0 (backoff never shrinks)")
        if self.dispatch_deadline_s is not None \
                and not self.dispatch_deadline_s > 0:
            bad("dispatch_deadline_s", "must be None (no watchdog) or > 0 "
                "seconds")
        if int(self.stall_chunks) < 1:
            bad("stall_chunks", "must be >= 1")
        if not self.freeze_threshold > 0:
            bad("freeze_threshold", "must be > 0")
        if self.psd_tol < 0 or self.r_floor < 0:
            bad("psd_tol" if self.psd_tol < 0 else "r_floor",
                "must be >= 0")
        allowed = {"freeze_action": ("auto", "remeasure_tau",
                                     "fallback_info", "warn"),
                   "check_params": ("on_event", "always", "never"),
                   "on_failure": ("raise", "cpu")}
        for field, opts in allowed.items():
            if getattr(self, field) not in opts:
                bad(field, f"must be one of {opts}")


class GuardControls:
    """Backend hooks the guard escalates through.

    The base class knows how to move an ``SSMParams`` pytree between
    device and host; backends override ``rebuild`` to offer escalations.
    ``rebuild(action, p_np)`` returns ``(scan_fn, p_device, updates)`` —
    the new chunk program, the current params re-materialized for it, and
    a dict that may update ``ss_tau`` / ``noise_floor`` — or ``None`` when
    the action is unavailable (guard tries the next rung or records and
    moves on).
    """

    def params_numpy(self, p) -> SSMParams:
        return SSMParams(*(np.asarray(np.asarray(x), np.float64) for x in p))

    def params_device(self, p_np: SSMParams):
        return p_np

    def rebuild(self, action: str, p_np: SSMParams):
        return None


@dataclasses.dataclass
class ChunkMonitor:
    """Bundle handed to ``run_em_chunked(..., monitor=...)``."""

    policy: RobustPolicy
    controls: GuardControls
    health: FitHealth = dataclasses.field(default_factory=FitHealth)


class GuardFailure(RuntimeError):
    """All recovery exhausted.  Carries the last good (host) params and the
    loglik trace so ``fit`` can degrade gracefully (``on_failure="cpu"``)."""

    def __init__(self, msg: str, health: FitHealth,
                 last_good: Optional[SSMParams], lls, p_iters: int):
        super().__init__(msg)
        self.health = health
        self.last_good = last_good
        self.lls = np.asarray(lls, np.float64)
        self.p_iters = int(p_iters)


def check_param_health(p_np: SSMParams, r_floor: float = 1e-6,
                       psd_tol: float = 1e-10) -> list:
    """Issues in a parameter pytree: nonfinite / nonpsd_{Q,P0} / r_floor."""
    issues = []
    leaves = (p_np.Lam, p_np.A, p_np.Q, p_np.R, p_np.mu0, p_np.P0)
    if not all(np.all(np.isfinite(x)) for x in leaves):
        issues.append("nonfinite")
        return issues       # eigvalsh on NaN would raise
    for name, M in (("Q", p_np.Q), ("P0", p_np.P0)):
        if np.linalg.eigvalsh(0.5 * (M + M.T)).min() < -psd_tol:
            issues.append(f"nonpsd_{name}")
    if np.any(p_np.R <= r_floor * (1.0 + 1e-9)):
        issues.append("r_floor")
    return issues


def repair_params(p_np: SSMParams, r_floor: float = 1e-6,
                  jitter: float = 0.0) -> SSMParams:
    """Project params back into the feasible set (host-side, f64).

    Symmetrize + eigenvalue-clip Q and P0 to PSD (plus an optional jitter
    ridge so a repeated Cholesky failure gets a progressively larger
    re-jitter), floor R, and replace any non-finite entries with benign
    identity-ish values.
    """
    def _psd(M, dim):
        M = np.asarray(M, np.float64)
        if not np.all(np.isfinite(M)):
            return np.eye(dim)
        M = 0.5 * (M + M.T)
        w, V = np.linalg.eigh(M)
        w = np.maximum(w, 0.0) + jitter
        return (V * w) @ V.T

    k = p_np.Q.shape[0]
    Lam = np.asarray(p_np.Lam, np.float64)
    Lam = np.where(np.isfinite(Lam), Lam, 0.0)
    A = np.asarray(p_np.A, np.float64)
    A = np.where(np.isfinite(A), A, 0.0)
    R = np.asarray(p_np.R, np.float64)
    R = np.where(np.isfinite(R), R, 1.0)
    # Lift clear of the floor: exactly-at-floor entries still count as
    # "pinned" in check_param_health.
    R = np.maximum(R, 2.0 * r_floor)
    mu0 = np.asarray(p_np.mu0, np.float64)
    mu0 = np.where(np.isfinite(mu0), mu0, 0.0)
    return SSMParams(Lam=Lam, A=A, Q=_psd(p_np.Q, k), R=R, mu0=mu0,
                     P0=_psd(p_np.P0, k))


def guarded_run_em_chunked(scan_fn, p0, max_iters: int, tol: float,
                           noise_floor: float, callback=None,
                           fused_chunk: int = 8, ss_tau=None,
                           monitor: ChunkMonitor = None, progress=None,
                           pipeline=None, monotone: bool = True):
    """Monitored twin of ``estim.em.run_em_chunked`` (same return tuple,
    same optional 4-element scan_fn metrics contract and per-chunk
    ``progress`` hook).

    ``pipeline``: same contract as the unguarded driver.  With depth > 1
    the guard issues chunks speculatively and runs its health checks at
    drain time, one round behind — a drained chunk's pre-fetched result
    is "attempt 0" of the serial recovery machinery, so any pathology
    (NaN chunk, divergence, escalation, dispatch error) discards the
    younger speculative chunks and replays the SAME recovery trajectory
    the serial guard produces from that chunk's entry params.
    """
    from ..estim.em import _ChunkCall, em_progress, warn_ss_delta
    from ..obs.trace import current_tracer, shape_key
    from ..pipeline import resolve_pipeline

    policy, controls, health = (monitor.policy, monitor.controls,
                                monitor.health)
    pipe = resolve_pipeline(pipeline)
    tr = current_tracer()
    prog = getattr(scan_fn, "trace_name", "em_chunk")
    prog_key = getattr(scan_fn, "trace_key", "")
    engine = getattr(scan_fn, "trace_engine", prog)
    if not health.engine:
        health.engine = engine
    if policy.wrap_scan is not None:
        scan_fn = policy.wrap_scan(scan_fn)

    fused_chunk = max(1, int(fused_chunk))
    cc = _ChunkCall(pipe.bucket, fused_chunk)
    pass_piter = getattr(callback, "wants_params_iter", False)
    lls: list = []
    converged = False
    stop = False
    target = 0
    p = p0
    it = 0
    p_entry = p_entry_prev = p0
    entry_it = entry_it_prev = 0
    entry_floor = 0         # iteration of the last escalation: replay
    #                       # cannot cross a scan_fn swap
    chunk_idx = 0
    stall_run = 0
    done_actions: set = set()
    t0 = time.perf_counter()

    def _fail(msg: str, cause=None):
        try:
            last_good = controls.params_numpy(p)
        except Exception:
            last_good = None
        if policy.checkpoint_path and last_good is not None:
            from ..utils.checkpoint import save_checkpoint
            try:
                save_checkpoint(policy.checkpoint_path, last_good,
                                policy.iter_offset + it, lls,
                                fingerprint=policy.checkpoint_fingerprint)
            except Exception:
                pass
        err = GuardFailure(msg, health, last_good, lls, it)
        if cause is not None:
            raise err from cause
        raise err

    def _pull(out, n):
        """Transfer one chunk's outputs to host, sliced to the active
        prefix (a no-op unbucketed; bucketed scans return the full
        fused-length arrays)."""
        p_out, chunk = out[0], np.asarray(out[1], np.float64)[:n]
        deltas = out[2]
        if deltas is not None:
            deltas = np.asarray(deltas, np.float64)[:n]
        metrics = (np.asarray(out[3], np.float64)[:n]
                   if len(out) > 3 and out[3] is not None else None)
        return p_out, chunk, deltas, metrics

    def _dispatch(fn, p_in, n, first_exc=None):
        """One chunk dispatch with bounded retry + exponential backoff,
        routed through the shared ``robust.dispatch.guarded_dispatch``
        seam (which also supplies the watchdog deadline and the
        ``wrap_dispatch`` fault-injection surface).

        The device->host transfers happen INSIDE the guarded call: on the
        tunneled device errors surface at the transfer, not the (async)
        dispatch.

        ``first_exc``: a pre-observed attempt-0 failure (a pipelined
        issue/drain already consumed the dispatch and raised) — recorded
        and retried exactly as if attempt 0 had failed here.
        """
        pending = [first_exc]

        def call(attempt):
            if pending[0] is not None:
                e, pending[0] = pending[0], None
                raise e
            if tr is None:
                return _pull(cc.run(fn, p_in, n), n)
            # Failed attempts each leave a dispatch event with an
            # ``error`` field; the transfers inside the span make its
            # wall time the true execution barrier.
            with tr.dispatch(
                    getattr(fn, "trace_name", prog),
                    cc.key(fn, getattr(fn, "trace_key", prog_key), n),
                    barrier=True, n_iters=n, attempt=attempt,
                    **cc.payload(fn)):
                return _pull(cc.run(fn, p_in, n), n)

        try:
            return guarded_dispatch(call, policy, health,
                                    chunk=chunk_idx, iteration=it)
        except GuardFailure as e:
            # Re-raise through _fail: same message, plus the last-good
            # checkpoint save and the chunked loop's loglik trace.
            _fail(str(e), e.__cause__)

    def _apply_rebuild(action: str, reason_event: HealthEvent):
        """Swap in an escalated chunk program; returns True on success."""
        nonlocal scan_fn, p, ss_tau, noise_floor
        nonlocal p_entry, p_entry_prev, entry_it, entry_it_prev, entry_floor
        if action in done_actions:
            return False
        try:
            p_np = controls.params_numpy(p)
        except Exception:
            return False
        built = controls.rebuild(action, p_np)
        if built is None:
            return False
        scan_fn, p, updates = built
        if policy.wrap_scan is not None:
            scan_fn = policy.wrap_scan(scan_fn)
        if "ss_tau" in updates:
            ss_tau = updates["ss_tau"]
        if "noise_floor" in updates:
            noise_floor = updates["noise_floor"]
        done_actions.add(action)
        health.escalate(action)
        reason_event.action = action
        # The new program starts a fresh replay window: stored entries
        # belong to the old scan_fn.
        p_entry = p_entry_prev = p
        entry_it = entry_it_prev = it
        entry_floor = it
        return True

    def _chunk_attempts(n, pre=None, first_exc=None):
        """The serial NaN-retry attempts loop for one chunk.  ``pre`` is
        a pre-drained attempt-0 result, ``first_exc`` a pre-observed
        attempt-0 dispatch failure (the pipelined loop's seam — either
        way attempt 0's dispatch was already consumed at issue time, so
        retries line up with the serial call sequence)."""
        nonlocal p
        chunk = deltas = metrics = None
        p_try = None
        for attempt in range(policy.chunk_retries + 1):
            if attempt == 0 and pre is not None:
                p_try, chunk, deltas, metrics = pre
            else:
                p_try, chunk, deltas, metrics = _dispatch(
                    scan_fn, p, n, first_exc=first_exc)
                first_exc = None
            if np.all(np.isfinite(chunk)):
                break
            if not policy.recover_divergence:
                # Legacy semantics (the default): ``em_progress`` treats
                # NaN as "continue", so a poisoned fit sails through to a
                # garbage loglik — pinned by tests/test_debug.py.  Record
                # the pathology; don't rewrite the trajectory.
                health.record(HealthEvent(
                    chunk=chunk_idx, iteration=it, kind="nan_loglik",
                    detail="non-finite loglik in chunk", action="none"))
                break
            ev = health.record(HealthEvent(
                chunk=chunk_idx, iteration=it, kind="nan_loglik",
                detail=f"non-finite loglik in chunk (attempt {attempt})",
                action="restored"))
            if attempt >= policy.chunk_retries:
                if not _apply_rebuild("loglik_f64", ev):
                    _fail("non-finite logliks persisted through "
                          f"{policy.chunk_retries} chunk retries")
                p_try, chunk, deltas, metrics = _dispatch(scan_fn, p, n)
                if not np.all(np.isfinite(chunk)):
                    _fail("non-finite logliks survived f64 escalation")
                break
            # Restore = do not advance past the chunk entry (p is the
            # entry params); repair + re-jitter before retrying so a
            # Cholesky-adjacent pathology doesn't reproduce the NaN.
            p_np = controls.params_numpy(p)
            issues = check_param_health(p_np, policy.r_floor, policy.psd_tol)
            if issues:
                health.record(HealthEvent(
                    chunk=chunk_idx, iteration=it,
                    kind=("nonfinite_params" if "nonfinite" in issues
                          else "nonpsd"),
                    detail=",".join(issues), action="repaired"))
            p = controls.params_device(repair_params(
                p_np, policy.r_floor, jitter=policy.psd_tol
                * (10.0 ** attempt)))
        return p_try, chunk, deltas, metrics

    def _consume_chunk(n, p_try, chunk, deltas, metrics):
        """Host-side per-chunk machinery (emit, stopping rule, recovery,
        between-chunk escalations) — the serial loop body after its
        dispatch.  Returns "redo" (chunk escalated: re-run the same
        budget from the entry), "stop", or "continue"."""
        nonlocal p, it, stop, converged, target, stall_run, chunk_idx
        nonlocal p_entry, p_entry_prev, entry_it, entry_it_prev
        if tr is not None and chunk is not None:
            drops = np.diff(chunk)
            extra = ({"dparams": [float(x) for x in metrics[:, 2]]}
                     if metrics is not None else {})
            tr.emit("chunk", engine=engine, iter0=it, n=int(n),
                    lls=[float(x) for x in chunk],
                    noise_floor=float(noise_floor),
                    max_drop=float(-drops.min()) if drops.size else 0.0,
                    below_floor=bool(drops.size == 0
                                     or np.abs(drops).max() < noise_floor),
                    **extra)
        p_entry_prev, entry_it_prev = p_entry, entry_it
        p_entry, entry_it = p, it
        p = p_try
        consumed = n
        chunk_escalated = False
        for j, ll in enumerate(chunk):
            lls.append(float(ll))
            if callback is not None:
                if pass_piter:
                    callback(it + j, float(ll), p_entry,
                             params_iter=entry_it)
                else:
                    callback(it + j, float(ll), p_entry)
            if (monotone and len(lls) >= 2
                    and lls[-2] - lls[-1] > noise_floor):
                health.monotonicity_violations += 1
            state = em_progress(lls, tol, noise_floor, monotone=monotone)
            if state == "diverged" and policy.recover_divergence:
                ev = health.record(HealthEvent(
                    chunk=chunk_idx, iteration=it + j, kind="divergence",
                    detail=f"drop {lls[-2] - lls[-1]:.3e}",
                    action="restored"))
                p = p_entry     # rebuild from the chunk entry, not the
                #               # post-drop update
                if _apply_rebuild("loglik_f64", ev):
                    # Forget the divergent tail; continue from the chunk
                    # entry with the escalated program.
                    del lls[len(lls) - (j + 1):]
                    consumed = 0
                    chunk_escalated = True
                    break
                state = "diverged"      # escalation unavailable: legacy stop
            if state != "continue":
                converged = state == "converged"
                if state == "diverged":
                    health.record(HealthEvent(
                        chunk=chunk_idx, iteration=it + j, kind="divergence",
                        detail=f"drop {lls[-2] - lls[-1]:.3e}",
                        action="stopped"))
                target = len(lls) if converged else max(len(lls) - 2, 0)
                target = max(target, entry_floor)
                stop = True
                consumed = j + 1
                break
        if chunk_escalated:
            health.n_chunks += 1
            chunk_idx += 1
            return "redo"   # it unchanged: redo the budget from the entry
        # --- between-chunk health (host-side only) -----------------------
        max_chunk_delta = 0.0
        if deltas is not None and consumed:
            max_chunk_delta = float(np.max(deltas[:consumed]))
            health.max_ss_delta = max(health.max_ss_delta, max_chunk_delta)
        it += n
        health.n_chunks += 1
        chunk_idx += 1
        if progress is not None:
            # Same per-chunk live-progress contract as the unguarded
            # driver (see run_em_chunked): fires after the stopping rule,
            # with the amortized-wall ETA over the remaining budget.
            iters_done = entry_it + consumed
            elapsed = time.perf_counter() - t0
            left = 0 if stop else max_iters - it
            progress({"chunk": chunk_idx - 1, "iter": int(iters_done),
                      "total": int(max_iters), "loglik": lls[-1],
                      "delta": (lls[-1] - lls[-2]) if len(lls) > 1
                      else None,
                      "dparam": (float(metrics[consumed - 1, 2])
                                 if metrics is not None and consumed
                                 else None),
                      "elapsed_s": elapsed,
                      "eta_s": ((elapsed / iters_done) * left
                                if iters_done else None),
                      "metrics": metrics, "stopped": bool(stop),
                      "converged": bool(converged)})
        if stop:
            return "stop"
        # Freeze drift: correct, don't just warn (ADVICE #2).
        if (max_chunk_delta > policy.freeze_threshold
                and policy.freeze_action != "warn"):
            ev = health.record(HealthEvent(
                chunk=chunk_idx - 1, iteration=it, kind="freeze_drift",
                detail=f"delta {max_chunk_delta:.3e} > "
                       f"{policy.freeze_threshold:.0e}", action="warned"))
            acted = False
            if policy.freeze_action in ("auto", "remeasure_tau"):
                acted = _apply_rebuild("remeasure_tau", ev)
            if not acted and policy.freeze_action in ("auto",
                                                      "fallback_info"):
                acted = _apply_rebuild("fallback_info", ev)
            if acted:
                return "continue"
        # Stall: a whole chunk inside the noise floor without converging.
        diffs = np.abs(np.diff(np.asarray(lls[-(n + 1):], np.float64)))
        if len(diffs) and np.all(diffs <= max(noise_floor, 0.0)) and tol > 0:
            stall_run += 1
        else:
            stall_run = 0
        if stall_run >= policy.stall_chunks:
            ev = health.record(HealthEvent(
                chunk=chunk_idx - 1, iteration=it, kind="stall",
                detail=f"{stall_run} chunks inside noise floor "
                       f"{noise_floor:.3e}", action="none"))
            if policy.escalate_f64 and _apply_rebuild("loglik_f64", ev):
                stall_run = 0
                return "continue"
            health.stalled = True
            stall_run = 0
        # Parameter pathology scan (costs one small transfer; off the
        # healthy path unless check_params="always").
        if (policy.check_params == "always"
                or (policy.check_params == "on_event"
                    and health.events
                    and health.events[-1].chunk == chunk_idx - 1)):
            p_np = controls.params_numpy(p)
            issues = check_param_health(p_np, policy.r_floor,
                                        policy.psd_tol)
            if "r_floor" in issues:
                health.r_floor_hits += 1
            bad = [i for i in issues if i.startswith("nonpsd")
                   or i == "nonfinite"]
            if bad:
                # Mutating the trajectory is opt-in: either the caller
                # asked for continuous checking or enabled recovery.
                repairing = (policy.recover_divergence
                             or policy.check_params == "always")
                health.record(HealthEvent(
                    chunk=chunk_idx - 1, iteration=it,
                    kind=("nonfinite_params" if "nonfinite" in bad
                          else "nonpsd"),
                    detail=",".join(bad),
                    action="repaired" if repairing else "detected"))
                if repairing:
                    p = controls.params_device(repair_params(
                        p_np, policy.r_floor, jitter=policy.psd_tol))
        return "continue"

    if not pipe.active:
        # Serial driver: dispatch, block on the transfer, check — exactly
        # the pre-pipeline loop (``_chunk_attempts`` + ``_consume_chunk``
        # manage ``it``/``stop`` themselves).
        while it < max_iters and not stop:
            n = min(fused_chunk, max_iters - it)
            res = _chunk_attempts(n)
            _consume_chunk(n, *res)
    else:
        def _issue(fn, p_in, n, k):
            """Speculative enqueue (non-barrier span; ``queue_depth``
            records how deep the device queue was at issue)."""
            if tr is None:
                return cc.run(fn, p_in, n)
            with tr.dispatch(getattr(fn, "trace_name", prog),
                             cc.key(fn, getattr(fn, "trace_key", prog_key),
                                    n),
                             n_iters=n, queue_depth=k, **cc.payload(fn)):
                return cc.run(fn, p_in, n)

        while it < max_iters and not stop:
            # -- issue: up to depth chunks chained through device params.
            flights = []        # [entry, it, n, out, exc, pulled]
            p_issue, it_issue = p, it
            while len(flights) < pipe.depth and it_issue < max_iters:
                n = min(fused_chunk, max_iters - it_issue)
                try:
                    out = _issue(scan_fn, p_issue, n, len(flights) + 1)
                except GuardFailure:
                    raise
                except Exception as e:      # fed to _dispatch at drain
                    flights.append([p_issue, it_issue, n, None, e, None])
                    break
                flights.append([p_issue, it_issue, n, out, None, None])
                p_issue = out[0]
                it_issue += n
            # -- drain: newest successful flight first — the round's one
            # blocking transfer (older outputs are complete by then, so
            # their fetches just move bytes).
            live = [i for i, fl in enumerate(flights)
                    if fl[3] is not None]
            for pos, i in enumerate(reversed(live)):
                fl = flights[i]
                tt = time.perf_counter()
                err = None
                try:
                    fl[5] = _pull(fl[3], fl[2])
                except policy.retry_exceptions as e:
                    fl[3], fl[4] = None, e
                    err = f"{type(e).__name__}: {e}"[:200]
                if tr is not None:
                    ev = dict(program=prog, direction="d2h",
                              blocking=bool(pos == 0),
                              n_iters=int(fl[2]))
                    if err is not None:
                        ev["error"] = err
                    tr.emit("transfer", t=tt,
                            dur=time.perf_counter() - tt, **ev)
            # -- process: the serial machinery oldest-first, with each
            # drained result as attempt 0.  Any recovery replaces ``p``
            # (and leaves ``it`` at the recovered chunk), breaking the
            # chain check below, so the younger speculative results are
            # discarded and the next round re-issues from the recovered
            # state — the same trajectory the serial guard walks.
            for f_entry, f_it, n, out, exc, pulled in flights:
                if stop:
                    break
                if f_it != it or f_entry is not p:
                    break       # chain broken by an older recovery
                res = _chunk_attempts(n, pre=pulled, first_exc=exc)
                _consume_chunk(n, *res)

    corrected = done_actions & {"remeasure_tau", "fallback_info"}
    if ss_tau is not None and not corrected:
        # No correction happened (policy "warn", or controls couldn't
        # rebuild): preserve the legacy warning so drift is never silent.
        warn_ss_delta(health.max_ss_delta, ss_tau)
    p_iters = it
    if stop and target != it:
        base, base_it = ((p_entry, entry_it) if target >= entry_it
                         else (p_entry_prev, entry_it_prev))
        n_replay = max(target - base_it, 0)   # clamped at escalations
        p = base if n_replay == 0 else _dispatch(scan_fn, base, n_replay)[0]
        p_iters = base_it + n_replay
    return p, np.asarray(lls), converged, p_iters

"""Backend-specific ``GuardControls`` (the guard's escalation hooks).

``TPUControls`` rebuilds the single-device fused chunk program
(``estim.em.em_fit_scan``) under a new engine/precision; ``ShardedControls``
drives the same escalations through a ``parallel.sharded.ShardedEM`` (whose
``run_scan`` re-reads ``drv.cfg`` per dispatch, so swapping the config IS
the rebuild — padding and device placement are handled by the driver's
``params_device``).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .guard import GuardControls

__all__ = ["TPUControls", "ShardedControls"]


class TPUControls(GuardControls):
    """Escalation hooks for ``api.TPUBackend``'s chunked driver."""

    def __init__(self, Yj, mj, cfg, em_fit_scan):
        self.Yj = Yj
        self.mj = mj
        self.cfg = cfg
        self.em_fit_scan = em_fit_scan

    def params_device(self, p_np):
        from ..ssm.params import SSMParams as JaxParams
        return JaxParams.from_numpy(p_np, dtype=self.Yj.dtype)

    def _scan(self):
        Yj, mj, cfg, em = self.Yj, self.mj, self.cfg, self.em_fit_scan

        def scan_fn(p, n):
            p_new, lls, deltas = em(Yj, p, n, mask=mj, cfg=cfg)
            return p_new, lls, (deltas if cfg.filter == "ss" else None)

        return scan_fn

    def rebuild(self, action: str, p_np):
        import jax
        import jax.numpy as jnp
        if action == "remeasure_tau" and self.cfg.filter == "ss":
            from ..ssm.steady import remeasure_tau
            new_tau = remeasure_tau(p_np, self.cfg.tau)
            if new_tau <= self.cfg.tau:
                return None     # longer freeze horizon cannot help
            self.cfg = dataclasses.replace(self.cfg, tau=new_tau)
            return self._scan(), self.params_device(p_np), {
                "ss_tau": new_tau}
        if action == "fallback_info" and self.cfg.filter == "ss":
            self.cfg = dataclasses.replace(self.cfg, filter="info")
            return self._scan(), self.params_device(p_np), {"ss_tau": None}
        if action == "loglik_f64":
            if (not jax.config.jax_enable_x64
                    or self.Yj.dtype == jnp.float64):
                return None
            from ..estim.em import noise_floor_for
            self.Yj = self.Yj.astype(jnp.float64)
            if self.mj is not None:
                self.mj = self.mj.astype(jnp.float64)
            nf = noise_floor_for(np.float64, self.Yj.size,
                                 mult=self.cfg.noise_floor_mult)
            return self._scan(), self.params_device(p_np), {
                "noise_floor": nf}
        return None


class ShardedControls(GuardControls):
    """Escalation hooks for ``api.ShardedBackend`` via its ``ShardedEM``."""

    def __init__(self, drv):
        self.drv = drv

    def params_numpy(self, p):
        return self.drv.params_numpy(p)

    def params_device(self, p_np):
        return self.drv.params_device(p_np)

    def _scan(self):
        drv = self.drv

        def scan_fn(p, n):
            return drv.run_scan(p, n)

        return scan_fn

    def rebuild(self, action: str, p_np):
        drv = self.drv
        if action == "remeasure_tau" and drv.cfg.filter == "ss":
            from ..ssm.steady import remeasure_tau
            new_tau = remeasure_tau(p_np, drv.cfg.tau)
            if new_tau <= drv.cfg.tau:
                return None
            drv.cfg = dataclasses.replace(drv.cfg, tau=new_tau)
            return self._scan(), drv.params_device(p_np), {
                "ss_tau": new_tau}
        if action == "fallback_info" and drv.cfg.filter == "ss":
            drv.cfg = dataclasses.replace(drv.cfg, filter="info")
            return self._scan(), drv.params_device(p_np), {"ss_tau": None}
        # f64 loglik escalation is not offered under sharding: the panel,
        # params and every shard_map program would need re-materializing in
        # a second dtype — the info fallback is the sharded escape hatch.
        return None

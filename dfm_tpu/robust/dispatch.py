"""Unified guarded dispatch for one-shot device programs.

The chunked EM guard (``robust.guard``) has always owned retry/backoff
around its per-chunk dispatches; the serving stack (fused fit, scheduler
bucket programs, session updates) dispatches ONE program per request and
had no guard at all.  ``guarded_dispatch`` is the shared seam: every
dispatch site builds a ``call(attempt)`` thunk that performs the dispatch
AND the blocking d2h read, and this wrapper supplies

- retry with exponential backoff on ``policy.retry_exceptions``
  (``GuardFailure`` always passes through untouched — it IS the guard's
  own terminal signal);
- a watchdog deadline (``policy.dispatch_deadline_s``) around the whole
  call.  On axon the blocking d2h transfer is the only execution barrier
  and a hung tunnel blocks it forever; the watchdog runs the call on a
  daemon thread and raises ``TimeoutError`` (a retryable exception) when
  the deadline passes, so a hung transfer feeds the same retry loop as a
  raised one.  The abandoned thread is left to die with the process —
  there is no portable way to cancel a blocked transfer, so a deadline
  only makes sense where the hung call will never land (tunnel death).
- the deterministic fault-injection seam (``policy.wrap_dispatch``) that
  gives one-shot programs the same chaos-testing surface
  ``policy.wrap_scan`` gives the chunked loop;
- ``HealthEvent`` records carrying tenant/session attribution and the
  backoff charged before each retry.  Every record flows through
  ``FitHealth.record``, which mirrors it to the active tracer OR (when
  untraced) straight to the always-on live metrics plane
  (``obs.live``) — retries/backoff/quarantines are metered even with
  telemetry off.

``policy=None`` short-circuits to ``call(0)`` — the off path adds no
wrapper, no thread, no payload keys, keeping default trajectories and
dispatch counts byte-identical.
"""

from __future__ import annotations

import contextvars
import dataclasses
import threading
import time
from typing import Callable, Optional, Sequence

from .health import FitHealth, HealthEvent

__all__ = ["guarded_dispatch"]


def _call_with_deadline(fn: Callable[[], object],
                        deadline_s: Optional[float]):
    """Run ``fn()`` under a watchdog deadline.

    ``deadline_s`` falsy -> direct call (zero overhead).  Otherwise the
    call runs on a daemon thread (with the caller's contextvars, so the
    active tracer is visible) and ``TimeoutError`` is raised if it has
    not returned within the deadline.  The timed-out call keeps running
    in the background; callers must only retry when the abandoned
    dispatch cannot land (see module docstring).
    """
    if not deadline_s or deadline_s <= 0:
        return fn()
    box: dict = {}
    ctx = contextvars.copy_context()

    def _run():
        try:
            box["value"] = ctx.run(fn)
        except BaseException as e:  # re-raised on the caller thread
            box["error"] = e

    th = threading.Thread(target=_run, daemon=True,
                          name="dfm-dispatch-watchdog")
    th.start()
    th.join(float(deadline_s))
    if th.is_alive():
        raise TimeoutError(
            f"dispatch exceeded the {float(deadline_s):g}s watchdog "
            f"deadline (hung d2h transfer?)")
    if "error" in box:
        raise box["error"]
    return box.get("value")


def guarded_dispatch(call: Callable[[int], object], policy,
                     health: Optional[FitHealth] = None, *,
                     label: str = "dispatch", tenant: str = "",
                     tenants: Sequence[str] = (), session: str = "",
                     chunk: int = -1, iteration: int = 0, last_good=None,
                     lls: Sequence[float] = (), p_iters: int = 0,
                     trace_id: str = "", trace_ids: Sequence[str] = ()):
    """Run ``call(attempt)`` under ``policy``'s retry/backoff/watchdog.

    ``call`` receives the 0-based attempt number (so dispatch spans can
    stamp ``attempt=`` into their trace payload) and must perform both
    the dispatch and the blocking read — a failure anywhere in that span
    is what the guard retries.  On exhaustion raises ``GuardFailure``
    whose message carries ``label`` plus tenant/session attribution and
    whose payload carries ``last_good`` (called first if callable — the
    site's cheapest route to host params), ``lls`` and ``p_iters`` so
    ``on_failure="cpu"`` degradation can resume from the last good state.

    ``trace_id``/``trace_ids`` attach the in-flight request trace(s) to
    every retry/abort record (``trace_ids`` aligned positionally with
    ``tenants``), so ``obs.report`` can tie a guard intervention back to
    the specific requests it delayed.  Empty ids ride nowhere — the
    untraced payload stays byte-identical.

    ``tenants`` (fleet buckets): ONE dispatch serves many tenants, so a
    dispatch failure is every bucket member's failure — each retry/abort
    event is emitted once to the trace and then fanned out to the health
    record per tenant (``emit=False`` replays, the batched engine's
    convention), keeping per-tenant accountability for a shared program.
    Mutually exclusive with the singular ``tenant``.
    """
    if policy is None:
        return call(0)
    if tenant and tenants:
        raise ValueError("pass tenant= or tenants=, not both")
    from .guard import GuardFailure
    run = call if policy.wrap_dispatch is None else policy.wrap_dispatch(call)
    h = health if health is not None else FitHealth()
    attempt = 0
    delay = policy.backoff_base
    while True:
        try:
            return _call_with_deadline(lambda: run(attempt),
                                       policy.dispatch_deadline_s)
        except policy.retry_exceptions as e:
            if isinstance(e, GuardFailure):
                raise
            h.n_dispatch_retries += 1
            last = attempt >= policy.dispatch_retries
            tids = list(trace_ids) + [""] * max(
                0, len(tenants) - len(trace_ids))
            ev = HealthEvent(
                chunk=chunk, iteration=iteration, kind="dispatch_error",
                detail=f"{type(e).__name__}: {e}"[:200],
                action="abort" if last else "retried",
                tenant=tenants[0] if tenants else tenant, session=session,
                backoff_s=0.0 if last else float(delay),
                trace_id=tids[0] if tenants else trace_id)
            h.record(ev)
            for t, tid in zip(tenants[1:], tids[1:]):
                h.record(dataclasses.replace(ev, tenant=t, trace_id=tid),
                         emit=False)
            if last:
                scope = ""
                if tenants:
                    scope += f" (tenants {', '.join(tenants)})"
                elif tenant:
                    scope += f" (tenant {tenant})"
                if session:
                    scope += f" (session {session})"
                lg = last_good() if callable(last_good) else last_good
                raise GuardFailure(
                    f"{label} failed after {policy.dispatch_retries} "
                    f"retries{scope}: {e}", h, lg, list(lls),
                    int(p_iters)) from e
            time.sleep(delay)
            delay *= policy.backoff_factor
            attempt += 1

"""Work-efficient blocked prefix scan for expensive element algebras.

``lax.associative_scan`` has log-depth but does ~2T combine invocations, and
for the Kalman element algebra each combine carries several k x k solves —
measured SLOWER than the plain sequential scan at T=500, k=10 on TPU v5 lite
(the sequential scan's cost is per-step dispatch overhead, not FLOPs).

``blocked_scan`` instead does S + B sequential steps (T = S*B) where every
step's combine is BATCHED over the B blocks:

  phase 1  within-block inclusive prefixes — lax.scan over S, batch B
  phase 2  inclusive prefix of the B block products — lax.scan over B
  phase 3  one batched combine applying block offsets to phase-1 results

With S ~ sqrt(T) the sequential depth drops from T to ~2*sqrt(T) while every
remaining step amortizes its dispatch overhead over B lanes.  Exact (same
element algebra, associativity only) — equivalence with both the sequential
and the associative_scan paths is tested.

``combine(a, b)`` must accept arbitrary leading batch dims and compose a
(earlier in sequence) with b (later).  For reverse=True the array is flipped
and combine is called as combine(later, earlier) — matching the convention
``lax.associative_scan(..., reverse=True)`` uses, so the same combine works
for both this and the associative path.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["blocked_scan", "affine_const_prefix"]


def affine_const_prefix(M: jax.Array, d: jax.Array, x0: jax.Array):
    """All states of ``x_t = M x_{t-1} + d_t`` (t = 1..n) for CONSTANT M,
    via shift-doubling: log2(n) rounds, each one (n, k) x (k, k) batched
    matmul plus a shifted add (round r adds ``M^(2^r)`` times the sequence
    shifted by 2^r, so entry t accumulates sum_j M^(t-j) d_j in a window
    that doubles per round).  Sequential depth ~log2(n) with every op
    batched over the whole sequence — for the steady-state engine's frozen
    mean recursions this beats ``blocked_scan``'s ~2*sqrt(T) matrix-matrix
    combine steps (the doubling works on k-VECTORS; no (k,k)@(k,k) prefix
    products ever form).  Stable because the filter/smoother closed-loop M
    has spectral radius < 1 — the powers decay monotonically.

    Returns the (n, k) stack of x_1..x_n.
    """
    seq = jnp.concatenate([x0[None], d], axis=0)        # entry 0 = M^0 x0
    P = M
    shift = 1
    n1 = seq.shape[0]
    while shift < n1:                                   # static trip count
        pad = jnp.zeros((shift,) + seq.shape[1:], seq.dtype)
        seq = seq + jnp.concatenate([pad, seq[:-shift]], axis=0) @ P.T
        P = P @ P
        shift *= 2
    return seq[1:]


def _take(tree, idx):
    return jax.tree.map(lambda x: x[idx], tree)


def _flip(tree):
    return jax.tree.map(lambda x: jnp.flip(x, axis=0), tree)


def blocked_scan(combine: Callable, elems, block_size: int | None = None,
                 reverse: bool = False):
    """Inclusive prefix (suffix if reverse) products of ``elems`` under
    ``combine``; leading axis is the sequence axis."""
    T = jax.tree.leaves(elems)[0].shape[0]
    if reverse:
        out = blocked_scan(combine, _flip(elems), block_size, reverse=False)
        return _flip(out)
    if block_size is None:
        block_size = max(1, int(math.sqrt(T)))
    S = min(block_size, T)
    B = T // S
    T0 = B * S

    main = jax.tree.map(
        lambda x: jnp.moveaxis(x[:T0].reshape((B, S) + x.shape[1:]), 0, 1),
        elems)                                    # (S, B, ...)

    def step(carry, es):
        new = combine(carry, es)
        return new, new

    init = _take(main, 0)                         # (B, ...)
    if S > 1:
        _, rest = lax.scan(step, init, _take(main, slice(1, None)))
        within = jax.tree.map(
            lambda a, b: jnp.concatenate([a[None], b], axis=0), init, rest)
    else:
        within = jax.tree.map(lambda x: x[None], init)   # (S, B, ...)

    # Phase 2: inclusive prefix over the B block products.
    products = _take(within, S - 1)               # (B, ...)
    first = _take(products, 0)
    if B > 1:
        _, incl_rest = lax.scan(step, first, _take(products, slice(1, None)))
        offsets = jax.tree.map(
            lambda a, b: jnp.concatenate([a[None], b], axis=0),
            first, incl_rest)                     # (B, ...) inclusive
        # Phase 3: offset blocks 1..B-1 with the product of all earlier blocks.
        off = jax.tree.map(lambda x: x[:-1], offsets)          # (B-1, ...)
        off_b = jax.tree.map(
            lambda x: jnp.broadcast_to(x[:, None],
                                       (B - 1, S) + x.shape[1:]), off)
        tail_blocks = jax.tree.map(lambda x: jnp.moveaxis(x, 0, 1)[1:],
                                   within)        # (B-1, S, ...)
        combined = combine(off_b, tail_blocks)
        full = jax.tree.map(
            lambda w, c: jnp.concatenate(
                [jnp.moveaxis(w, 0, 1)[:1], c], axis=0).reshape(
                    (T0,) + w.shape[2:]),
            within, combined)
    else:
        full = jax.tree.map(
            lambda w: jnp.moveaxis(w, 0, 1).reshape((T0,) + w.shape[2:]),
            within)

    if T0 < T:
        # Sequential tail for the remainder (< S elements).
        carry0 = _take(full, T0 - 1)
        _, tail = lax.scan(step, carry0, _take(elems, slice(T0, None)))
        full = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], axis=0), full, tail)
    return full

"""PSD-safe linear-algebra primitives shared by the JAX state-space code.

SURVEY.md section 7.2 item 1: float32 covariance recursions on TPU lose
symmetry/PSD-ness quickly; everything here exists to keep them sane.
Cholesky-only solves — no explicit inverses anywhere in the framework.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

__all__ = ["sym", "psd_cholesky", "chol_solve", "chol_logdet",
           "solve_psd", "default_jitter", "chol_unrolled",
           "chol_solve_unrolled", "chol_small", "chol_solve_small",
           "matmul_vpu", "matvec_vpu",
           "UNROLL_K_MAX", "QR_UNROLL_K_MAX", "tria_unrolled", "tria",
           "tri_solve_unrolled", "tri_solve", "psd_factor_unrolled",
           "psd_factor"]

# Unrolling is ~k^2/2 fused elementwise ops for the factorization and
# ~k^2 per solve column; past this bound compile time and op count beat
# the batched-linalg savings.
UNROLL_K_MAX = 8

# The QR-factor parallel-in-time engine unrolls over the state dim too;
# its ops are row-vector MGS steps (cheaper per entry than a chol pivot
# chain), so the bound sits a little higher — k ~ 2-10 factor blocks stay
# unrolled, the m ~ 15-25 mixed-frequency augmented states fall back to
# the generic batched-linalg lowerings (correct, just not VPU-formed).
QR_UNROLL_K_MAX = 10


def sym(M: jax.Array) -> jax.Array:
    """Symmetrize the trailing two axes."""
    return 0.5 * (M + jnp.swapaxes(M, -1, -2))


def default_jitter(dtype) -> float:
    """Diagonal jitter matched to precision: ~1e-10 in f64, ~1e-6 in f32."""
    return 1e-10 if jnp.dtype(dtype) == jnp.float64 else 1e-6


def psd_cholesky(M: jax.Array, jitter: float | None = None) -> jax.Array:
    """Cholesky of a nominally-PSD matrix with symmetrization + jitter."""
    k = M.shape[-1]
    if jitter is None:
        jitter = default_jitter(M.dtype)
    return jnp.linalg.cholesky(sym(M) + jitter * jnp.eye(k, dtype=M.dtype))


def chol_solve(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve (L L') X = B given lower-triangular L.  B may be matrix or vector."""
    vec = B.ndim == L.ndim - 1
    if vec:
        B = B[..., None]
    X = solve_triangular(L, B, lower=True)
    X = solve_triangular(L, X, lower=True, trans=1)
    return X[..., 0] if vec else X


def chol_logdet(L: jax.Array) -> jax.Array:
    """log det(L L') from the Cholesky factor."""
    return 2.0 * jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)


def solve_psd(M: jax.Array, B: jax.Array, jitter: float | None = None) -> jax.Array:
    """Solve M X = B for symmetric PSD M via Cholesky."""
    return chol_solve(psd_cholesky(M, jitter), B)


def chol_unrolled(P: jax.Array, jitter: float = 0.0) -> jax.Array:
    """Batched Cholesky for SMALL static k, unrolled into elementwise ops.

    ``jnp.linalg.cholesky`` on (batch, k, k) with k ~ 4-8 lowers to a
    batched-linalg path that costs ~ms per call on this TPU toolchain —
    inside a ``lax.scan`` step that is the whole wall (measured: the S4
    loading smoother spent ~0.7 s/round in it, ~8x the rest of the pass;
    the S5 RBPF the same pattern per particle).  The unrolled form is
    ~k^2/2 Python-generated fused VPU ops over the batch: same math, same
    stability (it IS the textbook factorization), no linalg primitive.
    Use for k <= UNROLL_K_MAX; fall back to ``psd_cholesky`` above it.
    """
    k = P.shape[-1]
    L: list = [[None] * k for _ in range(k)]
    for i in range(k):
        s = P[..., i, i] + jitter
        for j in range(i):
            s = s - L[i][j] * L[i][j]
        # No clamp: a negative pivot must produce NaN exactly like the
        # jnp.linalg.cholesky paths this replaces (and the k > UNROLL_K_MAX
        # fallback), so indefinite inputs FAIL VISIBLY instead of silently
        # corrupting downstream weights/logdets.
        L[i][i] = jnp.sqrt(s)
        for r in range(i + 1, k):
            s2 = P[..., r, i]
            for j in range(i):
                s2 = s2 - L[r][j] * L[i][j]
            L[r][i] = s2 / L[i][i]
    zeros = jnp.zeros_like(P[..., 0, 0])
    rows = [jnp.stack([L[i][j] if j <= i else zeros for j in range(k)],
                      axis=-1) for i in range(k)]
    return jnp.stack(rows, axis=-2)


def matmul_vpu(A: jax.Array, B: jax.Array) -> jax.Array:
    """(..., i, j) x (..., j, l) -> (..., i, l) as broadcast multiply + sum.

    For SMALL static trailing dims inside scan loops: a batched (B, k, k)
    ``dot_general`` with k ~ 4-8 pads the MXU's 128-wide tiles ~97% empty
    and costs ~100x this fused-VPU form (measured — the S4/S5 hot-loop
    finding, docs/PERF.md).  Leading dims broadcast normally, so a global
    (k, k) factor composes with a batched (B, k, k) via ``A[None]``.
    Use real matmuls for anything with a large contracted axis.
    """
    return (A[..., :, :, None] * B[..., None, :, :]).sum(-2)


def matvec_vpu(A: jax.Array, v: jax.Array) -> jax.Array:
    """(..., i, j) x (..., j) -> (..., i); same rationale as matmul_vpu."""
    return (A * v[..., None, :]).sum(-1)


def chol_solve_unrolled(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve (L L') X = B by unrolled forward/back substitution.

    ``L`` from ``chol_unrolled`` (or any lower factor), ``B`` (..., k) or
    (..., k, r) with small static k and r.  Same result as ``chol_solve``;
    every op is an elementwise multiply-add over the batch dims.
    """
    vec = B.ndim == L.ndim - 1
    if vec:
        B = B[..., None]
    k = L.shape[-1]
    r = B.shape[-1]
    cols = []
    for c in range(r):
        y: list = [None] * k
        for i in range(k):
            s = B[..., i, c]
            for j in range(i):
                s = s - L[..., i, j] * y[j]
            y[i] = s / L[..., i, i]
        x: list = [None] * k
        for i in reversed(range(k)):
            s = y[i]
            for j in range(i + 1, k):
                s = s - L[..., j, i] * x[j]
            x[i] = s / L[..., i, i]
        cols.append(jnp.stack(x, axis=-1))
    X = jnp.stack(cols, axis=-1)
    return X[..., 0] if vec else X


def _unroll_small() -> bool:
    # The unrolled forms exist for the axon toolchain's pathological
    # small-linalg lowerings (CLAUDE.md; PERF.md item 6a).  On the CPU
    # backend LAPACK beats them badly (~2.5x on the whole lowrank scan at
    # r = 8: the ~k^2 fused scalar ops form one serial dependency chain),
    # so the gate is platform-aware.  Trace-time Python branch — resolved
    # once per compile, never inside the program.
    return jax.default_backend() == "tpu"


def chol_small(M: jax.Array, jitter: float = 0.0) -> jax.Array:
    """``chol_unrolled`` for k <= UNROLL_K_MAX on TPU, jitter-preserving
    ``jnp.linalg.cholesky`` otherwise — the standard gate for r x r
    factorizations inside scan bodies (the low-rank engine's
    S/Gamma/Sigma systems carry their own additive regularization, so the
    fallback must not add a second one)."""
    if M.shape[-1] <= UNROLL_K_MAX and _unroll_small():
        return chol_unrolled(M, jitter)
    k = M.shape[-1]
    return jnp.linalg.cholesky(M + jitter * jnp.eye(k, dtype=M.dtype))


def chol_solve_small(L: jax.Array, B: jax.Array) -> jax.Array:
    """``chol_solve_unrolled`` for small k on TPU, generic ``chol_solve``
    otherwise (same platform gate as ``chol_small``)."""
    if L.shape[-1] <= UNROLL_K_MAX and _unroll_small():
        return chol_solve_unrolled(L, B)
    return chol_solve(L, B)


def tria_unrolled(X: jax.Array) -> jax.Array:
    """Unrolled thin-QR "Tria" operator: lower-triangular L with L L' = X X'.

    ``X`` is (..., k, m) with SMALL static k (m is typically 2k: two stacked
    square-root factors side by side).  L is the transposed R factor of a
    thin QR of X' — computed here as modified Gram-Schmidt on the ROWS of X
    (k rows of length m), which is ~k^2/2 fused dot/axpy VPU ops over the
    batch dims and never touches a linalg primitive (``jnp.linalg.qr`` on
    (B, 2k, k) hits the same ~100x batched-linalg lowering penalty as the
    batched Cholesky this module already unrolls).  Unlike the Gram-matrix
    route chol(X X'), MGS never squares the condition number — this is the
    orthogonal-transformation stability the QR-factor filter rides on
    (PAPERS.md, arXiv 2502.11686).

    Exactly-zero rows (structural: t=0 elements carry Z = 0, fully-masked
    steps carry U = 0) produce a zero row in L; near-dependent rows resolve
    to a ~eps diagonal like any rank-revealing factorization would.  The
    diagonal of L is >= 0 by construction.
    """
    k = X.shape[-2]
    q: list = [None] * k
    L: list = [[None] * k for _ in range(k)]
    zero = jnp.zeros_like(X[..., 0, 0])
    for i in range(k):
        v = X[..., i, :]
        for j in range(i):
            c = (v * q[j]).sum(-1)
            L[i][j] = c
            v = v - c[..., None] * q[j]
        nrm = jnp.sqrt((v * v).sum(-1))
        L[i][i] = nrm
        nz = nrm[..., None] > 0
        q[i] = jnp.where(nz, v / jnp.where(nz, nrm[..., None], 1.0), 0.0)
    rows = [jnp.stack([L[i][j] if j <= i else zero for j in range(k)],
                      axis=-1) for i in range(k)]
    return jnp.stack(rows, axis=-2)


def tria(X: jax.Array) -> jax.Array:
    """``tria_unrolled`` for k <= QR_UNROLL_K_MAX, generic fallback above.

    The fallback forms the Gram matrix and takes its (jittered) Cholesky —
    mathematically the same L, acceptable for the large augmented states
    that only run in the f64 accumulation dtype anyway.
    """
    k = X.shape[-2]
    if k <= QR_UNROLL_K_MAX:
        return tria_unrolled(X)
    return psd_cholesky(X @ jnp.swapaxes(X, -1, -2))


def tri_solve_unrolled(L: jax.Array, B: jax.Array,
                       trans: bool = False) -> jax.Array:
    """Solve L X = B (or L' X = B with ``trans``) by unrolled substitution.

    ``L`` lower-triangular with small static k; ``B`` (..., k) or
    (..., k, r).  Every op is an elementwise multiply-add over the batch
    dims (the single-triangle half of ``chol_solve_unrolled``).  Division
    is guarded on exactly-zero pivots (structural zero rows from ``tria``/
    ``psd_factor`` factors): a zero pivot with a consistent RHS yields 0,
    matching the pseudo-inverse the semidefinite algebra expects.
    """
    vec = B.ndim == L.ndim - 1
    if vec:
        B = B[..., None]
    k = L.shape[-1]
    r = B.shape[-1]
    diag = [L[..., i, i] for i in range(k)]
    safe = [jnp.where(d > 0, d, 1.0) for d in diag]
    cols = []
    for c in range(r):
        x: list = [None] * k
        order = reversed(range(k)) if trans else range(k)
        for i in order:
            s = B[..., i, c]
            if trans:
                for j in range(i + 1, k):
                    s = s - L[..., j, i] * x[j]
            else:
                for j in range(i):
                    s = s - L[..., i, j] * x[j]
            x[i] = jnp.where(diag[i] > 0, s / safe[i], 0.0)
        cols.append(jnp.stack(x, axis=-1))
    X = jnp.stack(cols, axis=-1)
    return X[..., 0] if vec else X


def tri_solve(L: jax.Array, B: jax.Array, trans: bool = False) -> jax.Array:
    """``tri_solve_unrolled`` for small k, ``solve_triangular`` above."""
    if L.shape[-1] <= QR_UNROLL_K_MAX:
        return tri_solve_unrolled(L, B, trans=trans)
    vec = B.ndim == L.ndim - 1
    if vec:
        B = B[..., None]
    X = solve_triangular(L, B, lower=True, trans=1 if trans else 0)
    return X[..., 0] if vec else X


def psd_factor_unrolled(P: jax.Array) -> jax.Array:
    """Guarded Cholesky-type factor of a possibly-SINGULAR PSD matrix.

    Same unrolled elementwise structure as ``chol_unrolled``, but pivots
    at or below ~eps * diag are treated as exact zeros (zero row/column in
    the factor) instead of producing NaN.  This is a FACTOR-CONSTRUCTION
    helper for the square-root filter elements — observation precisions
    C_t = Lam' W R^{-1} Lam are rank-deficient whenever a step observes
    fewer than k series (and exactly zero on fully-masked steps), and the
    mixed-frequency augmented Q has rank k out of m.  ``chol_unrolled``
    keeps its fail-visibly contract for genuinely indefinite inputs; use
    THAT for matrices that must be positive definite.
    """
    k = P.shape[-1]
    eps = float(jnp.finfo(P.dtype).eps)
    L: list = [[None] * k for _ in range(k)]
    for i in range(k):
        s = P[..., i, i]
        for j in range(i):
            s = s - L[i][j] * L[i][j]
        tol = eps * k * jnp.abs(P[..., i, i])
        live = s > tol
        d = jnp.sqrt(jnp.where(live, s, 1.0))
        L[i][i] = jnp.where(live, d, 0.0)
        for r in range(i + 1, k):
            s2 = P[..., r, i]
            for j in range(i):
                s2 = s2 - L[r][j] * L[i][j]
            L[r][i] = jnp.where(live, s2 / d, 0.0)
    zeros = jnp.zeros_like(P[..., 0, 0])
    rows = [jnp.stack([L[i][j] if j <= i else zeros for j in range(k)],
                      axis=-1) for i in range(k)]
    return jnp.stack(rows, axis=-2)


def psd_factor(P: jax.Array) -> jax.Array:
    """``psd_factor_unrolled`` for small k; jittered Cholesky above it."""
    if P.shape[-1] <= QR_UNROLL_K_MAX:
        return psd_factor_unrolled(P)
    return psd_cholesky(P)

"""PSD-safe linear-algebra primitives shared by the JAX state-space code.

SURVEY.md section 7.2 item 1: float32 covariance recursions on TPU lose
symmetry/PSD-ness quickly; everything here exists to keep them sane.
Cholesky-only solves — no explicit inverses anywhere in the framework.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

__all__ = ["sym", "psd_cholesky", "chol_solve", "chol_logdet",
           "solve_psd", "default_jitter", "chol_unrolled",
           "chol_solve_unrolled", "matmul_vpu", "matvec_vpu",
           "UNROLL_K_MAX"]

# Unrolling is ~k^2/2 fused elementwise ops for the factorization and
# ~k^2 per solve column; past this bound compile time and op count beat
# the batched-linalg savings.
UNROLL_K_MAX = 8


def sym(M: jax.Array) -> jax.Array:
    """Symmetrize the trailing two axes."""
    return 0.5 * (M + jnp.swapaxes(M, -1, -2))


def default_jitter(dtype) -> float:
    """Diagonal jitter matched to precision: ~1e-10 in f64, ~1e-6 in f32."""
    return 1e-10 if jnp.dtype(dtype) == jnp.float64 else 1e-6


def psd_cholesky(M: jax.Array, jitter: float | None = None) -> jax.Array:
    """Cholesky of a nominally-PSD matrix with symmetrization + jitter."""
    k = M.shape[-1]
    if jitter is None:
        jitter = default_jitter(M.dtype)
    return jnp.linalg.cholesky(sym(M) + jitter * jnp.eye(k, dtype=M.dtype))


def chol_solve(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve (L L') X = B given lower-triangular L.  B may be matrix or vector."""
    vec = B.ndim == L.ndim - 1
    if vec:
        B = B[..., None]
    X = solve_triangular(L, B, lower=True)
    X = solve_triangular(L, X, lower=True, trans=1)
    return X[..., 0] if vec else X


def chol_logdet(L: jax.Array) -> jax.Array:
    """log det(L L') from the Cholesky factor."""
    return 2.0 * jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)


def solve_psd(M: jax.Array, B: jax.Array, jitter: float | None = None) -> jax.Array:
    """Solve M X = B for symmetric PSD M via Cholesky."""
    return chol_solve(psd_cholesky(M, jitter), B)


def chol_unrolled(P: jax.Array, jitter: float = 0.0) -> jax.Array:
    """Batched Cholesky for SMALL static k, unrolled into elementwise ops.

    ``jnp.linalg.cholesky`` on (batch, k, k) with k ~ 4-8 lowers to a
    batched-linalg path that costs ~ms per call on this TPU toolchain —
    inside a ``lax.scan`` step that is the whole wall (measured: the S4
    loading smoother spent ~0.7 s/round in it, ~8x the rest of the pass;
    the S5 RBPF the same pattern per particle).  The unrolled form is
    ~k^2/2 Python-generated fused VPU ops over the batch: same math, same
    stability (it IS the textbook factorization), no linalg primitive.
    Use for k <= UNROLL_K_MAX; fall back to ``psd_cholesky`` above it.
    """
    k = P.shape[-1]
    L: list = [[None] * k for _ in range(k)]
    for i in range(k):
        s = P[..., i, i] + jitter
        for j in range(i):
            s = s - L[i][j] * L[i][j]
        # No clamp: a negative pivot must produce NaN exactly like the
        # jnp.linalg.cholesky paths this replaces (and the k > UNROLL_K_MAX
        # fallback), so indefinite inputs FAIL VISIBLY instead of silently
        # corrupting downstream weights/logdets.
        L[i][i] = jnp.sqrt(s)
        for r in range(i + 1, k):
            s2 = P[..., r, i]
            for j in range(i):
                s2 = s2 - L[r][j] * L[i][j]
            L[r][i] = s2 / L[i][i]
    zeros = jnp.zeros_like(P[..., 0, 0])
    rows = [jnp.stack([L[i][j] if j <= i else zeros for j in range(k)],
                      axis=-1) for i in range(k)]
    return jnp.stack(rows, axis=-2)


def matmul_vpu(A: jax.Array, B: jax.Array) -> jax.Array:
    """(..., i, j) x (..., j, l) -> (..., i, l) as broadcast multiply + sum.

    For SMALL static trailing dims inside scan loops: a batched (B, k, k)
    ``dot_general`` with k ~ 4-8 pads the MXU's 128-wide tiles ~97% empty
    and costs ~100x this fused-VPU form (measured — the S4/S5 hot-loop
    finding, docs/PERF.md).  Leading dims broadcast normally, so a global
    (k, k) factor composes with a batched (B, k, k) via ``A[None]``.
    Use real matmuls for anything with a large contracted axis.
    """
    return (A[..., :, :, None] * B[..., None, :, :]).sum(-2)


def matvec_vpu(A: jax.Array, v: jax.Array) -> jax.Array:
    """(..., i, j) x (..., j) -> (..., i); same rationale as matmul_vpu."""
    return (A * v[..., None, :]).sum(-1)


def chol_solve_unrolled(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve (L L') X = B by unrolled forward/back substitution.

    ``L`` from ``chol_unrolled`` (or any lower factor), ``B`` (..., k) or
    (..., k, r) with small static k and r.  Same result as ``chol_solve``;
    every op is an elementwise multiply-add over the batch dims.
    """
    vec = B.ndim == L.ndim - 1
    if vec:
        B = B[..., None]
    k = L.shape[-1]
    r = B.shape[-1]
    cols = []
    for c in range(r):
        y: list = [None] * k
        for i in range(k):
            s = B[..., i, c]
            for j in range(i):
                s = s - L[..., i, j] * y[j]
            y[i] = s / L[..., i, i]
        x: list = [None] * k
        for i in reversed(range(k)):
            s = y[i]
            for j in range(i + 1, k):
                s = s - L[..., j, i] * x[j]
            x[i] = s / L[..., i, i]
        cols.append(jnp.stack(x, axis=-1))
    X = jnp.stack(cols, axis=-1)
    return X[..., 0] if vec else X

"""PSD-safe linear-algebra primitives shared by the JAX state-space code.

SURVEY.md section 7.2 item 1: float32 covariance recursions on TPU lose
symmetry/PSD-ness quickly; everything here exists to keep them sane.
Cholesky-only solves — no explicit inverses anywhere in the framework.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

__all__ = ["sym", "psd_cholesky", "chol_solve", "chol_logdet",
           "solve_psd", "default_jitter"]


def sym(M: jax.Array) -> jax.Array:
    """Symmetrize the trailing two axes."""
    return 0.5 * (M + jnp.swapaxes(M, -1, -2))


def default_jitter(dtype) -> float:
    """Diagonal jitter matched to precision: ~1e-10 in f64, ~1e-6 in f32."""
    return 1e-10 if jnp.dtype(dtype) == jnp.float64 else 1e-6


def psd_cholesky(M: jax.Array, jitter: float | None = None) -> jax.Array:
    """Cholesky of a nominally-PSD matrix with symmetrization + jitter."""
    k = M.shape[-1]
    if jitter is None:
        jitter = default_jitter(M.dtype)
    return jnp.linalg.cholesky(sym(M) + jitter * jnp.eye(k, dtype=M.dtype))


def chol_solve(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve (L L') X = B given lower-triangular L.  B may be matrix or vector."""
    vec = B.ndim == L.ndim - 1
    if vec:
        B = B[..., None]
    X = solve_triangular(L, B, lower=True)
    X = solve_triangular(L, X, lower=True, trans=1)
    return X[..., 0] if vec else X


def chol_logdet(L: jax.Array) -> jax.Array:
    """log det(L L') from the Cholesky factor."""
    return 2.0 * jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)


def solve_psd(M: jax.Array, B: jax.Array, jitter: float | None = None) -> jax.Array:
    """Solve M X = B for symmetric PSD M via Cholesky."""
    return chol_solve(psd_cholesky(M, jitter), B)

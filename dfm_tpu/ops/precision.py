"""Dtype policy helpers: where float64 enters a float32 pipeline.

Two DISTINCT policies exist in the framework, both depending on
``jax_enable_x64``; keeping them named here stops the call sites drifting:

- ``accum_dtype(dt)``: upgrade small ASSEMBLY work (loglik pieces, (T,)-
  sized reductions) to f64 whenever x64 is on — even on TPUs, where f64 is
  emulated, because the upgraded tensors are tiny and the alternative is
  a ~100x cancellation amplification (see info_filter.loglik_from_terms).
- ``accum_dtype(dt, native_only=True)``: upgrade only on backends with
  NATIVE f64 (CPU).  Use for SEQUENTIAL work — e.g. the mixed-frequency
  augmented-state scans — where emulated f64 multiplies the scan's
  wall-clock ~10x but highest-precision f32 is already sufficient.
- ``default_compute_dtype()``: the framework's compute-dtype default —
  f32 on accelerators (the MXU path), f64 on CPU when x64 is enabled
  (the golden/test regime).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["accum_dtype", "default_compute_dtype"]


def accum_dtype(compute_dtype, native_only: bool = False):
    if jax.config.jax_enable_x64 and (
            not native_only or jax.default_backend() == "cpu"):
        return jnp.float64
    return jnp.dtype(compute_dtype)


def default_compute_dtype():
    if jax.config.jax_enable_x64 and jax.default_backend() == "cpu":
        return jnp.dtype("float64")
    return jnp.dtype("float32")

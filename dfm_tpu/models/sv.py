"""Stochastic-volatility DFM via Rao-Blackwellized particle Kalman filter
(config S5, BASELINE.json:11; SURVEY.md sections 3.5, 7.1 M5).

Model:  y_t = Lam f_t + eps_t, eps ~ N(0, diag R);
        f_t = A f_{t-1} + eta_t, eta_t ~ N(0, diag(exp(h_t)));
        h_t = h_{t-1} + sigma_h * xi_t          (factor-innovation log-vols).

Conditional on the log-vol path {h_t} the model is linear-Gaussian, so a
particle filter need only sample h (Rao-Blackwellization): each particle
carries an EXACT Kalman state (x^m, P^m) plus its h^m, and the marginal
likelihood increment per particle is the Kalman innovation density.

TPU layout:

  - The k x k info-form state update is batched over M particles inside a
    lax.scan over T (batched Cholesky on the MXU-adjacent VPU path).
  - Loglik / weight pieces come in two forms (``SVSpec.quad_form``):
      * ``"residual"`` (default, cancellation-free): per-particle residuals
        V = y_t - Lam x_p are formed explicitly and v'R^{-1}v is a sum of
        positives — the RBPF analog of the residual pass the non-SV
        ``info_filter`` uses (its docstring measured ~1e-3 f32 error for the
        expanded form).  Costs one (M,k)x(k,N) + one (M,N)x(N,k) MXU matmul
        per step.
      * ``"expanded"`` (fast): v'R^{-1}v expanded as c2 - 2 x_p.b + x_p'Cx_p
        with the particle-independent reductions b_t = Lam'R^{-1}y_t and
        C = Lam'R^{-1}Lam precomputed as one big matmul.  Per-step work is
        pure k x k, but the expansion cancels in f32 at large N, so the
        REPORTED loglik (not the normalized weights, where shared terms
        cancel) is only ~1e-3-accurate — use for timing runs.
    In both forms the particle-independent constant -(n log 2pi + log|R|)/2
    (plus -c2_t/2 in the expanded form) is added OUTSIDE the jitted scan in
    float64 on host, and the T per-step increments are summed in float64, so
    accumulation error does not grow with T.
  - Resampling is jit-safe systematic resampling (sorted uniform positions +
    searchsorted + gather), triggered by ESS < ess_frac * M through lax.cond.

Estimation (``sv_fit``) is particle EM (a.k.a. Monte-Carlo EM):

  E-step: RBPF forward pass storing the particle h-cloud and weights, then
          FFBS (forward-filtering backward-sampling) draws smoothed h
          trajectories using the random-walk transition density.
  M-step: closed-form update of the per-factor vol-walk scale
          sigma_h,j^2 = E[ (h_t,j - h_t-1,j)^2 ] over draws and steps, and of
          the h_0 prior center.  sigma_h is a traced argument of the jitted
          filter, so EM iterations do not recompile.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.linalg import (UNROLL_K_MAX, chol_solve_unrolled, chol_unrolled,
                          matmul_vpu, matvec_vpu, sym)
from ..ssm.params import SSMParams

__all__ = ["SVSpec", "SVResult", "SVFit", "sv_filter", "sv_smooth_h",
           "sv_fit", "sv_forecast"]

_LOG2PI = 1.8378770664093453


@dataclasses.dataclass(frozen=True)
class SVSpec:
    n_factors: int
    n_particles: int = 512
    ess_frac: float = 0.5         # resample when ESS < ess_frac * M
    sigma_h: float = 0.1          # initial log-vol random-walk scale
    h0_scale: float = 0.1         # prior std of h_0 around its center
    quad_form: str = "residual"   # "residual" (exact) | "expanded" (fast)
    n_smooth_draws: int = 64      # FFBS trajectories for smoothing / EM


class SVResult(NamedTuple):
    loglik: np.ndarray            # scalar marginal loglik (f64 host assembly)
    f_mean: jax.Array             # (T, k) weighted filtered factor means
    h_mean: jax.Array             # (T, k) weighted filtered log-vols
    ess: jax.Array                # (T,) effective sample size per step
    n_resamples: jax.Array        # scalar
    h_particles: Optional[jax.Array]  # (T, M, k) filtering h-cloud (post-
                                      # resample); None if store_paths=False
    logw: Optional[jax.Array]         # (T, M) matching normalized log-weights
    lls: np.ndarray               # (T,) per-step loglik increments (f64)


def _systematic_indices(logW, key):
    """Jit-safe systematic resampling indices (M,) from normalized logW."""
    M = logW.shape[0]
    W = jnp.exp(logW)
    cum = jnp.cumsum(W)
    cum = cum / cum[-1]
    u = jax.random.uniform(key, (), dtype=cum.dtype)
    pos = (jnp.arange(M, dtype=cum.dtype) + u) / M
    return jnp.clip(jnp.searchsorted(cum, pos), 0, M - 1)


def _rbpf_scan(Y, Lam, R, C, B, A, mu0, P0, h_center, sigma_h, h0_scale, key,
               k: int, M: int, ess_frac: float, residual: bool,
               store_paths: bool, reduce_fn=lambda x: x):
    """The RBPF time scan over a (possibly local) series block.

    ``Y (T, n) / Lam (n, k) / R (n,)`` may be one device's shard; ``C/B``
    are the GLOBAL stats (psum'd by the caller under sharding) and
    ``reduce_fn`` sums the per-step residual reductions across shards
    (identity on a single device, psum inside ``shard_map`` — see
    ``parallel.sharded_sv``).  Everything except those reductions is
    replicated k/M-sized work, so the single-device and sharded paths run
    the IDENTICAL op sequence — matched PRNG keys give matching particle
    paths and resampling decisions up to psum rounding.
    """
    dtype = Y.dtype
    I_k = jnp.eye(k, dtype=dtype)
    Rinv = 1.0 / R
    LamT = Lam.T

    k0, k1 = jax.random.split(key)
    h = h_center[None, :] + h0_scale * jax.random.normal(k0, (M, k), dtype)
    x = jnp.broadcast_to(mu0, (M, k)).astype(dtype)
    P = jnp.broadcast_to(P0, (M, k, k)).astype(dtype)
    logW = jnp.full((M,), -jnp.log(float(M)), dtype)

    def step(carry, inp):
        x, P, h, logW, key, n_rs = carry
        y_t, b_t = inp
        key, kh, kr = jax.random.split(key, 3)
        # Propagate log-vols; per-particle predicted moments.
        h = h + sigma_h[None, :] * jax.random.normal(kh, (M, k), dtype)
        # Per-particle contractions via the VPU helpers (ops.linalg
        # matmul_vpu/matvec_vpu — batched small dot_generals cost ~100x);
        # only the (M, n) panel products below stay matmuls.
        x_p = matvec_vpu(A[None], x)                             # x A'
        P_p = matmul_vpu(matmul_vpu(A[None], P), A.T[None])      # A P A'
        P_p = P_p + jnp.exp(h)[:, :, None] * I_k[None]
        # Info-form update, batched over particles (k x k only).  Unrolled
        # small-k Cholesky: the batched-linalg primitives inside this scan
        # step dominate the pass wall otherwise (same finding as the S4
        # loading smoother — see ops.linalg.chol_unrolled).
        if k <= UNROLL_K_MAX:
            Lp = chol_unrolled(sym(P_p), jitter=1e-6)
        else:
            Lp = jnp.linalg.cholesky(sym(P_p) + 1e-6 * I_k[None])
        LpT = jnp.swapaxes(Lp, -1, -2)
        Gm = I_k[None] + matmul_vpu(LpT, matmul_vpu(C[None], Lp))
        if k <= UNROLL_K_MAX:
            Lg = chol_unrolled(Gm)
            Xs = chol_solve_unrolled(Lg, LpT)
        else:
            Lg = jnp.linalg.cholesky(Gm)
            Xs = jax.scipy.linalg.cho_solve((Lg, True), LpT)
        P_f = sym(matmul_vpu(Lp, Xs))

        def quad_form(P, u):                         # u' P u, (M,)
            return (matvec_vpu(P, u) * u).sum(-1)

        if residual:
            # Cancellation-free: true residuals per particle (module docstring).
            V = y_t[None, :] - x_p @ LamT             # (M, n_local) — MXU
            VR = V * Rinv[None, :]
            c2_p = reduce_fn((V * VR).sum(-1))        # v'R^{-1}v >= 0
            u = reduce_fn(VR @ Lam)                   # Lam'R^{-1}v, (M, k)
            quad = c2_p - quad_form(P_f, u)
        else:
            u = b_t[None, :] - matvec_vpu(C[None], x_p)
            quad = (-2.0 * (x_p * b_t[None, :]).sum(-1)
                    + (matvec_vpu(C[None], x_p) * x_p).sum(-1)
                    - quad_form(P_f, u))
        x_f = x_p + matvec_vpu(P_f, u)
        logdetG = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(Lg, axis1=-2, axis2=-1)), axis=-1)
        lw = -0.5 * (logdetG + quad)
        tot = logW + lw
        mx = jnp.max(tot)
        ll_rel = mx + jnp.log(jnp.sum(jnp.exp(tot - mx)))
        logW = tot - ll_rel                           # normalized
        ess = 1.0 / jnp.sum(jnp.exp(2.0 * logW))

        def do_resample(args):
            x_f, P_f, h, logW, kr = args
            idx = _systematic_indices(logW, kr)
            return (x_f[idx], P_f[idx], h[idx],
                    jnp.full((M,), -jnp.log(float(M)), dtype), 1)

        def no_resample(args):
            x_f, P_f, h, logW, _ = args
            return x_f, P_f, h, logW, 0

        x_f, P_f, h, logW, did = lax.cond(
            ess < ess_frac * M, do_resample, no_resample,
            (x_f, P_f, h, logW, kr))
        # Weighted filtered means; after resampling weights are uniform so
        # the gathered mean represents the same distribution.
        W = jnp.exp(logW)
        f_mean = W @ x_f
        h_mean = W @ h
        outs = (ll_rel, f_mean, h_mean, ess)
        if store_paths:
            # The FFBS smoother needs the filtering cloud; the filter-only
            # timing path skips this per-step M*(k+1) HBM write.
            outs = outs + (h, logW)
        return (x_f, P_f, h, logW, key, n_rs + did), outs

    carry, outs = lax.scan(step, (x, P, h, logW, k1, 0), (Y, B))
    if store_paths:
        ll_rel, f_mean, h_mean, ess, h_hist, logw_hist = outs
    else:
        ll_rel, f_mean, h_mean, ess = outs
        h_hist = logw_hist = None
    return ll_rel, f_mean, h_mean, ess, carry[5], h_hist, logw_hist


@partial(jax.jit,
         static_argnames=("k", "M", "ess_frac", "residual", "store_paths"))
def _sv_filter_impl(Y, p: SSMParams, h_center, sigma_h, h0_scale, key,
                    k: int, M: int, ess_frac: float, residual: bool,
                    store_paths: bool):
    # Statics are the individual shape/branch fields, NOT the whole SVSpec:
    # sweeping spec.sigma_h (particle EM, grid profiling) must not recompile.
    Rinv = 1.0 / p.R
    G0 = p.Lam * Rinv[:, None]                        # R^{-1} Lam, (N, k)
    C = p.Lam.T @ G0                                  # (k, k)
    B = Y @ G0                                        # (T, k)
    return _rbpf_scan(Y, p.Lam, p.R, C, B, p.A, p.mu0, p.P0, h_center,
                      sigma_h, h0_scale, key, k=k, M=M, ess_frac=ess_frac,
                      residual=residual, store_paths=store_paths)


def _as_sigma_vec(sigma_h, k, dtype):
    s = jnp.asarray(sigma_h, dtype)
    return jnp.broadcast_to(s, (k,)) if s.ndim == 0 else s


def _host_lls(ll_rel, Y, R64: np.ndarray, residual: bool) -> np.ndarray:
    """Host float64 assembly of the per-step loglik increments.

    Adds the particle-independent constant -(N log 2pi + log|R|)/2 (plus the
    -c2_t/2 data term the expanded quad omits in-scan) in float64, so
    accumulation error does not grow with T (module docstring).  Y and R64
    must be the UNPADDED panel/noise — shared by ``sv_filter`` and
    ``parallel.sharded_sv.sharded_sv_filter`` so the two paths cannot drift.
    """
    N = Y.shape[1]
    const = -0.5 * (N * _LOG2PI + np.sum(np.log(R64)))
    lls = np.asarray(ll_rel, np.float64) + const
    if not residual:
        Y64 = np.asarray(Y, np.float64)
        lls -= 0.5 * np.einsum("tn,n,tn->t", Y64, 1.0 / R64, Y64)
    return lls


def sv_filter(Y, p: SSMParams, spec: SVSpec,
              key: Optional[jax.Array] = None,
              h_center: Optional[jax.Array] = None,
              sigma_h=None, store_paths: bool = True) -> SVResult:
    """Rao-Blackwellized particle Kalman filter for the SV-DFM.

    ``p`` supplies (Lam, A, R, mu0, P0); the factor-innovation covariance is
    NOT p.Q but diag(exp(h_t)) with h_0 ~ N(h_center, h0_scale^2 I) — pass
    ``h_center=log(diag(Q_hat))`` from a standard EM pre-fit (default).
    ``sigma_h`` (scalar or (k,)) overrides ``spec.sigma_h`` — it is a traced
    argument, so sweeping it (particle EM) does not recompile.
    ``store_paths=False`` skips the (T, M, k) particle-history emission
    (needed only for FFBS smoothing) — the pure filter-timing mode.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    dtype = Y.dtype
    p = p.astype(dtype)
    if h_center is None:
        h_center = jnp.log(jnp.clip(jnp.diagonal(p.Q), 1e-8, None))
    sig = _as_sigma_vec(spec.sigma_h if sigma_h is None else sigma_h,
                        spec.n_factors, dtype)
    h0s = jnp.asarray(spec.h0_scale, dtype)
    # True-f32 matmul products: bf16-rounded residual matmuls (the XLA f32
    # default on TPU) distort the particle weights at large N.
    with jax.default_matmul_precision("highest"):
        ll_rel, f_mean, h_mean, ess, n_rs, h_hist, logw_hist = \
            _sv_filter_impl(
                Y, p, jnp.asarray(h_center, dtype), sig, h0s, key,
                k=spec.n_factors, M=spec.n_particles,
                ess_frac=spec.ess_frac,
                residual=spec.quad_form == "residual",
                store_paths=store_paths)
    lls = _host_lls(ll_rel, Y, np.asarray(p.R, np.float64),
                    residual=spec.quad_form == "residual")
    return SVResult(loglik=np.sum(lls), f_mean=f_mean, h_mean=h_mean,
                    ess=ess, n_resamples=n_rs, h_particles=h_hist,
                    logw=logw_hist, lls=lls)


@partial(jax.jit, static_argnames=("n_draws",))
def _ffbs_impl(h_hist, logw_hist, sigma_h, key, n_draws: int):
    T, M, k = h_hist.shape
    dtype = h_hist.dtype
    s2 = jnp.maximum(sigma_h.astype(dtype) ** 2, 1e-20)
    kT, kb = jax.random.split(key)
    g = jax.random.gumbel(kT, (n_draws, M), dtype)
    idx = jnp.argmax(logw_hist[-1][None, :] + g, axis=1)
    h_last = h_hist[-1][idx]                          # (S, k)

    def back(h_next, inp):
        h_t, logw_t, k_t = inp
        d2 = jnp.sum((h_next[:, None, :] - h_t[None, :, :]) ** 2
                     / s2[None, None, :], axis=-1)    # (S, M)
        logbw = logw_t[None, :] - 0.5 * d2
        g = jax.random.gumbel(k_t, logbw.shape, dtype)
        idx = jnp.argmax(logbw + g, axis=1)
        h_s = h_t[idx]
        return h_s, h_s

    keys = jax.random.split(kb, T - 1)
    _, hs = lax.scan(back, h_last,
                     (h_hist[:-1], logw_hist[:-1], keys), reverse=True)
    return jnp.concatenate([hs, h_last[None]], axis=0)   # (T, S, k)


def sv_smooth_h(res: SVResult, sigma_h, key, n_draws: int = 64) -> jax.Array:
    """FFBS: draw ``n_draws`` smoothed log-vol trajectories, shape (T, S, k).

    Backward weights combine the stored filtering weights with the
    random-walk transition density N(h_{t+1}; h_t, diag(sigma_h^2));
    sampling is jit-safe via the Gumbel-max trick.
    """
    if res.h_particles is None:
        raise ValueError(
            "sv_smooth_h needs the filtering particle history; run "
            "sv_filter with store_paths=True")
    k = res.h_particles.shape[-1]
    sig = _as_sigma_vec(sigma_h, k, res.h_particles.dtype)
    return _ffbs_impl(res.h_particles, res.logw, sig, key, n_draws)


@dataclasses.dataclass
class SVFit:
    params: object               # cpu_ref.SSMParams from the EM pre-fit
    result: SVResult             # filter output at the final SV parameters
    vol_paths: np.ndarray        # (T, k) smoothed vol proxy exp(h_smooth/2)
    loglik: float
    sigma_h: np.ndarray = None   # (k,) estimated vol-walk scales
    h_center: np.ndarray = None  # (k,) estimated h_0 prior center
    h_smooth: np.ndarray = None  # (T, k) FFBS-smoothed log-vol means
    logliks: np.ndarray = None   # per-SV-iteration marginal logliks
    standardizer: object = None  # utils.data.Standardizer from the pre-fit
    health: object = None        # robust.FitHealth trace record


def sv_forecast(fit: SVFit, horizon: int):
    """h-step forecast for the SV-DFM, mirroring ``api.forecast``'s
    contract (SURVEY.md section 3.2 extended to the SV family).

    Conditional MEANS are the homoskedastic iteration — volatility moves
    bands, not means: f_{T+j} = A^j f_T from the filtered particle mean,
    y = f Lam' de-standardized.  The third return is the factor-innovation
    vol forecast E[exp(h_{T+j}/2)] under the log-vol random walk,
    = exp(h_T/2 + j sigma_h^2 / 8) (lognormal mean of h ~ N(h_T, j s^2)).
    Returns (y_fore (h, N), f_fore (h, k), vol_fore (h, k)).
    """
    A = np.asarray(fit.params.A, np.float64)
    Lam = np.asarray(fit.params.Lam, np.float64)
    k = A.shape[0]
    x = np.asarray(fit.result.f_mean[-1], np.float64)
    h_T = np.asarray(fit.h_smooth[-1], np.float64)
    s2 = np.asarray(fit.sigma_h, np.float64) ** 2 \
        if fit.sigma_h is not None else np.zeros(k)
    f = np.zeros((horizon, k))
    vol = np.zeros((horizon, k))
    for j in range(horizon):
        x = A @ x
        f[j] = x
        vol[j] = np.exp(0.5 * h_T + (j + 1) * s2 / 8.0)
    y = f @ Lam.T
    if fit.standardizer is not None:
        y = fit.standardizer.inverse(y)
    return y, f, vol


def sv_fit(Y: np.ndarray, spec: SVSpec, em_iters: int = 20,
           key: Optional[jax.Array] = None, backend="tpu",
           standardize: bool = True, sv_iters: int = 10,
           sv_accel: float = 3.0, estimate_sv: bool = True,
           mesh=None) -> SVFit:
    """SV-DFM estimation (BASELINE.json:11; SURVEY.md section 3.5):

    1. EM pre-fit of the homoskedastic DFM (Lam, A, Q, R) — info-form path.
    2. Particle EM for the SV law: RBPF E-step + FFBS h-trajectory draws,
       closed-form M-step for the per-factor vol-walk scale sigma_h and the
       h_0 center (module docstring).  ``estimate_sv=False`` reproduces the
       old two-stage behavior (filter once at spec.sigma_h).

    ``sv_accel`` over-relaxes the M-step in the log domain
    (sigma <- sigma * (sigma_EM/sigma)^accel): plain EM for a random-walk
    variance contracts very slowly (~0.95/iter measured on simulated data,
    the missing-information fraction is large), and over-relaxation stays
    convergent for accel << 2/(1-contraction) — 3.0 is far inside that and
    was verified stable at the fixed point on simulated panels.

    The marginal loglik is a particle estimate, so it is monotone only up to
    Monte-Carlo noise; convergence is left to the fixed ``sv_iters`` budget.

    ``mesh``: a 1-D ``jax.sharding.Mesh`` routes every RBPF E-step through
    the series-sharded filter (``parallel.sharded_sv``) — S5's full particle
    EM on a multi-chip topology; the EM pre-fit shards via
    ``backend="sharded"``.
    """
    from ..api import DynamicFactorModel, fit as _fit
    from ..ssm.params import SSMParams as JP
    model = DynamicFactorModel(n_factors=spec.n_factors,
                               standardize=standardize)
    pre = _fit(model, Y, backend=backend, max_iters=em_iters)
    Yz = np.asarray(Y, np.float64)
    if pre.standardizer is not None:
        Yz = pre.standardizer.transform(Yz)
    from ..ops.precision import default_compute_dtype
    dtype = default_compute_dtype()
    pj = JP.from_numpy(pre.params, dtype=dtype)
    Yj = jnp.asarray(Yz, dtype)
    if key is None:
        key = jax.random.PRNGKey(0)

    k = spec.n_factors
    sigma = jnp.full((k,), spec.sigma_h, dtype)
    h_center = jnp.log(jnp.clip(jnp.diagonal(pj.Q), 1e-8, None))
    if sv_iters <= 0:
        estimate_sv = False
    SIGMA_FLOOR = 1e-4   # below this the model is effectively homoskedastic
    if estimate_sv:
        sigma = jnp.maximum(sigma, SIGMA_FLOOR)   # log-step needs sigma > 0

    def e_step(key, sigma, h_center, smooth):
        kf_, ks_ = jax.random.split(key)
        if mesh is not None:
            # Series-sharded RBPF (parallel.sharded_sv): the particle cloud
            # and its stored history come back replicated, so the FFBS pass
            # below is unchanged — the entire particle EM runs multi-chip.
            from ..parallel.sharded_sv import sharded_sv_filter
            res = sharded_sv_filter(Yj, pj, spec, key=kf_,
                                    h_center=h_center, sigma_h=sigma,
                                    store_paths=smooth, mesh=mesh)
        else:
            res = sv_filter(Yj, pj, spec, key=kf_, h_center=h_center,
                            sigma_h=sigma, store_paths=smooth)
        H = (sv_smooth_h(res, sigma, ks_, spec.n_smooth_draws)
             if smooth else None)
        return res, H

    logliks = []
    prev_step = None
    for _ in range(sv_iters if estimate_sv else 1):
        key, k_ = jax.random.split(key)
        res, H = e_step(k_, sigma, h_center, smooth=estimate_sv)
        logliks.append(float(res.loglik))
        if estimate_sv:
            dH = jnp.diff(H, axis=0)
            sigma_em = jnp.sqrt(jnp.mean(dH ** 2, axis=(0, 1)))
            # Over-relaxed log-domain step, with two safeguards: fall back
            # to plain EM (accel 1) per factor when the step direction flips
            # (over-relaxation oscillates when EM contracts fast), and floor
            # sigma so a collapsed estimate cannot divide-by-zero or NaN.
            step = jnp.log(jnp.maximum(sigma_em, SIGMA_FLOOR)) - jnp.log(sigma)
            accel = (jnp.where(step * prev_step < 0, 1.0, sv_accel)
                     if prev_step is not None else sv_accel)
            sigma = jnp.maximum(sigma * jnp.exp(accel * step), SIGMA_FLOOR)
            prev_step = step
            h_center = jnp.mean(H[0], axis=0)
    if estimate_sv:
        # One final E-step at the returned (sigma_h, h_center), so result /
        # loglik / h_smooth are consistent with the reported parameters.
        key, k_ = jax.random.split(key)
        res, H = e_step(k_, sigma, h_center, smooth=True)
        logliks.append(float(res.loglik))
    # Without estimation no FFBS pass runs (keeps the filter-only timing
    # path pure); the smoothed proxy is then the filtered h mean.
    h_smooth = np.asarray(jnp.mean(H, axis=1) if H is not None
                          else res.h_mean, np.float64)
    from ..robust.health import health_from_trace
    return SVFit(params=pre.params, result=res,
                 vol_paths=np.exp(0.5 * h_smooth),
                 loglik=logliks[-1],
                 sigma_h=np.asarray(sigma, np.float64),
                 h_center=np.asarray(h_center, np.float64),
                 h_smooth=h_smooth,
                 logliks=np.asarray(logliks),
                 standardizer=pre.standardizer,
                 # MC particle logliks are noisy by construction: record only
                 # non-finite values, never count monotonicity "violations".
                 health=health_from_trace(logliks, noise_floor=np.inf))

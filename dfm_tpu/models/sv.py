"""Stochastic-volatility DFM via Rao-Blackwellized particle Kalman filter
(config S5, BASELINE.json:11; SURVEY.md sections 3.5, 7.1 M5).

Model:  y_t = Lam f_t + eps_t, eps ~ N(0, diag R);
        f_t = A f_{t-1} + eta_t, eta_t ~ N(0, diag(exp(h_t)));
        h_t = h_{t-1} + sigma_h * xi_t          (factor-innovation log-vols).

Conditional on the log-vol path {h_t} the model is linear-Gaussian, so a
particle filter need only sample h (Rao-Blackwellization): each particle
carries an EXACT Kalman state (x^m, P^m) plus its h^m, and the marginal
likelihood increment per particle is the Kalman innovation density.

TPU layout (the whole point of this implementation):

  - The info-form observation reductions b_t = Lam'R^{-1}y_t (T, k) and
    C = Lam'R^{-1}Lam (k, k) are PARTICLE-INDEPENDENT — computed once as one
    big MXU matmul before the scan.  Per-particle, per-step work is pure
    k x k (batched Cholesky over M particles inside a lax.scan over T).
  - Particle WEIGHTS need only the particle-dependent loglik pieces
    (-2 x_p.b + x_p'C x_p - u'P_f u + log|G^m|); the large shared terms
    (n log 2pi + log|R| + y'R^{-1}y) are identical across particles, so they
    cancel in normalized weights and are added to the total loglik outside
    the softmax — which also sidesteps the f32 large-term cancellation that
    the non-SV filter solves with a residual pass (info_filter docstring).
  - Resampling is jit-safe systematic resampling (sorted uniform positions +
    searchsorted + gather), triggered by ESS < M/2 through lax.cond.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.linalg import sym
from ..ssm.params import SSMParams

__all__ = ["SVSpec", "SVResult", "sv_filter", "sv_fit"]

_LOG2PI = 1.8378770664093453


@dataclasses.dataclass(frozen=True)
class SVSpec:
    n_factors: int
    n_particles: int = 512
    ess_frac: float = 0.5         # resample when ESS < ess_frac * M
    sigma_h: float = 0.1          # log-vol random-walk scale
    h0_scale: float = 0.1         # prior std of h_0 around its center


class SVResult(NamedTuple):
    loglik: jax.Array             # scalar marginal loglik estimate
    f_mean: jax.Array             # (T, k) weighted filtered factor means
    h_mean: jax.Array             # (T, k) weighted filtered log-vols
    ess: jax.Array                # (T,) effective sample size per step
    n_resamples: jax.Array        # scalar


def _systematic_indices(logW, key):
    """Jit-safe systematic resampling indices (M,) from normalized logW."""
    M = logW.shape[0]
    W = jnp.exp(logW)
    cum = jnp.cumsum(W)
    cum = cum / cum[-1]
    u = jax.random.uniform(key, (), dtype=cum.dtype)
    pos = (jnp.arange(M, dtype=cum.dtype) + u) / M
    return jnp.clip(jnp.searchsorted(cum, pos), 0, M - 1)


@partial(jax.jit, static_argnames=("spec",))
def _sv_filter_impl(Y, p: SSMParams, h_center, key, spec: SVSpec):
    dtype = Y.dtype
    T, N = Y.shape
    k = spec.n_factors
    M = spec.n_particles
    I_k = jnp.eye(k, dtype=dtype)
    A = p.A

    # Shared (particle-independent) observation reductions — one big matmul.
    Rinv = 1.0 / p.R
    G0 = p.Lam * Rinv[:, None]
    B = Y @ G0                                        # (T, k)
    C = p.Lam.T @ G0                                  # (k, k)
    c2 = jnp.einsum("tn,n,tn->t", Y, Rinv, Y)         # (T,)
    ldR = jnp.sum(jnp.log(p.R))
    shared = -0.5 * (N * _LOG2PI + ldR + c2)          # (T,)

    k0, k1, k2 = jax.random.split(key, 3)
    h = h_center[None, :] + spec.h0_scale * jax.random.normal(
        k0, (M, k), dtype)
    x = jnp.broadcast_to(p.mu0, (M, k)).astype(dtype)
    P = jnp.broadcast_to(p.P0, (M, k, k)).astype(dtype)
    logW = jnp.full((M,), -jnp.log(float(M)), dtype)

    def step(carry, inp):
        x, P, h, logW, key, n_rs = carry
        y_b, t_shared = inp
        key, kh, kr = jax.random.split(key, 3)
        # Propagate log-vols; per-particle predicted moments.
        h = h + spec.sigma_h * jax.random.normal(kh, (M, k), dtype)
        x_p = x @ A.T
        P_p = jnp.einsum("ij,mjl,kl->mik", A, P, A)
        P_p = P_p + jnp.exp(h)[:, :, None] * I_k[None]
        # Info-form update, batched over particles (k x k only).
        Lp = jnp.linalg.cholesky(sym(P_p) + 1e-6 * I_k[None])
        CL = jnp.einsum("kl,mln->mkn", C, Lp)
        Gm = I_k[None] + jnp.einsum("mlk,mln->mkn", Lp, CL)
        Lg = jnp.linalg.cholesky(Gm)
        LpT = jnp.swapaxes(Lp, -1, -2)
        P_f = jnp.einsum("mkl,mln->mkn",
                         Lp, jax.scipy.linalg.cho_solve((Lg, True), LpT))
        P_f = sym(P_f)
        u = y_b[None, :] - x_p @ C.T                  # (M, k)
        x_f = x_p + jnp.einsum("mkl,ml->mk", P_f, u)
        logdetG = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(Lg, axis1=-2, axis2=-1)), axis=-1)
        # Particle-dependent loglik pieces (shared terms cancel in weights).
        quad_p = (-2.0 * (x_p @ y_b) + jnp.einsum("mk,kl,ml->m", x_p, C, x_p)
                  - jnp.einsum("mk,mkl,ml->m", u, P_f, u))
        lw = -0.5 * (logdetG + quad_p)
        tot = logW + lw
        mx = jnp.max(tot)
        ll_rel = mx + jnp.log(jnp.sum(jnp.exp(tot - mx)))
        ll_t = ll_rel + t_shared
        logW = tot - ll_rel                           # normalized
        ess = 1.0 / jnp.sum(jnp.exp(2.0 * logW))

        def do_resample(args):
            x_f, P_f, h, logW, kr = args
            idx = _systematic_indices(logW, kr)
            return (x_f[idx], P_f[idx], h[idx],
                    jnp.full((M,), -jnp.log(float(M)), dtype), 1)

        def no_resample(args):
            x_f, P_f, h, logW, _ = args
            return x_f, P_f, h, logW, 0

        x_f, P_f, h, logW, did = lax.cond(
            ess < spec.ess_frac * M, do_resample, no_resample,
            (x_f, P_f, h, logW, kr))
        # Weighted filtered means BEFORE resampling would be ideal; after
        # resampling weights are uniform so the gathered mean is identical.
        W = jnp.exp(logW)
        f_mean = W @ x_f
        h_mean = W @ h
        return ((x_f, P_f, h, logW, key, n_rs + did),
                (ll_t, f_mean, h_mean, ess))

    (carry, (lls, f_mean, h_mean, ess)) = lax.scan(
        step, (x, P, h, logW, k1, 0), (B, shared))
    return SVResult(loglik=jnp.sum(lls), f_mean=f_mean, h_mean=h_mean,
                    ess=ess, n_resamples=carry[5])


def sv_filter(Y, p: SSMParams, spec: SVSpec,
              key: Optional[jax.Array] = None,
              h_center: Optional[jax.Array] = None) -> SVResult:
    """Rao-Blackwellized particle Kalman filter for the SV-DFM.

    ``p`` supplies (Lam, A, R, mu0, P0); the factor-innovation covariance is
    NOT p.Q but diag(exp(h_t)) with h_0 ~ N(h_center, h0_scale^2 I) — pass
    ``h_center=log(diag(Q_hat))`` from a standard EM pre-fit (default).
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    dtype = Y.dtype
    p = p.astype(dtype)
    if h_center is None:
        h_center = jnp.log(jnp.clip(jnp.diagonal(p.Q), 1e-8, None))
    return _sv_filter_impl(Y, p, jnp.asarray(h_center, dtype), key, spec)


@dataclasses.dataclass
class SVFit:
    params: object               # cpu_ref.SSMParams from the EM pre-fit
    result: SVResult
    vol_paths: np.ndarray        # (T, k) E[exp(h_t/2)] proxy: exp(h_mean/2)
    loglik: float


def sv_fit(Y: np.ndarray, spec: SVSpec, em_iters: int = 20,
           key: Optional[jax.Array] = None, backend: str = "tpu",
           standardize: bool = True) -> SVFit:
    """Two-stage estimation (standard for RBPF SV models):

    1. EM pre-fit of the homoskedastic DFM (Lam, A, Q, R) — info-form path.
    2. RBPF over log-vol paths with h centered on log diag(Q_hat), yielding
       the SV marginal likelihood, filtered factors, and vol paths.
    """
    from ..api import DynamicFactorModel, fit as _fit
    from ..ssm.params import SSMParams as JP
    model = DynamicFactorModel(n_factors=spec.n_factors,
                               standardize=standardize)
    pre = _fit(model, Y, backend=backend, max_iters=em_iters)
    Yz = np.asarray(Y, np.float64)
    if pre.standardizer is not None:
        Yz = pre.standardizer.transform(Yz)
    dtype = (jnp.float64 if jax.config.jax_enable_x64
             and jax.default_backend() == "cpu" else jnp.float32)
    pj = JP.from_numpy(pre.params, dtype=dtype)
    res = sv_filter(jnp.asarray(Yz, dtype), pj, spec, key=key)
    return SVFit(params=pre.params, result=res,
                 vol_paths=np.exp(0.5 * np.asarray(res.h_mean, np.float64)),
                 loglik=float(res.loglik))

"""Time-varying-loadings DFM (config S4, BASELINE.json:10; SURVEY.md M4).

Model:  y_it = lam_it' f_t + eps_it,  lam_it = lam_i,t-1 + xi_it (random walk,
Var xi = tau2_i I);  f_t = A f_{t-1} + eta_t.

The naive formulation puts all N*k loadings in the state (dim k(N+1) — 25k at
the S4 scale, infeasible; SURVEY.md section 7.2 item 4).  Instead the model
factorizes: CONDITIONAL on the factor path the N loading processes are
independent k-dim linear-Gaussian chains, and conditional on the loading
paths the factors follow a time-varying-loadings SSM the information-form
filter already handles (C_t, b_t simply become per-t einsums).  Estimation
alternates the two exact conditional smoothers (a dual-Kalman/EM-style
coordinate scheme):

  A-step  factors | loadings:  info-form filter/smoother with Lam_t (T,N,k)
  B-step  loadings | factors:  N independent scalar-observation Kalman
          filters, batched as ONE lax.scan over time carrying (N,k) means and
          (N,k,k) covariances — rank-1 updates, no solves, pure vector ops
  M-bits  R, tau2 from smoothed residuals/increments; A, Q from factor
          moments (same closed forms as the core EM)

Both directions are large batched scans — the TPU-native layout for this
model family.  Exact joint likelihood is intractable (bilinear); the reported
loglik is the factor-filter loglik conditional on the current loading paths,
which is the standard convergence monitor for dual estimation.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.linalg import (UNROLL_K_MAX, chol_solve_unrolled, chol_unrolled,
                          matmul_vpu, matvec_vpu, solve_psd, sym)
from ..ssm.info_filter import (ObsStats, info_scan, loglik_from_terms)
from ..ssm.params import FilterResult, SmootherResult
from ..ssm.kalman import rts_smoother
from ..ssm.params import SSMParams

__all__ = ["TVLSpec", "TVLParams", "tvl_fit", "tvl_forecast", "TVLResult",
           "factor_pass_tv", "loading_pass", "tvl_round_core",
           "tvl_round_scan", "tvl_loglik_eval"]


@dataclasses.dataclass(frozen=True)
class TVLSpec:
    n_factors: int
    n_rounds: int = 10
    tol: float = 1e-6
    estimate_tau2: bool = True
    r_floor: float = 1e-6
    tau2_floor: float = 1e-10


class TVLParams(NamedTuple):
    """Lam0 (N, k) initial loadings; tau2 (N,) loading-walk variances;
    A, Q (k, k); R (N,); mu0 (k,); P0 (k, k)."""

    Lam0: jax.Array
    tau2: jax.Array
    A: jax.Array
    Q: jax.Array
    R: jax.Array
    mu0: jax.Array
    P0: jax.Array

    def astype(self, dtype):
        return TVLParams(*(jnp.asarray(x, dtype) for x in self))


# ---------------------------------------------------------------------------
# A-step: factor filter/smoother with time-varying loadings (info form)
# ---------------------------------------------------------------------------

def obs_stats_tv(Y, Lam_t, R, mask=None) -> ObsStats:
    """Info-form observation stats with per-t loadings Lam_t (T, N, k)."""
    dtype = Y.dtype
    T, N = Y.shape
    Rinv = 1.0 / R
    logR = jnp.log(R)
    if mask is None:
        b = jnp.einsum("tn,n,tnk->tk", Y, Rinv, Lam_t)
        C = jnp.einsum("tnk,n,tnl->tkl", Lam_t, Rinv, Lam_t)
        n = jnp.full((T,), float(N), dtype)
        ldR = jnp.full((T,), jnp.sum(logR), dtype)
    else:
        W = mask.astype(dtype)
        Yw = W * jnp.nan_to_num(Y)
        b = jnp.einsum("tn,n,tnk->tk", Yw, Rinv, Lam_t)
        C = jnp.einsum("tnk,tn,n,tnl->tkl", Lam_t, W, Rinv, Lam_t)
        n = W.sum(axis=1)
        ldR = W @ logR
    return ObsStats(b, C, n, ldR)


def factor_pass_tv(Y, Lam_t, p: TVLParams, mask=None,
                   reduce_tree=lambda x: x):
    """Filter + RTS smoother over factors given loading paths.

    Returns (FilterResult, SmootherResult); loglik is conditional on Lam_t.
    ``reduce_tree`` sums the series-axis reductions across shards (identity
    on one device, psum in ``parallel.sharded_tvl``).
    """
    stats = reduce_tree(obs_stats_tv(Y, Lam_t, p.R, mask=mask))
    xp, Pp, xf, Pf, logdetG = info_scan(stats, p.A, p.Q, p.mu0, p.P0)
    V = Y - jnp.einsum("tnk,tk->tn", Lam_t, xp)
    if mask is not None:
        V = mask.astype(Y.dtype) * jnp.nan_to_num(V)
    VR = V / p.R[None, :]
    quad_R, U = reduce_tree((jnp.einsum("tn,tn->t", V, VR),
                             jnp.einsum("tn,tnk->tk", VR, Lam_t)))
    ll = loglik_from_terms(stats, logdetG, Pf, quad_R, U)
    kf = FilterResult(xp, Pp, xf, Pf, ll)
    dummy = SSMParams(Lam=Lam_t[0], A=p.A, Q=p.Q, R=p.R, mu0=p.mu0, P0=p.P0)
    return kf, rts_smoother(kf, dummy)


# ---------------------------------------------------------------------------
# B-step: batched loading filter/smoother given the factor path
# ---------------------------------------------------------------------------

def loading_pass(Y, F, p: TVLParams, mask=None):
    """N independent k-dim random-walk chains, one scan over time.

    Scalar observation per (t, i): y_it = F_t' lam_it + eps.  The update is
    rank-1 (gain K = P f / (f'Pf + R)) so the whole cross-section advances
    with einsums only — no linear solves anywhere.

    Returns (lam_sm (T, N, k), P_sm (T, N, k, k), incr (N,), counts used for
    tau2), where incr accumulates E[|lam_t - lam_{t-1}|^2] for the tau2
    update (exact, using the random-walk smoother identities).
    """
    dtype = Y.dtype
    T, N = Y.shape
    k = p.A.shape[0]
    I_k = jnp.eye(k, dtype=dtype)
    tau2 = p.tau2
    R = p.R
    W = None if mask is None else mask.astype(dtype)
    Yz = jnp.nan_to_num(Y) if mask is None else jnp.nan_to_num(Y) * W

    def fstep(carry, inp):
        # Every contraction is a VPU broadcast-multiply+sum over the STATIC
        # k axis (ops.linalg matmul_vpu rationale): batched (N, 4, 4)
        # dot_generals cost ~100x on TPU.
        lam, P = carry                   # (N, k), (N, k, k) filtered t-1
        y_t, f_t, w_t = inp
        P_pred = P + tau2[:, None, None] * I_k[None]
        Pf = matvec_vpu(P_pred, f_t[None])              # (N, k)
        S = (Pf * f_t[None, :]).sum(-1) + R             # (N,)
        gate = w_t if w_t is not None else jnp.ones((N,), dtype)
        K = gate[:, None] * Pf / S[:, None]             # (N, k)
        v = y_t - (lam * f_t[None, :]).sum(-1)          # innovation vs pred
        lam_f = lam + K * v[:, None]
        P_f = P_pred - K[:, :, None] * Pf[:, None, :]
        P_f = sym(P_f)
        return (lam_f, P_f), (lam, P_pred, lam_f, P_f)

    lam0 = jnp.broadcast_to(p.Lam0, (N, k))
    P0 = jnp.broadcast_to((1e-2 + tau2)[:, None, None] * I_k[None],
                          (N, k, k))
    if W is None:
        (_, _), (lam_pr, P_pr, lam_fs, P_fs) = lax.scan(
            lambda c, i: fstep(c, (i[0], i[1], None)), (lam0, P0), (Yz, F))
    else:
        (_, _), (lam_pr, P_pr, lam_fs, P_fs) = lax.scan(
            lambda c, i: fstep(c, i), (lam0, P0), (Yz, F, W))

    # RTS for the random walk: J_t = P_f[t] (P_pred[t+1])^{-1}; both are
    # (N, k, k) PSD; batched Cholesky solve over (T-1, N).
    small_k = k <= UNROLL_K_MAX

    def bstep(carry, inp):
        lam_n, P_n, incr = carry         # smoothed at t+1, running increment
        lam_f, P_f, lam_p_next, P_p_next = inp
        # J' = solve(P_pred, P_f) via Cholesky.  The unrolled small-k path
        # is ~8x the batched-linalg one here (docs/PERF.md S4 note): the
        # (N, k, k) jnp.linalg.cholesky + cho_solve inside this scan step
        # WAS the whole S4 wall.
        if small_k:
            tmp = chol_solve_unrolled(chol_unrolled(P_p_next), P_f)
        else:
            L = jnp.linalg.cholesky(P_p_next)
            tmp = jax.scipy.linalg.cho_solve((L, True), P_f)  # (N,k,k) = J'
        J = jnp.swapaxes(tmp, -1, -2)
        JT = tmp
        lam_s = lam_f + matvec_vpu(J, lam_n - lam_p_next)
        P_s = sym(P_f + matmul_vpu(matmul_vpu(J, P_n - P_p_next), JT))
        # E|lam_{t+1} - lam_t|^2 = |dlam|^2 + tr(P_s[t+1]) + tr(P_s[t])
        #                          - 2 tr(P_lag), P_lag = P_sm[t+1] J'
        P_lag = matmul_vpu(P_n, JT)
        d = lam_n - lam_s
        incr = incr + (jnp.einsum("nk,nk->n", d, d)
                       + jnp.trace(P_n, axis1=-2, axis2=-1)
                       + jnp.trace(P_s, axis1=-2, axis2=-1)
                       - 2.0 * jnp.trace(P_lag, axis1=-2, axis2=-1))
        return (lam_s, P_s, incr), (lam_s, P_s)

    init = (lam_fs[-1], P_fs[-1], jnp.zeros((N,), dtype))
    inps = (lam_fs[:-1], P_fs[:-1], lam_pr[1:], P_pr[1:])
    (lam_s0, P_s0, incr), (lam_rev, P_rev) = lax.scan(
        bstep, init, inps, reverse=True)
    lam_sm = jnp.concatenate([lam_rev, lam_fs[-1:]], axis=0)
    P_sm = jnp.concatenate([P_rev, P_fs[-1:]], axis=0)
    return lam_sm, P_sm, incr


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def tvl_round_core(Y, mask, Lam_t, p: TVLParams, spec: TVLSpec,
                   reduce_tree=lambda x: x):
    """One alternation round (shared single-device / per-shard body).

    Returns (Lam_t', params', loglik, F_sm).  Only the A-step's k-sized
    observation reductions cross shards; the B-step loading chains, R and
    tau2 updates are per-series local (SURVEY.md section 2.3 layout).
    """
    m = mask
    dtype = Y.dtype
    T, N = Y.shape
    k = spec.n_factors

    # A-step: factors given loadings.
    kf, sm = factor_pass_tv(Y, Lam_t, p, mask=m, reduce_tree=reduce_tree)
    F = sm.x_sm

    # Factor-dynamics M-bits (exact given the factor smoother).
    EffT = sm.P_sm + jnp.einsum("ti,tj->tij", F, F)
    cross = sm.P_lag[1:] + jnp.einsum("ti,tj->tij", F[1:], F[:-1])
    S_lag = EffT[:-1].sum(0)
    S_cur = EffT[1:].sum(0)
    S_cross = cross.sum(0)
    A = solve_psd(S_lag, S_cross.T).T
    Q = sym((S_cur - A @ S_cross.T) / (T - 1))

    # B-step: loadings given (smoothed-mean) factor path.
    lam_sm, P_sm_l, incr = loading_pass(Y, F, p, mask=m)

    # R update: conditional residuals + loading-uncertainty smear.
    W = mask.astype(dtype) if mask is not None else jnp.ones_like(Y)
    Yz = jnp.nan_to_num(Y) * W
    resid = Yz - W * jnp.einsum("tnk,tk->tn", lam_sm, F)
    smear = jnp.einsum("tn,tnkl,tk,tl->n", W, P_sm_l, F, F)
    counts = jnp.maximum(W.sum(0), 1.0)
    R = jnp.maximum((jnp.einsum("tn,tn->n", resid, resid) + smear) / counts,
                    spec.r_floor)

    tau2 = p.tau2
    if spec.estimate_tau2:
        tau2 = jnp.maximum(incr / ((T - 1) * k), spec.tau2_floor)

    p_new = TVLParams(Lam0=lam_sm[0], tau2=tau2, A=A, Q=Q, R=R,
                      mu0=p.mu0, P0=p.P0)
    return lam_sm, p_new, kf.loglik, F


@partial(jax.jit, static_argnames=("has_mask",))
def _tvl_loglik_impl(Y, mask, Lam_t, p: TVLParams, has_mask: bool):
    m = mask if has_mask else None
    stats = obs_stats_tv(Y, Lam_t, p.R, mask=m)
    xp, Pp, xf, Pf, logdetG = info_scan(stats, p.A, p.Q, p.mu0, p.P0)
    V = Y - jnp.einsum("tnk,tk->tn", Lam_t, xp)
    if m is not None:
        V = m.astype(Y.dtype) * jnp.nan_to_num(V)
    VR = V / p.R[None, :]
    quad_R = jnp.einsum("tn,tn->t", V, VR)
    U = jnp.einsum("tn,tnk->tk", VR, Lam_t)
    return loglik_from_terms(stats, logdetG, Pf, quad_R, U)


def tvl_loglik_eval(Y, Lam_t, p: TVLParams, mask=None,
                    precise: bool = True) -> float:
    """Reporting-grade CONDITIONAL log-likelihood at (Lam_t, params).

    Semantics (documented, per BASELINE.json:5 / VERDICT r4 item 4): the
    TVL model's exact joint likelihood is intractable (bilinear in factors
    and loadings), so the estimation monitor — and this evaluator — is the
    factor-filter likelihood CONDITIONAL on the loading paths, i.e.
    p(Y | Lam_{1:T}, theta).  ``precise`` re-evaluates it in float64 on
    device (needs x64; falls back to the compute dtype with a warning).
    """
    use_f64 = precise and jax.config.jax_enable_x64
    if precise and not use_f64:
        import warnings
        warnings.warn(
            "precise tvl_loglik_eval needs jax_enable_x64; evaluating in "
            "the compute dtype instead", RuntimeWarning, stacklevel=2)
    dtype = jnp.float64 if use_f64 else jnp.asarray(Y).dtype
    Yj = jnp.asarray(np.nan_to_num(np.asarray(Y, np.float64)), dtype)
    Lj = jnp.asarray(np.asarray(Lam_t, np.float64), dtype)
    pj = TVLParams(*(jnp.asarray(np.asarray(x), dtype) for x in p))
    mj = jnp.asarray(mask, dtype) if mask is not None else Yj
    return float(_tvl_loglik_impl(Yj, mj, Lj, pj, mask is not None))


@partial(jax.jit, static_argnames=("has_mask",))
def _tvl_factors(Y, mask, Lam_t, p: TVLParams, has_mask: bool):
    """Smoothed factor path at fixed (Lam_t, params) — the reporting pass
    (A-step only; no B-step/M-step work)."""
    _, sm = factor_pass_tv(Y, Lam_t, p, mask=mask if has_mask else None)
    return sm.x_sm


@partial(jax.jit, static_argnames=("spec", "has_mask", "n_rounds"))
def tvl_round_scan(Y, mask, Lam_t, p: TVLParams, spec: TVLSpec,
                   has_mask: bool, n_rounds: int):
    """n alternation rounds fused into ONE XLA program (the TVL analog of
    ``estim.em.em_fit_scan``; VERDICT r4 weak item 5 — the per-round Python
    loop paid one ~60-100 ms tunneled dispatch per round).  The carry is
    (Lam_t, params): the loading PATHS are part of the alternation state.
    Returns ((Lam_t', params'), logliks (n,))."""
    m = mask if has_mask else None

    def body(carry, _):
        Lam_c, p_c = carry
        Lam_new, p_new, ll, _ = tvl_round_core(Y, m, Lam_c, p_c, spec)
        return (Lam_new, p_new), ll

    return lax.scan(body, (Lam_t, p), None, length=n_rounds)


@dataclasses.dataclass
class TVLResult:
    params: TVLParams
    loadings: np.ndarray       # (T, N, k) smoothed loading paths
    factors: np.ndarray        # (T, k)
    logliks: np.ndarray        # conditional loglik per round
    common: np.ndarray         # (T, N) fitted common component
    converged: bool
    spec: TVLSpec
    health: object = None      # robust.FitHealth trace record

    @property
    def loglik(self):
        return float(self.logliks[-1]) if len(self.logliks) else float("nan")


def tvl_forecast(result: TVLResult, horizon: int):
    """h-step out-of-sample forecast, mirroring ``api.forecast``'s contract
    (SURVEY.md section 3.2 extended to the TVL family).

    Loadings are frozen at their end-of-sample smoothed value Lam_T (the
    random walk's conditional expectation for every future step) and the
    factor VAR(1) is iterated from the last estimated factor state.
    Returns (y_fore (h, N), f_fore (h, k)) in the units ``tvl_fit`` saw.
    """
    A = np.asarray(result.params.A, np.float64)
    Lam_T = np.asarray(result.loadings[-1], np.float64)     # (N, k)
    f = np.zeros((horizon, A.shape[0]))
    x = np.asarray(result.factors[-1], np.float64)
    for h in range(horizon):
        x = A @ x
        f[h] = x
    return f @ Lam_T.T, f


def tvl_fit(Y: np.ndarray, spec: TVLSpec,
            mask: Optional[np.ndarray] = None,
            dtype=None, callback=None,
            init: Optional[TVLParams] = None,
            fused_chunk: int = 8) -> TVLResult:
    """Dual-Kalman alternating estimation of the TVL-DFM.

    Warm start: static PCA (loadings constant), tau2 small; then
    ``spec.n_rounds`` alternation rounds (or until the conditional loglik's
    relative change drops below ``spec.tol``).

    ``fused_chunk`` rounds run as ONE XLA program between host round-trips
    (``estim.em.run_em_chunked`` — same stop/replay semantics as the EM
    drivers; callbacks receive chunk-entry params).  Set 1 for one dispatch
    per round and exact per-round callbacks.  The reported factor path is
    a final A-pass at the final (Lam_t, params) state, so ``factors`` is
    consistent with ``loadings`` regardless of chunking.
    """
    from ..backends.cpu_ref import pca_init
    from ..utils.data import build_mask
    Y = np.asarray(Y, np.float64)
    T, N = Y.shape
    k = spec.n_factors
    W = build_mask(Y)
    if mask is not None:
        W = W * np.asarray(mask, np.float64)
    any_missing = bool((W == 0).any())
    if dtype is None:
        from ..ops.precision import default_compute_dtype
        dtype = default_compute_dtype()

    Yz = np.where(W > 0, np.nan_to_num(Y), 0.0)
    if init is None:
        p0 = pca_init(Yz, k, mask=W if any_missing else None)
        init = TVLParams(
            Lam0=jnp.asarray(p0.Lam), tau2=jnp.full((N,), 1e-4),
            A=jnp.asarray(p0.A), Q=jnp.asarray(p0.Q), R=jnp.asarray(p0.R),
            mu0=jnp.asarray(p0.mu0), P0=jnp.asarray(p0.P0))
    p = init.astype(dtype)
    Yj = jnp.asarray(Yz, dtype)
    Wj = jnp.asarray(W, dtype) if any_missing else None
    Wj_arg = Wj if Wj is not None else jnp.ones_like(Yj)
    Lam_t = jnp.broadcast_to(p.Lam0, (T, N, k))

    cb = None
    if callback is not None:
        def cb(it, ll, entry, **kw):
            callback(it, ll, entry[1], **kw)       # entry = (Lam_t, params)
        cb.wants_params_iter = getattr(callback, "wants_params_iter", False)

    from ..estim.em import noise_floor_for, run_em_chunked
    # bf16-rounded matmul inputs (XLA's f32 default on TPU) inject ~1e-3
    # relative error into the factor-filter stats — force true-f32 products
    # like every other fit driver.
    with jax.default_matmul_precision("highest"):
        def scan_fn(carry, n):
            (Lam_c, p_c), lls = tvl_round_scan(
                Yj, Wj_arg, carry[0], carry[1], spec, Wj is not None, n)
            return (Lam_c, p_c), lls, None

        floor = noise_floor_for(dtype, Yj.size)
        (Lam_t, p), lls, converged, _ = run_em_chunked(
            scan_fn, (Lam_t, p), spec.n_rounds, spec.tol,
            floor, cb, fused_chunk)

        # Final A-pass at the final state: the fused rounds never
        # materialize the factor path, and this keeps factors consistent
        # with the returned loadings/params.
        F = _tvl_factors(Yj, Wj_arg, Lam_t, p, Wj is not None)

    common = np.einsum("tnk,tk->tn", np.asarray(Lam_t, np.float64),
                       np.asarray(F, np.float64))
    from ..robust.health import health_from_trace
    return TVLResult(params=p,
                     loadings=np.asarray(Lam_t, np.float64),
                     factors=np.asarray(F, np.float64),
                     logliks=np.asarray(lls), common=common,
                     converged=converged, spec=spec,
                     health=health_from_trace(lls, floor))

"""Model families (SURVEY.md L4): the config surface of BASELINE.json:6-12.

Static and AR(1) DFMs live in the core API (``dfm_tpu.api``); this package
holds the structured variants: mixed-frequency nowcasting, time-varying
loadings, stochastic-volatility via particle Kalman filtering.
"""

from .mixed_freq import (MixedFreqSpec, MFParams, MFResult, augment,
                         mf_em_step, mf_fit, mf_forecast, mf_pca_init)
from .tv_loadings import (TVLSpec, TVLParams, TVLResult, tvl_fit,
                          tvl_forecast)
from .sv import (SVSpec, SVResult, SVFit, sv_filter, sv_smooth_h,
                 sv_fit, sv_forecast)

__all__ = [
    "MixedFreqSpec", "MFParams", "MFResult", "augment",
    "mf_em_step", "mf_fit", "mf_forecast", "mf_pca_init",
    "TVLSpec", "TVLParams", "TVLResult", "tvl_fit", "tvl_forecast",
    "SVSpec", "SVResult", "SVFit", "sv_filter", "sv_smooth_h", "sv_fit",
    "sv_forecast",
]

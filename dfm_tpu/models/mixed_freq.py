"""Mixed-frequency nowcasting DFM (config S3; SURVEY.md sections 3.4, 7.1 M3).

Monthly/quarterly panel with arbitrary missing observations:

  - **State augmentation (Mariano-Murasawa):** the state stacks n_lags=5
    monthly factor lags, x_t = [f_t, f_{t-1}, ..., f_{t-4}]; quarterly series
    load on the weighted combination g_t = sum_j w_j f_{t-j}, w = [1,2,3,2,1]/3
    (the quarterly-growth aggregation identity).  Transition is the companion
    matrix with the VAR(1) block A in the top-left; only the top k x k block
    of Q is nonzero.
  - **Missing data (Banbura-Modugno):** a {0,1} mask with static shapes —
    masked rows drop out of the info-form observation statistics and the
    log-likelihood; quarterly rows are masked except months 3, 6, ... plus
    any ragged-edge missingness.
  - **Constrained EM:** the M-step respects the loading structure.  Monthly
    rows regress on the f_t block only; quarterly rows regress on g_t (so the
    full augmented row is kron(w, lam_q) by construction); the transition
    block is estimated from within-period cross moments E[f_t f_{t-1}'] =
    sum_t EffT[t][0:k, k:2k], which the augmented state carries without lag-1
    smoother covariances.

Everything is jit-compiled JAX over the info-form filter (state dim m = 5k
stays small; N enters only through the masked observation reductions, so the
series axis shards exactly as in ``parallel.sharded``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linalg import sym, solve_psd
from ..ssm.kalman import rts_smoother
from ..ssm.params import SSMParams

__all__ = ["MixedFreqSpec", "MFParams", "augment", "mf_em_step", "mf_fit",
           "mf_forecast", "mf_loglik_eval", "MFResult"]

MM_WEIGHTS = (1.0 / 3, 2.0 / 3, 1.0, 2.0 / 3, 1.0 / 3)


@dataclasses.dataclass(frozen=True)
class MixedFreqSpec:
    """Static model description (hashable -> jit static argument)."""
    n_monthly: int
    n_quarterly: int
    n_factors: int
    n_lags: int = 5
    weights: tuple = MM_WEIGHTS
    r_floor: float = 1e-6
    estimate_init: bool = False
    # E-step time recursion: "seq" (lax.scan filter + RTS — the oracle
    # path), "pit" (parallel-in-time blocked prefix scans, ~2 sqrt(T)
    # sequential depth instead of 2T — the m = L*k augmented scans are the
    # S3 iteration's dominant cost and the mask rules out the steady-state
    # shortcut), "pit_qr" (same prefix-scan depth on square-root / QR
    # elements — f32-stable combines; above QR_UNROLL_K_MAX the augmented
    # state falls back to the generic triangular lowerings), or "lowrank"
    # (rank-r computation-aware downdate scans, ``rank`` below — only
    # r x r linalg touches the m-dim state per step, which keeps the
    # m ~ 25 augmented program inside what the axon compiler will build
    # where the exact masked scan SIGABRTs; conservative calibrated
    # covariances, exact at rank = m).  Same algebra; equivalence tested.
    time_scan: str = "seq"
    rank: int = 0   # time_scan="lowrank" only; <= 0 -> auto (min(m, 8))

    def __post_init__(self):
        if self.time_scan not in ("seq", "pit", "pit_qr", "lowrank"):
            raise ValueError(
                f"time_scan must be 'seq', 'pit', 'pit_qr' or 'lowrank'; "
                f"got {self.time_scan!r}")

    @property
    def state_dim(self) -> int:
        return self.n_lags * self.n_factors


class MFParams(NamedTuple):
    """Small (unaugmented) parameter pytree the EM iterates on.

    Lam_m: (Nm, k) monthly loadings; Lam_q: (Nq, k) quarterly loadings on the
    aggregated factor g_t; A, Q: (k, k) monthly-factor VAR(1); R: (Nm+Nq,);
    mu0, P0: augmented-state initial moments ((m,), (m, m)).
    """

    Lam_m: jax.Array
    Lam_q: jax.Array
    A: jax.Array
    Q: jax.Array
    R: jax.Array
    mu0: jax.Array
    P0: jax.Array

    def astype(self, dtype):
        return MFParams(*(jnp.asarray(x, dtype) for x in self))


def augment(p: MFParams, spec: MixedFreqSpec) -> SSMParams:
    """Build the augmented (state-dim m = L*k) SSMParams for the filter."""
    k, L = spec.n_factors, spec.n_lags
    m = spec.state_dim
    dtype = p.Lam_m.dtype
    wv = jnp.asarray(spec.weights, dtype)
    # Loadings: monthly rows live on block 0; quarterly rows = kron(w, lam_q).
    Lam_m_aug = jnp.concatenate(
        [p.Lam_m, jnp.zeros((spec.n_monthly, m - k), dtype)], axis=1)
    Lam_q_aug = jnp.reshape(wv[None, :, None] * p.Lam_q[:, None, :],
                            (spec.n_quarterly, m))
    Lam = jnp.concatenate([Lam_m_aug, Lam_q_aug], axis=0)
    # Companion transition and top-block-only innovation covariance.
    A_aug = jnp.zeros((m, m), dtype)
    A_aug = A_aug.at[:k, :k].set(p.A)
    A_aug = A_aug.at[k:, :-k].set(jnp.eye(m - k, dtype=dtype))
    Q_aug = jnp.zeros((m, m), dtype).at[:k, :k].set(p.Q)
    return SSMParams(Lam=Lam, A=A_aug, Q=Q_aug, R=p.R, mu0=p.mu0, P0=p.P0)


def _blocked(EffT, L, k):
    """(T, m, m) -> (T, L, k, L, k) block view."""
    T = EffT.shape[0]
    return EffT.reshape(T, L, k, L, k)


def _identity_reduce(x):
    return x


def mf_em_core(Y, mask, p: MFParams, spec: MixedFreqSpec,
               reduce_tree=_identity_reduce):
    """Shared single-device / per-shard EM body.

    ``spec`` describes the LOCAL series block (its n_monthly/n_quarterly are
    per-shard counts under sharding); ``reduce_tree`` sums pytrees of
    k-sized reductions across shards (identity on one device, psum in
    ``parallel.sharded_mf``).  The k x k scans and dynamics M-step are
    replicated; loading/noise rows are local — same device boundary as the
    plain sharded EM (SURVEY.md section 3.1).
    """
    from ..ssm.info_filter import (ObsStats, obs_stats, loglik_terms_local,
                                   loglik_from_terms, info_scan)
    from ..ssm.params import FilterResult
    k, L = spec.n_factors, spec.n_lags
    Nm = spec.n_monthly
    dtype = Y.dtype
    wv = jnp.asarray(spec.weights, dtype)
    T = Y.shape[0]

    aug = augment(p, spec)
    stats = reduce_tree(obs_stats(Y, aug.Lam, aug.R, mask=mask))
    # The m = L*k augmented time recursions concentrate the whole cross-
    # section's data precision on a ~25-dim state, so they are the panel's
    # most error-sensitive piece.  Two measures (measured at the S3 shape):
    # matmul_precision="highest" is MANDATORY (bf16-rounded stats wobble
    # the EM trajectory by ~1e2 loglik units and fake divergences — the
    # fit drivers set it); and on CPU-with-x64 (native f64, tests/goldens)
    # the small scans/smoother additionally run in f64 (x_pred error
    # 5e-4 -> 6e-7).  On TPUs f64 is emulated and a sequential-scan
    # emulation costs ~10x, while highest-precision f32 is already
    # monotone to <0.1 loglik units — so the compute dtype is kept there.
    from ..ops.precision import accum_dtype
    acc = accum_dtype(dtype, native_only=True)
    aug_acc = aug.astype(acc)
    stats_acc = ObsStats(*(jnp.asarray(s, acc) for s in stats))
    lr_corr = None
    if spec.time_scan == "lowrank":
        from ..ssm.lowrank_filter import (lowrank_from_stats,
                                          lowrank_loglik_from_terms,
                                          lowrank_smoother)
        xp, Pp, xf, Pf, logdetG, lr_corr = lowrank_from_stats(
            stats_acc, aug_acc, spec.rank)
    elif spec.time_scan == "pit":
        from ..ssm.parallel_filter import pit_from_stats, pit_smoother
        xp, Pp, xf, Pf, logdetG = pit_from_stats(stats_acc, aug_acc)
    elif spec.time_scan == "pit_qr":
        from ..ssm.parallel_filter import pit_qr_from_stats, pit_qr_smoother
        xp, Pp, xf, Pf, logdetG = pit_qr_from_stats(stats_acc, aug_acc)
    else:
        xp, Pp, xf, Pf, logdetG = info_scan(stats_acc, aug_acc.A, aug_acc.Q,
                                            aug_acc.mu0, aug_acc.P0)
    quad_R, U = reduce_tree(
        loglik_terms_local(Y, aug.Lam, aug.R, xp.astype(dtype), mask))
    if lr_corr is not None:
        # The rank-r scan's consistent quad correction replaces the
        # u'P_f u plug-in (ssm.lowrank_filter docstring) — the reported
        # loglik stays a true Gaussian density at any rank.
        ll = lowrank_loglik_from_terms(stats_acc, logdetG, lr_corr, quad_R)
    else:
        ll = loglik_from_terms(stats_acc, logdetG, Pf, quad_R, U.astype(acc))
    kf = FilterResult(xp, Pp, xf, Pf, ll)
    if spec.time_scan == "pit":
        sm = pit_smoother(kf, aug_acc)
    elif spec.time_scan == "pit_qr":
        sm = pit_qr_smoother(kf, aug_acc)
    elif spec.time_scan == "lowrank":
        sm = lowrank_smoother(kf, aug_acc, rank=spec.rank)
    else:
        sm = rts_smoother(kf, aug_acc)

    x, P = sm.x_sm.astype(dtype), sm.P_sm.astype(dtype)  # (T, m), (T, m, m)
    EffT = P + jnp.einsum("ti,tj->tij", x, x)
    E5 = _blocked(EffT, L, k)                     # (T, L, k, L, k)
    Ef = x.reshape(T, L, k)

    W = mask.astype(dtype)
    Yz = jnp.where(W > 0, jnp.nan_to_num(Y), 0.0)
    counts = jnp.maximum(W.sum(0), 1.0)

    # ----- monthly loadings: regress on the f_t (block-0) moments -----
    Ef0 = Ef[:, 0, :]                             # (T, k)
    Eff0 = E5[:, 0, :, 0, :]                      # (T, k, k)
    Wm, Ym = W[:, :Nm], Yz[:, :Nm]
    S_yf_m = jnp.einsum("ti,tk->ik", Ym, Ef0)
    S_ff_m = jnp.einsum("ti,tkl->ikl", Wm, Eff0)
    never_m = (Wm.sum(0) == 0)[:, None, None]
    S_ff_m = jnp.where(never_m, jnp.eye(k, dtype=dtype)[None], S_ff_m)
    Lam_m = jax.vmap(solve_psd)(S_ff_m, S_yf_m)
    # E[(y - lam'f)^2] summed: y^2 - 2 y lam'Ef + lam' (sum w Eff) lam,
    # reusing the per-series moment sums (Ym is already mask-zero-filled).
    rm = (jnp.einsum("ti,ti->i", Ym, Ym)
          - 2.0 * jnp.einsum("ti,ti->i", Ym, Ef0 @ Lam_m.T)
          + jnp.einsum("ik,ikl,il->i", Lam_m, S_ff_m, Lam_m))

    # ----- quarterly loadings: regress on g_t = sum_j w_j f_{t-j} -----
    Eg = jnp.einsum("tak,a->tk", Ef, wv)          # (T, k)
    Egg = jnp.einsum("tajbl,a,b->tjl", E5, wv, wv)  # (T, k, k)
    Wq, Yq = W[:, Nm:], Yz[:, Nm:]
    S_yg = jnp.einsum("ti,tk->ik", Yq, Eg)
    S_gg = jnp.einsum("ti,tkl->ikl", Wq, Egg)
    never_q = (Wq.sum(0) == 0)[:, None, None]
    S_gg = jnp.where(never_q, jnp.eye(k, dtype=dtype)[None], S_gg)
    Lam_q = jax.vmap(solve_psd)(S_gg, S_yg)
    rq = (jnp.einsum("ti,ti->i", Yq, Yq)
          - 2.0 * jnp.einsum("ti,ti->i", Yq, Eg @ Lam_q.T)
          + jnp.einsum("ik,ikl,il->i", Lam_q, S_gg, Lam_q))

    R = jnp.maximum(jnp.concatenate([rm, rq]) / counts, spec.r_floor)

    # ----- transition block: within-state cross moments -----
    # The augmented state carries (f_t, f_{t-1}) jointly, so E[f_t f_{t-1}']
    # needs no lag-one smoother covariance.  t=0's pair belongs to the prior,
    # not the dynamics, hence the [1:] sums over the T-1 real transitions.
    S_cur = E5[1:, 0, :, 0, :].sum(0)             # sum E[f_t f_t']
    S_cross = E5[1:, 0, :, 1, :].sum(0)           # sum E[f_t f_{t-1}']
    S_lag = E5[1:, 1, :, 1, :].sum(0)             # sum E[f_{t-1} f_{t-1}']
    A = solve_psd(S_lag, S_cross.T).T
    Q = sym((S_cur - A @ S_cross.T) / (T - 1))

    mu0, P0 = p.mu0, p.P0
    if spec.estimate_init:
        mu0 = x[0]
        P0 = sym(P[0])
    return MFParams(Lam_m, Lam_q, A, Q, R, mu0, P0), kf.loglik, sm


def mf_loglik_eval(Y, mask, p: MFParams, spec: MixedFreqSpec,
                   precise: bool = True) -> float:
    """Reporting-grade log-likelihood of the MF model at given params.

    The mixed-frequency model is EXACTLY linear-Gaussian in its augmented
    state, so this is the same contract as ``ssm.info_filter.loglik_eval``
    (f64 on device when ``precise`` and x64 are on; falls back to the
    compute dtype with a warning otherwise): augment the params (in f64, so
    the Mariano-Murasawa weight products don't round) and run the masked
    info-form filter.  Backs the per-config accuracy artifact of
    BASELINE.json:5 for S3 (VERDICT r4 item 4).
    """
    from ..ssm.info_filter import loglik_eval
    if precise and jax.config.jax_enable_x64:
        p = MFParams(*(jnp.asarray(np.asarray(x), jnp.float64) for x in p))
        aug = augment(p, spec)
        return loglik_eval(Y, aug, mask=mask, precise=True)
    if precise:
        import warnings
        warnings.warn(
            "precise mf_loglik_eval needs jax_enable_x64; evaluating in "
            "the compute dtype instead", RuntimeWarning, stacklevel=2)
    # Fast (compute-dtype) path: evaluate through the fit's OWN E-step
    # program — ``mf_em_step``'s second return is the loglik at the entry
    # params, i.e. exactly the in-loop figure whose noise this diagnostic
    # reports.  A standalone f32 masked ``info_scan`` at the augmented
    # shape SIGABRTs the axon TPU compiler (fusion-merge check failure,
    # 2026-07; see ``info_filter._loglik_eval_impl``), while this
    # fit-shaped program is the one every S3 run already compiles.
    Yj = jnp.asarray(Y)
    # A fully-observed panel legitimately reaches here with mask=None
    # (ADVICE r5 finding #1): the E-step program is mask-shaped, so feed
    # it an all-ones mask rather than crashing in asarray(None).
    mj = (jnp.asarray(mask, Yj.dtype) if mask is not None
          else jnp.ones_like(Yj))
    _, ll = mf_em_step(Yj, mj, p.astype(Yj.dtype), spec)
    return float(ll)


@partial(jax.jit, static_argnames=("spec",))
def mf_em_step(Y, mask, p: MFParams, spec: MixedFreqSpec):
    """One constrained EM iteration.  Returns (new_params, entry loglik)."""
    p_new, ll, _ = mf_em_core(Y, mask, p, spec)
    return p_new, ll


@partial(jax.jit, static_argnames=("spec",))
def _mf_smooth_impl(Y, mask, p: MFParams, spec: MixedFreqSpec):
    """Jitted filter+smoother at fixed params (the M-step outputs of the
    shared core are unused here, so XLA dead-code-eliminates them)."""
    _, ll, sm = mf_em_core(Y, mask, p, spec)
    return sm.x_sm, sm.P_sm, ll


@partial(jax.jit, static_argnames=("spec", "n_iters"))
def mf_em_scan(Y, mask, p: MFParams, spec: MixedFreqSpec, n_iters: int):
    """n constrained EM iterations fused into ONE XLA program (the MF analog
    of ``estim.em.em_fit_scan`` — at ~60-100 ms of dispatch per program on
    tunneled devices this is the difference between ~1 and ~8 iters/sec at
    the S3 shape).  Returns (params, logliks (n,))."""
    def body(p_c, _):
        p_new, ll, _ = mf_em_core(Y, mask, p_c, spec)
        return p_new, ll

    return jax.lax.scan(body, p, None, length=n_iters)


def mf_pca_init(Y: np.ndarray, mask: np.ndarray,
                spec: MixedFreqSpec) -> MFParams:
    """Warm start: PCA on the zero-filled monthly block, then regressions.

    Standard EM warm start for incomplete standardized panels (zero = series
    mean); quarterly loadings from OLS of observed quarterly values on the
    MM-aggregated PCA factor path.
    """
    from ..backends.cpu_ref import pca_init as _pca, \
        _solve_discrete_lyapunov_or_eye
    k, L, Nm = spec.n_factors, spec.n_lags, spec.n_monthly
    wv = np.asarray(spec.weights, np.float64)
    T = Y.shape[0]
    W = np.asarray(mask, np.float64)
    Yz = np.where(W > 0, np.nan_to_num(np.asarray(Y, np.float64)), 0.0)
    pm = _pca(Yz[:, :Nm], k)
    F = Yz[:, :Nm] @ pm.Lam / Nm                  # (T, k) PCA factor path
    # MM aggregate of the estimated path (zeros before t=0).
    G = np.zeros((T, k))
    for j in range(L):
        G[j:] += wv[j] * F[: T - j]
    Lam_q = np.zeros((spec.n_quarterly, k))
    Wq, Yq = W[:, Nm:], Yz[:, Nm:]
    for i in range(spec.n_quarterly):
        w = Wq[:, i] > 0
        if w.sum() > k:
            Gw = G[w]
            Lam_q[i] = np.linalg.lstsq(Gw, Yq[w, i], rcond=None)[0]
    resid_q = Yq - G @ Lam_q.T
    Rq = np.ones(spec.n_quarterly)
    for i in range(spec.n_quarterly):
        w = Wq[:, i] > 0
        Rq[i] = resid_q[w, i].var() if w.sum() > 1 else 1.0
    m = spec.state_dim
    A_aug = np.zeros((m, m))
    A_aug[:k, :k] = pm.A
    A_aug[k:, :-k] = np.eye(m - k)
    Q_aug = np.zeros((m, m))
    Q_aug[:k, :k] = pm.Q
    P0 = _solve_discrete_lyapunov_or_eye(A_aug, Q_aug + 1e-10 * np.eye(m))
    return MFParams(
        Lam_m=jnp.asarray(pm.Lam), Lam_q=jnp.asarray(Lam_q),
        A=jnp.asarray(pm.A), Q=jnp.asarray(pm.Q),
        R=jnp.asarray(np.concatenate([pm.R, np.maximum(Rq, 1e-6)])),
        mu0=jnp.zeros(m), P0=jnp.asarray(P0))


@dataclasses.dataclass
class MFResult:
    params: MFParams
    logliks: np.ndarray
    factors: np.ndarray          # (T, k) smoothed current-month factors
    factor_cov: np.ndarray       # (T, k, k)
    nowcast: np.ndarray          # (T, N) smoothed common component
    converged: bool
    spec: MixedFreqSpec
    state_T: np.ndarray = None       # (m,) smoothed augmented state at T
    state_cov_T: np.ndarray = None   # (m, m)
    standardizer: object = None      # utils.data.Standardizer or None
    health: object = None            # robust.FitHealth (trace-level)

    @property
    def loglik(self):
        return float(self.logliks[-1]) if len(self.logliks) else float("nan")


def mf_forecast(result: MFResult, horizon: int):
    """h-step out-of-sample forecast, mirroring ``api.forecast``'s contract
    (SURVEY.md section 3.2 extended to the mixed-frequency family).

    Iterates the augmented companion state x_{T+j} = A_aug x_{T+j-1} from
    the smoothed end-of-sample state and maps through the Mariano-Murasawa
    loadings, so monthly rows forecast off f_{T+j} and quarterly rows off
    the weighted lag aggregate automatically.  Returns (y_fore (h, N) in
    ORIGINAL data units, f_fore (h, k) monthly factors).
    """
    if result.state_T is None:
        raise ValueError("MFResult lacks state_T (old result object?)")
    spec = result.spec
    k = spec.n_factors
    aug = augment(result.params, spec)
    A = np.asarray(aug.A, np.float64)
    Lam = np.asarray(aug.Lam, np.float64)
    x = np.asarray(result.state_T, np.float64)
    f = np.zeros((horizon, k))
    y = np.zeros((horizon, Lam.shape[0]))
    for h in range(horizon):
        x = A @ x
        f[h] = x[:k]
        y[h] = Lam @ x
    if result.standardizer is not None:
        y = result.standardizer.inverse(y)
    return y, f


def mf_fit(Y: np.ndarray, spec: MixedFreqSpec,
           mask: Optional[np.ndarray] = None,
           max_iters: int = 50, tol: float = 1e-6,
           dtype=None, init: Optional[MFParams] = None,
           standardize: bool = True,
           callback=None, fused_chunk: int = 8) -> MFResult:
    """Estimate the mixed-frequency DFM.  Y is (T, Nm+Nq), monthly series
    first; NaNs and/or ``mask`` mark unobserved entries.  Standardization
    (per-series, over observed entries) is applied by default; the returned
    nowcast is mapped back to original data units.

    fused_chunk: EM iterations fused into one XLA program between host
    round-trips (same exact stop/replay semantics as the plain backends —
    ``estim.em.run_em_chunked``; callbacks receive chunk-entry params).
    Set 1 for one dispatch per iteration and exact per-iter callbacks."""
    Y = np.asarray(Y, np.float64)
    from ..utils.data import build_mask, standardize as _std
    W = build_mask(Y, mask)
    std = None
    if standardize:
        Y, std = _std(Y, mask=W)
    if dtype is None:
        from ..ops.precision import default_compute_dtype
        dtype = default_compute_dtype()
    if init is None:
        init = mf_pca_init(Y, W, spec)
    Yj = jnp.asarray(np.nan_to_num(Y * (W > 0)), dtype)
    Wj = jnp.asarray(W, dtype)
    p = init.astype(dtype)

    from ..estim.em import noise_floor_for, run_em_chunked
    floor = noise_floor_for(dtype, Yj.size)
    # bf16-rounded matmul inputs (XLA's f32 default on TPU) are NOT usable
    # for the augmented-state stats — see mf_em_core.
    with jax.default_matmul_precision("highest"):
        # run_em_chunked with fused_chunk=1 IS the per-iteration driver
        # (chunk-entry params == exact entering params; the divergence
        # replay resolves to the stored previous entry with no recompute),
        # so one driver serves both modes.
        def scan_fn(p_c, n):
            p_new, lls = mf_em_scan(Yj, Wj, p_c, spec, n)
            return p_new, lls, None

        p, lls, converged, _ = run_em_chunked(
            scan_fn, p, max_iters, tol, floor, callback, fused_chunk)

        x_sm, P_sm, _ = _mf_smooth_impl(Yj, Wj, p, spec)
    k = spec.n_factors
    x_sm = np.asarray(x_sm, np.float64)
    P_sm = np.asarray(P_sm, np.float64)
    aug = augment(p, spec)
    common = x_sm @ np.asarray(aug.Lam, np.float64).T
    if std is not None:
        common = std.inverse(common)
    from ..robust.health import health_from_trace
    return MFResult(params=p, logliks=np.asarray(lls),
                    factors=x_sm[:, :k], factor_cov=P_sm[:, :k, :k],
                    nowcast=common, converged=converged, spec=spec,
                    state_T=x_sm[-1], state_cov_T=P_sm[-1],
                    standardizer=std,
                    health=health_from_trace(lls, floor))

"""Public API: ``DynamicFactorModel`` + ``fit(model, data, backend=...)``.

The TPU-native mirror of the reference's user surface (SURVEY.md section 1.1):
a model description object and a ``fit`` entry point with a backend-dispatch
plugin seam (BASELINE.json:5 — ``fit(dfm; backend=...)``), where the dense
CPU reference backend and the JAX/TPU backend are interchangeable and must
agree in log-likelihood to 1e-5.

Backends are looked up in a registry so external code can register new ones —
the TPU analog of the reference's backend plugin hook:

    fit(model, Y, backend="cpu")     # NumPy float64 golden path
    fit(model, Y, backend="tpu")     # JAX path (TPU when available)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional, Type, Union

import numpy as np

from .backends import cpu_ref
from .obs.trace import activate, current_tracer, fit_tracer, shape_key
from .pipeline import compile_cache_entries, setup_compile_cache
from .utils.data import Standardizer, build_mask, standardize

__all__ = [
    "DynamicFactorModel", "FitResult", "fit", "fit_jobs", "forecast",
    "Backend", "CPUBackend", "TPUBackend", "ShardedBackend",
    "register_backend", "get_backend",
]


@dataclasses.dataclass(frozen=True)
class DynamicFactorModel:
    """Model description (what to estimate), independent of any backend.

    dynamics: "static" (f_t iid N(0, I) — A = 0, Q = I fixed) or
              "ar1" (factor VAR(1), A and Q estimated).
    """

    n_factors: int
    dynamics: str = "ar1"
    standardize: bool = True
    estimate_init: bool = False

    def __post_init__(self):
        if self.dynamics not in ("static", "ar1"):
            raise ValueError(f"unknown dynamics {self.dynamics!r}")
        if self.n_factors < 1:
            raise ValueError("n_factors must be >= 1")

    @property
    def estimate_A(self) -> bool:
        return self.dynamics == "ar1"

    @property
    def estimate_Q(self) -> bool:
        return self.dynamics == "ar1"


@dataclasses.dataclass
class FitResult:
    """Everything a user needs after estimation (NumPy, de-jaxed)."""

    params: cpu_ref.SSMParams          # in standardized units
    logliks: np.ndarray                # per-iteration loglik at entry params
    factors: np.ndarray                # (T, k) smoothed factor means
    factor_cov: np.ndarray             # (T, k, k) smoothed covariances
    converged: bool
    n_iters: int
    standardizer: Optional[Standardizer]
    model: DynamicFactorModel
    backend: str
    history: list                      # per-iter dicts {iter, loglik, secs}
    health: Optional[object] = None    # robust.FitHealth from guarded runs
    #                                  # (None: CPU oracle / unguarded path)
    telemetry: Optional[dict] = None   # obs.report.summarize() of this
    #                                  # fit's trace (fit(telemetry=...)
    #                                  # only; None when telemetry is off
    #                                  # or ambient via DFM_TRACE)
    fingerprint: Optional[str] = None  # structural warm-start fingerprint
    #                                  # (shape/model/missing-presence) —
    #                                  # validated by fit(warm_start=...)
    nowcast: Optional[np.ndarray] = None   # (N,) fitted-sample-end nowcast
    #                                  # Lam @ x_T in ORIGINAL units
    #                                  # (fused fits only)
    forecasts: Optional[dict] = None   # fused fits only: {"y": (h, N)
    #                                  # state-space forecast in original
    #                                  # units, "f": (h, k) factor path,
    #                                  # "di": (N,) diffusion-index h-step
    #                                  # forecast or None}
    advice: Optional[dict] = None      # fit(auto=True) only: the applied
    #                                  # plan {engine, fused_chunk, depth,
    #                                  # bucket, predicted_wall_s, ...} +
    #                                  # realized_wall_s / rel_err once
    #                                  # the fit returns
    session: Optional[object] = None   # fit(keep_session=True) only: a
    #                                  # serve.NowcastSession holding this
    #                                  # fit's params + panel device-
    #                                  # resident for streaming updates
    filter: Optional[str] = None       # resolved in-loop filter engine
    #                                  # ("dense"/"info"/"ss"/"pit"/
    #                                  # "pit_qr"); None on backends
    #                                  # without the filter knob (CPU
    #                                  # oracle) — also stamped on the
    #                                  # fit trace event
    tune: Optional[dict] = None        # fit(tune=...) only: the hyper
    #                                  # search record {method, q_scale,
    #                                  # r_scale, lam_ridge, heldout_
    #                                  # before/after, trajectory | cv,
    #                                  # dispatches, wall_s} — the chosen
    #                                  # hypers were applied to THIS fit

    @property
    def loglik(self) -> float:
        return float(self.logliks[-1]) if len(self.logliks) else float("nan")


class Backend:
    """Backend interface: estimate params and smooth factors.

    ``run_em`` returns (params, logliks, converged[, params_iters]) — the
    optional 4th element reports how many EM updates the returned params
    embody (used for checkpoint labeling; defaults to len(logliks)).
    """

    name = "abstract"

    def run_em(self, Y, mask, p0, model, max_iters, tol, callback):
        raise NotImplementedError

    def smooth(self, Y, mask, params):
        raise NotImplementedError

    def default_init(self, Y, mask, model):
        """PCA warm start.  The NumPy f64 initializer is canonical so CPU
        and accelerator fits start from IDENTICAL params; backends may
        override (``TPUBackend(device_init=True)`` runs the N-sized SVD
        work on device — see ``estim.init``)."""
        return cpu_ref.pca_init(Y, model.n_factors,
                                static=(model.dynamics == "static"),
                                mask=mask)


class CPUBackend(Backend):
    """NumPy float64 reference backend (the golden oracle).

    filter: "dense" (N x N innovation covariance — the canonical oracle and
    the default) or "info" (information form, O(N k^2)/step — the same
    algorithm class as the accelerator path; what the single-threaded CPU
    baselines of BASELINE.json:5 time at shapes where the dense form's
    O(N^3)/step is infeasible).  Both agree to fp tolerance (tested).
    """

    name = "cpu"

    def __init__(self, filter: str = "dense"):
        if filter not in ("dense", "info"):
            raise ValueError(f"unknown cpu filter {filter!r}")
        self.filter = filter

    def run_em(self, Y, mask, p0, model, max_iters, tol, callback):
        p, lls, converged = cpu_ref.em_fit(
            Y, p0, mask=mask, max_iters=max_iters, tol=tol,
            estimate_A=model.estimate_A, estimate_Q=model.estimate_Q,
            estimate_init=model.estimate_init, callback=callback,
            filter=self.filter)
        return p, np.asarray(lls), converged, len(lls)

    def smooth(self, Y, mask, params):
        ff = (cpu_ref.kalman_filter_info if self.filter == "info"
              else cpu_ref.kalman_filter)
        kf = ff(Y, params, mask=mask)
        sm = cpu_ref.rts_smoother(kf, params)
        return np.asarray(sm.x_sm), np.asarray(sm.P_sm)


# Host one-pass standardize gate: below this element count the two-pass
# f64 path is effectively free; a module constant so tests can lower it.
_ONEPASS_MIN_SIZE = 4_000_000


def _resolve_policy(robust):
    """``robust`` knob -> RobustPolicy | None (None means unguarded)."""
    if not robust:
        return None
    from .robust.guard import RobustPolicy
    if robust is True:
        return RobustPolicy()
    if isinstance(robust, RobustPolicy):
        return robust
    raise TypeError(
        f"robust must be bool or RobustPolicy; got {type(robust).__name__}")


def _TPUGuardControls(Yj, mj, cfg, em_fit_scan):
    from .robust.controls import TPUControls
    return TPUControls(Yj, mj, cfg, em_fit_scan)


class TPUBackend(Backend):
    """JAX backend: runs on TPU when present, any XLA device otherwise.

    dtype: computation precision.  None means float32 on accelerators (the
    TPU-native choice; MXU-friendly) and float64 on CPU when x64 is enabled.

    filter: "dense" (N x N innovation covariance), "info" (information form —
    k x k scan, N enters only through matmul reductions; the scalable path),
    "ss" (steady-state accelerated), "pit" (parallel-in-time,
    covariance-form), "pit_qr" (parallel-in-time on square-root factors —
    thin-QR combines in unrolled VPU form; the long-T engine, ~2*sqrt(T)
    sequential depth, f32-stable), "lowrank" (rank-r computation-aware
    downdate filter/smoother — only r x r linalg in the time scans,
    conservative calibrated covariances, exact at ``rank=k``; the wide-k
    engine, and the one that compiles at the MF m~25 augmented shape
    where the exact scan SIGABRTs — see ``ssm.lowrank_filter`` and the
    ``rank`` knob), or "auto": dense below N=32, info from
    there, ss for unmasked panels at N >= 512 (benchmark scale — ~5-30x
    faster in-loop, trajectory contract-checked; masked panels stay on the
    exact info scan).  ``fit(auto=True)`` additionally consults the
    calibrated advisor, which picks pit_qr per shape at long T.  All agree
    to fp tolerance (tested).

    matmul_precision: XLA matmul precision.  TPU MXUs round f32 matmul inputs
    to bf16 at the default setting, which costs ~1e-4 relative log-likelihood
    (measured on config S1) — far outside the 1e-5 contract (BASELINE.json:5).
    "highest" keeps true-f32 products (multi-pass bf16 on the MXU) and
    measured 7e-7 relative; it is the default.  Set "default" to trade
    accuracy for raw speed in benchmarks.

    fused_chunk: EM iterations fused into one XLA program between host
    round-trips.  Program dispatch costs ~60-100 ms on tunneled devices
    (docs/PERF.md) versus <1 ms of compute per iteration, so chunking is a
    large real-world win; the convergence check still sees every
    iteration's loglik (the fused scan emits them all).  Callbacks fire per
    iteration but receive the chunk-entry params (per-iter params never
    leave the device).  Set 1 for exact per-iteration params in callbacks.
    """

    name = "tpu"

    def __init__(self, dtype=None, filter: str = "auto",
                 matmul_precision: str = "highest", fused_chunk: int = 8,
                 debug: bool = False, device_init="auto", robust=True,
                 rank: int = 0):
        self.dtype = dtype
        if filter not in ("auto", "dense", "info", "ss", "pit", "pit_qr",
                          "lowrank"):
            raise ValueError(f"unknown filter {filter!r}")
        self.filter = filter
        # filter="lowrank" only: downdate rank r (<= 0 -> auto, min(k, 8);
        # see ssm.lowrank_filter.resolve_rank).  Ignored by exact engines.
        self.rank = int(rank)
        self.matmul_precision = matmul_precision
        self.fused_chunk = max(1, int(fused_chunk))
        # checkify NaN/inf guard around the filter scans (EMConfig.debug):
        # poisoned data/params raise located errors instead of silent NaNs.
        self.debug = debug
        # Health-monitored chunked EM (robust.guard): True uses the default
        # RobustPolicy, a RobustPolicy instance customizes it, False/None
        # keeps the legacy unguarded loop.  The guard runs host-side
        # between fused dispatches only — a healthy fit executes the
        # identical device workload (docs/PERF.md).
        self.robust = robust
        self._last_health = None
        self._guard_checkpoint = None
        # Transient per-fit live-progress hook (fit(progress=...) sets and
        # restores it); also switches the chunk program to the metrics twin.
        self._progress = None
        # Transient per-fit dispatch-pipeline config (fit(pipeline=...)
        # sets and restores it); resolved by estim.em.run_em_chunked —
        # None keeps the serial chunk driver.
        self._pipeline = None
        # Transient per-fit fused-program options (fit(fused=...) sets and
        # restores a FusedOptions); routes run_em through estim.fused.
        self._fused = None
        # Transient per-fit tuned hypers (fit(tune=...) sets and restores a
        # (q_scale, r_scale, lam_ridge) triple); _tuned_cfg folds them into
        # EMConfig's static hyper fields at every program-build site, so
        # the chunked/fused/sharded drivers all run the tuned M-step.
        # None (the default) keeps every program byte-identical.
        self._tune_hypers = None
        # PERSISTENT (not one-shot) device-panel cache for fused warm
        # refits: fit(warm_start=prev) with the same panel object re-enters
        # the fused program with ZERO h2d upload.  Keyed on the caller's
        # (Y, mask) object identity, like _panel_cache.
        self._fused_panel = None
        # PCA warm start on device (estim.init) — saves the ~1.2 s host SVD
        # at 10k series.  "auto" (default) switches it on when the panel is
        # large enough that the host SVD dominates the fit's fixed cost
        # (N*T >= 4e6 — the regime VERDICT r4 item 5 targets); small panels
        # keep the host init so cpu/tpu fits share identical warm starts.
        self.device_init = device_init

    def _use_device_init(self, Y) -> bool:
        if self.device_init == "auto":
            return Y.size >= 4_000_000
        return bool(self.device_init)

    def _tuned_cfg(self, cfg):
        """Fold the transient fit(tune=...) hypers into the EMConfig every
        driver builds its programs from.  No-op (the SAME cfg object) when
        no tune is active — the untuned program stays byte-identical."""
        if self._tune_hypers is None:
            return cfg
        q, r, lam = self._tune_hypers
        return dataclasses.replace(cfg, q_scale=float(q), r_scale=float(r),
                                   lam_ridge=float(lam))

    def default_init(self, Y, mask, model):
        if not self._use_device_init(Y):
            return super().default_init(Y, mask, model)
        import jax.numpy as jnp
        from .estim.init import pca_init_device
        Y_key = Y     # the object run_em will later be called with
        if mask is not None:
            # Same zero-fill contract as the NumPy initializer (fit()
            # pre-fills — making this a value no-op there — but this is a
            # public interface: a raw NaN panel must not reach the device
            # eigh).  The cache stays keyed on the CALLER'S object: keying
            # on the re-filled copy can never match run_em's argument, so
            # every masked panel would double-transfer (ADVICE r4 item 1).
            Y = np.where(np.asarray(mask) > 0, np.nan_to_num(Y), 0.0)
        with self._precision_ctx():
            # Transfer once: run_em reuses this device copy (the 40 MB
            # panel transfer costs more than the init compute on tunneled
            # devices — without the cache, device_init transfers twice and
            # LOSES to the host SVD end-to-end).
            Yj = jnp.asarray(Y, self._dtype())
            self._panel_cache = (Y_key, mask, Yj)
            return pca_init_device(Yj, model.n_factors,
                                   static=(model.dynamics == "static"),
                                   dtype=self._dtype())

    def _device_panel(self, Y, mask, dt):
        """The cached on-device panel when ``(Y, mask)`` are the objects it
        came from.  The mask identity matters: the cached values are
        zero-filled under default_init's mask, so handing them to a run_em
        called with a DIFFERENT mask (or none) would treat those zeros as
        observed data.

        One-shot: consuming the cache releases both copies, so a long-lived
        backend instance does not pin ~40 MB of host RAM + HBM per panel.
        """
        cached = getattr(self, "_panel_cache", None)
        self._panel_cache = None
        if (cached is not None and cached[0] is Y and cached[1] is mask
                and cached[2].dtype == dt):
            return cached[2]
        import jax.numpy as jnp
        return jnp.asarray(Y, dt)

    def prep_standardize(self, Y, model):
        """Device-side panel standardization (``estim.init
        .standardize_device``) for large fully-observed panels, or ``None``
        when the host path should run (small panel, missing data, or
        ``device_init`` off — same gate as the device PCA init, since the
        win is the same: the raw panel transfers once and every N-sized
        prep pass happens on device instead of in host NumPy).

        Returns ``(Yz_device, Standardizer)``; ``fit`` passes the device
        array through as the panel, and ``default_init``/``run_em``/
        ``smooth`` all already accept it (the identity-keyed caches make
        it zero-copy).  The stats are computed in the compute dtype — at
        f32 the mean/scale differ from the host f64 transform by ~1e-6
        relative, which only re-units the standardized problem; small
        panels keep the host path so cpu==tpu goldens stay exact.
        """
        if not model.standardize or not self._use_device_init(Y):
            return None
        if not bool(np.isfinite(Y).all()):
            return None          # missing data: host masked path
        import jax.numpy as jnp
        from .estim.init import standardize_device
        with self._precision_ctx():
            Yj, stats = standardize_device(jnp.asarray(Y, self._dtype()))
        stats = np.asarray(stats, np.float64)
        return Yj, Standardizer(stats[0], stats[1])

    def _filter_for(self, N: int, masked: bool = False) -> str:
        if self.filter == "auto":
            if N < 32:
                return "dense"
            if not masked and N >= 512:
                # Steady-state accelerated engine at benchmark scale: the
                # in-loop iteration is ~5-30x the exact info scan (docs/
                # PERF.md) and the trajectory meets the 1e-5 contract at
                # 1e-10 (checked every bench run); run_em picks tau from
                # the measured Riccati mixing time, the freeze diagnostic
                # guards it at runtime, and the reporting smooth is exact
                # info-form regardless.  Small panels keep the exact
                # engines so cpu==tpu goldens stay bit-tight.
                return "ss"
            return "info"
        return self.filter

    def _precision_ctx(self):
        import jax
        return jax.default_matmul_precision(self.matmul_precision)

    def _dtype(self):
        import jax
        import jax.numpy as jnp
        if self.dtype is not None:
            return jnp.dtype(self.dtype)
        from .ops.precision import default_compute_dtype
        return default_compute_dtype()

    def run_em(self, Y, mask, p0, model, max_iters, tol, callback):
        import jax.numpy as jnp
        from .estim.em import EMConfig, em_fit, em_fit_scan
        from .ssm.params import SSMParams as JaxParams
        self._fused_outputs = None   # never let a stale fused fit's
        #                            # nowcast attach to this result
        fz = getattr(self, "_fused", None)
        if fz is not None:
            return self._run_fused(Y, mask, p0, model, max_iters, tol,
                                   callback, fz)
        self._last_health = None
        dt = self._dtype()
        Yj = self._device_panel(Y, mask, dt)
        mj = jnp.asarray(mask, dt) if mask is not None else None
        pj = JaxParams.from_numpy(p0, dtype=dt)
        flt = self._filter_for(Y.shape[1], mask is not None)
        self._last_filter = flt
        cfg = self._tuned_cfg(
            EMConfig(estimate_A=model.estimate_A,
                     estimate_Q=model.estimate_Q,
                     estimate_init=model.estimate_init,
                     filter=flt, debug=self.debug, rank=self.rank))
        if flt == "ss":
            # tau from the measured covariance-recursion mixing time at the
            # init params (k x k on host, microseconds) — the same choice
            # bench.py makes; the freeze diagnostic warns if EM drifts the
            # params enough that tau stops covering the mixing time.
            from .ssm.steady import auto_tau
            cfg = dataclasses.replace(cfg, tau=auto_tau(p0))
        with self._precision_ctx():
            if self.fused_chunk <= 1:
                p, lls, converged, p_iters = em_fit(
                    Yj, pj, mask=mj, cfg=cfg, max_iters=max_iters, tol=tol,
                    callback=callback)
                return p.to_numpy(), np.asarray(lls), converged, p_iters
            p, lls, converged, p_iters = self._run_em_chunked(
                Yj, mj, pj, cfg, max_iters, tol, callback, em_fit_scan)
            pn = p.to_numpy()
            self._async_smooth_stash(Y, mask, Yj, mj, p, pn, cfg)
        return pn, np.asarray(lls), converged, p_iters

    def _async_smooth_stash(self, Y, mask, Yj, mj, p, pn, cfg):
        """Run the reporting smooth NOW, while the panel is still
        device-resident: smooth() would otherwise re-transfer it (~0.7 s
        of tunnel latency at the headline shape — the dominant cost
        VERDICT r4 item 5 flags).  Same exact-filter mapping as smooth()
        (ss/pit fall back to the sequential info form — the freeze
        approximation never reaches FitResult), and the dispatch is
        async: the transfer happens when smooth() consumes the
        identity-keyed cache."""
        from .ssm.kalman import kalman_filter
        from .ssm.info_filter import info_filter, smooth_jit
        ff = kalman_filter if cfg.filter == "dense" else info_filter
        tr = current_tracer()
        if tr is None:
            x_sm, P_sm = smooth_jit(Yj, mj if mj is not None else Yj, p,
                                    ff, mask is not None)
        else:
            # Async dispatch: the transfer (and its span) happens when
            # smooth() consumes the cache.
            with tr.dispatch("smooth", shape_key(Yj, cfg.filter)):
                x_sm, P_sm = smooth_jit(Yj, mj if mj is not None else Yj,
                                        p, ff, mask is not None)
        self._smooth_cache = (Y, mask, pn, x_sm, P_sm)

    def _run_fused(self, Y, mask, p0, model, max_iters, tol, callback, opts):
        """Dispatch-free fit: EM-to-convergence + smooth + forecast in ONE
        jitted program (``estim.fused.run_fused``); one barrier'd d2h read
        per fit.  A diverged run with the robust guard enabled falls back
        to the health-monitored chunked driver from the fused program's
        last-good checkpoint.
        """
        import jax.numpy as jnp
        from .estim.em import EMConfig, em_fit_scan, noise_floor_for
        from .estim.fused import run_fused
        from .ssm.params import SSMParams as JaxParams
        self._last_health = None
        if self.debug:
            raise ValueError(
                "fused=True has no checkify debug twin (a while-loop "
                "program cannot surface located errors mid-flight); use "
                "debug=True with the chunked driver instead")
        if getattr(self, "_progress", None) is not None:
            import warnings
            warnings.warn(
                "fused=True runs EM inside one device program — there are "
                "no per-chunk host round-trips to hook; ignoring "
                "progress=", RuntimeWarning, stacklevel=3)
        dt = self._dtype()
        # Panel residency for warm refits: unlike _panel_cache (one-shot),
        # this cache persists across fits on the same backend instance, so
        # fit(warm_start=prev) re-enters the program with zero h2d upload.
        # Identity hit is free; on an identity miss, CONTENT equality of
        # host panels (utils.checkpoint.panel_mismatch) still reuses the
        # device buffers — a serving loop that copies the panel between
        # refits keeps the zero-upload path.  A content mismatch re-uploads
        # and names the differing field in a panel_reupload trace event
        # (updated values are the normal serving flow, not a warning).
        fp = self._fused_panel
        reuse = False
        if fp is not None and fp[2].dtype == dt:
            if fp[0] is Y and fp[1] is mask:
                reuse = True
            elif isinstance(Y, np.ndarray) and isinstance(fp[0], np.ndarray):
                # Never content-compare device arrays: that would force
                # the d2h transfer the cache exists to avoid.
                from .utils.checkpoint import panel_mismatch
                diff = panel_mismatch(Y, mask, fp[0], fp[1])
                if diff is None:
                    reuse = True
                    self._fused_panel = (Y, mask, fp[2], fp[3])
                else:
                    tr = current_tracer()
                    if tr is not None:
                        tr.emit("panel_reupload", reason=diff)
        if reuse:
            Yj, mj = fp[2], fp[3]
        else:
            Yj = self._device_panel(Y, mask, dt)
            mj = jnp.asarray(mask, dt) if mask is not None else None
            self._fused_panel = (Y, mask, Yj, mj)
        pj = JaxParams.from_numpy(p0, dtype=dt)
        flt = self._filter_for(Y.shape[1], mask is not None)
        self._last_filter = flt
        cfg = self._tuned_cfg(
            EMConfig(estimate_A=model.estimate_A,
                     estimate_Q=model.estimate_Q,
                     estimate_init=model.estimate_init,
                     filter=flt, debug=False, rank=self.rank))
        if flt == "ss":
            from .ssm.steady import auto_tau
            cfg = dataclasses.replace(cfg, tau=auto_tau(p0))
        floor = noise_floor_for(dt, Yj.size, mult=cfg.noise_floor_mult)
        # The one fused dispatch goes through the unified guard
        # (robust.dispatch): retry/backoff + watchdog + fault seams, with
        # the host init params as the donated-twin recovery checkpoint.
        policy = _resolve_policy(self.robust)
        health = None
        if policy is not None:
            from .robust.health import FitHealth
            health = FitHealth(engine="fused")
        with self._precision_ctx():
            run = run_fused(Yj, mj, pj, cfg, max_iters, tol, floor, opts,
                            fused_chunk=self.fused_chunk, policy=policy,
                            health=health, p0_host=p0)
            if callback is not None:
                # Post-hoc replay: per-iter params never leave the device;
                # callbacks get the fit-entry params (the chunk-entry
                # contract degenerated to one "chunk" spanning the fit).
                wants = getattr(callback, "wants_params_iter", False)
                for i, ll in enumerate(run.lls):
                    if wants:
                        callback(i, float(ll), p0, params_iter=0)
                    else:
                        callback(i, float(ll), p0)
            if run.diverged:
                tr = current_tracer()
                if tr is not None:
                    tr.emit("fused_fallback", good_it=int(run.good_it),
                            n_iters=int(run.n_iters))
                if policy is None:
                    # Unguarded: mirror the chunked driver's divergence
                    # return — last-good params, full loglik path, not
                    # converged.  No smooth stash (params changed).
                    return (run.p_good, run.lls, False, run.good_it)
                # Guarded fallback: resume the health-monitored chunked
                # driver from the fused program's last-good checkpoint
                # with the remaining budget.
                from .robust.health import HealthEvent
                health.record(HealthEvent(
                    chunk=-1, iteration=int(run.good_it),
                    kind="divergence", action="restored",
                    detail=(f"fused fit diverged after {int(run.good_it)} "
                            f"good iterations; resuming chunked driver "
                            f"from last-good")))
                warm = JaxParams.from_numpy(run.p_good, dtype=dt)
                remaining = max(max_iters - run.good_it, 1)
                p, lls2, converged, p_it2 = self._run_em_chunked(
                    Yj, mj, warm, cfg, remaining, tol, callback,
                    em_fit_scan)
                pn = p.to_numpy()
                self._async_smooth_stash(Y, mask, Yj, mj, p, pn, cfg)
                lls = np.concatenate(
                    [run.lls[:run.good_it], np.asarray(lls2)])
                # Fold the fused guard's record into the chunked
                # monitor's health (set by _run_em_chunked) so one
                # FitResult.health tells the whole story.
                mh = self._last_health
                if mh is not None and mh is not health:
                    mh.events[:0] = health.events
                    mh.n_dispatch_retries += health.n_dispatch_retries
                    mh.n_recoveries += health.n_recoveries
                else:
                    self._last_health = health
                return pn, lls, converged, run.good_it + p_it2
        # Success: the program already smoothed at the final params —
        # smooth() consumes this identity-keyed cache as a pure host read
        # (non-blocking transfer event; values are already numpy).
        self._last_health = health
        self._smooth_cache = (Y, mask, run.params, run.x_sm, run.P_sm)
        # One-shot fused outputs for _fit_impl (nowcast/forecasts in
        # standardized units; fit() de-standardizes).
        self._fused_outputs = {
            "nowcast": run.nowcast, "f_fore": run.f_fore,
            "y_fore": run.y_fore, "di": run.di,
            "fused_iterations": int(run.n_iters),
        }
        return run.params, run.lls, run.converged, run.p_iters

    def _run_em_chunked(self, Yj, mj, pj, cfg, max_iters, tol, callback,
                        em_fit_scan, controls=None):
        """Fused-chunk driver: one XLA program per ``fused_chunk`` iters.

        Thin adapter over the shared ``estim.em.run_em_chunked`` (the exact
        stop/replay semantics — chunk-prefix replay on mid-chunk stops,
        chunk-entry params to callbacks — are documented there).  With
        ``self.robust`` enabled, a ``robust.ChunkMonitor`` rides along and
        the shared driver delegates to its health-monitored twin;
        ``controls`` lets subclasses supply their own escalation hooks
        (ShardedBackend re-pads params through its driver).
        """
        from .estim.em import noise_floor_for, run_em_chunked

        progress = getattr(self, "_progress", None)
        # Metrics ride along only when someone is listening (the progress
        # hook): the default chunk program stays byte-identical to the
        # metrics-free PR 3 path (telemetry alone must not change it —
        # pinned by tests/test_obs.py bit-identity).
        with_metrics = progress is not None

        def scan_fn(p, n):
            if with_metrics:
                p_new, lls, deltas, metrics = em_fit_scan(
                    Yj, p, n, mask=mj, cfg=cfg, with_metrics=True)
                return (p_new, lls,
                        (deltas if cfg.filter == "ss" else None), metrics)
            p_new, lls, deltas = em_fit_scan(Yj, p, n, mask=mj, cfg=cfg)
            return p_new, lls, (deltas if cfg.filter == "ss" else None)

        # Telemetry identity for the shared driver's dispatch spans; the
        # sharded backend hands a pre-tagged em_fit_scan whose attrs win.
        scan_fn.trace_name = getattr(em_fit_scan, "trace_name", "em_chunk")
        scan_fn.trace_key = getattr(em_fit_scan, "trace_key",
                                    shape_key(Yj, cfg.filter))
        scan_fn.trace_engine = getattr(em_fit_scan, "trace_engine", "tpu_em")

        # Bucketed-dispatch seam (PipelineConfig(bucket=True)): a fused-
        # length program with a traced n_active cap, so tail chunks and
        # mid-chunk replays reuse the full chunk's ONE executable (see
        # estim.em._em_scan_core_active).  checkify debug mode has no
        # bucketed twin — the attr's absence degrades to exact-length
        # dispatch, which is also what escalation-wrapped scan_fns do.
        if not cfg.debug:
            def bucket_call(p, n_active, n_bucket):
                if with_metrics:
                    p_new, lls, deltas, metrics = em_fit_scan(
                        Yj, p, n_bucket, mask=mj, cfg=cfg,
                        with_metrics=True, n_active=n_active)
                    return (p_new, lls,
                            (deltas if cfg.filter == "ss" else None),
                            metrics)
                p_new, lls, deltas = em_fit_scan(
                    Yj, p, n_bucket, mask=mj, cfg=cfg, n_active=n_active)
                return p_new, lls, (deltas if cfg.filter == "ss" else None)

            scan_fn.bucket_call = bucket_call

        monitor = None
        # checkify debug mode is a diagnostic: its located errors must
        # propagate verbatim, not be dispatch-retried (they are
        # deterministic) or converted into GuardFailure.
        policy = None if cfg.debug else _resolve_policy(self.robust)
        if policy is not None:
            from .robust.guard import ChunkMonitor
            if controls is None:
                controls = _TPUGuardControls(Yj, mj, cfg, em_fit_scan)
            gc = getattr(self, "_guard_checkpoint", None)
            if gc is not None and policy.checkpoint_path is None:
                policy = dataclasses.replace(
                    policy, checkpoint_path=gc[0],
                    checkpoint_fingerprint=gc[1], iter_offset=gc[2])
            monitor = ChunkMonitor(policy, controls)
            self._last_health = monitor.health
        from .estim.em import cfg_hypers
        return run_em_chunked(
            scan_fn, pj, max_iters, tol,
            noise_floor_for(Yj.dtype, Yj.size, mult=cfg.noise_floor_mult),
            callback, self.fused_chunk,
            ss_tau=cfg.tau if cfg.filter == "ss" else None,
            monitor=monitor, progress=progress,
            pipeline=getattr(self, "_pipeline", None),
            monotone=cfg_hypers(cfg) is None)

    def smooth(self, Y, mask, params):
        # fit() calls smooth right after run_em with the exact (Y, mask,
        # params) objects run_em saw/returned; the chunked driver already
        # smoothed at the final params inside the last chunk's program, so
        # that call costs only the transfer.  Identity checks on all three
        # objects — any other caller combination runs the full path.
        cache = getattr(self, "_smooth_cache", None)
        self._smooth_cache = None
        if (cache is not None and cache[0] is Y and cache[1] is mask
                and cache[2] is params):
            tr = current_tracer()
            if tr is None:
                return (np.asarray(cache[3], np.float64),
                        np.asarray(cache[4], np.float64))
            t0 = time.perf_counter()
            x_sm = np.asarray(cache[3], np.float64)
            P_sm = np.asarray(cache[4], np.float64)
            tr.emit("transfer", t=t0, direction="d2h", what="factors",
                    dur=time.perf_counter() - t0,
                    bytes=int(x_sm.nbytes + P_sm.nbytes))
            return x_sm, P_sm
        import jax.numpy as jnp
        from .ssm.kalman import kalman_filter
        from .ssm.info_filter import info_filter, smooth_jit
        from .ssm.params import SSMParams as JaxParams
        dt = self._dtype()
        Yj = jnp.asarray(Y, dt)
        mj = jnp.asarray(mask, dt) if mask is not None else None
        # A single smooth is not the hot path: ss/pit fall back to the
        # sequential info form here.
        ff = {"dense": kalman_filter, "info": info_filter,
              "ss": info_filter, "pit": info_filter,
              "pit_qr": info_filter, "lowrank": info_filter}[
                  self._filter_for(Y.shape[1])]
        pj = JaxParams.from_numpy(params, dtype=dt)
        tr = current_tracer()
        with self._precision_ctx():
            if mj is None:
                mj = Yj  # dead placeholder (body ignores it) — no extra op
            if tr is None:
                x_sm, P_sm = smooth_jit(Yj, mj, pj, ff, mask is not None)
            else:
                with tr.dispatch("smooth", shape_key(Yj), barrier=True):
                    x_sm, P_sm = smooth_jit(Yj, mj, pj, ff, mask is not None)
                    x_sm = np.asarray(x_sm, np.float64)
                    P_sm = np.asarray(P_sm, np.float64)
        return np.asarray(x_sm, np.float64), np.asarray(P_sm, np.float64)


class ShardedBackend(TPUBackend):
    """Multi-device backend: series-sharded EM over a 1-D mesh.

    ``shard_map`` + psum realization of BASELINE.json:5's distributed design
    (see ``parallel.sharded``).  n_devices=None uses every local device; on a
    single chip this degrades gracefully to a 1-shard mesh.

    filter: "info" (exact information-form scan), "ss" (steady-state
    accelerated — the single-chip headline path, replicated k x k under
    sharding; falls back to info on masked panels), or "auto" (ss for
    unmasked panels at N >= 512, info otherwise — same tiering as
    ``TPUBackend``).

    fused_chunk: as in ``TPUBackend`` — EM iterations fused into one XLA
    program (``lax.scan`` over the shard_map body) between host round-trips,
    so the multi-device path is not program-dispatch-bound (one ~60-100 ms
    dispatch per chunk instead of per iteration).  Callbacks receive
    chunk-entry params, unpadded to the true series count.

    debug: checkify float checks around the whole shard_map program — the
    sharded analog of ``TPUBackend(debug=True)`` (a poisoned shard raises a
    located error instead of silently psum-ing NaNs).
    """

    name = "sharded"

    def __init__(self, dtype=None, n_devices=None, filter: str = "auto",
                 matmul_precision: str = "highest", fused_chunk: int = 8,
                 debug: bool = False, device_init="auto", robust=True):
        super().__init__(dtype=dtype, filter=filter,
                         matmul_precision=matmul_precision,
                         fused_chunk=fused_chunk, debug=debug,
                         device_init=device_init, robust=robust)
        if self.filter not in ("auto", "info", "ss"):
            raise ValueError(
                f"sharded filter must be 'auto', 'info' or 'ss'; "
                f"got {filter!r}")
        self.n_devices = n_devices
        self._drv = None          # ShardedEM from the last run_em
        self._drv_params = None   # the numpy params it ended at
        self._drv_panel = (None, None)   # the (Y, mask) objects it fitted

    def _mesh(self):
        from .parallel.mesh import make_mesh
        return make_mesh(self.n_devices)

    def prep_standardize(self, Y, model):
        # Only when the series axis divides the mesh evenly: otherwise
        # ShardedEM must pad on host, which needs the host panel anyway.
        if Y.shape[1] % self._mesh().devices.size:
            return None
        return super().prep_standardize(Y, model)

    def _filter_for(self, N: int, masked: bool = False) -> str:
        # Same auto tiering as TPUBackend minus the dense oracle (the
        # sharded E-steps are info/ss only); ShardedEM itself falls back to
        # the exact info scan when a mask defeats the ss freeze.
        if self.filter == "auto":
            return "ss" if not masked and N >= 512 else "info"
        return self.filter

    @staticmethod
    def _unpad_callback(callback, drv):
        """Hand callbacks UNPADDED numpy params (checkpoints stay loadable).

        The fused driver re-passes the same chunk-entry params object for
        every iteration of a chunk; the one-slot identity cache makes the
        host transfer happen once per chunk, not per iteration."""
        if callback is None:
            return None
        cache: dict = {}

        def wrapped(it, ll, p, **kw):
            key = id(p)
            if key not in cache:
                cache.clear()
                cache[key] = drv.params_numpy(p)
            return callback(it, ll, cache[key], **kw)

        wrapped.wants_params_iter = getattr(callback, "wants_params_iter",
                                            False)
        return wrapped

    def run_em(self, Y, mask, p0, model, max_iters, tol, callback):
        from .estim.em import EMConfig
        from .parallel.sharded import ShardedEM, sharded_em_fit
        if getattr(self, "_fused", None) is not None:
            import warnings
            warnings.warn(
                "the sharded backend has no fused while-loop driver yet; "
                "running the chunked path", RuntimeWarning, stacklevel=3)
        self._last_health = None
        # debug: the checkify float checks wrap the whole shard_map program
        # (parallel.sharded._sharded_em_*_checked_impl) — a poisoned shard
        # raises a LOCATED error through the psum, same contract as the
        # single-device TPUBackend(debug=True).
        flt = self._filter_for(Y.shape[1], mask is not None)
        self._last_filter = flt
        cfg = self._tuned_cfg(
            EMConfig(estimate_A=model.estimate_A,
                     estimate_Q=model.estimate_Q,
                     estimate_init=model.estimate_init, filter=flt,
                     debug=self.debug))
        if flt == "ss":
            from .ssm.steady import auto_tau
            cfg = dataclasses.replace(cfg, tau=auto_tau(p0))
        # Consume the device-init panel cache up front (one-shot — consuming
        # releases the pinned host+HBM copies even on paths that cannot
        # reuse it); same identity contract as TPUBackend._device_panel.
        # ShardedEM ignores it whenever padding/masking forces a host-side
        # rewrite.
        cached = getattr(self, "_panel_cache", None)
        self._panel_cache = None
        Y_dev = (cached[2] if cached is not None and cached[0] is Y
                 and cached[1] is mask else None)
        with self._precision_ctx():
            if self.fused_chunk <= 1:
                p, lls, converged, drv = sharded_em_fit(
                    Y, p0, mask=mask, mesh=self._mesh(), cfg=cfg,
                    max_iters=max_iters, tol=tol, dtype=self._dtype(),
                    callback=callback, Y_dev=Y_dev)
                self._drv, self._drv_params = drv, p
                self._drv_panel = (Y, mask)
                return p, lls, converged, drv.p_iters
            drv = ShardedEM(Y, p0, mask=mask, mesh=self._mesh(),
                            dtype=self._dtype(), cfg=cfg, Y_dev=Y_dev)

            def scan_fn(Yj, p, n, mask=None, cfg=None, with_metrics=False,
                        n_active=None):
                return drv.run_scan(p, n, with_metrics=with_metrics,
                                    n_active=n_active)

            scan_fn.trace_name = "sharded_em_chunk"
            scan_fn.trace_key = drv._trace_key()
            scan_fn.trace_engine = "sharded_em"

            controls = None
            if _resolve_policy(self.robust) is not None:
                from .robust.controls import ShardedControls
                controls = ShardedControls(drv)
            p, lls, converged, p_iters = self._run_em_chunked(
                drv.Y, drv.mask, drv.p, drv.cfg, max_iters, tol,
                self._unpad_callback(callback, drv), scan_fn,
                controls=controls)
            drv.p, drv.p_iters = p, p_iters
            pn = drv.params_numpy()
        self._drv, self._drv_params = drv, pn
        self._drv_panel = (Y, mask)
        return pn, lls, converged, p_iters

    def smooth(self, Y, mask, params):
        import jax.numpy as jnp
        from .parallel.mesh import pad_panel
        from .parallel.sharded import sharded_filter_smoother
        from .ssm.params import SSMParams as JaxParams
        # fit() calls smooth right after run_em with the exact (Y, mask,
        # params) objects run_em saw/returned; in that case the driver
        # already holds the padded panel and params on device — reuse them
        # instead of re-padding and re-transferring.  Identity (not value)
        # checks on ALL THREE: any other caller combination re-runs the
        # full path — a value-equal params set smoothing a different panel
        # must never get the cached panel's factors.
        panel = getattr(self, "_drv_panel", (None, None))
        if (self._drv is not None and Y is panel[0] and mask is panel[1]
                and params is self._drv_params):
            with self._precision_ctx():
                x_sm, P_sm, _ = self._drv.smooth()
            return np.asarray(x_sm, np.float64), np.asarray(P_sm, np.float64)
        dt = self._dtype()
        mesh = self._mesh()
        Yp, Wp, Lp, Rp, _ = pad_panel(
            np.asarray(Y, np.float64), mask, np.asarray(params.Lam),
            np.asarray(params.R), mesh.devices.size)
        pj = JaxParams(Lam=jnp.asarray(Lp, dt),
                       A=jnp.asarray(params.A, dt),
                       Q=jnp.asarray(params.Q, dt),
                       R=jnp.asarray(Rp, dt),
                       mu0=jnp.asarray(params.mu0, dt),
                       P0=jnp.asarray(params.P0, dt))
        mj = jnp.asarray(Wp, dt) if Wp is not None else None
        with self._precision_ctx():
            x_sm, P_sm, _ = sharded_filter_smoother(
                jnp.asarray(Yp, dt), pj, mask=mj, mesh=mesh)
        return np.asarray(x_sm, np.float64), np.asarray(P_sm, np.float64)


_BACKENDS: Dict[str, Type[Backend]] = {}


def register_backend(name: str, cls: Type[Backend]) -> None:
    """Plugin hook: make ``fit(..., backend=name)`` resolve to ``cls``."""
    _BACKENDS[name] = cls


def get_backend(backend: Union[str, Backend, None]) -> Backend:
    if backend is None:
        backend = "tpu"
    if isinstance(backend, Backend):
        return backend
    try:
        return _BACKENDS[backend]()
    except KeyError:
        raise ValueError(
            f"unknown backend {backend!r}; registered: {sorted(_BACKENDS)}")


register_backend("cpu", CPUBackend)
register_backend("tpu", TPUBackend)
register_backend("jax", TPUBackend)
register_backend("sharded", ShardedBackend)


def _family_fit(model, Y, mask, backend, max_iters, tol, init, callback,
                checkpoint_path, debug):
    """Route the non-plain model families through their drivers.

    The reference exposes ONE estimation seam — ``fit(dfm; backend=...)``
    (BASELINE.json:5) — so ours accepts every family's spec as ``model``:
    ``MixedFreqSpec`` -> ``models.mixed_freq.mf_fit``, ``TVLSpec`` ->
    ``models.tv_loadings.tvl_fit``, ``SVSpec`` -> ``models.sv.sv_fit``,
    each swapping to its sharded driver under ``backend="sharded"``.
    Returns the family's own result type (``MFResult``/``TVLResult``/
    ``SVFit`` — their fields differ by model semantics), or None when
    ``model`` is a plain ``DynamicFactorModel``.

    ``max_iters``/``tol`` override the family defaults only when given
    (``None`` keeps e.g. ``TVLSpec.n_rounds``); for SV, ``max_iters`` maps
    to the particle-EM round count ``sv_iters`` and ``tol`` is ignored
    (convergence there is monotone only up to MC noise — see models.sv).
    """
    from .models.mixed_freq import MFParams, MixedFreqSpec
    from .models.sv import SVSpec
    from .models.tv_loadings import TVLParams, TVLSpec
    if not isinstance(model, (MixedFreqSpec, TVLSpec, SVSpec)):
        return None
    Y = np.asarray(Y)
    name = type(model).__name__
    if checkpoint_path is not None:
        raise ValueError(
            f"checkpointing is not supported for the {name} family yet")
    if debug:
        import warnings
        warnings.warn(
            f"the {name} family has no checkify debug mode; running "
            "unchecked", RuntimeWarning, stacklevel=3)
    b = get_backend(backend)
    if isinstance(b, ShardedBackend):
        mesh = b._mesh()
    elif isinstance(b, TPUBackend):
        mesh = None
    else:
        raise ValueError(
            f"backend {b.name!r} cannot run the {name} family: these "
            "fits run on the default JAX device (their f64 oracle regime "
            "is a CPU-device process with x64 — see tests/conftest.py)")
    # A configured backend instance's knobs carry over where the family
    # drivers support them (dtype, fused_chunk); filter is plain-model
    # only and debug warned above.
    kw = dict(dtype=b.dtype if mesh is None else b._dtype(),
              fused_chunk=b.fused_chunk)
    iters = max_iters if max_iters is not None else 50
    tol_v = tol if tol is not None else 1e-6
    if isinstance(model, MixedFreqSpec):
        if init is not None and not isinstance(init, MFParams):
            raise TypeError(
                f"init for the {name} family must be MFParams; "
                f"got {type(init).__name__}")
        if mesh is not None:
            from .parallel.sharded_mf import sharded_mf_fit
            return sharded_mf_fit(Y, model, mask=mask, mesh=mesh,
                                  max_iters=iters, tol=tol_v,
                                  init=init, callback=callback, **kw)
        from .models.mixed_freq import mf_fit
        return mf_fit(Y, model, mask=mask, max_iters=iters, tol=tol_v,
                      init=init, callback=callback, **kw)
    if isinstance(model, TVLSpec):
        if init is not None and not isinstance(init, TVLParams):
            raise TypeError(
                f"init for the {name} family must be TVLParams; "
                f"got {type(init).__name__}")
        spec = model
        if max_iters is not None or tol is not None:
            spec = dataclasses.replace(
                model,
                n_rounds=max_iters if max_iters is not None
                else model.n_rounds,
                tol=tol if tol is not None else model.tol)
        if mesh is not None:
            from .parallel.sharded_tvl import sharded_tvl_fit
            return sharded_tvl_fit(Y, spec, mask=mask, mesh=mesh,
                                   init=init, callback=callback, **kw)
        from .models.tv_loadings import tvl_fit
        return tvl_fit(Y, spec, mask=mask, init=init, callback=callback,
                       **kw)
    if mask is not None or not bool(np.isfinite(Y).all()):
        # NaN-coded missing data must fail HERE like an explicit mask:
        # sv_filter has no missing-data handling, and NaNs would silently
        # poison the loglik/vol paths.
        raise ValueError("the SV family does not support missing data")
    if init is not None:
        raise ValueError("sv_fit estimates its own warm start; init is "
                         "not supported (see models.sv.sv_fit)")
    if callback is not None:
        raise ValueError(
            "sv_fit has no per-iteration callback (particle-EM rounds "
            "are fused programs; see models.sv.sv_fit) — call it "
            "directly and consume SVFit.logliks instead")
    from .models.sv import sv_fit
    # The resolved backend INSTANCE drives the EM pre-fit too, so a
    # configured mesh/dtype cannot diverge between the pre-fit and the
    # RBPF (get_backend accepts instances).
    return sv_fit(Y, model, backend=b, mesh=mesh,
                  sv_iters=iters if max_iters is not None else 10)


def fit(model,                     # DynamicFactorModel | family spec
        Y: np.ndarray,
        mask: Optional[np.ndarray] = None,
        backend: Union[str, Backend, None] = None,
        max_iters: Optional[int] = None,
        tol: Optional[float] = None,
        init=None,                 # family-typed warm start (SSMParams /
        callback: Optional[Callable] = None,       # MFParams / TVLParams)
        checkpoint_path: Optional[str] = None,
        checkpoint_every: int = 10,
        debug: bool = False,
        robust=None,
        telemetry=None,
        progress: Optional[Callable] = None,
        pipeline=None,
        fused=False,
        warm_start=None,
        auto=False,
        tune=None,
        keep_session=False):
    """Estimate a DFM: standardize -> PCA init -> EM -> smooth.

    ``model`` may also be a family spec — ``MixedFreqSpec``, ``TVLSpec``,
    or ``SVSpec`` — in which case the corresponding family driver runs
    (single-device or sharded per ``backend``) and its own result type
    (``MFResult``/``TVLResult``/``SVFit``) is returned instead of
    ``FitResult``; ``init`` must then be that family's params type.  See
    ``_family_fit``.

    Y    : (T, N) panel; NaNs mark missing observations.
    mask : optional explicit {0,1} mask, combined with the NaN pattern.
    backend : "cpu", "tpu", a Backend instance, or a registered name.
    max_iters / tol : EM budget and relative-loglik stop (default 50 and
        1e-6; ``None`` keeps each family's own defaults).
    checkpoint_path : if set, EM params are saved there every
        ``checkpoint_every`` iterations (atomic npz) and a compatible
        existing checkpoint is used as the warm start (resume).
    debug : NaN/inf guard mode (SURVEY.md section 5, sanitizers row): on
        JAX backends the EM step is instrumented with
        ``jax.experimental.checkify`` float checks, so poisoned inputs or
        parameters raise a LOCATED error at the first bad op instead of
        silently producing NaN logliks.  Much slower; diagnostic use only.
        (NaNs in Y itself are treated as missing data, not poison — poison
        means non-finite values the mask logic cannot see, e.g. a bad
        ``init`` or a data bug reintroducing inf after masking.)
    robust : health-monitored EM (``robust.guard``) override for THIS fit:
        ``True`` (default ``RobustPolicy``), a ``RobustPolicy`` instance,
        or ``False`` (legacy unguarded loop).  ``None`` keeps the backend
        instance's own setting (JAX backends default to guarded).  When
        the policy's ``on_failure="cpu"``, a fit whose recovery budget is
        exhausted (e.g. persistent device dispatch failures) re-runs from
        the last good params on the NumPy f64 oracle instead of raising;
        ``FitResult.health`` records everything the guard saw/did.
        Composes with every execution mode: ``fused=True`` routes the
        one-shot program through the same ``robust.dispatch`` guard
        (retry/backoff, watchdog deadline, fault seams), ``auto=True``
        applies the policy to whichever plan the advisor picks, and
        ``keep_session=True`` carries it into the session so every
        ``update()`` dispatch is guarded too.
    telemetry : observability for THIS fit (see ``dfm_tpu.obs``): ``None``
        inherits the ambient tracer (the ``DFM_TRACE=<path>`` env var),
        ``False`` forces telemetry hard-off, ``True`` records in memory
        and attaches the summary dict as ``FitResult.telemetry``, a path
        string writes a JSONL trace there (and attaches the summary), and
        an ``obs.Tracer`` instance is used as-is (the caller keeps
        ownership and must close it).  With telemetry off the dispatch
        path does zero extra work — no events, no clock reads, no host
        syncs.  Family fits are traced too, but only ``FitResult`` carries
        the summary attribute.
    progress : live per-chunk progress hook (fused-chunk JAX backends
        only): ``progress(info)`` fires once per dispatched chunk with
        {chunk, iter, total, loglik, delta, dparam, elapsed_s, eta_s,
        metrics, stopped, converged} — ``eta_s`` is the amortized-wall
        estimate over the remaining budget, ``metrics`` the (n, 3)
        device-side per-iteration array [loglik, in-chunk delta, max
        param-update norm] the chunk program accumulated (zero extra
        dispatches; see ``estim.em``).  With ``progress=None`` the
        metrics code never runs and the device program is byte-identical
        to the metrics-free path.
    pipeline : latency-hiding dispatch pipeline for the fused-chunk JAX
        backends (see ``dfm_tpu.pipeline``): an int issues that many
        chunks speculatively before each BLOCKING device->host loglik
        transfer (``pipeline=2`` halves the per-chunk tunnel round-trips
        on healthy fits; results stay bit-identical — convergence/health
        checks just run up to depth-1 chunks behind, rolling back through
        the drivers' existing chunk-entry replay on a mid-round stop).
        ``True`` means depth 2; a ``pipeline.PipelineConfig`` additionally
        opts into tail-chunk bucketing (``bucket=True``) so one fused-
        length executable serves every chunk length the fit dispatches;
        ``None``/``False`` keep the serial driver.  CPU oracle fits and
        the family drivers ignore it.  Independently, when the
        ``DFM_COMPILE_CACHE`` env var names a directory, compiled XLA
        executables persist across processes (``fit`` never creates the
        default ``.dfm_cache/`` on its own — only the bench/entry CLIs
        do; see ``pipeline.setup_compile_cache``).
    fused : dispatch-free end-to-end fit (``estim.fused``; JAX single-
        device backends): ``True`` runs EM to convergence inside ONE
        jitted program (``lax.while_loop`` with the convergence predicate
        on device), then smooths and emits nowcast / diffusion-index
        forecasts in the same program — one barrier'd device->host read
        per fit (~2 dispatches end-to-end vs one per chunk).  An int sets
        the forecast horizon; an ``estim.fused.FusedOptions`` configures
        it fully.  The result gains ``nowcast`` (N,) and ``forecasts``
        {"y", "f", "di"} in original data units.  A diverged fused run
        falls back to the guarded chunked driver from the on-device
        last-good checkpoint (``robust=False`` returns last-good params
        directly).  ``pipeline``/``progress`` are meaningless inside one
        program and ignored; ``debug=True`` raises (no checkify twin).
        CPU oracle and family fits ignore it with a warning.
    warm_start : a previous ``FitResult`` whose params seed this fit
        (the serving seam: refit after a panel update without the PCA
        init).  Validated STRUCTURALLY before anything compiles — a
        panel-shape, model, or missing-data-presence mismatch raises
        with a clear message instead of silently recompiling; pass
        ``init=prev.params`` to bypass validation.  Combined with
        ``fused=`` on the same backend instance and the same panel
        object, a warm refit re-enters the donated device program with
        zero h2d re-upload.  Mutually exclusive with ``init``.
    auto : auto-tuned execution plan (``obs.advise``): rank the candidate
        plans (fused vs chunked+pipeline, ``fused_chunk``, depth,
        bucketing) with the cost model calibrated from the profile
        records in the run registry (``python -m dfm_tpu.obs.profile``)
        and apply the top one — exactly as if its knobs had been passed
        explicitly, so the result is bit-identical to that fit.  Emits an
        ``advice`` trace event (predicted vs realized wall; gated by
        ``obs.regress`` as ``advice_rel_err``) and attaches the plan as
        ``FitResult.advice``.  An empty/uncalibrated registry falls back
        to the default knobs with a RuntimeWarning — ``auto`` never
        profiles inside ``fit`` and never tunes on pure priors.
        Mutually exclusive with explicit ``pipeline=``/``fused=``.
    tune : hyperparameter search before the fit (``estim.tune``): ``True``
        (defaults: in-graph gradient search), a ``TuneOptions``, or a
        kwargs dict.  The search runs on the standardized panel —
        ``method="grad"`` differentiates the held-out one-step MSE
        through the filter and takes ~20 in-graph Adam steps over
        (log Q-scale, log R-scale) in ONE jitted program (one blocking
        device->host read); ``method="sweep"`` rides all grid candidates
        as ONE fused batched-EM program plus one vmapped scoring program
        (two reads); ``"both"`` composes them.  The winning
        (q_scale, r_scale, lam_ridge) is applied to THIS fit through
        ``EMConfig``'s hyper fields — every execution mode (chunked,
        fused, pipelined, sharded) runs the tuned M-step — and the
        search record lands as ``FitResult.tune``.  The best candidate
        is never worse than untuned at the search's EM budget (theta=0 /
        the (1,1,0) grid point is always evaluated).  Mutually exclusive
        with ``auto=True`` (the advisor would re-plan a program the tune
        already committed to); CPU oracle and family fits warn + ignore.
        ``tune=None`` (default) is bit-identical to pre-tune ``fit()``.
    keep_session : open a streaming ``serve.NowcastSession`` on the fitted
        model (``FitResult.session``): params AND panel stay device-
        resident in a capacity-padded buffer, and every
        ``session.update(new_rows)`` runs ONE fused program dispatch (m
        warm EM iterations + smooth + nowcast/forecast) with zero
        recompiles after warmup.  ``True`` uses the session defaults; a
        dict passes ``open_session`` keywords (capacity,
        max_update_rows, max_iters, tol, horizon, di).
        DynamicFactorModel fits on JAX backends only.
    """
    tracer, owned = fit_tracer(telemetry)
    cache_dir = setup_compile_cache(ambient_only=True)
    cache_n0 = (compile_cache_entries(cache_dir)
                if cache_dir is not None and tracer is not None else 0)
    t0 = time.perf_counter()
    try:
        with activate(tracer):
            res = _fit_impl(model, Y, mask, backend, max_iters, tol, init,
                            callback, checkpoint_path, checkpoint_every,
                            debug, robust, progress, pipeline, fused,
                            warm_start, auto, tune)
            if keep_session and isinstance(res, FitResult):
                # Session open uses the ORIGINAL-units panel from this
                # scope (the session re-applies res.standardizer itself).
                from .serve import open_session
                skw = (dict(keep_session) if isinstance(keep_session, dict)
                       else {})
                # The per-fit robust override outlives the fit for its
                # session: updates run under the same policy the fit ran
                # under (the backend's own setting was already restored).
                if robust is not None and "robust" not in skw:
                    skw["robust"] = robust
                res.session = open_session(res, Y, mask=mask,
                                           backend=backend, **skw)
            if isinstance(res, FitResult) and res.advice is not None:
                # Close the advisor's loop: realized wall next to the
                # prediction (rel_err is the model-drift metric obs.regress
                # gates as advice_rel_err).
                realized = time.perf_counter() - t0
                res.advice["realized_wall_s"] = realized
                pred = res.advice.get("predicted_wall_s")
                if isinstance(pred, (int, float)) and realized > 0:
                    res.advice["rel_err"] = abs(float(pred)
                                                - realized) / realized
            if tracer is not None and isinstance(res, FitResult):
                if cache_dir is not None:
                    n1 = compile_cache_entries(cache_dir)
                    tracer.emit("compile_cache", dir=cache_dir, entries=n1,
                                new_entries=n1 - cache_n0)
                tracer.emit("fit", t=t0, engine=res.backend,
                            filter=res.filter,
                            shape=shape_key(Y), n_iters=res.n_iters,
                            converged=bool(res.converged),
                            wall=time.perf_counter() - t0)
                if res.advice is not None:
                    tracer.emit("advice", **res.advice)
            elif isinstance(res, FitResult):
                # Untraced: the always-on live plane still counts the fit
                # (same payload the tracer would carry).
                from .obs.live import observe as live_observe
                live_observe({"t": t0, "kind": "fit", "engine": res.backend,
                              "filter": res.filter,
                              "shape": shape_key(Y),
                              "n_iters": res.n_iters,
                              "converged": bool(res.converged),
                              "wall": time.perf_counter() - t0})
    finally:
        if owned:
            tracer.close()
    if (tracer is not None and telemetry not in (None, False)
            and isinstance(res, FitResult)):
        res.telemetry = tracer.summary()
    if tracer is not None and isinstance(res, FitResult):
        # Perf observatory: a traced fit appends a RunRecord when (and
        # only when) DFM_RUNS is explicitly set — see obs/store.py.
        _maybe_record_fit_run(res, Y, time.perf_counter() - t0)
    return res


def _maybe_record_fit_run(res: "FitResult", Y, wall: float) -> None:
    from .obs.store import RunStore, device_kind, make_record, runs_dir
    d = runs_dir(ambient_only=True)
    if d is None:
        return
    try:
        import jax
        dev = str(jax.devices()[0].platform)
    except Exception:
        dev = None
    config = {"fit": type(res.model).__name__, "backend": res.backend,
              "n_factors": getattr(res.model, "n_factors", None),
              "T": int(Y.shape[0]), "N": int(Y.shape[1]),
              "device": device_kind(dev)}
    metrics = {"wall_s": wall}
    if wall > 0:
        metrics["fit_iters_per_sec"] = res.n_iters / wall
    tele = res.telemetry or {}
    if tele.get("blocking_transfers") is not None:
        metrics["blocking_transfers"] = tele["blocking_transfers"]
    try:
        RunStore(d).append(make_record(
            "fit", config, metrics, device=dev, loglik=res.loglik,
            convergence=[float(x) for x in res.logliks],
            dispatches=tele.get("dispatches"),
            recompiles=tele.get("recompiles"), wall_s=wall))
    except Exception as e:       # never fail a fit over bookkeeping
        import warnings
        warnings.warn(f"DFM_RUNS append failed: {e}", RuntimeWarning,
                      stacklevel=2)


def fit_jobs(jobs, *, backend: str = "tpu", max_buckets: int = 3,
             dtype=None, fused_chunk: int = 8,
             n_devices: Optional[int] = None, robust=True, pipeline=None,
             cost_model=None, telemetry=None, stats: Optional[dict] = None):
    """Fit heterogeneous (N, T, k) jobs as shape-bucketed fused batches.

    The multi-tenant seam over ``sched.submit``: each element of ``jobs``
    is a ``dfm_tpu.sched.Job`` (panel + model + per-tenant ``max_iters``/
    ``tol``), assigned by the cost-model bucket planner to one of at most
    ``max_buckets`` padded shapes, and every bucket runs as ONE fused
    batched program (per-tenant convergence freezes inside).  Returns
    per-tenant ``JobResult``s in submit order; each ``.fit`` is a full
    ``FitResult`` numerically identical to ``fit()`` of that job alone
    (x64 bit-exact, f32 within tolerance — pinned by tests/test_sched.py).

    backend: "tpu" (single-device fused batches) or "sharded" (bucket
    batch axes split across the mesh).  ``telemetry`` as in ``fit``;
    traced runs emit one ``tenant`` event per job (queue wait / compute /
    pad waste — ``obs.report`` renders the per-tenant table) and the
    summary attaches to every ``JobResult.telemetry``.  ``stats`` (a
    dict) receives plan/pack/compute accounting for benches.
    """
    from .sched import submit as _submit
    tracer, owned = fit_tracer(telemetry)
    try:
        with activate(tracer):
            results = _submit(jobs, backend=backend,
                              max_buckets=max_buckets, dtype=dtype,
                              fused_chunk=fused_chunk, n_devices=n_devices,
                              robust=robust, pipeline=pipeline,
                              cost_model=cost_model, stats=stats)
    finally:
        if owned:
            tracer.close()
    if tracer is not None and telemetry not in (None, False):
        summary = tracer.summary()
        for r in results:
            r.telemetry = summary
            r.fit.telemetry = summary
    return results


def _resolve_warm_start(ws, init, model, N, fp_now):
    """Validate ``fit(warm_start=...)`` and return the seed params.

    STRUCTURAL validation only (shape / model / missing-data presence):
    re-fitting updated VALUES of the same panel shape is the intended
    serving flow — a mismatch here means the warm start would force a
    silent recompile (or worse, a shape error deep in the scan), so it
    raises with the fix spelled out instead.
    """
    if init is not None:
        raise ValueError(
            "pass either warm_start= or init=, not both (warm_start is "
            "validated; init is used verbatim)")
    if not isinstance(ws, FitResult):
        raise TypeError(
            f"warm_start must be a FitResult; got {type(ws).__name__} "
            "(pass raw params via init= instead)")
    Lam = np.asarray(ws.params.Lam)
    if Lam.shape != (N, model.n_factors):
        raise ValueError(
            f"warm_start params have Lam shape {Lam.shape}, but this "
            f"panel/model needs ({N}, {model.n_factors}) — refusing to "
            "silently recompile; fit this panel cold or pass a matching "
            "warm start")
    if ws.model != model:
        raise ValueError(
            f"warm_start was fitted with {ws.model!r}, not {model!r} — "
            "a different model spec would silently recompile every "
            "program; pass init=warm_start.params to override")
    if ws.fingerprint is not None and ws.fingerprint != fp_now:
        raise ValueError(
            "warm_start fingerprint mismatch: the previous fit saw a "
            "different panel shape or missing-data structure, so its "
            "executables cannot be reused (every program would "
            "recompile).  Pass init=warm_start.params to warm-start "
            "anyway, or refit cold.")
    return ws.params


def _resolve_auto_plan(b, N, T, k, max_iters):
    """Pick the top ``obs.advise`` plan for this fit, or None (defaults).

    Reads the ambient run registry only — never profiles, never writes.
    A backend without the fused/pipeline seams, or a registry without
    profile records, falls back to the default knobs with a warning
    (auto-tuning on pure priors would be guessing with extra steps).
    """
    import warnings
    if not (hasattr(b, "_fused") and hasattr(b, "_pipeline")):
        warnings.warn(
            f"backend {b.name!r} has no fused/pipeline execution plans to "
            "choose between; ignoring auto=", RuntimeWarning, stacklevel=4)
        return None
    from .obs.advise import advise
    try:
        import jax
        dev = str(jax.devices()[0].platform)
    except Exception:
        dev = None
    from .obs.store import device_kind
    res = advise(N, T, k, max_iters=max_iters,
                 chunk=int(getattr(b, "fused_chunk", 8)),
                 device=device_kind(dev) if dev else None)
    if not res.get("calibrated") or not res.get("plans"):
        warnings.warn(
            "auto=True found no profile records in the run registry — "
            "running the default knobs.  Calibrate first: "
            f"python -m dfm_tpu.obs.profile --shape {N},{T},{k}",
            RuntimeWarning, stacklevel=4)
        return None
    plan = dict(res["plans"][0])
    plan["n_profiles"] = res["n_profiles"]
    return plan


def _fit_impl(model, Y, mask, backend, max_iters, tol, init, callback,
              checkpoint_path, checkpoint_every, debug, robust,
              progress=None, pipeline=None, fused=False, warm_start=None,
              auto=False, tune=None):
    if warm_start is not None and not isinstance(model, DynamicFactorModel):
        raise TypeError(
            f"warm_start is only supported for DynamicFactorModel fits; "
            f"the {type(model).__name__} family has its own init= type")
    family = _family_fit(model, Y, mask, backend, max_iters, tol, init,
                         callback, checkpoint_path, debug)
    if family is not None:
        if progress is not None:
            import warnings
            warnings.warn(
                f"the {type(model).__name__} family has no per-chunk "
                "progress hook; ignoring progress=", RuntimeWarning,
                stacklevel=3)
        if fused:
            import warnings
            warnings.warn(
                f"the {type(model).__name__} family has no fused "
                "while-loop driver; ignoring fused=", RuntimeWarning,
                stacklevel=3)
        if auto:
            import warnings
            warnings.warn(
                f"the {type(model).__name__} family has no auto-tunable "
                "execution plans; ignoring auto=", RuntimeWarning,
                stacklevel=3)
        if tune:
            import warnings
            warnings.warn(
                f"the {type(model).__name__} family has no hyper-tuning "
                "seam; ignoring tune=", RuntimeWarning, stacklevel=3)
        return family
    max_iters = 50 if max_iters is None else max_iters
    tol = 1e-6 if tol is None else tol
    Y = np.asarray(Y)
    if Y.ndim != 2:
        raise ValueError(f"Y must be (T, N); got shape {Y.shape}")
    T, N = Y.shape
    if model.n_factors > min(T, N):
        raise ValueError(f"n_factors={model.n_factors} exceeds min(T, N)={min(T, N)}")
    if T < 2 and model.dynamics == "ar1":
        raise ValueError("ar1 dynamics needs T >= 2 (the M-step divides by T-1)")
    from .utils.data import validate_panel
    # Fail fast with column indices instead of NaN/Inf panels downstream
    # (all-NaN columns have undefined stats; constant columns explode the
    # standardization scale floor).
    validate_panel(Y, mask, check_variance=model.standardize)

    # Structural warm-start fingerprint: computed on the ORIGINAL panel
    # (before standardization/device prep) so it matches what a later
    # fit(warm_start=this_result) will compute for the same inputs.
    from .utils.checkpoint import warm_fingerprint
    has_missing = bool(mask is not None or not np.isfinite(Y).all())
    fp_now = warm_fingerprint((T, N), model, has_missing)
    if warm_start is not None:
        init = _resolve_warm_start(warm_start, init, model, N, fp_now)

    b = get_backend(backend)
    b._last_filter = None   # set by run_em on backends with the filter knob
    # Auto-tuned plan (obs.advise): resolves to the SAME pipeline=/fused=/
    # fused_chunk knobs an explicit call would pass, so everything below
    # (and the result, bit for bit) is identical to the explicit-knob fit.
    auto_plan = None
    restore_chunk = None
    restore_filter = None
    if auto:
        if pipeline is not None or fused:
            raise ValueError(
                "auto=True picks the execution plan itself — drop the "
                "explicit pipeline=/fused= knobs (or drop auto=)")
        if tune:
            raise ValueError(
                "auto=True and tune=... are mutually exclusive: the "
                "advisor re-plans the very program the tuned hypers "
                "committed to (drop one of them)")
        auto_plan = _resolve_auto_plan(b, N, T, model.n_factors, max_iters)
        if auto_plan is not None:
            chunk = int(auto_plan.get("fused_chunk") or 0)
            if chunk and getattr(b, "fused_chunk", chunk) != chunk:
                restore_chunk = (b.fused_chunk,)
                b.fused_chunk = chunk
            # Time-scan engine choice (seq vs pit_qr vs lowrank): applied
            # transiently and only when the backend's own knob is "auto" —
            # an explicit filter= on the backend always wins.  The override
            # resolves to the SAME EMConfig an explicit
            # TPUBackend(filter="pit_qr") / TPUBackend(filter="lowrank")
            # (default rank — plans carry no rank key) would build, so the
            # result is bit-identical to that knob.
            plan_flt = auto_plan.get("filter")
            if (plan_flt and plan_flt != "seq"
                    and getattr(b, "filter", None) == "auto"):
                restore_filter = (b.filter,)
                b.filter = plan_flt
            if auto_plan["engine"] == "fused":
                fused = True
            elif (int(auto_plan.get("depth") or 1) > 1
                    or auto_plan.get("bucket")):
                from .pipeline import PipelineConfig
                pipeline = PipelineConfig(
                    depth=int(auto_plan.get("depth") or 1),
                    bucket=bool(auto_plan.get("bucket")))
    std: Optional[Standardizer] = None
    dev_prep = None
    if mask is None and checkpoint_path is None:
        # Device-side prep for large fully-observed panels on JAX backends:
        # the raw panel transfers once and standardization runs on device
        # (one fused program) instead of ~0.5 s of host NumPy passes at the
        # 10k x 500 shape.  Checkpointing keeps the host path — the data
        # fingerprint hashes host bytes.
        prep = getattr(b, "prep_standardize", None)
        if prep is not None:
            dev_prep = prep(Y, model)
    if dev_prep is not None:
        Yz, std = dev_prep         # Yz lives on device; Standardizer on host
        any_missing = False
        Wm = None
    else:
        Y = np.asarray(Y, dtype=np.float64)
        W = build_mask(Y, mask)
        any_missing = bool((W == 0).any())
        if model.standardize:
            if (not any_missing and checkpoint_path is None
                    and Y.size >= _ONEPASS_MIN_SIZE):
                # Large fully-observed panel on the host path: one fused
                # mean/var pass emitting the backend's compute dtype
                # directly (an f32 backend skips the f64 intermediate —
                # PERF.md host-prep line).  Checkpointing keeps the f64
                # path: the data fingerprint hashes the standardized bytes.
                from .utils.data import standardize_onepass
                bdt = getattr(b, "_dtype", None)
                out_dt = np.dtype(str(bdt())) if bdt is not None \
                    else np.float64
                Y, std = standardize_onepass(Y, out_dtype=out_dt)
            else:
                Y, std = standardize(Y, mask=W if any_missing else None)
        Wm = W if any_missing else None
        # Fully observed: Y already has no NaNs and the where() would be an
        # identity — skip the 40 MB copy (panels are never mutated).
        Yz = (Y if not any_missing
              else np.where(W > 0, np.nan_to_num(Y), 0.0))

    fingerprint = None
    done_iters = 0
    ck = None
    if checkpoint_path is not None:
        from .utils.checkpoint import data_fingerprint
        fingerprint = data_fingerprint(Y, W if any_missing else None, model)
    if init is None and checkpoint_path is not None:
        from .utils.checkpoint import load_checkpoint
        # Fingerprint mismatch -> cold start with the FULL iteration
        # budget (a checkpoint from foreign data must never warm-start the
        # fit; pinned by tests/test_select_eval.py).  Callers who want the
        # mismatch to fail loudly call load_checkpoint(on_mismatch="raise")
        # themselves.
        ck = load_checkpoint(checkpoint_path, fingerprint=fingerprint)
        if ck is not None and ck[0].Lam.shape == (N, model.n_factors):
            init = ck[0]
            # The stored iter counts EM iterations those params embody:
            # resume with the remaining budget, not max_iters from scratch.
            done_iters = ck[1]
        else:
            ck = None
    if init is None:
        init = b.default_init(Yz, Wm, model)
    # tune: hyper search BEFORE the fit (estim.tune), its winner applied
    # transiently through the backend's _tune_hypers seam — every program
    # the drivers build below folds the (q_scale, r_scale, lam_ridge)
    # triple in via _tuned_cfg.  Same transient contract as debug/robust.
    tune_rec = None
    restore_tune = None
    if tune is not None and tune is not False:
        from .estim.tune import resolve_tune as _resolve_tune
        from .estim.tune import tune_fit as _tune_fit
        topts = _resolve_tune(tune)
        if topts is not None and not hasattr(b, "_tune_hypers"):
            import warnings
            warnings.warn(
                f"backend {b.name!r} has no tuned-hyper seam; ignoring "
                "tune=", RuntimeWarning, stacklevel=3)
        elif topts is not None:
            from .estim.em import EMConfig as _EMConfig
            bdt = getattr(b, "_dtype", None)
            tune_rec = _tune_fit(
                Yz, Wm, init,
                _EMConfig(estimate_A=model.estimate_A,
                          estimate_Q=model.estimate_Q,
                          estimate_init=model.estimate_init,
                          filter="info"),
                topts, dtype=(bdt() if bdt is not None else None))
            restore_tune = (b._tune_hypers,)
            b._tune_hypers = (tune_rec["q_scale"], tune_rec["r_scale"],
                              tune_rec["lam_ridge"])
    # debug only toggles THIS fit: user-supplied backend instances are
    # restored on exit (checkify mode is orders of magnitude slower — it
    # must not silently stick to the instance for later fits).
    restore_debug = None
    if debug:
        if hasattr(b, "debug"):
            restore_debug = b.debug
            b.debug = True
        else:
            import warnings
            warnings.warn(
                f"backend {b.name!r} has no debug (checkify) mode; "
                "running unchecked", RuntimeWarning, stacklevel=2)
    # robust only toggles THIS fit, same transient contract as debug
    # (user-supplied backend instances are restored on exit).  The CPU
    # oracle has no guarded loop — robust= is a no-op there.
    restore_robust = None
    if robust is not None and hasattr(b, "robust"):
        restore_robust = (b.robust,)
        b.robust = robust
    # progress only rides along for THIS fit, same transient contract as
    # debug/robust.  Backends without the fused-chunk driver (CPU oracle)
    # have no seam for it.
    restore_progress = None
    if progress is not None:
        if hasattr(b, "_progress"):
            restore_progress = (b._progress,)
            b._progress = progress
        else:
            import warnings
            warnings.warn(
                f"backend {b.name!r} has no per-chunk progress hook; "
                "ignoring progress=", RuntimeWarning, stacklevel=2)
    # pipeline rides along for THIS fit only, same transient contract as
    # debug/robust/progress.  A perf knob with no semantic effect, so
    # backends without the fused-chunk driver just ignore it silently.
    restore_pipeline = None
    if pipeline is not None and hasattr(b, "_pipeline"):
        restore_pipeline = (b._pipeline,)
        b._pipeline = pipeline
    # fused rides along for THIS fit only, same transient contract as
    # debug/robust/progress/pipeline.
    restore_fused = None
    if fused:
        if hasattr(b, "_fused"):
            from .estim.fused import resolve_fused
            restore_fused = (b._fused,)
            b._fused = resolve_fused(fused)
        else:
            import warnings
            warnings.warn(
                f"backend {b.name!r} has no fused while-loop driver; "
                "running the standard path", RuntimeWarning, stacklevel=2)
    restore_gck = None
    if checkpoint_path is not None and hasattr(b, "_guard_checkpoint"):
        # Let the guard save the last GOOD params before declaring failure
        # (resume seam: the next run warm-starts past the trouble).
        restore_gck = (b._guard_checkpoint,)
        b._guard_checkpoint = (checkpoint_path, fingerprint, done_iters)

    history: list = []
    t_prev = time.perf_counter()

    def _cb(it, ll, p, params_iter=None):
        nonlocal t_prev
        now = time.perf_counter()
        rec = {"iter": it, "loglik": float(ll), "secs": now - t_prev}
        t_prev = now
        history.append(rec)
        if checkpoint_path is not None and (it + 1) % checkpoint_every == 0:
            from .utils.checkpoint import save_checkpoint
            # p embodies `p_it` completed iterations counted from this run's
            # start (== it except in the fused-chunk driver, which hands
            # chunk-entry params); stored globally, offset by the resumed-in
            # iterations.
            p_it = it if params_iter is None else params_iter
            save_checkpoint(checkpoint_path, p, done_iters + p_it,
                            [h["loglik"] for h in history][:p_it],
                            fingerprint=fingerprint)
        if callback is not None:
            callback(it, ll, p)

    _cb.wants_params_iter = True

    smooth_b = b
    health = None
    try:
        if ck is not None and done_iters >= max_iters:
            # The checkpoint already exhausted this budget: return its state
            # instead of creeping past max_iters one iteration per rerun.
            params, lls, converged = init, np.asarray(ck[2]), ck[3]
        else:
            try:
                out = b.run_em(Yz, Wm, init, model, max_iters - done_iters,
                               tol, _cb)
                health = getattr(b, "_last_health", None)
            except Exception as e:
                from .robust.guard import GuardFailure
                pol = (_resolve_policy(getattr(b, "robust", None))
                       if isinstance(e, GuardFailure) else None)
                if pol is None or pol.on_failure != "cpu":
                    raise
                # Graceful degradation: the guard exhausted its recovery
                # budget — re-run the REMAINING iterations from the last
                # good params on the NumPy f64 oracle.  Everything the
                # guard saw (and this fallback) is in FitResult.health.
                health = e.health
                health.fallback_backend = "cpu"
                warm = e.last_good if e.last_good is not None else init
                remaining = max(max_iters - done_iters - e.p_iters, 1)
                smooth_b = CPUBackend()
                cpu_out = smooth_b.run_em(
                    np.asarray(Yz, np.float64), Wm, warm, model, remaining,
                    tol, _cb)
                cpu_piters = (cpu_out[3] if len(cpu_out) > 3
                              else len(cpu_out[1]))
                out = (cpu_out[0],
                       np.concatenate([e.lls[:e.p_iters],
                                       np.asarray(cpu_out[1])]),
                       cpu_out[2], e.p_iters + cpu_piters)
            params, lls, converged = out[:3]
            # Built-in backends report how many EM updates the returned
            # params embody (!= len(lls) after a divergence or mid-chunk
            # stop); third-party 3-tuple backends default to len(lls).
            p_iters = out[3] if len(out) > 3 else len(lls)
            if checkpoint_path is not None:
                from .utils.checkpoint import save_checkpoint
                save_checkpoint(checkpoint_path, params,
                                done_iters + p_iters,
                                [h["loglik"] for h in history],
                                fingerprint=fingerprint, converged=converged)
        x_sm, P_sm = smooth_b.smooth(
            Yz if smooth_b is b else np.asarray(Yz, np.float64), Wm, params)
        # One-shot fused extras (nowcast/forecasts, standardized units) —
        # only valid when the backend that fitted also smoothed.
        fused_extra = None
        if smooth_b is b and getattr(b, "_fused_outputs", None) is not None:
            fused_extra = b._fused_outputs
            b._fused_outputs = None
    finally:
        if restore_debug is not None:
            b.debug = restore_debug
        if restore_robust is not None:
            b.robust = restore_robust[0]
        if restore_progress is not None:
            b._progress = restore_progress[0]
        if restore_pipeline is not None:
            b._pipeline = restore_pipeline[0]
        if restore_fused is not None:
            b._fused = restore_fused[0]
        if restore_chunk is not None:
            b.fused_chunk = restore_chunk[0]
        if restore_filter is not None:
            b.filter = restore_filter[0]
        if restore_gck is not None:
            b._guard_checkpoint = restore_gck[0]
        if restore_tune is not None:
            b._tune_hypers = restore_tune[0]
    nowcast = forecasts = None
    if fused_extra is not None:
        inv = std.inverse if std is not None else (lambda a: a)
        nowcast = np.asarray(inv(fused_extra["nowcast"]))
        di = fused_extra["di"]
        forecasts = {"y": np.asarray(inv(fused_extra["y_fore"])),
                     "f": np.asarray(fused_extra["f_fore"]),
                     "di": np.asarray(inv(di)) if di is not None else None}
    return FitResult(params=params, logliks=np.asarray(lls),
                     factors=x_sm, factor_cov=P_sm,
                     converged=bool(converged), n_iters=len(lls),
                     standardizer=std, model=model,
                     backend=smooth_b.name if smooth_b is not b else b.name,
                     history=history, health=health,
                     fingerprint=fp_now, nowcast=nowcast,
                     forecasts=forecasts, advice=auto_plan,
                     filter=getattr(b, "_last_filter", None),
                     tune=tune_rec)


def forecast(result, horizon: int):
    """h-step-ahead forecasts in ORIGINAL data units (de-standardized).

    Returns (y_fore (h, N), f_fore (h, k)).  Reference behavior per SURVEY.md
    section 3.2 (filter to T, iterate dynamics, map through loadings).
    Dispatches across every model family: plain/AR(1) ``FitResult``,
    mixed-frequency ``MFResult`` (companion-state iteration), TVL
    ``TVLResult`` (loadings frozen at T), and SV ``SVFit`` (conditional
    means; ``models.sv.sv_forecast`` additionally returns the vol bands).
    """
    from .models.mixed_freq import MFResult, mf_forecast
    from .models.sv import SVFit, sv_forecast
    from .models.tv_loadings import TVLResult, tvl_forecast
    if isinstance(result, MFResult):
        return mf_forecast(result, horizon)
    if isinstance(result, TVLResult):
        return tvl_forecast(result, horizon)
    if isinstance(result, SVFit):
        return sv_forecast(result, horizon)[:2]
    p = result.params
    # Re-filter to the end of sample using smoothed factors' last state:
    x_T = result.factors[-1]
    P_T = result.factor_cov[-1]
    f, y, _ = cpu_ref.forecast(p, x_T, P_T, horizon)
    if result.standardizer is not None:
        y = result.standardizer.inverse(y)
    return y, f

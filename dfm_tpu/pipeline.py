"""Latency-hiding dispatch pipeline: config + persistent compile cache.

The chunked EM drivers pay three FIXED costs per fit that have nothing to
do with the math (docs/PERF.md "End-to-end fixed costs"): ~60-100 ms of
axon tunnel latency per fused-chunk dispatch, a fresh XLA executable per
distinct tail-chunk length, and seconds of compile on first call.  This
module owns the knobs that hide them:

- :class:`PipelineConfig` — ``depth`` speculative chunks in flight before
  the driver blocks on a device->host loglik transfer (the only true
  execution barrier on axon), and ``bucket`` tail-chunk padding so every
  chunk dispatch reuses ONE executable (inert extra iterations via the
  convergence-freeze selects the batched engine pioneered).  The drivers
  consume this via ``run_em_chunked(pipeline=...)`` /
  ``fit(pipeline=...)``; ``PipelineConfig()`` is bit-for-bit today's
  serial behavior.
- :func:`setup_compile_cache` — wires jax's persistent compilation cache
  (``jax_compilation_cache_dir``) so a fresh process re-fitting a known
  shape skips XLA compilation entirely.  Resolution mirrors the run
  registry (``obs.store.runs_dir``): an explicit path wins, then the
  ``DFM_COMPILE_CACHE`` env var (empty/"0"/"off" disables), then the
  git-ignored ``.dfm_cache/`` default — but library calls (``fit()``)
  pass ``ambient_only=True`` so a default never creates directories as a
  side effect; only the CLIs (bench.py, bench/run.py, __graft_entry__.py)
  opt into the default dir.

Kept jax-free at import time (jax is imported lazily inside
``setup_compile_cache``) so config resolution is usable from offline
tooling.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Union

__all__ = ["PipelineConfig", "resolve_pipeline", "setup_compile_cache",
           "compile_cache_dir", "compile_cache_entries",
           "CACHE_ENV", "DEFAULT_CACHE_DIR"]


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """Dispatch-pipeline knobs for the chunked EM drivers.

    depth: chunks issued speculatively before the driver performs its one
        BLOCKING device->host transfer per round (the newest chunk's
        logliks; older chunks' outputs are already materialized by then).
        Device programs queue on axon, so depth d turns d serial
        (dispatch, block, check) round-trips into d async dispatches plus
        one block — convergence checks run up to d-1 chunks behind and
        roll back through the drivers' existing chunk-entry replay when a
        stop lands mid-round.  Results are bit-identical to serial: the
        chunk programs and the params they chain through do not depend on
        WHEN the logliks are read.  depth=1 is today's behavior.

    bucket: pad tail chunks (``n = min(fused_chunk, max_iters - it)`` and
        mid-chunk replays) up to the fused chunk length with a dynamic
        ``n_active`` cap — iterations past the cap hold the carry via
        where-selects, so one executable serves every chunk length a fit
        can produce and the RecompileDetector sees one bucket-aware shape
        key (``itersNb``) instead of per-tail churn.
    """
    depth: int = 1
    bucket: bool = False

    def __post_init__(self):
        if int(self.depth) < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {self.depth}")

    @property
    def active(self) -> bool:
        """Whether this config changes anything vs the serial driver."""
        return self.depth > 1 or self.bucket


def resolve_pipeline(spec: Union[None, bool, int, PipelineConfig]
                     ) -> PipelineConfig:
    """Coerce a user-facing ``pipeline=`` value into a PipelineConfig.

    None / False -> defaults (serial); True -> depth 2; an int -> that
    depth (bucketing stays opt-in via an explicit PipelineConfig so the
    plain ``pipeline=2`` path keeps the strict bit-identity guarantee).
    """
    if spec is None or spec is False:
        return PipelineConfig()
    if spec is True:
        return PipelineConfig(depth=2)
    if isinstance(spec, PipelineConfig):
        return spec
    if isinstance(spec, int):
        return PipelineConfig(depth=spec)
    raise TypeError(
        f"pipeline= expects None, bool, int, or PipelineConfig; "
        f"got {type(spec).__name__}")


# -- persistent compilation cache -----------------------------------------

CACHE_ENV = "DFM_COMPILE_CACHE"
DEFAULT_CACHE_DIR = ".dfm_cache"
_DISABLE_VALUES = {"", "0", "off", "none", "disable", "disabled"}

# Process-global record of what was wired, so repeated fits are free and
# telemetry can report the active dir without re-resolving.
_state = {"dir": None, "configured": False}


def _resolve_cache_dir(path: Optional[str],
                       ambient_only: bool) -> Optional[str]:
    if path is not None:
        p = str(path)
        return None if p.strip().lower() in _DISABLE_VALUES else p
    env = os.environ.get(CACHE_ENV)
    if env is not None:
        return None if env.strip().lower() in _DISABLE_VALUES else env
    return None if ambient_only else DEFAULT_CACHE_DIR


def setup_compile_cache(path: Optional[str] = None, *,
                        ambient_only: bool = False) -> Optional[str]:
    """Point jax's persistent compile cache at a directory; idempotent.

    Returns the resolved absolute cache dir, or None when disabled (an
    explicit/env value of ""/"0"/"off"..., or ``ambient_only=True`` with
    ``DFM_COMPILE_CACHE`` unset — the library-call mode: ``fit()`` must
    not create ``.dfm_cache/`` as a side effect of a default, same
    contract as ``obs.store.runs_dir``).

    Beyond ``jax_compilation_cache_dir`` this clears jax's minimum
    compile-time / entry-size thresholds: the defaults skip sub-second
    compiles, which on the CPU fallback (and for the small per-fit
    assembly programs) is EVERY program — with the thresholds in place
    the cache would sit empty exactly where the cold-start cost lives.
    """
    d = _resolve_cache_dir(path, ambient_only)
    if d is None:
        return None
    d = os.path.abspath(d)
    if _state["configured"] and _state["dir"] == d:
        return d
    import jax
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _state["dir"] = d
    _state["configured"] = True
    return d


def compile_cache_dir() -> Optional[str]:
    """The cache dir wired by ``setup_compile_cache`` this process (None
    when the cache was never enabled)."""
    return _state["dir"] if _state["configured"] else None


def compile_cache_entries(path: Optional[str]) -> int:
    """Number of persisted executables under a cache dir (0 when absent).

    The before/after delta around a fit is the cache-miss count the trace
    surfaces as a ``compile_cache`` event: ``new_entries == 0`` with
    first-call dispatches present means every compile was served warm —
    the tracked cold-start metric next to ``compile_proxy_s``.
    """
    if not path or not os.path.isdir(path):
        return 0
    n = 0
    for _root, _dirs, files in os.walk(path):
        n += len(files)
    return n

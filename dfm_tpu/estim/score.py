"""Shared held-out one-step prediction scoring (arXiv 1910.08615).

One definition of "model quality" rides every seam that needs it:

- ``fleet/maintenance.heldout_score`` (the drift-refit quality gate),
- ``estim.tune``'s cross-validated / differentiable objective, and
- ``estim.evaluate.oos_evaluate``'s forecast-error windowing

all call into this module, so a change to the objective changes every
consumer at once instead of drifting three private copies apart.

The core (:func:`one_step_sse`) is array-module generic: pass ``xp=numpy``
for the f64 oracle paths (jax-free — maintenance can score without
touching the device) or ``xp=jax.numpy`` to compute the SAME reduction
in-graph, where it is reverse-mode differentiable (the seam
``estim.tune`` drives gradients through).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["one_step_sse", "heldout_mse_np", "heldout_mse_graph",
           "forecast_origin_errors", "clamp_holdout"]


def clamp_holdout(holdout_rows: int, T: int) -> int:
    """Trailing-window length actually scored: at least 1 row, never the
    whole panel (one-step predictions need at least one training row)."""
    return max(1, min(int(holdout_rows), T - 1))


def one_step_sse(Y, W, x_pred, Lam, holdout_rows: int, xp=np):
    """Sum of squared one-step prediction errors over the observed entries
    of the trailing ``holdout_rows`` rows, plus the observed count.

    ``x_pred`` (T, k) are the filter's one-step state predictions —
    ``x_pred[t]`` uses data strictly before ``t``, so scoring rows the
    filter also saw is legitimate pseudo-out-of-sample scoring.  ``W``
    may be ``None`` (observedness falls back to ``isfinite(Y)``).

    Returns ``(sse, n_obs)`` in ``xp``'s array type; callers divide
    (hosts guard n == 0 with NaN, graphs with ``maximum(n, 1)``).
    """
    T = Y.shape[0]
    h = clamp_holdout(holdout_rows, T)
    lo = T - h
    pred = x_pred[lo:] @ Lam.T
    obs = (W[lo:] > 0) if W is not None else xp.isfinite(Y[lo:])
    err = xp.where(obs, xp.nan_to_num(Y[lo:]) - pred, 0.0)
    return (err * err).sum(), obs.sum()


def heldout_mse_np(Y_std: np.ndarray, W: Optional[np.ndarray], params,
                   holdout_rows: int) -> float:
    """Held-out one-step MSE via the NumPy f64 oracle filter (standardized
    units; lower is better; NaN when the window holds no observed entry).

    This is the maintenance quality gate's scorer — the historical
    ``fleet.maintenance.heldout_score`` body, now shared.
    """
    from ..backends import cpu_ref
    Y = np.asarray(Y_std, np.float64)
    kf = cpu_ref.kalman_filter(Y, params, mask=W)
    sse, n = one_step_sse(Y, None if W is None else np.asarray(W, np.float64),
                          kf.x_pred, np.asarray(params.Lam, np.float64),
                          holdout_rows, xp=np)
    n = float(n)
    if n == 0:
        return float("nan")
    return float(sse / n)


def heldout_mse_graph(Y, W, x_pred, Lam, holdout_rows: int):
    """In-graph held-out one-step MSE (same reduction as the oracle, in
    the caller's compute dtype): differentiable, vmappable, zero-guarded
    with ``maximum(n, 1)`` instead of host NaN logic."""
    import jax.numpy as jnp
    sse, n = one_step_sse(Y, W, x_pred, Lam, holdout_rows, xp=jnp)
    return sse / jnp.maximum(n.astype(sse.dtype), 1.0)


def forecast_origin_errors(Y: np.ndarray, origins, y_hats, min_train: int,
                           window: str, horizon: int):
    """Per-window forecast errors vs truth plus the naive benchmarks —
    the ``oos_evaluate`` windowing loop, shared.

    Returns ``(errors, naive, meanb)``, each (W, N): model error, last-
    value-benchmark error and train-mean-benchmark error at each origin.
    """
    Y = np.asarray(Y, np.float64)
    N = Y.shape[1]
    errors = np.zeros((len(origins), N))
    naive = np.zeros((len(origins), N))
    meanb = np.zeros((len(origins), N))
    for w, t0 in enumerate(origins):
        lo = max(0, t0 - min_train) if window == "rolling" else 0
        Ytr = Y[lo:t0]
        truth = Y[t0 + horizon - 1]
        errors[w] = truth - y_hats[w]
        naive[w] = truth - Ytr[-1]
        meanb[w] = truth - Ytr.mean(0)
    return errors, naive, meanb

"""EM estimation for DFMs in JAX: jitted E+M step, Python-loop driver.

Mirrors the CPU reference M-step exactly (same closed forms — see
``cpu_ref.em_step``), with the E-step smoother from ``ssm.kalman``.  The
convergence loop stays in Python (one jitted step per iteration) so the driver
can log/checkpoint per iteration; a fully-fused ``lax.scan`` over iterations is
provided for benchmarking where Python overhead would pollute timing.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.linalg import sym, solve_psd
from ..ssm.kalman import kalman_filter, rts_smoother
from ..ssm.params import SSMParams, SmootherResult

__all__ = ["EMConfig", "em_step", "em_fit", "em_fit_scan"]


@dataclasses.dataclass(frozen=True)
class EMConfig:
    """Static EM switches (hashable -> usable as a jit static argument)."""
    estimate_A: bool = True
    estimate_Q: bool = True
    estimate_init: bool = False
    r_floor: float = 1e-6


def _moments(sm: SmootherResult):
    x, P, Pl = sm.x_sm, sm.P_sm, sm.P_lag
    EffT = P + jnp.einsum("ti,tj->tij", x, x)
    cross = Pl[1:] + jnp.einsum("ti,tj->tij", x[1:], x[:-1])
    return EffT, cross


def _m_step(Y, mask, sm: SmootherResult, p: SSMParams, cfg: EMConfig):
    T = Y.shape[0]
    dtype = Y.dtype
    k = p.n_factors
    EffT, cross = _moments(sm)
    S_ff = EffT.sum(0)
    S_ff_lag = EffT[:-1].sum(0)
    S_ff_cur = EffT[1:].sum(0)
    S_cross = cross.sum(0)
    Ef = sm.x_sm

    if mask is None:
        S_yf = Y.T @ Ef                                       # (N, k)
        Lam = solve_psd(S_ff, S_yf.T).T
        R = (jnp.einsum("ti,ti->i", Y, Y)
             - jnp.einsum("ik,ik->i", Lam, S_yf)) / T
    else:
        W = mask.astype(dtype)
        Yz = jnp.where(W > 0, Y, 0.0)
        S_yf_i = jnp.einsum("ti,tk->ik", Yz, Ef)              # (N, k)
        S_ff_i = jnp.einsum("ti,tkl->ikl", W, EffT)           # (N, k, k)
        never = (W.sum(0) == 0)[:, None, None]
        S_ff_i = jnp.where(never, jnp.eye(k, dtype=dtype)[None], S_ff_i)
        Lam = jax.vmap(solve_psd)(S_ff_i, S_yf_i)
        counts = jnp.maximum(W.sum(0), 1.0)
        resid_sq = jnp.einsum("ti,ti->i", W, (Yz - Ef @ Lam.T) ** 2)
        PV = jnp.einsum("ti,tkl->ikl", W, sm.P_sm)
        smear = jnp.einsum("ik,ikl,il->i", Lam, PV, Lam)
        R = (resid_sq + smear) / counts
    R = jnp.maximum(R, cfg.r_floor)

    A, Q = p.A, p.Q
    if cfg.estimate_A:
        A = solve_psd(S_ff_lag, S_cross.T).T
        if cfg.estimate_Q:
            Q = sym((S_ff_cur - A @ S_cross.T) / (T - 1))
    elif cfg.estimate_Q:
        Q = sym((S_ff_cur - A @ S_cross.T - S_cross @ A.T
                 + A @ S_ff_lag @ A.T) / (T - 1))
    mu0, P0 = p.mu0, p.P0
    if cfg.estimate_init:
        mu0 = sm.x_sm[0]
        P0 = sym(sm.P_sm[0])
    return SSMParams(Lam, A, Q, R, mu0, P0)


@partial(jax.jit, static_argnames=("cfg", "has_mask"))
def _em_step_impl(Y, mask, p: SSMParams, cfg: EMConfig, has_mask: bool):
    m = mask if has_mask else None
    kf = kalman_filter(Y, p, mask=m)
    sm = rts_smoother(kf, p)
    p_new = _m_step(Y, m, sm, p, cfg)
    return p_new, kf.loglik


def em_step(Y, p: SSMParams, mask=None, cfg: EMConfig = EMConfig()):
    """One EM iteration.  Returns (new_params, loglik at entry params)."""
    return _em_step_impl(Y, mask, p, cfg, mask is not None)


def em_fit(Y, p0: SSMParams, mask=None, cfg: EMConfig = EMConfig(),
           max_iters: int = 50, tol: float = 1e-6, callback=None):
    """EM driver with relative-loglik convergence.

    Returns (params, loglik history, converged).  ``callback(it, loglik,
    params)`` fires per iteration (logging/checkpoint hook — SURVEY.md
    section 5 observability row).
    """
    p = p0
    lls = []
    converged = False
    for it in range(max_iters):
        p_new, ll = em_step(Y, p, mask=mask, cfg=cfg)
        ll = float(ll)
        lls.append(ll)
        if callback is not None:
            callback(it, ll, p)
        p = p_new
        if it > 0 and (ll - lls[-2]) / max(abs(lls[-2]), 1e-12) < tol:
            converged = True
            break
    return p, jnp.asarray(lls), converged


@partial(jax.jit, static_argnames=("cfg", "has_mask", "n_iters"))
def _em_fit_scan_impl(Y, mask, p0, cfg, has_mask, n_iters):
    m = mask if has_mask else None

    def body(p, _):
        kf = kalman_filter(Y, p, mask=m)
        sm = rts_smoother(kf, p)
        return _m_step(Y, m, sm, p, cfg), kf.loglik

    return jax.lax.scan(body, p0, None, length=n_iters)


def em_fit_scan(Y, p0: SSMParams, n_iters: int, mask=None,
                cfg: EMConfig = EMConfig()):
    """Fixed-iteration EM fused into one XLA program (benchmark path:
    BASELINE.json:2 'EM iters/sec' measured without host round-trips)."""
    return _em_fit_scan_impl(Y, mask, p0, cfg, mask is not None, n_iters)

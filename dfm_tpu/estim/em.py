"""EM estimation for DFMs in JAX: jitted E+M step, Python-loop driver.

Mirrors the CPU reference M-step exactly (same closed forms — see
``cpu_ref.em_step``), with the E-step smoother from ``ssm.kalman``.  The
convergence loop stays in Python (one jitted step per iteration) so the driver
can log/checkpoint per iteration; a fully-fused ``lax.scan`` over iterations is
provided for benchmarking where Python overhead would pollute timing.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..obs.trace import current_tracer, shape_key
from ..ops.linalg import sym, solve_psd
from ..pipeline import resolve_pipeline
from ..ssm.kalman import kalman_filter, rts_smoother
from ..ssm.info_filter import info_filter
from ..ssm.lowrank_filter import lowrank_filter, lowrank_smoother
from ..ssm.parallel_filter import (pit_filter, pit_smoother, pit_qr_filter,
                                   pit_qr_smoother)
from ..ssm.params import SSMParams, SmootherResult

__all__ = ["EMConfig", "em_step", "em_fit", "em_fit_scan", "run_em_loop",
           "run_em_chunked", "em_progress", "noise_floor_for",
           "warn_ss_delta", "moments", "moment_sums", "mstep_rows",
           "mstep_dynamics", "mstep_dynamics_sums"]


@dataclasses.dataclass(frozen=True)
class EMConfig:
    """Static EM switches (hashable -> usable as a jit static argument).

    filter: "dense" (N x N innovation covariance — small-N oracle path),
            "info" (information form, k x k sequential scan — the N-scalable
            TPU path, see ``ssm.info_filter``), "pit" (parallel-in-time
            associative scan for both filter and smoother, see
            ``ssm.parallel_filter``), "pit_qr" (parallel-in-time on
            SQUARE-ROOT factors — combines are thin-QR + triangular solves
            in unrolled VPU form, the long-T engine: ~2*sqrt(T) sequential
            depth at f32 noise at-or-below the sequential scan's),
            "lowrank" (rank-r computation-aware downdate filter/smoother,
            see ``ssm.lowrank_filter`` — the wide-k engine: only r x r
            linalg in the scans, conservative calibrated covariances,
            exact at rank = k; ``rank`` below sets r, <= 0 auto-picks
            min(k, 8)), or "ss" (steady-state accelerated — ~3*tau
            sequential covariance steps + blocked affine mean scans, see
            ``ssm.steady``; falls back to exact when masked/short).

    debug: instrument the jitted EM step with ``jax.experimental.checkify``
           float checks (NaN/inf/div-by-zero on every primitive, threaded
           through the scans), so a poisoned panel or non-PSD parameter
           raises a LOCATED error at the producing op instead of silently
           propagating NaNs (SURVEY.md section 5, sanitizers row).  Orders
           of magnitude slower — a diagnostic mode, never the hot path.
    """
    estimate_A: bool = True
    estimate_Q: bool = True
    estimate_init: bool = False
    r_floor: float = 1e-6
    filter: str = "dense"
    tau: int = 96        # steady-state horizon (filter="ss" only); raise for
                         # very persistent factor dynamics (see ssm.steady)
    debug: bool = False
    noise_floor_mult: float = 100.0   # headroom for the absolute loglik
                                      # noise floor (see noise_floor_for)
    rank: int = 0        # filter="lowrank" only: rank r (<= 0 -> auto,
                         # min(k, 8); see ssm.lowrank_filter.resolve_rank)
    # -- tuned EM hyperparameters (estim.tune / fit(tune=...)) ----------
    # Applied every M-step: Q <- q_scale * Q, R <- max(r_scale * R,
    # r_floor), and lam_ridge adds a ridge to the loading normal
    # equations (solve of S_ff + lam I).  At the defaults the guard in
    # ``_m_step`` short-circuits so the compiled program is byte-
    # identical to pre-tune builds (off-path bit-identity).
    q_scale: float = 1.0
    r_scale: float = 1.0
    lam_ridge: float = 0.0

    def filter_fn(self):
        if self.filter == "lowrank":
            return partial(lowrank_filter, rank=self.rank)
        return {"dense": kalman_filter, "info": info_filter,
                "pit": pit_filter, "pit_qr": pit_qr_filter}[self.filter]

    def smoother_fn(self):
        if self.filter == "lowrank":
            return partial(lowrank_smoother, rank=self.rank)
        return {"pit": pit_smoother,
                "pit_qr": pit_qr_smoother}.get(self.filter, rts_smoother)

    def report_pair(self):
        """Filter/smoother pair for the reporting smooth at the FITTED
        params (the fused drivers' and serving cores' final pass).

        The engines whose smoothed moments ARE their contract route
        through themselves — pit_qr (RTS-equivalent at f32-stable
        square-root combines) and lowrank (the conservative rank-r
        bands the serving layer promotes to outputs).  Everything else
        keeps the historical pairs bit-for-bit: dense keeps the N x N
        oracle filter, and info/ss/pit report through the exact
        info-form scan, matching ``api.smooth()``."""
        if self.filter in ("pit_qr", "lowrank"):
            return self.filter_fn(), self.smoother_fn()
        ff = kalman_filter if self.filter == "dense" else info_filter
        return ff, rts_smoother

    def e_step(self, Y, mask, p, sumsq=None):
        """Filter + smoother under the configured implementation.

        Returns (kf, sm, delta): ``delta`` is the steady-state freeze
        diagnostic (relative covariance error at the freeze point) for
        filter="ss", and exact 0 for the exact filters — surfaced so ss
        users learn when ``tau`` is too small (ADVICE r1 item 1).

        ``sumsq`` (optional, precomputed Y*Y): enables the ss path's
        expanded-form loglik quadratic (see ``ss_filter_smoother``).
        """
        if self.filter == "ss":
            from ..ssm.steady import ss_filter_smoother
            kf, sm, delta = ss_filter_smoother(Y, p, mask=mask, tau=self.tau,
                                               sumsq=sumsq)
            return kf, sm, delta
        kf = self.filter_fn()(Y, p, mask=mask)
        return kf, self.smoother_fn()(kf, p), jnp.zeros((), Y.dtype)


def moments(sm: SmootherResult):
    """Smoothed second moments: (EffT (T,k,k), cross (T-1,k,k)).

    Compute ONCE per M-step and thread into ``mstep_rows`` /
    ``mstep_dynamics`` — the (T,k,k) einsums are not free at scale.
    Needed only on the MASKED path (per-series S_ff_i sums); the unmasked
    M-step uses ``moment_sums``, which never materializes them.
    """
    x, P, Pl = sm.x_sm, sm.P_sm, sm.P_lag
    EffT = P + jnp.einsum("ti,tj->tij", x, x)
    cross = Pl[1:] + jnp.einsum("ti,tj->tij", x[1:], x[:-1])
    return EffT, cross


def moment_sums(sm: SmootherResult):
    """Unmasked M-step moment sums in matmul form.

    Returns (S_ff, S_ff_lag, S_ff_cur, S_cross): the summed-over-t second
    moments the closed-form updates need, computed as (k,T)x(T,k) matmuls +
    (T,k,k) reductions — no per-t outer-product temporaries, fewer/larger
    ops than summing ``moments`` (measured on the headline shape as part of
    the per-iteration sequential-tail cost, docs/PERF.md roofline table).
    """
    x, P, Pl = sm.x_sm, sm.P_sm, sm.P_lag
    S_ff = P.sum(0) + x.T @ x
    last = P[-1] + jnp.outer(x[-1], x[-1])
    first = P[0] + jnp.outer(x[0], x[0])
    S_cross = Pl[1:].sum(0) + x[1:].T @ x[:-1]
    return S_ff, S_ff - last, S_ff - first, S_cross


def mstep_rows(Y, mask, Ef, EffT, P_sm, S_ff, r_floor: float, Ysq=None,
               lam_ridge=None):
    """Per-series M-step rows: new (Lam (n, k), R (n,)) for a series block.

    ``Y`` is (T, n) — the full panel or one device's shard.  Each series' row
    of Lam/R depends only on that series' own column of Y plus the replicated
    smoother moments, so under sharding this runs locally with NO collective
    (the psum lives in the E-step; SURVEY.md section 3.1 device boundary).

    ``Ysq``: optional precomputed per-series sum of squares (unmasked path).
    It is EM-iteration-invariant, so fused-scan drivers hoist the panel pass
    out of the iteration loop and thread it in.

    ``lam_ridge`` (optional, scalar — static float or traced): ridge on the
    loading normal equations, solving (S_ff + lam I) instead of S_ff.  The
    unmasked R then uses the full quadratic (the ``Ysq - Lam.S_yf`` shortcut
    is exact only at the OLS solution); ``None`` keeps the historical program
    byte-identical.
    """
    T = Y.shape[0]
    dtype = Y.dtype
    if mask is None:
        S_yf = Y.T @ Ef                                       # (n, k)
        if Ysq is None:
            Ysq = jnp.einsum("ti,ti->i", Y, Y)
        if lam_ridge is None:
            Lam = solve_psd(S_ff, S_yf.T).T
            R = (Ysq - jnp.einsum("ik,ik->i", Lam, S_yf)) / T
        else:
            k = S_ff.shape[0]
            Lam = solve_psd(S_ff + lam_ridge * jnp.eye(k, dtype=dtype),
                            S_yf.T).T
            R = (Ysq - 2.0 * jnp.einsum("ik,ik->i", Lam, S_yf)
                 + jnp.einsum("ik,kl,il->i", Lam, S_ff, Lam)) / T
    else:
        k = S_ff.shape[0]
        W = mask.astype(dtype)
        Yz = jnp.where(W > 0, jnp.nan_to_num(Y), 0.0)
        S_yf_i = jnp.einsum("ti,tk->ik", Yz, Ef)              # (n, k)
        S_ff_i = jnp.einsum("ti,tkl->ikl", W, EffT)           # (n, k, k)
        never = (W.sum(0) == 0)[:, None, None]
        S_ff_i = jnp.where(never, jnp.eye(k, dtype=dtype)[None], S_ff_i)
        if lam_ridge is not None:
            S_ff_i = S_ff_i + lam_ridge * jnp.eye(k, dtype=dtype)[None]
        Lam = jax.vmap(solve_psd)(S_ff_i, S_yf_i)
        counts = jnp.maximum(W.sum(0), 1.0)
        resid_sq = jnp.einsum("ti,ti->i", W, (Yz - Ef @ Lam.T) ** 2)
        PV = jnp.einsum("ti,tkl->ikl", W, P_sm)
        smear = jnp.einsum("ik,ikl,il->i", Lam, PV, Lam)
        R = (resid_sq + smear) / counts
    return Lam, jnp.maximum(R, r_floor)


def mstep_dynamics_sums(sm: SmootherResult, S_ff_lag, S_ff_cur, S_cross,
                        p: SSMParams, cfg: EMConfig, n_steps=None):
    """Replicated k x k M-step updates (A, Q, mu0, P0) from SUMMED moments.

    ``n_steps`` (optional, traced): effective panel length when ``Y`` is
    capacity-padded past the live data (serve sessions) — the transition
    count divisor becomes ``n_steps - 1`` instead of the static ``T - 1``.
    """
    T = sm.x_sm.shape[0] if n_steps is None else n_steps
    A, Q = p.A, p.Q
    if cfg.estimate_A:
        A = solve_psd(S_ff_lag, S_cross.T).T
        if cfg.estimate_Q:
            Q = sym((S_ff_cur - A @ S_cross.T) / (T - 1))
    elif cfg.estimate_Q:
        Q = sym((S_ff_cur - A @ S_cross.T - S_cross @ A.T
                 + A @ S_ff_lag @ A.T) / (T - 1))
    mu0, P0 = p.mu0, p.P0
    if cfg.estimate_init:
        mu0 = sm.x_sm[0]
        P0 = sym(sm.P_sm[0])
    return A, Q, mu0, P0


def mstep_dynamics(sm: SmootherResult, EffT, cross, p: SSMParams,
                   cfg: EMConfig):
    """Replicated k x k M-step updates (A, Q, mu0, P0) from smoother moments."""
    return mstep_dynamics_sums(sm, EffT[:-1].sum(0), EffT[1:].sum(0),
                               cross.sum(0), p, cfg)


def mstep_dynamics_tmasked(sm: SmootherResult, EffT, cross, p: SSMParams,
                           cfg: EMConfig, n_steps):
    """``mstep_dynamics`` for a capacity-padded panel: only the first
    ``n_steps`` (traced) time steps are live data; the trailing pad rows are
    zero-masked in the observation model, so their smoother moments must be
    excluded from the transition sums.  The sums become {0,1}-weighted
    reductions (weights exact, so pad entries contribute exact zeros) with
    a traced ``n_steps - 1`` transition-count divisor — ONE executable then
    serves every live length a session can reach."""
    Tc = EffT.shape[0]
    dt = EffT.dtype
    t_idx = jnp.arange(Tc)
    w_lag = (t_idx < n_steps - 1).astype(dt)
    w_cur = ((t_idx >= 1) & (t_idx < n_steps)).astype(dt)
    w_x = (jnp.arange(Tc - 1) < n_steps - 1).astype(dt)
    S_lag = jnp.einsum("t,tkl->kl", w_lag, EffT)
    S_cur = jnp.einsum("t,tkl->kl", w_cur, EffT)
    S_cross = jnp.einsum("t,tkl->kl", w_x, cross)
    return mstep_dynamics_sums(sm, S_lag, S_cur, S_cross, p, cfg,
                               n_steps=n_steps)


def cfg_hypers(cfg: EMConfig):
    """Static (q_scale, r_scale, lam_ridge) from ``cfg``, or ``None`` at
    the defaults — the ``None`` short-circuit is what keeps untuned
    programs byte-identical to pre-tune builds."""
    if cfg.q_scale != 1.0 or cfg.r_scale != 1.0 or cfg.lam_ridge != 0.0:
        return (cfg.q_scale, cfg.r_scale, cfg.lam_ridge)
    return None


def _m_step(Y, mask, sm: SmootherResult, p: SSMParams, cfg: EMConfig,
            Ysq=None, n_steps=None, hypers=None):
    """Closed-form M-step.  ``hypers`` (optional (q_scale, r_scale,
    lam_ridge), traced or static) overrides the cfg's static hyper
    fields — the seam ``estim.tune`` differentiates through; the tuned
    ``fit()`` path reaches the same code through ``cfg_hypers``."""
    hy = cfg_hypers(cfg) if hypers is None else hypers
    ridge = None if hy is None else hy[2]
    if mask is None:
        if n_steps is not None:
            raise ValueError("n_steps (capacity-padded panels) requires a "
                             "mask: the pad tail must be zero-masked")
        S_ff, S_lag, S_cur, S_cross = moment_sums(sm)
        Lam, R = mstep_rows(Y, None, sm.x_sm, None, None, S_ff, cfg.r_floor,
                            Ysq=Ysq, lam_ridge=ridge)
        A, Q, mu0, P0 = mstep_dynamics_sums(sm, S_lag, S_cur, S_cross, p, cfg)
    else:
        EffT, cross = moments(sm)
        S_ff = EffT.sum(0)
        Lam, R = mstep_rows(Y, mask, sm.x_sm, EffT, sm.P_sm, S_ff,
                            cfg.r_floor, lam_ridge=ridge)
        if n_steps is None:
            A, Q, mu0, P0 = mstep_dynamics(sm, EffT, cross, p, cfg)
        else:
            A, Q, mu0, P0 = mstep_dynamics_tmasked(sm, EffT, cross, p, cfg,
                                                   n_steps)
    if hy is not None:
        Q = hy[0] * Q
        R = jnp.maximum(hy[1] * R, cfg.r_floor)
    return SSMParams(Lam, A, Q, R, mu0, P0)


def _panel_consts(Y, has_mask: bool, cfg: EMConfig):
    """EM-iteration-invariant panel reductions (hoisted by the fused scans).

    Returns (sumsq (T,N) | None, Ysq (N,) | None): ``sumsq`` feeds the ss
    path's expanded loglik quadratic, ``Ysq`` the unmasked M-step rows.
    """
    if has_mask:
        return None, None
    if cfg.filter == "ss":
        sumsq = Y * Y
        return sumsq, jnp.sum(sumsq, axis=0)
    return None, jnp.einsum("ti,ti->i", Y, Y)


@partial(jax.jit, static_argnames=("cfg", "has_mask"))
def _em_step_impl(Y, mask, p: SSMParams, cfg: EMConfig, has_mask: bool):
    m = mask if has_mask else None
    sumsq, Ysq = _panel_consts(Y, has_mask, cfg)
    kf, sm, delta = cfg.e_step(Y, m, p, sumsq=sumsq)
    p_new = _m_step(Y, m, sm, p, cfg, Ysq=Ysq)
    return p_new, kf.loglik, delta


@partial(jax.jit, static_argnames=("cfg", "has_mask"))
def _em_step_checked_impl(Y, mask, p: SSMParams, cfg: EMConfig,
                          has_mask: bool):
    """Debug-mode EM step: every float op checkified (see EMConfig.debug)."""
    from jax.experimental import checkify

    def f(Y, mask, p):
        m = mask if has_mask else None
        sumsq, Ysq = _panel_consts(Y, has_mask, cfg)
        kf, sm, delta = cfg.e_step(Y, m, p, sumsq=sumsq)
        return _m_step(Y, m, sm, p, cfg, Ysq=Ysq), kf.loglik, delta

    return checkify.checkify(f, errors=checkify.float_checks)(Y, mask, p)


def em_step(Y, p: SSMParams, mask=None, cfg: EMConfig = EMConfig()):
    """One EM iteration.

    Returns (new_params, loglik at entry params, ss_delta) — ss_delta is the
    steady-state freeze diagnostic (0 for exact filters; see EMConfig.e_step).
    With ``cfg.debug`` the step raises a located error on the first NaN/inf
    any primitive produces (instead of returning NaN silently).
    """
    if cfg.debug:
        err, out = _em_step_checked_impl(Y, mask, p, cfg, mask is not None)
        err.throw()
        return out
    tr = current_tracer()
    if tr is None:
        return _em_step_impl(Y, mask, p, cfg, mask is not None)
    with tr.dispatch("em_step", shape_key(Y, cfg.filter)):
        return _em_step_impl(Y, mask, p, cfg, mask is not None)


def em_progress(lls, tol: float, noise_floor: float = 0.0,
                monotone: bool = True) -> str:
    """Classify the last loglik step: 'continue' | 'converged' | 'diverged'.

    |relative change| < tol -> converged.  A DROP is impossible for exact
    EM; a drop within ``noise_floor`` (an ABSOLUTE loglik tolerance — see
    ``noise_floor_for``) means the fit has hit numerical convergence,
    while a larger drop is real trouble.

    tol <= 0 means "run the full budget" (benchmarks, fixed-iteration
    studies): noise-floor drops then do NOT stop the fit either — only a
    genuine divergence does.

    monotone=False is the tuned-update rule (``estim.tune``): scaling
    Q/R after the M-step makes the iteration a contraction toward a
    fixed point that is NOT a likelihood stationary point, so the loglik
    legitimately dips once the iterates cross their likelihood plateau.
    A drop then classifies as 'converged' (stop at the plateau) instead
    of 'diverged' — drivers pass ``monotone = (cfg_hypers(cfg) is
    None)`` so exact-EM fits keep the sharp divergence alarm.
    """
    if len(lls) < 2:
        return "continue"
    rel = (lls[-1] - lls[-2]) / max(abs(lls[-2]), 1e-12)
    if tol > 0 and abs(rel) < tol:
        return "converged"
    drop = lls[-2] - lls[-1]
    if drop > noise_floor and monotone:
        return "diverged"
    if drop > 0 and tol > 0:
        return "converged"      # noise-floor drop at a plateau
    return "continue"


def noise_floor_for(dtype, n_obs: float = 1.0, mult: float = 100.0) -> float:
    """ABSOLUTE loglik noise floor for a compute dtype.

    The computed loglik is assembled from pieces of magnitude O(n_obs)
    (n log 2pi + log|R| + the innovation quadratic each scale with the
    number of observed values), so its evaluation noise is ~eps * n_obs
    REGARDLESS of the loglik's own magnitude — a well-fit panel can have a
    loglik near zero while the pieces are 1e7, making any relative-to-
    loglik floor arbitrarily wrong (measured: an f32 10k x 500 fit shows
    absolute wobble ~1 on a loglik of ~1e4).  Pass ``n_obs = number of
    observed scalars`` (T*N for a dense panel).

    ``mult`` is the headroom over the eps*n_obs scale.  The default 100x
    covers the tree-reduction constant conservatively, which at large f32
    panels (~60 absolute units at 10k x 500) can also mask a GENUINE small
    divergence as converged (ADVICE r4 item 2) — drivers expose it via
    ``EMConfig.noise_floor_mult`` so studies that need a sharp divergence
    alarm can tighten it (e.g. 10x) at the cost of false alarms near the
    measured ~1-unit wobble.
    """
    return mult * float(jnp.finfo(jnp.dtype(dtype)).eps) * max(n_obs, 1.0)


def run_em_loop(step, max_iters: int, tol: float, callback=None,
                noise_floor: float = 0.0, monotone: bool = True):
    """Shared EM convergence loop (used by single-device AND sharded drivers).

    ``step(it) -> (loglik, params_for_callback)`` advances one iteration;
    the loglik is at the ENTERING params, matching ``callback(it, ll, p)``.
    See ``em_progress`` for the stopping rule.

    Returns (lls, converged, state) with state in {"converged", "diverged",
    "maxiter"} — drivers use "diverged" to hand back the entering params of
    the failing iteration instead of the post-divergence update
    (ADVICE r1 item 5).
    """
    lls = []
    state = "maxiter"
    for it in range(max_iters):
        ll, cb_params = step(it)
        ll = float(ll)
        lls.append(ll)
        if callback is not None:
            callback(it, ll, cb_params)
        progress = em_progress(lls, tol, noise_floor, monotone=monotone)
        if progress != "continue":
            state = progress
            break
    return lls, state == "converged", state


def run_em_chunked(scan_fn, p0, max_iters: int, tol: float,
                   noise_floor: float, callback=None, fused_chunk: int = 8,
                   ss_tau=None, monitor=None, progress=None, pipeline=None,
                   monotone: bool = True):
    """Shared fused-chunk EM driver (single-device, sharded, and MF fits).

    ``scan_fn(p, n) -> (p_new, logliks (n,), ss_deltas (n,) | None)`` runs n
    fused EM iterations in one XLA program.  A scan_fn may append a 4th
    element — a (n, 3) per-iteration metrics array [loglik, in-chunk delta,
    max param-update norm] (see ``em_fit_scan(with_metrics=True)``) —
    surfaced in the chunk trace events and the ``progress`` hook.
    Convergence/divergence can only
    be detected once a chunk's logliks reach the host, by which point the
    device params embody the WHOLE chunk; a mid-chunk stop therefore replays
    the chunk's prefix from the stored chunk-entry params (one shorter fused
    program, compiled once per distinct tail length) so the returned params
    embody precisely the update count the stopping rule selected — including
    the divergence rule's "params entering the pre-drop iteration".

    Callbacks receive chunk-entry params; a callback carrying
    ``wants_params_iter = True`` is additionally passed ``params_iter`` (the
    iteration those params embody) so checkpoints are never mislabeled.

    ``progress``: live per-chunk hook — ``progress(info)`` fires once per
    dispatched chunk with a dict {chunk, iter, total, loglik, delta,
    dparam, elapsed_s, eta_s, metrics, stopped, converged}; ``eta_s`` is
    the amortized-wall estimate ``elapsed / iters_done * iters_left``
    (first chunk includes compile — the estimate improves as chunks
    amortize it).  Fires AFTER the stopping rule so ``stopped`` is final.

    ``ss_tau``: when set, ss freeze deltas (up to the stop) feed
    ``warn_ss_delta`` with this tau.  Returns (p, lls, converged, p_iters).

    ``monitor``: a ``robust.ChunkMonitor`` switches to the health-monitored
    twin of this loop (same contract; adds between-chunk recovery and
    escalation — see ``robust.guard``).  None keeps the legacy loop below.

    ``pipeline``: a ``pipeline.PipelineConfig`` (or int depth / None) —
    ``depth > 1`` issues that many chunks speculatively before the one
    blocking loglik transfer per round (latency hiding; bit-identical
    results), ``bucket=True`` dispatches every chunk through the
    scan_fn's ``bucket_call`` so one fused-length executable serves all
    tail/replay lengths.  The default is exactly the serial loop below.
    """
    if monitor is not None:
        from ..robust.guard import guarded_run_em_chunked
        return guarded_run_em_chunked(
            scan_fn, p0, max_iters, tol, noise_floor, callback=callback,
            fused_chunk=fused_chunk, ss_tau=ss_tau, monitor=monitor,
            progress=progress, pipeline=pipeline, monotone=monotone)
    import time
    import numpy as np
    fused_chunk = max(1, int(fused_chunk))   # 0/negative would never advance
    pipe = resolve_pipeline(pipeline)
    if pipe.active:
        return _run_em_chunked_pipelined(
            scan_fn, p0, max_iters, tol, noise_floor, callback=callback,
            fused_chunk=fused_chunk, ss_tau=ss_tau, progress=progress,
            pipe=pipe, monotone=monotone)
    pass_piter = getattr(callback, "wants_params_iter", False)
    tr = current_tracer()
    prog = getattr(scan_fn, "trace_name", "em_chunk")
    prog_key = getattr(scan_fn, "trace_key", "")
    engine = getattr(scan_fn, "trace_engine", prog)
    lls: list = []
    converged = False
    stop = False
    target = 0      # update count the stopping rule selects (from start)
    max_delta = 0.0
    p = p0
    it = 0
    n_chunks = 0
    t0 = time.perf_counter()
    p_entry = p_entry_prev = p0
    entry_it = entry_it_prev = 0
    while it < max_iters and not stop:
        n = min(fused_chunk, max_iters - it)
        p_entry_prev, entry_it_prev = p_entry, entry_it
        p_entry, entry_it = p, it
        if tr is None:
            out = scan_fn(p, n)
            p, chunk = out[0], np.asarray(out[1], np.float64)
            deltas = out[2]
            metrics = (np.asarray(out[3], np.float64)
                       if len(out) > 3 and out[3] is not None else None)
        else:
            # The np.asarray transfer is the execution barrier (CLAUDE.md:
            # block_until_ready is a no-op on axon), so the span wall time
            # is true chunk execution + tunnel latency.  A distinct fused
            # length n is a distinct XLA program -> part of the shape key.
            with tr.dispatch(prog, shape_key(prog_key, f"iters{n}"),
                             barrier=True, n_iters=n):
                out = scan_fn(p, n)
                p, chunk = out[0], np.asarray(out[1], np.float64)
                deltas = out[2]
                metrics = (np.asarray(out[3], np.float64)
                           if len(out) > 3 and out[3] is not None else None)
            drops = np.diff(chunk)
            extra = ({"dparams": [float(x) for x in metrics[:, 2]]}
                     if metrics is not None else {})
            tr.emit("chunk", engine=engine, iter0=it, n=int(n),
                    lls=[float(x) for x in chunk],
                    noise_floor=float(noise_floor),
                    max_drop=float(-drops.min()) if drops.size else 0.0,
                    below_floor=bool(drops.size == 0
                                     or np.abs(drops).max() < noise_floor),
                    **extra)
        consumed = n
        for j, ll in enumerate(chunk):
            lls.append(float(ll))
            if callback is not None:
                if pass_piter:
                    callback(it + j, float(ll), p_entry,
                             params_iter=entry_it)
                else:
                    callback(it + j, float(ll), p_entry)
            state = em_progress(lls, tol, noise_floor, monotone=monotone)
            if state != "continue":
                converged = state == "converged"
                # Same update counts the run_em_loop drivers return:
                # converged -> every iteration that ran; diverged -> the
                # params entering the pre-drop iteration.
                target = len(lls) if converged else max(len(lls) - 2, 0)
                stop = True
                consumed = j + 1
                break
        if deltas is not None:
            # Only iterations up to the stop count toward the freeze
            # warning — post-stop iterations of the chunk ran on the device
            # but are discarded (after a divergence their deltas reflect
            # garbage params).
            max_delta = max(max_delta,
                            float(np.max(np.asarray(deltas)[:consumed])))
        if progress is not None:
            iters_done = entry_it + consumed
            elapsed = time.perf_counter() - t0
            left = 0 if stop else max_iters - (it + n)
            progress({"chunk": n_chunks, "iter": int(iters_done),
                      "total": int(max_iters), "loglik": lls[-1],
                      "delta": (lls[-1] - lls[-2]) if len(lls) > 1
                      else None,
                      "dparam": (float(metrics[consumed - 1, 2])
                                 if metrics is not None and consumed
                                 else None),
                      "elapsed_s": elapsed,
                      "eta_s": ((elapsed / iters_done) * left
                                if iters_done else None),
                      "metrics": metrics, "stopped": bool(stop),
                      "converged": bool(converged)})
        n_chunks += 1
        it += n
    if ss_tau is not None:
        warn_ss_delta(max_delta, ss_tau)
    p_iters = it
    if stop and target != it:
        # A diverged target can precede the current chunk's entry (drop at
        # the chunk's first loglik blames the previous chunk's last update)
        # — replay from whichever stored entry covers it.
        base, base_it = ((p_entry, entry_it) if target >= entry_it
                         else (p_entry_prev, entry_it_prev))
        n_replay = target - base_it
        if n_replay == 0:
            p = base
        elif tr is None:
            p = scan_fn(base, n_replay)[0]
        else:
            with tr.dispatch(prog, shape_key(prog_key, f"iters{n_replay}"),
                             n_iters=n_replay, replay=True):
                p = scan_fn(base, n_replay)[0]
        p_iters = target
    # (a stop with target == it needs nothing: the chunk end already
    # embodies exactly `target` updates and p_iters == it == target)
    return p, np.asarray(lls), converged, p_iters


class _ChunkCall:
    """Resolves one chunk dispatch against the current scan_fn.

    With bucketing on and a scan_fn carrying ``bucket_call(p, n_active,
    n_bucket)`` (the api-layer closures do), every chunk — full, tail,
    or replay — dispatches the ONE fused-length executable with a
    dynamic active-iteration cap; scan_fns without the attribute
    (escalated rebuilds, wrapped test seams) degrade to per-length
    programs.  The bucketed shape key gains a ``b`` suffix so the
    RecompileDetector sees one bucket-aware key instead of tail churn.
    """

    def __init__(self, bucket: bool, n_bucket: int):
        self.bucket = bool(bucket)
        self.n_bucket = int(n_bucket)

    def bucketed(self, scan_fn) -> bool:
        return (self.bucket
                and getattr(scan_fn, "bucket_call", None) is not None)

    def run(self, scan_fn, p, n):
        if self.bucketed(scan_fn):
            return scan_fn.bucket_call(p, n, self.n_bucket)
        return scan_fn(p, n)

    def key(self, scan_fn, prog_key, n) -> str:
        if self.bucketed(scan_fn):
            return shape_key(prog_key, f"iters{self.n_bucket}b")
        return shape_key(prog_key, f"iters{n}")

    def payload(self, scan_fn) -> dict:
        return ({"bucket": self.n_bucket} if self.bucketed(scan_fn)
                else {})


def _run_em_chunked_pipelined(scan_fn, p0, max_iters: int, tol: float,
                              noise_floor: float, callback=None,
                              fused_chunk: int = 8, ss_tau=None,
                              progress=None, pipe=None,
                              monotone: bool = True):
    """Latency-hiding twin of the serial ``run_em_chunked`` loop.

    Issues up to ``pipe.depth`` chunks back-to-back, each chained from
    the previous chunk's still-on-device output params — the values
    computed do not depend on when the host reads them, so results are
    bit-identical to serial — then performs ONE blocking device->host
    transfer per round: the newest chunk's logliks (the only read that
    waits on device compute; the older chunks are finished by then and
    their fetches just move bytes).  Host-side convergence checks run up
    to depth-1 chunks late; a stop mid-round discards the younger
    speculative chunks and lands on exactly the serial stopping rule's
    update count via the shared chunk-entry replay.
    """
    import time
    import numpy as np
    pass_piter = getattr(callback, "wants_params_iter", False)
    tr = current_tracer()
    prog = getattr(scan_fn, "trace_name", "em_chunk")
    prog_key = getattr(scan_fn, "trace_key", "")
    engine = getattr(scan_fn, "trace_engine", prog)
    cc = _ChunkCall(pipe.bucket, fused_chunk)
    lls: list = []
    converged = False
    stop = False
    target = 0
    max_delta = 0.0
    p = p0
    it = 0
    n_chunks = 0
    t0 = time.perf_counter()
    p_entry = p_entry_prev = p0
    entry_it = entry_it_prev = 0
    while it < max_iters and not stop:
        # -- issue: enqueue up to depth chunks, chaining device params.
        # No host read happens here, so the spans record async-enqueue
        # overhead only (non-barrier) plus how deep the device queue was
        # when each program was issued.
        flights = []
        while len(flights) < pipe.depth and it < max_iters:
            n = min(fused_chunk, max_iters - it)
            if tr is None:
                out = cc.run(scan_fn, p, n)
            else:
                with tr.dispatch(prog, cc.key(scan_fn, prog_key, n),
                                 n_iters=n, queue_depth=len(flights) + 1,
                                 **cc.payload(scan_fn)):
                    out = cc.run(scan_fn, p, n)
            flights.append([p, it, n, out, None, None, None])
            p = out[0]
            it += n
        # -- drain: one blocking transfer per round, newest chunk first.
        for idx in range(len(flights) - 1, -1, -1):
            fl = flights[idx]
            out, n = fl[3], fl[2]
            blocking = idx == len(flights) - 1
            tt = time.perf_counter()
            chunk = np.asarray(out[1], np.float64)[:n]
            deltas = (np.asarray(out[2], np.float64)[:n]
                      if out[2] is not None else None)
            metrics = (np.asarray(out[3], np.float64)[:n]
                       if len(out) > 3 and out[3] is not None else None)
            if tr is not None:
                tr.emit("transfer", t=tt, dur=time.perf_counter() - tt,
                        program=prog, direction="d2h",
                        blocking=bool(blocking), n_iters=int(n))
            fl[4], fl[5], fl[6] = chunk, deltas, metrics
        # -- process: the serial loop's host-side checks, oldest first.
        for f_entry, f_it, n, out, chunk, deltas, metrics in flights:
            if stop:
                break       # younger speculative chunks are discarded
            p_entry_prev, entry_it_prev = p_entry, entry_it
            p_entry, entry_it = f_entry, f_it
            if tr is not None:
                drops = np.diff(chunk)
                extra = ({"dparams": [float(x) for x in metrics[:, 2]]}
                         if metrics is not None else {})
                tr.emit("chunk", engine=engine, iter0=f_it, n=int(n),
                        lls=[float(x) for x in chunk],
                        noise_floor=float(noise_floor),
                        max_drop=float(-drops.min()) if drops.size else 0.0,
                        below_floor=bool(drops.size == 0
                                         or np.abs(drops).max()
                                         < noise_floor),
                        **extra)
            consumed = n
            for j, ll in enumerate(chunk):
                lls.append(float(ll))
                if callback is not None:
                    if pass_piter:
                        callback(f_it + j, float(ll), p_entry,
                                 params_iter=entry_it)
                    else:
                        callback(f_it + j, float(ll), p_entry)
                state = em_progress(lls, tol, noise_floor,
                                    monotone=monotone)
                if state != "continue":
                    converged = state == "converged"
                    target = (len(lls) if converged
                              else max(len(lls) - 2, 0))
                    stop = True
                    consumed = j + 1
                    break
            if deltas is not None and consumed:
                max_delta = max(max_delta,
                                float(np.max(deltas[:consumed])))
            if progress is not None:
                iters_done = entry_it + consumed
                elapsed = time.perf_counter() - t0
                left = 0 if stop else max_iters - (f_it + n)
                progress({"chunk": n_chunks, "iter": int(iters_done),
                          "total": int(max_iters), "loglik": lls[-1],
                          "delta": (lls[-1] - lls[-2]) if len(lls) > 1
                          else None,
                          "dparam": (float(metrics[consumed - 1, 2])
                                     if metrics is not None and consumed
                                     else None),
                          "elapsed_s": elapsed,
                          "eta_s": ((elapsed / iters_done) * left
                                    if iters_done else None),
                          "metrics": metrics, "stopped": bool(stop),
                          "converged": bool(converged)})
            n_chunks += 1
            if stop:
                # Land on the stopped chunk's state: the younger flights
                # (and their iterations) never happened.
                p = out[0]
                it = f_it + n
    if ss_tau is not None:
        warn_ss_delta(max_delta, ss_tau)
    p_iters = it
    if stop and target != it:
        base, base_it = ((p_entry, entry_it) if target >= entry_it
                         else (p_entry_prev, entry_it_prev))
        n_replay = target - base_it
        if n_replay == 0:
            p = base
        elif tr is None:
            p = cc.run(scan_fn, base, n_replay)[0]
        else:
            with tr.dispatch(prog, cc.key(scan_fn, prog_key, n_replay),
                             n_iters=n_replay, replay=True,
                             **cc.payload(scan_fn)):
                p = cc.run(scan_fn, base, n_replay)[0]
        p_iters = target
    return p, np.asarray(lls), converged, p_iters


def warn_ss_delta(max_delta: float, tau: int, threshold: float = 1e-4):
    """Warn when the steady-state freeze error is large enough to bias EM
    (the delta ss_filter_smoother reports; see ssm.steady)."""
    if max_delta > threshold:
        import warnings
        warnings.warn(
            f"steady-state filter freeze error {max_delta:.2e} exceeds "
            f"{threshold:.0e} at tau={tau}; EM moments may be biased — "
            "raise EMConfig.tau or use filter='info'", RuntimeWarning,
            stacklevel=3)


def em_fit(Y, p0: SSMParams, mask=None, cfg: EMConfig = EMConfig(),
           max_iters: int = 50, tol: float = 1e-6, callback=None):
    """EM driver with relative-loglik convergence.

    Returns (params, loglik history, converged, params_iters).
    ``params_iters`` counts the EM updates the returned params embody (==
    len(history) except after a divergence).  ``callback(it, loglik,
    params)`` fires per iteration with the params the loglik was evaluated
    at (logging/checkpoint hook — SURVEY.md section 5 observability row).
    A drop at iteration j means the update in iteration j-1 produced bad
    params, so on divergence the params ENTERING iteration j-1 (whose
    loglik is the last pre-drop value) are returned.
    """
    p = p0
    entering = prev_entering = p0
    max_delta = 0.0

    def step(it):
        nonlocal p, entering, prev_entering, max_delta
        prev_entering = entering
        entering = p
        p, ll, delta = em_step(Y, entering, mask=mask, cfg=cfg)
        if cfg.filter == "ss":
            max_delta = max(max_delta, float(delta))
        return ll, entering

    lls, converged, state = run_em_loop(
        step, max_iters, tol, callback,
        noise_floor=noise_floor_for(Y.dtype, Y.size,
                                    mult=cfg.noise_floor_mult),
        monotone=cfg_hypers(cfg) is None)
    if cfg.filter == "ss":
        warn_ss_delta(max_delta, cfg.tau)
    p_iters = len(lls)
    if state == "diverged":
        p = prev_entering
        p_iters = max(len(lls) - 2, 0)
    return p, jnp.asarray(lls), converged, p_iters


def _em_scan_core(Y, mask, p0, cfg, has_mask, n_iters):
    m = mask if has_mask else None
    # Iteration-invariant panel passes hoisted out of the fused loop.
    sumsq, Ysq = _panel_consts(Y, has_mask, cfg)

    def body(p, _):
        kf, sm, delta = cfg.e_step(Y, m, p, sumsq=sumsq)
        return _m_step(Y, m, sm, p, cfg, Ysq=Ysq), (kf.loglik, delta)

    p, (lls, deltas) = jax.lax.scan(body, p0, None, length=n_iters)
    return p, lls, deltas, sumsq


def max_abs_update(p_new, p):
    """max over all param leaves of max|p_new - p| (the in-loop
    param-update norm of the per-iteration metrics row)."""
    leaves = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda a, b: jnp.max(jnp.abs(a - b)),
                               p_new, p))
    return jnp.max(jnp.stack(leaves))


def _em_scan_core_metrics(Y, mask, p0, cfg, has_mask, n_iters):
    """Metrics twin of ``_em_scan_core``: the scan carry additionally
    threads the previous loglik so each fused iteration emits a metrics
    row [loglik, in-chunk delta, max param-update norm] — iteration-
    granularity convergence data at ZERO extra dispatches.  A separate
    function (not a flag on the default body) so the metrics-off path is
    the byte-identical PR 3 program with an unchanged jit cache."""
    m = mask if has_mask else None
    sumsq, Ysq = _panel_consts(Y, has_mask, cfg)

    def body(carry, _):
        p, ll_prev = carry
        kf, sm, delta = cfg.e_step(Y, m, p, sumsq=sumsq)
        p_new = _m_step(Y, m, sm, p, cfg, Ysq=Ysq)
        ll = jnp.asarray(kf.loglik, jnp.float64)
        row = jnp.stack([ll, ll - ll_prev,
                         jnp.asarray(max_abs_update(p_new, p),
                                     jnp.float64)])
        return (p_new, ll), (kf.loglik, delta, row)

    # NaN seed: the first iteration of a chunk has no in-device
    # predecessor loglik (the chunk driver knows the cross-chunk delta).
    ll0 = jnp.asarray(jnp.nan, jnp.float64)
    (p, _), (lls, deltas, metrics) = jax.lax.scan(
        body, (p0, ll0), None, length=n_iters)
    return p, lls, deltas, metrics


def _em_chunk_body(Y, m, cfg, sumsq, Ysq, n_active, n_steps=None):
    """Shared live-capped EM chunk body: one (E-step, M-step) per scanned
    index ``j``, holding the param carry via where-selects once
    ``j >= n_active`` (the batched engine's convergence-freeze idiom).
    Used by both the bucketed chunk scan (`_em_scan_core_active`) and the
    fused while-loop driver (`estim.fused`).  ``n_steps`` (traced,
    optional): live time-step count for capacity-padded panels — threads
    into the t-masked M-step dynamics (serve sessions)."""

    def body(p, j):
        kf, sm, delta = cfg.e_step(Y, m, p, sumsq=sumsq)
        p_new = _m_step(Y, m, sm, p, cfg, Ysq=Ysq, n_steps=n_steps)
        live = j < n_active
        p_out = jax.tree_util.tree_map(
            lambda a, b: jnp.where(live, a, b), p_new, p)
        return p_out, (kf.loglik, delta)

    return body


def _em_scan_core_active(Y, mask, p0, n_active, cfg, has_mask, n_bucket):
    """Bucketed twin of ``_em_scan_core``: a STATIC ``n_bucket`` fused
    length with a DYNAMIC (traced) ``n_active`` cap.  Iterations at index
    >= n_active hold the param carry via where-selects (the batched
    engine's convergence-freeze idiom), so ONE executable serves every
    tail-chunk and replay length a fit can produce; the driver slices the
    scanned outputs down to the active prefix host-side."""
    m = mask if has_mask else None
    sumsq, Ysq = _panel_consts(Y, has_mask, cfg)
    body = _em_chunk_body(Y, m, cfg, sumsq, Ysq, n_active)
    p, (lls, deltas) = jax.lax.scan(body, p0, jnp.arange(n_bucket))
    return p, lls, deltas


def _em_scan_core_active_metrics(Y, mask, p0, n_active, cfg, has_mask,
                                 n_bucket):
    """Metrics twin of ``_em_scan_core_active`` (see
    ``_em_scan_core_metrics`` for the per-iteration row contract)."""
    m = mask if has_mask else None
    sumsq, Ysq = _panel_consts(Y, has_mask, cfg)

    def body(carry, j):
        p, ll_prev = carry
        kf, sm, delta = cfg.e_step(Y, m, p, sumsq=sumsq)
        p_new = _m_step(Y, m, sm, p, cfg, Ysq=Ysq)
        ll = jnp.asarray(kf.loglik, jnp.float64)
        row = jnp.stack([ll, ll - ll_prev,
                         jnp.asarray(max_abs_update(p_new, p),
                                     jnp.float64)])
        live = j < n_active
        p_out = jax.tree_util.tree_map(
            lambda a, b: jnp.where(live, a, b), p_new, p)
        ll_out = jnp.where(live, ll, ll_prev)
        return (p_out, ll_out), (kf.loglik, delta, row)

    ll0 = jnp.asarray(jnp.nan, jnp.float64)
    (p, _), (lls, deltas, metrics) = jax.lax.scan(
        body, (p0, ll0), jnp.arange(n_bucket))
    return p, lls, deltas, metrics


@partial(jax.jit, static_argnames=("cfg", "has_mask", "n_iters"))
def _em_fit_scan_impl(Y, mask, p0, cfg, has_mask, n_iters):
    return _em_scan_core(Y, mask, p0, cfg, has_mask, n_iters)[:3]


@partial(jax.jit, static_argnames=("cfg", "has_mask", "n_bucket"))
def _em_fit_scan_active_impl(Y, mask, p0, n_active, cfg, has_mask,
                             n_bucket):
    return _em_scan_core_active(Y, mask, p0, n_active, cfg, has_mask,
                                n_bucket)


@partial(jax.jit, static_argnames=("cfg", "has_mask", "n_bucket"))
def _em_fit_scan_active_metrics_impl(Y, mask, p0, n_active, cfg, has_mask,
                                     n_bucket):
    return _em_scan_core_active_metrics(Y, mask, p0, n_active, cfg,
                                        has_mask, n_bucket)


@partial(jax.jit, static_argnames=("cfg", "has_mask", "n_iters"))
def _em_fit_scan_metrics_impl(Y, mask, p0, cfg, has_mask, n_iters):
    return _em_scan_core_metrics(Y, mask, p0, cfg, has_mask, n_iters)


@partial(jax.jit, static_argnames=("cfg", "has_mask", "n_iters"))
def _em_fit_scan_checked_impl(Y, mask, p0, cfg, has_mask, n_iters):
    """Debug-mode fused scan: checkify threads the error state through the
    iteration scan, so the raised error locates the first bad op across ALL
    fused iterations."""
    from jax.experimental import checkify

    def g(Y, mask, p0):
        m = mask if has_mask else None
        sumsq, Ysq = _panel_consts(Y, has_mask, cfg)

        def body(p, _):
            kf, sm, delta = cfg.e_step(Y, m, p, sumsq=sumsq)
            return _m_step(Y, m, sm, p, cfg, Ysq=Ysq), (kf.loglik, delta)

        p, (lls, deltas) = jax.lax.scan(body, p0, None, length=n_iters)
        return p, lls, deltas

    return checkify.checkify(g, errors=checkify.float_checks)(Y, mask, p0)


def em_fit_scan(Y, p0: SSMParams, n_iters: int, mask=None,
                cfg: EMConfig = EMConfig(), with_metrics: bool = False,
                n_active=None):
    """Fixed-iteration EM fused into one XLA program (benchmark path:
    BASELINE.json:2 'EM iters/sec' measured without host round-trips).
    Returns (params, logliks (n,), ss_deltas (n,)); with
    ``with_metrics=True`` a 4th element is appended — a (n, 3) per-
    iteration array [loglik, in-chunk delta, max param-update norm]
    (see ``_em_scan_core_metrics``; the default path's compiled program
    is untouched).  Debug mode has no metrics twin (checkify is the
    diagnostic already): it returns metrics=None.

    ``n_active`` (bucketed mode): ``n_iters`` becomes the STATIC bucket
    length and ``n_active`` the traced count of iterations that advance
    the params — the rest hold the carry (see ``_em_scan_core_active``),
    so every (n_active <= n_iters) call reuses one executable.  Scanned
    outputs still have length ``n_iters``; callers slice ``[:n_active]``.
    """
    if n_active is not None:
        if cfg.debug:
            raise ValueError(
                "bucketed scans (n_active=) have no debug/checkify twin — "
                "run debug fits unbucketed")
        impl = (_em_fit_scan_active_metrics_impl if with_metrics
                else _em_fit_scan_active_impl)
        tr = current_tracer()
        if tr is None:
            return impl(Y, mask, p0, n_active, cfg, mask is not None,
                        n_iters)
        key = shape_key(Y, cfg.filter, f"iters{n_iters}b")
        tr.maybe_cost("em_fit_scan", key, impl,
                      Y, mask, p0, n_active, cfg, mask is not None, n_iters)
        with tr.dispatch("em_fit_scan", key, n_iters=n_iters,
                         bucket=n_iters):
            return impl(Y, mask, p0, n_active, cfg, mask is not None,
                        n_iters)
    if cfg.debug:
        err, out = _em_fit_scan_checked_impl(Y, mask, p0, cfg,
                                             mask is not None, n_iters)
        err.throw()
        return out + (None,) if with_metrics else out
    impl = _em_fit_scan_metrics_impl if with_metrics else _em_fit_scan_impl
    tr = current_tracer()
    if tr is None:
        return impl(Y, mask, p0, cfg, mask is not None, n_iters)
    # When called from a chunk driver this span is suppressed (the driver's
    # barrier'd span owns the launch); direct callers (bench, dryrun) get
    # the async-dispatch record here.
    key = shape_key(Y, cfg.filter, f"iters{n_iters}")
    tr.maybe_cost("em_fit_scan", key, impl,
                  Y, mask, p0, cfg, mask is not None, n_iters)
    with tr.dispatch("em_fit_scan", key, n_iters=n_iters):
        return impl(Y, mask, p0, cfg, mask is not None, n_iters)

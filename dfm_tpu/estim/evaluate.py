"""Pseudo out-of-sample forecast evaluation (SURVEY.md R9 / section 3.2).

Window loop: re-fit on Y[:t0], forecast h steps ahead, collect errors at
t0 + h - 1, compare against naive benchmarks.  The windows are independent
EM runs, which gives two execution strategies:

- ``engine="loop"`` (reference behavior): one ``fit()`` per window on the
  given backend.  With ``warm_start`` each window initializes from the
  previous window's fitted params instead of a cold PCA init — consecutive
  rolling windows share most of their data, so EM starts near the optimum
  and converges in a fraction of the iterations.
- ``engine="batched"`` (rolling only): all windows stacked into ONE fused
  multi-fit program (``estim.batched.fit_many``) — W fits per dispatch
  instead of W dispatched fits; with ``warm_start`` the first window is fit
  once and its params seed every window's init.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from ..api import DynamicFactorModel, fit, forecast
from ..backends import cpu_ref

__all__ = ["oos_evaluate", "OOSResult"]


@dataclasses.dataclass
class OOSResult:
    origins: np.ndarray        # (W,) forecast origins t0 (exclusive end)
    errors: np.ndarray         # (W, N) forecast errors at horizon h
    rmse: np.ndarray           # (N,) per-series RMSE
    rmse_naive: np.ndarray     # (N,) RMSE of the last-value benchmark
    rmse_mean: np.ndarray      # (N,) RMSE of the in-sample-mean benchmark
    horizon: int

    @property
    def rel_rmse(self) -> np.ndarray:
        """RMSE relative to the naive last-value forecast (<1 == better)."""
        return self.rmse / np.maximum(self.rmse_naive, 1e-300)


def oos_evaluate(model: DynamicFactorModel, Y: np.ndarray,
                 horizon: int = 1,
                 n_windows: int = 20,
                 min_train: Optional[int] = None,
                 window: str = "rolling",
                 backend="cpu",
                 max_iters: int = 20,
                 origins: Optional[Sequence[int]] = None,
                 warm_start: bool = True,
                 engine: str = "loop") -> OOSResult:
    """Pseudo-OOS evaluation of h-step DFM forecasts.

    window: "rolling" keeps the train length fixed (same shapes -> one XLA
    compile for all windows); "expanding" grows it (reference behavior).
    warm_start: initialize each window's EM from the previous window's
    fitted params (params live in standardized units, so this re-units
    automatically; cold-start equivalence is a regression test).
    engine: "loop" | "batched" (see module docstring).  The batched engine
    accepts backend "tpu"/"sharded" (anything else maps to "tpu").
    """
    Y = np.asarray(Y, np.float64)
    T, N = Y.shape
    if min_train is None:
        min_train = max(40, T // 2)
    if origins is None:
        last = T - horizon
        origins = np.unique(np.linspace(min_train, last, n_windows,
                                        dtype=int))
    else:
        origins = np.asarray(list(origins), dtype=int)

    if engine == "batched":
        y_hats = _batched_window_forecasts(
            model, Y, origins, min_train, window, backend, max_iters,
            horizon, warm_start)
    elif engine == "loop":
        y_hats = _looped_window_forecasts(
            model, Y, origins, min_train, window, backend, max_iters,
            horizon, warm_start)
    else:
        raise ValueError(f"unknown engine {engine!r} (loop|batched)")

    # Shared windowing (estim.score): the same error/benchmark definition
    # the tune objective and the maintenance quality gate build on.
    from .score import forecast_origin_errors
    errors, naive, meanb = forecast_origin_errors(
        Y, origins, y_hats, min_train, window, horizon)
    rmse = np.sqrt((errors ** 2).mean(0))
    return OOSResult(origins=np.asarray(origins), errors=errors, rmse=rmse,
                     rmse_naive=np.sqrt((naive ** 2).mean(0)),
                     rmse_mean=np.sqrt((meanb ** 2).mean(0)),
                     horizon=horizon)


def _looped_window_forecasts(model, Y, origins, min_train, window, backend,
                             max_iters, horizon, warm_start):
    """One fit() per window; warm_start chains inits window-to-window."""
    y_hats = []
    prev = None
    for t0 in origins:
        lo = max(0, t0 - min_train) if window == "rolling" else 0
        init = prev.params if (warm_start and prev is not None) else None
        res = fit(model, Y[lo:t0], backend=backend, max_iters=max_iters,
                  init=init)
        y_hat, _ = forecast(res, horizon)
        y_hats.append(y_hat[-1])
        prev = res
    return y_hats


def _batched_window_forecasts(model, Y, origins, min_train, window, backend,
                              max_iters, horizon, warm_start):
    """All windows in one fused multi-fit program (rolling only)."""
    from .batched import DFMBatchSpec, fit_many
    if window != "rolling":
        raise ValueError(
            "engine='batched' needs same-shaped windows; use "
            "window='rolling' (expanding windows change T per window)")
    if (np.asarray(origins) < min_train).any():
        raise ValueError("engine='batched' needs origins >= min_train "
                         "(every window must have the full train length)")
    spec = DFMBatchSpec.rolling_windows(model, Y, origins,
                                        train_len=min_train)
    if warm_start:
        t0 = int(origins[0])
        first = fit(model, Y[t0 - min_train:t0], backend=backend,
                    max_iters=max_iters)
        spec.inits = [first.params] * len(origins)
    bb = "sharded" if backend == "sharded" else "tpu"
    res = fit_many(spec, backend=bb, max_iters=max_iters)
    y_hats = []
    for w in range(len(origins)):
        _, y, _ = cpu_ref.forecast(res.params[w], res.factors[w][-1],
                                   res.factor_cov[w][-1], horizon)
        if res.standardizers[w] is not None:
            y = res.standardizers[w].inverse(y)
        y_hats.append(y[-1])
    return y_hats

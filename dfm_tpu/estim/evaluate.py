"""Pseudo out-of-sample forecast evaluation (SURVEY.md R9 / section 3.2).

Expanding-window loop: re-fit on Y[:t0], forecast h steps ahead, collect
errors at t0 + h - 1, compare against naive benchmarks.  Embarrassingly
parallel over windows — each window's fit is an independent EM run, so the
loop simply reuses whatever backend it is given (TPU backends amortize
compilation across windows because shapes repeat when ``window="rolling"``;
expanding windows re-trace per origin, which is why rolling is the default).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from ..api import DynamicFactorModel, fit, forecast

__all__ = ["oos_evaluate", "OOSResult"]


@dataclasses.dataclass
class OOSResult:
    origins: np.ndarray        # (W,) forecast origins t0 (exclusive end)
    errors: np.ndarray         # (W, N) forecast errors at horizon h
    rmse: np.ndarray           # (N,) per-series RMSE
    rmse_naive: np.ndarray     # (N,) RMSE of the last-value benchmark
    rmse_mean: np.ndarray      # (N,) RMSE of the in-sample-mean benchmark
    horizon: int

    @property
    def rel_rmse(self) -> np.ndarray:
        """RMSE relative to the naive last-value forecast (<1 == better)."""
        return self.rmse / np.maximum(self.rmse_naive, 1e-300)


def oos_evaluate(model: DynamicFactorModel, Y: np.ndarray,
                 horizon: int = 1,
                 n_windows: int = 20,
                 min_train: Optional[int] = None,
                 window: str = "rolling",
                 backend="cpu",
                 max_iters: int = 20,
                 origins: Optional[Sequence[int]] = None) -> OOSResult:
    """Pseudo-OOS evaluation of h-step DFM forecasts.

    window: "rolling" keeps the train length fixed (same shapes -> one XLA
    compile for all windows); "expanding" grows it (reference behavior).
    """
    Y = np.asarray(Y, np.float64)
    T, N = Y.shape
    if min_train is None:
        min_train = max(40, T // 2)
    if origins is None:
        last = T - horizon
        origins = np.unique(np.linspace(min_train, last, n_windows,
                                        dtype=int))
    else:
        origins = np.asarray(list(origins), dtype=int)

    errors = np.zeros((len(origins), N))
    naive = np.zeros((len(origins), N))
    meanb = np.zeros((len(origins), N))
    for w, t0 in enumerate(origins):
        lo = max(0, t0 - min_train) if window == "rolling" else 0
        Ytr = Y[lo:t0]
        res = fit(model, Ytr, backend=backend, max_iters=max_iters)
        y_hat, _ = forecast(res, horizon)
        truth = Y[t0 + horizon - 1]
        errors[w] = truth - y_hat[-1]
        naive[w] = truth - Ytr[-1]
        meanb[w] = truth - Ytr.mean(0)
    rmse = np.sqrt((errors ** 2).mean(0))
    return OOSResult(origins=np.asarray(origins), errors=errors, rmse=rmse,
                     rmse_naive=np.sqrt((naive ** 2).mean(0)),
                     rmse_mean=np.sqrt((meanb ** 2).mean(0)),
                     horizon=horizon)

"""Differentiable EM hyperparameter tuning (``fit(tune=...)``).

Q/R mis-scaling is the classic DFM failure mode: EM's closed-form M-step
is a maximum-likelihood update, so a panel whose innovation scale the
model family can't express (structural breaks, deliberate shrinkage,
short panels) ends up with over-confident bands and poor held-out
one-step prediction.  The standard fix is a grid sweep — G full fits,
each paying the ~100 ms-per-dispatch tunnel tax of this device class —
over multiplicative (Q-scale, R-scale) corrections and a loading ridge.

This module replaces that host loop with two in-graph engines sharing
ONE objective (``estim.score``'s held-out one-step MSE — the same
definition the maintenance quality gate and ``oos_evaluate`` use):

- **CV sweep** (``method="sweep"``): all G candidate (q_scale, r_scale,
  lam_ridge) points ride the ``run_batched_em`` multi-fit lanes as ONE
  fused B-way EM program (per-lane hypers via ``Hetero``; the trailing
  holdout window is excluded from training through the lane time masks),
  then one vmapped scoring program filters the FULL panel at each lane's
  fitted params and reduces the held-out MSE in-graph.  Two blocking
  device->host transfers total, independent of G.

- **Gradient search** (``method="grad"``, the headline): the held-out
  loss is differentiated THROUGH the filter itself.  The inner EM is a
  fixed-iteration ``lax.scan`` twin of the fit drivers' step (the
  info-form filter and RTS smoother are reverse-mode differentiable —
  plain ``lax.scan``s, no while_loop), hyperparameters enter
  log-parameterized (positivity for free, scale-free steps), and an
  in-graph Adam loop takes ``steps`` gradient steps inside ONE jitted
  program — one blocking device->host read for the whole search.  The
  best iterate is tracked in-carry over every EVALUATED theta including
  theta = 0 (the untuned hypers), so the search result is never worse
  than untuned at the same EM budget by construction.

The NumPy f64 twin (``heldout_loss_np``) computes the SAME loss from
``backends.cpu_ref`` pieces — the oracle that the gradient is
finite-difference-checked against in ``tests/test_tune.py``.

``fit(tune=...)`` runs the search on the standardized panel before the
main fit and applies the winning hypers through ``EMConfig``'s static
hyper fields (``em.cfg_hypers``), so every execution mode — chunked,
fused, pipelined, sharded — runs the tuned M-step with zero new
driver seams.  ``tune=None`` short-circuits everywhere: the untuned
program is byte-identical to pre-tune builds.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Tuple

import numpy as np

from ..obs.trace import current_tracer, shape_key

__all__ = ["TuneOptions", "resolve_tune", "tune_fit", "heldout_loss_np",
           "DEFAULT_GRID"]

# 3 x 3 multiplicative (q_scale, r_scale) grid around the MLE point, no
# ridge: the untuned point (1, 1, 0) is IN the grid, so the sweep's best
# is never worse than untuned at the same budget.
DEFAULT_GRID: Tuple[Tuple[float, float, float], ...] = tuple(
    (q, r, 0.0) for q in (0.25, 1.0, 4.0) for r in (0.25, 1.0, 4.0))

_ADAM_B1, _ADAM_B2, _ADAM_EPS = 0.9, 0.999, 1e-8


@dataclasses.dataclass(frozen=True)
class TuneOptions:
    """Hyper-search configuration for ``fit(tune=...)``.

    method: "grad" (in-graph Adam over log hypers — the headline),
        "sweep" (batched CV grid), or "both" (sweep first, gradient
        search second; best of the two wins).
    steps / lr: gradient-search budget and Adam step size (log space).
    em_iters: inner EM iterations per objective evaluation — the FIXED
        budget both the tuned and untuned fits are compared at.
    holdout_rows: trailing rows scored held-out one-step (clamped by
        ``estim.score.clamp_holdout``); they are excluded from the
        search's training window.
    grid: sweep candidates ((q_scale, r_scale, lam_ridge), ...);
        ``None`` uses :data:`DEFAULT_GRID`.
    lam_ridge: fixed loading ridge during the gradient search (the grad
        search optimizes the two scale hypers; the ridge is a sweep
        dimension).
    """

    method: str = "grad"
    steps: int = 20
    lr: float = 0.15
    em_iters: int = 5
    holdout_rows: int = 8
    grid: Optional[Tuple[Tuple[float, float, float], ...]] = None
    lam_ridge: float = 0.0

    def __post_init__(self):
        if self.method not in ("grad", "sweep", "both"):
            raise ValueError(
                f"unknown tune method {self.method!r} (grad|sweep|both)")
        if self.steps < 1 or self.em_iters < 1:
            raise ValueError("tune steps and em_iters must be >= 1")


def resolve_tune(tune) -> Optional[TuneOptions]:
    """``fit(tune=)`` knob -> TuneOptions | None."""
    if tune is None or tune is False:
        return None
    if tune is True:
        return TuneOptions()
    if isinstance(tune, TuneOptions):
        return tune
    if isinstance(tune, dict):
        return TuneOptions(**tune)
    raise TypeError(
        f"tune must be bool, dict or TuneOptions; got {type(tune).__name__}")


# ---------------------------------------------------------------------------
# In-graph held-out objective (reverse-mode differentiable)
# ---------------------------------------------------------------------------

def _heldout_loss(theta, Yz, Wtr, Wfull, p0, cfg, em_iters: int,
                  holdout_rows: int, lam_ridge):
    """Held-out one-step MSE after ``em_iters`` fixed EM iterations at
    hypers (exp theta[0], exp theta[1], lam_ridge).

    Training runs masked to ``Wtr`` (the holdout window zeroed out);
    the evaluation filter sees ``Wfull`` — one-step predictions at t use
    only data before t, so scoring the trailing rows is legitimate
    pseudo-out-of-sample scoring (``estim.score``).  Everything is a
    ``lax.scan`` over the info-form filter, so ``jax.grad`` flows
    through the WHOLE pipeline: filter -> smoother -> M-step x em_iters
    -> eval filter -> loss.  Returns (loss, fitted params).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from .em import _m_step
    from .score import heldout_mse_graph

    hy = (jnp.exp(theta[0]), jnp.exp(theta[1]),
          jnp.asarray(lam_ridge, Yz.dtype))

    def em_iter(p, _):
        # Convergence bookkeeping (the loglik) is detached: the tuned
        # objective is the held-out loss, not the in-sample likelihood.
        kf, sm, _ = cfg.e_step(Yz, Wtr, p)
        p_new = _m_step(Yz, Wtr, sm, p, cfg, hypers=hy)
        return p_new, lax.stop_gradient(kf.loglik)

    # Rematerialize per-iteration: reverse-mode through em_iters chained
    # filter+smoother scans would otherwise hold every iteration's
    # (T, k, k) residuals live at once.
    p_fit, _ = lax.scan(jax.checkpoint(em_iter), p0, None, length=em_iters)
    kf = cfg.filter_fn()(Yz, p_fit, mask=Wfull)
    loss = heldout_mse_graph(Yz, Wfull, kf.x_pred, p_fit.Lam, holdout_rows)
    return loss, p_fit


def _grad_search_core(Yz, Wtr, Wfull, p0, cfg, steps: int, em_iters: int,
                      holdout_rows: int, lr, lam_ridge):
    """``steps`` Adam iterations over theta = (log q_scale, log r_scale)
    in ONE program.  Carry tracks the best (loss, theta, params) over
    every evaluated theta — step 0 evaluates theta = 0, so the returned
    best is <= the untuned objective by construction."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    dt = Yz.dtype

    def loss_fn(th):
        return _heldout_loss(th, Yz, Wtr, Wfull, p0, cfg, em_iters,
                             holdout_rows, lam_ridge)

    vg = jax.value_and_grad(loss_fn, has_aux=True)

    def body(c, i):
        th, m, v, bl, bth, bp = c
        (loss, p_fit), g = vg(th)
        ok = jnp.isfinite(loss)
        better = ok & (loss < bl)
        bl = jnp.where(better, loss, bl)
        bth = jnp.where(better, th, bth)
        bp = jax.tree_util.tree_map(
            lambda a, b: jnp.where(better, a, b), p_fit, bp)
        g = jnp.where(ok, g, jnp.zeros_like(g))
        m = _ADAM_B1 * m + (1.0 - _ADAM_B1) * g
        v = _ADAM_B2 * v + (1.0 - _ADAM_B2) * g * g
        t = (i + 1).astype(dt)
        mh = m / (1.0 - _ADAM_B1 ** t)
        vh = v / (1.0 - _ADAM_B2 ** t)
        th_new = th - lr * mh / (jnp.sqrt(vh) + _ADAM_EPS)
        return (th_new, m, v, bl, bth, bp), (th, loss)

    th0 = jnp.zeros((2,), dt)
    c0 = (th0, jnp.zeros((2,), dt), jnp.zeros((2,), dt),
          jnp.asarray(jnp.inf, dt), th0,
          jax.tree_util.tree_map(jnp.zeros_like, p0))
    (_, _, _, bl, bth, bp), (thetas, losses) = lax.scan(
        body, c0, jnp.arange(steps))
    return bl, bth, bp, thetas, losses


_GRAD_IMPL = None


def _grad_search_impl(*args, **kw):
    """Jitted-on-first-use twin of ``_grad_search_core`` (keeps the
    module importable without touching jax at import time)."""
    global _GRAD_IMPL
    if _GRAD_IMPL is None:
        import jax
        _GRAD_IMPL = jax.jit(
            _grad_search_core,
            static_argnames=("cfg", "steps", "em_iters", "holdout_rows"))
    return _GRAD_IMPL(*args, **kw)


_SCORE_IMPL = None


def _score_lanes_impl(Y, W, params, holdout_rows: int):
    """Vmapped lane scorer: filter the FULL panel at each lane's fitted
    params, reduce the held-out MSE in-graph -> (G,) scores."""
    global _SCORE_IMPL
    if _SCORE_IMPL is None:
        import jax

        def _core(Y, W, params, holdout_rows):
            from ..ssm.info_filter import info_filter
            from .score import heldout_mse_graph

            def one(p):
                kf = info_filter(Y, p, mask=W)
                return heldout_mse_graph(Y, W, kf.x_pred, p.Lam,
                                         holdout_rows)

            return jax.vmap(one)(params)

        _SCORE_IMPL = jax.jit(_core, static_argnames=("holdout_rows",))
    return _SCORE_IMPL(Y, W, params, holdout_rows)


# ---------------------------------------------------------------------------
# Host driver
# ---------------------------------------------------------------------------

def tune_fit(Y, mask, p0, cfg, opts=None, dtype=None,
             return_params: bool = False) -> dict:
    """Run the configured hyper search on a STANDARDIZED panel.

    Y    : (T, N) standardized panel (host or device array; NaNs allowed
           at missing entries).
    mask : optional {0,1} observedness (combined with the NaN pattern).
    p0   : warm-start params (``cpu_ref.SSMParams`` or the jax twin).
    cfg  : the fit's ``EMConfig`` — the estimate_A/Q/init flags and
           r_floor carry over; the tune objective always runs the
           (differentiable) info filter with hypers at the defaults.
    opts : ``TuneOptions`` (``None``/``True`` -> defaults).

    Returns the tune record: chosen hypers, held-out before/after, the
    gradient trajectory and/or CV curve, dispatch count and wall.  With
    ``return_params=True`` the record also carries ``best_params`` (the
    searched fit at the winning hypers, ``cpu_ref.SSMParams``) — the
    maintenance retune path swaps those in directly.

    Blocking device->host transfers: 1 (grad), 2 (sweep), 3 (both) —
    independent of the number of candidates/steps.
    """
    import jax
    import jax.numpy as jnp
    from ..backends import cpu_ref
    from ..ops.precision import default_compute_dtype
    from ..ssm.params import SSMParams as JaxParams
    from .em import EMConfig
    from .score import clamp_holdout

    opts = resolve_tune(True if opts is None else opts)
    if opts is None:      # resolve_tune(False) can't happen via fit(); guard
        raise ValueError("tune_fit called with tune disabled")
    t0 = time.perf_counter()

    Yh = np.asarray(Y, np.float64)
    T, N = Yh.shape
    Wfull = (np.ones((T, N)) if mask is None
             else np.asarray(mask, np.float64).copy())
    Wfull = Wfull * np.isfinite(Yh)
    h = clamp_holdout(opts.holdout_rows, T)
    Wtr = Wfull.copy()
    Wtr[T - h:] = 0.0
    Yimp = np.where(Wfull > 0, np.nan_to_num(Yh), 0.0)

    dt = jnp.dtype(dtype) if dtype is not None else default_compute_dtype()
    cfg_t = dataclasses.replace(cfg, filter="info", debug=False,
                                q_scale=1.0, r_scale=1.0, lam_ridge=0.0)
    tr = current_tracer()
    dispatches = 0
    record: dict = {"method": opts.method, "steps": int(opts.steps),
                    "em_iters": int(opts.em_iters), "holdout_rows": int(h),
                    "lr": float(opts.lr)}
    best = None          # (loss, q, r, lam, params_np | None)
    heldout_before = None

    with jax.default_matmul_precision("highest"):
        Yj = jnp.asarray(Yimp, dt)
        Wtr_j = jnp.asarray(Wtr, dt)
        Wfull_j = jnp.asarray(Wfull, dt)
        p0j = JaxParams(*(jnp.asarray(np.asarray(x), dt) for x in
                          (p0.Lam, p0.A, p0.Q, p0.R, p0.mu0, p0.P0)))

        if opts.method in ("sweep", "both"):
            cv, sweep_best, before = _run_sweep(
                Yj, Wfull_j, p0j, cfg_t, opts, dt, tr)
            dispatches += 2
            record["cv"] = cv
            if before is not None:
                heldout_before = before
            if sweep_best is not None and (
                    best is None or sweep_best[0] < best[0]):
                best = sweep_best

        if opts.method in ("grad", "both"):
            def _run():
                out = _grad_search_impl(
                    Yj, Wtr_j, Wfull_j, p0j, cfg_t, opts.steps,
                    opts.em_iters, h, jnp.asarray(opts.lr, dt),
                    jnp.asarray(opts.lam_ridge, dt))
                # ONE blocking pull for the whole search (the only
                # execution barrier this device class has).
                return jax.device_get(out)

            key = shape_key(Yj, "info", f"s{opts.steps}i{opts.em_iters}")
            if tr is not None:
                with tr.dispatch("tune_grad", key, barrier=True,
                                 steps=int(opts.steps)):
                    bl, bth, bp, thetas, losses = _run()
            else:
                bl, bth, bp, thetas, losses = _run()
            dispatches += 1
            record["trajectory"] = {
                "theta": np.asarray(thetas, np.float64).tolist(),
                "loss": np.asarray(losses, np.float64).tolist()}
            heldout_before = float(losses[0])   # theta = 0 == untuned
            if np.isfinite(bl):
                p_np = cpu_ref.SSMParams(
                    *(np.asarray(x, np.float64) for x in bp))
                cand = (float(bl), float(np.exp(bth[0])),
                        float(np.exp(bth[1])), float(opts.lam_ridge), p_np)
                if best is None or cand[0] < best[0]:
                    best = cand

    wall = time.perf_counter() - t0
    if best is None:      # every evaluation non-finite: keep the defaults
        q, r, lam, after, p_best = 1.0, 1.0, 0.0, float("nan"), None
    else:
        after, q, r, lam, p_best = best
    record.update(q_scale=q, r_scale=r, lam_ridge=lam,
                  heldout_before=heldout_before, heldout_after=after,
                  dispatches=int(dispatches), wall_s=float(wall))
    if return_params and p_best is not None:
        record["best_params"] = p_best
    ev = {k: record[k] for k in
          ("method", "q_scale", "r_scale", "lam_ridge", "heldout_before",
           "heldout_after", "dispatches", "steps", "em_iters",
           "holdout_rows")}
    ev["wall"] = float(wall)
    if tr is not None:
        tr.emit("tune", **ev)
    else:
        from ..obs.live import observe
        observe({"t": t0, "kind": "tune", **ev})
    return record


def _run_sweep(Yj, Wfull_j, p0j, cfg_t, opts: TuneOptions, dt, tr):
    """The batched CV sweep: G candidate hyper points as G ``Hetero``
    lanes of ONE fused EM program (training excludes the trailing
    holdout via the lane time masks), then one vmapped scoring program.
    Returns (cv_curve, best | None, untuned_score | None)."""
    import jax
    import jax.numpy as jnp
    from ..backends import cpu_ref
    from .batched import make_hetero, run_batched_em
    from .score import clamp_holdout

    grid = tuple(opts.grid) if opts.grid is not None else DEFAULT_GRID
    G = len(grid)
    qs = np.array([g[0] for g in grid], np.float64)
    rs = np.array([g[1] for g in grid], np.float64)
    ls = np.array([g[2] for g in grid], np.float64)
    T, N = Yj.shape
    h = clamp_holdout(opts.holdout_rows, T)
    # Train on the first T-h rows only (lane time masks); the batched FIT
    # engine is unmasked-within-the-window, so elementwise-missing panels
    # ride mean-imputed exactly as the maintenance refits do — the
    # holdout SCORING below stays masked to truly observed entries.
    het = make_hetero([T - h] * G, [N] * G, T, N, dtype=dt, tol=0.0,
                      iter_cap=opts.em_iters, q_scale=qs, r_scale=rs,
                      lam_ridge=ls)
    Yb = jnp.broadcast_to(Yj, (G, T, N))
    p0b = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (G,) + x.shape), p0j)
    # Dispatch 1: the whole grid's EM in one fused chunk.
    p, _, _, _, _ = run_batched_em(
        Yb, p0b, cfg_t, max_iters=opts.em_iters, tol=0.0,
        fused_chunk=opts.em_iters, hetero=het)
    # Dispatch 2: vmapped full-panel filters + in-graph held-out MSE; the
    # device_get of the (G,) scores is the blocking pull.
    key = shape_key(Yj, "info", f"g{G}")
    if tr is not None:
        with tr.dispatch("tune_sweep_score", key, barrier=True, lanes=G):
            scores = np.asarray(jax.device_get(
                _score_lanes_impl(Yj, Wfull_j, p, h)), np.float64)
    else:
        scores = np.asarray(jax.device_get(
            _score_lanes_impl(Yj, Wfull_j, p, h)), np.float64)
    cv = [{"q_scale": float(qs[g]), "r_scale": float(rs[g]),
           "lam_ridge": float(ls[g]), "heldout": float(scores[g])}
          for g in range(G)]
    before = None
    for g in range(G):
        if qs[g] == 1.0 and rs[g] == 1.0 and ls[g] == 0.0:
            before = float(scores[g])
            break
    finite = np.isfinite(scores)
    if not finite.any():
        return cv, None, before
    gbest = int(np.argmin(np.where(finite, scores, np.inf)))
    p_np = cpu_ref.SSMParams(*(np.asarray(x[gbest], np.float64) for x in p))
    return cv, (float(scores[gbest]), float(qs[gbest]), float(rs[gbest]),
                float(ls[gbest]), p_np), before


# ---------------------------------------------------------------------------
# NumPy f64 oracle twin (jax-free): the FD-check target
# ---------------------------------------------------------------------------

def _sym_np(M):
    return 0.5 * (M + M.T)


def _m_step_np(Y, W, sm, p, hy, r_floor: float, estimate_A: bool,
               estimate_Q: bool, estimate_init: bool):
    """NumPy twin of ``em._m_step``'s masked branch with tuned hypers:
    ridge on the per-series loading normal equations, then Q/R scaled
    AFTER the closed-form update — the exact order the in-graph
    objective applies."""
    from ..backends import cpu_ref
    mom = cpu_ref.smoothed_moments(sm)
    Ef, EffT = mom["Ef"], mom["EffT"]
    T = Y.shape[0]
    k = p.A.shape[0]
    Yz = np.where(W > 0, np.nan_to_num(Y), 0.0)
    S_yf_i = np.einsum("ti,tk->ik", Yz, Ef)
    S_ff_i = np.einsum("ti,tkl->ikl", W, EffT)
    never = W.sum(0) == 0
    S_ff_i = np.where(never[:, None, None], np.eye(k)[None], S_ff_i)
    S_ff_i = S_ff_i + hy[2] * np.eye(k)[None]
    Lam = np.linalg.solve(np.swapaxes(S_ff_i, 1, 2),
                          S_yf_i[:, :, None])[:, :, 0]
    counts = np.maximum(W.sum(0), 1.0)
    resid_sq = np.einsum("ti,ti->i", W, (Yz - Ef @ Lam.T) ** 2)
    smear = np.einsum("ik,ikl,il->i", Lam,
                      np.einsum("ti,tkl->ikl", W, sm.P_sm), Lam)
    R = np.maximum((resid_sq + smear) / counts, r_floor)
    A, Q = p.A, p.Q
    if estimate_A:
        A = np.linalg.solve(mom["S_ff_lag"].T, mom["S_cross"].T).T
        if estimate_Q:
            Q = _sym_np((mom["S_ff_cur"] - A @ mom["S_cross"].T) / (T - 1))
    elif estimate_Q:
        Q = _sym_np((mom["S_ff_cur"] - A @ mom["S_cross"].T
                     - mom["S_cross"] @ A.T
                     + A @ mom["S_ff_lag"] @ A.T) / (T - 1))
    mu0, P0 = p.mu0, p.P0
    if estimate_init:
        mu0, P0 = sm.x_sm[0], _sym_np(sm.P_sm[0])
    Q = hy[0] * Q
    R = np.maximum(hy[1] * R, r_floor)
    from ..backends.cpu_ref import SSMParams
    return SSMParams(Lam=Lam, A=A, Q=Q, R=np.asarray(R), mu0=mu0, P0=P0)


def heldout_loss_np(theta, Y, Wtr, Wfull, p0, em_iters: int,
                    holdout_rows: int, lam_ridge: float = 0.0,
                    estimate_A: bool = True, estimate_Q: bool = True,
                    estimate_init: bool = False,
                    r_floor: float = 1e-6) -> float:
    """The gradient search's objective on the NumPy f64 oracle: the SAME
    function ``_heldout_loss`` computes in-graph (masked EM at hypers
    (exp theta_0, exp theta_1, lam_ridge), full-panel filter, held-out
    one-step MSE with the graph's ``max(n, 1)`` zero-guard), evaluated
    with ``cpu_ref`` pieces.  The FD-parity tests differentiate THIS."""
    from ..backends import cpu_ref
    from .score import one_step_sse
    Y = np.asarray(Y, np.float64)
    Wtr = np.asarray(Wtr, np.float64)
    Wfull = np.asarray(Wfull, np.float64)
    hy = (float(np.exp(theta[0])), float(np.exp(theta[1])),
          float(lam_ridge))
    p = p0.copy()
    for _ in range(int(em_iters)):
        kf = cpu_ref.kalman_filter(Y, p, mask=Wtr)
        sm = cpu_ref.rts_smoother(kf, p)
        p = _m_step_np(Y, Wtr, sm, p, hy, r_floor, estimate_A, estimate_Q,
                       estimate_init)
    kf = cpu_ref.kalman_filter(Y, p, mask=Wfull)
    sse, n = one_step_sse(Y, Wfull, kf.x_pred, np.asarray(p.Lam),
                          holdout_rows, xp=np)
    return float(sse) / max(float(n), 1.0)

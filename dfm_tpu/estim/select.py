"""Factor-number selection and targeted predictors (SURVEY.md R7/R8).

Bai-Ng (2002) information criteria choose the number of factors from the
PCA residual variance profile (one SVD gives every k at once); Bai-Ng
(2008)-style targeted predictors pre-select the series entering factor
extraction with an elastic-net regression on a forecast target.

Both are small host-side model-selection utilities — NumPy float64, run once
before the device path starts (same placement as data prep, SURVEY.md R2).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

__all__ = ["bai_ng_ic", "select_n_factors", "lasso_path",
           "targeted_predictors", "select_n_factors_em", "EMSelectResult"]


@dataclasses.dataclass
class ICResult:
    k_icp1: int
    k_icp2: int
    k_icp3: int
    icp1: np.ndarray    # (k_max + 1,) criterion values, index = k
    icp2: np.ndarray
    icp3: np.ndarray
    V: np.ndarray       # residual variance profile V(k)

    @property
    def k_best(self) -> int:
        """ICp2 is the standard conservative default."""
        return self.k_icp2


def bai_ng_ic(Y: np.ndarray, k_max: int = 15) -> ICResult:
    """Bai-Ng (2002) ICp1-3 over k = 0..k_max from one SVD.

    Y must be standardized (T, N).  V(k) = (1/NT) sum of squared PCA
    residuals with k factors = (1/NT) * sum_{j>k} s_j^2.
    """
    Y = np.asarray(Y, np.float64)
    T, N = Y.shape
    k_max = int(min(k_max, min(T, N) - 1))
    s = np.linalg.svd(Y, compute_uv=False)
    total = np.sum(s ** 2)
    tail = total - np.cumsum(np.concatenate([[0.0], s[: k_max] ** 2]))
    V = tail / (N * T)                                 # V(0..k_max)
    ks = np.arange(k_max + 1)
    NT = N * T
    c1 = (N + T) / NT * np.log(NT / (N + T))
    m = min(N, T)
    c2 = (N + T) / NT * np.log(m)
    c3 = np.log(m) / m
    logV = np.log(np.maximum(V, 1e-300))
    icp1 = logV + ks * c1
    icp2 = logV + ks * c2
    icp3 = logV + ks * c3
    return ICResult(int(np.argmin(icp1)), int(np.argmin(icp2)),
                    int(np.argmin(icp3)), icp1, icp2, icp3, V)


def select_n_factors(Y: np.ndarray, k_max: int = 15,
                     criterion: str = "icp2") -> int:
    """Convenience wrapper; criterion in {'icp1','icp2','icp3'}."""
    res = bai_ng_ic(Y, k_max=k_max)
    return {"icp1": res.k_icp1, "icp2": res.k_icp2,
            "icp3": res.k_icp3}[criterion]


def lasso_path(X: np.ndarray, y: np.ndarray, lam: float,
               alpha: float = 1.0, max_iters: int = 500,
               tol: float = 1e-8) -> np.ndarray:
    """Elastic-net coefficients by cyclic coordinate descent.

    Minimizes (1/2T)||y - X b||^2 + lam*(alpha*|b|_1 + (1-alpha)/2*|b|_2^2).
    X is assumed column-standardized.  Small, dependency-free — the
    reference used a GLMNet binding for this role [SURVEY.md R8].
    """
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    T, N = X.shape
    b = np.zeros(N)
    col_sq = (X ** 2).sum(0) / T + lam * (1.0 - alpha)
    r = y.copy()
    for _ in range(max_iters):
        max_delta = 0.0
        for j in range(N):
            bj_old = b[j]
            rho = X[:, j] @ r / T + col_sq[j] * bj_old - lam * (
                1.0 - alpha) * bj_old
            bj = np.sign(rho) * max(abs(rho) - lam * alpha, 0.0) / col_sq[j]
            if bj != bj_old:
                r -= X[:, j] * (bj - bj_old)
                b[j] = bj
                max_delta = max(max_delta, abs(bj - bj_old))
        if max_delta < tol:
            break
    return b


def targeted_predictors(Y: np.ndarray, target: np.ndarray,
                        horizon: int = 1, lam: Optional[float] = None,
                        n_keep: Optional[int] = None,
                        alpha: float = 0.9) -> np.ndarray:
    """Indices of series worth extracting factors from, for a given target.

    Regresses target_{t+h} on the panel at t with an elastic net; keeps the
    series with nonzero coefficients (or the top ``n_keep`` by |coef|).  If
    ``lam`` is None a small grid is scanned and the sparsest solution
    keeping >= max(10, N/10) series is used.
    """
    Y = np.asarray(Y, np.float64)
    target = np.asarray(target, np.float64)
    T, N = Y.shape
    X = Y[: T - horizon]
    yv = target[horizon:]
    X = (X - X.mean(0)) / np.maximum(X.std(0), 1e-12)
    yv = (yv - yv.mean()) / max(yv.std(), 1e-12)
    min_keep = max(10, N // 10)
    if lam is not None:
        lams = [lam]
    else:
        lam_max = np.max(np.abs(X.T @ yv)) / len(yv)
        lams = [lam_max * f for f in (0.5, 0.2, 0.1, 0.05, 0.02, 0.01)]
    last = None
    for l in lams:
        b = lasso_path(X, yv, l, alpha=alpha)
        nz = np.flatnonzero(b != 0.0)
        last = (b, nz)
        if len(nz) >= min_keep:
            break
    b, nz = last
    if n_keep is not None:
        order = np.argsort(-np.abs(b))
        return np.sort(order[:n_keep])
    return nz if len(nz) else np.arange(N)


@dataclasses.dataclass
class EMSelectResult:
    """Likelihood-based factor-count selection over a k-grid."""

    ks: np.ndarray           # (G,) candidate factor counts
    logliks: np.ndarray      # (G,) final EM loglik per k
    ic: np.ndarray           # (G,) criterion values (lower is better)
    k_best: int
    fit: object              # the underlying estim.batched.BatchFitResult


def select_n_factors_em(Y: np.ndarray, k_max: int = 8,
                        ks: Optional[np.ndarray] = None,
                        criterion: str = "bic", dynamics: str = "ar1",
                        max_iters: int = 30, tol: float = 1e-6,
                        backend: str = "tpu", **fit_kw) -> EMSelectResult:
    """Choose k by penalized EM log-likelihood — ONE fused device program.

    Unlike the SVD-profile ``bai_ng_ic`` (host, no dynamics), this refits
    the full DFM at every k on the candidate grid through the batched
    multi-fit engine (``estim.batched.fit_many``): the grid members are
    padded to k_max with inert factors and fit simultaneously, so the whole
    selection costs ~one fit's dispatches instead of one PER k.

    criterion: "bic" (penalty n_params * log(T*N)) or "aic" (2 * n_params);
    n_params counts Lam (N*k), R (N), and for AR(1) dynamics A (k^2) and Q
    (k(k+1)/2).  Returns the full ``BatchFitResult`` so the winning fit's
    params/factors need no refit.
    """
    from .batched import DFMBatchSpec, fit_many
    Y = np.asarray(Y, np.float64)
    T, N = Y.shape
    if ks is None:
        ks = np.arange(1, int(k_max) + 1)
    ks = np.asarray(sorted(int(k) for k in ks), np.int64)
    spec = DFMBatchSpec.k_grid(Y, ks, dynamics=dynamics)
    res = fit_many(spec, backend=backend, max_iters=max_iters, tol=tol,
                   **fit_kw)
    lls = res.logliks_final
    n_par = N * ks + N + (ks ** 2 + ks * (ks + 1) // 2
                          if dynamics == "ar1" else 0)
    if criterion == "bic":
        ic = -2.0 * lls + n_par * np.log(T * N)
    elif criterion == "aic":
        ic = -2.0 * lls + 2.0 * n_par
    else:
        raise ValueError(f"unknown criterion {criterion!r} (bic|aic)")
    return EMSelectResult(ks=ks, logliks=lls, ic=ic,
                          k_best=int(ks[np.argmin(ic)]), fit=res)

"""Estimation layer (SURVEY.md L2): EM, model selection, evaluation."""

from .em import EMConfig, em_step, em_fit, em_fit_scan, run_em_loop
from .select import (bai_ng_ic, select_n_factors, select_n_factors_em,
                     EMSelectResult, lasso_path, targeted_predictors)
from .evaluate import oos_evaluate, OOSResult
from .batched import DFMBatchSpec, BatchFitResult, fit_many
from .diffusion import diffusion_index_forecast, DIForecast

__all__ = [
    "EMConfig", "em_step", "em_fit", "em_fit_scan", "run_em_loop",
    "bai_ng_ic", "select_n_factors", "select_n_factors_em", "EMSelectResult",
    "lasso_path", "targeted_predictors",
    "oos_evaluate", "OOSResult",
    "DFMBatchSpec", "BatchFitResult", "fit_many",
    "diffusion_index_forecast", "DIForecast",
]

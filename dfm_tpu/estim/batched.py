"""Batched multi-fit EM engine: B independent problems in ONE fused program.

The serving-shaped workloads (EM restarts, Bai-Ng k-grid refits, rolling-
window OOS evaluation) are host loops of independent ``fit()`` calls today,
so each fit pays the ~100 ms tunnel dispatch plus the small-problem op floor
(docs/PERF.md: "next levers: batching").  This module stacks B same-shaped
(T, N, k) problems along a leading batch axis and runs them through one
jitted ``lax.scan`` over EM iterations — B fits per dispatch instead of B
dispatches.

Design constraints this file encodes:

- Everything inside the time scan is (B, k, k)/(B, k)-shaped with k ~ 2-8:
  exactly the shapes the toolchain's batched-linalg path punishes ~100x
  (PERF.md item 6a), so the scan body uses the unrolled small-matrix forms
  from ``ops.linalg`` (``chol_unrolled`` / ``matmul_vpu``) throughout.
- No early exit from the fused scan: per-problem convergence is tracked
  IN-CARRY (state 0 run / 1 converged / 2 diverged / 3 pad) and finished
  problems freeze via ``jnp.where`` selects — same stopping semantics as
  the host loop (``em.em_progress`` / ``run_em_chunked``), including the
  divergence rule's roll-back to the params entering the pre-drop
  iteration (kept as ``p_prev`` in the carry, no replay dispatch needed).
- The host driver runs fused chunks and checks the (B,) state vector
  between chunks (one small transfer — the only execution barrier this
  device class has); dispatches go through the ``robust.guard`` retry seam
  and per-problem ``FitHealth`` records are built from the traces.
- The FIT engine is unmasked-panels-only: a per-problem mask would make
  C_t time-varying ((B, T, k, k) carried through the scan) and the masked
  M-step needs the (T, k, k) moment tensors — the host-loop path already
  covers that case.  The SERVING twins at the bottom of this file
  (``batched_ragged_append`` + the ``*_masked`` filter/M-step) accept that
  cost deliberately: they batch ``serve/session.py``'s capacity-padded
  elementwise-masked program, where the mask IS the live-length/missing
  encoding and T is session-capacity-sized, for the fleet tier
  (``dfm_tpu/fleet/``).

The batch members may differ by init (restarts), by data (windows), or by
ACTIVE factor count (k-grid): problems with k_b < k_max are padded with
inert trailing factors (Lam cols 0, A zero row/col, Q/P0 identity block,
mu0 0) which EM preserves exactly — zero loading columns keep the inactive
block out of the loglik and every update (the blockdiag Cholesky has exact
zero cross terms), so the padded problem's trace equals the unpadded k_b
problem's to fp-op-order tolerance.  Results are sliced back to k_b.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..backends import cpu_ref
from ..obs.trace import current_tracer, shape_key
from ..ops.linalg import (UNROLL_K_MAX, chol_logdet, chol_solve,
                          chol_solve_unrolled, chol_unrolled, default_jitter,
                          matmul_vpu, matvec_vpu, psd_cholesky, sym)
from ..ops.precision import accum_dtype, default_compute_dtype
from ..robust.dispatch import _call_with_deadline
from ..robust.health import FitHealth, HealthEvent, health_from_trace
from ..ssm.params import SSMParams
from ..utils.data import Standardizer, standardize, validate_panel
from .em import EMConfig, noise_floor_for

__all__ = ["DFMBatchSpec", "BatchFitResult", "fit_many", "run_batched_em",
           "stack_params", "unstack_params", "pad_params_to_k",
           "slice_params_to_k", "batched_m_step", "Hetero", "make_hetero",
           "pad_panel_to_t", "pad_panel_to_n", "pad_params_to_n",
           "slice_params_to_n", "batched_ragged_append",
           "batched_filter_masked", "batched_m_step_masked"]

_LOG2PI = 1.8378770664093453


# ---------------------------------------------------------------------------
# Small-matrix batched linalg (PERF.md item 6a shapes)
# ---------------------------------------------------------------------------

def _bT(M):
    return jnp.swapaxes(M, -1, -2)


def bchol(P, jitter=None):
    """Batched PSD Cholesky: unrolled elementwise form for k <= UNROLL_K_MAX
    (the (B, k, k) batched-linalg lowering costs ~100x its flops here),
    ``psd_cholesky`` above it.  Matches ``psd_cholesky`` exactly: sym +
    dtype-matched jitter, NaN on a negative pivot."""
    if jitter is None:
        jitter = default_jitter(P.dtype)
    if P.shape[-1] <= UNROLL_K_MAX:
        return chol_unrolled(sym(P), jitter)
    return psd_cholesky(P, jitter)


def bchol_solve(L, B):
    if L.shape[-1] <= UNROLL_K_MAX:
        return chol_solve_unrolled(L, B)
    return chol_solve(L, B)


def _bsolve_rows(S, V):
    """Row-wise PSD solve: V (..., n, k) rows, S (..., k, k) -> X with
    X[..., i, :] = S^{-1} V[..., i, :].  Used for Lam = (S_ff^{-1} S_yf')'
    and A = (S_lag^{-1} S_cross')' — both are "solve against many rows".

    For small k the factor L broadcasts (..., 1, k, k) against (..., n, k)
    so the unrolled VEC path runs k^2 elementwise ops over (..., n) arrays
    — NOT n Python-unrolled columns (the matrix path would generate n * k^2
    ops for Lam's n = N rows)."""
    if S.shape[-1] <= UNROLL_K_MAX:
        L = bchol(S)
        return chol_solve_unrolled(L[..., None, :, :], V)
    return _bT(chol_solve(psd_cholesky(S), _bT(V)))


# ---------------------------------------------------------------------------
# Param stacking / k-grid padding
# ---------------------------------------------------------------------------

def stack_params(ps: Sequence, dtype=None) -> SSMParams:
    """Stack per-problem params (cpu_ref or jax SSMParams, same shapes)
    into one SSMParams pytree with a leading B axis on every leaf."""
    fields = zip(*((p.Lam, p.A, p.Q, p.R, p.mu0, p.P0) for p in ps))
    return SSMParams(*(jnp.stack([jnp.asarray(x, dtype) for x in xs])
                       for xs in fields))


def unstack_params(p: SSMParams) -> List["cpu_ref.SSMParams"]:
    """Split a batched SSMParams into per-problem NumPy f64 params."""
    leaves = [np.asarray(x, np.float64) for x in p]
    B = leaves[0].shape[0]
    return [cpu_ref.SSMParams(*(lf[b] for lf in leaves)) for b in range(B)]


def pad_params_to_k(p: "cpu_ref.SSMParams", k_max: int) -> "cpu_ref.SSMParams":
    """Pad a k-factor param set to k_max with INERT trailing factors.

    Lam gets zero columns, A a zero row/col block, Q and P0 an identity
    block, mu0 zeros — a state-space model whose trailing factors are
    unit-variance white noise that loads on nothing.  EM preserves this
    structure exactly (zero loadings keep the inactive block out of every
    moment sum), so the padded fit IS the k-factor fit; slice back with
    ``slice_params_to_k``."""
    k = p.Lam.shape[1]
    if k > k_max:
        raise ValueError(f"params have k={k} > k_max={k_max}")
    if k == k_max:
        return p
    m = k_max - k
    N = p.Lam.shape[0]

    def block(M, fill_eye):
        out = np.eye(k_max, dtype=np.float64) if fill_eye else \
            np.zeros((k_max, k_max))
        out[:k, :k] = M
        if fill_eye:
            out[:k, k:] = 0.0
            out[k:, :k] = 0.0
        return out

    return cpu_ref.SSMParams(
        Lam=np.concatenate([np.asarray(p.Lam, np.float64),
                            np.zeros((N, m))], axis=1),
        A=block(p.A, fill_eye=False),
        Q=block(p.Q, fill_eye=True),
        R=np.asarray(p.R, np.float64),
        mu0=np.concatenate([np.asarray(p.mu0, np.float64), np.zeros(m)]),
        P0=block(p.P0, fill_eye=True))


def slice_params_to_k(p: "cpu_ref.SSMParams", k: int) -> "cpu_ref.SSMParams":
    """Drop the inert trailing factors: leading-k slice of every block."""
    return cpu_ref.SSMParams(Lam=p.Lam[:, :k], A=p.A[:k, :k], Q=p.Q[:k, :k],
                             R=p.R, mu0=p.mu0[:k], P0=p.P0[:k, :k])


# ---------------------------------------------------------------------------
# Heterogeneous (N, T) padding: inert series rows + trailing time mask
# ---------------------------------------------------------------------------
#
# The scheduler (dfm_tpu.sched) packs panels of DIFFERENT (N, T, k) into one
# bucket-shaped batched program.  k-padding reuses pad_params_to_k above;
# the two new axes each get an exactly-inert padding story:
#
# - N: pad SERIES are zero-observation / zero-loading / unit-variance rows.
#   A zero Lam row keeps the series out of the k-dim observation reductions
#   (its contribution to b and C is exactly 0), the zero Y column keeps it
#   out of quad_R and the M-step moments, and pinning its R entry to 1.0
#   keeps ldR unchanged (log 1.0 == 0).  The M-step preserves all three
#   invariants exactly: S_yf pad rows are zero sums, so the unrolled
#   triangular solves return exactly-zero Lam rows, and the R update is
#   re-pinned by the hetero mask.
#
# - T: pad STEPS are trailing masked time indices.  At a pad step the
#   filter's ``jnp.where`` selects freeze the state carry entirely (both
#   the filtered moments and the next-step prediction), so the RTS backward
#   corrections through the pad tail are exactly zero and the smoothed
#   trajectory over the real prefix equals the unpadded run's.  The per-t
#   loglik pieces and M-step moment sums are masked; denominators use the
#   per-problem T_act.
#
# Both stories are equality-by-algebra, not approximation: the padded
# problem's loglik trace, convergence decisions and params match the
# unpadded problem's to fp-op-order tolerance (tests/test_sched.py pins
# this per axis).


def pad_panel_to_n(Y: np.ndarray, n_max: int) -> np.ndarray:
    """Pad a (T, N) panel to (T, n_max) with exact-zero inert series
    columns (pair with ``pad_params_to_n``; see the padding notes above)."""
    T, N = Y.shape
    if N > n_max:
        raise ValueError(f"panel has N={N} > n_max={n_max}")
    if N == n_max:
        return Y
    return np.concatenate([Y, np.zeros((T, n_max - N), Y.dtype)], axis=1)


def pad_panel_to_t(Y: np.ndarray, t_max: int) -> np.ndarray:
    """Pad a (T, N) panel to (t_max, N) with exact-zero trailing time steps
    (masked out of the fit via ``Hetero.t_mask``; see the notes above)."""
    T, N = Y.shape
    if T > t_max:
        raise ValueError(f"panel has T={T} > t_max={t_max}")
    if T == t_max:
        return Y
    return np.concatenate([Y, np.zeros((t_max - T, N), Y.dtype)], axis=0)


def pad_params_to_n(p: "cpu_ref.SSMParams", n_max: int) -> "cpu_ref.SSMParams":
    """Pad an N-series param set to n_max with INERT trailing series: zero
    loading rows (out of every k-dim reduction) and unit idiosyncratic
    variance (log 1.0 == 0 keeps ldR unchanged).  The masked M-step
    preserves both exactly; slice back with ``slice_params_to_n``."""
    N = p.Lam.shape[0]
    if N > n_max:
        raise ValueError(f"params have N={N} > n_max={n_max}")
    if N == n_max:
        return p
    m = n_max - N
    k = p.Lam.shape[1]
    return cpu_ref.SSMParams(
        Lam=np.concatenate([np.asarray(p.Lam, np.float64),
                            np.zeros((m, k))], axis=0),
        A=np.asarray(p.A, np.float64), Q=np.asarray(p.Q, np.float64),
        R=np.concatenate([np.asarray(p.R, np.float64), np.ones(m)]),
        mu0=np.asarray(p.mu0, np.float64), P0=np.asarray(p.P0, np.float64))


def slice_params_to_n(p: "cpu_ref.SSMParams", n: int) -> "cpu_ref.SSMParams":
    """Drop the inert trailing series: leading-n slice of Lam rows and R."""
    return cpu_ref.SSMParams(Lam=p.Lam[:n], A=p.A, Q=p.Q, R=p.R[:n],
                             mu0=p.mu0, P0=p.P0)


class Hetero(NamedTuple):
    """Per-problem heterogeneity bundle for a mixed-shape batched fit.

    Every leaf leads with the batch axis, so ONE ``P("batch")`` pytree-
    prefix spec shards the whole bundle in the mesh twins — and per-problem
    stopping knobs (tol / noise floor / iteration cap) ride in the same
    pytree instead of widening the jitted signatures.

    t_mask:      (B, T) compute dtype; 1.0 on real steps, 0.0 on the pad
                 tail (trailing only — step 0 is always real).
    n_mask:      (B, N) compute dtype; 1.0 on real series, 0.0 on pads.
    n_act:       (B,) accum dtype; true series count (loglik constant).
    t_act:       (B,) compute dtype; true step count (M-step denominators).
    tol:         (B,) accum dtype; per-problem relative tolerance.
    noise_floor: (B,) accum dtype; per-problem divergence floor, from the
                 problem's OWN n_obs = T_act * N_act.
    iter_cap:    (B,) int32; per-problem max EM iterations.
    q_scale:     optional (B,) compute dtype; per-lane tuned EM hypers
                 (``estim.tune``'s CV sweep lanes): Q <- q_scale * Q.
    r_scale:     optional (B,); R <- max(r_scale * R, r_floor).
    lam_ridge:   optional (B,); ridge on the loading normal equations.
                 ``None`` (the default) keeps the historical program
                 byte-identical — the hyper ops never trace.
    """

    t_mask: jnp.ndarray
    n_mask: jnp.ndarray
    n_act: jnp.ndarray
    t_act: jnp.ndarray
    tol: jnp.ndarray
    noise_floor: jnp.ndarray
    iter_cap: jnp.ndarray
    q_scale: Optional[jnp.ndarray] = None
    r_scale: Optional[jnp.ndarray] = None
    lam_ridge: Optional[jnp.ndarray] = None


def make_hetero(t_act, n_act, T: int, N: int, *, dtype, tol, iter_cap,
                noise_floor_mult: float = 100.0,
                q_scale=None, r_scale=None, lam_ridge=None) -> Hetero:
    """Build a ``Hetero`` bundle for problems of true sizes (t_act, n_act)
    padded into a (T, N) bucket.  ``tol`` / ``iter_cap`` broadcast from
    scalars or per-problem sequences; per-problem noise floors come from
    ``noise_floor_for(dtype, t*n)`` exactly as a lone fit would compute.
    ``q_scale``/``r_scale``/``lam_ridge`` (scalars or per-lane sequences)
    attach tuned EM hypers per lane; ``None`` (the default) keeps the
    historical programs byte-identical."""
    t_act = np.asarray(t_act, np.int64).reshape(-1)
    n_act = np.asarray(n_act, np.int64).reshape(-1)
    B = len(t_act)
    if len(n_act) != B:
        raise ValueError("t_act and n_act lengths differ")
    if (t_act < 1).any() or (t_act > T).any():
        raise ValueError(f"t_act entries must lie in [1, {T}]")
    if (n_act < 1).any() or (n_act > N).any():
        raise ValueError(f"n_act entries must lie in [1, {N}]")
    dt = jnp.dtype(dtype)
    acc = accum_dtype(dt)
    tols = np.broadcast_to(np.asarray(tol, np.float64), (B,))
    caps = np.broadcast_to(np.asarray(iter_cap, np.int64), (B,))
    nf = np.array([noise_floor_for(dt, int(t * n), mult=noise_floor_mult)
                   for t, n in zip(t_act, n_act)])
    def _lane(v):
        if v is None:
            return None
        return jnp.asarray(np.broadcast_to(np.asarray(v, np.float64),
                                           (B,)), dt)

    return Hetero(
        t_mask=jnp.asarray(np.arange(T)[None, :] < t_act[:, None], dt),
        n_mask=jnp.asarray(np.arange(N)[None, :] < n_act[:, None], dt),
        n_act=jnp.asarray(n_act, acc),
        t_act=jnp.asarray(t_act, dt),
        tol=jnp.asarray(tols, acc),
        noise_floor=jnp.asarray(nf, acc),
        iter_cap=jnp.asarray(caps, jnp.int32),
        q_scale=_lane(q_scale),
        r_scale=_lane(r_scale),
        lam_ridge=_lane(lam_ridge))


# ---------------------------------------------------------------------------
# Batched information-form filter + RTS smoother (template: ssm.info_filter)
# ---------------------------------------------------------------------------

def _batched_obs_stats(Y, Lam, R):
    """Per-problem k-dim observation reductions (unmasked): b (B, T, k),
    C (B, k, k), ldR (B,).  The einsums are the only place N appears."""
    acc = accum_dtype(Y.dtype)
    Rinv = 1.0 / R
    G = Lam * Rinv[..., None]                       # (B, N, k)
    b = jnp.einsum("btn,bnk->btk", Y, G)
    C = jnp.einsum("bnk,bnl->bkl", Lam, G)
    ldR = jnp.sum(jnp.log(R).astype(acc), axis=-1)  # (B,)
    return b, C, ldR


def _batched_info_scan(b_seq, C, A, Q, mu0, P0, t_seq=None):
    """k x k info-form time scan over B problems at once: every op in the
    body is an unrolled/VPU form over the (B,) batch (a batched (B, k, k)
    cholesky or dot_general here would be the whole wall — PERF.md 6a).

    b_seq is TIME-major (T, B, k); C/A/Q are static per problem (B, k, k).
    Returns time-major (x_pred, P_pred, x_filt, P_filt, logdetG).

    ``t_seq`` (time-major (T, B), 1.0 real / 0.0 pad — ``Hetero.t_mask``
    transposed) freezes a problem's state carry at its trailing pad steps:
    both the filtered moments and the next-step prediction hold the values
    entering the first pad step, so the RTS backward corrections through
    the pad tail are EXACTLY zero (the smoothed real prefix is untouched)
    and nothing in the frozen region can overflow.  ``None`` leaves the
    traced program byte-identical to the homogeneous one."""
    k = A.shape[-1]
    I_k = jnp.eye(k, dtype=b_seq.dtype)

    def step(carry, inp):
        b_t = inp if t_seq is None else inp[0]
        x, P = carry                                # (B, k), (B, k, k)
        Lp = bchol(P)
        CL = matmul_vpu(C, Lp)
        G = I_k + matmul_vpu(_bT(Lp), CL)           # >= I: no jitter needed
        Lg = bchol(G, jitter=0.0)
        P_f = sym(matmul_vpu(Lp, bchol_solve(Lg, _bT(Lp))))
        u = b_t - matvec_vpu(C, x)
        x_f = x + matvec_vpu(P_f, u)
        if t_seq is not None:
            s = inp[1] > 0                          # (B,) real-step mask
            x_f = jnp.where(s[:, None], x_f, x)
            P_f = jnp.where(s[:, None, None], P_f, P)
        x_n = matvec_vpu(A, x_f)
        P_n = sym(matmul_vpu(matmul_vpu(A, P_f), _bT(A)) + Q)
        if t_seq is not None:
            x_n = jnp.where(s[:, None], x_n, x)
            P_n = jnp.where(s[:, None, None], P_n, P)
        return (x_n, P_n), (x, P, x_f, P_f, chol_logdet(Lg))

    seq = b_seq if t_seq is None else (b_seq, t_seq)
    return lax.scan(step, (mu0, P0), seq)[1]


def _mask_t(a, t_mask):
    """Zero a batch-major (B, T, ...) tensor at pad steps via where-select
    (a select, not a multiply: pad-step junk must not reach the sums even
    as 0 * inf)."""
    m = t_mask.reshape(t_mask.shape + (1,) * (a.ndim - 2)) > 0
    return jnp.where(m, a, jnp.zeros((), a.dtype))


def _batched_loglik(Y, p, b, C, ldR, x_pred, P_filt, logdetG, hetero=None):
    """Per-problem loglik (B,), same cancellation-free assembly as
    ``info_filter.loglik_from_terms``: residual-pass quad_R, U from stats,
    U'P_f U in compute dtype, (T,)-sized pieces assembled in accum dtype.

    With ``hetero``, the constant uses the per-problem true series count
    (pad series contribute exact zeros to every other piece — zero Lam
    rows, zero Y columns, log R = log 1 = 0) and the per-t pieces are
    where-masked to the real time prefix."""
    acc = accum_dtype(Y.dtype)
    V = Y - jnp.einsum("btk,bnk->btn", x_pred, p.Lam)
    quad_R = jnp.sum((V * (V / p.R[:, None, :])).astype(acc), axis=-1)
    U = b - jnp.einsum("bkl,btl->btk", C, x_pred)   # C symmetric
    upu = jnp.einsum("btk,btkl,btl->bt", U, P_filt, U)
    n_const = (float(Y.shape[-1]) if hetero is None
               else hetero.n_act[:, None])
    lls = -0.5 * (n_const * _LOG2PI + ldR[:, None]
                  + logdetG.astype(acc) + quad_R - upu.astype(acc))
    if hetero is not None:
        lls = jnp.where(hetero.t_mask > 0, lls, jnp.zeros((), acc))
    return jnp.sum(lls, axis=1)


def _batched_filter(Y, p, hetero=None):
    """Info-form filter over the batch: returns (loglik (B,), batch-major
    (x_pred, P_pred, x_filt, P_filt) with shapes (B, T, ...))."""
    b, C, ldR = _batched_obs_stats(Y, p.Lam, p.R)
    t_seq = None if hetero is None else jnp.moveaxis(hetero.t_mask, 1, 0)
    outs = _batched_info_scan(jnp.moveaxis(b, 1, 0), C, p.A, p.Q,
                              p.mu0, p.P0, t_seq=t_seq)
    xp, Pp, xf, Pf, ldG = (jnp.moveaxis(o, 0, 1) for o in outs)
    ll = _batched_loglik(Y, p, b, C, ldR, xp, Pf, ldG, hetero=hetero)
    return ll, (xp, Pp, xf, Pf)


def _batched_rts(xp, Pp, xf, Pf, A):
    """Batched RTS smoother (inputs batch-major (B, T, ...)); mirrors
    ``ssm.kalman.rts_smoother`` with the scan body in VPU forms.
    Returns (x_sm (B, T, k), P_sm (B, T, k, k), P_lag (B, T, k, k))."""
    B, T, k = xf.shape
    Pp_next = Pp[:, 1:]
    APf = jnp.einsum("bij,btjk->btik", A, Pf[:, :-1])
    L = bchol(Pp_next)
    J = _bT(bchol_solve(L, APf))                    # (B, T-1, k, k)

    def step(carry, inp):
        x_next, P_next = carry
        x_f, P_f, x_p_next, P_p_next, J_t = inp
        x_s = x_f + matvec_vpu(J_t, x_next - x_p_next)
        P_s = sym(P_f + matmul_vpu(matmul_vpu(J_t, P_next - P_p_next),
                                   _bT(J_t)))
        return (x_s, P_s), (x_s, P_s)

    tm = lambda a: jnp.moveaxis(a, 1, 0)            # batch-major -> time-major
    seq = (tm(xf[:, :-1]), tm(Pf[:, :-1]), tm(xp[:, 1:]), tm(Pp_next), tm(J))
    _, (xs, Ps) = lax.scan(step, (xf[:, -1], Pf[:, -1]), seq, reverse=True)
    x_sm = jnp.concatenate([jnp.moveaxis(xs, 0, 1), xf[:, -1:]], axis=1)
    P_sm = jnp.concatenate([jnp.moveaxis(Ps, 0, 1), Pf[:, -1:]], axis=1)
    P_lag = jnp.concatenate(
        [jnp.zeros((B, 1, k, k), xf.dtype),
         jnp.einsum("btij,btkj->btik", P_sm[:, 1:], J)], axis=1)
    return x_sm, P_sm, P_lag


# ---------------------------------------------------------------------------
# Batched M-step (closed forms of em._m_step, unmasked, per problem)
# ---------------------------------------------------------------------------

def batched_m_step(Y, x_sm, P_sm, P_lag, p: SSMParams, cfg: EMConfig, Ysq,
                   hetero=None):
    """Per-problem closed-form M-step from batched smoother moments.

    Same algebra as ``em.moment_sums`` + ``mstep_rows`` +
    ``mstep_dynamics_sums``; the k x k solves go through ``_bsolve_rows``
    (unrolled) and the k x k products through ``matmul_vpu``.

    With ``hetero`` (mixed-shape buckets), the moment sums run over the
    where-masked real time prefix — the ``last`` terms select each
    problem's own final step via the one-hot ``t_mask[t] - t_mask[t+1]`` —
    the denominators use the per-problem T_act, pad series keep exactly
    zero loading rows (their S_yf rows are zero sums through the zero-RHS
    triangular solves), and pad R entries are re-pinned to 1.0."""
    if hetero is None:
        T = Y.shape[1]
        x_m, P_m, Pl_m = x_sm, P_sm, P_lag
        last = P_sm[:, -1] + jnp.einsum("bi,bj->bij",
                                        x_sm[:, -1], x_sm[:, -1])
        T_r, T_q = float(T), float(T - 1)
    else:
        tm = hetero.t_mask
        x_m = _mask_t(x_sm, tm)
        P_m = _mask_t(P_sm, tm)
        Pl_m = _mask_t(P_lag, tm)
        # One-hot of each problem's last real step (padding is trailing).
        lw = tm - jnp.concatenate([tm[:, 1:], jnp.zeros_like(tm[:, :1])],
                                  axis=1)
        x_last = jnp.einsum("bt,bti->bi", lw, x_m)
        last = (jnp.einsum("bt,btij->bij", lw, P_m)
                + jnp.einsum("bi,bj->bij", x_last, x_last))
        T_r = hetero.t_act[:, None]
        T_q = (hetero.t_act - 1.0)[:, None, None]
    S_ff = P_m.sum(1) + jnp.einsum("bti,btj->bij", x_m, x_m)
    first = P_sm[:, 0] + jnp.einsum("bi,bj->bij", x_sm[:, 0], x_sm[:, 0])
    S_lag, S_cur = S_ff - last, S_ff - first
    S_cross = Pl_m[:, 1:].sum(1) + jnp.einsum("bti,btj->bij",
                                              x_m[:, 1:], x_m[:, :-1])
    S_yf = jnp.einsum("btn,btk->bnk", Y, x_m)       # (B, N, k)
    # Optional per-lane tuned hypers (estim.tune CV sweep lanes).  With a
    # ridge the OLS shortcut (Ysq - Lam.S_yf)/T for R is biased, so the
    # ridge branch computes the full residual quadratic — exactly as
    # ``em.mstep_rows`` does.  None (the default) traces the historical
    # program byte-identically.
    ridge = None if hetero is None else hetero.lam_ridge
    if ridge is not None:
        k = S_ff.shape[-1]
        eye_k = jnp.eye(k, dtype=S_ff.dtype)
        Lam = _bsolve_rows(S_ff + ridge[:, None, None] * eye_k, S_yf)
        quad = (Ysq - 2.0 * jnp.einsum("bnk,bnk->bn", Lam, S_yf)
                + jnp.einsum("bnk,bkl,bnl->bn", Lam, S_ff, Lam))
        R = jnp.maximum(quad / T_r, cfg.r_floor)
    else:
        Lam = _bsolve_rows(S_ff, S_yf)
        R = jnp.maximum(
            (Ysq - jnp.einsum("bnk,bnk->bn", Lam, S_yf)) / T_r,
            cfg.r_floor)
    if hetero is not None and hetero.r_scale is not None:
        R = jnp.maximum(hetero.r_scale[:, None] * R, cfg.r_floor)
    if hetero is not None:
        nm = hetero.n_mask > 0
        Lam = jnp.where(nm[..., None], Lam, jnp.zeros((), Lam.dtype))
        R = jnp.where(nm, R, jnp.ones((), R.dtype))
    A, Q = p.A, p.Q
    if cfg.estimate_A:
        A = _bsolve_rows(S_lag, S_cross)
        if cfg.estimate_Q:
            Q = sym((S_cur - matmul_vpu(A, _bT(S_cross))) / T_q)
    elif cfg.estimate_Q:
        Q = sym((S_cur - matmul_vpu(A, _bT(S_cross))
                 - matmul_vpu(S_cross, _bT(A))
                 + matmul_vpu(matmul_vpu(A, S_lag), _bT(A))) / T_q)
    if hetero is not None and hetero.q_scale is not None:
        Q = hetero.q_scale[:, None, None] * Q
    mu0, P0 = p.mu0, p.P0
    if cfg.estimate_init:
        mu0, P0 = x_sm[:, 0], sym(P_sm[:, 0])
    return SSMParams(Lam, A, Q, R, mu0, P0)


# ---------------------------------------------------------------------------
# Serving twins: elementwise-masked batched filter/M-step + ragged append
# (the B-way batch of serve/session.py's capacity-padded program — every
# formula mirrors the lone masked path op-for-op so a fleet lane pins to
# the same tenant's lone NowcastSession)
# ---------------------------------------------------------------------------

def batched_ragged_append(Ybuf, Wbuf, rows, rmask, t_cur):
    """In-graph ragged per-tenant row append: scatter each tenant's
    ``rows[b, :n_new_b]`` into its capacity-padded panel slot starting at
    its OWN live length ``t_cur[b]`` — one executable regardless of which
    tenants appended or how many rows each brought.

    Exactness across the seams (pinned by tests/test_fleet.py): rows past
    each tenant's true count arrive exact-zero with an exact-zero row
    mask (the host pads them that way), so they land zeros on the already
    -zero pad region — value-inert, bit-identical to the lone session's
    ``Ybuf.at[idx].set(rows, mode="drop")`` which performs the SAME
    per-tenant scatter.  A tenant with ``n_new == 0`` (inactive this
    tick, or a pure re-forecast query) writes only zeros-on-zeros.
    ``mode="drop"`` discards indices past capacity, exactly as the lone
    session's scatter does.

    Ybuf/Wbuf (B, T_cap, N); rows/rmask (B, r_max, N); t_cur (B,) int32.
    """
    r_max = rows.shape[1]
    off = jnp.arange(r_max, dtype=t_cur.dtype)

    def one(buf, wbuf, r, m, t0):
        idx = t0 + off
        return (buf.at[idx].set(r, mode="drop"),
                wbuf.at[idx].set(m, mode="drop"))

    return jax.vmap(one)(Ybuf, Wbuf, rows, rmask, t_cur)


def _batched_obs_stats_masked(Y, W, Lam, R):
    """Per-tenant TIME-VARYING info-form observation reductions for
    elementwise-masked panels: b (B, T, k), C (B, T, k, k), n (B, T),
    ldR (B, T).  The (B,)-batched twin of the masked branch of
    ``ssm.info_filter.obs_stats`` — W encodes everything (missing cells,
    the dead capacity tail past each tenant's live length, and inert
    N-pad series), so no separate shape masks are needed: a fully-masked
    step contributes b_t = 0, C_t = 0, n_t = 0, ldR_t = 0 and the filter
    step degenerates to the exact prediction-only update."""
    acc = accum_dtype(Y.dtype)
    Yw = W * jnp.nan_to_num(Y)
    Rinv = 1.0 / R
    logR = jnp.log(R).astype(acc)
    G = Lam * Rinv[..., None]                       # (B, N, k)
    b = jnp.einsum("btn,bnk->btk", Yw, G)
    C = jnp.einsum("bnk,btn,bn,bnl->btkl", Lam, W, Rinv, Lam)
    n = jnp.sum(W, axis=-1).astype(acc)             # (B, T)
    ldR = jnp.einsum("btn,bn->bt", W.astype(acc), logR)
    return b, C, n, ldR


def _batched_info_scan_tv(b_seq, C_seq, A, Q, mu0, P0):
    """Info-form time scan with TIME-VARYING per-step stats (B-batched
    twin of ``ssm.info_filter.info_scan`` with a time-varying C_t), every
    op an unrolled/VPU form over (B,).

    NO freeze machinery here, deliberately: the lone session filter runs
    masked updates over the FULL capacity buffer — a dead step has
    C_t = 0 (G = I, P_f = P_p, x_f = x_p: an exact no-op update) but the
    prediction still advances through the tail, and the RTS backward
    corrections through that tail are exactly zero by induction, leaving
    the live prefix exact.  Reproducing that (rather than ``Hetero``'s
    carry-freeze, which changes the prediction semantics) is what pins a
    fleet lane bit-for-bit to its lone session.

    b_seq (T, B, k) / C_seq (T, B, k, k) time-major; returns time-major
    (x_pred, P_pred, x_filt, P_filt, logdetG)."""
    k = A.shape[-1]
    I_k = jnp.eye(k, dtype=b_seq.dtype)

    def step(carry, inp):
        b_t, C_t = inp
        x, P = carry                                # (B, k), (B, k, k)
        Lp = bchol(P)
        CL = matmul_vpu(C_t, Lp)
        G = I_k + matmul_vpu(_bT(Lp), CL)           # >= I: no jitter needed
        Lg = bchol(G, jitter=0.0)
        P_f = sym(matmul_vpu(Lp, bchol_solve(Lg, _bT(Lp))))
        u = b_t - matvec_vpu(C_t, x)
        x_f = x + matvec_vpu(P_f, u)
        x_n = matvec_vpu(A, x_f)
        P_n = sym(matmul_vpu(matmul_vpu(A, P_f), _bT(A)) + Q)
        return (x_n, P_n), (x, P, x_f, P_f, chol_logdet(Lg))

    return lax.scan(step, (mu0, P0), (b_seq, C_seq))[1]


def _batched_loglik_masked(Y, W, p, b, C, n, ldR, x_pred, P_filt, logdetG):
    """Per-tenant loglik (B,) for the elementwise-masked filter — the
    batched twin of ``info_filter.loglik_from_terms`` fed by the masked
    ``quad_local``/``u_from_stats``: residual-pass quad_R, U from the
    time-varying stats, U'P_f U in compute dtype, assembly in accum
    dtype.  Fully-masked steps contribute exact zeros, so summing over
    the full capacity axis equals the live-prefix sum."""
    acc = accum_dtype(Y.dtype)
    V = W * jnp.nan_to_num(Y - jnp.einsum("btk,bnk->btn", x_pred, p.Lam))
    quad_R = jnp.sum((V * (V / p.R[:, None, :])).astype(acc), axis=-1)
    U = b - jnp.einsum("btkl,btl->btk", C, x_pred)
    upu = jnp.einsum("btk,btkl,btl->bt", U.astype(P_filt.dtype), P_filt,
                     U.astype(P_filt.dtype))
    lls = -0.5 * (n * _LOG2PI + ldR + logdetG.astype(acc) + quad_R
                  - upu.astype(acc))
    return jnp.sum(lls, axis=1)


def batched_filter_masked(Y, W, p):
    """Elementwise-masked info-form filter over the batch: returns
    (loglik (B,), batch-major (x_pred, P_pred, x_filt, P_filt)).  The
    B-way twin of ``info_filter.info_filter(Y, p, mask=W)`` as the serve
    session drives it (capacity-padded panel, W zero past each tenant's
    live length)."""
    b, C, n, ldR = _batched_obs_stats_masked(Y, W, p.Lam, p.R)
    tm = lambda a: jnp.moveaxis(a, 1, 0)            # noqa: E731
    outs = _batched_info_scan_tv(tm(b), tm(C), p.A, p.Q, p.mu0, p.P0)
    xp, Pp, xf, Pf, ldG = (jnp.moveaxis(o, 0, 1) for o in outs)
    ll = _batched_loglik_masked(Y, W, p, b, C, n, ldR, xp, Pf, ldG)
    return ll, (xp, Pp, xf, Pf)


def batched_m_step_masked(Y, W, x_sm, P_sm, P_lag, p: SSMParams,
                          cfg: EMConfig, t_new):
    """Closed-form masked M-step per tenant — the batched twin of
    ``em._m_step(Y, mask, ..., n_steps=t_new)`` with TRACED per-tenant
    live lengths ``t_new`` (B,) int32: observation rows follow
    ``em.mstep_rows``'s masked path (never-observed series get identity
    S_ff and thus exact-zero loading rows — which is also what keeps
    N-pad series inert), dynamics follow ``em.mstep_dynamics_tmasked``
    with per-tenant {0,1} time weights and a traced ``t_new - 1``
    transition divisor, so ONE executable serves every live-length
    vector a fleet bucket can reach."""
    dt = Y.dtype
    B, T, N = Y.shape
    k = p.A.shape[-1]
    Wz = W.astype(dt)
    Yz = jnp.where(Wz > 0, jnp.nan_to_num(Y), 0.0)
    EffT = P_sm + jnp.einsum("bti,btj->btij", x_sm, x_sm)   # (B, T, k, k)
    cross = P_lag[:, 1:] + jnp.einsum("bti,btj->btij",
                                      x_sm[:, 1:], x_sm[:, :-1])
    # -- observation rows (em.mstep_rows, masked branch) -----------------
    S_yf_i = jnp.einsum("btn,btk->bnk", Yz, x_sm)           # (B, N, k)
    S_ff_i = jnp.einsum("btn,btkl->bnkl", Wz, EffT)         # (B, N, k, k)
    never = (Wz.sum(1) == 0)[..., None, None]
    S_ff_i = jnp.where(never, jnp.eye(k, dtype=dt), S_ff_i)
    Lam = bchol_solve(bchol(S_ff_i), S_yf_i)                # (B, N, k)
    counts = jnp.maximum(Wz.sum(1), 1.0)
    resid_sq = jnp.einsum(
        "btn,btn->bn", Wz,
        (Yz - jnp.einsum("btk,bnk->btn", x_sm, Lam)) ** 2)
    PV = jnp.einsum("btn,btkl->bnkl", Wz, P_sm)
    smear = jnp.einsum("bnk,bnkl,bnl->bn", Lam, PV, Lam)
    R = jnp.maximum((resid_sq + smear) / counts, cfg.r_floor)
    # -- dynamics (em.mstep_dynamics_tmasked, per-tenant weights) --------
    A, Q = p.A, p.Q
    if cfg.estimate_A or cfg.estimate_Q:
        t_idx = jnp.arange(T)[None, :]
        tn = t_new[:, None]
        w_lag = (t_idx < tn - 1).astype(dt)
        w_cur = ((t_idx >= 1) & (t_idx < tn)).astype(dt)
        w_x = (jnp.arange(T - 1)[None, :] < tn - 1).astype(dt)
        S_lag = jnp.einsum("bt,btkl->bkl", w_lag, EffT)
        S_cur = jnp.einsum("bt,btkl->bkl", w_cur, EffT)
        S_cross = jnp.einsum("bt,btkl->bkl", w_x, cross)
        T_q = (t_new.astype(dt) - 1.0)[:, None, None]
        if cfg.estimate_A:
            A = _bsolve_rows(S_lag, S_cross)
            if cfg.estimate_Q:
                Q = sym((S_cur - matmul_vpu(A, _bT(S_cross))) / T_q)
        elif cfg.estimate_Q:
            Q = sym((S_cur - matmul_vpu(A, _bT(S_cross))
                     - matmul_vpu(S_cross, _bT(A))
                     + matmul_vpu(matmul_vpu(A, S_lag), _bT(A))) / T_q)
    mu0, P0 = p.mu0, p.P0
    if cfg.estimate_init:
        mu0, P0 = x_sm[:, 0], sym(P_sm[:, 0])
    return SSMParams(Lam, A, Q, R, mu0, P0)


# ---------------------------------------------------------------------------
# Fused chunk: n EM iterations with in-carry per-problem convergence
# ---------------------------------------------------------------------------

# Per-problem progress states carried through the scan.
RUNNING, CONVERGED, DIVERGED, PADDED = 0, 1, 2, 3
STATE_NAMES = {RUNNING: "running", CONVERGED: "converged",
               DIVERGED: "diverged", PADDED: "padded"}


def _bmask(m, x):
    """Broadcast a (B,) bool against an arbitrary (B, ...) leaf."""
    return m.reshape(m.shape + (1,) * (x.ndim - 1))


def _em_chunk_core(Y, carry, tol, noise_floor, cfg: EMConfig, n_iters: int,
                   with_metrics: bool = False, n_active=None, hetero=None):
    """n fused EM iterations over the batch.  Pure (jit/shard_map-able).

    carry = (p, p_prev, ll_prev (B,), state (B,) int32, n_lls (B,) int32):
    ``p`` embodies the updates so far, ``p_prev`` the params ENTERING the
    previous active iteration (the divergence roll-back target), ``state``
    the per-problem progress, ``n_lls`` the trace length (the host slices
    each problem's loglik column to this).  Frozen problems still compute
    (no early exit from a fused program) but their carry is held by
    ``jnp.where`` selects — the decision logic reproduces ``em_progress``
    exactly, including NaN -> continue.

    ``with_metrics`` (static) additionally scans out a per-iteration
    (B, 3) [loglik, delta, max param-update] block in f64 — a device-side
    convergence record with zero extra dispatches.  The flag only ADDS
    outputs; the default program's traced ops are untouched.

    ``n_active`` (traced scalar, bucketed mode): iterations at index
    >= n_active freeze EVERY problem — the same in-carry hold the state
    machine already performs for converged problems — so a STATIC
    ``n_iters`` bucket serves every tail-chunk length (the host slices
    the scanned outputs to the active prefix).  ``None`` (default) leaves
    the traced program untouched.

    ``hetero`` (a ``Hetero`` bundle, static-None by default): mixed-shape
    bucket mode.  The filter/loglik/M-step run their masked forms, the
    per-problem tol / noise floor OVERRIDE the scalar arguments, and each
    problem additionally freezes once its trace reaches its own
    ``iter_cap`` — short jobs stop early inside the bucket with exactly
    the stopping semantics a lone fit of that job would have."""
    if hetero is not None:
        tol = hetero.tol                             # (B,) overrides
        noise_floor = hetero.noise_floor
    Ysq = jnp.einsum("btn,btn->bn", Y, Y)           # iteration-invariant

    def body(c, j):
        p, p_prev, ll_prev, state, n_lls = c
        ll, (xp, Pp, xf, Pf) = _batched_filter(Y, p, hetero)
        x_sm, P_sm, P_lag = _batched_rts(xp, Pp, xf, Pf, p.A)
        p_new = batched_m_step(Y, x_sm, P_sm, P_lag, p, cfg, Ysq,
                               hetero=hetero)

        active = state == RUNNING
        if n_active is not None:
            active = active & (j < n_active)
        if hetero is not None:
            active = active & (n_lls < hetero.iter_cap)
        n_new = n_lls + active.astype(n_lls.dtype)
        # em_progress on the device: rel-tol convergence, noise-floor
        # divergence, plateau-drop convergence; <2 lls -> continue.
        rel = (ll - ll_prev) / jnp.maximum(jnp.abs(ll_prev), 1e-12)
        drop = ll_prev - ll
        conv_rel = (tol > 0) & (jnp.abs(rel) < tol)
        # Hyper-scaled lanes (estim.tune sweep) are generalized EM: their
        # fixed point is not a loglik stationary point, so a drop is the
        # plateau stop, not a divergence (host twin: em_progress's
        # monotone=False rule).  Hetero's hyper fields are pytree
        # structure, so hyper-free programs stay byte-identical.
        monotone = hetero is None or (hetero.q_scale is None
                                      and hetero.r_scale is None
                                      and hetero.lam_ridge is None)
        diverged = (drop > noise_floor) & monotone
        conv_plateau = (drop > 0) & (tol > 0)
        prog = jnp.where(conv_rel, CONVERGED,
                         jnp.where(diverged, DIVERGED,
                                   jnp.where(conv_plateau, CONVERGED,
                                             RUNNING)))
        prog = jnp.where(n_new < 2, RUNNING, prog).astype(state.dtype)
        new_state = jnp.where(active, prog, state)

        adv = active & (prog != DIVERGED)   # take this iteration's update
        roll = active & (prog == DIVERGED)  # roll back to pre-drop entry
        p_out = jax.tree_util.tree_map(
            lambda new, prv, cur: jnp.where(
                _bmask(adv, new), new,
                jnp.where(_bmask(roll, cur), prv, cur)),
            p_new, p_prev, p)
        p_prev_out = jax.tree_util.tree_map(
            lambda cur, prv: jnp.where(_bmask(active, cur), cur, prv),
            p, p_prev)
        ll_prev_out = jnp.where(active, ll, ll_prev)
        c_out = (p_out, p_prev_out, ll_prev_out, new_state, n_new)
        if with_metrics:
            dl = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda new, cur: jnp.max(
                    jnp.abs(new - cur).reshape(new.shape[0], -1), axis=1),
                p_out, p))
            dparam = jnp.max(jnp.stack(dl), axis=0)        # (B,)
            ll64 = jnp.asarray(ll, jnp.float64)
            row = jnp.stack(
                [ll64, ll64 - jnp.asarray(ll_prev, jnp.float64),
                 jnp.asarray(dparam, jnp.float64)], axis=-1)  # (B, 3)
            return c_out, (ll, row)
        return c_out, ll

    xs = None if n_active is None else jnp.arange(n_iters)
    return lax.scan(body, carry, xs, length=n_iters)


@partial(jax.jit, static_argnames=("cfg", "n_iters"))
def _em_chunk_impl(Y, carry, tol, noise_floor, cfg, n_iters, hetero=None):
    return _em_chunk_core(Y, carry, tol, noise_floor, cfg, n_iters,
                          hetero=hetero)


@partial(jax.jit, static_argnames=("cfg", "n_iters"))
def _em_chunk_metrics_impl(Y, carry, tol, noise_floor, cfg, n_iters,
                           hetero=None):
    return _em_chunk_core(Y, carry, tol, noise_floor, cfg, n_iters,
                          with_metrics=True, hetero=hetero)


@partial(jax.jit, static_argnames=("cfg", "n_iters"))
def _em_chunk_capped_impl(Y, carry, tol, noise_floor, n_active, cfg,
                          n_iters, hetero=None):
    """Bucketed chunk: STATIC ``n_iters`` fused length, TRACED ``n_active``
    cap — one executable serves every tail-chunk length (pipeline
    bucketing; the default program above stays byte-identical)."""
    return _em_chunk_core(Y, carry, tol, noise_floor, cfg, n_iters,
                          n_active=n_active, hetero=hetero)


@partial(jax.jit, static_argnames=("cfg", "n_iters"))
def _em_chunk_capped_metrics_impl(Y, carry, tol, noise_floor, n_active, cfg,
                                  n_iters, hetero=None):
    return _em_chunk_core(Y, carry, tol, noise_floor, cfg, n_iters,
                          with_metrics=True, n_active=n_active,
                          hetero=hetero)


def _smooth_core(Y, p, hetero=None):
    """Batched filter+smoother -> (x_sm (B, T, k), P_sm (B, T, k, k))."""
    _, (xp, Pp, xf, Pf) = _batched_filter(Y, p, hetero)
    x_sm, P_sm, _ = _batched_rts(xp, Pp, xf, Pf, p.A)
    return x_sm, P_sm


_smooth_impl = jax.jit(_smooth_core)


# ---------------------------------------------------------------------------
# Host chunk driver: dispatch retry + per-problem health
# ---------------------------------------------------------------------------

def run_batched_em(Y, p0: SSMParams, cfg: EMConfig, max_iters: int,
                   tol: float, fused_chunk: int = 8, policy=None,
                   scan_impl=None, state0=None, with_metrics: bool = False,
                   scan_impl_metrics=None, pipeline=None,
                   scan_impl_capped=None, scan_impl_capped_metrics=None,
                   hetero=None):
    """Chunked host driver around the fused batched-EM program.

    ``Y`` (B, T, N) and ``p0`` batched (device or host arrays).  Runs
    ceil(max_iters / fused_chunk) dispatches at most, stopping as soon as
    every problem's in-carry state leaves RUNNING.  ``policy`` (a
    ``robust.RobustPolicy``) wraps each dispatch in the guard's retry/
    backoff seam; dispatch events are recorded on EVERY problem's health
    (one program serves them all).  ``scan_impl`` overrides the jitted
    chunk program (the sharded driver passes its shard_map'd twin);
    ``state0`` overrides the initial per-problem state vector (the sharded
    driver marks its pad problems PADDED so they freeze from the start).

    ``pipeline`` (``pipeline.PipelineConfig`` / int depth / None): depth d
    issues d chunks speculatively — chaining the DEVICE carries, so no
    transfer is needed between issues — then performs ONE blocking
    device->host state/loglik pull per round (the early-exit check runs up
    to d-1 chunks behind; speculative chunks past an all-frozen state are
    inert by the in-carry freeze, so results match serial exactly).
    ``bucket=True`` routes every chunk through the capped twin program
    (static fused length, traced ``n_active``) so one executable serves
    every tail length; ``scan_impl_capped`` / ``scan_impl_capped_metrics``
    override it the way ``scan_impl`` does (bucketing silently degrades
    when a custom ``scan_impl`` comes without its capped twin).

    Returns (params (batched SSMParams), lls_list (per-problem trace
    arrays), converged (B,) bool, p_iters (B,) int, healths (B,) list);
    with ``with_metrics`` a 6th element — the (total_iters, B, 3) f64
    per-iteration [loglik, delta, max param-update] block scanned out of
    the chunk programs (``scan_impl_metrics`` overrides the metrics twin
    the way ``scan_impl`` overrides the default program).

    ``hetero`` (a ``Hetero`` bundle): mixed-shape bucket mode — the chunk
    programs run their masked forms, each problem's tol / noise floor /
    iteration cap come from the bundle (the scalar ``tol`` argument is
    ignored), and the early-exit check also counts cap-reached problems
    as done.  Custom ``scan_impl*`` twins must accept the ``hetero``
    keyword (the sharded twins do); the default path is untouched when
    ``hetero`` is None.
    """
    from ..pipeline import resolve_pipeline
    B, T, N = Y.shape
    Yj = jnp.asarray(Y)
    dt = Yj.dtype
    acc = accum_dtype(dt)
    nf = noise_floor_for(dt, T * N, mult=cfg.noise_floor_mult)
    nf_b = (np.full((B,), float(nf)) if hetero is None
            else np.asarray(hetero.noise_floor, np.float64))
    cap_h = None if hetero is None else np.asarray(hetero.iter_cap)
    hk = {} if hetero is None else {"hetero": hetero}
    if with_metrics:
        impl = (scan_impl_metrics if scan_impl_metrics is not None
                else _em_chunk_metrics_impl)
        impl_c = (scan_impl_capped_metrics
                  if scan_impl_metrics is not None
                  else _em_chunk_capped_metrics_impl)
    else:
        impl = scan_impl if scan_impl is not None else _em_chunk_impl
        impl_c = (scan_impl_capped if scan_impl is not None
                  else _em_chunk_capped_impl)
    pipe = resolve_pipeline(pipeline)
    n_bucket = max(1, int(fused_chunk))
    use_bucket = pipe.bucket and impl_c is not None
    tol_j = jnp.asarray(tol, acc)
    nf_j = jnp.asarray(nf, acc)
    state = (jnp.zeros((B,), jnp.int32) if state0 is None
             else jnp.asarray(state0, jnp.int32))
    carry = (p0, p0, jnp.zeros((B,), acc), state, jnp.zeros((B,), jnp.int32))

    tr = current_tracer()
    prog = getattr(impl, "trace_name", "batched_em_chunk")
    prog_key = getattr(impl, "trace_key", "")
    engine = getattr(impl, "trace_engine", "batched_em")
    state_prev_h = np.asarray(state) if tr is not None else None

    traces: list = []
    metric_chunks: list = []
    dispatch_events: list = []
    n_chunks = 0
    n_retries = 0
    it = 0
    retry_exc = policy.retry_exceptions if policy is not None else ()

    def _key(n):
        parts = [Yj, prog_key,
                 f"iters{n_bucket}b" if use_bucket else f"iters{n}"]
        if hetero is not None:
            parts.append("het")
        return shape_key(*parts)

    def _payload(n):
        d = {"n_iters": int(n)}
        if use_bucket:
            d["bucket"] = n_bucket
        return d

    def _call(carry_in, n):
        if use_bucket:
            return impl_c(Yj, carry_in, tol_j, nf_j,
                          jnp.asarray(n, jnp.int32), cfg, n_bucket, **hk)
        return impl(Yj, carry_in, tol_j, nf_j, cfg, n, **hk)

    def _pull(new_carry, out, n):
        lls, mets = out if with_metrics else (out, None)
        # The small state transfer is the execution barrier on this device
        # class (block_until_ready is a no-op on axon).
        state_h = np.asarray(new_carry[3])
        lls_h = np.asarray(lls, np.float64)[:n]     # bucketed pad sliced off
        mets_h = (np.asarray(mets, np.float64)[:n]
                  if mets is not None else None)
        return state_h, lls_h, mets_h

    # Unified-guard seams (robust.dispatch): the policy's wrap_dispatch
    # test hook and watchdog deadline apply to the bucket program's
    # dispatch + blocking pull exactly as they do to the fused fit and
    # session update.  Both are None on the default policy — the wrapped
    # call is then the original callable and no watchdog thread exists.
    wrap = policy.wrap_dispatch if policy is not None else None
    deadline = policy.dispatch_deadline_s if policy is not None else None

    def _dispatch_block(carry_in, n, a):
        def _go():
            if tr is None:
                new_carry, out = _call(carry_in, n)
                return (new_carry,) + _pull(new_carry, out, n)
            with tr.dispatch(prog, _key(n), barrier=True, attempt=a,
                             **_payload(n)):
                new_carry, out = _call(carry_in, n)
                res = _pull(new_carry, out, n)
            return (new_carry,) + res
        run = _go if wrap is None else wrap(_go)
        return _call_with_deadline(run, deadline)

    def _attempt_chunk(carry_in, n, pre=None, first_exc=None):
        """The guard's dispatch retry/backoff seam.  ``pre`` short-circuits
        attempt 0 with a pipeline-drained result; ``first_exc`` replays an
        issue/drain-time exception AS attempt 0 so health records and retry
        counts match the serial driver exactly."""
        nonlocal n_retries
        attempts = 1 + (policy.dispatch_retries if policy is not None else 0)
        delay = policy.backoff_base if policy is not None else 0.0
        for a in range(attempts):
            try:
                if first_exc is not None:
                    e, first_exc = first_exc, None
                    raise e
                if pre is not None:
                    res, pre = pre, None
                    return res
                return _dispatch_block(carry_in, n, a)
            except retry_exc as e:
                last = a == attempts - 1
                ev = HealthEvent(
                    chunk=n_chunks, iteration=it, kind="dispatch_error",
                    detail=f"{type(e).__name__}: {e}"[:200],
                    action="abort" if last else "retried",
                    t=time.perf_counter(), engine=engine,
                    backoff_s=0.0 if last else float(delay))
                dispatch_events.append(ev)
                if tr is not None:
                    # Emitted once here; the per-problem health fan-out
                    # below replays with emit=False.
                    tr.emit("health", t=ev.t, event=ev.kind, chunk=ev.chunk,
                            iteration=ev.iteration, action=ev.action,
                            detail=ev.detail, engine=ev.engine)
                else:
                    from ..obs.live import observe as live_observe
                    live_observe({"t": ev.t, "kind": "health",
                                  "event": ev.kind, "chunk": ev.chunk,
                                  "iteration": ev.iteration,
                                  "action": ev.action, "detail": ev.detail,
                                  "engine": ev.engine})
                if last:
                    raise
                n_retries += 1
                time.sleep(delay)
                delay *= policy.backoff_factor

    def _consume(n, new_carry, state_h, lls_h, mets_h):
        """Host-side bookkeeping for one pulled chunk; True means every
        problem left RUNNING (early exit)."""
        nonlocal n_chunks, it, state_prev_h
        traces.append(lls_h)                        # (n, B)
        if mets_h is not None:
            metric_chunks.append(mets_h)            # (n, B, 3)
        n_chunks += 1
        it += n
        if tr is not None:
            # Per-problem state transitions (freezes) computed from the
            # already-transferred state vector — no extra device traffic.
            n_lls_h = np.asarray(new_carry[4])
            for b in np.flatnonzero(state_h != state_prev_h):
                tr.emit("freeze", engine=engine, problem=int(b),
                        state=STATE_NAMES.get(int(state_h[b]), "?"),
                        chunk=n_chunks - 1, iteration=int(n_lls_h[b]))
            # Batch-max param-update per fused iteration, when the metrics
            # twin ran (same "dparams" field the single-fit chunk emits).
            extra = ({"dparams": [float(x) for x in mets_h[:, :, 2].max(1)]}
                     if mets_h is not None else {})
            tr.emit("chunk", engine=engine, iter0=it - n, n=int(n),
                    noise_floor=float(nf),
                    running=int((state_h == RUNNING).sum()),
                    converged=int((state_h == CONVERGED).sum()),
                    diverged=int((state_h == DIVERGED).sum()), **extra)
            state_prev_h = state_h
        done = state_h != RUNNING
        if cap_h is not None:
            # Per-problem iteration caps: a still-RUNNING problem whose
            # trace reached its own cap is done too (tiny post-barrier
            # transfer — the blocking pull above already synced).
            done = done | (np.asarray(new_carry[4]) >= cap_h)
        return bool(done.all())

    if not pipe.active:
        while it < max_iters:
            n = min(n_bucket, max_iters - it)
            new_carry, state_h, lls_h, mets_h = _attempt_chunk(carry, n)
            carry = new_carry
            if _consume(n, new_carry, state_h, lls_h, mets_h):
                break
    else:
        def _issue(carry_in, n, k):
            if tr is None:
                return _call(carry_in, n)
            with tr.dispatch(prog, _key(n), queue_depth=k, **_payload(n)):
                return _call(carry_in, n)

        stop = False
        while it < max_iters and not stop:
            # Issue phase: up to depth chunks, chaining DEVICE carries —
            # no host transfer between issues.
            flights = []         # [carry_entry, n, new_carry, out, exc, res]
            carry_i, it_i = carry, it
            while len(flights) < pipe.depth and it_i < max_iters:
                n = min(n_bucket, max_iters - it_i)
                try:
                    new_c, out = _issue(carry_i, n, len(flights) + 1)
                except retry_exc as e:
                    flights.append([carry_i, n, None, None, e, None])
                    break
                flights.append([carry_i, n, new_c, out, None, None])
                carry_i = new_c
                it_i += n
            # Drain phase, newest-first: the newest flight's state pull is
            # the round's ONE blocking transfer; older flights' outputs
            # are already materialized by the time it returns.
            live = [i for i, fl in enumerate(flights) if fl[3] is not None]
            for pos, i in enumerate(reversed(live)):
                fl = flights[i]
                tt = time.perf_counter()
                err = None
                try:
                    fl[5] = _pull(fl[2], fl[3], fl[1])
                except retry_exc as e:
                    fl[4], fl[2], fl[3] = e, None, None
                    err = f"{type(e).__name__}: {e}"[:200]
                if tr is not None:
                    ev = dict(program=prog, direction="d2h",
                              blocking=bool(pos == 0), n_iters=int(fl[1]))
                    if err is not None:
                        ev["error"] = err
                    tr.emit("transfer", t=tt, dur=time.perf_counter() - tt,
                            **ev)
            # Process phase, oldest-first (serial order).  A failed flight
            # re-enters the retry seam with its captured exception as
            # attempt 0; anything younger chained on it is discarded.
            for carry_e, n, new_c, out, exc, res in flights:
                if exc is not None or res is None:
                    new_c, state_h, lls_h, mets_h = _attempt_chunk(
                        carry_e, n, first_exc=exc)
                    carry = new_c
                    stop = _consume(n, new_c, state_h, lls_h, mets_h)
                    break
                state_h, lls_h, mets_h = res
                carry = new_c
                stop = _consume(n, new_c, state_h, lls_h, mets_h)
                if stop:
                    break

    p, _, _, state_f, n_lls = carry
    state_h = np.asarray(state_f)
    n_lls_h = np.asarray(n_lls)
    all_lls = (np.concatenate(traces, axis=0) if traces
               else np.zeros((0, B)))
    lls_list = [all_lls[:n_lls_h[b], b] for b in range(B)]
    converged = state_h == CONVERGED
    p_iters = np.where(state_h == DIVERGED,
                       np.maximum(n_lls_h - 2, 0), n_lls_h)
    healths = []
    for b in range(B):
        h = health_from_trace(lls_list[b], noise_floor=float(nf_b[b]),
                              engine=engine)
        h.n_chunks = n_chunks
        h.n_dispatch_retries = n_retries
        for ev in dispatch_events:
            h.record(dataclasses.replace(ev), emit=False)
        healths.append(h)
    if with_metrics:
        metrics_all = (np.concatenate(metric_chunks, axis=0) if metric_chunks
                       else np.zeros((0, B, 3)))
        return p, lls_list, converged, p_iters, healths, metrics_all
    return p, lls_list, converged, p_iters, healths


# ---------------------------------------------------------------------------
# Public API: DFMBatchSpec / fit_many / BatchFitResult
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DFMBatchSpec:
    """B same-shaped DFM problems to fit in one fused program.

    Y: (B, T, N) stacked panels (fully observed — see module docstring).
    model: shared ``DynamicFactorModel`` (its ``n_factors`` is k_max).
    inits: optional per-problem ``cpu_ref.SSMParams`` in STANDARDIZED
        units (what ``FitResult.params`` holds), each with k_b factors —
        padded to k_max internally.  None -> per-problem PCA warm start.
    k_active: optional (B,) active factor counts for the k-grid workload;
        None means every problem uses all ``model.n_factors`` factors.
    origins: optional (B,) window-origin bookkeeping for rolling-window
        specs (carried through to the result; not used by the fit).
    """

    Y: np.ndarray
    model: object
    inits: Optional[list] = None
    k_active: Optional[np.ndarray] = None
    origins: Optional[np.ndarray] = None

    @classmethod
    def restarts(cls, model, Y, n_restarts: int, seed: int = 0,
                 jitter: float = 0.1) -> "DFMBatchSpec":
        """One panel, B jittered inits: restart 0 is the exact PCA warm
        start, the rest perturb it (multiplicative loading noise,
        log-normal R noise) so EM explores distinct basins."""
        Y = np.asarray(Y, np.float64)
        Yz = Y
        if model.standardize:
            Yz, _ = standardize(Y)
        p0 = cpu_ref.pca_init(Yz, model.n_factors,
                              static=(model.dynamics == "static"))
        rng = np.random.default_rng(seed)
        inits = [p0]
        for _ in range(n_restarts - 1):
            inits.append(cpu_ref.SSMParams(
                Lam=p0.Lam * (1.0 + jitter * rng.standard_normal(p0.Lam.shape)),
                A=p0.A.copy(), Q=p0.Q.copy(),
                R=p0.R * np.exp(jitter * rng.standard_normal(p0.R.shape)),
                mu0=p0.mu0.copy(), P0=p0.P0.copy()))
        return cls(Y=np.broadcast_to(Y, (n_restarts,) + Y.shape).copy(),
                   model=model, inits=inits)

    @classmethod
    def k_grid(cls, Y, ks: Sequence[int], dynamics: str = "ar1",
               standardize: bool = True) -> "DFMBatchSpec":
        """One panel fit at each k in ``ks``, padded to k_max = max(ks)."""
        from ..api import DynamicFactorModel
        ks = np.asarray(sorted(ks), np.int64)
        Y = np.asarray(Y, np.float64)
        model = DynamicFactorModel(n_factors=int(ks.max()), dynamics=dynamics,
                                   standardize=standardize)
        return cls(Y=np.broadcast_to(Y, (len(ks),) + Y.shape).copy(),
                   model=model, k_active=ks)

    @classmethod
    def rolling_windows(cls, model, Y, origins: Sequence[int],
                        train_len: int) -> "DFMBatchSpec":
        """Fixed-length training windows ending at each origin (the rolling
        OOS evaluation workload): window w trains on Y[t0-train_len:t0]."""
        Y = np.asarray(Y, np.float64)
        origins = np.asarray(origins, np.int64)
        if (origins < train_len).any() or (origins > Y.shape[0]).any():
            raise ValueError("origins must lie in [train_len, T]")
        stacked = np.stack([Y[t0 - train_len:t0] for t0 in origins])
        return cls(Y=stacked, model=model, origins=origins)


@dataclasses.dataclass
class BatchFitResult:
    """Per-problem results of a batched fit (NumPy, de-jaxed, unpadded)."""

    params: list                  # per-problem cpu_ref.SSMParams (std units)
    logliks: list                 # per-problem loglik trace arrays
    converged: np.ndarray         # (B,) bool
    n_iters: np.ndarray           # (B,) trace lengths
    p_iters: np.ndarray           # (B,) EM updates the params embody
    factors: list                 # per-problem (T, k_b) smoothed means
    factor_cov: list              # per-problem (T, k_b, k_b)
    standardizers: list           # per-problem Standardizer | None
    health: list                  # per-problem robust.FitHealth
    model: object
    spec: DFMBatchSpec
    backend: str
    # (total_iters, B, 3) f64 [loglik, delta, max param-update] per fused
    # iteration when fit_many(with_metrics=True); None otherwise.
    metrics: Optional[np.ndarray] = None

    @property
    def logliks_final(self) -> np.ndarray:
        return np.array([t[-1] if len(t) else np.nan for t in self.logliks])

    def best(self) -> int:
        """Index of the problem with the highest final loglik (restarts)."""
        return int(np.nanargmax(self.logliks_final))


def fit_many(spec: DFMBatchSpec, backend: str = "tpu", max_iters: int = 50,
             tol: float = 1e-6, dtype=None, fused_chunk: int = 8,
             n_devices: Optional[int] = None, robust=True,
             device_init: bool = False,
             with_metrics: bool = False, pipeline=None) -> BatchFitResult:
    """Fit B independent DFM problems in ONE fused program per chunk.

    The batched twin of ``api.fit`` for same-shaped, fully-observed
    problems: standardize each panel (same host path as ``fit``), PCA warm
    starts (or ``spec.inits``), then the fused info-form EM with in-carry
    convergence and a final batched smooth — 2 + ceil(iters/fused_chunk)
    dispatches total instead of ~that many PER problem.

    backend: "tpu" (single-device fused batch) or "sharded" (batch axis
    split across the mesh — see ``parallel.batched``).  ``robust`` as in
    ``api.fit``: True/policy wraps dispatches in the retry seam.
    ``device_init`` opts into the vmapped Gram-eigh PCA init on device
    (``estim.init.pca_init_batched``; uniform-k specs only) — the NumPy
    initializer stays canonical, same policy as ``TPUBackend``.
    ``with_metrics`` routes the chunks through the metrics twin program
    and fills ``BatchFitResult.metrics`` (per-iteration device-side
    convergence record; the default program is untouched when off).
    ``pipeline`` as in ``api.fit``: speculative chunk issue + bucketed
    executable reuse in the chunk driver (see ``dfm_tpu.pipeline``).
    """
    from ..api import _resolve_policy
    Y = np.asarray(spec.Y, np.float64)
    if Y.ndim != 3:
        raise ValueError(f"spec.Y must be (B, T, N), got {Y.shape}")
    if not np.isfinite(Y).all():
        raise ValueError("batched fits require fully-observed panels "
                         "(no NaN/mask support); use api.fit per problem")
    B, T, N = Y.shape
    model = spec.model
    k_max = model.n_factors
    if k_max > min(T, N):
        raise ValueError(f"n_factors={k_max} exceeds min(T, N)={min(T, N)}")
    k_act = (np.full((B,), k_max, np.int64) if spec.k_active is None
             else np.asarray(spec.k_active, np.int64))
    if len(k_act) != B:
        raise ValueError("k_active length != B")
    if (k_act < 1).any() or (k_act > k_max).any():
        raise ValueError("k_active entries must lie in [1, n_factors]")
    static = model.dynamics == "static"

    # Host prep: the same standardize() call api.fit uses, per problem.
    Yz = np.empty_like(Y)
    stds: list = []
    for b in range(B):
        validate_panel(Y[b], check_variance=model.standardize)
        if model.standardize:
            Yz[b], s = standardize(Y[b])
            stds.append(s)
        else:
            Yz[b] = Y[b]
            stds.append(None)

    # Per-problem inits (canonical host PCA unless provided), padded to
    # k_max with inert factors.
    if spec.inits is not None:
        if len(spec.inits) != B:
            raise ValueError("spec.inits length != B")
        inits = [pad_params_to_k(p, k_max) for p in spec.inits]
    elif device_init and (k_act == k_max).all():
        from .init import pca_init_batched
        dt0 = dtype or default_compute_dtype()
        inits = pca_init_batched(Yz, k_max, static=static, dtype=dt0)
    else:
        inits = [pad_params_to_k(
            cpu_ref.pca_init(Yz[b], int(k_act[b]), static=static), k_max)
            for b in range(B)]

    dt = dtype or default_compute_dtype()
    cfg = EMConfig(estimate_A=model.estimate_A, estimate_Q=model.estimate_Q,
                   estimate_init=model.estimate_init, filter="info")
    policy = _resolve_policy(robust)
    Yj = jnp.asarray(Yz, dt)
    p0 = stack_params(inits, dt)

    metrics = None
    with jax.default_matmul_precision("highest"):
        if backend == "sharded":
            from ..parallel.batched import (batched_smooth_sharded,
                                            run_batched_em_sharded)
            out = run_batched_em_sharded(
                Yj, p0, cfg, max_iters, tol, fused_chunk=fused_chunk,
                n_devices=n_devices, policy=policy,
                with_metrics=with_metrics, pipeline=pipeline)
            if with_metrics:
                p, lls_list, conv, p_iters, healths, metrics = out
            else:
                p, lls_list, conv, p_iters, healths = out

            def _smooth():
                return batched_smooth_sharded(Yj, p, n_devices=n_devices)
        elif backend == "tpu":
            out = run_batched_em(
                Yj, p0, cfg, max_iters, tol, fused_chunk=fused_chunk,
                policy=policy, with_metrics=with_metrics,
                pipeline=pipeline)
            if with_metrics:
                p, lls_list, conv, p_iters, healths, metrics = out
            else:
                p, lls_list, conv, p_iters, healths = out

            def _smooth():
                return _smooth_impl(Yj, p)
        else:
            raise ValueError(f"unknown batched backend {backend!r} "
                             "(use 'tpu' or 'sharded')")
        tr = current_tracer()
        if tr is None:
            x_sm, P_sm = _smooth()
            x_h = np.asarray(x_sm, np.float64)
            P_h = np.asarray(P_sm, np.float64)
        else:
            with tr.dispatch("batched_smooth", shape_key(Yj, backend),
                             barrier=True):
                x_sm, P_sm = _smooth()
                x_h = np.asarray(x_sm, np.float64)
                P_h = np.asarray(P_sm, np.float64)

    params = [slice_params_to_k(pb, int(k_act[b]))
              for b, pb in enumerate(unstack_params(p))]
    factors = [x_h[b, :, :k_act[b]] for b in range(B)]
    factor_cov = [P_h[b, :, :k_act[b], :k_act[b]] for b in range(B)]
    return BatchFitResult(
        params=params, logliks=lls_list, converged=np.asarray(conv),
        n_iters=np.array([len(t) for t in lls_list]),
        p_iters=np.asarray(p_iters), factors=factors,
        factor_cov=factor_cov, standardizers=stds, health=healths,
        model=model, spec=spec, backend=backend, metrics=metrics)

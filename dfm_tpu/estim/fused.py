"""Dispatch-free end-to-end fit: EM + smooth + forecast in ONE program.

``run_fused`` wraps the existing EM chunk body (`estim.em._em_chunk_body`)
in a ``lax.while_loop`` whose stopping predicate mirrors the host-side
``obs.convergence.em_progress`` rule exactly (relative-tolerance
convergence, plateau detection, divergence vs. the absolute
``noise_floor_for`` floor), then smooths and emits nowcast /
diffusion-index forecasts inside the same jitted program.  Only small
host-bound outputs cross the tunnel: params, the loglik path, iteration
count, and the forecast arrays.  One barrier'd d2h read per fit.

Donation: warm refits go through ``_fused_fit_impl_donated``
(``donate_argnums`` on the incoming params pytree) so device-resident
state is updated in place; the panel itself is cached by the backend
(`TPUBackend._fused_panel`) so a warm ``fit(warm_start=prev)`` uploads
nothing.

Semantics vs. the chunked driver (`run_em_chunked`):

- The while loop exits at the first chunk whose in-chunk predicate fires,
  so the *consumed* iteration count matches the host rule to within one
  chunk-length (parity-tested in tests/test_fused.py).
- On convergence the returned params embody the full chunk's updates
  (up to ``chunk - 1`` extra M-steps at an already-converged point);
  there is no mid-chunk replay on device.
- On divergence the last-good checkpoint follows the chunked driver's
  replay rule: a drop at the chunk's *first* loglik blames the previous
  chunk's params, otherwise this chunk's entry params are last-good.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..obs.trace import current_tracer, shape_key
from ..ops.precision import accum_dtype
from .em import _em_chunk_body, _panel_consts

__all__ = ["FusedOptions", "FusedRun", "resolve_fused", "run_fused"]

_RUNNING, _CONVERGED, _DIVERGED = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class FusedOptions:
    """Static options for the fused end-to-end fit program.

    horizon: forecast steps ahead (state-space iterate + diffusion index).
    di: also compute the diffusion-index (observable-regression) forecast.
    fault_chunk/fault_drop: test seam — subtract ``fault_drop`` from the
    logliks of chunk index ``fault_chunk`` on device, forcing the
    divergence branch (used by the robust-fallback equivalence tests).
    """

    horizon: int = 1
    di: bool = True
    fault_chunk: Optional[int] = None
    fault_drop: float = 1e6


def resolve_fused(fused):
    """Normalize the ``fit(fused=...)`` knob to FusedOptions or None."""
    if not fused:
        return None
    if fused is True:
        return FusedOptions()
    if isinstance(fused, FusedOptions):
        return fused
    if isinstance(fused, int):
        return FusedOptions(horizon=max(1, int(fused)))
    raise TypeError(
        "fused must be bool, int (forecast horizon) or FusedOptions; "
        f"got {type(fused).__name__}"
    )


def _di_forecast_core(F, Y, horizon, ridge=1e-8):
    # In-graph port of estim.diffusion.diffusion_index_forecast at its
    # defaults (f_lags=0, y_lags=1), vectorized over every panel column.
    # Normal equations share the factor Gram block across columns; the
    # per-column own-lag row/column is assembled into a batched
    # (N, k+2, k+2) solve.  This is a ONE-OFF batched solve outside the
    # EM loop, so the in-scan batched-linalg tax (CLAUDE.md) does not
    # apply.
    T, k = F.shape
    N = Y.shape[1]
    d = k + 2
    dt = F.dtype
    n_fit = max(T - 1 - horizon, 0)
    Xf = jnp.concatenate([jnp.ones((T - 1, 1), dt), F[1:]], axis=1)
    Xf_fit = Xf[:n_fit]
    Ylag_fit = Y[:-1][:n_fit]
    Z = Y[1 + horizon :]
    Gff = Xf_fit.T @ Xf_fit
    Gfy = Xf_fit.T @ Ylag_fit
    Gyy = jnp.einsum("ti,ti->i", Ylag_fit, Ylag_fit)
    bf = Xf_fit.T @ Z
    by = jnp.einsum("ti,ti->i", Ylag_fit, Z)
    XtX = jnp.zeros((N, d, d), dt)
    XtX = XtX.at[:, : d - 1, : d - 1].set(Gff[None])
    XtX = XtX.at[:, : d - 1, d - 1].set(Gfy.T)
    XtX = XtX.at[:, d - 1, : d - 1].set(Gfy.T)
    XtX = XtX.at[:, d - 1, d - 1].set(Gyy)
    XtX = XtX + ridge * jnp.eye(d, dtype=dt)[None]
    Xtz = jnp.concatenate([bf.T, by[:, None]], axis=1)
    beta = jnp.linalg.solve(XtX, Xtz[..., None])[..., 0]
    x_last = jnp.concatenate(
        [jnp.ones((N, 1), dt), jnp.broadcast_to(F[-1], (N, k)), Y[-2][:, None]],
        axis=1,
    )
    return jnp.einsum("nd,nd->n", x_last, beta)


def _di_forecast_core_masked(F, Y, t_new, horizon, ridge=1e-8):
    """``_di_forecast_core`` for a capacity-padded panel: only the first
    ``t_new`` (traced) time steps are live.  The regression rows past the
    live prefix get exact {0,1} zero weights (pad-tail smoother states are
    finite predictions, so weighted products stay finite), and the "last"
    rows are dynamic gathers at ``t_new - 1`` / ``t_new - 2`` — static
    shapes throughout, so ONE executable serves every live length."""
    T, k = F.shape
    N = Y.shape[1]
    d = k + 2
    dt = F.dtype
    L = max(T - 1 - horizon, 0)
    n_fit = jnp.maximum(t_new - 1 - horizon, 0)
    w = (jnp.arange(L) < n_fit).astype(dt)
    Xf = jnp.concatenate([jnp.ones((L, 1), dt), F[1 : 1 + L]], axis=1)
    Ylag = Y[:L]
    Z = Y[1 + horizon : 1 + horizon + L]
    Xw = Xf * w[:, None]
    Gff = Xw.T @ Xf
    Gfy = Xw.T @ Ylag
    Gyy = jnp.einsum("t,ti,ti->i", w, Ylag, Ylag)
    bf = Xw.T @ Z
    by = jnp.einsum("t,ti,ti->i", w, Ylag, Z)
    XtX = jnp.zeros((N, d, d), dt)
    XtX = XtX.at[:, : d - 1, : d - 1].set(Gff[None])
    XtX = XtX.at[:, : d - 1, d - 1].set(Gfy.T)
    XtX = XtX.at[:, d - 1, : d - 1].set(Gfy.T)
    XtX = XtX.at[:, d - 1, d - 1].set(Gyy)
    XtX = XtX + ridge * jnp.eye(d, dtype=dt)[None]
    Xtz = jnp.concatenate([bf.T, by[:, None]], axis=1)
    beta = jnp.linalg.solve(XtX, Xtz[..., None])[..., 0]
    f_last = jnp.take(F, t_new - 1, axis=0, mode="clip")
    y_prev = jnp.take(Y, t_new - 2, axis=0, mode="clip")
    x_last = jnp.concatenate(
        [jnp.ones((N, 1), dt), jnp.broadcast_to(f_last, (N, k)),
         y_prev[:, None]],
        axis=1,
    )
    return jnp.einsum("nd,nd->n", x_last, beta)


def _em_while_core(Y, m, p0, tol, noise_floor, cfg, max_iters, chunk, opts,
                   sumsq=None, Ysq=None, n_steps=None):
    """EM-to-convergence while-loop shared by the fused fit and the serve
    session program.  Returns the final while-loop carry dict (params,
    last-good checkpoint, loglik path, iteration counters, status).

    ``n_steps`` (traced, optional): live time-step count for
    capacity-padded panels (serve sessions) — threads into the t-masked
    M-step dynamics via ``_em_chunk_body``; the zero-masked pad tail is
    exactly inert in the E-step, so ONE executable serves every live
    length a session can reach."""
    C = chunk
    n_chunks = -(-max_iters // C)
    acc = accum_dtype(Y.dtype)
    tol = jnp.asarray(tol, acc)
    floor = jnp.asarray(noise_floor, acc)
    i32 = jnp.int32

    def sel(pred, a, b):
        return jax.tree_util.tree_map(lambda x, y: jnp.where(pred, x, y), a, b)

    def cond(c):
        return (c["status"] == _RUNNING) & (c["it"] < max_iters)

    def step(c):
        p, it = c["p"], c["it"]
        # Tail chunks reuse the same executable: always scan C iterations
        # with a traced live-cap, exactly like _em_scan_core_active.
        n_active = jnp.minimum(C, max_iters - it).astype(i32)
        body = _em_chunk_body(Y, m, cfg, sumsq, Ysq, n_active,
                              n_steps=n_steps)
        p_end, (lls_c, _) = lax.scan(body, p, jnp.arange(C))
        lls_c = lls_c.astype(acc)
        if opts.fault_chunk is not None:  # static test seam
            lls_c = lls_c - jnp.where(
                it // C == opts.fault_chunk,
                jnp.asarray(opts.fault_drop, acc),
                jnp.zeros((), acc),
            )
        j = jnp.arange(C)
        active = j < n_active
        # On-device mirror of obs.convergence.em_progress over this
        # chunk's loglik path (prev entry NaN on the very first chunk).
        prev = jnp.concatenate([c["ll_last"][None], lls_c[:-1]])
        has_prev = jnp.isfinite(prev)
        rel = (lls_c - prev) / jnp.maximum(jnp.abs(prev), 1e-12)
        drop = prev - lls_c
        small = (tol > 0) & (jnp.abs(rel) < tol)
        # Tuned fits (cfg_hypers active — estim.tune) stop at the
        # likelihood plateau instead of alarming on it: the hyper-scaled
        # update's fixed point is not a loglik stationary point, so a
        # drop is the expected terminal behavior, not a divergence
        # (host twin: em_progress(monotone=False)).  cfg is static, so
        # the untuned predicate is byte-identical to pre-tune programs.
        from .em import cfg_hypers
        monotone = cfg_hypers(cfg) is None
        diver = ~small & (drop > floor) & monotone
        plateau = ~small & ~diver & (drop > 0) & (tol > 0)
        conv = has_prev & active & (small | plateau)
        # Non-finite logliks count as divergence: NaN comparisons are all
        # False, so without this a NaN run would burn the whole budget.
        dive = active & ((has_prev & diver) | ~jnp.isfinite(lls_c))
        stop = conv | dive
        any_stop = jnp.any(stop)
        first = jnp.argmax(stop).astype(i32)
        stopped_div = any_stop & dive[first]
        status = jnp.where(
            any_stop, jnp.where(stopped_div, _DIVERGED, _CONVERGED), _RUNNING
        ).astype(i32)
        consumed = jnp.where(any_stop, first + 1, n_active)
        # Last-good checkpoint (chunked driver's replay rule): a drop at
        # this chunk's first loglik blames the previous chunk's update.
        cand_p = sel(first >= 1, p, c["p_prev"])
        cand_it = jnp.where(first >= 1, it, c["prev_it"])
        p_good = sel(stopped_div, cand_p, c["p_good"])
        good_it = jnp.where(stopped_div, cand_it, c["good_it"])
        return {
            "p": p_end,
            "p_prev": p,
            "prev_it": it,
            "p_good": p_good,
            "good_it": good_it,
            "lls": lax.dynamic_update_slice(c["lls"], lls_c, (it,)),
            "ll_last": lls_c[n_active - 1],
            "it": it + consumed,
            "emb": it + n_active,
            "status": status,
        }

    carry0 = {
        "p": p0,
        "p_prev": p0,
        "prev_it": jnp.zeros((), i32),
        "p_good": p0,
        "good_it": jnp.zeros((), i32),
        "lls": jnp.full((n_chunks * C,), jnp.nan, acc),
        "ll_last": jnp.asarray(jnp.nan, acc),
        "it": jnp.zeros((), i32),
        "emb": jnp.zeros((), i32),
        "status": jnp.asarray(_RUNNING, i32),
    }
    return lax.while_loop(cond, step, carry0)


def _fused_fit_core(Y, mask, p0, tol, noise_floor, cfg, has_mask, max_iters, chunk, opts):
    m = mask if has_mask else None
    sumsq, Ysq = _panel_consts(Y, has_mask, cfg)
    f = _em_while_core(Y, m, p0, tol, noise_floor, cfg, max_iters, chunk,
                       opts, sumsq=sumsq, Ysq=Ysq)
    p_fit = f["p"]

    # Smooth + forecast at the fitted params, same program — routed by
    # engine (EMConfig.report_pair: pit_qr/lowrank report through their
    # own smoothers; dense/info/ss/pit keep the historical pairs
    # bit-for-bit, matching api.smooth()).
    ff, sf = cfg.report_pair()
    kf = ff(Y, p_fit, mask=m)
    sm = sf(kf, p_fit)
    x_T, P_T = sm.x_sm[-1], sm.P_sm[-1]
    nowcast = p_fit.Lam @ x_T

    def fstep(carry, _):
        x, P = carry
        x1 = p_fit.A @ x
        P1 = p_fit.A @ P @ p_fit.A.T + p_fit.Q
        return (x1, P1), (x1, p_fit.Lam @ x1)

    _, (f_fore, y_fore) = lax.scan(fstep, (x_T, P_T), None, length=opts.horizon)
    di = _di_forecast_core(sm.x_sm, Y, opts.horizon) if opts.di else None
    return {
        "p": p_fit,
        "p_good": f["p_good"],
        "good_it": f["good_it"],
        "lls": f["lls"],
        "n_iters": f["it"],
        "emb": f["emb"],
        "status": f["status"],
        "x_sm": sm.x_sm,
        "P_sm": sm.P_sm,
        "nowcast": nowcast,
        "f_fore": f_fore,
        "y_fore": y_fore,
        "di": di,
    }


_STATICS = ("cfg", "has_mask", "max_iters", "chunk", "opts")


@partial(jax.jit, static_argnames=_STATICS)
def _fused_fit_impl(Y, mask, p0, tol, noise_floor, *, cfg, has_mask, max_iters, chunk, opts):
    return _fused_fit_core(Y, mask, p0, tol, noise_floor, cfg, has_mask, max_iters, chunk, opts)


# Donated twin for warm refits: the incoming params pytree (positional
# index 2) is consumed in place.  Y/mask are never donated — they stay
# device-resident across refits (TPUBackend._fused_panel).
@partial(jax.jit, static_argnames=_STATICS, donate_argnums=(2,))
def _fused_fit_impl_donated(Y, mask, p0, tol, noise_floor, *, cfg, has_mask, max_iters, chunk, opts):
    return _fused_fit_core(Y, mask, p0, tol, noise_floor, cfg, has_mask, max_iters, chunk, opts)


@dataclasses.dataclass
class FusedRun:
    """Host-side view of one fused fit (all fields materialized numpy)."""

    params: object
    p_good: object
    good_it: int
    lls: np.ndarray
    n_iters: int
    p_iters: int
    converged: bool
    diverged: bool
    x_sm: np.ndarray
    P_sm: np.ndarray
    nowcast: np.ndarray
    f_fore: np.ndarray
    y_fore: np.ndarray
    di: Optional[np.ndarray]


def _read_run(out, max_iters):
    n = min(int(out["n_iters"]), max_iters)
    status = int(out["status"])
    return FusedRun(
        params=out["p"].to_numpy(),
        p_good=out["p_good"].to_numpy(),
        good_it=int(out["good_it"]),
        lls=np.asarray(out["lls"], np.float64)[:n],
        n_iters=n,
        p_iters=int(out["emb"]),
        converged=status == _CONVERGED,
        diverged=status == _DIVERGED,
        x_sm=np.asarray(out["x_sm"], np.float64),
        P_sm=np.asarray(out["P_sm"], np.float64),
        nowcast=np.asarray(out["nowcast"], np.float64),
        f_fore=np.asarray(out["f_fore"], np.float64),
        y_fore=np.asarray(out["y_fore"], np.float64),
        di=np.asarray(out["di"], np.float64) if out["di"] is not None else None,
    )


def run_fused(Yj, mj, pj, cfg, max_iters, tol, noise_floor, opts, fused_chunk=8,
              policy=None, health=None, p0_host=None):
    """Run the fused fit program; returns a host-materialized FusedRun.

    All device→host reads happen inside one barrier'd dispatch span, so a
    traced fused fit counts exactly one blocking transfer.

    With a ``RobustPolicy`` the single dispatch + read goes through
    ``robust.dispatch.guarded_dispatch`` (retry/backoff, watchdog
    deadline, ``wrap_dispatch`` fault seam); a retry after a failed
    donated dispatch rebuilds the entry params from ``p0_host`` (the
    donated twin consumed them in flight).  ``policy=None`` is the exact
    pre-guard code path: one dispatch, no wrapper.
    """
    max_iters = max(1, int(max_iters))
    C = max(1, int(fused_chunk))
    # CPU backend: donation is unimplemented and warns; use the plain twin.
    impl = _fused_fit_impl if jax.default_backend() == "cpu" else _fused_fit_impl_donated
    acc = accum_dtype(Yj.dtype)
    tol_j, floor_j = jnp.asarray(tol, acc), jnp.asarray(noise_floor, acc)
    kw = dict(cfg=cfg, has_mask=mj is not None, max_iters=max_iters, chunk=C, opts=opts)
    tr = current_tracer()
    key = shape_key(Yj, cfg.filter, f"chunk{C}", f"max{max_iters}")

    def _once(attempt):
        p_in = pj
        if attempt > 0 and p0_host is not None:
            # The failed attempt may have consumed the donated params
            # pytree; re-enter from the host copy (tiny h2d upload).
            from ..ssm.params import SSMParams as JaxParams
            p_in = JaxParams.from_numpy(p0_host, dtype=Yj.dtype)
        args = (Yj, mj, p_in, tol_j, floor_j)
        if tr is None:
            return _read_run(impl(*args, **kw), max_iters)
        if attempt == 0:
            # Static cost capture (DFM_TRACE_COST=1): lower+compile only —
            # nothing executes, so the donated twin's buffers are
            # untouched.  Both twins share the program name AND shape key,
            # so the RecompileDetector sees the donated warm refit as the
            # SAME logical program, not a recompile.
            tr.maybe_cost("fused_fit", key, impl, *args, **kw)
        extra = {"attempt": attempt} if policy is not None else {}
        with tr.dispatch("fused_fit", key, barrier=True, fused=True,
                         n_iters=max_iters, **extra) as rec:
            out = impl(*args, **kw)
            run = _read_run(out, max_iters)
            if rec is not None:
                rec["n_iters"] = int(run.n_iters)
        return run

    if policy is None:
        run = _once(0)
    else:
        from ..robust.dispatch import guarded_dispatch
        run = guarded_dispatch(_once, policy, health, label="fused fit",
                               last_good=p0_host)
    if tr is None:
        return run
    drops = np.diff(run.lls)
    tr.emit(
        "chunk",
        engine="fused",
        iter0=0,
        n=int(run.n_iters),
        lls=[float(x) for x in run.lls],
        noise_floor=float(noise_floor),
        max_drop=float(-drops.min()) if drops.size else 0.0,
        below_floor=bool(drops.size == 0 or np.abs(drops).max() < float(noise_floor)),
    )
    return run

"""Diffusion-index (factor-augmented) forecasting — SURVEY.md R9.

Stock-Watson style h-step direct forecast: regress target_{t+h} on current
factors and lags of factors/target, then apply at the end of sample.  This
is the workhorse use of extracted factors in the reference package's
domain; composes with ``api.fit`` (use ``FitResult.factors``) and
``estim.select.targeted_predictors``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..utils.data import lag_matrix

__all__ = ["diffusion_index_forecast", "DIForecast"]


@dataclasses.dataclass
class DIForecast:
    forecast: float               # point forecast of target_{T+h}
    coef: np.ndarray              # regression coefficients
    fitted: np.ndarray            # in-sample fitted values
    resid: np.ndarray
    r2: float


def _design(F: np.ndarray, target: np.ndarray, f_lags: int, y_lags: int):
    """Rows t -> [1, F_t, F_{t-1}.., y_t, y_{t-1}..]; valid t range."""
    T = len(target)
    start = max(f_lags, y_lags)
    cols = [np.ones((T - start, 1)), F[start:]]
    if f_lags > 0:
        cols.append(lag_matrix(F, f_lags)[start - f_lags:])
    if y_lags > 0:
        cols.append(lag_matrix(target, y_lags)[start - y_lags:])
    return np.concatenate(cols, axis=1), start


def diffusion_index_forecast(factors: np.ndarray, target: np.ndarray,
                             horizon: int = 1, f_lags: int = 0,
                             y_lags: int = 1,
                             ridge: float = 1e-8) -> DIForecast:
    """Direct h-step forecast target_{T+h} from factors.

    factors : (T, k) estimated factor path (e.g. ``FitResult.factors``).
    target  : (T,) series to forecast (need not be in the panel).
    """
    F = np.asarray(factors, np.float64)
    y = np.asarray(target, np.float64)
    T = len(y)
    X_all, start = _design(F, y, f_lags, y_lags)
    X = X_all[: T - start - horizon]
    z = y[start + horizon:]
    XtX = X.T @ X + ridge * np.eye(X.shape[1])
    beta = np.linalg.solve(XtX, X.T @ z)
    fitted = X @ beta
    resid = z - fitted
    r2 = 1.0 - resid.var() / max(z.var(), 1e-300)
    x_T = X_all[-1]
    return DIForecast(forecast=float(x_T @ beta), coef=beta,
                      fitted=fitted, resid=resid, r2=float(r2))

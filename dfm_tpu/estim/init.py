"""Device-side PCA initializer: the Stock-Watson warm start on the TPU.

Mirrors ``backends.cpu_ref.pca_init`` (reference component R3) but runs the
N-sized work — the (T, N) SVD, the loading/factor projections, the residual
variances — on the accelerator, so a 10k-series fit does not spend ~1.2 s in
a host SVD before the first EM iteration.  The k-sized dynamics tail (VAR(1)
OLS + stationary P0, which needs a data-dependent stability branch) reuses
the host implementation ``cpu_ref.var_tail`` from the device factor path.

Not the default: the NumPy f64 initializer stays canonical so that CPU/TPU
backend fits start from IDENTICAL params (the backend-parity goldens depend
on it).  Opt in per backend with ``TPUBackend(device_init=True)`` — EM
contracts to the same optimum from either start.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..backends import cpu_ref

__all__ = ["pca_init_device", "pca_init_batched", "standardize_device"]


@jax.jit
def standardize_device(Y):
    """Column standardization of a FULLY-OBSERVED panel on the device.

    The device analog of ``utils.data.standardize`` for the no-missing case
    (same ddof-1 / 1e-12 variance-floor semantics): ``api.fit`` uses it so a
    large panel's prep costs one raw transfer plus a tiny fused program
    instead of ~0.5 s of host NumPy passes (docs/PERF.md, fixed-cost table).
    Two-pass (mean, then centered sum of squares) so it is stable in f32 for
    arbitrarily-shifted data.  Returns ``(Yz, stack([mean, scale]))`` — the
    stats stacked into ONE array so the host fetch is a single transfer
    (each device->host transfer pays the tunnel's latency floor).
    """
    T = Y.shape[0]
    mean = jnp.mean(Y, axis=0)
    xc = Y - mean[None, :]
    var = jnp.sum(xc * xc, axis=0) / max(float(T - 1), 1.0)
    scale = jnp.sqrt(jnp.maximum(var, 1e-12))
    return xc / scale[None, :], jnp.stack([mean, scale])


@partial(jax.jit, static_argnames=("k",))
def _pca_parts(Y, k: int):
    T, N = Y.shape
    # Top right-singular vectors via eigh of the (T, T) Gram matrix — NOT
    # jnp.linalg.svd: the axon XLA toolchain SIGABRTs compiling SVD at the
    # (500, 10k) shape (TransposeFolding check failure), and the Gram route
    # is faster anyway (one (T,N)x(N,T) MXU matmul + a T x T eigh).
    # Y = U S V'  =>  Y Y' = U S^2 U'  and  V = Y' U / S.
    G = Y @ Y.T
    w, U = jnp.linalg.eigh(G)                     # ascending eigenvalues
    w_k = w[-k:][::-1]                            # top-k, descending
    U_k = U[:, -k:][:, ::-1]
    s_k = jnp.sqrt(jnp.maximum(w_k, 1e-12))
    V = (Y.T @ U_k) / s_k[None, :]                # (N, k)
    Lam = jnp.sqrt(float(N)) * V
    F = Y @ Lam / N                               # (T, k)
    resid = Y - F @ Lam.T
    R = jnp.maximum(jnp.var(resid, axis=0), 1e-6)
    return Lam, F, R


def pca_init_device(Y, k: int, static: bool = False,
                    dtype=jnp.float32) -> "cpu_ref.SSMParams":
    """Device PCA init; returns host-dtype params (same type as the NumPy
    initializer so every downstream path is unchanged).  ``Y`` must already
    be standardized and zero-filled at missing entries (what ``api.fit``
    passes)."""
    Lam, F, R = _pca_parts(jnp.asarray(Y, dtype), k)
    A, Q, mu0, P0 = cpu_ref.var_tail(np.asarray(F, np.float64), k, static)
    return cpu_ref.SSMParams(np.asarray(Lam, np.float64), A, Q,
                             np.asarray(R, np.float64), mu0, P0)


@partial(jax.jit, static_argnames=("k",))
def _pca_parts_batched(Y, k: int):
    """vmapped Gram-eigh PCA over stacked panels (B, T, N)."""
    return jax.vmap(lambda y: _pca_parts(y, k))(Y)


def pca_init_batched(Y, k: int, static: bool = False, dtype=jnp.float32):
    """Device PCA warm starts for a STACK of same-shaped panels.

    One fused program runs the B Gram-eigh decompositions (the batched init
    of ``estim.batched.fit_many``; per-problem this is ``_pca_parts``
    exactly), then the k-sized VAR tails run on host per problem — same
    placement split as ``pca_init_device``.  Panels must be standardized
    with no missing entries.  Returns a list of B host-dtype param sets.
    """
    Lam, F, R = _pca_parts_batched(jnp.asarray(Y, dtype), k)
    Lam_h = np.asarray(Lam, np.float64)
    F_h = np.asarray(F, np.float64)
    R_h = np.asarray(R, np.float64)
    out = []
    for b in range(Lam_h.shape[0]):
        A, Q, mu0, P0 = cpu_ref.var_tail(F_h[b], k, static)
        out.append(cpu_ref.SSMParams(Lam_h[b], A, Q, R_h[b], mu0, P0))
    return out

"""Daemon lifecycle: crash recovery and zero-downtime handoff.

Crash recovery (``restore_daemon_state``): restore the latest fleet
snapshot (``fleet.restore_fleet`` — fingerprint-verified, schema-
checked), then replay the request journal tail after the snapshot's
``journal_seq`` watermark through ``submit``/``drain``.  Replay is
deterministic and per-tenant answers are pinned to lone sessions, so
the recovered device state is bit-equal to the uninterrupted daemon's —
and the replay itself compiles (or warms from ``DFM_COMPILE_CACHE``)
the exact serving executables the first live query needs.

Zero-downtime handoff (blue/green): the listening socket is passed
between processes over a unix control socket with ``SCM_RIGHTS``
(``socket.send_fds``/``recv_fds``), so it NEVER closes — connections
arriving during the swap wait in the kernel backlog instead of being
refused.  Choreography:

1. successor restores the current snapshot + journal tail (warm),
2. successor listens on a throwaway ``reply_to`` unix socket and sends
   ``{"op": "handoff", "reply_to": ...}`` to the predecessor,
3. predecessor stops accepting, drains every in-flight ticket, takes a
   final snapshot, stamps ``t_stop`` and sends the listener fd + meta
   (``last_seq``) to ``reply_to``, then exits,
4. successor replays the journal delta ``(replayed, last_seq]``, adopts
   the fd and serves.  ``handoff_gap_ms`` = successor-ready minus
   predecessor ``t_stop`` — the only window where queries queue.
"""

from __future__ import annotations

import array
import json
import os
import socket
import time
from typing import Optional, Tuple

from .journal import Journal

__all__ = ["send_listener", "recv_listener", "restore_daemon_state",
           "replay_entries"]

_META_MAX = 1 << 20


def send_listener(reply_to: str, listener: socket.socket,
                  meta: dict) -> None:
    """Predecessor side: hand the listening socket's fd + a JSON meta
    blob to the successor waiting on the ``reply_to`` unix socket."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.connect(reply_to)
        payload = json.dumps(meta).encode("utf-8")
        if hasattr(socket, "send_fds"):
            socket.send_fds(s, [payload], [listener.fileno()])
        else:                            # pragma: no cover - py<3.9
            fds = array.array("i", [listener.fileno()])
            s.sendmsg([payload], [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                                   fds.tobytes())])


def recv_listener(reply_sock: socket.socket,
                  timeout: Optional[float] = None
                  ) -> Tuple[socket.socket, dict]:
    """Successor side: accept one connection on the ``reply_to`` listener
    and receive (listening socket, meta).  The rebuilt socket owns the
    received fd."""
    if timeout is not None:
        reply_sock.settimeout(timeout)
    conn, _ = reply_sock.accept()
    try:
        if hasattr(socket, "recv_fds"):
            payload, fds, _, _ = socket.recv_fds(conn, _META_MAX, 1)
        else:                            # pragma: no cover - py<3.9
            payload, anc, _, _ = conn.recvmsg(
                _META_MAX, socket.CMSG_LEN(array.array("i", [0]).itemsize))
            fds = array.array("i")
            for level, tp, data in anc:
                if level == socket.SOL_SOCKET and tp == socket.SCM_RIGHTS:
                    fds.frombytes(data)
        if not fds:
            raise RuntimeError("handoff peer sent no listener fd")
        meta = json.loads(payload.decode("utf-8"))
        listener = socket.socket(fileno=fds[0])
        return listener, meta
    finally:
        conn.close()


def replay_entries(fleet, entries) -> int:
    """Apply journaled submits to a fleet (answers discarded — replay
    rebuilds STATE; the original answers went to the original clients).
    Returns the highest seq applied.

    The live pump validates before journaling, so every entry SHOULD
    replay cleanly — but a journal written by an older build (or a
    tenant evicted since) must not brick recovery: an entry the fleet
    rejects is skipped with a warning, exactly like a torn line."""
    import numpy as np
    from ..obs.trace import request_clock
    hi = 0
    n_bad = 0
    for e in entries:
        rows = e.get("rows")
        mask = e.get("mask")
        # Cross-process trace continuity: a journaled entry keeps its
        # original trace_id, but replay is NOT the original request — a
        # fresh replay-marked context (re-stamped t_send so the replayed
        # waterfall measures replay timing) keeps the id linkable while
        # making the span impossible to mistake for live traffic.
        jt = e.get("trace")
        trace = ({"id": str(jt.get("id", "")), "t_send": request_clock(),
                  "replay": True} if isinstance(jt, dict) else None)
        try:
            fleet.submit(
                e["tenant"],
                None if rows is None else np.asarray(rows, np.float64),
                mask=None if mask is None else np.asarray(mask),
                trace=trace)
        except (KeyError, ValueError, TypeError) as err:
            n_bad += 1
            import warnings
            warnings.warn(f"journal replay: skipping entry "
                          f"seq={e.get('seq')} the fleet rejects ({err})")
            continue
        hi = max(hi, int(e["seq"]))
    if hi:
        fleet.drain()
    return hi


def restore_daemon_state(snapshot_dir: str, journal_path: str, *,
                         backend=None, robust=None,
                         resident: Optional[int] = None,
                         max_classes: int = 3,
                         runs: Optional[str] = None):
    """Crash-recovery entry: (fleet, watermark, n_replayed).

    Restores the snapshot under ``snapshot_dir`` and replays the journal
    tail past its ``journal_seq`` watermark.  The returned watermark is
    the highest seq now reflected in the fleet — the daemon resumes
    journaling after it."""
    from ..fleet.driver import read_manifest, restore_fleet
    manifest = read_manifest(snapshot_dir)
    kw = {"max_classes": max_classes}
    if backend is not None:
        kw["backend"] = backend
    if robust is not None:
        kw["robust"] = robust
    if resident is not None:
        kw["resident"] = resident
    if runs is not None:
        kw["runs"] = runs
    fleet = restore_fleet(snapshot_dir, **kw)
    wm = int(manifest.get("journal_seq") or 0)
    entries = Journal.read(journal_path, after=wm)
    hi = replay_entries(fleet, entries)
    ev = dict(session=fleet.fleet_id, action="replay",
              n_entries=len(entries), watermark=wm)
    from ..obs.trace import current_tracer
    tr = current_tracer()
    if tr is not None:
        tr.emit("daemon", **ev)
    else:
        from ..obs.live import observe
        observe({"t": time.perf_counter(), "kind": "daemon", **ev})
    return fleet, max(wm, hi), len(entries)

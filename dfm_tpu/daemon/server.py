"""The serving daemon: a robust front door over one ``SessionFleet``.

``DFMDaemon`` owns a fleet and serves the JSON-lines protocol
(``daemon.protocol``) from a BOUNDED request queue, with robustness as
the product at three layers:

1. **Overload protection** — admission happens at enqueue, priced per
   tenant by the calibrated cost model (``obs.cost``): a full queue (by
   count or by estimated queued seconds) answers deterministic
   backpressure (``retry_after_s`` = the predicted time to drain what is
   already queued), and when the PR 12 ``SLOMonitor`` burn signal fires
   the daemon load-sheds the LOWEST-priority tenants first — every shed
   is a ``HealthEvent(kind="shed")`` + ledger row, observable in
   ``obs.report``/``obs.live``, never silent.
2. **Crash durability** — every accepted submit is fsync'd into the
   request journal BEFORE it touches the fleet; every ``snapshot_every``
   served requests the daemon writes a fingerprint-verified fleet
   snapshot (``SessionFleet.snapshot_all``) and compacts the journal to
   its watermark.  ``DFMDaemon.recover`` restores + replays to device
   state bit-equal to an uninterrupted run.
3. **Zero-downtime handoff** — ``DFMDaemon.takeover`` implements the
   successor side of the blue/green swap (``daemon.lifecycle``): warm
   from snapshot + journal, receive the listening socket fd from the
   draining predecessor, replay the delta, serve.  No connection is ever
   refused; ``handoff_gap_ms`` is recorded and gated.

Jax enters only through the fleet the daemon is handed (CLI:
``python -m dfm_tpu.daemon``); the front door itself — queue,
admission, journal, protocol — never touches a device.
"""

from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from ..obs.trace import current_tracer, finish_request, request_clock
from ..robust.health import FitHealth, HealthEvent
from .journal import Journal
from .lifecycle import recv_listener, restore_daemon_state, send_listener
from .protocol import DaemonClient, make_listener, recv_json, send_json

__all__ = ["DaemonConfig", "DFMDaemon"]


def _live_observe(ev: dict) -> None:
    from ..obs.live import observe
    observe(ev)


def _slo_breached() -> bool:
    from ..obs.live import plane
    return bool(plane().slo.breached)


@dataclasses.dataclass(frozen=True)
class DaemonConfig:
    """Front-door knobs (validated at construction, like RobustPolicy)."""

    queue_max: int = 64             # bounded queue: requests
    work_max_s: Optional[float] = None   # and/or estimated queued seconds
    tick_requests: int = 8          # max requests folded into one pump
    snapshot_every: int = 0         # snapshot + compact cadence (0 = off)
    retry_after_floor_s: float = 0.05
    # tenant -> priority (higher = shed later); unlisted tenants get 0.
    priority: Optional[Dict[str, int]] = None
    accept_poll_s: float = 0.1      # listener poll (handoff fencing)
    request_timeout_s: float = 600.0

    def __post_init__(self):
        def bad(field, want):
            raise ValueError(f"DaemonConfig.{field} {want}; got "
                             f"{getattr(self, field)!r}")
        if int(self.queue_max) < 1:
            bad("queue_max", "must be >= 1")
        if self.work_max_s is not None and not self.work_max_s > 0:
            bad("work_max_s", "must be None (no work cap) or > 0 seconds")
        if int(self.tick_requests) < 1:
            bad("tick_requests", "must be >= 1")
        if int(self.snapshot_every) < 0:
            bad("snapshot_every", "must be >= 0 (0 disables)")
        if not self.retry_after_floor_s > 0:
            bad("retry_after_floor_s", "must be > 0")
        if not self.accept_poll_s > 0:
            bad("accept_poll_s", "must be > 0")
        if not self.request_timeout_s > 0:
            bad("request_timeout_s", "must be > 0")


class _Ticket:
    __slots__ = ("req", "seq", "resp", "done", "t_enq", "trace")

    def __init__(self, req: dict):
        self.req = req
        self.seq = 0
        self.resp: Optional[dict] = None
        self.done = threading.Event()
        self.t_enq = time.perf_counter()
        # Request-scoped span context (obs.trace), carried BY REFERENCE
        # through the queue, the fleet tick, and the ack: each seam
        # stamps one request_clock() boundary into this dict.
        tr = req.get("trace")
        self.trace: Optional[dict] = tr if isinstance(tr, dict) else None
        if self.trace is not None:
            self.trace["t_admit"] = request_clock()
            self.trace["owner"] = "daemon"   # the ack emits the waterfall


class DFMDaemon:
    """See module docstring.  Construct over an open fleet + journal, or
    via :meth:`recover` (crash restart) / :meth:`takeover` (blue/green
    successor)."""

    def __init__(self, fleet, journal: Journal, *,
                 config: Optional[DaemonConfig] = None,
                 snapshot_dir: Optional[str] = None,
                 served_ids=()):
        self._fleet = fleet
        self._journal = journal
        self.config = config or DaemonConfig()
        self.snapshot_dir = snapshot_dir
        self.health = FitHealth(engine="daemon")
        self._lock = threading.Lock()          # queue + counters
        self._fleet_lock = threading.Lock()    # serializes fleet access
        self._queue: List[_Ticket] = []
        self._have_work = threading.Condition(self._lock)
        self._served_ids = set(served_ids)
        self._last_answer: Dict[str, dict] = {}
        self._listener: Optional[socket.socket] = None
        self._accepting = False
        self._stopping = False
        self._handlers = 0
        self._fence_ack = threading.Event()   # accept loop saw the fence
        self._serve_thread: Optional[threading.Thread] = None
        self.n_requests = 0
        self.n_served = 0
        self.n_backpressure = 0
        self.n_shed = 0
        self.dedup_hits = 0
        self.n_snapshots = 0
        self.n_handoffs = 0
        self._since_snapshot = 0
        # Maintenance visibility: journal seq at which the most recent
        # params swap (live-plane swaps_total movement) became visible.
        self._seen_swaps = 0
        self._last_swap_seq: Optional[int] = None
        # Per-tenant admission price from the calibrated cost model: one
        # query = one dispatch floor + max_iters warm-EM iterations at
        # the tenant's padded class shape.  Deterministic given the
        # profile registry; used for work-bounded queues and the
        # deterministic retry_after_s quote.
        from ..fleet.admission import _load_model
        m = _load_model(None, None)
        self._est_s: Dict[str, float] = {}
        for name, (bucket, slot) in fleet._slot_of.items():
            T_cap, N_max, k_max = bucket.dims
            self._est_s[name] = float(
                m.dispatch_floor_s
                + slot.max_iters * m.iter_s(N_max, T_cap, k_max, "seq"))
        if self.config.priority:
            unknown = set(self.config.priority) - set(self._est_s)
            if unknown:
                raise ValueError(
                    f"DaemonConfig.priority names unknown tenants "
                    f"{sorted(unknown)} (fleet has "
                    f"{sorted(self._est_s)})")

    # -- constructors --------------------------------------------------
    @classmethod
    def recover(cls, snapshot_dir: str, journal_path: str, *,
                backend=None, robust=None, resident: Optional[int] = None,
                max_classes: int = 3, runs: Optional[str] = None,
                config: Optional[DaemonConfig] = None) -> "DFMDaemon":
        """Crash restart: restore the snapshot, replay the journal tail,
        resume journaling after the recovered watermark.  The recovered
        daemon's answers are bit-equal to an uninterrupted twin's."""
        fleet, wm, _ = restore_daemon_state(
            snapshot_dir, journal_path, backend=backend, robust=robust,
            resident=resident, max_classes=max_classes, runs=runs)
        ids = [e["id"] for e in Journal.read(journal_path) if "id" in e]
        journal = Journal(journal_path)
        return cls(fleet, journal, config=config,
                   snapshot_dir=snapshot_dir, served_ids=ids)

    @classmethod
    def takeover(cls, addr, snapshot_dir: str, journal_path: str, *,
                 backend=None, robust=None,
                 resident: Optional[int] = None, max_classes: int = 3,
                 runs: Optional[str] = None,
                 config: Optional[DaemonConfig] = None,
                 reply_to: Optional[str] = None):
        """Blue/green successor: warm up, take the listener from the
        predecessor at ``addr``, replay the delta.  Returns
        ``(daemon, listener, gap_ms)`` — call ``serve_forever(listener)``
        next.  Zero queries are dropped: the listener fd moves between
        processes without closing, so the kernel backlog bridges the
        gap."""
        # 1. Warm: restore + replay what the predecessor has snapshotted
        #    and journaled so far (compiles the serving executables).
        fleet, wm, _ = restore_daemon_state(
            snapshot_dir, journal_path, backend=backend, robust=robust,
            resident=resident, max_classes=max_classes, runs=runs)
        # 2. Ask the predecessor to drain and hand over its listener.
        reply_to = reply_to or os.path.join(
            os.path.dirname(os.path.abspath(journal_path)),
            f"handoff-{os.getpid()}.sock")
        reply_sock = make_listener(reply_to, backlog=1)
        try:
            # Single-shot on purpose: after the predecessor fences its
            # accept loop, a RETRIED handoff request would sit in the
            # listener backlog forever — any failure must surface, not
            # silently spin.
            DaemonClient(addr, timeout=600.0,
                         connect_retries=0).handoff(reply_to)
            listener, meta = recv_listener(reply_sock, timeout=600.0)
        finally:
            reply_sock.close()
            if os.path.exists(reply_to):
                os.unlink(reply_to)
        # 3. Replay the delta the predecessor served while we warmed.
        from .lifecycle import replay_entries
        tail = Journal.read(journal_path, after=wm,
                            upto=int(meta["last_seq"]))
        replay_entries(fleet, tail)
        gap_ms = max(0.0, (time.clock_gettime(time.CLOCK_MONOTONIC)
                           - float(meta["t_stop"])) * 1e3)
        ids = [e["id"] for e in Journal.read(journal_path) if "id" in e]
        journal = Journal(journal_path)
        self = cls(fleet, journal, config=config,
                   snapshot_dir=snapshot_dir, served_ids=ids)
        self.n_handoffs += 1
        self.health.record(HealthEvent(
            chunk=-1, iteration=int(meta["last_seq"]), kind="handoff",
            action="adopted", session=fleet.fleet_id,
            detail=(f"took listener from predecessor; gap "
                    f"{gap_ms:.1f} ms, replayed {len(tail)} entries")))
        self._emit(action="handoff", role="successor", gap_ms=gap_ms,
                   n_replayed=len(tail), last_seq=int(meta["last_seq"]))
        return self, listener, gap_ms

    # -- observability -------------------------------------------------
    def _emit(self, **ev) -> None:
        ev = dict(session=self._fleet.fleet_id, **ev)
        tr = current_tracer()
        if tr is not None:
            tr.emit("daemon", **ev)
        else:
            _live_observe({"t": time.perf_counter(), "kind": "daemon",
                           **ev})

    # -- admission -----------------------------------------------------
    def _priority(self, tenant: str) -> int:
        return int((self.config.priority or {}).get(tenant, 0))

    def _shed_floor(self) -> Optional[int]:
        """Priority class currently being sacrificed, or None.

        When the SLO burn signal is FIRING, requests from the lowest
        priority class are shed.  With a single class (nobody marked
        out as less important) shedding everything would be a full
        outage, so the single-class fleet sheds only when the queue is
        ALSO at least half full — backpressure remains the first line.
        Deterministic given (burn state, queue depth)."""
        if not _slo_breached():
            return None
        prios = {self._priority(t) for t in self._est_s}
        lo = min(prios)
        if len(prios) == 1 and len(self._queue) < (self.config.queue_max
                                                   + 1) // 2:
            return None
        return lo

    def _queued_work_s(self) -> float:
        return sum(self._est_s.get(tk.req.get("tenant"), 0.0)
                   for tk in self._queue)

    def _admit(self, req: dict):
        """Admission under the queue lock: a response dict (rejection /
        duplicate short-circuit) or an enqueued ticket."""
        tenant = req.get("tenant")
        if tenant not in self._est_s:
            return {"ok": False,
                    "error": f"unknown tenant {tenant!r} (fleet has "
                             f"{sorted(self._est_s)})"}
        rid = req.get("id")
        self.n_requests += 1
        if rid is not None and rid in self._served_ids:
            # Idempotent retry (client reconnected after a crash or
            # handoff): the state change already happened — answer the
            # tenant's latest served result WITHOUT touching the fleet.
            # Dedup is a first-class observable, not a silent
            # short-circuit: counted in status(), emitted as a daemon
            # event, and answered with its own (two-stage) waterfall so
            # "every answered request has a request event" holds.
            self.dedup_hits += 1
            resp = dict(self._last_answer.get(
                tenant, {"ok": True, "note": "already applied"}))
            resp["duplicate"] = True
            self._emit(action="dedup", tenant=tenant, id=str(rid))
            trc = req.get("trace")
            if isinstance(trc, dict):
                trc.setdefault("t_admit", request_clock())
                trc["t_ack"] = request_clock()
                rev = finish_request(trc, tenant=str(tenant),
                                     session=self._fleet.fleet_id,
                                     dedup=True)
                tr = current_tracer()
                if tr is not None:
                    tr.emit("request", t=trc.get("t_ack"), **rev)
                else:
                    _live_observe({"t": trc.get("t_ack"),
                                   "kind": "request", **rev})
                resp["trace_id"] = rev["trace_id"]
            return resp
        floor = self._shed_floor()
        if floor is not None and self._priority(tenant) <= floor:
            self.n_shed += 1
            self.health.record(HealthEvent(
                chunk=-1, iteration=self._journal.last_seq, kind="shed",
                action="rejected", tenant=str(tenant),
                session=self._fleet.fleet_id,
                detail=(f"SLO burn firing; shed priority class "
                        f"<= {floor} (queue depth "
                        f"{len(self._queue)})")))
            return {"ok": False, "shed": True, "tenant": tenant,
                    "error": "overload: SLO burn firing and this "
                             "tenant's priority class is being shed"}
        depth = len(self._queue)
        work = self._queued_work_s()
        over_depth = depth >= self.config.queue_max
        over_work = (self.config.work_max_s is not None
                     and work + self._est_s[tenant]
                     > self.config.work_max_s)
        if over_depth or over_work:
            retry = max(self.config.retry_after_floor_s, work)
            self.n_backpressure += 1
            self._emit(action="backpressure", tenant=tenant, depth=depth,
                       queued_work_s=round(work, 6),
                       retry_after_s=round(retry, 6))
            return {"ok": False, "backpressure": True,
                    "retry_after_s": retry, "depth": depth,
                    "error": "queue full"
                             if over_depth else "queued work over budget"}
        tk = _Ticket(req)
        self._queue.append(tk)
        self._emit(action="request", tenant=tenant, op="submit",
                   depth=len(self._queue))
        self._have_work.notify_all()
        return tk

    # -- the pump ------------------------------------------------------
    def _pump(self) -> int:
        """Serve one batch: journal -> submit -> drain -> answer.
        Returns the number of tickets answered.  Runs on whatever
        thread calls it, always under ``_fleet_lock``."""
        with self._lock:
            batch = self._queue[:self.config.tick_requests]
            del self._queue[:len(batch)]
        if not batch:
            return 0
        t_batch = request_clock() if any(tk.trace is not None
                                         for tk in batch) else None
        for tk in batch:
            if tk.trace is not None:
                tk.trace["t_batch"] = t_batch   # queue_wait ends here
        with self._fleet_lock:
            import numpy as np
            # Validate + enqueue FIRST: a request the fleet rejects
            # (bad row shape, capacity overrun) is answered as an error
            # and never journaled — a journaled entry must replay
            # cleanly on every future restart, so validation gates the
            # journal, not the other way around.
            accepted = []
            for tk in batch:
                rows = tk.req.get("rows")
                mask = tk.req.get("mask")
                try:
                    self._fleet.submit(
                        tk.req["tenant"],
                        None if rows is None
                        else np.asarray(rows, np.float64),
                        mask=None if mask is None else np.asarray(mask),
                        trace=tk.trace)
                except (ValueError, TypeError) as e:
                    tk.resp = {"ok": False, "tenant": tk.req["tenant"],
                               "error": f"rejected: {e}"}
                    tk.done.set()
                    continue
                accepted.append(tk)
            for tk in accepted:
                # Durability before the state change: once journaled, a
                # crash replays it; enqueued-but-unjournaled submits die
                # with the process UNACKED (client retries, dedup holds).
                # "trace" rides into the journal so replay (crash
                # recovery, takeover delta) keeps the original trace_id
                # — continuity across the daemon's process boundaries.
                tk.seq = self._journal.append(
                    {k: tk.req.get(k) for k in ("id", "tenant", "rows",
                                                "mask", "trace")})
            if not accepted:
                return len(batch)
            try:
                outs = self._fleet.drain()
            except Exception as e:
                # Fail-stop: a tick the guarded fleet could not serve
                # leaves device state unknowable — answer everyone,
                # stop, and let the supervisor restart us into a clean
                # snapshot+journal replay (which DOES include this
                # batch: it was journaled and will be applied).
                self.health.record(HealthEvent(
                    chunk=-1, iteration=self._journal.last_seq,
                    kind="dispatch_error", action="fatal",
                    session=self._fleet.fleet_id,
                    detail=f"fleet tick failed: {e!r}; daemon stopping"))
                for tk in accepted:
                    tk.resp = {"ok": False,
                               "error": f"fleet tick failed: {e!r}; "
                                        "daemon restarting"}
                    tk.done.set()
                self._stopping = True
                with self._lock:
                    self._have_work.notify_all()
                raise
            by_tenant: Dict[str, list] = {t: list(u)
                                          for t, u in outs.items()}
            for tk in accepted:
                upd = by_tenant[tk.req["tenant"]].pop(0)
                resp = {
                    "ok": True, "tenant": tk.req["tenant"],
                    "t": int(upd.t), "n_iters": int(upd.n_iters),
                    "converged": bool(upd.converged),
                    "diverged": bool(upd.diverged),
                    "nowcast": np.asarray(upd.nowcast).tolist(),
                    "forecast_y": np.asarray(
                        upd.forecasts["y"]).tolist(),
                }
                with self._lock:
                    if tk.req.get("id") is not None:
                        self._served_ids.add(tk.req["id"])
                    self._last_answer[tk.req["tenant"]] = dict(resp)
                    self.n_served += 1
                    self._since_snapshot += 1
                if tk.trace is not None:
                    # The ack boundary closes the waterfall: stages are
                    # adjacent deltas of one clock, so they sum to the
                    # measured e2e exactly.
                    tk.trace["t_ack"] = request_clock()
                    rev = finish_request(tk.trace,
                                         tenant=str(tk.req["tenant"]),
                                         session=self._fleet.fleet_id,
                                         seq=int(tk.seq))
                    tr = current_tracer()
                    if tr is not None:
                        tr.emit("request", t=tk.trace["t_ack"], **rev)
                    else:
                        _live_observe({"t": tk.trace["t_ack"],
                                       "kind": "request", **rev})
                    resp["trace_id"] = rev["trace_id"]
                tk.resp = resp
                tk.done.set()
            if (self.config.snapshot_every
                    and self.snapshot_dir
                    and self._since_snapshot >= self.config.snapshot_every):
                self._snapshot_locked()
        return len(batch)

    def _snapshot_locked(self, compact: bool = True) -> str:
        """Snapshot (+ journal compaction) — caller holds ``_fleet_lock``.

        ``compact=False`` is for the handoff's final snapshot: the
        successor warmed from an OLDER snapshot and still needs the
        journal entries between its warm watermark and ``last_seq`` to
        replay the delta — compacting here would destroy exactly those.
        The successor compacts at its own next snapshot cadence."""
        wm = self._journal.last_seq
        path = self._fleet.snapshot_all(self.snapshot_dir, journal_seq=wm)
        if compact:
            self._journal.compact(wm)
        with self._lock:
            self.n_snapshots += 1
            self._since_snapshot = 0
        return path

    # -- request dispatch ----------------------------------------------
    def handle(self, req: dict) -> dict:
        """Process one protocol request to a response dict.  The socket
        loop calls this per connection; tests call it directly (no
        sockets) — identical code path either way."""
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pong": True,
                    "fleet": self._fleet.fleet_id}
        if op == "status":
            return self.status()
        if op == "submit":
            with self._lock:
                got = self._admit(req)
            if isinstance(got, dict):
                return got
            if self._serve_thread is None:
                self._pump()           # no pump thread: serve inline
            if not got.done.wait(self.config.request_timeout_s):
                return {"ok": False, "error": "request timed out in "
                                              "queue"}
            return got.resp
        if op == "snapshot":
            if not self.snapshot_dir:
                return {"ok": False,
                        "error": "daemon has no snapshot_dir"}
            self._drain_queue()
            with self._fleet_lock:
                path = self._snapshot_locked()
            return {"ok": True, "manifest": path,
                    "journal_seq": self._journal.last_seq}
        if op == "shutdown":
            self._begin_drain()
            self._drain_queue(wait_handlers=True)
            if self.snapshot_dir:
                with self._fleet_lock:
                    self._snapshot_locked()
            self._stopping = True
            with self._lock:
                self._have_work.notify_all()
            return {"ok": True, "stopped": True,
                    "last_seq": self._journal.last_seq}
        if op == "handoff":
            return self._handoff(req)
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _begin_drain(self):
        self._fence_ack.clear()
        self._accepting = False

    def _drain_queue(self, wait_handlers: bool = False):
        """Serve until the queue is empty (pump inline if no thread).

        ``wait_handlers=True`` (handoff/shutdown) additionally waits for
        every OTHER in-flight connection handler to finish: a request
        that connected before the accept fence but has not enqueued yet
        must be answered before the drain is complete.  First it waits
        for the accept loop to ACKNOWLEDGE the fence — a connection it
        accepted a microsecond before parking is counted in
        ``_handlers`` before the acknowledgment, so the barrier below
        cannot miss it."""
        if wait_handlers and self._listener is not None:
            self._fence_ack.wait(
                timeout=10.0 * self.config.accept_poll_s + 5.0)
        while True:
            with self._lock:
                busy = bool(self._queue) or (wait_handlers
                                             and self._handlers > 1)
            if not busy:
                # Wait for an in-flight pump batch to finish answering.
                with self._fleet_lock:
                    pass
                with self._lock:
                    if not self._queue and not (wait_handlers
                                                and self._handlers > 1):
                        return
                continue
            if self._serve_thread is None:
                self._pump()
            else:
                time.sleep(0.01)

    def _handoff(self, req: dict) -> dict:
        """Predecessor side of the blue/green swap: fence the accept
        loop, drain every in-flight ticket, final snapshot, pass the
        listener fd, stop."""
        reply_to = req.get("reply_to")
        if not reply_to:
            return {"ok": False, "error": "handoff needs reply_to"}
        if self._listener is None:
            return {"ok": False, "error": "daemon has no listener to "
                                          "hand off (not serving?)"}
        if not self.snapshot_dir:
            return {"ok": False, "error": "daemon has no snapshot_dir"}
        self._begin_drain()
        self._drain_queue(wait_handlers=True)
        with self._fleet_lock:
            self._snapshot_locked(compact=False)
            # CLOCK_MONOTONIC is system-wide on one host: the successor
            # (another process) subtracts it from its own reading to get
            # the handoff gap.  perf_counter's epoch is per-process and
            # time.time() steps under NTP — both would lie here.
            t_stop = time.clock_gettime(time.CLOCK_MONOTONIC)
            meta = {"last_seq": self._journal.last_seq, "t_stop": t_stop,
                    "snapshot_dir": self.snapshot_dir}
            try:
                send_listener(reply_to, self._listener, meta)
            except OSError as e:
                self._accepting = True   # successor gone: keep serving
                return {"ok": False,
                        "error": f"handoff fd transfer to {reply_to!r} "
                                 f"failed: {e!r}"}
        self.n_handoffs += 1
        self.health.record(HealthEvent(
            chunk=-1, iteration=self._journal.last_seq, kind="handoff",
            action="released", session=self._fleet.fleet_id,
            detail=f"listener passed to {reply_to!r}; drained + "
                   "snapshotted"))
        self._emit(action="handoff", role="predecessor",
                   last_seq=self._journal.last_seq)
        self._stopping = True
        with self._lock:
            self._have_work.notify_all()
        return {"ok": True, "last_seq": self._journal.last_seq,
                "t_stop": t_stop}

    def status(self) -> dict:
        from ..obs.live import plane
        pl = plane()
        with self._lock:
            depth = len(self._queue)
            work = self._queued_work_s()
        # Model-quality trail: the live plane's per-tenant drift score +
        # hot-swap counters (fed by fleet.run_maintenance events), and
        # the journal seq at which the latest swap became visible —
        # answers were served from the OLD params up to that seq.
        ds = pl.drift_status()
        drift = {t: {"drift_score": round(float(v.get(
                         "drift_score", 0.0)), 6),
                     "breached": bool(v.get("breached")),
                     "n_fired": int(v.get("n_fired", 0))}
                 for t, v in ds.get("per_tenant", {}).items()
                 if t in self._est_s}
        counters = pl.registry.snapshot().get("counters", {})
        swaps = {t: int(counters.get(f"swaps_total{{tenant={t}}}", 0))
                 for t in self._est_s}
        n_swaps = sum(swaps.values())
        if n_swaps > self._seen_swaps:
            self._seen_swaps = n_swaps
            self._last_swap_seq = self._journal.last_seq
        return {
            "ok": True, "fleet": self._fleet.fleet_id,
            "tenants": sorted(self._est_s),
            "tiers": {t: self._fleet.tier(t) for t in self._est_s},
            "queue_depth": depth, "queued_work_s": work,
            "queue_max": self.config.queue_max,
            "n_requests": self.n_requests, "n_served": self.n_served,
            "n_backpressure": self.n_backpressure,
            "n_shed": self.n_shed, "dedup_hits": self.dedup_hits,
            "n_snapshots": self.n_snapshots,
            "n_handoffs": self.n_handoffs,
            "journal_seq": self._journal.last_seq,
            "slo": plane().slo.status(),
            "drift": {"armed": bool(ds.get("armed")),
                      "per_tenant": drift,
                      "swaps": {t: n for t, n in swaps.items() if n},
                      "last_swap_seq": self._last_swap_seq},
        }

    # -- socket serving -------------------------------------------------
    def _serve_loop(self):
        while not self._stopping:
            with self._lock:
                if not self._queue:
                    self._have_work.wait(timeout=0.2)
                has = bool(self._queue)
            if has:
                self._pump()

    def _handle_conn(self, conn: socket.socket):
        # NB: self._handlers was incremented by the ACCEPT loop before
        # this thread was spawned — counting here instead would leave a
        # window where a just-accepted connection is invisible to the
        # handoff/shutdown drain barrier (which waits on _handlers),
        # letting the predecessor close the fleet under our feet.
        try:
            conn.settimeout(self.config.request_timeout_s)
            req = recv_json(conn)
            if req is not None:
                try:
                    resp = self.handle(req)
                except Exception as e:   # answer, don't drop the conn
                    resp = {"ok": False, "error": f"internal: {e!r}"}
                send_json(conn, resp)
        except (OSError, ValueError):
            pass                      # client went away mid-request
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._handlers -= 1

    def serve_forever(self, listener: socket.socket) -> None:
        """Serve until ``shutdown`` or a completed handoff.  Owns the
        accept loop; the pump runs on a dedicated thread so a slow
        fleet tick never blocks accepting (admission keeps rejecting
        above the bounded queue)."""
        self._listener = listener
        self._accepting = True
        self._stopping = False
        self._serve_thread = threading.Thread(
            target=self._serve_loop, name="dfm-daemon-pump", daemon=True)
        self._serve_thread.start()
        listener.settimeout(self.config.accept_poll_s)
        try:
            while not self._stopping:
                if not self._accepting:
                    self._fence_ack.set()
                    time.sleep(self.config.accept_poll_s)
                    continue
                try:
                    conn, _ = listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                with self._lock:
                    self._handlers += 1
                threading.Thread(target=self._handle_conn,
                                 args=(conn,), daemon=True).start()
        finally:
            self._stopping = True
            self._fence_ack.set()
            with self._lock:
                self._have_work.notify_all()
            self._serve_thread.join(timeout=5.0)
            self._serve_thread = None
            try:
                listener.close()     # fd was dup'd to a successor on
            except OSError:          # handoff; closing ours is safe
                pass
            self._listener = None

    def close(self):
        self._stopping = True
        self._journal.close()
        self._fleet.close()

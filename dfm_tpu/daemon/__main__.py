"""CLI: run (or take over) the serving daemon.

Cold start / crash restart (always restores from the snapshot dir, then
replays the journal tail — a fresh dir + empty journal is a fresh
daemon only if a snapshot exists; bootstrap one with
``SessionFleet.snapshot_all`` or the ``snapshot`` protocol op):

    python -m dfm_tpu.daemon --listen /tmp/dfm.sock \
        --snapshot-dir /tmp/dfm-snap --journal /tmp/dfm.journal \
        [--snapshot-every 32] [--priority news=1,fast=0] [--queue-max 64]

Blue/green handoff (successor; predecessor keeps serving until we are
warm, then passes its listener fd and exits — zero dropped queries):

    python -m dfm_tpu.daemon --takeover /tmp/dfm.sock \
        --snapshot-dir /tmp/dfm-snap --journal /tmp/dfm.journal

``DFM_COMPILE_CACHE`` defaults to ``.dfm_cache/`` here (like bench/
``__graft_entry__``) so restart + takeover warm executables from disk.
"""

from __future__ import annotations

import argparse
import os
import sys


def _parse_priority(s):
    out = {}
    for part in filter(None, (s or "").split(",")):
        name, _, v = part.partition("=")
        out[name] = int(v)
    return out or None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dfm_tpu.daemon",
        description="robust serving daemon over a restored fleet")
    ap.add_argument("--listen", help="address to bind (unix path or "
                                     "host:port); required unless "
                                     "--takeover")
    ap.add_argument("--takeover", metavar="ADDR",
                    help="blue/green: take the listener over from the "
                         "daemon at ADDR instead of binding")
    ap.add_argument("--snapshot-dir", required=True)
    ap.add_argument("--journal", required=True)
    ap.add_argument("--snapshot-every", type=int, default=32)
    ap.add_argument("--queue-max", type=int, default=64)
    ap.add_argument("--tick-requests", type=int, default=8)
    ap.add_argument("--priority", default="",
                    help="tenant=prio[,tenant=prio...]; higher sheds "
                         "later under SLO burn")
    ap.add_argument("--resident", type=int, default=None,
                    help="cap on hot fleet lanes (tiering)")
    ap.add_argument("--runs", default=None,
                    help="RunStore dir for the admission cost model")
    args = ap.parse_args(argv)
    if not args.listen and not args.takeover:
        ap.error("need --listen ADDR or --takeover ADDR")

    # Warm executables from the persistent compile cache, like the other
    # long-lived CLIs (bench, __graft_entry__).
    os.environ.setdefault("DFM_COMPILE_CACHE", ".dfm_cache")

    from . import DaemonConfig, DFMDaemon, make_listener
    cfg = DaemonConfig(queue_max=args.queue_max,
                       tick_requests=args.tick_requests,
                       snapshot_every=args.snapshot_every,
                       priority=_parse_priority(args.priority))
    kw = dict(config=cfg, resident=args.resident, runs=args.runs)
    if args.takeover:
        daemon, listener, gap_ms = DFMDaemon.takeover(
            args.takeover, args.snapshot_dir, args.journal, **kw)
        print(f"dfm-daemon: took over {args.takeover!r} "
              f"(gap {gap_ms:.1f} ms)", file=sys.stderr, flush=True)
    else:
        daemon = DFMDaemon.recover(args.snapshot_dir, args.journal, **kw)
        listener = make_listener(args.listen)
        print(f"dfm-daemon: serving on {args.listen!r} "
              f"({len(daemon.status()['tenants'])} tenants)",
              file=sys.stderr, flush=True)
    try:
        daemon.serve_forever(listener)
    finally:
        daemon.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Append-only request journal (jax-free).

One JSON object per line, fsync'd per append: the daemon journals every
accepted submit BEFORE it touches the fleet, so a SIGKILL at any point
loses nothing that was acknowledged — a restarted daemon restores the
latest fleet snapshot and replays the journal tail after its watermark,
arriving at device state bit-equal to an uninterrupted run
(tests/test_daemon.py).

A crash mid-append leaves a torn final line; ``read`` skips it (and any
mid-file corruption) by count rather than raising — a damaged journal
line is a lost un-acked request, not a reason to refuse every other
entry.  ``compact(upto)`` atomically rewrites the file without entries
already covered by a snapshot, bounding growth at one snapshot period.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import List, Optional

__all__ = ["Journal"]


def _fsync_dir(d: str) -> None:
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class Journal:
    """Durable append-only JSONL journal with monotone ``seq`` stamps."""

    def __init__(self, path: str):
        self.path = str(path)
        self.torn_lines = 0
        self._last_seq = 0
        for e in self.read(self.path):          # crash recovery: resume seq
            self._last_seq = max(self._last_seq, int(e.get("seq", 0)))
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        self._fh = open(self.path, "ab")

    @property
    def last_seq(self) -> int:
        return self._last_seq

    def append(self, entry: dict) -> int:
        """Durably append one entry; returns its ``seq``.  The write is
        flushed AND fsync'd before returning — once a request is
        acknowledged, a power cut cannot unwind it."""
        if self._fh is None:
            raise RuntimeError("journal is closed")
        self._last_seq += 1
        rec = {"seq": self._last_seq}
        rec.update(entry)
        self._fh.write((json.dumps(rec) + "\n").encode("utf-8"))
        self._fh.flush()
        os.fsync(self._fh.fileno())
        return self._last_seq

    @staticmethod
    def read(path: str, after: int = 0,
             upto: Optional[int] = None) -> List[dict]:
        """Entries with ``after < seq <= upto`` from a journal file —
        usable on a file another process is still appending to (the
        handoff successor tails its predecessor's journal this way).
        Torn/corrupt lines are skipped, never raised."""
        if not os.path.exists(path):
            return []
        out = []
        with open(path, "rb") as f:
            for raw in f.read().split(b"\n"):
                if not raw.strip():
                    continue
                try:
                    e = json.loads(raw)
                except ValueError:
                    continue            # torn tail / damaged line
                if not isinstance(e, dict) or "seq" not in e:
                    continue
                s = int(e["seq"])
                if s > after and (upto is None or s <= upto):
                    out.append(e)
        return out

    def replay(self, after: int = 0) -> List[dict]:
        return self.read(self.path, after=after)

    def compact(self, upto: int) -> int:
        """Atomically drop entries with ``seq <= upto`` (already covered
        by a fleet snapshot).  Returns the number of entries kept."""
        if self._fh is None:
            raise RuntimeError("journal is closed")
        keep = self.replay(after=int(upto))
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".jsonl.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                for e in keep:
                    f.write((json.dumps(e) + "\n").encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            _fsync_dir(d)
            self._fh = open(self.path, "ab")
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            self._fh = open(self.path, "ab")
            raise
        return len(keep)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __repr__(self):
        return f"Journal({self.path!r}, last_seq={self._last_seq})"

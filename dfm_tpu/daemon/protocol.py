"""Wire protocol for the serving daemon (jax-free).

JSON-lines over a stream socket, one request per connection: the client
connects, writes ONE JSON object on one line, reads ONE JSON-line
response, and closes.  Connection-per-request keeps the client trivially
correct across blue/green handoffs — a request that lands during the
swap simply waits in the listener backlog for the successor (the
listening socket itself never closes; see ``lifecycle``).

Values use Python's JSON dialect (``NaN`` literals mark missing panel
entries); both ends are Python, and the journal shares the encoding.

Requests (``op`` selects):

- ``{"op": "submit", "tenant": t, "rows": [[...]]|null, "mask": ...,
  "id": "...", "trace": {"id": "...", "t_send": s}}`` — enqueue one
  update (``rows=null`` = pure re-forecast).  ``id`` is the client's
  idempotency token: retrying a request with the same id after a
  crash/handoff never double-appends (the daemon answers a duplicate
  with a pure re-forecast, flagged ``"duplicate": true``).  ``trace``
  is the request-scoped span context (``obs.trace``): the client births
  a trace_id and stamps ``t_send`` from CLOCK_MONOTONIC; the daemon
  stamps every downstream boundary into the same dict, journals it, and
  answers with ``"trace_id"`` so client and server waterfalls join.
- ``{"op": "ping"}`` / ``{"op": "status"}`` — liveness / introspection.
- ``{"op": "snapshot"}`` — force a fleet snapshot + journal compaction.
- ``{"op": "handoff", "reply_to": path}`` — blue/green: drain, snapshot,
  pass the listener fd to the successor waiting on ``reply_to``.
- ``{"op": "shutdown"}`` — drain and exit.

Responses: ``{"ok": true, ...}`` with per-op payload, or ``{"ok":
false, "error": ...}`` with ``"backpressure": true, "retry_after_s": s``
(bounded queue: slow down and retry) or ``"shed": true`` (overload
load-shed under SLO burn: this tenant's priority class is being
sacrificed; retry later or escalate priority).
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Optional, Tuple, Union

__all__ = ["DaemonClient", "send_json", "recv_json", "make_listener",
           "connect", "parse_addr"]

Addr = Union[str, Tuple[str, int]]

_MAX_LINE = 64 * 1024 * 1024       # 64 MB: a (rows, mask) block is tiny


def parse_addr(addr: Addr) -> Tuple[int, Addr]:
    """Resolve an address to (family, sockaddr).  A string with a path
    separator (or .sock suffix) is a unix socket path; ``host:port``
    strings and (host, port) tuples are TCP."""
    if isinstance(addr, tuple):
        return socket.AF_INET, (str(addr[0]), int(addr[1]))
    a = str(addr)
    if os.sep in a or a.endswith(".sock"):
        return socket.AF_UNIX, a
    if ":" in a:
        host, port = a.rsplit(":", 1)
        return socket.AF_INET, (host or "127.0.0.1", int(port))
    raise ValueError(f"cannot parse daemon address {addr!r}: want a unix "
                     "socket path (contains / or ends in .sock) or "
                     "host:port")


def make_listener(addr: Addr, backlog: int = 128) -> socket.socket:
    """Bind + listen.  The backlog is the zero-downtime buffer: during a
    handoff the kernel parks incoming connections here until the
    successor accepts, so no client sees a refused connection."""
    fam, sa = parse_addr(addr)
    sock = socket.socket(fam, socket.SOCK_STREAM)
    if fam == socket.AF_UNIX:
        if os.path.exists(sa):
            os.unlink(sa)
    else:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind(sa)
    sock.listen(backlog)
    return sock


def connect(addr: Addr, timeout: Optional[float] = None) -> socket.socket:
    fam, sa = parse_addr(addr)
    sock = socket.socket(fam, socket.SOCK_STREAM)
    if timeout is not None:
        sock.settimeout(timeout)
    sock.connect(sa)
    return sock


def send_json(sock: socket.socket, obj: dict) -> None:
    sock.sendall((json.dumps(obj) + "\n").encode("utf-8"))


def recv_json(sock: socket.socket) -> Optional[dict]:
    """Read one newline-terminated JSON object (None on clean EOF)."""
    buf = bytearray()
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            if not buf.strip():
                return None
            break
        buf.extend(chunk)
        if b"\n" in chunk:
            break
        if len(buf) > _MAX_LINE:
            raise ValueError("daemon protocol line exceeds 64 MB")
    line = bytes(buf).split(b"\n", 1)[0]
    return json.loads(line)


class DaemonClient:
    """Blocking client for one daemon address.

    ``request`` opens a fresh connection per call and retries
    connection-level failures (refused / reset / timeout) with bounded
    deterministic backoff — combined with per-request ``id`` dedup on
    the server this gives exactly-once effect from at-least-once
    delivery, across daemon restarts AND handoffs.  Backpressure
    responses are surfaced to the caller by default; ``submit(...,
    wait=True)`` sleeps the advertised ``retry_after_s`` and retries
    until accepted.
    """

    def __init__(self, addr: Addr, timeout: float = 60.0,
                 connect_retries: int = 40,
                 connect_backoff_s: float = 0.25):
        self.addr = addr
        self.timeout = float(timeout)
        self.connect_retries = int(connect_retries)
        self.connect_backoff_s = float(connect_backoff_s)
        self._ids = 0

    def request(self, obj: dict) -> dict:
        last: Exception = RuntimeError("unreachable")
        for attempt in range(self.connect_retries + 1):
            try:
                sock = connect(self.addr, timeout=self.timeout)
                try:
                    send_json(sock, obj)
                    resp = recv_json(sock)
                finally:
                    sock.close()
                if resp is None:       # peer died mid-request: retry
                    raise ConnectionError("daemon closed the connection "
                                          "without answering")
                return resp
            except (ConnectionError, socket.timeout, TimeoutError,
                    FileNotFoundError, OSError) as e:
                last = e
                if attempt < self.connect_retries:
                    time.sleep(self.connect_backoff_s)
        raise ConnectionError(
            f"daemon at {self.addr!r} unreachable after "
            f"{self.connect_retries + 1} attempts: {last}")

    def _next_id(self) -> str:
        self._ids += 1
        return f"c{os.getpid()}-{id(self)}-{self._ids}"

    # -- ops -----------------------------------------------------------
    def submit(self, tenant: str, rows=None, mask=None,
               req_id: Optional[str] = None, wait: bool = False) -> dict:
        """One tenant update.  ``rows`` is a nested list (or numpy-like
        with ``.tolist()``); NaN = missing.  ``wait=True`` honors
        backpressure responses by sleeping ``retry_after_s`` and
        retrying (same id — idempotent) until accepted or shed."""
        for name in ("tolist",):
            f = getattr(rows, name, None)
            if f is not None:
                rows = f()
            f = getattr(mask, name, None)
            if f is not None:
                mask = f()
        from ..obs.trace import new_trace_id, request_clock
        req = {"op": "submit", "tenant": str(tenant), "rows": rows,
               "id": req_id or self._next_id(),
               # Trace birth: one uuid + one clock read per round-trip.
               # Retries reuse the same context (same id, fresh send time
               # would lie about the true client-observed e2e).
               "trace": {"id": new_trace_id(),
                         "t_send": request_clock()}}
        if mask is not None:
            req["mask"] = mask
        while True:
            resp = self.request(req)
            if wait and resp.get("backpressure"):
                time.sleep(float(resp.get("retry_after_s", 0.1)))
                continue
            return resp

    def ping(self) -> dict:
        return self.request({"op": "ping"})

    def status(self) -> dict:
        return self.request({"op": "status"})

    def snapshot(self) -> dict:
        return self.request({"op": "snapshot"})

    def shutdown(self) -> dict:
        return self.request({"op": "shutdown"})

    def handoff(self, reply_to: str) -> dict:
        return self.request({"op": "handoff", "reply_to": str(reply_to)})

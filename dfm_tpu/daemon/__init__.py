"""Serving daemon: a robust socket front door over one fleet.

``python -m dfm_tpu.daemon --listen ADDR --snapshot-dir D --journal J``
runs the daemon; ``DaemonClient(ADDR)`` talks to it (jax-free).  Three
robustness layers — bounded-queue backpressure + SLO-burn load-shedding,
journal + snapshot crash durability (restart replays to bit-equal
answers), and blue/green zero-downtime handoff (``--takeover``).  See
``daemon.server`` for the architecture and ``daemon.protocol`` for the
wire format.

Jax-free in the ``obs`` sense: ``DaemonClient``, ``Journal`` and the
protocol/lifecycle helpers never touch a device or compile anything —
clients and tooling pay no jax runtime cost (the fleet the daemon
serves is the only jax surface, and it loads with the fleet).
"""

from .journal import Journal
from .lifecycle import (recv_listener, replay_entries,
                        restore_daemon_state, send_listener)
from .protocol import (DaemonClient, connect, make_listener, parse_addr,
                       recv_json, send_json)
from .server import DaemonConfig, DFMDaemon

__all__ = ["DFMDaemon", "DaemonConfig", "DaemonClient", "Journal",
           "restore_daemon_state", "replay_entries", "send_listener",
           "recv_listener", "make_listener", "connect", "parse_addr",
           "send_json", "recv_json"]

"""Cost-model-driven shape bucketing for mixed (T, N, k) job mixes.

The packing problem: every distinct padded shape is one more executable
(compile + a dispatch stream of its own), but every job padded into a
bucket pays the bucket's per-iteration cost, not its own.  The planner
balances the two with the calibrated ``obs.cost.CostModel``: sort jobs by
predicted per-iteration cost, then a small exact DP over CONTIGUOUS
partitions of that order picks at most ``max_buckets`` groups minimizing

    sum_buckets [ overhead + dispatches(cap) * dispatch_floor ]
      + sum_jobs iters_j * iter_s(bucket dims)

where a bucket's dims are the elementwise max over its members — so the
DP trades padded-flop waste (big bucket, few executables) against
dispatch/compile overhead (tight buckets, many executables) using the
same coefficients ``obs.advise`` ranks single-fit plans with.  Ties are
broken deterministically: fewer buckets first, then lexicographically
smallest cut positions.

Everything here is jax-free and pure: same inputs -> same plan.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ..obs.cost import CostModel, DEFAULT_COEFFS, em_iter_work

__all__ = ["Bucket", "BucketPlan", "lane_rent_bytes", "plan_buckets",
           "plan_capacity_classes"]


def lane_rent_bytes(dims: Tuple[int, int, int], r_max: int = 0,
                    bytes_per: int = 4) -> float:
    """HBM rent of ONE resident lane of a capacity class: the device
    bytes a tenant occupies just by being hot — padded panel + mask
    (T_cap x N each), the stacked params slice, and its share of the
    per-tick row staging.  This is the "rent" side of the paging
    economics: ``fleet.admission.readmission_cost_s`` prices the other
    side (what paging the tenant back in would cost), and the fleet's
    admission-pressure paging trades the two.  Pure arithmetic,
    deterministic; ``bytes_per`` = device dtype width (4 = f32)."""
    T, N, k = (int(d) for d in dims)
    panel = 2 * T * N                       # Ybuf + Wbuf
    params = N * k + N + 3 * k * k + k      # Lam, R, A/Q/P0, x0
    staging = 2 * max(0, int(r_max)) * N    # rows + rmask slice
    return float(bytes_per * (panel + params + staging))


@dataclass(frozen=True)
class Bucket:
    """One padded shape: ``dims`` = (T, N, k) every member is padded to,
    ``jobs`` = original submit-order indices, ``cap`` = max member
    iteration budget (the bucket program's worst-case chunk count)."""

    dims: Tuple[int, int, int]
    jobs: Tuple[int, ...]
    cap: int


@dataclass
class BucketPlan:
    """The planner's output: buckets plus the waste/cost accounting the
    scheduler and ``obs.advise --jobs`` both report."""

    buckets: List[Bucket]
    bucket_of: List[int]            # job index -> bucket index
    job_pad_waste: List[float]      # per-job padded-flop waste fraction
    pad_waste_frac: float           # aggregate: 1 - true/padded flops
    predicted_wall_s: float         # DP objective value of the chosen plan
    n_executables: int = field(init=False)

    def __post_init__(self):
        self.n_executables = len({b.dims for b in self.buckets})


def _prior_model(device: str = "cpu") -> CostModel:
    prior = DEFAULT_COEFFS.get(device, DEFAULT_COEFFS["cpu"])
    return CostModel(device=device, calibrated=False, **prior)


def _bucket_cost(model: CostModel, dims: Tuple[int, int, int],
                 iters: Sequence[int], chunk: int) -> float:
    """Predicted wall for one bucket: fixed overhead, the dispatch stream
    for the slowest member's cap (plus one smoother dispatch), and every
    member's iterations at the PADDED per-iteration rate."""
    T, N, k = dims
    cap = max(iters)
    nd = model.dispatches(cap, engine="chunked", chunk=chunk, depth=1) + 1
    it = model.iter_s(N, T, k)
    return (model.overhead_s + nd * model.dispatch_floor_s
            + sum(iters) * it)


def plan_buckets(shapes: Sequence[Tuple[int, int, int]],
                 iters: Optional[Sequence[int]] = None, *,
                 max_buckets: int = 3, model: Optional[CostModel] = None,
                 chunk: int = 8) -> BucketPlan:
    """Partition jobs with shapes ``[(T, N, k), ...]`` into at most
    ``max_buckets`` shape buckets minimizing predicted wall time.

    ``iters`` is each job's iteration budget (defaults to 50); ``model``
    a calibrated :class:`~dfm_tpu.obs.cost.CostModel` (defaults to cpu
    priors — relative rankings, which is all bucketing needs, survive
    uncalibrated coefficients).  Deterministic: ties prefer fewer
    buckets, then the lexicographically smallest cut positions.
    """
    B = len(shapes)
    if B == 0:
        return BucketPlan([], [], [], 0.0, 0.0)
    shapes = [(int(T), int(N), int(k)) for (T, N, k) in shapes]
    its = [50] * B if iters is None else [int(x) for x in iters]
    if len(its) != B:
        raise ValueError("iters must match shapes length")
    if any(x < 1 for x in its):
        raise ValueError("iteration budgets must be >= 1")
    m = model if model is not None else _prior_model()
    max_buckets = max(1, int(max_buckets))

    # Deterministic cost order: cheap jobs first, shape then index as
    # tie-breaks so equal-cost shapes stay grouped.
    order = sorted(range(B),
                   key=lambda i: (m.iter_s(shapes[i][1], shapes[i][0],
                                           shapes[i][2]), shapes[i], i))

    # group_cost[i][j]: cost of packing sorted slice [i, j] as ONE bucket.
    dims_ij: List[List[Tuple[int, int, int]]] = [[None] * B for _ in range(B)]
    cost_ij = [[0.0] * B for _ in range(B)]
    for i in range(B):
        T, N, k = shapes[order[i]]
        for j in range(i, B):
            Tj, Nj, kj = shapes[order[j]]
            T, N, k = max(T, Tj), max(N, Nj), max(k, kj)
            dims_ij[i][j] = (T, N, k)
            cost_ij[i][j] = _bucket_cost(
                m, (T, N, k), [its[order[x]] for x in range(i, j + 1)],
                chunk)

    # DP over contiguous partitions: state key (cost, n_groups, cuts)
    # compares deterministically — fewer groups then smaller cuts on ties.
    INF = (float("inf"), 0, ())
    dp = [[INF] * (max_buckets + 1) for _ in range(B + 1)]
    dp[0][0] = (0.0, 0, ())
    for j in range(1, B + 1):
        for g in range(1, max_buckets + 1):
            best = INF
            for i in range(j):
                prev = dp[i][g - 1]
                if prev[0] == float("inf"):
                    continue
                cand = (prev[0] + cost_ij[i][j - 1], g, prev[2] + (i,))
                if cand < best:
                    best = cand
            dp[j][g] = best
    final = min(dp[B][g] for g in range(1, max_buckets + 1))
    cuts = list(final[2]) + [B]

    buckets: List[Bucket] = []
    bucket_of = [0] * B
    for bi in range(len(cuts) - 1):
        lo, hi = cuts[bi], cuts[bi + 1]
        members = tuple(sorted(order[x] for x in range(lo, hi)))
        dims = dims_ij[lo][hi - 1]
        for ji in members:
            bucket_of[ji] = bi
        buckets.append(Bucket(dims=dims, jobs=members,
                              cap=max(its[ji] for ji in members)))

    true_fl = padded_fl = 0.0
    job_waste = [0.0] * B
    for ji in range(B):
        T, N, k = shapes[ji]
        bT, bN, bk = buckets[bucket_of[ji]].dims
        f_true = em_iter_work(N, T, k)[0] * its[ji]
        f_pad = em_iter_work(bN, bT, bk)[0] * its[ji]
        true_fl += f_true
        padded_fl += f_pad
        job_waste[ji] = 1.0 - f_true / f_pad if f_pad > 0 else 0.0
    agg = 1.0 - true_fl / padded_fl if padded_fl > 0 else 0.0
    return BucketPlan(buckets, bucket_of, job_waste, agg, final[0])


def plan_capacity_classes(shapes: Sequence[Tuple[int, int, int]],
                          iters: Optional[Sequence[int]] = None, *,
                          max_classes: int = 3,
                          model: Optional[CostModel] = None) -> BucketPlan:
    """Assign fleet tenants to serving CAPACITY CLASSES.

    ``shapes`` are per-tenant (T_capacity, N, k) — the padded panel each
    tenant needs resident — and ``iters`` the per-TICK warm-EM budget
    (default 5, the serve default).  A class is a bucket whose dims every
    member is padded to; each class costs ONE fused ``serve_update``
    dispatch per tick, so the DP runs with the chunk set to the largest
    budget (the whole tick is one program: ``dispatches == 1`` per class
    in the cost), trading per-tick padded-iteration waste against one
    extra dispatch + executable per additional class — the same
    calibrated coefficients ``obs.advise`` uses, jax-free and
    deterministic.  Returned as a plain :class:`BucketPlan` (class ==
    bucket; ``pad_waste_frac`` is the fleet bench's
    ``fleet_pad_waste_frac``).
    """
    its = ([5] * len(shapes) if iters is None
           else [int(x) for x in iters])
    cap = max(its) if its else 1
    return plan_buckets(shapes, its, max_buckets=max_classes, model=model,
                        chunk=max(1, cap))

"""Job / JobResult containers for the multi-tenant batch scheduler."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

__all__ = ["Job", "JobResult"]


@dataclass(frozen=True)
class Job:
    """One tenant's fit request: a panel plus its model and stop knobs.

    ``Y`` is a fully-observed (T, N) panel (the batched engine has no
    missing-data path; NaNs surface as a per-tenant DIVERGED health, they
    never contaminate bucket-mates).  ``model`` is a
    :class:`dfm_tpu.DynamicFactorModel`; ``init`` optionally overrides the
    PCA initializer with explicit ``DFMParams``-shaped values (already in
    the standardized scale).  ``max_iters``/``tol`` stop this tenant
    independently of everyone else sharing its bucket.
    """

    Y: Any
    model: Any
    tenant: Optional[str] = None
    init: Any = None
    max_iters: int = 50
    tol: float = 1e-6


@dataclass
class JobResult:
    """Per-tenant outcome: the sliced-back fit plus queue telemetry.

    ``fit`` is a full :class:`dfm_tpu.FitResult` (params / factors /
    logliks / health), numerically identical to running ``fit()`` on the
    job alone.  ``queue_wait_s`` measures submit -> bucket-launch,
    ``compute_s`` the bucket's device wall (shared by bucket-mates), and
    ``pad_waste_frac`` the fraction of this tenant's padded flops that
    were pure padding.
    """

    tenant: str
    fit: Any
    bucket: int
    shape: Tuple[int, int, int]  # (T, N, k)
    queue_wait_s: float
    compute_s: float
    pad_waste_frac: float
    telemetry: Any = field(default=None)

    @property
    def converged(self) -> bool:
        return bool(self.fit.converged)

    @property
    def loglik(self) -> float:
        return self.fit.loglik

"""Multi-tenant submit(): mixed-shape jobs -> bucketed fused batch fits.

The serving path for heterogeneous traffic: every job is padded into its
cost-model-chosen bucket (k via inert factors, N via inert zero-weight
series, T via the info-form trailing mask — all three exactness-proven
seams from ``estim.batched``) and each bucket runs as ONE fused chunked
program with per-tenant convergence freezes, so B tenants pay
2 + ceil(cap/chunk) tunnel dispatches per BUCKET instead of per job.
Results slice back per tenant, numerically identical to a lone
``fit()`` of the same job (x64 bit-exact; pinned by tests/test_sched.py).

Jobs whose models differ structurally (estimate_A / estimate_Q /
estimate_init — static branches of the jitted program) can never share an
executable, so they are grouped first and bucketed within each group.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..backends import cpu_ref
from ..robust.guard import GuardFailure
from ..robust.health import FitHealth, HealthEvent
from ..estim.batched import (_smooth_impl, make_hetero, pad_panel_to_n,
                             pad_panel_to_t, pad_params_to_k,
                             pad_params_to_n, run_batched_em,
                             slice_params_to_k, slice_params_to_n,
                             stack_params, unstack_params)
from ..estim.em import EMConfig
from ..obs.cost import CostModel, em_iter_work, fit_cost_model
from ..obs.trace import current_request, current_tracer, shape_key
from ..ops.precision import default_compute_dtype
from ..utils.data import standardize, validate_panel
from .buckets import BucketPlan, plan_buckets
from .jobs import Job, JobResult

__all__ = ["submit", "registry_cost_model"]


def live_observe(ev: dict) -> None:
    """Feed the always-on live plane (lazy import, see serve.session)."""
    from ..obs.live import observe
    observe(ev)


def registry_cost_model(runs: Optional[str] = None,
                        device: Optional[str] = None) -> CostModel:
    """The scheduler's default planner input: a ``CostModel`` calibrated
    from the ambient run registry's profile records (``obs.profile``),
    falling back to device priors when the registry is empty/absent —
    bucketing only needs relative rankings, which priors preserve."""
    from ..obs.store import RunStore, device_kind, runs_dir
    if device is None:
        try:
            device = device_kind(str(jax.devices()[0].platform))
        except Exception:
            device = "cpu"
    d = runs_dir(runs)
    profiles: list = []
    if d is not None:
        profiles = [r for r in RunStore(d).load()
                    if r.get("kind") == "profile"]
    return fit_cost_model(profiles, device=device)


def _cfg_key(model) -> tuple:
    """Static program identity: jobs differing here need different
    executables regardless of shape, so they can't share a bucket."""
    return (bool(model.estimate_A), bool(model.estimate_Q),
            bool(model.estimate_init))


def _prep_job(i: int, job: Job):
    """Host prep mirroring ``fit_many`` (itself mirroring ``api.fit``):
    validate, standardize, PCA warm start in the standardized scale."""
    Y = np.asarray(job.Y, np.float64)
    if Y.ndim != 2:
        raise ValueError(f"job {i}: Y must be (T, N); got shape {Y.shape}")
    T, N = Y.shape
    model = job.model
    if model.n_factors > min(T, N):
        raise ValueError(f"job {i}: n_factors={model.n_factors} exceeds "
                         f"min(T, N)={min(T, N)}")
    if T < 2 and model.dynamics == "ar1":
        raise ValueError(f"job {i}: ar1 dynamics needs T >= 2")
    if not np.isfinite(Y).all():
        raise ValueError(f"job {i}: batched fits require fully-observed "
                         "panels (no NaN/mask support); use api.fit")
    validate_panel(Y, check_variance=model.standardize)
    std = None
    if model.standardize:
        Yz, std = standardize(Y)
    else:
        Yz = Y
    if job.init is not None:
        init = job.init
    else:
        init = cpu_ref.pca_init(Yz, model.n_factors,
                                static=(model.dynamics == "static"))
    return Yz, std, init


def _requeue_quarantined(job: Job, tenant: str, bucket: int, reason: str,
                         policy, *, dtype, filter: str, fused_chunk: int,
                         queue_wait: float, shape, tr) -> JobResult:
    """Blast-radius isolation: refit one tenant alone after its bucket was
    quarantined (dispatch retries exhausted, or — under
    ``recover_divergence=True`` — a NaN-poisoned lane).

    The lone fit runs under the SAME policy, so the chunk guard's full
    repair ladder (and ``on_failure="cpu"`` degradation to the NumPy
    oracle) applies per tenant; bucket-mates are never re-run.  The
    quarantine itself is recorded as a ``HealthEvent(kind="quarantine")``
    at the head of the refit's health trail.
    """
    from ..api import TPUBackend, fit
    t0 = time.perf_counter()
    ev = HealthEvent(chunk=-1, iteration=0, kind="quarantine",
                     action="requeued", tenant=tenant, engine="sched",
                     detail=f"bucket {bucket}: {reason}",
                     t=time.perf_counter())
    if tr is not None:
        tr.emit("health", t=ev.t, event=ev.kind, chunk=ev.chunk,
                iteration=ev.iteration, action=ev.action, detail=ev.detail,
                engine=ev.engine, tenant=ev.tenant)
    try:
        f = fit(job.model, job.Y,
                backend=TPUBackend(dtype=dtype, filter=filter,
                                   fused_chunk=fused_chunk, robust=policy),
                max_iters=job.max_iters, tol=job.tol, init=job.init)
    except GuardFailure as e:
        raise GuardFailure(
            f"tenant {tenant!r} was quarantined from bucket {bucket} "
            f"({reason}) and its lone refit failed too: {e}",
            e.health, e.last_good, e.lls, e.p_iters) from e
    h = f.health
    if h is None:                       # defensive: policy is non-None here
        h = FitHealth(engine="sched")
        f = dataclasses.replace(f, health=h)
    for hev in h.events:
        if not hev.tenant:
            hev.tenant = tenant
    h.events.insert(0, ev)
    wall = time.perf_counter() - t0
    T_j, N_j, k_j = shape
    tev = dict(tenant=tenant, bucket=bucket, T=T_j, N=N_j, k=k_j,
               bucket_T=T_j, bucket_N=N_j, bucket_k=k_j,
               queue_wait_s=float(queue_wait), compute_s=float(wall),
               pad_waste_frac=0.0, n_iters=int(len(f.logliks)),
               converged=bool(f.converged), quarantined=True)
    _req = current_request()
    if _req is not None:     # fit_jobs inside a request_span: join spans
        tev["trace_id"] = _req.get("id", "")
    if tr is not None:
        tr.emit("tenant", **tev)
    else:
        live_observe({"t": t0 + wall, "kind": "tenant", **tev})
    return JobResult(tenant=tenant, fit=f, bucket=bucket,
                     shape=(T_j, N_j, k_j), queue_wait_s=float(queue_wait),
                     compute_s=float(wall), pad_waste_frac=0.0)


def submit(jobs: Sequence[Job], *, backend: str = "tpu",
           max_buckets: int = 3, dtype=None, fused_chunk: int = 8,
           n_devices: Optional[int] = None, robust=True, pipeline=None,
           cost_model: Optional[CostModel] = None,
           stats: Optional[dict] = None) -> List[JobResult]:
    """Fit heterogeneous (N, T, k) jobs as a small set of fused batches.

    backend: "tpu" (single-device fused batches) or "sharded" (each
    bucket's batch axis split across the mesh — ``parallel.batched``).
    ``max_buckets`` caps executables per model-structure group;
    ``cost_model`` overrides the registry-calibrated planner input;
    ``pipeline`` / ``robust`` / ``fused_chunk`` ride through to the chunk
    driver exactly as in ``fit_many``.  ``stats`` (a dict, optional) is
    filled with plan/pack/compute accounting for benches.

    Returns per-tenant ``JobResult``s in submit order; each ``.fit`` is a
    full ``FitResult`` numerically identical to fitting that job alone.
    """
    from ..api import FitResult, _resolve_policy
    from ..utils.checkpoint import warm_fingerprint
    t_submit = time.perf_counter()
    jobs = list(jobs)
    if not jobs:
        return []
    for i, j in enumerate(jobs):
        if not isinstance(j, Job):
            raise TypeError(f"jobs[{i}] must be a sched.Job, "
                            f"got {type(j).__name__}")
    prepped = [_prep_job(i, j) for i, j in enumerate(jobs)]
    shapes = [(p[0].shape[0], p[0].shape[1], j.model.n_factors)
              for p, j in zip(prepped, jobs)]
    its = [max(1, int(j.max_iters)) for j in jobs]

    m = cost_model if cost_model is not None else registry_cost_model()
    # Structural groups first (incompatible executables), then the
    # cost-model DP packs shapes within each group.
    groups: dict = {}
    for i, j in enumerate(jobs):
        groups.setdefault(_cfg_key(j.model), []).append(i)
    plans: List[tuple] = []       # (job indices, BucketPlan)
    for key in sorted(groups):
        idx = groups[key]
        plans.append((idx, plan_buckets([shapes[i] for i in idx],
                                        [its[i] for i in idx],
                                        max_buckets=max_buckets, model=m,
                                        chunk=fused_chunk)))
    t_planned = time.perf_counter()

    dt = dtype or default_compute_dtype()
    policy = _resolve_policy(robust)
    tr = current_tracer()
    results: List[Optional[JobResult]] = [None] * len(jobs)
    agg_waste_num = agg_waste_den = 0.0
    bucket_dims: List[tuple] = []
    compute_total = 0.0
    n_bucket_global = 0
    n_quarantined = 0

    for idx, plan in plans:
        for b_local, bucket in enumerate(plan.buckets):
            bi = n_bucket_global
            n_bucket_global += 1
            members = [idx[x] for x in bucket.jobs]
            T_b, N_b, k_b = bucket.dims
            bucket_dims.append((T_b, N_b, k_b))
            model0 = jobs[members[0]].model
            cfg = EMConfig(estimate_A=model0.estimate_A,
                           estimate_Q=model0.estimate_Q,
                           estimate_init=model0.estimate_init,
                           filter="info")
            Yp = np.stack([
                pad_panel_to_t(pad_panel_to_n(prepped[i][0], N_b), T_b)
                for i in members])
            inits = [pad_params_to_n(
                pad_params_to_k(prepped[i][2], k_b), N_b)
                for i in members]
            het = make_hetero(
                t_act=[shapes[i][0] for i in members],
                n_act=[shapes[i][1] for i in members],
                T=T_b, N=N_b, dtype=dt,
                tol=[float(jobs[i].tol) for i in members],
                iter_cap=[its[i] for i in members],
                noise_floor_mult=cfg.noise_floor_mult)
            Yj = jnp.asarray(Yp, dt)
            p0 = stack_params(inits, dt)
            cap = max(its[i] for i in members)
            t_launch = time.perf_counter()
            queue_wait = t_launch - t_submit

            quarantined: dict = {}              # job index -> reason
            try:
                with jax.default_matmul_precision("highest"):
                    if backend == "sharded":
                        from ..parallel.batched import (
                            batched_smooth_sharded, run_batched_em_sharded)
                        p, lls_list, conv, p_iters, healths = \
                            run_batched_em_sharded(
                                Yj, p0, cfg, cap, 0.0,
                                fused_chunk=fused_chunk,
                                n_devices=n_devices, policy=policy,
                                pipeline=pipeline, hetero=het)

                        def _smooth(Yj=Yj, p=p, het=het):
                            return batched_smooth_sharded(
                                Yj, p, n_devices=n_devices, hetero=het)
                    elif backend == "tpu":
                        p, lls_list, conv, p_iters, healths = run_batched_em(
                            Yj, p0, cfg, cap, 0.0, fused_chunk=fused_chunk,
                            policy=policy, pipeline=pipeline, hetero=het)

                        def _smooth(Yj=Yj, p=p, het=het):
                            return _smooth_impl(Yj, p, het)
                    else:
                        raise ValueError(
                            f"unknown scheduler backend "
                            f"{backend!r} (use 'tpu' or 'sharded')")
                    if tr is None:
                        x_sm, P_sm = _smooth()
                        x_h = np.asarray(x_sm, np.float64)
                        P_h = np.asarray(P_sm, np.float64)
                    else:
                        with tr.dispatch("batched_smooth",
                                         shape_key(Yj, backend, "het"),
                                         barrier=True):
                            x_sm, P_sm = _smooth()
                            x_h = np.asarray(x_sm, np.float64)
                            P_h = np.asarray(P_sm, np.float64)
            except Exception as e:
                # Blast-radius isolation: a bucket program whose dispatch
                # exhausted its retries (GuardFailure is a RuntimeError)
                # quarantines the BUCKET — every member is requeued below
                # as a lone guarded fit.  Non-retryable exceptions (bad
                # backend name, shape errors) propagate unchanged, as does
                # everything when unguarded.
                if policy is None or not isinstance(
                        e, tuple(policy.retry_exceptions)):
                    raise
                reason = f"{type(e).__name__}: {e}"[:200]
                quarantined = {i: reason for i in members}
            compute_s = time.perf_counter() - t_launch
            compute_total += compute_s

            if not quarantined:
                p_list = unstack_params(p)
                if policy is not None and policy.recover_divergence:
                    # NaN blast radius: under recover_divergence a lane
                    # with a non-finite trace is evicted and refit alone
                    # (where the chunk guard's divergence repair applies);
                    # clean lanes keep their bucket results.  The default
                    # policy keeps the legacy sail-through semantics
                    # (pinned by test_sched).
                    for slot, i in enumerate(members):
                        lls_s = np.asarray(lls_list[slot])
                        if lls_s.size and not np.isfinite(lls_s).all():
                            quarantined[i] = ("non-finite loglik trace in "
                                              f"bucket lane {slot}")
            for slot, i in enumerate(members):
                T_j, N_j, k_j = shapes[i]
                job = jobs[i]
                tenant = job.tenant if job.tenant is not None else f"job{i}"
                if i in quarantined:
                    results[i] = _requeue_quarantined(
                        job, tenant, bi, quarantined[i], policy,
                        dtype=dt, filter="info", fused_chunk=fused_chunk,
                        queue_wait=queue_wait, shape=(T_j, N_j, k_j), tr=tr)
                    n_quarantined += 1
                    continue
                waste = plan.job_pad_waste[idx.index(i)]
                pj = slice_params_to_n(
                    slice_params_to_k(p_list[slot], k_j), N_j)
                lls = np.asarray(lls_list[slot])
                fit = FitResult(
                    params=pj, logliks=lls,
                    factors=x_h[slot, :T_j, :k_j],
                    factor_cov=P_h[slot, :T_j, :k_j, :k_j],
                    converged=bool(conv[slot]), n_iters=len(lls),
                    standardizer=prepped[i][1], model=job.model,
                    backend=f"sched:{backend}", history=[],
                    health=healths[slot],
                    fingerprint=warm_fingerprint((T_j, N_j), job.model,
                                                 False))
                if fit.health is not None:
                    # Multi-tenant attribution on the shared bucket events.
                    for hev in fit.health.events:
                        if not hev.tenant:
                            hev.tenant = tenant
                tev = dict(tenant=tenant, bucket=bi,
                           T=T_j, N=N_j, k=k_j,
                           bucket_T=T_b, bucket_N=N_b, bucket_k=k_b,
                           queue_wait_s=float(queue_wait),
                           compute_s=float(compute_s),
                           pad_waste_frac=float(waste),
                           n_iters=int(len(lls)),
                           converged=bool(conv[slot]))
                _req = current_request()
                if _req is not None:   # fit_jobs inside a request_span
                    tev["trace_id"] = _req.get("id", "")
                if tr is not None:
                    tr.emit("tenant", **tev)
                else:
                    live_observe({"t": t_launch + compute_s,
                                  "kind": "tenant", **tev})
                results[i] = JobResult(
                    tenant=tenant, fit=fit, bucket=bi,
                    shape=(T_j, N_j, k_j),
                    queue_wait_s=float(queue_wait),
                    compute_s=float(compute_s),
                    pad_waste_frac=float(waste))
        # Aggregate pad waste across groups (flop-weighted, from the
        # per-group plans' own accounting).
        for pos, i in enumerate(idx):
            T_j, N_j, k_j = shapes[i]
            bT, bN, bk = plan.buckets[plan.bucket_of[pos]].dims
            agg_waste_num += em_iter_work(N_j, T_j, k_j)[0] * its[i]
            agg_waste_den += em_iter_work(bN, bT, bk)[0] * its[i]

    if stats is not None:
        stats.update({
            "n_jobs": len(jobs),
            "n_buckets": n_bucket_global,
            "bucket_dims": bucket_dims,
            "plan_s": t_planned - t_submit,
            "compute_s": compute_total,
            "pad_waste_frac": (1.0 - agg_waste_num / agg_waste_den
                               if agg_waste_den > 0 else 0.0),
            "predicted_wall_s": sum(pl.predicted_wall_s
                                    for _, pl in plans),
            "n_quarantined": n_quarantined,
            "calibrated": m.calibrated,
        })
    return results  # type: ignore[return-value]

"""Multi-tenant batch scheduler: mixed-shape panels packed into shape
buckets, each bucket ONE fused batched-EM program (see ``sched.scheduler``).

    from dfm_tpu.sched import Job, submit
    results = submit([Job(Y1, model1), Job(Y2, model2), ...])

or through the public API seam, ``dfm_tpu.fit_jobs(...)``.
"""

from .buckets import Bucket, BucketPlan, plan_buckets
from .jobs import Job, JobResult
from .scheduler import submit

__all__ = ["Job", "JobResult", "Bucket", "BucketPlan", "plan_buckets",
           "submit"]

"""Fleet driver: batched session multiplexing over shape-bucketed tenants.

``open_fleet(results, panels)`` packs B fitted tenants into capacity
classes (``admission.plan_admission`` — the calibrated cost-model DP) and
keeps every class device-resident in one ``FleetBucket``; ``submit``
enqueues per-tenant ragged row updates (host-side validation only) and
``drain`` serves the queue in TICKS: one fused batched ``serve_update``
program per bucket per tick answers every member's queued query — ragged
scatter-append, per-tenant warm EM with independent freezes, RTS smooth,
nowcast + forecasts — with at most ONE blocking d2h per tick and ONE
executable per bucket shape for the fleet's lifetime (active set, row
counts and live lengths are traced vectors).

Per-tenant answers are the lone session's: lane b of a tick pins to the
same tenant's ``NowcastSession.update`` at the same budget
(tests/test_fleet.py).  Tenants with no query this tick are frozen
bit-inert; a tick with Q active tenants costs the same dispatch as one.

Unbounded streams (PR 14): ``ring=True`` rolls each tenant's oldest rows
off in-graph once its capacity fills (a traced per-lane ``n_evict``
vector rides the SAME executable — non-ring fleets pay nothing), and
``resident=`` caps the hot-lane budget: tenants beyond it park as WARM
host shadows (or COLD on-disk snapshots via ``evict(tier="cold")``) and
page back in on submit, bit-identical to never-evicted twins — the fleet
registers far more tenants than it holds HBM lanes for.

Self-healing mirrors the serving stack (PR 10): every tick runs under
``robust.dispatch.guarded_dispatch`` with the tenant fan-out (a bucket
dispatch failure is every member's failure), donated-retry rebuilds from
host shadows, and per-tenant quarantine — a tenant diverging more than
``policy.chunk_retries`` consecutive ticks is EVICTED to a lone guarded
``NowcastSession`` rebuilt from its host state (params + original-units
live panel), its lane frozen, its future queries routed to the lone
session; bucket-mates never stall and their trajectories are untouched
(no op crosses the batch axis).  A tick exhausting dispatch retries
quarantines the whole bucket the same way, from last-good shadows.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import time
import warnings
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..estim.batched import (CONVERGED, DIVERGED, pad_params_to_k,
                             pad_params_to_n, slice_params_to_k,
                             slice_params_to_n)
from ..obs.trace import current_tracer, finish_request, request_clock
from ..robust.dispatch import guarded_dispatch
from ..robust.health import FitHealth, HealthEvent
from ..serve.batched import (FleetOptions, _fleet_impl, _fleet_impl_donated,
                             fleet_impl_sharded)
from ..serve.session import _Z90, NowcastSession, SessionUpdate
from ..utils.data import build_mask
from .admission import (choose_engine, fleet_pad_waste, plan_admission,
                        plan_residency, readmission_cost_s)
from .buffers import FleetBucket

# Engines a fleet bucket can route (the batched serving core runs the
# info-form twins or vmaps the lone pit_qr/lowrank pair per lane);
# "auto" defers the choice to the calibrated cost model per capacity
# class (evidence-gated: unprofiled engine switches are never chosen).
_FLEET_FILTERS = ("info", "pit_qr", "lowrank")

__all__ = ["SessionFleet", "open_fleet", "restore_fleet"]

# On-disk fleet snapshot layout (manifest.json + one npz per tenant).
# Bump on incompatible change; restore refuses FUTURE versions loudly.
FLEET_SNAPSHOT_FORMAT = 1

_FLEET_IDS = itertools.count(1)


def live_observe(ev: dict) -> None:
    """Feed the always-on live plane (lazy import, see serve.session)."""
    from ..obs.live import observe
    observe(ev)


def _live_accounting(session: str) -> dict:
    from ..obs.live import accounting
    return accounting(session)


class _Query:
    """One queued tenant update (host units, validated at submit)."""

    __slots__ = ("tenant", "rows", "W_rows", "rz", "n_new", "t_submit",
                 "seq", "trace")

    def __init__(self, tenant, rows, W_rows, rz, n_new, seq, trace=None):
        self.tenant = tenant
        self.rows = rows            # (n, N) original units, NaNs kept
        self.W_rows = W_rows        # (n, N) {0,1}
        self.rz = rz                # (n, N) standardized, zero-filled
        self.n_new = n_new
        self.seq = seq
        self.t_submit = time.perf_counter()
        self.trace = trace          # request span context (obs.trace)


def _per_tenant(value, B, name, cast):
    """Broadcast a scalar knob or validate a per-tenant sequence."""
    if value is None or np.isscalar(value):
        return [value] * B
    vals = [cast(x) for x in value]
    if len(vals) != B:
        raise ValueError(f"{name} must be a scalar or one value per "
                         f"tenant; got {len(vals)} for {B} tenants")
    return vals


class SessionFleet:
    """Batched multi-tenant serving fleet (see module docstring).

    Open via :func:`open_fleet`; then ``submit(tenant, rows)`` enqueues
    and ``drain()`` serves the whole queue, returning per-tenant
    ``SessionUpdate`` lists in submit order.
    """

    def __init__(self, results, panels, masks=None, *,
                 tenants: Optional[Sequence[str]] = None,
                 capacity=None, max_update_rows: int = 8, max_iters=5,
                 tol=1e-6, horizon: Optional[int] = None,
                 di: Optional[bool] = None, ring: bool = False,
                 filter=None, rank=None,
                 resident: Optional[int] = None, backend=None,
                 robust=None, max_classes: int = 3,
                 runs: Optional[str] = None):
        from ..api import (CPUBackend, DynamicFactorModel, FitResult,
                           ShardedBackend, _resolve_policy, get_backend)
        results = list(results)
        panels = list(panels)
        B = len(results)
        if B == 0:
            raise ValueError("open_fleet needs at least one tenant")
        if len(panels) != B:
            raise ValueError(
                f"{B} results but {len(panels)} panels")
        masks = [None] * B if masks is None else list(masks)
        if len(masks) != B:
            raise ValueError(f"{B} results but {len(masks)} masks")
        names = ([f"t{i}" for i in range(B)] if tenants is None
                 else [str(t) for t in tenants])
        if len(names) != B or len(set(names)) != B:
            raise ValueError("tenants must be one UNIQUE name per tenant")
        b = get_backend(backend if backend is not None else "tpu")
        if isinstance(b, CPUBackend) or not hasattr(b, "_fused_panel"):
            raise ValueError(
                f"backend {b.name!r} has no fused device programs; "
                "fleets need a JAX backend (backend=\"tpu\"/\"sharded\" "
                "or a TPUBackend instance)")
        self._opts = FleetOptions(
            horizon=1 if horizon is None else max(1, int(horizon)),
            di=True if di is None else bool(di))
        caps = _per_tenant(capacity, B, "capacity", int)
        m_its = _per_tenant(max_iters, B, "max_iters", int)
        tols = _per_tenant(tol, B, "tol", float)
        filts = _per_tenant(filter, B, "filter", str)
        ranks = _per_tenant(rank, B, "rank", int)
        shapes, cfg_keys, entries, engines = [], [], [], []
        for i, (res, Y) in enumerate(zip(results, panels)):
            if not isinstance(res, FitResult):
                raise TypeError(
                    f"tenant {names[i]!r}: open_fleet needs FitResults; "
                    f"got {type(res).__name__}")
            if not isinstance(res.model, DynamicFactorModel):
                raise TypeError(
                    f"tenant {names[i]!r}: fleets support "
                    f"DynamicFactorModel fits only; got "
                    f"{type(res.model).__name__}")
            Y = np.asarray(Y, dtype=np.float64)
            if Y.ndim != 2:
                raise ValueError(
                    f"tenant {names[i]!r}: Y must be (T, N); got shape "
                    f"{Y.shape}")
            T0, N = Y.shape
            Lam = np.asarray(res.params.Lam)
            if Lam.shape[0] != N:
                raise ValueError(
                    f"tenant {names[i]!r}: params are for "
                    f"N={Lam.shape[0]} series but the panel has N={N}")
            if T0 < self._opts.horizon + 3:
                raise ValueError(
                    f"tenant {names[i]!r}: needs T >= horizon + 3 = "
                    f"{self._opts.horizon + 3} live rows; got T={T0}")
            cap = 2 * T0 if caps[i] is None else int(caps[i])
            if cap < T0:
                raise ValueError(
                    f"tenant {names[i]!r}: capacity={cap} < panel "
                    f"length T={T0}")
            if ring and max_update_rows > cap:
                raise ValueError(
                    f"tenant {names[i]!r}: ring mode needs "
                    f"max_update_rows <= capacity so an update never "
                    f"evicts more rows than it appends; got "
                    f"max_update_rows={max_update_rows} > capacity={cap}")
            m_it = max(1, 5 if m_its[i] is None else int(m_its[i]))
            tl = 1e-6 if tols[i] is None else float(tols[i])
            k = Lam.shape[1]
            shapes.append((cap, N, k))
            m = res.model
            # Per-tenant engine: an explicit filter= wins ("auto" defers
            # to the cost model per capacity class); the default inherits
            # the fit's resolved engine when the batched core routes it
            # (pit_qr/lowrank), mapping everything else to the info-form
            # twins — exactly the pre-routing fleet, bit-for-bit.
            f_i = filts[i]
            if f_i is None:
                rf = getattr(res, "filter", None)
                f_i = rf if rf in ("pit_qr", "lowrank") else "info"
            elif f_i not in _FLEET_FILTERS + ("auto",):
                raise ValueError(
                    f"tenant {names[i]!r}: unknown fleet filter {f_i!r}; "
                    f"buckets route {_FLEET_FILTERS} (or 'auto' for the "
                    "calibrated cost-model choice per class)")
            r_i = int(0 if ranks[i] is None else ranks[i])
            r_i = r_i if f_i in ("lowrank", "auto") else 0
            engines.append((f_i, r_i))
            # The engine joins the admission key: buckets are engine-
            # homogeneous, so ONE executable per (bucket-shape, engine).
            cfg_keys.append((m.estimate_A, m.estimate_Q, m.estimate_init,
                             f_i, r_i))
            entries.append((names[i], res, Y, masks[i], cap, m_it, tl))
        self._iters = [e[5] for e in entries]
        classes = plan_admission(shapes, self._iters, cfg_keys,
                                 max_classes=max_classes, runs=runs)
        self.pad_waste_frac = fleet_pad_waste(shapes, self._iters, classes)
        self._sharded = isinstance(b, ShardedBackend)
        self._mesh = None
        mesh_d = 1
        if self._sharded:
            from ..parallel.batched import make_batch_mesh
            self._mesh = make_batch_mesh(getattr(b, "n_devices", None))
            mesh_d = self._mesh.devices.size
        self._r_max = max(1, int(max_update_rows))
        self._ring = bool(ring)
        self._backend = b
        # Resident-lane budget: how many tenants start hot per class —
        # the calibrated paging economics (re-admission cost vs lane
        # rent) split the budget; members beyond a class's allocation
        # start WARM and page in on first submit.
        lane_plan = plan_residency(classes, resident, r_max=self._r_max,
                                   runs=runs)
        self._buckets: List[FleetBucket] = []
        self._slot_of = {}           # tenant -> (bucket, slot)
        for ca, n_lanes in zip(classes, lane_plan):
            ents = [entries[i] for i in ca.members]
            # Engine-homogeneous by the admission key; "auto" resolves
            # HERE, per class, through the calibrated cost model with the
            # PR 15 evidence gate (an unprofiled engine is never chosen).
            eng, rk = engines[ca.members[0]]
            if eng == "auto":
                its = [self._iters[i] for i in ca.members]
                eng = choose_engine(ca.dims, max(its), rank=rk, runs=runs)
            n_hot = min(len(ents), max(1, n_lanes))
            pad = (-n_hot) % mesh_d
            bk = FleetBucket(ents, ca.dims, r_max=self._r_max, backend=b,
                             opts=self._opts, pad_lanes=pad, lanes=n_hot,
                             filter=eng, rank=rk)
            self._buckets.append(bk)
            for s in bk.slots:
                self._slot_of[s.name] = (bk, s)
        self._policy = _resolve_policy(
            getattr(b, "robust", True) if robust is None else robust)
        self.health = FitHealth(engine="fleet")
        self._fid = f"f{next(_FLEET_IDS)}"
        self._pending: List[_Query] = []
        self._seq = itertools.count()
        self._closed = False
        self._n_ticks = 0
        self._n_queries = 0

    # -- introspection -------------------------------------------------
    @property
    def fleet_id(self) -> str:
        return self._fid

    @property
    def tenants(self) -> List[str]:
        return list(self._slot_of)

    @property
    def n_buckets(self) -> int:
        return len(self._buckets)

    @property
    def classes(self) -> List[dict]:
        """The admission plan: padded dims + members per capacity class."""
        return [{"dims": {"T": bk.dims[0], "N": bk.dims[1],
                          "k": bk.dims[2]},
                 "filter": bk.cfg.filter, "rank": bk.cfg.rank,
                 "tenants": [s.name for s in bk.slots]}
                for bk in self._buckets]

    @property
    def pending(self) -> int:
        return len(self._pending)

    def tenant_length(self, tenant: str) -> int:
        """Live panel length of one tenant (accepted rows only)."""
        _, slot = self._slot_of[tenant]
        return slot.t

    @property
    def ring(self) -> bool:
        """True if tenants evict their oldest rows past capacity
        (unbounded streams) instead of raising at submit."""
        return self._ring

    @property
    def resident_lanes(self) -> int:
        """Device lanes available to tenants (mesh fillers excluded)."""
        return sum(bk.n_lanes for bk in self._buckets)

    def tier(self, tenant: str) -> str:
        """Tenant residency tier: "hot" (device lane), "warm" (host
        shadow parked, lane freed) or "cold" (on-disk snapshot)."""
        _, slot = self._slot_of[tenant]
        return slot.tier

    def quarantined(self) -> List[str]:
        return [t for t, (_, s) in self._slot_of.items() if s.quarantined]

    def accounting(self) -> dict:
        """Per-tenant live-plane resource ledger for this fleet: queries
        answered, attributed device-wall ms (tick wall split over the
        tick's active lanes), EM iterations, estimated flops
        (``obs.cost.em_iter_work``), retries and degraded/quarantined
        counts.  Quarantined tenants keep accumulating under their name
        via their lone evicted session.  Always on, host-side only."""
        out = _live_accounting(self._fid)
        # merge lone-session rows field-by-field (a quarantined tenant's
        # post-eviction queries are accounted under its lone session id)
        for tenant, (_, slot) in self._slot_of.items():
            if slot.evicted is None:
                continue
            for row in _live_accounting(slot.evicted.session_id).values():
                dst = out.get(tenant)
                if dst is None:
                    out[tenant] = dict(row)
                    continue
                for f, v in row.items():
                    if f == "pad_waste_frac":
                        continue
                    dst[f] = dst.get(f, 0) + v
        return dict(sorted(out.items()))

    def _check_open(self):
        if self._closed:
            raise RuntimeError("fleet is closed")

    # -- the queue -----------------------------------------------------
    def submit(self, tenant: str, rows=None, mask=None,
               trace=None) -> int:
        """Enqueue one tenant update ((n, N) or (N,) original-units rows,
        NaN = missing; ``rows=None`` queues a pure re-forecast — warm EM
        + smooth + forecast with no append).  All capacity/shape
        validation happens here, against the PROJECTED live length (rows
        already queued count) — an invalid submit raises without touching
        the queue.  Returns the queue depth after the submit.

        ``trace`` is the request span context (``obs.trace``): the
        daemon passes its ticket's dict; direct callers inherit any
        enclosing ``request_span`` or, when a tracer is active, birth a
        fresh context here — the tick stamps dispatch/d2h boundaries
        into it and the query event carries its trace_id.  Untraced,
        context-free submits skip the machinery entirely (no clock
        reads, no ids — byte-identical events to pre-trace builds)."""
        self._check_open()
        if tenant not in self._slot_of:
            raise KeyError(f"unknown tenant {tenant!r} (fleet has "
                           f"{sorted(self._slot_of)})")
        _, slot = self._slot_of[tenant]
        if rows is None:
            if mask is not None:
                raise ValueError("mask requires rows")
            r = np.zeros((0, slot.N))
            W_rows = np.zeros((0, slot.N))
            rz = r
        else:
            r = np.asarray(rows, dtype=np.float64)
            if r.ndim == 1:
                r = r[None, :]
            if r.ndim != 2 or r.shape[1] != slot.N:
                raise ValueError(
                    f"tenant {tenant!r}: rows must be (n, {slot.N}) or "
                    f"({slot.N},); got shape {np.asarray(rows).shape}")
            if r.shape[0] > self._r_max:
                raise ValueError(
                    f"tenant {tenant!r}: update has {r.shape[0]} rows "
                    f"but the fleet was opened with max_update_rows="
                    f"{self._r_max}")
            W_rows = build_mask(r, mask)
            rz = slot.std.transform(r) if slot.std is not None else r
            rz = np.where(W_rows > 0, np.nan_to_num(rz), 0.0)
        queued = sum(q.n_new for q in self._pending if q.tenant == tenant)
        if (not self._ring
                and slot.t + queued + r.shape[0] > slot.capacity):
            raise ValueError(
                f"tenant {tenant!r}: capacity overflow — holds {slot.t} "
                f"rows (+{queued} queued) of {slot.capacity} and cannot "
                f"take {r.shape[0]} more; open the fleet with ring=True "
                "to evict the oldest rows in place (unbounded streams "
                "at constant memory)")
        # Admission-pressure paging: a warm/cold tenant pages into a hot
        # lane before its query can ride a tick (quarantined tenants are
        # served on their lone sessions and need no lane).
        if slot.tier != "hot" and not slot.quarantined:
            self.admit(tenant)
        slot.last_used = next(self._seq)
        if trace is None:
            from ..obs.trace import current_request, current_tracer
            trace = current_request()
            if trace is None and current_tracer() is not None:
                from ..obs.trace import new_trace_id, request_clock
                trace = {"id": new_trace_id(), "t_send": request_clock()}
        if trace is not None:
            from ..obs.trace import request_clock
            trace.setdefault("t_admit", request_clock())
        self._pending.append(_Query(tenant, r, W_rows, rz, r.shape[0],
                                    next(self._seq), trace=trace))
        return len(self._pending)

    # -- snapshot tiering ----------------------------------------------
    def evict(self, tenant: str, tier: str = "warm",
              path: Optional[str] = None) -> str:
        """Demote a hot tenant out of its device lane.

        ``tier="warm"`` parks the exact padded host shadows (panel +
        params, one small d2h) on the slot and frees the lane for a
        bucket-mate; ``tier="cold"`` additionally spills the shadows to
        an on-disk npz at ``path`` and drops the host copies.  The
        tenant stays registered — its next ``submit`` pages it back in
        automatically (admission-pressure paging) and serves bit-
        identically to a never-evicted twin.  Returns the new tier.
        Tenants with pending queries (drain first) or quarantined
        tenants (they live on lone sessions, no lane) cannot be evicted.
        """
        self._check_open()
        if tier not in ("warm", "cold"):
            raise ValueError(f"tier must be 'warm' or 'cold'; got {tier!r}")
        if tenant not in self._slot_of:
            raise KeyError(f"unknown tenant {tenant!r} (fleet has "
                           f"{sorted(self._slot_of)})")
        bucket, slot = self._slot_of[tenant]
        if slot.quarantined:
            raise ValueError(
                f"tenant {tenant!r} is quarantined: it already lives on "
                "a lone session and holds no lane to evict")
        if any(q.tenant == tenant for q in self._pending):
            raise ValueError(
                f"tenant {tenant!r} has pending queries; drain() before "
                "evicting")
        if slot.tier == "hot":
            t0 = time.perf_counter()
            bucket.demote(slot)
            self._page("demote", slot, bucket,
                       time.perf_counter() - t0)
        if tier == "cold" and slot.tier == "warm":
            if path is None:
                raise ValueError(
                    "cold eviction spills to disk: pass path= for the "
                    "lane snapshot npz")
            self._spill(slot, bucket, str(path))
        return slot.tier

    def admit(self, tenant: str) -> None:
        """Page a warm/cold tenant back into a hot device lane (no-op if
        already hot).  If the bucket has no free lane, the least-
        recently-used hot bucket-mate WITHOUT pending queries is demoted
        to warm first — the victim's re-admission price is the class's
        ``admission.readmission_cost_s``, already weighed against lane
        rent by the residency plan.  The restored device state is bit-
        identical to a never-evicted twin's (d2h/h2d of the f64 shadows
        is exact)."""
        self._check_open()
        if tenant not in self._slot_of:
            raise KeyError(f"unknown tenant {tenant!r} (fleet has "
                           f"{sorted(self._slot_of)})")
        bucket, slot = self._slot_of[tenant]
        if slot.quarantined:
            raise ValueError(
                f"tenant {tenant!r} is quarantined: queries route to its "
                "lone session; there is no lane state to admit")
        if slot.tier == "hot":
            return
        t0 = time.perf_counter()
        if slot.tier == "cold":
            self._thaw(slot, bucket)
        if not bucket.free_lanes:
            victim = self._choose_victim(bucket)
            if victim is None:
                raise RuntimeError(
                    f"cannot admit tenant {tenant!r}: no free lane and "
                    "every hot bucket-mate has pending queries — drain() "
                    "first or open the fleet with a larger resident= "
                    "budget")
            bucket.demote(victim)
            self._page("demote", victim, bucket, 0.0, reason="pressure")
        lane = bucket.admit(slot)
        self._page("admit", slot, bucket, time.perf_counter() - t0,
                   lane=lane)

    def swap_params(self, tenant: str, params) -> None:
        """Hot-swap one tenant's model params wherever it lives (the
        maintenance seam, ``fleet.maintenance``).

        ``params`` is a ``cpu_ref.SSMParams`` at the tenant's TRUE
        (N, k), in its frozen standardized scale.  A hot tenant's lane is
        rewritten through the exact demote/admit round-trip (refresh the
        bucket-mates' f64 shadows from the device — an exact
        representation — then redeploy), so bucket-mates are bit-
        identical before and after; warm/cold tenants get their parked
        shadows rewritten in place; a quarantined tenant delegates to its
        lone session's ``swap_params``.  No executable changes, no
        recompiles: the next tick is the same fused program.  Swapping
        bit-equal params is a bit-identical no-op.
        """
        self._check_open()
        if tenant not in self._slot_of:
            raise KeyError(f"unknown tenant {tenant!r} (fleet has "
                           f"{sorted(self._slot_of)})")
        bucket, slot = self._slot_of[tenant]
        Lam = np.asarray(params.Lam, np.float64)
        if tuple(Lam.shape) != (slot.N, slot.k):
            raise ValueError(
                f"swap_params: Lam has shape {tuple(Lam.shape)}, tenant "
                f"{tenant!r} serves (N, k)=({slot.N}, {slot.k})")
        if slot.quarantined:
            slot.evicted.swap_params(params)
            return
        _, N_b, k_b = bucket.dims
        p_pad = pad_params_to_n(pad_params_to_k(params.copy(), k_b), N_b)
        if slot.tier == "hot":
            bucket.p_host = bucket.params_host()
            bucket.p_host[slot.lane] = p_pad
            bucket.redeploy()
            # Materialize the rebuilt device buffers NOW: the swap runs
            # on the maintenance pass, and the h2d re-upload must not
            # land on the next serving query's wall.  A d2h read-back is
            # the only real barrier on axon (block_until_ready is a
            # no-op there — CLAUDE.md, pinned by test_timing_guard).
            for leaf in jax.tree_util.tree_leaves(
                    (bucket.Ybuf, bucket.Wbuf, bucket.p)):
                np.asarray(leaf)
        elif slot.tier == "warm":
            slot.warm_p = p_pad
        else:                           # cold: rewrite the npz in place
            from ..utils.checkpoint import _FIELDS
            with np.load(slot.cold_path) as z:
                keep = {f: np.asarray(z[f]) for f in z.files
                        if f not in _FIELDS}
            np.savez(slot.cold_path, **keep,
                     **{f: np.asarray(getattr(p_pad, f), np.float64)
                        for f in _FIELDS})

    def _choose_victim(self, bucket):
        """Pick the hot lane to page out: among bucket-mates with no
        pending work (and not quarantined), the least-recently-used.
        Candidates share the bucket's dims, so the cost model prices
        their re-admission identically (``readmission_cost_s`` priced
        the class when the residency plan was cut) — recency is the
        remaining signal.  Deterministic."""
        busy = {q.tenant for q in self._pending}
        cands = [s for s in bucket.slots
                 if s.tier == "hot" and not s.quarantined
                 and s.name not in busy]
        if not cands:
            return None
        return min(cands, key=lambda s: (s.last_used, s.lane))

    def _spill(self, slot, bucket, path: str):
        """Warm -> cold: write the parked shadows to one npz and drop
        the host copies.  The file round-trips bit-exactly (f64)."""
        from ..utils.checkpoint import _FIELDS
        np.savez(path, fleet_lane_format=1,
                 Y=slot.warm_Y, W=slot.warm_W,
                 t=slot.t, t_total=slot.t_total,
                 dims=np.asarray(bucket.dims, np.int64),
                 **{f: np.asarray(getattr(slot.warm_p, f), np.float64)
                    for f in _FIELDS})
        slot.cold_path = path
        slot.warm_Y = slot.warm_W = slot.warm_p = None
        slot.tier = "cold"
        self._page("spill", slot, bucket, 0.0, path=path)

    def _thaw(self, slot, bucket):
        """Cold -> warm: reload the spilled shadows from disk."""
        from ..backends.cpu_ref import SSMParams
        from ..utils.checkpoint import _FIELDS
        with np.load(slot.cold_path) as z:
            if "fleet_lane_format" not in z.files:
                raise ValueError(
                    f"{slot.cold_path!r} is not a fleet lane snapshot")
            dims = tuple(int(d) for d in z["dims"])
            if dims != tuple(bucket.dims):
                raise ValueError(
                    f"lane snapshot {slot.cold_path!r} was taken at "
                    f"class dims {dims}, bucket is {tuple(bucket.dims)}")
            slot.warm_Y = np.asarray(z["Y"], np.float64)
            slot.warm_W = np.asarray(z["W"], np.float64)
            slot.warm_p = SSMParams(*(np.asarray(z[f], np.float64)
                                      for f in _FIELDS))
        slot.tier = "warm"

    def _page(self, action: str, slot, bucket, wall: float, **extra):
        """Emit one paging event (trace stream or the always-on live
        plane) — ``obs.report``/``bench.stream`` read these for
        occupancy and ``readmission_ms``."""
        ev = dict(session=self._fid, tenant=slot.name, action=action,
                  bucket=self._buckets.index(bucket), wall=wall,
                  tier=slot.tier, **extra)
        tr = current_tracer()
        if tr is not None:
            tr.emit("page", **ev)
        else:
            live_observe({"t": time.perf_counter(), "kind": "page", **ev})

    def drain(self, *, on_tick: Optional[Callable] = None
              ) -> Dict[str, List[SessionUpdate]]:
        """Serve the whole queue: repeated TICKS (one fused dispatch per
        bucket with work, each answering every member's next query) until
        empty.  Returns per-tenant ``SessionUpdate`` lists in submit
        order.  Quarantined tenants' queries route to their lone evicted
        sessions (guarded there).

        ``on_tick``: host-side hook called with the fleet after each tick
        ROUND (every bucket that had work this round has answered) — the
        daemon's seam for mid-drain snapshots/journal watermarks on long
        queues.  Runs between dispatches, never inside one."""
        self._check_open()
        out: Dict[str, List[SessionUpdate]] = {}
        while self._pending:
            # Evicted tenants first: their queries never wait on a tick.
            still = []
            for q in self._pending:
                _, slot = self._slot_of[q.tenant]
                if slot.quarantined:
                    upd = self._serve_evicted(slot, q)
                    out.setdefault(q.tenant, []).append(upd)
                else:
                    still.append(q)
            self._pending = still
            if not self._pending:
                break
            # One query per tenant per tick, FIFO.
            picks: Dict[int, Dict[int, _Query]] = {}
            taken = set()
            for q in self._pending:
                bk, slot = self._slot_of[q.tenant]
                bi = self._buckets.index(bk)
                if (bi, slot.lane) not in taken:
                    picks.setdefault(bi, {})[slot.lane] = q
                    taken.add((bi, slot.lane))
            served = []
            for bi, lane_q in picks.items():
                for tenant, upd in self._tick(self._buckets[bi], lane_q):
                    out.setdefault(tenant, []).append(upd)
                served.extend(lane_q.values())
            self._pending = [q for q in self._pending
                             if q not in served]
            if on_tick is not None:
                on_tick(self)
        return out

    # -- the tick ------------------------------------------------------
    def _tick(self, bucket: FleetBucket, lane_q: Dict[int, "_Query"]):
        """One fused batched dispatch answering every picked lane."""
        from ..robust.guard import GuardFailure
        T_cap, N_max, _ = bucket.dims
        B, r_max = bucket.B, bucket.r_max
        rows_b = np.zeros((B, r_max, N_max))
        rmask_b = np.zeros((B, r_max, N_max))
        n_new = np.zeros(B, np.int32)
        evictv = np.zeros(B, np.int32)
        # Free / mesh-filler lanes default to t_cur = T_cap: with
        # n_evict = 0 the ring pass keeps every row (bit-identical
        # passthrough) and the zero-row scatter lands past the buffer
        # (mode="drop") — the lane is inert whatever stale data it holds.
        t_cur = np.full(B, T_cap, np.int32)
        tolv = np.zeros(B)
        floorv = np.zeros(B)
        capv = np.ones(B, np.int32)
        act = np.zeros(B, bool)
        for lane in range(B):
            slot = bucket.lane_of.get(lane)
            if slot is None:
                continue
            t_cur[lane] = slot.t
            tolv[lane] = slot.tol
            capv[lane] = slot.max_iters
            floorv[lane] = bucket.floor_for(slot, slot.t)
        active = []
        for lane, q in sorted(lane_q.items()):
            slot = bucket.lane_of[lane]
            rows_b[lane, :q.n_new, :slot.N] = q.rz
            rmask_b[lane, :q.n_new, :slot.N] = q.W_rows
            n_new[lane] = q.n_new
            # Ring eviction: past capacity the oldest rows roll off
            # in-graph before the append (non-ring submit already raised,
            # so e == 0 there).
            e = max(0, slot.t + q.n_new - slot.capacity)
            evictv[lane] = e
            act[lane] = True
            floorv[lane] = bucket.floor_for(
                slot, min(slot.t + q.n_new, slot.capacity))
            active.append(slot.name)
        if self._sharded:
            impl, donated = fleet_impl_sharded, False
            kw = dict(cfg=bucket.cfg, max_iters=bucket.max_iters,
                      opts=bucket.opts, mesh=self._mesh)
        else:
            donated = jax.default_backend() != "cpu"
            impl = _fleet_impl_donated if donated else _fleet_impl
            kw = dict(cfg=bucket.cfg, max_iters=bucket.max_iters,
                      opts=bucket.opts)
        pol = self._policy
        tr = current_tracer()
        # Request spans riding this tick (obs.trace): one CLOCK_MONOTONIC
        # read per boundary, shared by every span in the batch — zero
        # clock reads when no query carries a trace.
        tids = [q.trace.get("id", "") if q.trace is not None else ""
                for _, q in sorted(lane_q.items())]
        tr_q = [q.trace for _, q in sorted(lane_q.items())
                if q.trace is not None]

        def _stamp(key):
            if tr_q:
                t_now = request_clock()
                for trc in tr_q:
                    trc[key] = t_now

        _stamp("t_tick0")
        acc, dt = bucket.acc, bucket.dt
        t0 = time.perf_counter()
        with self._backend._precision_ctx():
            rows_j = jnp.asarray(rows_b, dt)
            rmask_j = jnp.asarray(rmask_b, dt)
            consts = (jnp.asarray(n_new), jnp.asarray(evictv),
                      jnp.asarray(t_cur), jnp.asarray(tolv, acc),
                      jnp.asarray(floorv, acc), jnp.asarray(capv),
                      jnp.asarray(act))

            def _once(attempt):
                if attempt > 0 and donated:
                    # The failed dispatch consumed the donated buffers;
                    # rebuild from host shadows (one recovery h2d of the
                    # exact original values).
                    bucket.redeploy()
                args = (bucket.Ybuf, bucket.Wbuf, rows_j, rmask_j,
                        consts[0], consts[1], consts[2], bucket.p,
                        consts[3], consts[4], consts[5], consts[6])
                # Span stamps land on EVERY attempt (last one wins), so a
                # retried dispatch's waterfall truthfully absorbs the
                # backoff into its dispatch stage.
                if tr is None:
                    o = impl(*args, **kw)
                    _stamp("t_launch")
                    host = self._read(o, donated and pol is not None)
                    _stamp("t_read")
                    return o, host
                if attempt == 0:
                    tr.maybe_cost("serve_update", bucket.key, impl, *args,
                                  **kw)
                extra = {"attempt": attempt} if pol is not None else {}
                with tr.dispatch("serve_update", bucket.key, barrier=True,
                                 fused=True, n_iters=bucket.max_iters,
                                 batch=B, **extra) as rec:
                    o = impl(*args, **kw)
                    _stamp("t_launch")
                    host = self._read(o, donated and pol is not None)
                    _stamp("t_read")
                    if rec is not None:
                        rec["n_iters"] = int(host["n_iters"].max())
                return o, host

            try:
                if pol is None:
                    out, host = _once(0)
                else:
                    out, host = guarded_dispatch(
                        _once, pol, self.health, label="fleet tick",
                        session=self._fid, tenants=active,
                        trace_ids=tids, iteration=self._n_ticks,
                        last_good=lambda: bucket.p_host)
            except GuardFailure as e:
                # The bucket program cannot be dispatched: quarantine
                # EVERY member from the last-good host shadows and serve
                # this tick's queries on the lone evicted sessions.
                warnings.warn(
                    f"fleet bucket dispatch failed ({e}); quarantining "
                    f"{len(bucket.slots)} tenants to lone sessions",
                    RuntimeWarning, stacklevel=3)
                results = []
                for slot in bucket.slots:
                    if not slot.quarantined:
                        self._quarantine(
                            bucket, slot, "bucket dispatch exhausted "
                            "retries",
                            p_pad=(bucket.p_host[slot.lane]
                                   if slot.lane is not None
                                   else slot.warm_p))
                for lane, q in sorted(lane_q.items()):
                    slot = bucket.lane_of[lane]
                    results.append(
                        (slot.name, self._serve_evicted(slot, q)))
                return results
        wall = time.perf_counter() - t0
        bucket.rebind(out)
        if "p_list" in host:      # guarded donated path: last-good shadow
            bucket.p_host = host["p_list"]
        bucket.n_ticks += 1
        self._n_ticks += 1
        results = []
        for lane, q in sorted(lane_q.items()):
            slot = bucket.lane_of[lane]
            e = int(evictv[lane])
            t_mid = slot.t - e
            t_new = t_mid + q.n_new
            # Host shadows track the same roll + append in numpy
            # (standardized units, exactly what the device ring pass and
            # scatter landed: shift left by e, zero the wrapped tail that
            # the append does not overwrite, then write the new rows).
            if e:
                bucket.Yhost[lane, :T_cap - e] = \
                    bucket.Yhost[lane, e:].copy()
                bucket.Whost[lane, :T_cap - e] = \
                    bucket.Whost[lane, e:].copy()
                bucket.Yhost[lane, T_cap - e:] = 0.0
                bucket.Whost[lane, T_cap - e:] = 0.0
            bucket.Yhost[lane, t_mid:t_new, :slot.N] = q.rz
            bucket.Whost[lane, t_mid:t_new, :slot.N] = q.W_rows
            slot.append_orig(q.rows, q.W_rows)
            slot.evict_orig(e)
            slot.n_queries += 1
            self._n_queries += 1
            # Live coverage: this query's observed new rows vs the
            # PREVIOUS query's 90% band (original units, host-only —
            # the fleet twin of the lone session's tracking).
            cov = None
            inz = None
            if q.n_new and slot.last_band is not None:
                pf, ps = slot.last_band
                n_cmp = min(q.n_new, pf.shape[0])
                obs = q.W_rows[:n_cmp] > 0
                if obs.any():
                    err = np.abs(q.rows[:n_cmp] - pf[:n_cmp])
                    hit = err <= _Z90 * ps[:n_cmp]
                    cov = float(np.mean(hit[obs]))
                    # Standardized innovation magnitude — the fleet twin
                    # of the lone session's drift signal (obs/drift.py).
                    z = err / np.maximum(ps[:n_cmp], 1e-12)
                    inz = float(np.mean(z[obs]))
            upd = self._lane_update(bucket, host, slot, t_new, wall)
            upd.coverage = cov
            slot.last_band = (upd.forecasts["y"], upd.forecast_sd)
            diverged = int(host["status"][lane]) == DIVERGED
            if diverged:
                slot.div_run += 1
                warnings.warn(
                    f"fleet tenant {slot.name!r} diverged after "
                    f"{int(host['good_it'][lane])} good iterations; "
                    "kept the rolled-back params", RuntimeWarning,
                    stacklevel=3)
                if pol is not None:
                    self.health.record(HealthEvent(
                        chunk=-1, iteration=slot.t, kind="divergence",
                        action="restored", tenant=slot.name,
                        session=self._fid,
                        detail=(f"tick update diverged after "
                                f"{int(host['good_it'][lane])} good "
                                "iterations; kept rolled-back params")))
                    if slot.div_run > pol.chunk_retries:
                        self._quarantine(
                            bucket, slot,
                            f"{slot.div_run} consecutive diverged ticks",
                            p_pad=(host["p_list"][lane]
                                   if "p_list" in host else None))
            else:
                slot.div_run = 0
            degraded = bool(diverged or slot.quarantined)
            # wall_share: this tenant's attributed slice of the tick's
            # wall (split equally over the tick's active lanes), so the
            # per-tenant ledger sums back to the tick walls.
            # Loglik-per-row trend signal (values already in the tick's
            # host read — zero extra dispatches).
            n_ll = min(int(host["n_iters"][lane]), slot.max_iters)
            llpr = None
            if n_ll > 0 and t_new > 0:
                ll_last = float(host["lls"][lane][n_ll - 1])
                if np.isfinite(ll_last):
                    llpr = ll_last / t_new
            qev = dict(session=self._fid, tenant=slot.name,
                       t_rows=int(t_new), n_new=int(q.n_new), wall=wall,
                       wall_share=wall / max(len(lane_q), 1),
                       queue_wait=max(0.0, t0 - q.t_submit),
                       n_iters=int(host["n_iters"][lane]),
                       N=int(slot.N), k=int(slot.k),
                       engine=bucket.cfg.filter,
                       converged=bool(int(host["status"][lane])
                                      == CONVERGED),
                       diverged=diverged,
                       **({"coverage": cov} if cov is not None else {}),
                       **({"innov_z": inz} if inz is not None else {}),
                       **({"ll_per_row": llpr} if llpr is not None
                          else {}),
                       **({"n_evicted": int(e)} if e else {}),
                       **({"degraded": True} if degraded else {}),
                       **({"trace_id": q.trace.get("id", "")}
                          if q.trace is not None else {}),
                       **({"replay": True}
                          if q.trace is not None and q.trace.get("replay")
                          else {}))
            if tr is not None:
                tr.emit("query", **qev)
            else:
                live_observe({"t": t0 + wall, "kind": "query", **qev})
            if q.trace is not None and q.trace.get("owner") != "daemon":
                # Direct fleet.submit / journal replay: the fleet ends
                # the span here (daemon-owned spans finish at the ack —
                # the daemon stamps t_ack and emits the request event).
                q.trace["t_ack"] = request_clock()
                rev = finish_request(q.trace, tenant=slot.name,
                                     session=self._fid)
                if tr is not None:
                    tr.emit("request", t=q.trace["t_ack"], **rev)
                else:
                    live_observe({"t": q.trace["t_ack"],
                                  "kind": "request", **rev})
            results.append((slot.name, upd))
        tev = dict(session=self._fid,
                   bucket=self._buckets.index(bucket), batch=B,
                   n_active=len(lane_q), wall=wall,
                   n_tenants=len(bucket.slots))
        if tr is not None:
            tr.emit("tick", **tev)
        else:
            # Untraced serving still feeds the always-on live plane from
            # the timestamps this tick already took.
            live_observe({"t": t0 + wall, "kind": "tick", **tev})
        return results

    def _read(self, out, want_params: bool = False):
        """Materialize the host-bound outputs inside the dispatch span
        (one blocking d2h per tick).  ``want_params`` (guarded donated
        path) also reads the resulting stacked params so the last-good
        host shadow stays current for donated-retry rebuilds."""
        host = {
            "status": np.asarray(out["status"], np.int32),
            "n_iters": np.asarray(out["n_iters"], np.int32),
            "good_it": np.asarray(out["good_it"], np.int32),
            "lls": np.asarray(out["lls"], np.float64),
            "nowcast": np.asarray(out["nowcast"], np.float64),
            "nowcast_sd": np.asarray(out["nowcast_sd"], np.float64),
            "f_fore": np.asarray(out["f_fore"], np.float64),
            "y_fore": np.asarray(out["y_fore"], np.float64),
            "y_sd": np.asarray(out["y_sd"], np.float64),
            "di": (np.asarray(out["di"], np.float64)
                   if out["di"] is not None else None),
            "x_sm": np.asarray(out["x_sm"], np.float64),
            "P_sm": np.asarray(out["P_sm"], np.float64),
        }
        if want_params:
            from ..estim.batched import unstack_params
            host["p_list"] = unstack_params(out["p"])
        return host

    def _lane_update(self, bucket, host, slot, t_new, wall):
        """Slice lane ``slot.lane`` out of the tick's host outputs and
        destandardize — the fleet's ``SessionUpdate`` for this tenant."""
        ln, N, k = slot.lane, slot.N, slot.k
        inv = (slot.std.inverse if slot.std is not None else (lambda a: a))
        # Bands destandardize by the scale alone (the shift cancels).
        sd_inv = ((lambda s: s * slot.std.scale)
                  if slot.std is not None else (lambda s: s))
        n = min(int(host["n_iters"][ln]), slot.max_iters)
        di = host["di"]
        return SessionUpdate(
            nowcast=np.asarray(inv(host["nowcast"][ln][:N])),
            forecasts={
                "y": np.asarray(inv(host["y_fore"][ln][:, :N])),
                "f": host["f_fore"][ln][:, :k],
                "di": (np.asarray(inv(di[ln][:N]))
                       if di is not None else None)},
            logliks=host["lls"][ln][:n],
            n_iters=n,
            converged=bool(int(host["status"][ln]) == CONVERGED),
            diverged=bool(int(host["status"][ln]) == DIVERGED),
            factors=host["x_sm"][ln][:t_new, :k],
            factor_cov=host["P_sm"][ln][:t_new, :k, :k],
            t=t_new,
            wall_s=wall,
            nowcast_sd=np.asarray(sd_inv(host["nowcast_sd"][ln][:N])),
            forecast_sd=np.asarray(sd_inv(host["y_sd"][ln][:, :N])))

    # -- quarantine / eviction -----------------------------------------
    def _quarantine(self, bucket, slot, reason: str, p_pad=None):
        """Evict one tenant to a lone guarded ``NowcastSession`` rebuilt
        from its host state and freeze its lane forever.  Bucket-mates
        are untouched (the frozen lane is value-inert by construction)."""
        from ..api import FitResult
        if p_pad is None:
            if slot.lane is not None:
                p_pad = bucket.params_host()[slot.lane]
            else:                       # warm/cold: the parked shadow
                if slot.tier == "cold":
                    self._thaw(slot, bucket)
                p_pad = slot.warm_p
        p = slice_params_to_n(slice_params_to_k(p_pad, slot.k), slot.N)
        res = FitResult(
            params=p, logliks=np.zeros(0),
            factors=np.zeros((0, slot.k)),
            factor_cov=np.zeros((0, slot.k, slot.k)),
            converged=False, n_iters=0, standardizer=slot.std,
            model=slot.model, backend=self._backend.name, history=[])
        sess = NowcastSession(
            res, slot.Y_orig, slot.W_orig,
            capacity=slot.capacity, max_update_rows=self._r_max,
            max_iters=slot.max_iters, tol=slot.tol,
            horizon=self._opts.horizon, di=self._opts.di,
            ring=self._ring, filter=bucket.cfg.filter,
            rank=bucket.cfg.rank, backend=self._backend,
            robust=self._policy)
        slot.evicted = sess
        slot.quarantined = True
        slot.div_run = 0
        self.health.record(HealthEvent(
            chunk=-1, iteration=slot.t, kind="quarantine",
            action="evicted", tenant=slot.name, session=self._fid,
            detail=(f"{reason}; evicted to lone session "
                    f"{sess.session_id}")))
        warnings.warn(
            f"fleet tenant {slot.name!r} quarantined ({reason}); future "
            f"queries route to lone session {sess.session_id}",
            RuntimeWarning, stacklevel=3)

    def _serve_evicted(self, slot, q: "_Query") -> SessionUpdate:
        """Route one queued query to the tenant's lone evicted session.
        The request span (if any) rides along — the lone session stamps
        its boundaries, so quarantined requests keep their waterfall."""
        slot.n_queries += 1
        self._n_queries += 1
        if q.n_new == 0:
            return slot.evicted.update(None, trace=q.trace)
        upd = slot.evicted.update(q.rows, mask=q.W_rows, trace=q.trace)
        slot.append_orig(q.rows, q.W_rows)
        if self._ring and slot.t > slot.capacity:
            # Mirror the lone session's ring: the quarantine seed stays
            # bounded at the trailing window.
            slot.evict_orig(slot.t - slot.capacity)
        return upd

    # -- durability ----------------------------------------------------
    def _slot_params_np(self, bucket, slot):
        """Current params of one tenant, sliced to its true (N, k) —
        wherever the tenant lives (hot lane d2h, parked warm shadow,
        cold npz, or its lone quarantine session)."""
        from ..backends.cpu_ref import SSMParams
        from ..utils.checkpoint import _FIELDS
        if slot.quarantined:
            return slot.evicted.params()
        if slot.tier == "hot":
            p_pad = bucket.params_host()[slot.lane]
        elif slot.tier == "warm":
            p_pad = slot.warm_p
        else:                           # cold: read without thawing
            with np.load(slot.cold_path) as z:
                p_pad = SSMParams(*(np.asarray(z[f], np.float64)
                                    for f in _FIELDS))
        return slice_params_to_n(slice_params_to_k(p_pad, slot.k), slot.N)

    def snapshot_all(self, dir_path: str,
                     journal_seq: Optional[int] = None) -> str:
        """Fleet-wide durable snapshot: one atomic fingerprint-stamped
        npz per tenant (params + original-units live panel + budgets,
        via ``utils.checkpoint.save_checkpoint`` — tmp + fsync + rename)
        plus an atomic ``manifest.json`` naming every file, its content
        fingerprint and the fleet-level config.  ``journal_seq`` is the
        daemon's request-journal watermark: a restart restores the
        snapshot then replays only entries after it.  Restore with
        :func:`restore_fleet`; restored answers are bit-equal to the
        uninterrupted fleet's (pinned by tests/test_daemon.py).  Pending
        queries are NOT snapshotted — drain first (the daemon journals
        requests before submitting, so nothing is lost)."""
        from ..utils.checkpoint import (SNAPSHOT_SCHEMA_VERSION, fsync_dir,
                                        panel_fingerprint, save_checkpoint)
        self._check_open()
        if self._pending:
            raise RuntimeError(
                f"{len(self._pending)} queries still pending; drain() "
                "before snapshot_all (the snapshot holds served state "
                "only)")
        os.makedirs(dir_path, exist_ok=True)
        tenants = []
        for name, (bucket, slot) in self._slot_of.items():
            p = self._slot_params_np(bucket, slot)
            fp = panel_fingerprint(slot.Y_orig, slot.W_orig)
            fname = f"tenant-{name}.npz"
            m = slot.model
            save_checkpoint(
                os.path.join(dir_path, fname), p, it=slot.t, logliks=[],
                fingerprint=fp, converged=False,
                extra={
                    "fleet_tenant_format": 1,
                    "Y_orig": slot.Y_orig, "W_orig": slot.W_orig,
                    "std_mean": (slot.std.mean if slot.std is not None
                                 else np.zeros(0)),
                    "std_scale": (slot.std.scale if slot.std is not None
                                  else np.zeros(0)),
                    "model_n_factors": m.n_factors,
                    "model_dynamics": m.dynamics,
                    "model_standardize": m.standardize,
                    "model_estimate_init": m.estimate_init,
                })
            tenants.append({
                "name": name, "file": fname, "fingerprint": fp,
                "capacity": int(slot.capacity),
                "max_iters": int(slot.max_iters), "tol": float(slot.tol),
                "t": int(slot.t), "t_total": int(slot.t_total),
                "n_queries": int(slot.n_queries),
                "filter": bucket.cfg.filter,
                "rank": int(bucket.cfg.rank),
                "was_quarantined": bool(slot.quarantined),
            })
            # PR 18: the tenant's drift-detector state (None when the
            # plane is disarmed or nothing scored) rides the manifest so
            # a restored fleet continues mid-baseline.
            from ..obs.live import plane as _plane
            dstate = _plane().drift_state(name)
            if dstate is not None:
                tenants[-1]["drift_state"] = dstate
        manifest = {
            "fleet_snapshot_format": FLEET_SNAPSHOT_FORMAT,
            "schema_version": SNAPSHOT_SCHEMA_VERSION,
            "fleet_id": self._fid,
            "tenants": tenants,
            "horizon": int(self._opts.horizon), "di": bool(self._opts.di),
            "ring": bool(self._ring), "max_update_rows": int(self._r_max),
            "journal_seq": (None if journal_seq is None
                            else int(journal_seq)),
        }
        mpath = os.path.join(dir_path, "manifest.json")
        fd, tmp = tempfile.mkstemp(dir=dir_path, suffix=".json.tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, mpath)
            fsync_dir(dir_path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        ev = dict(session=self._fid, action="snapshot", dir=dir_path,
                  n_tenants=len(tenants),
                  **({} if journal_seq is None
                     else {"journal_seq": int(journal_seq)}))
        tr = current_tracer()
        if tr is not None:
            tr.emit("daemon", **ev)
        else:
            live_observe({"t": time.perf_counter(), "kind": "daemon", **ev})
        return mpath

    # -- lifecycle -----------------------------------------------------
    def close(self):
        """Release the device buffers; further submits/drains raise."""
        for bk in self._buckets:
            bk.Ybuf = bk.Wbuf = bk.p = None
            bk.Yhost = bk.Whost = None
            bk.p_host = None
        for _, slot in self._slot_of.values():
            if slot.evicted is not None:
                slot.evicted.close()
        self._pending = []
        self._closed = True

    def __repr__(self):
        state = "closed" if self._closed else (
            f"{len(self._slot_of)} tenants / {len(self._buckets)} "
            f"buckets, {self._n_queries} queries, "
            f"{len(self._pending)} pending")
        return f"SessionFleet({self._fid}, {state})"


def open_fleet(results, panels, masks=None, **kwargs) -> SessionFleet:
    """Open a batched serving fleet over B fitted tenants.

    results : per-tenant ``FitResult`` of a ``DynamicFactorModel`` fit.
    panels  : per-tenant (T, N) panels the models were fitted on
              (original units; NaNs = missing), ``masks`` as in ``fit``.
    tenants : unique names (default ``t0..t{B-1}``).
    capacity        : per-tenant row budget, scalar or sequence
                      (default 2*T per tenant).
    max_update_rows : largest per-query row count (default 8) — one
                      executable per bucket serves every count up to it.
    max_iters / tol : per-tenant warm EM budget per query (scalar or
                      sequence; default 5 / 1e-6).
    horizon / di    : forecast steps and diffusion-index toggle.
    ring            : ring-buffer panels — a submit past a tenant's
                      capacity evicts its oldest rows IN-GRAPH instead
                      of raising: unbounded streams at constant memory,
                      zero recompiles, each tenant pinned to a lone
                      ring session over the same trailing window.
    filter / rank   : per-tenant serving engine ("info", "pit_qr",
                      "lowrank" + rank, or "auto" for the calibrated
                      cost-model choice per capacity class — evidence-
                      gated, so an unprofiled engine is never chosen);
                      scalar or one per tenant.  Default inherits each
                      fit's resolved ``FitResult.filter`` when the
                      batched core routes it (pit_qr/lowrank), else the
                      info-form twins.  Buckets are engine-homogeneous:
                      ONE executable per (bucket-shape, engine).
    resident        : fleet-wide hot-lane budget (default: every tenant
                      resident).  With fewer lanes than tenants the
                      overflow starts WARM (host shadows parked, no HBM
                      footprint) and pages in on submit — victims are
                      chosen by the calibrated paging economics
                      (``admission.readmission_cost_s`` vs lane rent);
                      see also ``fleet.evict(tenant, tier="warm"/"cold")``
                      and ``fleet.admit(tenant)``.
    backend         : "tpu" (default), "sharded" (bucket batch axes
                      split over the mesh), or a TPUBackend instance.
    robust          : ``RobustPolicy`` / True / False — the tick guard +
                      quarantine; default inherits the backend's policy.
    max_classes     : capacity-class budget for admission control.
    runs            : profile registry for the admission cost model
                      (default: ambient ``DFM_RUNS`` / ``.dfm_runs``).
    """
    return SessionFleet(results, panels, masks, **kwargs)


def read_manifest(dir_path: str) -> dict:
    """Load + validate a ``snapshot_all`` manifest (schema-checked)."""
    mpath = os.path.join(dir_path, "manifest.json")
    with open(mpath, "r", encoding="utf-8") as f:
        manifest = json.load(f)
    if "fleet_snapshot_format" not in manifest:
        raise ValueError(f"{mpath!r} is not a fleet snapshot manifest")
    from ..utils.checkpoint import check_schema_version
    check_schema_version(manifest, mpath)
    if int(manifest["fleet_snapshot_format"]) > FLEET_SNAPSHOT_FORMAT:
        raise ValueError(
            f"fleet snapshot {dir_path!r} carries fleet_snapshot_format="
            f"{manifest['fleet_snapshot_format']}, this build reads "
            f"<= {FLEET_SNAPSHOT_FORMAT}")
    return manifest


def restore_fleet(dir_path: str, **kwargs) -> SessionFleet:
    """Rebuild a warm fleet from ``SessionFleet.snapshot_all(dir_path)``.

    Every tenant npz is verified against its manifest content
    fingerprint before use (a corrupt or hand-edited snapshot fails
    loudly, naming the tenant); the restored per-tenant device state is
    the padded image of the exact saved f64 params + original-units
    panels, so answers are bit-equal to the uninterrupted fleet's.
    Tenants that were quarantined at snapshot time re-admit onto fresh
    lanes (their saved params came from the lone session, so their
    trajectory continues exactly; the manifest records
    ``was_quarantined`` for the forensic trail).

    ``kwargs`` pass through to :func:`open_fleet` (``backend=``,
    ``robust=``, ``resident=``, ``max_classes=``, ``runs=``); fleet
    geometry (capacity / budgets / horizon / ring / max_update_rows)
    always comes from the manifest."""
    from ..api import DynamicFactorModel, FitResult
    from ..backends.cpu_ref import SSMParams
    from ..utils.checkpoint import (_FIELDS, check_schema_version,
                                    panel_fingerprint)
    from ..utils.data import Standardizer
    manifest = read_manifest(dir_path)
    results, panels, masks, names = [], [], [], []
    caps, m_its, tols, filts, ranks = [], [], [], [], []
    for ten in manifest["tenants"]:
        path = os.path.join(dir_path, ten["file"])
        with np.load(path) as z:
            check_schema_version(z, path)
            if "fleet_tenant_format" not in z.files:
                raise ValueError(
                    f"{path!r} is not a fleet tenant snapshot")
            p = SSMParams(*(np.asarray(z[f], np.float64) for f in _FIELDS))
            Y = np.asarray(z["Y_orig"], np.float64)
            W = np.asarray(z["W_orig"], np.float64)
            mean = np.asarray(z["std_mean"], np.float64)
            scale = np.asarray(z["std_scale"], np.float64)
            model = DynamicFactorModel(
                n_factors=int(z["model_n_factors"][()]),
                dynamics=str(z["model_dynamics"]),
                standardize=bool(z["model_standardize"][()]),
                estimate_init=bool(z["model_estimate_init"][()]))
        if panel_fingerprint(Y, W) != ten["fingerprint"]:
            raise ValueError(
                f"fleet snapshot tenant {ten['name']!r} is corrupt: the "
                f"stored panel in {path!r} does not match the manifest "
                "content fingerprint")
        std = (Standardizer(mean=mean, scale=scale) if mean.size
               else None)
        results.append(FitResult(
            params=p, logliks=np.zeros(0),
            factors=np.zeros((0, p.A.shape[0])),
            factor_cov=np.zeros((0, p.A.shape[0], p.A.shape[0])),
            converged=False, n_iters=0, standardizer=std, model=model,
            backend="tpu", history=[]))
        panels.append(Y)
        masks.append(W)
        names.append(ten["name"])
        caps.append(int(ten["capacity"]))
        m_its.append(int(ten["max_iters"]))
        tols.append(float(ten["tol"]))
        # Engine round-trip (PR 17); pre-engine manifests restore as the
        # info-form fleet they were.
        filts.append(str(ten.get("filter", "info")))
        ranks.append(int(ten.get("rank", 0)))
    fleet = open_fleet(
        results, panels, masks, tenants=names, capacity=caps,
        max_iters=m_its, tol=tols, horizon=int(manifest["horizon"]),
        di=bool(manifest["di"]), ring=bool(manifest["ring"]),
        filter=filts, rank=ranks,
        max_update_rows=int(manifest["max_update_rows"]), **kwargs)
    # Stream-position ledger (ring eviction counts) survives the restart.
    from ..obs.live import plane as _plane
    for ten in manifest["tenants"]:
        _, slot = fleet._slot_of[ten["name"]]
        slot.t_total = int(ten["t_total"])
        slot.n_queries = int(ten["n_queries"])
        # PR 18: drift-detector state continues mid-baseline (no-op when
        # the plane is disarmed — the off path stays bit-identical).
        if ten.get("drift_state"):
            _plane().restore_drift(ten["name"], ten["drift_state"])
    return fleet

"""Admission control: assign fleet tenants to capacity classes.

A capacity class is one bucket shape — every member tenant's panel is
resident padded to the class dims and one fused ``serve_update`` dispatch
per tick answers all of its queued queries.  More classes means tighter
padding (less per-tick flop waste) but one more executable AND one more
~60-100 ms tunnel dispatch per tick; ``sched.buckets.plan_capacity_classes``
runs the calibrated cost-model DP over exactly that trade.

Tenants whose models differ in estimation flags (estimate_A/Q/init)
cannot share a program (the flags are jit statics), so admission first
partitions by config and plans classes within each group — deterministic:
groups are visited in first-tenant submit order, and the DP itself is
deterministic given the profile registry.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from ..obs.cost import em_iter_work, fit_cost_model
from ..sched.buckets import lane_rent_bytes, plan_capacity_classes

__all__ = ["ClassAssignment", "choose_engine", "plan_admission",
           "fleet_pad_waste", "plan_residency", "readmission_cost_s"]


@dataclasses.dataclass(frozen=True)
class ClassAssignment:
    """One planned capacity class: padded ``dims`` = (T_cap, N_max, k_max)
    and the submit-order tenant indices assigned to it."""

    dims: Tuple[int, int, int]
    members: Tuple[int, ...]


def _load_model(runs: Optional[str], device: Optional[str]):
    from ..obs.store import RunStore, runs_dir
    d = runs_dir(runs)
    profiles = []
    if d is not None:
        profiles = [r for r in RunStore(d).load()
                    if r.get("kind") == "profile"]
    return fit_cost_model(profiles, device=device)


def plan_admission(shapes: Sequence[Tuple[int, int, int]],
                   iters: Sequence[int],
                   cfg_keys: Optional[Sequence[tuple]] = None, *,
                   max_classes: int = 3, model=None,
                   runs: Optional[str] = None,
                   device: Optional[str] = None) -> List[ClassAssignment]:
    """Plan capacity classes for tenants with per-tenant resident shapes
    ``[(T_capacity, N, k), ...]`` and per-tick EM budgets ``iters``.

    ``cfg_keys`` (optional, one hashable per tenant) force tenants with
    different keys into different classes; ``max_classes`` bounds the
    TOTAL class count (each config group gets at least one).  ``model``
    overrides the cost model (default: calibrate from the profile
    registry, device priors when empty — same resolution as
    ``obs.advise``).  Deterministic given a fixed registry.
    """
    B = len(shapes)
    if B == 0:
        return []
    if len(iters) != B:
        raise ValueError("iters must match shapes length")
    keys = [()] * B if cfg_keys is None else list(cfg_keys)
    if len(keys) != B:
        raise ValueError("cfg_keys must match shapes length")
    m = model if model is not None else _load_model(runs, device)
    groups: List[Tuple[tuple, List[int]]] = []
    for i, key in enumerate(keys):
        for gk, members in groups:
            if gk == key:
                members.append(i)
                break
        else:
            groups.append((key, [i]))
    if max_classes < len(groups):
        raise ValueError(
            f"max_classes={max_classes} but the fleet has {len(groups)} "
            "incompatible model configs (each needs its own class)")
    # Budget split: every group gets one class; the extras round-robin
    # over groups largest-first (deterministic, and generous where the
    # padding waste can actually accrue).
    extra = max_classes - len(groups)
    alloc = [1] * len(groups)
    order = sorted(range(len(groups)), key=lambda gi: -len(groups[gi][1]))
    gi = 0
    while extra > 0 and any(alloc[j] < len(groups[j][1]) for j in order):
        j = order[gi % len(order)]
        if alloc[j] < len(groups[j][1]):
            alloc[j] += 1
            extra -= 1
        gi += 1
    out: List[ClassAssignment] = []
    for (gk, members), mc in zip(groups, alloc):
        plan = plan_capacity_classes(
            [shapes[i] for i in members], [iters[i] for i in members],
            max_classes=mc, model=m)
        for b in plan.buckets:
            out.append(ClassAssignment(
                dims=b.dims,
                members=tuple(members[j] for j in b.jobs)))
    return out


def choose_engine(dims: Tuple[int, int, int], iters: int, *,
                  rank: int = 0, model=None, runs: Optional[str] = None,
                  device: Optional[str] = None) -> str:
    """Pick the serving engine for one capacity class (``filter="auto"``).

    Compares the calibrated per-iteration cost of the info-form scan
    against ``pit_qr`` and ``lowrank`` at the class's padded dims, under
    the PR 15 evidence gate: an engine whose residual scale was never
    measured (``pit_qr_calibrated``/``lowrank_calibrated`` False) is NOT
    a candidate — raw structural priors never make an "auto" fleet
    compile an engine nobody timed.  With an empty registry every gate is
    closed and the choice is "info" (the pre-routing fleet).
    Deterministic given a fixed profile registry; ties keep "info".
    """
    m = model if model is not None else _load_model(runs, device)
    T, N, k = int(dims[0]), int(dims[1]), int(dims[2])
    best, best_s = "info", m.iter_s(N, T, k, "seq")
    if getattr(m, "pit_qr_calibrated", False):
        s = m.iter_s(N, T, k, "pit_qr")
        if s < best_s:
            best, best_s = "pit_qr", s
    if getattr(m, "lowrank_calibrated", False) and k > max(1, int(rank)):
        s = m.iter_s(N, T, k, "lowrank")
        if s < best_s:
            best, best_s = "lowrank", s
    return best


def fleet_pad_waste(shapes: Sequence[Tuple[int, int, int]],
                    iters: Sequence[int],
                    classes: Sequence[ClassAssignment]) -> float:
    """Aggregate padded-flop waste of an admission plan: 1 - true/padded
    EM flops over all tenants at their per-tick budgets (the bench's
    ``fleet_pad_waste_frac``)."""
    true_fl = padded_fl = 0.0
    for ca in classes:
        bT, bN, bk = ca.dims
        for i in ca.members:
            T, N, k = shapes[i]
            true_fl += em_iter_work(N, T, k)[0] * iters[i]
            padded_fl += em_iter_work(bN, bT, bk)[0] * iters[i]
    return 1.0 - true_fl / padded_fl if padded_fl > 0 else 0.0


def readmission_cost_s(dims: Tuple[int, int, int], *, r_max: int = 0,
                       model=None, runs: Optional[str] = None,
                       device: Optional[str] = None) -> float:
    """Predicted wall of paging one warm tenant back into a hot lane of a
    class with padded ``dims``: a d2h of the bucket params (the shadow
    refresh that keeps bucket-mates exact), the full-lane h2d re-upload,
    and one dispatch floor — priced with the SAME calibrated coefficients
    ``obs.advise`` ranks plans with (``per_byte_s``/``dispatch_floor_s``;
    ``sched.buckets.lane_rent_bytes`` supplies the byte count).
    Deterministic given a fixed profile registry."""
    m = model if model is not None else _load_model(runs, device)
    rent = lane_rent_bytes(dims, r_max)
    return float(m.dispatch_floor_s + 2.0 * rent * m.per_byte_s)


def plan_residency(classes: Sequence[ClassAssignment],
                   resident: Optional[int], *, r_max: int = 0,
                   model=None, runs: Optional[str] = None,
                   device: Optional[str] = None) -> List[int]:
    """Split a fleet-wide resident-lane budget over capacity classes.

    Returns per-class hot-lane counts.  Every class keeps >= 1 lane (a
    bucket with zero lanes has no program to serve its members), then
    the remaining budget goes greedily to the class where a hot lane
    AVOIDS the most predicted paging cost: ``readmission_cost_s(dims) *
    unhoused members`` — the calibrated cost model's re-admission price
    against the HBM rent the lane charges.  ``resident=None`` (no cap)
    makes every member hot.  Deterministic: ties break on class index.
    """
    n_members = [len(ca.members) for ca in classes]
    if resident is None:
        return n_members
    m = model if model is not None else _load_model(runs, device)
    want = max(len(classes), int(resident))
    lanes = [1 if n else 0 for n in n_members]
    budget = want - sum(lanes)
    costs = [readmission_cost_s(ca.dims, r_max=r_max, model=m)
             for ca in classes]
    while budget > 0:
        best, best_gain = -1, 0.0
        for ci, ca in enumerate(classes):
            unhoused = n_members[ci] - lanes[ci]
            gain = costs[ci] * unhoused
            if unhoused > 0 and gain > best_gain:
                best, best_gain = ci, gain
        if best < 0:
            break
        lanes[best] += 1
        budget -= 1
    return lanes
